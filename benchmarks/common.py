"""Shared harness for the secondary benchmarks (BASELINE.md configs 3-5).

Timing methodology matches bench.py: the tunneled TPU runtime's
block_until_ready can return early and host transfers are slow, so every
measurement enqueues K dispatches back-to-back, reduces to a scalar on
device, and syncs once — slope = steady-state device time; a single
synchronized rep gives the interactive latency.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _scl(x):
    return jnp.sum(x)


def devtime(fn, pick, K=4, warm=1, nrun=3):
    """fn() -> result pytree; pick(result) -> array to reduce.
    Returns (slope_s, single_s).

    Takes the MIN over nrun separate measurements of both the single
    synchronized rep and the K-rep pipelined run: the tunneled TPU is a
    shared resource whose effective throughput swings by up to ~8x with
    external load, and min-of-several is the standard way to estimate
    the unloaded cost."""
    for _ in range(warm):
        _ = np.asarray(_scl(pick(fn())))

    def single():
        t0 = time.perf_counter()
        _ = np.asarray(_scl(pick(fn())))
        return time.perf_counter() - t0

    def krun():
        t0 = time.perf_counter()
        for _ in range(K):
            s = _scl(pick(fn()))
        _ = np.asarray(s)
        return time.perf_counter() - t0

    t1 = min(single() for _ in range(nrun))
    tK = min(krun() for _ in range(nrun))
    slope = (tK - t1) / (K - 1)
    if slope <= 0:
        # different run populations under variable load; conservative
        # fallback counts one round-trip against the K batches
        slope = tK / K
    return slope, t1


def bench_model(nchan, nbin, dtype=jnp.float32, P=0.003, nu_fit=1500.0):
    """Shared synthetic template at bench shapes."""
    from pulseportraiture_tpu.models.gaussian import gen_gaussian_portrait
    from pulseportraiture_tpu.synth import default_test_model

    tm = default_test_model(nu_fit)
    freqs = jnp.linspace(1300.0, 1899.0, nchan, dtype=dtype)
    params = {k: jnp.asarray(v, dtype) for k, v in tm.params_pytree().items()}
    model = gen_gaussian_portrait(params, freqs, tm.nu_ref, nbin, P=P,
                                  code=tm.code, scattered=False).astype(dtype)
    return model, freqs
