"""Minimal wideband timing: .tim reading and a NumPy GLS fitter.

Closes the loop the reference's example notebook closes with an
external ``tempo`` GLS run on the produced .tim with DMDATA 1
(examples/example_make_model_and_TOAs.ipynb cells 43-56) — here with
no external binaries: read the wideband TOAs (+ -pp_dm DM
measurements) back, fit a linearized timing model jointly to arrival
times and DMs, and report white(ned) residuals.
"""

from .gls import WidebandGLSResult, wideband_gls_fit
from .tim import TimTOA, read_tim

__all__ = ["read_tim", "TimTOA", "wideband_gls_fit", "WidebandGLSResult"]
