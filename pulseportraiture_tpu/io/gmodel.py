"""The `.gmodel` text format: frequency-evolving Gaussian-component
models (grammar documented in the reference's examples/example.gmodel;
reader/writer parity: reference pplib.py:2931-3057).

Round-trips through the in-memory GaussianModel dataclass
(models/gaussian.py); generation at given (phases, freqs, P) goes
through the jittable portrait generator.
"""

import numpy as np

from ..models.gaussian import GaussianModel, gen_gaussian_portrait

# flat parameter vector layout, matching the on-disk column order:
# [dc, tau, (loc, mloc, wid, mwid, amp, mamp) * ngauss]


def model_to_flat(model):
    """GaussianModel -> (params, fit_flags) flat vectors of length
    2 + 6*ngauss (tau in seconds)."""
    ngauss = model.ngauss
    params = np.zeros(2 + 6 * ngauss)
    flags = np.zeros(2 + 6 * ngauss, int)
    params[0], params[1] = model.dc, model.tau
    ff = model.fit_flags
    flags[0] = int(ff.get("dc", 0))
    flags[1] = int(ff.get("tau", 0))
    for i in range(ngauss):
        params[2 + 6 * i: 8 + 6 * i] = [
            model.locs[i], model.mlocs[i], model.wids[i],
            model.mwids[i], model.amps[i], model.mamps[i]]
        flags[2 + 6 * i: 8 + 6 * i] = [
            int(f[i]) for f in (
                ff.get("locs", np.zeros(ngauss)),
                ff.get("mlocs", np.zeros(ngauss)),
                ff.get("wids", np.zeros(ngauss)),
                ff.get("mwids", np.zeros(ngauss)),
                ff.get("amps", np.zeros(ngauss)),
                ff.get("mamps", np.zeros(ngauss)))]
    return params, flags


def model_from_flat(name, code, nu_ref, params, fit_flags, alpha,
                    fit_alpha=0):
    """Flat vectors -> GaussianModel."""
    params = np.asarray(params, float)
    fit_flags = np.asarray(fit_flags, int)
    ngauss = (len(params) - 2) // 6
    comp = params[2:].reshape(ngauss, 6)
    cflags = fit_flags[2:].reshape(ngauss, 6)
    return GaussianModel(
        name=name, code=code, nu_ref=float(nu_ref),
        dc=float(params[0]), tau=float(params[1]), alpha=float(alpha),
        locs=comp[:, 0].copy(), mlocs=comp[:, 1].copy(),
        wids=comp[:, 2].copy(), mwids=comp[:, 3].copy(),
        amps=comp[:, 4].copy(), mamps=comp[:, 5].copy(),
        fit_flags={
            "dc": int(fit_flags[0]), "tau": int(fit_flags[1]),
            "alpha": int(fit_alpha),
            "locs": cflags[:, 0].copy(), "mlocs": cflags[:, 1].copy(),
            "wids": cflags[:, 2].copy(), "mwids": cflags[:, 3].copy(),
            "amps": cflags[:, 4].copy(), "mamps": cflags[:, 5].copy()})


def write_gmodel(model, filename, append=False, quiet=False):
    """Serialize a GaussianModel to the .gmodel text grammar
    (reference write_model, pplib.py:2931-2968)."""
    params, flags = model_to_flat(model)
    lines = [f"MODEL   {model.name}",
             f"CODE    {model.code}",
             f"FREQ    {model.nu_ref:.5f}",
             f"DC     {params[0]: .8f} {flags[0]:d}",
             f"TAU    {params[1]: .8f} {flags[1]:d}",
             f"ALPHA  {model.alpha: .3f}      "
             f"{int(model.fit_flags.get('alpha', 0)):d}"]
    for i in range(model.ngauss):
        vals = params[2 + 6 * i: 8 + 6 * i]
        ffs = flags[2 + 6 * i: 8 + 6 * i]
        pairs = "  ".join(f"{v: .8f} {f:d}" for v, f in zip(vals, ffs))
        lines.append(f"COMP{i + 1:02d} {pairs}")
    with open(filename, "a" if append else "w") as f:
        f.write("\n".join(lines) + "\n")
    if not quiet:
        print(f"{filename} written.")


def read_gmodel(modelfile, quiet=False):
    """Parse a .gmodel file -> GaussianModel (reference read_model
    read-only path, pplib.py:2971-3057; tolerates comments/blank
    lines/trailing comments the same way)."""
    name, code, nu_ref = "unknown", "000", None
    dc = tau = 0.0
    fit_dc = fit_tau = 0
    alpha, fit_alpha = 0.0, 0
    comps = []
    if not quiet:
        print(f"Reading model from {modelfile}...")
    with open(modelfile) as f:
        for line in f:
            info = line.split()
            if not info:
                continue
            key = info[0]
            try:
                if key == "MODEL":
                    name = info[1]
                elif key == "CODE":
                    code = info[1]
                elif key == "FREQ":
                    nu_ref = float(info[1])
                elif key == "DC":
                    dc, fit_dc = float(info[1]), int(info[2])
                elif key == "TAU":
                    tau, fit_tau = float(info[1]), int(info[2])
                elif key == "ALPHA":
                    alpha, fit_alpha = float(info[1]), int(info[2])
                elif key.startswith("COMP") and not key.startswith("#"):
                    vals = [float(x) for x in info[1::2][:6]]
                    ffs = [int(x) for x in info[2::2][:6]]
                    comps.append((vals, ffs))
            except (IndexError, ValueError):
                continue
    if nu_ref is None:
        raise ValueError(f"{modelfile}: no FREQ line — not a .gmodel file")
    ngauss = len(comps)
    params = np.zeros(2 + 6 * ngauss)
    flags = np.zeros(2 + 6 * ngauss, int)
    params[:2] = dc, tau
    flags[:2] = fit_dc, fit_tau
    for i, (vals, ffs) in enumerate(comps):
        params[2 + 6 * i: 8 + 6 * i] = vals
        flags[2 + 6 * i: 8 + 6 * i] = ffs
    return model_from_flat(name, code, nu_ref, params, flags, alpha,
                           fit_alpha)


def gen_gmodel_portrait(model, phases, freqs, P=None, quiet=True):
    """Build the model portrait at the given phase-bin count and
    frequencies (reference read_model generation path; tau on disk is
    seconds and needs P when non-zero)."""
    nbin = len(np.atleast_1d(phases))
    if model.tau != 0.0 and P is None:
        raise ValueError("need period P for non-zero scattering TAU")
    port = gen_gaussian_portrait(
        {k: np.asarray(v) for k, v in model.params_pytree().items()},
        np.atleast_1d(np.asarray(freqs, float)), model.nu_ref, nbin,
        P=P, code=model.code, scattered=model.tau != 0.0)
    if not quiet:
        print(f"Model Name: {model.name}: {model.ngauss} components, "
              f"{nbin} bins, {len(np.atleast_1d(freqs))} channels, "
              f"referenced at {model.nu_ref:.3f} MHz.")
    return np.asarray(port)
