"""Package install (reference setup.py:1-14 installs flat py_modules +
scripts; here a proper package with console entry points)."""

from setuptools import find_packages, setup

setup(
    name="pulseportraiture_tpu",
    version="0.1.0",
    description="TPU-native (JAX/XLA/Pallas) wideband pulsar-timing "
                "framework with PulsePortraiture's capabilities",
    packages=find_packages(exclude=("tests",)),
    python_requires=">=3.10",
    install_requires=["numpy", "scipy", "jax", "matplotlib"],
    entry_points={
        "console_scripts": [
            "pptoas=pulseportraiture_tpu.cli.pptoas:main",
            "pptime=pulseportraiture_tpu.cli.pptime:main",
            "ppserve=pulseportraiture_tpu.cli.ppserve:main",
            "pproute=pulseportraiture_tpu.cli.pproute:main",
            "ppalign=pulseportraiture_tpu.cli.ppalign:main",
            "ppgauss=pulseportraiture_tpu.cli.ppgauss:main",
            "ppfactory=pulseportraiture_tpu.cli.ppfactory:main",
            "ppspline=pulseportraiture_tpu.cli.ppspline:main",
            "ppzap=pulseportraiture_tpu.cli.ppzap:main",
            "ppwatch=pulseportraiture_tpu.cli.ppwatch:main",
            "ppmon=pulseportraiture_tpu.cli.ppmon:main",
        ]
    },
)
