"""Shared harness for the secondary benchmarks (BASELINE.md configs 3-5).

Timing methodology matches bench.py: the tunneled TPU runtime's
block_until_ready can return early and host transfers are slow, so every
measurement enqueues K dispatches back-to-back, reduces to a scalar on
device, and syncs once — slope = steady-state device time; a single
synchronized rep gives the interactive latency.  The timer itself lives
in pulseportraiture_tpu.profiling (the reusable stage-attribution
profiler); this module keeps the import path the benchmarks always used.
"""

import jax.numpy as jnp

from pulseportraiture_tpu.profiling import devtime  # noqa: F401


# bf16 MXU peak per chip, shared by every bench's mfu accounting (one
# table — a second copy would drift when a chip generation is added)
MXU_PEAK_TFLOPS = {"v5 lite": 197.0, "v4": 275.0, "v5p": 459.0,
                   "v6": 918.0}


def mxu_peak_tflops(device):
    """bf16 MXU peak for a jax device, or None when unknown (CPU)."""
    name = str(device).lower()
    return next((v for k, v in MXU_PEAK_TFLOPS.items() if k in name),
                None)


def bench_model(nchan, nbin, dtype=jnp.float32, P=0.003, nu_fit=1500.0):
    """Shared synthetic template at bench shapes."""
    from pulseportraiture_tpu.models.gaussian import gen_gaussian_portrait
    from pulseportraiture_tpu.synth import default_test_model

    tm = default_test_model(nu_fit)
    freqs = jnp.linspace(1300.0, 1899.0, nchan, dtype=dtype)
    params = {k: jnp.asarray(v, dtype) for k, v in tm.params_pytree().items()}
    model = gen_gaussian_portrait(params, freqs, tm.nu_ref, nbin, P=P,
                                  code=tm.code, scattered=False).astype(dtype)
    return model, freqs
