"""Rotation / dispersion phase models.

Conventions (used consistently everywhere in this framework):

- Frequencies ``nu`` are in MHz, periods ``P`` in seconds, phases in
  rotations, DM in pc cm^-3, GM in pc^2 cm^-6 (Lam et al. 2016
  "geometric measure").
- The per-channel achromatic+dispersive+refractive phase delay is::

      t_n = phi
          + (Dconst   * DM / P) * (nu_n**-2 - nu_DM**-2)
          + (Dconst**2 * GM / P) * (nu_n**-4 - nu_GM**-4)

- Rotating data *by* positive ``t_n`` moves features to earlier phase
  (a left shift); rotating the data by the fitted ``(phi, DM)`` aligns
  it with the template.

Behavioral parity targets: reference pplib.py:2672-2729 (DM_delay,
phase_transform, guess_fit_freq) and pptoaslib.py:195-257
(phase_shifts, phasor), re-derived rather than translated — the
gradient/Hessian chains (reference pptoaslib.py:231-249) are replaced
by `jax.grad` on these primitives.
"""

import jax
import jax.numpy as jnp

from ..config import Dconst


def cexp(x):
    """exp(i*x) with the complex dtype matching x (f32 -> c64).

    Avoids Python complex literals, whose weak-complex128 constants the
    TPU compiler rejects (C128 unsupported on TPU)."""
    return jax.lax.complex(jnp.cos(x), jnp.sin(x))


def DM_delay(DM, freq, freq_ref=jnp.inf, P=None):
    """Dispersion delay [s] of ``freq`` relative to ``freq_ref`` [MHz].

    Positive for freq < freq_ref (lower frequencies arrive later).
    If ``P`` is given, the delay is returned in rotations instead.
    Parity: reference pplib.py:2672-2685.
    """
    delay = Dconst * DM * (freq**-2.0 - freq_ref**-2.0)
    if P is not None:
        delay = delay / P
    return delay


def dispersion_phases(freqs, DM, P, nu_ref):
    """Per-channel dispersive phase offsets [rot] relative to nu_ref."""
    return (Dconst * DM / P) * (freqs**-2.0 - nu_ref**-2.0)


def phase_shifts(phi, DM, GM, freqs, P, nu_DM, nu_GM):
    """Per-channel total phase delays t_n [rot] for the portrait fit.

    Parity: reference pptoaslib.py:195-228.
    """
    return (
        phi
        + (Dconst * DM / P) * (freqs**-2.0 - nu_DM**-2.0)
        + (Dconst**2.0 * GM / P) * (freqs**-4.0 - nu_GM**-4.0)
    )


def phasor(delays, nharm):
    """exp(2*pi*i * outer(delays, k)) for harmonics k = 0..nharm-1.

    Multiplying a channel's rFFT by its phasor row rotates that channel
    to *earlier* phase by ``delays`` rotations.
    Parity: reference pptoaslib.py:252-257.
    """
    k = jnp.arange(nharm, dtype=delays.dtype)
    return cexp(2.0 * jnp.pi * delays[..., None] * k)


def phase_transform(phi, DM, nu_ref1, nu_ref2, P, mod=True):
    """Re-reference a fitted phase from nu_ref1 to nu_ref2 [MHz].

    phi2 = phi1 + (Dconst*DM/P) * (nu_ref2**-2 - nu_ref1**-2), so the
    per-channel delays t_n are invariant.  With ``mod``, result is
    wrapped to [-0.5, 0.5).  Use nu_ref = inf for the infinite-frequency
    (unrotated) phase.  Parity: reference pplib.py:2688-2712.
    """
    phi2 = phi + (Dconst * DM / P) * (nu_ref2**-2.0 - nu_ref1**-2.0)
    if mod:
        phi2 = jnp.mod(phi2 + 0.5, 1.0) - 0.5
    return phi2


def guess_fit_freq(freqs, SNRs=None):
    """S/N- and nu^-2-weighted mean frequency — the initial guess for
    the zero-covariance reference frequency of a (phi, DM) fit.

    Parity: reference pplib.py:2715-2729: a weighted center-of-mass
    with weights w_n = SNR_n * nu_n**-2, evaluated as
    nu_fit = (sum w / sum (w * nu**-2))**0.5.
    """
    if SNRs is None:
        SNRs = jnp.ones_like(freqs)
    w = SNRs * freqs**-2.0
    return (jnp.sum(w) / jnp.sum(w * freqs**-2.0)) ** 0.5


def doppler_correct_freqs(freqs, doppler_factor):
    """Barycenter topocentric frequencies: nu_bary = nu_topo * df.

    The fitted DM transforms as DM_bary = DM_topo * df and
    GM_bary = GM_topo * df**3 (applied in the pipeline; reference
    pptoas.py:583-591).
    """
    return freqs * doppler_factor
