"""pproute — shard a campaign's TOA requests across a fleet of warm
``ppserve --listen`` hosts (ISSUE 10).

Reads the SAME JSONL request file as ``ppserve -r`` (one JSON object
per line: name, datafiles, modelfile, options), but instead of serving
locally it routes every request through a
:class:`~..serve.router.ToaRouter` over ``--hosts`` (or
PPT_ROUTER_HOSTS): least-pending-archives placement with sticky
per-template affinity, retryable-backpressure retries with capped
exponential backoff, and per-request ``.tim`` files written by
whichever host served the request — byte-identical to the single-host
one-shot driver.

Fleet assumptions: archive paths and ``--outdir`` are visible on
every host (shared filesystem — no bulk data crosses the wire), and
each endpoint is a running ``ppserve --listen``.  ``--telemetry``
records the route_submit/route_retry/route_done ledger; read it with
``tools/pptrace.py report`` (the "router" section: per-host shares,
retry rate, placement imbalance).
"""

import argparse
import json
import os
import sys
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="pproute", description=__doc__.splitlines()[0])
    p.add_argument("-r", "--requests", metavar="requests.jsonl",
                   required=True,
                   help="JSONL request file (ppserve's format: name, "
                        "datafiles, modelfile, options per line).")
    p.add_argument("-H", "--hosts", metavar="host:port[,host:port...]",
                   default=None,
                   help="Fleet endpoints, each a running 'ppserve "
                        "--listen'. [default: config.router_hosts / "
                        "PPT_ROUTER_HOSTS]")
    p.add_argument("-O", "--outdir", metavar="DIR", default=".",
                   help="Directory for per-request <name>.tim outputs "
                        "(must be visible to every host). "
                        "[default: .]")
    p.add_argument("--retry-max", dest="retry_max", type=int,
                   default=None, metavar="N",
                   help="Total placement attempts per request before "
                        "the last retryable rejection is raised. "
                        "[default: config.router_retry_max / "
                        "PPT_ROUTER_RETRY_MAX]")
    p.add_argument("--timeout", type=float, default=None, metavar="S",
                   help="Per-request result timeout in seconds. "
                        "[default: none]")
    p.add_argument("--telemetry", metavar="trace.jsonl", default=None,
                   help="Write the routing trace (route_submit/"
                        "route_retry/route_done) here; analyze with "
                        "tools/pptrace.py. Also via PPT_TELEMETRY. "
                        "[default: off]")
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.retry_max is not None and args.retry_max < 1:
        raise SystemExit("--retry-max: must be >= 1, got "
                         f"{args.retry_max}")
    from .. import config

    hosts = args.hosts
    if hosts is not None:
        hosts = [h.strip() for h in str(hosts).split(",") if h.strip()]
    else:
        hosts = list(config.router_hosts)
    if not hosts:
        raise SystemExit("pproute: no fleet endpoints — pass --hosts "
                         "host:port[,host:port...] or set "
                         "PPT_ROUTER_HOSTS")
    for h in hosts:
        try:
            config.parse_hostport(h)
        except ValueError as e:
            raise SystemExit(f"pproute: --hosts: {e}")

    from .ppserve import parse_requests

    reqs = parse_requests(args.requests)
    # tim paths cross the wire and are resolved by the SERVING host —
    # the shared-filesystem assumption only holds for absolute paths
    # (a relative outdir would land in the remote ppserve's cwd)
    args.outdir = os.path.abspath(args.outdir)
    os.makedirs(args.outdir, exist_ok=True)

    from ..serve import ToaRouter, TransportError

    try:
        router = ToaRouter(hosts, retry_max=args.retry_max,
                           telemetry=args.telemetry, quiet=args.quiet)
    except TransportError as e:
        raise SystemExit(f"pproute: {e}")
    failures = 0
    t0 = time.time()
    with router:
        handles = []
        for rec in reqs:
            tim = os.path.join(args.outdir, f"{rec['name']}.tim")
            try:
                handles.append(router.submit(
                    rec["datafiles"], rec["modelfile"], tim_out=tim,
                    name=rec["name"], **rec["options"]))
            except Exception as e:
                # a saturated/terminal fleet fails THIS request (the
                # documented rc=1 path), not the whole batch — the
                # already-placed requests must still be collected
                handles.append(None)
                failures += 1
                print(f"pproute: request {rec['name']!r} FAILED to "
                      f"place: {e}", file=sys.stderr)
        for rec, h in zip(reqs, handles):
            if h is None:
                continue
            try:
                res = h.result(args.timeout)
            except Exception as e:
                failures += 1
                print(f"pproute: request {rec['name']!r} FAILED on "
                      f"{h.host.label}: {e}", file=sys.stderr)
                continue
            if not args.quiet:
                print(f"pproute: {rec['name']}: "
                      f"{len(res.TOA_list)} TOAs from "
                      f"{len(res.order)} archive(s) on "
                      f"{h.host.label} -> {res.tim_out}")
        placed = router.stats()
    if not args.quiet:
        share = ", ".join(f"{lbl}: {st['n_archives']} archive(s)/"
                          f"{st['n_requests']} request(s)"
                          for lbl, st in placed.items())
        print(f"pproute: {len(reqs) - failures}/{len(reqs)} requests "
              f"across {len(hosts)} host(s) in {time.time() - t0:.2f} "
              f"s [{share}]")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
