"""ISSUE 6: the double-buffered transfer pipeline and the universal
raw device lane.

Covers: depth-1 vs depth-2 byte-identity (+ the exact in-flight
bound), raw-vs-decoded digit-identity for every newly supported DATA
sample type (u8, signed byte, float32) and multi-pol state (4-pol
IQUV, AA+BB), the h2d_start/h2d_done telemetry schema and pptrace's
link section, the PPT_PIPELINE_DEPTH / PPT_COMPILE_CACHE env hooks,
and the persistent compilation cache wiring.  All shapes tiny
(nchan <= 16, nbin <= 256) per the tier-1 budget."""

import io
import os

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.pipeline import stream as S

from fits_forge import forge_archive, gaussian_portrait


def _noisy_maker(nchan, nbin, nsub, npol, seed=3, sigma=0.08):
    """Gaussian portrait + per-(subint, pol) noise: a noiseless forge
    makes chi2 astronomically conditioned (data == template exactly),
    where host-FFT-vs-device-DFT rounding at 1e-16 shows in the 11th
    digit of the -snr flag; realistic noise is what the lanes meet."""
    base = gaussian_portrait(nchan, nbin)
    rng = np.random.default_rng(seed)
    noise = {(s, p): rng.normal(0.0, sigma, (nchan, nbin))
             for s in range(nsub) for p in range(npol)}
    return lambda s, p: base * (1.0 + 0.1 * p) + 0.1 * s + noise[(s, p)]


def _forge_and_template(tmp_path, name, **kw):
    """Forge one noisy archive + a template built from its scrunch."""
    from pulseportraiture_tpu.io.psrfits import (read_archive,
                                                 unload_new_archive)

    nsub, nchan, nbin = 2, 8, 128
    npol = kw.get("npol", 1)
    f = str(tmp_path / f"{name}.fits")
    forge_archive(f, nsub=nsub, nchan=nchan, nbin=nbin, dedisp=0,
                  data_maker=_noisy_maker(nchan, nbin, nsub, npol),
                  **kw)
    arch = read_archive(f)
    arch.tscrunch()
    tmpl = str(tmp_path / f"{name}_tmpl.fits")
    unload_new_archive(np.asarray(arch.amps), arch, tmpl, DM=0.0,
                      dmc=1, quiet=True)
    return f, tmpl


# ---------------------------------------------------------------------------
# universal raw lane: every sample type / pol state, digit-identical
# ---------------------------------------------------------------------------

RAW_CASES = {
    # name -> (forge kwargs, expected raw_code, expected pol_sum)
    "u8": (dict(data_dtype="u1"), "u8", False),
    "i8": (dict(data_dtype="i1"), "i8", False),
    "f32be": (dict(data_dtype=">f4"), "f32", False),
    "iquv4": (dict(data_dtype=">i2", npol=4, pol_type="IQUV"),
              "i16", False),
    "aabb": (dict(data_dtype=">i2", npol=2, pol_type="AA+BB"),
             "i16", True),
}


@pytest.mark.parametrize("case", sorted(RAW_CASES))
def test_raw_lane_universal_digit_identical(case, tmp_path,
                                            monkeypatch):
    """The raw device lane must (a) actually engage for the new
    sample types / pol states and (b) produce .tim output
    digit-identical to the decoded host lane (the oracle)."""
    kw, want_code, want_sum = RAW_CASES[case]
    f, tmpl = _forge_and_template(tmp_path, case, **kw)

    d = S._load_raw(f)
    assert d.raw_code == want_code
    assert d.pol_sum is want_sum
    if want_sum:
        assert d.raw.shape[1] == 2  # two summand pols ship

    tim_raw = str(tmp_path / "raw.tim")
    r1 = S.stream_wideband_TOAs([f], tmpl, nsub_batch=4, quiet=True,
                                tim_out=tim_raw)
    assert len(r1.TOA_list) == 2
    assert r1.h2d_bytes > 0

    # force the decoded fallback lane (the digit-exactness oracle)
    def refuse(path):
        raise ValueError("forced decode for the oracle arm")

    monkeypatch.setattr(S, "_load_raw", refuse)
    tim_dec = str(tmp_path / "dec.tim")
    r2 = S.stream_wideband_TOAs([f], tmpl, nsub_batch=4, quiet=True,
                                tim_out=tim_dec)
    assert len(r2.TOA_list) == 2
    assert open(tim_raw).read() == open(tim_dec).read()


def test_raw_refuses_sub_byte_and_scaled(tmp_path):
    """Layouts raw mode cannot represent keep refusing loudly (the
    loader then falls back to the decoded lane)."""
    nchan, nbin = 8, 64
    f = str(tmp_path / "nbit4.fits")
    forge_archive(f, nsub=1, nchan=nchan, nbin=nbin,
                  data_dtype="nbit4")
    with pytest.raises(ValueError):
        S._load_raw(f)


# ---------------------------------------------------------------------------
# the transfer pipeline: depth A/B, exact bound, telemetry
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pipeline_corpus(tmp_path_factory):
    """Three tiny int16 archives + template, shared by the depth A/B
    and telemetry tests."""
    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.synth import (default_test_model,
                                            make_fake_pulsar)
    from pulseportraiture_tpu.utils.mjd import MJD

    tmp = tmp_path_factory.mktemp("tpipe")
    model = default_test_model(1500.0)
    gmodel = str(tmp / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(3):
        p = str(tmp / f"a{i}.fits")
        make_fake_pulsar(model, {"PSR": "TP", "P0": 0.003, "DM": 10.0,
                                 "PEPOCH": 55000.0},
                         outfile=p, nsub=2, nchan=16, nbin=128,
                         dDM=2e-4 * i, start_MJD=MJD(55100 + i, 0.1),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=i)
        files.append(p)
    return tmp, files, gmodel


def test_pipeline_depth_byte_identical_and_bounded(pipeline_corpus):
    """depth=1 (serialized copy/fit, the pre-pipeline arm) and
    depth=2 (double-buffered) must produce byte-identical .tim and
    TOA fields, and the exact per-device in-flight bound must hold
    with the pipeline in front of it."""
    tmp, files, gmodel = pipeline_corpus
    outs = {}
    for depth in (1, 2):
        tim = str(tmp / f"d{depth}.tim")
        res = S.stream_wideband_TOAs(
            files, gmodel, nsub_batch=2, quiet=True, tim_out=tim,
            pipeline_depth=depth, max_inflight=2)
        assert res.peak_inflight <= 2
        assert res.h2d_bytes > 0 and res.h2d_duration >= 0.0
        outs[depth] = (open(tim).read(),
                       [(t.MJD.tim_string(), t.TOA_error, dict(t.flags))
                        for t in res.TOA_list])
    assert outs[1] == outs[2]


def test_h2d_telemetry_schema_and_report(pipeline_corpus):
    """A traced pipelined run emits schema-valid h2d_start/h2d_done
    pairs (one per dispatch, keyed by seq, byte counts positive) and
    pptrace's link section aggregates them."""
    tmp, files, gmodel = pipeline_corpus
    trace = str(tmp / "trace.jsonl")
    res = S.stream_wideband_TOAs(files, gmodel, nsub_batch=2,
                                 quiet=True, telemetry=trace,
                                 pipeline_depth=2)
    manifest, events = telemetry.validate_trace(trace)
    assert manifest["config"]["stream_pipeline_depth"] == \
        config.stream_pipeline_depth
    starts = {e["seq"]: e for e in events if e["type"] == "h2d_start"}
    dones = {e["seq"]: e for e in events if e["type"] == "h2d_done"}
    dispatches = {e["seq"] for e in events if e["type"] == "dispatch"}
    assert len(dones) == res.nfit
    assert set(starts) == set(dones) == dispatches
    assert sum(e["bytes"] for e in dones.values()) == res.h2d_bytes
    for seq, e in dones.items():
        assert e["bytes"] > 0 and e["h2d_s"] >= 0.0
        assert isinstance(e["overlap"], bool)
        assert starts[seq]["device"] == e["device"]
    run_end = [e for e in events if e["type"] == "run_end"][-1]
    assert run_end["h2d_bytes"] == res.h2d_bytes
    assert run_end["pipeline_depth"] == 2

    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_h2d"] == res.nfit
    assert summary["h2d_bytes"] == res.h2d_bytes
    assert summary["h2d_s"] >= 0.0
    sf = summary["h2d_stall_frac"]
    assert sf is None or 0.0 <= sf <= 1.0


def test_report_tolerates_pre_pipeline_traces(tmp_path):
    """Traces written before the transfer pipeline (no h2d events)
    must still report — the link section just says so."""
    trace = str(tmp_path / "old.jsonl")
    tr = telemetry.Tracer(trace, run="old")
    tr.emit("run_end", driver="x", n_toas=0, nfit=0)
    tr.close()
    buf = io.StringIO()
    summary = telemetry.report(trace, file=buf)
    assert summary["n_h2d"] == 0
    assert summary["h2d_stall_frac"] is None
    assert "no h2d events" in buf.getvalue()


def test_pipeline_depth_config_and_env(monkeypatch):
    """config.stream_pipeline_depth default, the PPT_PIPELINE_DEPTH /
    PPT_COMPILE_CACHE env hooks, and their strict parses."""
    assert config.stream_pipeline_depth >= 1
    monkeypatch.setenv("PPT_PIPELINE_DEPTH", "3")
    monkeypatch.setenv("PPT_COMPILE_CACHE", "/tmp/ppt-cc-test")
    saved = (config.stream_pipeline_depth, config.compile_cache_dir)
    try:
        changed = config.env_overrides()
        assert "stream_pipeline_depth" in changed
        assert "compile_cache_dir" in changed
        assert config.stream_pipeline_depth == 3
        assert config.compile_cache_dir == "/tmp/ppt-cc-test"
        monkeypatch.setenv("PPT_COMPILE_CACHE", "off")
        config.env_overrides()
        assert config.compile_cache_dir is None
        monkeypatch.setenv("PPT_PIPELINE_DEPTH", "0")
        with pytest.raises(ValueError):
            config.env_overrides()
        monkeypatch.setenv("PPT_PIPELINE_DEPTH", "two")
        with pytest.raises(ValueError):
            config.env_overrides()
    finally:
        config.stream_pipeline_depth, config.compile_cache_dir = saved


def test_compile_cache_populates(tmp_path, monkeypatch):
    """enable_compile_cache routes jax's persistent cache to the
    configured directory and compiled programs land there (ROADMAP
    item 5 down payment — fleet restarts skip the recompile)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.utils import device as D

    cache = str(tmp_path / "cc")
    monkeypatch.setattr(D, "_compile_cache_dir", None)
    monkeypatch.setattr(config, "compile_cache_dir", cache)
    try:
        assert D.enable_compile_cache() == cache
        fn = jax.jit(lambda x: jnp.cos(x) @ x.T * 2.0)
        jax.block_until_ready(fn(jnp.ones((32, 32))))
        assert os.listdir(cache), "no cache entries written"
        # idempotent re-apply
        assert D.enable_compile_cache() == cache
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        monkeypatch.setattr(D, "_compile_cache_dir", None)


def test_pptoas_pipeline_flags_validate():
    """--pipeline-depth needs --stream and a sane value (cheap parse-
    level checks; the e2e plumbing rides test_cli's stream runs)."""
    from pulseportraiture_tpu.cli import pptoas

    with pytest.raises(SystemExit):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel",
                     "--pipeline-depth", "2"])
    with pytest.raises(SystemExit):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel", "--stream",
                     "--pipeline-depth", "0"])


def test_ops_decode_units():
    """ops/decode: the signed-byte bias is removed exactly BEFORE
    scl/offs (bit-matching the host decode order), and pol_sum
    refuses payloads without a pol axis."""
    import jax.numpy as jnp

    from pulseportraiture_tpu.ops.decode import affine_decode

    raw = np.array([[[0, 128, 255, 7]]], np.uint8)  # (1, 1, 4)
    scl = np.array([[0.5]])
    offs = np.array([[1.0]])
    got = np.asarray(affine_decode(jnp.asarray(raw), jnp.asarray(scl),
                                   jnp.asarray(offs), jnp.float64,
                                   code="i8"))
    want = (raw.astype(np.float64) - 128.0) * 0.5 + 1.0
    assert np.array_equal(got, want)
    got_u8 = np.asarray(affine_decode(jnp.asarray(raw),
                                      jnp.asarray(scl),
                                      jnp.asarray(offs), jnp.float64,
                                      code="u8"))
    assert np.array_equal(got_u8, raw * 0.5 + 1.0)
    with pytest.raises(ValueError):
        affine_decode(jnp.asarray(raw), jnp.asarray(scl),
                      jnp.asarray(offs), jnp.float64, code="i4")

    # pol_sum: the two summand pols are baselined PER POL then summed
    # (host rm_baseline -> pscrunch order), and a payload without a
    # pol axis refuses
    from pulseportraiture_tpu.ops.decode import decode_stokes_I
    from pulseportraiture_tpu.ops.noise import min_window_baseline

    rng = np.random.default_rng(11)
    raw2 = rng.integers(0, 255, (1, 2, 3, 64)).astype(np.uint8)
    scl2 = np.ones((1, 2, 3))
    offs2 = np.zeros((1, 2, 3))
    got2 = np.asarray(decode_stokes_I(
        jnp.asarray(raw2), jnp.asarray(scl2), jnp.asarray(offs2),
        jnp.float64, code="u8", pol_sum=True))
    per_pol = raw2.astype(np.float64)
    per_pol = per_pol - np.asarray(
        min_window_baseline(jnp.asarray(per_pol)))[..., None]
    np.testing.assert_allclose(got2, per_pol[:, 0] + per_pol[:, 1],
                               rtol=0, atol=1e-12)
    with pytest.raises(ValueError):
        decode_stokes_I(jnp.asarray(raw2[:, 0]), jnp.asarray(scl2[:, 0]),
                        jnp.asarray(offs2[:, 0]), jnp.float64,
                        code="u8", pol_sum=True)
