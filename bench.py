"""Benchmark: batched wideband (phi, DM) portrait fits on one TPU chip
vs the single-core NumPy reference implementation (BASELINE.md config 2:
batch of synthetic archives at 512 chan x 2048 bin).

Measures the full fit from time-domain portraits — matmul real DFTs,
CCF phase seed, damped-Newton loop, covariance/packaging — through
fit_portrait_batch_fast (the complex-free TPU throughput path).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401  (x64 host config)
    from pulseportraiture_tpu import config
    from pulseportraiture_tpu.fit import fit_portrait_batch_fast
    from pulseportraiture_tpu.fit.reference_numpy import fit_portrait_numpy

    # single-pass bf16 DFTs + bf16 cross-spectrum storage: ~2x faster
    # end-to-end than 3-pass, and the per-harmonic quantization error
    # averages down across harmonics x channels — the |dphi| gate below
    # measures BETTER than at 'high' at these noise levels (must be set
    # before the first jit trace — the program caches it).  The
    # documented PPT_* env hooks (config.env_overrides: PPT_XSPEC,
    # PPT_DFT_PRECISION, PPT_DFT_FOLD) re-apply after the script
    # defaults so A/B runs always win.
    import os as _os

    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    # batch size amortizes the tunneled runtime's ~100 ms per-dispatch
    # floor and fills the MXU; 1280 x 512 x 2048 (f32) measures ~13%
    # faster than 640 and peaks HBM at ~13 GB of 15.75 GB (1920 OOMs).
    # CPU runs (smoke tests) keep a size that fits in host RAM.
    # PPT_NB/PPT_NCHAN/PPT_NBIN override (the standard shape knobs) so
    # single-core hosts can run the fused/unfused A/B honestly at a
    # feasible shape — the headline number stays the config-2 shape.
    NB, NCHAN, NBIN = (1280 if on_tpu else 256), 512, 2048
    NB = int(_os.environ.get("PPT_NB", NB))
    NCHAN = int(_os.environ.get("PPT_NCHAN", NCHAN))
    NBIN = int(_os.environ.get("PPT_NBIN", NBIN))
    DTYPE = jnp.float32
    P = 0.003
    NU_FIT = 1500.0

    # --- synthesize the batch on device (f32) ---------------------------
    # complex-free: known (phi, DM) injected via matmul DFT rotations
    # (jnp.fft is unusably slow on this TPU runtime); synth runs at a
    # smaller batch and tiles up, and the shared model portrait stays a
    # broadcast instead of NB materialized copies
    from pulseportraiture_tpu.models.gaussian import gen_gaussian_portrait
    from pulseportraiture_tpu.ops.fourier import irfft_mm, rfft_mm
    from pulseportraiture_tpu.ops.phasor import phase_shifts
    from pulseportraiture_tpu.synth import default_test_model

    NB_SYNTH = min(128, NB)
    tmodel = default_test_model(NU_FIT)
    freqs = jnp.linspace(1300.0, 1899.0, NCHAN, dtype=DTYPE)
    params = {k: jnp.asarray(v, DTYPE)
              for k, v in tmodel.params_pytree().items()}
    model_clean = gen_gaussian_portrait(
        params, freqs, tmodel.nu_ref, NBIN, P=P, code=tmodel.code,
        scattered=False).astype(DTYPE)

    @jax.jit
    def synth(key):
        k1, k2, k3 = jax.random.split(key, 3)
        phis = 0.1 * jax.random.uniform(k1, (NB_SYNTH,), DTYPE)
        dms = 0.003 * jax.random.uniform(k2, (NB_SYNTH,), DTYPE)
        delays = jax.vmap(
            lambda ph, dm: phase_shifts(ph, dm, 0.0, freqs, P, NU_FIT,
                                        NU_FIT))(phis, dms)
        Xr, Xi = rfft_mm(model_clean)
        k = jnp.arange(Xr.shape[-1], dtype=DTYPE)
        ang = -2.0 * jnp.pi * delays[..., None] * k  # rotate by -delays
        c, s = jnp.cos(ang), jnp.sin(ang)
        rot = irfft_mm(Xr * c - Xi * s, Xr * s + Xi * c, NBIN)
        return rot + 0.05 * jax.random.normal(k3, rot.shape, DTYPE)

    ports_s = synth(jax.random.PRNGKey(0))
    ports = jnp.tile(ports_s, (NB // NB_SYNTH, 1, 1))
    del ports_s
    # 2-D template -> fit_portrait_batch_fast vmaps it with in_axes=None
    # (no NB materialized copies in HBM)
    models = model_clean
    noise = jnp.full((NB, NCHAN), 0.05, DTYPE)
    Ps = jnp.full((NB,), P, DTYPE)
    nus = jnp.full((NB,), NU_FIT, DTYPE)
    jax.block_until_ready(ports)

    # harmonic window from the template's measured spectral support
    # (fit/portrait.model_harmonic_window; the one-time device pull of
    # the 4 MB template is amortized over the whole run).  The |dphi|
    # gate below validates it against the full-spectrum f64 oracle.
    # PPT_HARMONIC_WINDOW=off reverts to the full spectrum for A/B.
    from pulseportraiture_tpu.fit.portrait import model_harmonic_window

    _hw = _os.environ.get("PPT_HARMONIC_WINDOW", "").lower()
    if _hw == "off":
        hwin = None
    elif _hw:
        # forced integer window (tile-rounded): lets the fused/Pallas
        # A/B arms run at shapes where the content-derived window
        # would refuse — the CI interpret-mode smoke arm
        from pulseportraiture_tpu.fit.portrait import (
            resolve_harmonic_window)

        hwin = resolve_harmonic_window(int(_hw), None, NBIN)
    else:
        hwin = model_harmonic_window(np.asarray(model_clean), NBIN)

    def run():
        return fit_portrait_batch_fast(
            ports, models, noise, freqs, Ps, nus, max_iter=25,
            harmonic_window=hwin if hwin is not None else False,
        )

    # warmup/compile; all timing ends with a host transfer because
    # block_until_ready can return early under the tunneled TPU runtime
    res = run()
    _ = np.asarray(res.phi)

    # (a) synchronized latency: one batch, host sync per rep — includes
    # the tunnel round-trip, the number an interactive caller sees
    nrep = 5
    t_sync = []
    for _ in range(nrep):
        t0 = time.perf_counter()
        res = run()
        _ = np.asarray(res.phi)
        t_sync.append(time.perf_counter() - t0)
    t_lat = min(t_sync)

    # (b) pipelined throughput: enqueue K batches back-to-back, sync
    # once — steady-state rate when streaming a campaign (the per-batch
    # round-trip amortizes away; results are small and pulled async).
    # Min of 6 runs: the shared tunneled chip's load swings up to ~8x
    # within minutes; more samples give the min-of-N estimator a
    # better chance of catching an unloaded window.
    K = 8
    tKs = []
    for _ in range(6):
        t0 = time.perf_counter()
        for _ in range(K):
            res = run()
        _ = np.asarray(res.phi)
        tKs.append(time.perf_counter() - t0)
    # t_lat and tKs come from different run populations under variable
    # load, so the subtraction can go non-positive; fall back to the
    # conservative tK/K (counts one round-trip against the K batches)
    t_tpu = (min(tKs) - t_lat) / (K - 1)
    if t_tpu <= 0:
        t_tpu = min(tKs) / K
    toas_per_sec = NB / t_tpu

    # --- single-core NumPy baseline on a few portraits ------------------
    # transfer ONLY what the baseline needs: pulling the full batch
    # through the tunneled runtime is gigabytes and takes minutes
    n_base = 3
    ports_np = np.asarray(ports[:n_base], np.float64)
    model_np = np.asarray(model_clean, np.float64)
    freqs_np = np.asarray(freqs, np.float64)
    noise_np = np.full(NCHAN, 0.05)

    t0 = time.perf_counter()
    base_res = [
        fit_portrait_numpy(
            ports_np[i], model_np, noise_np, freqs_np, P, NU_FIT
        )
        for i in range(n_base)
    ]
    t_np = (time.perf_counter() - t0) / n_base
    base_toas_per_sec = 1.0 / t_np

    # --- accuracy gate: |dphi| vs NumPy ref on the same portraits -------
    dphi = max(
        abs(float(res.phi[i]) - _ref_phi_at(base_res[i], float(res.nu_DM[i]), P))
        for i in range(n_base)
    )

    # --- fused-vs-unfused A/B (ISSUE 14 tentpole b) ---------------------
    # The windowed DFT -> cross-spectrum hot path as one hand-blocked
    # program (ops/fused.py) vs the round-5 separate-ops program.  The
    # fused lane is BITWISE identical (enforced here every run: the
    # fit's phi must match to the bit — the .tim byte gates live in
    # tests/test_stream.py); the chip re-measure (Pallas variant) is
    # pre-scoped in BENCHMARKS.md.
    fused_keys = {}
    if hwin is not None:
        def timed_arm(reps=3, k=4):
            r = run()
            _ = np.asarray(r.phi)  # warm (compile) this arm's program
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(k):
                    r = run()
                _ = np.asarray(r.phi)
                ts.append((time.perf_counter() - t0) / k)
            return min(ts), np.asarray(r.phi)

        fused_prev = config.fit_fused
        try:
            config.fit_fused = False
            t_unf, phi_unf = timed_arm()
            config.fit_fused = True
            t_fus, phi_fus = timed_arm()
        finally:
            config.fit_fused = fused_prev
        fused_identical = bool(np.array_equal(phi_unf, phi_fus))
        fused_keys = {
            "fused_toas_per_sec": round(NB / t_fus, 2),
            "unfused_toas_per_sec": round(NB / t_unf, 2),
            "fused_vs_unfused": round(t_unf / t_fus, 3),
            "fused_identical": fused_identical,
        }
        if not fused_identical:
            raise SystemExit(
                "bench: fused-vs-unfused phi NOT bitwise identical — "
                "the fused program drifted (ops/fused.py)")
        # --- Pallas arm (ISSUE 16): the hand-placed channel-tile
        # kernel vs the fused scan program.  Runs when the kernel lane
        # resolves on (TPU 'auto', or PPT_FIT_PALLAS=on forcing
        # interpret mode off-TPU — the CI smoke arm).  Same bitwise
        # gate as the scan: interpret or compiled, phi must not drift.
        from pulseportraiture_tpu.ops.fused import use_fit_pallas

        if use_fit_pallas():
            pallas_prev = config.fit_pallas
            try:
                config.fit_fused = True
                config.fit_pallas = True
                t_pal, phi_pal = timed_arm()
            finally:
                config.fit_fused = fused_prev
                config.fit_pallas = pallas_prev
            pallas_identical = bool(np.array_equal(phi_fus, phi_pal))
            fused_keys.update({
                "pallas_toas_per_sec": round(NB / t_pal, 2),
                "pallas_vs_fused": round(t_fus / t_pal, 3),
                "pallas_interpret": bool(not on_tpu),
                "pallas_identical": pallas_identical,
            })
            if not pallas_identical:
                raise SystemExit(
                    "bench: pallas-vs-fused phi NOT bitwise identical "
                    "— the Pallas kernel drifted (ops/fused.py)")
        # optional re-tune sweep of (harmonic_window,
        # cross_spectrum_dtype) against the FUSED program
        # (PPT_RETUNE=1; the decision table lives in BENCHMARKS.md) —
        # kept off the default path so CI smoke stays fast
        if _os.environ.get("PPT_RETUNE", "") == "1":
            sweep = []
            xspec_prev = config.cross_spectrum_dtype
            try:
                config.fit_fused = True
                for win in sorted({hwin, min(2 * hwin, NBIN // 2 + 1)}):
                    for xspec in ("bfloat16", None):
                        config.cross_spectrum_dtype = xspec

                        def run_w(win=win):
                            return fit_portrait_batch_fast(
                                ports, models, noise, freqs, Ps, nus,
                                max_iter=25, harmonic_window=win)

                        r = run_w()
                        _ = np.asarray(r.phi)
                        t0 = time.perf_counter()
                        for _ in range(4):
                            r = run_w()
                        _ = np.asarray(r.phi)
                        tw = (time.perf_counter() - t0) / 4
                        dphi_w = max(
                            abs(float(r.phi[i]) - _ref_phi_at(
                                base_res[i], float(r.nu_DM[i]), P))
                            for i in range(n_base))
                        sweep.append({
                            "harmonic_window": int(win),
                            "cross_spectrum_dtype": str(xspec),
                            "toas_per_sec": round(NB / tw, 2),
                            "max_dphi_vs_numpy": float(f"{dphi_w:.2e}"),
                        })
            finally:
                config.cross_spectrum_dtype = xspec_prev
                config.fit_fused = fused_prev
            fused_keys["retune"] = sweep

    # --- MFU accounting (analytic FLOP count / measured device time) ----
    # The fit's MXU work is the matmul DFT of the data batch: two
    # (NCHAN, NBIN) x (NBIN, NHARM) matmuls (cos + sin weights) per
    # element at 2 flops/MAC; 'default' precision is a single bf16
    # pass, so the arithmetic count equals the analytic count.  The
    # CCF-seed inverse DFT adds one (NHARM,) x (NHARM, 2*NBIN) pair per
    # element.  Everything else (cross-spectrum assembly, ~2-3 moment
    # passes) is VPU elementwise/transcendental work with no meaningful
    # peak to normalize against, so it is EXCLUDED — mfu here is
    # "fraction of MXU peak spent on the DFTs", a lower bound on how
    # far from roofline the whole fit runs (the moment passes keep the
    # chip busy between matmuls).
    # the harmonic window shrinks the DFT output width (honest
    # accounting: count the matmul actually dispatched, not the full-
    # spectrum one)
    nharm = hwin if hwin is not None else NBIN // 2 + 1
    dft_flops = NB * 2 * (2.0 * NCHAN * NBIN * nharm)
    ccf_flops = NB * 2 * (2.0 * nharm * 2 * NBIN)
    mxu_flops = dft_flops + ccf_flops
    tflops = mxu_flops / t_tpu / 1e12
    # bf16 MXU peak per chip: v5e 197 TFLOPS, v4 275, v5p 459
    from benchmarks.common import mxu_peak_tflops

    peak = mxu_peak_tflops(dev)

    out = {
        "metric": f"wideband (phi,DM) portrait fits, "
                  f"{NCHAN}ch x {NBIN}bin",
        "value": round(toas_per_sec, 2),
        "unit": "TOAs/sec",
        "vs_baseline": round(toas_per_sec / base_toas_per_sec, 1),
        "baseline_toas_per_sec": round(base_toas_per_sec, 3),
        "batch": NB,
        "batch_latency_ms": round(t_lat * 1e3, 1),
        "device": str(dev),
        "dtype": "float32" if on_tpu else str(np.dtype("float32")),
        "cross_spectrum_dtype": str(config.cross_spectrum_dtype),
        "max_dphi_vs_numpy": float(f"{dphi:.2e}"),
        "accuracy_gate_1e-4": bool(dphi < 1e-4),
        "harmonic_window": hwin,
        "dft_tflops": round(tflops, 1),
        "mfu": round(tflops / peak, 3) if peak else None,
    }
    out.update(fused_keys)
    print(json.dumps(out))


def _ref_phi_at(ref, nu, P):
    """Transform the NumPy reference phi (at NU_FIT=1500) to nu."""
    from pulseportraiture_tpu.config import Dconst

    phi = ref["phi"] + (Dconst * ref["DM"] / P) * (nu**-2.0 - 1500.0**-2.0)
    return ((phi + 0.5) % 1.0) - 0.5


if __name__ == "__main__":
    main()
