"""Channel-zap proposals and application (ppzap equivalent).

Parity target: reference ppzap.py:24-104.  Two paths, as in the
reference CLI: the model-less median algorithm on per-channel noise
levels, and the model-based path using GetTOAs red-chi2/S-N cuts
(pipeline/toas.get_channels_to_zap).  Where the reference only emits
`paz` shell commands, this module can also apply the zaps directly
(weight edits through the archive writer) since there is no external
PSRCHIVE to delegate to.

Since ISSUE 12 the median algorithm's iterating lives in
``quality/excision.py`` as ONE batched program: the host lane is the
reference loop vectorized over subints (the digit oracle), the device
lane runs every subint's whole iterative cut in a single jitted
dispatch — zero per-iteration host round-trips (round 14's device lane
still pulled each iteration's median to host).  The same traceable
core fuses into the streaming raw-bucket program (pipeline/stream.py
``zap_inline``), so the offline tool and the inline service lane
cannot drift.
"""

import time

import numpy as np

from ..io.psrfits import read_archive
from ..quality.excision import (zap_bunch, zap_keep_device,  # noqa: F401
                                zap_keep_np, zap_lists_from_masks)
from ..telemetry import NULL_TRACER


def resolve_zap_device(device=None):
    """Tri-state resolution of the zap statistics lane: None follows
    config.zap_device; 'auto' = device on TPU backends (where the
    streaming lane's noise_stds already live on chip and the batched
    one-dispatch cut beats a host loop); True/False force."""
    from .. import config

    if device is None:
        device = getattr(config, "zap_device", "auto")
    from ..tune.capability import resolve_auto

    return resolve_auto("zap_device", device)


def resolve_zap_nstd(nstd=None):
    """None follows ``config.zap_nstd`` (PPT_ZAP_NSTD); explicit
    values pass through (loud on non-positive)."""
    from .. import config

    if nstd is None:
        nstd = getattr(config, "zap_nstd", 3.0)
    nstd = float(nstd)
    if not nstd > 0:
        raise ValueError(f"zap nstd must be > 0, got {nstd}")
    return nstd


def get_zap_channels(data, nstd=None, device=None, tracer=None):
    """Iterative median + nstd*std cut on per-channel noise levels
    (reference ppzap.py:24-54).  data: a load_data DataBunch.
    Returns [subint][channel indices], one row per TRUE subint (empty
    rows for subints with no usable channels) — the same indexing
    GetTOAs.get_channels_to_zap uses, and what print_paz_cmds' ``-w``
    flags and apply_zaps consume.  (The reference returns one row per
    OK subint, which silently mis-pairs those consumers on any archive
    with a fully-zapped subint.)

    nstd: threshold in stds (None = config.zap_nstd / PPT_ZAP_NSTD).
    device: tri-state (resolve_zap_device / config.zap_device /
    PPT_ZAP_DEVICE) — route the WHOLE batched iterative cut through
    one jitted device dispatch instead of the host loop; the flagged
    channel lists are digit-identical either way (median bit-exact,
    std within ~1 ulp of accumulation — guarded by tests and
    bench_zap's list gate).  tracer: optional telemetry sink; emits
    one ``zap_propose`` event (n_channels, n_iter, device, wall_s)."""
    nstd = resolve_zap_nstd(nstd)
    use_device = resolve_zap_device(device)
    ok = np.asarray(data.ok_isubs, int)
    nchan = int(data.nchan)
    noise = np.asarray(data.noise_stds[ok, 0])
    keep0 = np.zeros((len(ok), nchan), bool)
    for j, isub in enumerate(ok):
        keep0[j, np.asarray(data.ok_ichans[isub], int)] = True
    t0 = time.perf_counter()
    if use_device:
        keep, iters = zap_keep_device(noise, keep0, nstd)
    else:
        keep, iters = zap_keep_np(noise, keep0, nstd)
    wall = time.perf_counter() - t0
    ok_lists = zap_lists_from_masks(keep0, keep)
    zap_channels = [[] for _ in range(int(data.nsub))]
    for isub, z in zip(ok, ok_lists):
        zap_channels[int(isub)] = z
    if tracer is not None and tracer.enabled:
        tracer.emit("zap_propose",
                    datafile=str(data.get("filename", "")),
                    n_channels=sum(len(z) for z in zap_channels),
                    n_iter=int(np.max(iters, initial=0)),
                    device=bool(use_device), wall_s=round(wall, 6))
    return zap_channels


def print_paz_cmds(datafiles, zap_list, all_subs=False, modify=True,
                   outfile=None, quiet=False, append=False):
    """Emit PSRCHIVE `paz` commands for a zap list (reference
    ppzap.py:57-104) — for users whose downstream tooling is PSRCHIVE.
    Returns the command lines.

    outfile is WRITTEN (truncated) by default; pass ``append=True`` to
    add to an existing command file.  (This used to open in append
    mode unconditionally, so every rerun silently duplicated the whole
    command set in the file.)"""
    lines = []
    for iarch, datafile in enumerate(datafiles):
        count = sum(len(z) for z in zap_list[iarch])
        if not count:
            continue
        if modify:
            paz_outfile = datafile
        else:
            ii = datafile[::-1].find(".")
            paz_outfile = (datafile + ".zap" if ii < 0
                           else datafile[:-ii] + "zap")
            lines.append(f"paz -e zap {datafile}")
        last = ""
        for isub, bad_ichans in enumerate(zap_list[iarch]):
            for bad in bad_ichans:
                if not all_subs:
                    lines.append(
                        f"paz -m -I -z {bad} -w {isub} {paz_outfile}")
                else:
                    line = f"paz -m -z {bad} {paz_outfile}"
                    if line != last:
                        lines.append(line)
                    last = line
    if outfile is not None:
        with open(outfile, "a" if append else "w") as f:
            f.write("".join(line + "\n" for line in lines))
        if not quiet:
            print(f"Wrote {outfile}.")
    elif not quiet:
        for line in lines:
            print(line)
    return lines


def apply_zaps(datafile, zap_channels, all_subs=False, outfile=None,
               quiet=False, tracer=None):
    """Zero the weights of flagged channels directly in the archive —
    the internal replacement for shelling out to `paz`.
    zap_channels: [subint][channel indices].

    NOTE: this rewrites the archive, and the PSRFITS writer
    re-quantizes DATA from the decoded floats — the weights change
    losslessly but the data picks up ~half-LSB requantization noise.
    For a bit-exact offline-zap fit (the inline lane's digit oracle),
    feed the lists to the streaming drivers' ``zap_channels=`` option
    (quality.zap_bunch under the hood) instead of round-tripping the
    file."""
    tracer = NULL_TRACER if tracer is None else tracer
    arch = read_archive(datafile)
    w = arch.get_weights()
    for isub, chans in enumerate(zap_channels):
        if not len(chans):
            continue
        if all_subs:
            w[:, np.asarray(chans, int)] = 0.0
        elif isub < len(w):
            w[isub, np.asarray(chans, int)] = 0.0
    arch.set_weights(w)
    arch.unload(outfile or datafile)
    n = sum(map(len, zap_channels))
    if tracer.enabled:
        tracer.emit("zap_apply", datafile=str(datafile),
                    n_channels=int(n))
    if not quiet:
        print(f"Zapped {n} channel entries in "
              f"{outfile or datafile}.")
    return w
