"""Content-addressed result-cache benchmark (ISSUE 17 acceptance
gate): Zipfian repeat-heavy campaign replay against a warm
``ToaServer`` with the cache on vs off, plus the 2-host router arm
proving a hit never touches a host.

Real timing campaigns re-fit the same (archive, template, options)
triples constantly — nightly re-runs, pipeline restarts, shared
archives across users.  The cache (serve/cache.py) keys completed
``.tim`` payloads by SHA-256 over the archive/template BYTES and the
frozen fit options; a hit is an O(1) atomic byte copy of the stored
entry, byte-identical to a fresh fit by construction.

Arms (one process, bench_router's virtual-device discipline):
  references — warm cache-OFF server fits each unique archive once:
              the fresh-fit ``.tim`` bytes every hit is gated against.
  off       — the Zipf(s) request replay (PPT_NREQ draws over
              PPT_NARCH archives) on the cache-off server: the
              baseline wall.
  on        — a cache-ON server: one populate pass over the unique
              archives (all misses, all stored), then the SAME Zipf
              replay — every request must HIT (``all_hits``), every
              hit ``.tim`` must be byte-identical to its fresh-fit
              reference (``hit_identical``), and at high skew the
              replay must run >= PPT_CACHE_SPEEDUP_GATE x faster than
              the off arm (``speedup_ok``; gate 5.0, 0 disables for
              smoke runs).
  perturb   — one archive copied and ONE byte of its data payload
              flipped: the submit MUST miss (``perturb_missed``) and
              fit fresh — content addressing, not path addressing.
  router@H  — H emulated hosts behind a ToaRouter holding its OWN
              cache: populate pass places fits on hosts, the Zipf
              replay resolves entirely router-side — per-host
              ``n_requests`` must NOT move during the hit replay
              (``router_hits_bypass_hosts``), bytes gated identical.

Telemetry traces (PPT_TELEMETRY base) for the on/router arms must
schema-validate with the cache section populated (n_cache_hit,
cache_hit_rate, cache_bytes_served).

Knobs via env: PPT_NARCH (8 unique archives), PPT_NSUB (4), PPT_NCHAN
(32), PPT_NBIN (128), PPT_NREQ (40 Zipf draws), PPT_ZIPF_S (1.1),
PPT_CACHE_SPEEDUP_GATE (5.0), PPT_NHOSTS (2), PPT_CAMPAIGN_CACHE,
PPT_TELEMETRY.  Prints ONE JSON line.
"""

import io
import json
import os
import shutil
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _ensure_devices(n):
    """Force >= n virtual CPU devices BEFORE jax initializes (the
    bench_stream discipline) so each emulated router host owns its
    own device."""
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def main():
    NHOSTS = max(1, int(os.environ.get("PPT_NHOSTS", 2)))
    _ensure_devices(NHOSTS)
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()

    import jax
    import numpy as np

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.serve import (InProcTransport, ToaClient,
                                            ToaRouter, ToaServer)
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = max(2, int(os.environ.get("PPT_NARCH", 8)))
    NSUB = int(os.environ.get("PPT_NSUB", 4))
    NCHAN = int(os.environ.get("PPT_NCHAN", 32))
    NBIN = int(os.environ.get("PPT_NBIN", 128))
    NREQ = max(4, int(os.environ.get("PPT_NREQ", 40)))
    ZIPF_S = float(os.environ.get("PPT_ZIPF_S", 1.1))
    GATE = float(os.environ.get("PPT_CACHE_SPEEDUP_GATE", 5.0))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"rc{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * (i % 50), dDM=1e-4 * (i % 40),
                             noise_stds=0.05, quiet=True, rng=i)
        files.append(path)

    # the Zipf(s) replay sequence: rank-r archive drawn with weight
    # 1/r^s — the repeat-heavy access pattern the cache exists for
    rng = np.random.default_rng(0)
    w = 1.0 / np.arange(1, NARCH + 1, dtype=float) ** ZIPF_S
    w /= w.sum()
    seq = [int(k) for k in rng.choice(NARCH, size=NREQ, p=w)]
    uniq_hot = len(set(seq))

    out_root = os.path.join(root, "cache_out")
    shutil.rmtree(out_root, ignore_errors=True)
    os.makedirs(out_root, exist_ok=True)

    def tim(arm, j):
        return os.path.join(out_root, f"{arm}_{j}.tim")

    def run_replay(submit, arm):
        """Submit the full Zipf sequence, then collect; returns wall."""
        t0 = time.perf_counter()
        handles = [submit([files[k]], mpath, tim_out=tim(arm, j),
                          name=f"{arm}{j}")
                   for j, k in enumerate(seq)]
        for h in handles:
            h.result(3600)
        return time.perf_counter() - t0

    # ---- references + cache-off baseline (one warm server) --------
    srv = ToaServer(nsub_batch=64, quiet=True).start()
    client = ToaClient(srv)
    client.get_TOAs([files[0]], mpath, timeout=600)  # warm jit caches
    for i in range(NARCH):
        client.get_TOAs([files[i]], mpath, tim_out=tim("ref", i),
                        timeout=600)
    off_wall = run_replay(srv.submit, "off")
    assert srv.stats()["cache_hits"] == 0, "cache-off server hit?"
    srv.stop()

    # ---- cache-ON server: populate, then an all-hit replay --------
    trace = f"{trace_base}.cache" if trace_base else None
    cdir = os.path.join(out_root, "rcache_server")
    srv = ToaServer(nsub_batch=64, quiet=True, telemetry=trace,
                    result_cache=True, cache_dir=cdir).start()
    client = ToaClient(srv)
    client.get_TOAs([files[0]], mpath, timeout=600)  # warm (+ stores)
    for i in range(NARCH):  # populate pass: every unique archive
        client.get_TOAs([files[i]], mpath, tim_out=tim("pop", i),
                        timeout=600)
    hits0 = srv.stats()["cache_hits"]
    on_wall = run_replay(srv.submit, "on")
    stats = srv.stats()
    n_hits = stats["cache_hits"] - hits0
    all_hits = n_hits == NREQ
    assert all_hits, (
        f"warm replay expected {NREQ} cache hits, got {n_hits} — "
        "the populate pass or the content key is broken")
    hit_identical = all(
        open(tim("on", j), "rb").read()
        == open(tim("ref", k), "rb").read()
        for j, k in enumerate(seq))
    assert hit_identical, (
        "a cache hit's .tim diverged from its fresh-fit reference — "
        "the byte-identity contract is broken")
    speedup = off_wall / max(on_wall, 1e-9)
    speedup_ok = bool(speedup >= GATE) if GATE > 0 else None
    assert speedup_ok is not False, (
        f"repeat-heavy replay sped up only {speedup:.2f}x with the "
        f"cache on (gate {GATE}x) — hits are not O(1)")

    # ---- one-byte perturbation MUST miss ---------------------------
    pert = os.path.join(out_root, "perturbed.fits")
    shutil.copyfile(files[0], pert)
    with open(pert, "r+b") as fh:
        fh.seek(os.path.getsize(pert) - 64)  # inside the data payload
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0x01]))
    misses0 = srv.cache.misses
    client.get_TOAs([pert], mpath, tim_out=tim("pert", 0), timeout=600)
    perturb_missed = (srv.cache.misses == misses0 + 1
                      and srv.stats()["cache_hits"] == stats["cache_hits"])
    assert perturb_missed, (
        "a one-byte archive perturbation was served from cache — "
        "content addressing is broken")
    srv.stop()
    if trace:
        summary = telemetry.report(trace, file=io.StringIO())
        # NREQ replay hits + the populate pass re-hitting the entry
        # the warmup fit already stored
        assert summary["n_cache_hit"] >= NREQ, summary["n_cache_hit"]
        assert summary["cache_bytes_served"] > 0, summary
        assert summary["n_cache_store"] >= NARCH, summary

    # ---- router arm: hits never touch a host -----------------------
    router_arm = None
    if NHOSTS >= 2:
        trace = f"{trace_base}.rcache" if trace_base else None
        rdir = os.path.join(out_root, "rcache_router")
        servers = [
            ToaServer(nsub_batch=64, quiet=True,
                      stream_devices=[jax.local_devices()[h]]).start()
            for h in range(NHOSTS)]
        for s in servers:
            ToaClient(s).get_TOAs([files[0]], mpath, timeout=600)
        router = ToaRouter(
            [InProcTransport(s, label=f"host{h}")
             for h, s in enumerate(servers)],
            telemetry=trace, result_cache=True, cache_dir=rdir)
        for i in range(NARCH):  # populate: fits placed on hosts
            router.submit([files[i]], mpath, tim_out=tim("rpop", i),
                          name=f"rpop{i}").result(3600)
        placed0 = {lbl: st["n_requests"]
                   for lbl, st in router.stats().items()}
        r_wall = run_replay(router.submit, "rtr")
        placed1 = {lbl: st["n_requests"]
                   for lbl, st in router.stats().items()}
        bypass = placed0 == placed1 and router.cache_hits == NREQ
        assert bypass, (
            f"router hit replay touched a host: {placed0} -> "
            f"{placed1}, cache_hits={router.cache_hits}")
        r_identical = all(
            open(tim("rtr", j), "rb").read()
            == open(tim("ref", k), "rb").read()
            for j, k in enumerate(seq))
        assert r_identical, "a router-side hit diverged from one-shot"
        router.close()
        for s in servers:
            s.stop()
        router_arm = {
            "hosts": NHOSTS,
            "replay_wall_s": round(r_wall, 3),
            "router_hits_bypass_hosts": bool(bypass),
            "tim_identical": bool(r_identical),
        }
        if trace:
            summary = telemetry.report(trace, file=io.StringIO())
            assert summary["n_cache_hit"] == NREQ, summary
            assert summary["n_route_done"] == NARCH + NREQ, summary
            router_arm["cache_hit_rate"] = round(
                summary["cache_hit_rate"], 3)

    print(json.dumps({
        "metric": f"Zipf(s={ZIPF_S}) replay of {NREQ} requests over "
                  f"{NARCH} archives x {NSUB}sub x {NCHAN}ch x "
                  f"{NBIN}bin, warm server, result cache on vs off",
        "value": round(NREQ / max(on_wall, 1e-9), 2),
        "unit": "requests/sec",
        "off_requests_per_sec": round(NREQ / max(off_wall, 1e-9), 2),
        "cache_speedup": round(speedup, 3),
        "speedup_ok": speedup_ok,
        "speedup_gate": GATE,
        "zipf_s": ZIPF_S,
        "unique_archives_drawn": uniq_hot,
        "all_hits": bool(all_hits),
        "hit_identical": bool(hit_identical),
        "perturb_missed": bool(perturb_missed),
        "cache_bytes_served": stats["cache_bytes"],
        "router": router_arm,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
