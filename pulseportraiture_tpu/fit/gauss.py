"""Gaussian template fitting: profile and evolving-portrait fits.

TPU-native replacement for the reference's lmfit-based template
builders (fit_gaussian_profile pplib.py:1922-2002,
fit_gaussian_portrait pplib.py:2005-2133), driven by the JAX
Levenberg-Marquardt engine in fit/lm.py.  Model generation is the
analytic-FT Gaussian portrait from models/gaussian.py, so the Jacobian
comes from autodiff through the FFT instead of finite differences.

Flat parameter layouts mirror the reference exactly (so .gmodel round-
tripping and ppgauss-style iteration carry over):

profile:  [dc, tau_bins, (loc, wid, amp) * ngauss]
portrait: [dc, tau_bins, (loc, mloc, wid, mwid, amp, mamp) * ngauss]
          (+ per-join (phase, DM) pairs, + scattering index, handled as
          separate arguments like the reference's lmfit Parameters)
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Dconst, wid_max
from ..ops.gaussian import gaussian_profile_FT
from ..ops.phasor import cexp
from ..ops.scattering import scattering_profile_FT
from ..utils.bunch import DataBunch
from .lm import levenberg_marquardt

__all__ = ["fit_gaussian_profile", "fit_gaussian_portrait",
           "gen_gaussian_profile_flat", "gen_gaussian_portrait_flat"]


def _profile_FT_flat(theta, nbin):
    """rFFT of DC + ngauss Gaussians + scattering, theta as in the
    profile layout (tau in bins)."""
    nharm = nbin // 2 + 1
    dc, tau = theta[0], theta[1]
    locs, wids, amps = theta[2::3], theta[3::3], theta[4::3]
    gFT = gaussian_profile_FT(nharm, locs[:, None], wids[:, None],
                              amps[:, None])
    pFT = jnp.sum(gFT, axis=0)
    pFT = pFT.at[0].add(dc * nbin)
    return pFT * scattering_profile_FT(tau / nbin, nharm)


def gen_gaussian_profile_flat(theta, nbin):
    """Phase-domain profile from the flat layout (reference
    gen_gaussian_profile, pplib.py:859-883; tau in bins)."""
    return jnp.fft.irfft(_profile_FT_flat(jnp.asarray(theta, float), nbin),
                         n=nbin)


def _profile_resid(theta, data, errs):
    nbin = data.shape[-1]
    return (data - jnp.fft.irfft(_profile_FT_flat(theta, nbin), n=nbin)) / errs


def fit_gaussian_profile(data, init_params, errs, fit_flags=None,
                         fit_scattering=False, quiet=True):
    """Fit DC + ngauss Gaussians (+ scattering tau) to a profile.

    init_params: [dc, tau_bins, (loc, wid, amp)*ngauss].  Bounds follow
    the reference: tau >= 0, 0 <= wid <= wid_max, amp >= 0
    (pplib.py:1954-1974).  fit_flags covers the NON-scattering params
    (dc + 3*ngauss entries) as in the reference signature; scattering
    is toggled by fit_scattering.  Returns DataBunch(fitted_params,
    fit_errs, residuals, chi2, dof, red_chi2).
    """
    data = jnp.asarray(data, float)
    errs_arr = jnp.broadcast_to(jnp.asarray(errs, float), data.shape)
    x0 = np.asarray(init_params, float)
    n = len(x0)
    ngauss = (n - 2) // 3
    vary = np.ones(n, bool)
    if fit_flags is not None:
        ff = [bool(f) for f in fit_flags]
        vary[0] = ff[0]
        vary[2:] = ff[1:]
    vary[1] = bool(fit_scattering)
    nbin = data.shape[-1]
    lower = np.full(n, -np.inf)
    upper = np.full(n, np.inf)
    lower[1] = 0.0
    # wids: reference uses min=0 (pplib.py:1969), but an exactly-zero
    # width is a stationary trap (all derivatives vanish, the component
    # can never regrow).  A half-bin floor is below anything resolvable
    # and keeps the optimizer out of the trap.
    lower[3::3] = 0.5 / nbin
    upper[3::3] = wid_max
    lower[4::3] = 0.0  # amps
    res = levenberg_marquardt(_profile_resid, x0, aux=(data, errs_arr),
                              lower=lower, upper=upper, vary=vary)
    residuals = np.asarray(_profile_resid(res.x, data, errs_arr)) * \
        np.asarray(errs_arr)
    dof = int(res.dof)
    out = DataBunch(
        fitted_params=np.asarray(res.x),
        fit_errs=np.asarray(res.x_err),
        residuals=residuals,
        chi2=float(res.chi2),
        dof=dof,
        red_chi2=float(res.chi2) / max(dof, 1),
    )
    if not quiet:
        print(f"Gaussians: {ngauss}  DoF: {dof}  "
              f"reduced chi-sq: {out.red_chi2:.2f}")
    return out


# --------------------------------------------------------------------------
# Portrait fit
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("code", "nbin", "njoin"))
def _portrait_FT_flat(theta, join_theta, alpha_s, freqs, nu_ref, P,
                      join_mask, code="000", nbin=None, njoin=0):
    """(nchan, nharm) model rFFT from the flat portrait layout.

    theta: [dc, tau_bins, (loc, mloc, wid, mwid, amp, mamp)*ngauss];
    join_theta: (njoin, 2) of (phase, DM) applied to channels selected
    by join_mask (njoin, nchan); alpha_s: scattering index.
    """
    from ..models.gaussian import apply_scattering_FT, gaussian_components_FT

    nharm = nbin // 2 + 1
    params = {
        "dc": theta[0],
        "locs": theta[2::6], "mlocs": theta[3::6],
        "wids": theta[4::6], "mwids": theta[5::6],
        "amps": theta[6::6], "mamps": theta[7::6],
    }
    pFT = gaussian_components_FT(params, freqs, nu_ref, nharm, code)
    # tau in this layout is in bins (the fitter's unit): /nbin -> rotations
    pFT = apply_scattering_FT(pFT, theta[1] / nbin, alpha_s, freqs, nu_ref)
    if njoin:
        k = jnp.arange(nharm, dtype=freqs.dtype)
        for ij in range(njoin):
            phi, DM = join_theta[ij, 0], join_theta[ij, 1]
            delays = phi + (Dconst * DM / P) * (freqs**-2.0 - nu_ref**-2.0)
            rot = jnp.conj(cexp(2.0 * jnp.pi * delays[:, None] * k))
            pFT = jnp.where(join_mask[ij][:, None], pFT * rot, pFT)
    return pFT


def gen_gaussian_portrait_flat(theta, freqs, nu_ref, nbin, alpha_s,
                               code="000", join_theta=None, join_mask=None,
                               P=None):
    """Phase-domain portrait from the flat layout (reference
    gen_gaussian_portrait, pplib.py:886-963, incl. JOIN rotations)."""
    theta = jnp.asarray(theta, float)
    freqs = jnp.asarray(freqs, float)
    njoin = 0 if join_theta is None else int(np.shape(join_theta)[0])
    if join_theta is None:
        join_theta = jnp.zeros((0, 2))
        join_mask = jnp.zeros((0, len(freqs)), bool)
    pFT = _portrait_FT_flat(theta, jnp.asarray(join_theta),
                            jnp.asarray(alpha_s, float), freqs,
                            jnp.asarray(nu_ref, float),
                            jnp.asarray(1.0 if P is None else P, float),
                            jnp.asarray(join_mask), code=code, nbin=nbin,
                            njoin=njoin)
    return jnp.fft.irfft(pFT, n=nbin, axis=-1)


def _make_portrait_resid(code, nbin, njoin, nmain):
    """Residual over the concatenated [theta, join.flat, alpha_s]."""

    def resid(x, data, errs, freqs, nu_ref, P, join_mask):
        theta = x[:nmain]
        join_theta = x[nmain:nmain + 2 * njoin].reshape(njoin, 2)
        alpha_s = x[-1]
        pFT = _portrait_FT_flat(theta, join_theta, alpha_s, freqs, nu_ref,
                                P, join_mask, code=code, nbin=nbin,
                                njoin=njoin)
        model = jnp.fft.irfft(pFT, n=nbin, axis=-1)
        return ((data - model) / errs[:, None]).ravel()

    return resid


_PORTRAIT_RESID_CACHE = {}


def fit_gaussian_portrait(data, init_params, scattering_index, errs,
                          fit_flags, fit_scattering_index, freqs, nu_ref,
                          model_code="000", join_params=None, P=None,
                          quiet=True):
    """Fit evolving Gaussian components to an (nchan, nbin) portrait.

    init_params: [dc, tau_bins, (loc, mloc, wid, mwid, amp, mamp)*g];
    fit_flags: same length; join_params = (join_ichans, values, flags)
    with values/flags = [phase1, DM1, phase2, DM2, ...] as in the
    reference (pplib.py:2073-2092).  Bounds: tau >= 0,
    0 <= wid <= wid_max, amp >= 0.  Returns DataBunch(fitted_params,
    fit_errs, scattering_index, scattering_index_err, join_fit, chi2,
    dof, red_chi2, residuals).
    """
    data = jnp.asarray(data, float)
    nchan, nbin = data.shape
    errs = jnp.broadcast_to(jnp.asarray(errs, float), (nchan,))
    freqs = jnp.asarray(freqs, float)
    x0_main = np.asarray(init_params, float)
    nmain = len(x0_main)
    vary_main = np.asarray(fit_flags, bool)

    if join_params:
        join_ichans, join_vals, join_flags = join_params
        njoin = len(join_ichans)
        join_mask = np.zeros((njoin, nchan), bool)
        for ij, ichans in enumerate(join_ichans):
            join_mask[ij, np.asarray(ichans)] = True
        x0_join = np.asarray(join_vals, float)
        vary_join = np.asarray(join_flags, bool)
    else:
        njoin = 0
        join_mask = np.zeros((0, nchan), bool)
        x0_join = np.zeros(0)
        vary_join = np.zeros(0, bool)

    x0 = np.concatenate([x0_main, x0_join, [float(scattering_index)]])
    vary = np.concatenate([vary_main, vary_join, [bool(fit_scattering_index)]])
    n = len(x0)
    lower = np.full(n, -np.inf)
    upper = np.full(n, np.inf)
    lower[1] = 0.0
    lower[4:nmain:6] = 0.5 / nbin  # wids: half-bin floor (see profile fit)
    upper[4:nmain:6] = wid_max
    lower[6:nmain:6] = 0.0       # amps

    key = (model_code, nbin, njoin, nmain)
    if key not in _PORTRAIT_RESID_CACHE:
        _PORTRAIT_RESID_CACHE[key] = _make_portrait_resid(
            model_code, nbin, njoin, nmain)
    resid = _PORTRAIT_RESID_CACHE[key]

    aux = (data, errs, freqs, jnp.asarray(nu_ref, float),
           jnp.asarray(1.0 if P is None else P, float),
           jnp.asarray(join_mask))
    res = levenberg_marquardt(resid, x0, aux=aux, lower=lower, upper=upper,
                              vary=vary, max_iter=200)
    x = np.asarray(res.x)
    x_err = np.asarray(res.x_err)
    residuals = np.asarray(resid(res.x, *aux)).reshape(nchan, nbin) * \
        np.asarray(errs)[:, None]
    dof = int(res.dof)
    out = DataBunch(
        fitted_params=x[:nmain],
        fit_errs=x_err[:nmain],
        join_fit=x[nmain:nmain + 2 * njoin],
        join_fit_errs=x_err[nmain:nmain + 2 * njoin],
        scattering_index=float(x[-1]),
        scattering_index_err=float(x_err[-1]),
        residuals=residuals,
        chi2=float(res.chi2),
        dof=dof,
        red_chi2=float(res.chi2) / max(dof, 1),
        nfev=int(res.nfev),
    )
    if not quiet:
        print(f"Gaussian portrait fit: ngauss={(nmain - 2) // 6} "
              f"DoF={dof} reduced chi-sq: {out.red_chi2:.2f}")
    return out
