"""CLI tools end-to-end: the reference's examples/example.py workflow
driven entirely through the command-line entry points —
make fake data -> ppalign -> ppgauss/ppspline -> pptoas -> ppzap —
asserting injected-dDM recovery from the emitted .tim file
(SURVEY §4; this doubles as the integration test of the whole stack).
"""

import re

import numpy as np
import pytest

from pulseportraiture_tpu.cli import (ppalign, ppfactory, ppgauss,
                                      pproute, ppserve, ppspline,
                                      pptime, pptoas, ppzap)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J2145-0750", "RAJ": "21:45:50.5", "DECJ": "-07:50:18.5",
       "P0": 0.016052, "PEPOCH": 55000.0, "DM": 9.003}
DDMS = [4e-4, -2e-4]


@pytest.fixture(scope="module")
def workspace(tmp_path_factory):
    root = tmp_path_factory.mktemp("cli")
    model = default_test_model(1500.0)
    files = []
    for i, dDM in enumerate(DDMS):
        path = str(root / f"example-{i + 1}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=3, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                         dDM=dDM, start_MJD=MJD(55150 + 10 * i, 0.3),
                         noise_stds=0.07, dedispersed=False, quiet=True,
                         rng=50 + i)
        files.append(path)
    meta = root / "meta.txt"
    meta.write_text("\n".join(files) + "\n")
    return root, str(meta), files


def test_ppalign_cli(workspace):
    root, meta, files = workspace
    rc = ppalign.main(["-M", meta, "--niter", "2", "-o",
                       str(root / "avg.fits")])
    assert rc == 0
    assert (root / "avg.fits").exists()


def test_ppgauss_cli(workspace):
    root, meta, files = workspace
    rc = ppgauss.main(["-d", str(root / "avg.fits"), "--niter", "2",
                       "--fitloc", "-m", "CLI_MODEL",
                       "-o", str(root / "avg.gmodel"),
                       "-e", str(root / "avg.gmodel_errs")])
    assert rc == 0
    text = (root / "avg.gmodel").read_text()
    assert text.startswith("MODEL   CLI_MODEL")
    assert "COMP01" in text
    assert (root / "avg.gmodel_errs").exists()


@pytest.mark.slow
def test_ppspline_cli(workspace):
    root, meta, files = workspace
    # self-sufficient under -m slow, where the tier-1 ppalign test
    # that normally writes avg.fits into the module workspace is
    # deselected
    if not (root / "avg.fits").exists():
        assert ppalign.main(["-M", meta, "--niter", "2", "-o",
                             str(root / "avg.fits")]) == 0
    rc = ppspline.main(["-d", str(root / "avg.fits"),
                        "-o", str(root / "avg.spl"),
                        "-S", "50.0", "--quiet"])
    assert rc == 0
    assert (root / "avg.spl").exists()


@pytest.mark.parametrize("template", [
    "avg.gmodel",
    # rides with test_ppspline_cli (slow), which writes avg.spl into
    # the shared workspace
    pytest.param("avg.spl", marks=pytest.mark.slow),
])
def test_pptoas_cli_recovers_ddms(workspace, template):
    root, meta, files = workspace
    tim = root / f"out_{template}.tim"
    rc = pptoas.main(["-d", meta, "-m", str(root / template),
                      "-o", str(tim), "--quiet"])
    assert rc == 0
    lines = tim.read_text().strip().splitlines()
    assert len(lines) == 6  # 2 archives x 3 subints
    # A data-built template absorbs the seed epoch's dDM (profile
    # evolution following nu^-2 is degenerate with dispersion), so the
    # physical observable is the epoch-to-epoch dDM DIFFERENCE.
    means = []
    for i, dDM in enumerate(DDMS):
        dms = [float(re.search(r"-pp_dm ([-\d.]+)", ln).group(1))
               for ln in lines if f"example-{i + 1}" in ln]
        assert len(dms) == 3
        assert np.std(dms) < 3e-4  # subints within an epoch agree
        means.append(np.mean(dms))
    assert means[0] - means[1] == pytest.approx(DDMS[0] - DDMS[1],
                                                abs=3e-4)


def test_pptoas_cli_narrowband_and_princeton(workspace):
    root, meta, files = workspace
    tim = root / "nb.tim"
    rc = pptoas.main(["-d", files[0], "-m", str(root / "avg.gmodel"),
                      "-o", str(tim), "--narrowband", "--quiet"])
    assert rc == 0
    assert len(tim.read_text().strip().splitlines()) == 3 * 32
    # princeton format emits fixed-width lines
    tim2 = root / "pr.tim"
    rc = pptoas.main(["-d", files[0], "-m", str(root / "avg.gmodel"),
                      "-o", str(tim2), "-f", "princeton", "--quiet"])
    assert rc == 0
    line = tim2.read_text().splitlines()[0]
    assert re.match(r"^\S+ +\S.*\d{5}\.\d{13}", line)


def test_ppzap_cli(workspace, tmp_path):
    root, meta, files = workspace
    model = default_test_model(1500.0)
    noisy = str(tmp_path / "rfi.fits")
    make_fake_pulsar(model, PAR, outfile=noisy, nsub=1, nchan=32,
                     nbin=256, tsub=60.0,
                     noise_stds=np.where(np.arange(32) == 4, 1.2, 0.06),
                     dedispersed=False, quiet=True, rng=77)
    cmds = tmp_path / "paz.sh"
    rc = ppzap.main(["-d", noisy, "-o", str(cmds), "--quiet", "--apply"])
    assert rc == 0
    assert "-z 4" in cmds.read_text()
    from pulseportraiture_tpu.io import load_data

    d = load_data(noisy, quiet=True)
    assert 4 not in d.ok_ichans[0]
    # model-based path on the clean files
    rc = ppzap.main(["-d", files[0], "-m", str(root / "avg.gmodel"),
                     "--quiet"])
    assert rc == 0


def test_ppzap_cli_telemetry_and_write_mode(workspace, tmp_path):
    """ppzap --telemetry emits the zap_propose/zap_apply ledger the
    inline lane shares (ISSUE 12 satellite), and -o overwrites on
    rerun instead of silently duplicating (--append opts back in)."""
    from pulseportraiture_tpu.telemetry import validate_trace

    root, meta, files = workspace
    model = default_test_model(1500.0)
    noisy = str(tmp_path / "rfi.fits")
    make_fake_pulsar(model, PAR, outfile=noisy, nsub=1, nchan=32,
                     nbin=256, tsub=60.0,
                     noise_stds=np.where(np.arange(32) == 4, 1.2, 0.06),
                     dedispersed=False, quiet=True, rng=78)
    cmds = tmp_path / "paz.sh"
    trace = str(tmp_path / "zap.jsonl")
    argv = ["-d", noisy, "-o", str(cmds), "--quiet", "--apply",
            "--telemetry", trace, "--zap-device", "off"]
    assert ppzap.main(argv) == 0
    once = cmds.read_text()
    assert "-z 4" in once
    _, evs = validate_trace(trace)
    props = [e for e in evs if e["type"] == "zap_propose"]
    apps = [e for e in evs if e["type"] == "zap_apply"]
    assert len(props) == 1 and props[0]["device"] is False
    assert len(apps) == 1 and apps[0]["n_channels"] >= 1
    # rerun: file rewritten, not appended (nothing left to flag after
    # --apply, so the command file comes back empty)
    assert ppzap.main(["-d", noisy, "-o", str(cmds), "--quiet"]) == 0
    assert cmds.read_text() == ""


@pytest.mark.slow  # ~14 s; the stream-vs-get_TOAs parity stays tier-1
# via tests/test_stream.py::test_stream_matches_gettoas and the CLI
# surface keeps test_pptoas_cli_recovers_ddms
def test_pptoas_cli_stream_matches(workspace, tmp_path):
    """--stream produces the same TOA lines (up to float formatting) as
    the per-archive path for a wideband phi/DM run."""
    from pulseportraiture_tpu.io import write_gmodel

    root, meta, files = workspace
    gm = str(tmp_path / "truth.gmodel")
    write_gmodel(default_test_model(1500.0), gm, quiet=True)
    tim_a = tmp_path / "seq.tim"
    tim_b = tmp_path / "str.tim"
    assert pptoas.main(["-d", meta, "-m", gm, "-o", str(tim_a),
                        "--quiet"]) == 0
    # --stream-devices 8: the CLI plumbing into the multi-device
    # executor (output is digit-identical to any device count, so the
    # comparisons below are unchanged)
    assert pptoas.main(["-d", meta, "-m", gm, "-o", str(tim_b),
                        "--stream", "--stream-devices", "8",
                        "--quiet"]) == 0
    la = tim_a.read_text().strip().splitlines()
    lb = tim_b.read_text().strip().splitlines()
    assert len(la) == len(lb) == 6
    for a, b in zip(la, lb):
        fa, fb = a.split(), b.split()
        assert fa[0] == fb[0]          # archive
        assert abs(float(fa[1]) - float(fb[1])) < 1e-6  # freq
        # MJD to f64 parse precision (~1e-11 day ~ 1 us), TOA error and
        # -pp_dm/-pp_dme to ppm — catches dropped backend_delay, P
        # scaling, or error-propagation bugs in the fused path
        assert abs(float(fa[2]) - float(fb[2])) < 2e-11
        assert float(fb[3]) == pytest.approx(float(fa[3]), rel=1e-5)
        da = dict(zip(fa[5::2], fa[6::2]))
        db = dict(zip(fb[5::2], fb[6::2]))
        for key in ("-pp_dm", "-pp_dme"):
            assert float(db[key]) == pytest.approx(float(da[key]),
                                                   rel=1e-5, abs=1e-9)
    # scattering IS streamable (fit_scat + auto seed run through the
    # bucketed complex engine); GM remains a rejected configuration
    tim_c = tmp_path / "str_scat.tim"
    assert pptoas.main(["-d", meta, "-m", gm, "-o", str(tim_c),
                        "--stream", "--fit_scat", "--scat_guess", "auto",
                        "--quiet"]) == 0
    assert "-scat_time" in tim_c.read_text()
    with pytest.raises(SystemExit):
        pptoas.main(["-d", meta, "-m", gm, "--stream", "--fit_GM",
                     "--quiet"])


def test_ppserve_cli_serves_requests(workspace, tmp_path):
    """ppserve end-to-end: a 2-request JSONL spec served through one
    warm loop writes per-request .tim files identical to the one-shot
    --stream driver's checkpoints."""
    import json

    from pulseportraiture_tpu.io import write_gmodel

    root, meta, files = workspace
    gm = str(tmp_path / "truth.gmodel")
    write_gmodel(default_test_model(1500.0), gm, quiet=True)
    # per-request one-shot references
    refs = {}
    for name, f in (("R0", files[0]), ("R1", files[1])):
        tim = tmp_path / f"{name}.ref.tim"
        from pulseportraiture_tpu.pipeline import stream_wideband_TOAs

        stream_wideband_TOAs([f], gm, nsub_batch=8, tim_out=str(tim),
                             quiet=True)
        refs[name] = tim.read_bytes()
    reqfile = tmp_path / "requests.jsonl"
    reqfile.write_text("".join(
        json.dumps({"name": name, "datafiles": [f], "modelfile": gm})
        + "\n" for name, f in (("R0", files[0]), ("R1", files[1]))))
    outdir = tmp_path / "served"
    rc = ppserve.main(["-r", str(reqfile), "-O", str(outdir),
                       "--nsub-batch", "8", "--max-wait-ms", "30",
                       "--quiet"])
    assert rc == 0
    for name, ref in refs.items():
        assert (outdir / f"{name}.tim").read_bytes() == ref


def test_ppserve_flag_and_request_validation(tmp_path):
    """ppserve rejects malformed flags and request files loudly,
    before any serving starts."""
    import json

    good = tmp_path / "ok.jsonl"
    good.write_text(json.dumps({"name": "A", "datafiles": ["a.fits"],
                                "modelfile": "m.gmodel"}) + "\n")
    base = ["-r", str(good)]
    with pytest.raises(SystemExit, match="max-wait-ms"):
        ppserve.main(base + ["--max-wait-ms", "-5"])
    with pytest.raises(SystemExit, match="queue-depth"):
        ppserve.main(base + ["--queue-depth", "0"])
    with pytest.raises(SystemExit, match="nsub-batch"):
        ppserve.main(base + ["--nsub-batch", "0"])
    with pytest.raises(SystemExit, match="pipeline-depth"):
        ppserve.main(base + ["--pipeline-depth", "0"])
    with pytest.raises(SystemExit, match="stream-devices"):
        ppserve.main(base + ["--stream-devices", "several"])
    with pytest.raises(SystemExit, match="warmup-model"):
        ppserve.main(base + ["--warmup-model", "m.gmodel"])
    with pytest.raises(SystemExit, match="not found"):
        ppserve.main(["-r", str(tmp_path / "missing.jsonl")])
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    with pytest.raises(SystemExit, match="bad JSON"):
        ppserve.main(["-r", str(bad)])
    bad.write_text(json.dumps({"datafiles": ["a.fits"]}) + "\n")
    with pytest.raises(SystemExit, match="modelfile"):
        ppserve.main(["-r", str(bad)])
    dup = json.dumps({"name": "X", "datafiles": ["a.fits"],
                      "modelfile": "m"})
    bad.write_text(dup + "\n" + dup + "\n")
    with pytest.raises(SystemExit, match="duplicate"):
        ppserve.main(["-r", str(bad)])
    bad.write_text("")
    with pytest.raises(SystemExit, match="no requests"):
        ppserve.main(["-r", str(bad)])


def test_ppserve_listen_and_pproute_validation(tmp_path):
    """The fleet-mode flags are loud: --listen and -r are mutually
    exclusive, a bare ppserve needs one of them, endpoints must parse
    as host:port, and pproute refuses an empty/garbled fleet before
    touching the network."""
    import json

    good = tmp_path / "ok.jsonl"
    good.write_text(json.dumps({"name": "A", "datafiles": ["a.fits"],
                                "modelfile": "m.gmodel"}) + "\n")
    with pytest.raises(SystemExit, match="mutually exclusive"):
        ppserve.main(["-r", str(good), "--listen", "127.0.0.1:0"])
    with pytest.raises(SystemExit, match="need -r"):
        ppserve.main([])
    with pytest.raises(SystemExit, match="listen"):
        ppserve.main(["--listen", "nowhere"])
    # PPT_SERVE_LISTEN is a default for LISTEN mode only: an explicit
    # -r on a fleet-profiled host must still run batch mode (here it
    # proceeds far enough to reject the missing request file, not the
    # flag combination)
    from pulseportraiture_tpu import config

    old_listen = config.serve_listen
    config.serve_listen = "0.0.0.0:9090"
    try:
        with pytest.raises(SystemExit, match="not found"):
            ppserve.main(["-r", str(tmp_path / "missing.jsonl")])
    finally:
        config.serve_listen = old_listen
    with pytest.raises(SystemExit, match="retry-max"):
        pproute.main(["-r", str(good), "-H", "h:1",
                      "--retry-max", "0"])
    with pytest.raises(SystemExit, match="no fleet"):
        pproute.main(["-r", str(good)])
    with pytest.raises(SystemExit, match="hosts"):
        pproute.main(["-r", str(good), "-H", "nodeA"])
    with pytest.raises(SystemExit, match="not found"):
        pproute.main(["-r", str(tmp_path / "missing.jsonl"),
                      "-H", "nodeA:1"])
    # an unreachable fleet fails loudly at router construction
    with pytest.raises(SystemExit, match="cannot reach"):
        pproute.main(["-r", str(good), "-H", "127.0.0.1:9",
                      "--quiet"])
    # elastic-fleet flags (ISSUE 13) are validated before the network
    with pytest.raises(SystemExit, match="probe-ms"):
        pproute.main(["-r", str(good), "-H", "h:1",
                      "--probe-ms", "0"])
    with pytest.raises(SystemExit, match="hedge-ms"):
        pproute.main(["-r", str(good), "-H", "h:1",
                      "--hedge-ms", "-5"])
    with pytest.raises(SystemExit, match="mutually exclusive"):
        pproute.main(["-r", str(good), "-H", "h:1",
                      "--fleet-file", "fleet.txt"])
    with pytest.raises(SystemExit, match="fleet-file not found"):
        pproute.main(["-r", str(good),
                      "--fleet-file", str(tmp_path / "no.txt")])
    # a request line's tenant must be a string (the QoS lane label)
    bad_tenant = tmp_path / "tenant.jsonl"
    bad_tenant.write_text(json.dumps(
        {"name": "A", "datafiles": ["a.fits"],
         "modelfile": "m.gmodel", "tenant": 7}) + "\n")
    with pytest.raises(SystemExit, match="tenant"):
        pproute.main(["-r", str(bad_tenant), "-H", "h:1"])


def test_pproute_routes_across_listening_fleet(workspace, tmp_path):
    """pproute end-to-end (ISSUE 10): two in-process ppserve-style
    listeners on ephemeral ports, a 2-request JSONL spec routed
    across them — per-request .tim files byte-identical to the
    one-shot --stream driver, requests landing on BOTH hosts."""
    import json

    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
    from pulseportraiture_tpu.serve import ToaServer, TransportServer

    root, meta, files = workspace
    gm = str(tmp_path / "truth.gmodel")
    write_gmodel(default_test_model(1500.0), gm, quiet=True)
    refs = {}
    for name, f in (("R0", files[0]), ("R1", files[1])):
        tim = tmp_path / f"{name}.ref.tim"
        stream_wideband_TOAs([f], gm, nsub_batch=8, tim_out=str(tim),
                             quiet=True)
        refs[name] = tim.read_bytes()
    reqfile = tmp_path / "requests.jsonl"
    reqfile.write_text("".join(
        json.dumps({"name": name, "datafiles": [f], "modelfile": gm})
        + "\n" for name, f in (("R0", files[0]), ("R1", files[1]))))
    outdir = tmp_path / "routed"
    trace = str(tmp_path / "pproute.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as s0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as s1:
        with TransportServer(s0, port=0) as l0, \
                TransportServer(s1, port=0) as l1:
            rc = pproute.main([
                "-r", str(reqfile), "-O", str(outdir),
                "-H", f"127.0.0.1:{l0.port},127.0.0.1:{l1.port}",
                "--telemetry", trace, "--quiet"])
    assert rc == 0
    for name, ref in refs.items():
        assert (outdir / f"{name}.tim").read_bytes() == ref
    from pulseportraiture_tpu import telemetry

    _, events = telemetry.validate_trace(trace)
    subs = [e for e in events if e["type"] == "route_submit"]
    assert {e["host"] for e in subs} == {
        f"127.0.0.1:{l0.port}", f"127.0.0.1:{l1.port}"}


@pytest.fixture(scope="module")
def tiny_fleet(tmp_path_factory):
    """Two tiny single-pulsar archives + a fleet metafile (NOT a JOIN
    metafile) for the ppfactory/ppgauss --batch paths; shapes match
    test_factory so the jitted programs are already warm in-process."""
    from pulseportraiture_tpu.synth import make_fake_pulsar

    root = tmp_path_factory.mktemp("fleet")
    files = []
    for i in range(2):
        p = str(root / f"fleet{i}.fits")
        make_fake_pulsar(default_test_model(1500.0),
                         {"PSR": f"FLEET{i}", "P0": 0.003, "DM": 10.0,
                          "PEPOCH": 56000.0},
                         outfile=p, nsub=2, nchan=8, nbin=64,
                         nu0=1500.0, bw=600.0, tsub=60.0,
                         start_MJD=MJD(55200 + i, 0.3),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=60 + i)
        files.append(p)
    meta = root / "fleet.txt"
    meta.write_text("\n".join(files) + "\n")
    return root, str(meta), files


def test_ppfactory_cli_builds_fleet(tiny_fleet):
    """ppfactory end-to-end: one .gmodel per archive via the batched
    template factory (serial lane on CPU 'auto' routing)."""
    root, meta, files = tiny_fleet
    outdir = root / "models"
    rc = ppfactory.main(["-M", meta, "-O", str(outdir),
                         "--max-ngauss", "2", "--niter", "0"])
    assert rc == 0
    for f in files:
        import os

        out = outdir / (os.path.basename(f) + ".gmodel")
        assert out.exists()
        assert "COMP01" in out.read_text()


def test_ppgauss_batch_cli(tiny_fleet):
    """ppgauss --batch routes -M through the template factory (one
    model per archive, default naming)."""
    root, meta, files = tiny_fleet
    rc = ppgauss.main(["-M", meta, "--batch", "--max-ngauss", "2",
                       "--niter", "0"])
    assert rc == 0
    for f in files:
        assert (root / (f.split("/")[-1] + ".gmodel")).exists() or \
            __import__("os").path.exists(f + ".gmodel")


def test_ppfactory_flag_validation(tmp_path):
    """ppfactory rejects malformed flags loudly before any file IO."""
    meta = tmp_path / "m.txt"
    meta.write_text("a.fits\n")
    base = ["-M", str(meta)]
    with pytest.raises(SystemExit, match="gauss-device"):
        ppfactory.main(base + ["--gauss-device", "sometimes"])
    with pytest.raises(SystemExit, match="max-ngauss"):
        ppfactory.main(base + ["--max-ngauss", "0"])
    with pytest.raises(SystemExit, match="niter"):
        ppfactory.main(base + ["--niter", "-1"])
    with pytest.raises(SystemExit, match="not found"):
        ppfactory.main(["-M", str(tmp_path / "missing.txt")])
    empty = tmp_path / "empty.txt"
    empty.write_text("")
    with pytest.raises(SystemExit, match="no archives"):
        ppfactory.main(["-M", str(empty)])
    # ISSUE 14: the Jacobian-source flag is strict on both CLIs
    with pytest.raises(SystemExit, match="lm-jacobian"):
        ppfactory.main(base + ["--lm-jacobian", "symbolic"])
    with pytest.raises(SystemExit, match="lm-jacobian"):
        ppgauss.main(["-d", "x.fits", "--lm-jacobian", "numeric"])


def test_lm_jacobian_flag_applies_config(tmp_path):
    """--lm-jacobian sets config.lm_jacobian (the knob every LM fit of
    the process resolves) before any file IO; the metafile error fires
    AFTER, proving the parse ran first."""
    from pulseportraiture_tpu import config

    saved = config.lm_jacobian
    try:
        config.lm_jacobian = "auto"
        with pytest.raises(SystemExit, match="not found"):
            ppfactory.main(["-M", str(tmp_path / "missing.txt"),
                            "--lm-jacobian", "ad"])
        assert config.lm_jacobian == "ad"
    finally:
        config.lm_jacobian = saved


def test_pptoas_fit_fused_flag_validation(tmp_path):
    """--fit-fused parses the strict tri-state and applies it to
    config before any file IO."""
    from pulseportraiture_tpu import config

    with pytest.raises(SystemExit, match="fit-fused"):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel",
                     "--fit-fused", "sometimes"])
    saved = config.fit_fused
    try:
        config.fit_fused = "auto"
        with pytest.raises((SystemExit, FileNotFoundError)):
            # the missing datafile dies later in main — after the
            # knob applied
            pptoas.main(["-d", str(tmp_path / "none.fits"),
                         "-m", str(tmp_path / "none.gmodel"),
                         "--fit-fused", "on"])
        assert config.fit_fused is True
    finally:
        config.fit_fused = saved


def test_ppgauss_gauss_device_and_batch_validation():
    """--gauss-device parses the strict tri-state on ppgauss and
    ppspline; --batch requires -M; bad values die before IO."""
    args = ppgauss.build_parser().parse_args(
        ["-d", "x.fits", "--gauss-device", "auto"])
    assert args.gauss_device == "auto"
    with pytest.raises(SystemExit, match="gauss-device"):
        ppgauss.main(["-d", "x.fits", "--gauss-device", "maybe"])
    with pytest.raises(SystemExit, match="max-ngauss"):
        ppgauss.main(["-d", "x.fits", "--max-ngauss", "0"])
    with pytest.raises(SystemExit, match="batch requires"):
        ppgauss.main(["-d", "x.fits", "--batch"])
    # options the fleet factory cannot honor die loudly instead of
    # being silently dropped
    with pytest.raises(SystemExit, match="not supported with --batch"):
        ppgauss.main(["-M", "m.txt", "--batch", "-o", "out.gmodel"])
    with pytest.raises(SystemExit, match="not supported with --batch"):
        ppgauss.main(["-M", "m.txt", "--batch", "-I", "start.gmodel"])
    with pytest.raises(SystemExit, match="gauss-device"):
        ppspline.main(["-d", "x.fits", "--gauss-device", "maybe"])
    # the flag selects the mean-smoothing lane, which only exists
    # under -s — silently running no smoothing would be worse
    with pytest.raises(SystemExit, match="requires -s"):
        ppspline.main(["-d", "x.fits", "--gauss-device", "on"])
    args = ppspline.build_parser().parse_args(
        ["-d", "x.fits", "--gauss-device", "off"])
    assert args.gauss_device == "off"


@pytest.mark.slow
def test_ppspline_gauss_device_smooths_mean(tiny_fleet):
    """ppspline -s --gauss-device routes the MEAN smoothing through
    the template factory's Gaussian LM lane (the injected
    smooth_mean_prof hook) instead of wavelets."""
    root, meta, files = tiny_fleet
    out = root / "gd.spl"
    rc = ppspline.main(["-d", files[0], "-o", str(out), "-s",
                        "--gauss-device", "off", "-S", "50.0",
                        "--quiet"])
    assert rc == 0
    assert out.exists()


def test_pptoas_stream_devices_flag_validation():
    """--stream-devices parses 'auto' or a positive count, requires
    --stream, and rejects garbage loudly — all before any file IO."""
    args = pptoas.build_parser().parse_args(
        ["-d", "x.fits", "-m", "m.gmodel", "--stream",
         "--stream-devices", "auto"])
    assert args.stream_devices == "auto"
    with pytest.raises(SystemExit, match="requires --stream"):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel",
                     "--stream-devices", "2"])
    with pytest.raises(SystemExit, match="expected 'auto'"):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel", "--stream",
                     "--stream-devices", "several"])
    with pytest.raises(SystemExit, match=">= 1"):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel", "--stream",
                     "--stream-devices", "0"])


def test_pptime_cli_flag_validation(tmp_path):
    """pptime validates its job spec loudly before any file IO."""
    with pytest.raises(SystemExit, match="need a timfile"):
        pptime.main([])
    with pytest.raises(SystemExit, match="not both"):
        pptime.main(["-j", "jobs.txt", "a.tim", "a.par"])
    with pytest.raises(SystemExit, match="jobs file not found"):
        pptime.main(["-j", str(tmp_path / "missing.txt")])
    empty = tmp_path / "empty.txt"
    empty.write_text("# nothing\n")
    with pytest.raises(SystemExit, match="no jobs"):
        pptime.main(["-j", str(empty)])
    torn = tmp_path / "torn.txt"
    torn.write_text("PSR only_two_fields\n")
    with pytest.raises(SystemExit, match="expected '<pulsar>"):
        pptime.main(["-j", str(torn)])
    # strict tri-state on the device knob (argparse choices)
    with pytest.raises(SystemExit):
        pptime.main(["a.tim", "a.par", "--gls-device", "sometimes"])


def test_pptime_cli_times_a_fleet(tmp_path, capsys):
    """End-to-end: synthetic ELL1 + isolated .tim fleet -> pptime -j
    -> per-pulsar solutions on stdout (JSON mode parseable)."""
    import json

    from pulseportraiture_tpu.io.tim import write_TOAs  # noqa: F401
    from pulseportraiture_tpu.synth import fake_timing_campaign

    specs = []
    for i, binary in enumerate((True, False)):
        par = {"PSR": f"T{i}", "F0": str(210.0 + 10 * i),
               "PEPOCH": "55500", "DM": "7.5"}
        if binary:
            par.update({"BINARY": "ELL1", "PB": "0.7", "A1": "0.06",
                        "TASC": "55499.2", "EPS1": "1e-6",
                        "EPS2": "-4e-7"})
        toas, _ = fake_timing_campaign(par, n_epochs=6, rng=70 + i)
        tim = tmp_path / f"t{i}.tim"
        with open(tim, "w") as f:
            f.write("FORMAT 1\n")
            for t in toas:
                frac = f"{t.mjd_frac:.15f}"[1:]
                f.write(f"{t.archive} 0.0 {t.mjd_int}{frac} "
                        f"{t.error_us:.3f} @ -pp_dm {t.dm:.7f} "
                        f"-pp_dme {t.dm_err:.7f}\n")
        parf = tmp_path / f"t{i}.par"
        parf.write_text("".join(f"{k} {v}\n" for k, v in par.items()))
        specs.append((f"T{i}", str(tim), str(parf)))
    jobs = tmp_path / "jobs.txt"
    jobs.write_text("".join(f"{p} {t} {pr}\n" for p, t, pr in specs))

    assert pptime.main(["-j", str(jobs), "--gls-device", "on",
                        "--json", "--quiet"]) == 0
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 2
    by_psr = {json.loads(ln)["pulsar"]: json.loads(ln)
              for ln in lines}
    assert by_psr["T0"]["binary"] == "ELL1"
    assert by_psr["T1"]["binary"] is None
    for rec in by_psr.values():
        assert rec["n_toas"] == 12
        assert 0.1 < rec["red_chi2"] < 5.0
        assert "PB" in rec["params"] or rec["binary"] is None
        assert set(rec["param_errs"]) == set(rec["params"])
    # table mode + serial arm still run
    assert pptime.main([specs[0][1], specs[0][2], "--serial"]) == 0
    out = capsys.readouterr().out
    assert "red-chi2" in out and "binary=ELL1" in out
