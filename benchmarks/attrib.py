"""Stage-attribution driver for the historically-unprofiled lanes:
the 5-parameter scattering fit (BASELINE config 3), the
device-resident raw-campaign bucket program (config 5c), the
device-resident align iteration (config 4, ISSUE 2), and — ISSUE 4 —
the end-to-end streaming campaign (config 5).

Built on pulseportraiture_tpu.profiling (the reusable promotion of
exp_breakdown.py's methodology): each lane is decomposed into named
PREFIX stages — cumulative slices of the real program, so fusion
behavior stays honest — plus a PIECE stage (the Newton loop on
precomputed inputs), and the profiler checks that the independently
measured stages sum to the end-to-end slope (>= 90% gates the
benchmarks).

The stage builders here are imported by bench_scatter.py,
bench_device_campaign.py and bench_align.py so their JSON lines carry
the same per-stage breakdown this script prints; run standalone for
the attribution alone:

    python benchmarks/attrib.py scatter
    python benchmarks/attrib.py campaign
    python benchmarks/attrib.py align
    python benchmarks/attrib.py stream

Shapes via PPT_NB / PPT_NCHAN / PPT_NBIN (campaign: PPT_NSUBB; align:
PPT_NE; stream: PPT_NARCH / PPT_NSUB).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def scatter_stage_profile(ports, model, noise, freqs, P, nu_fit, th0,
                          flags, hwin, max_iter, compensated, full_fn,
                          K=3, nrun=2):
    """Attribution of the complex-free scattering lane
    (fit_portrait_batch_fast -> fast_scatter_fit_one):

      dft    (prefix)  windowed matmul DFTs of data + model
      xasm   (prefix)  + weights, X/M2 assembly, Parseval Sd (no seed)
      seed   (prefix)  + the tau-matched CCF phase seed
      newton (piece)   the _cgh_scatter Newton loop + finalize on a
                       precomputed cross-spectrum

    full_fn: the end-to-end batched fit the bench times (so the
    attribution denominator is exactly the benched program)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.portrait import (
        FitFlags, _fit_portrait_core_real_scatter, effective_x_bf16,
        prepare_scatter_fit_real)
    from pulseportraiture_tpu.ops.fourier import _gated_precision, rfft_mm
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    dt = ports.dtype
    nbin = ports.shape[-1]
    prec = _gated_precision(None)
    x_bf16 = effective_x_bf16(compensated)
    kw = dict(fit_flags=flags, log10_tau=True, compensated=compensated,
              x_bf16=x_bf16, nharm_eff=hwin, seed_derotate=False)

    # every stage program takes its arrays as ARGUMENTS: a jnp array
    # closed over by jit becomes an embedded constant, and XLA
    # constant-folds the whole stage at compile time (minutes of
    # single-threaded folding; the exp_breakdown lesson, round 5)
    @jax.jit
    def dft_prefix(ports, model):
        dr, di = jax.vmap(
            lambda p: rfft_mm(p, precision=prec, nharm=hwin))(ports)
        mr, mi = rfft_mm(model.astype(dt), precision=prec, nharm=hwin)
        return (jnp.sum(dr) + jnp.sum(di) + jnp.sum(mr) + jnp.sum(mi))

    def _prep(seed):
        fl = flags if seed else FitFlags(False, *flags[1:])

        def one(p, m, n, t):
            Xr, Xi, M2w, Sd, th = prepare_scatter_fit_real(
                p, m, n, jnp.ones(p.shape[0], dt), freqs, P,
                nu_fit, t, **{**kw, "fit_flags": fl})
            return (jnp.sum(Xr.astype(jnp.float32)) + jnp.sum(M2w)
                    + Sd + jnp.sum(th))

        return jax.jit(jax.vmap(one, in_axes=(0, None, 0, 0)))

    xasm = _prep(False)
    seed = _prep(True)

    @jax.jit
    def prep_out(ports, model, noise, th0):
        def one(p, m, n, t):
            return prepare_scatter_fit_real(
                p, m, n, jnp.ones(p.shape[0], dt), freqs, P,
                nu_fit, t, **kw)

        return jax.vmap(one, in_axes=(0, None, 0, 0))(
            ports, model, noise, th0)

    Xr, Xi, M2w, Sd, th = jax.block_until_ready(
        prep_out(ports, model, noise, th0))

    # X ships as arguments, not closed-over constants — a closure would
    # embed the spectra into the program (compile-request size limits
    # on tunneled runtimes)
    nu_out = jnp.asarray(-1.0, dt)
    core = jax.jit(jax.vmap(
        lambda xr, xi, m2, sd, t0: (
            _fit_portrait_core_real_scatter.__wrapped__(
                xr, xi, m2, sd, freqs, P, nu_fit, nu_out, t0,
                fit_flags=flags, log10_tau=True, max_iter=max_iter,
                compensated=compensated,
                nharm_total=nbin // 2 + 1 if hwin else None))))

    stages = [
        Stage("dft", lambda: dft_prefix(ports, model), "prefix"),
        Stage("xasm", lambda: xasm(ports, model, noise, th0), "prefix"),
        Stage("seed", lambda: seed(ports, model, noise, th0), "prefix"),
        Stage("newton", lambda: core(Xr, Xi, M2w, Sd, th), "piece",
              lambda r: r.phi),
    ]
    return profile_stages(full_fn, stages, pick=lambda r: r.phi, K=K,
                          nrun=nrun)


def campaign_stage_profile(raw, scl, offs, cmask, model, freqs, Ps,
                           DMg, hwin, flags, max_iter, full_fn,
                           K=3, nrun=2):
    """Attribution of the fused raw-bucket program (pipeline/stream
    _raw_fit_fn):

      decode (prefix)  int16 decode + min-window baseline
      stats  (prefix)  + PS noise, S/N (sort-free median), nu_fit seed
      fit    (piece)   the batched no-scatter fit on the decoded ports

    The prefixes call the SAME _raw_decode/_raw_stats helpers the
    production program runs."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.portrait import FitFlags, _fast_batch_fn
    from pulseportraiture_tpu.ops.fourier import use_dft_fold
    from pulseportraiture_tpu.pipeline.stream import _raw_decode, _raw_stats
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    ft = jnp.float32
    nbin = raw.shape[-1]
    tiny = float(np.finfo("float32").tiny)

    # arrays ship as ARGUMENTS, never jit-closed-over constants (XLA
    # would constant-fold the whole stage at compile time — see
    # scatter_stage_profile)
    @jax.jit
    def decode_prefix(raw, scl, offs):
        return jnp.sum(_raw_decode(raw, scl, offs, nbin, ft))

    @jax.jit
    def stats_prefix(raw, scl, offs, cmask, freqs):
        x = _raw_decode(raw, scl, offs, nbin, ft)
        noise, snr, nu_fit = _raw_stats(x, cmask, freqs, ft, tiny)
        return jnp.sum(x) + jnp.sum(noise) + jnp.sum(nu_fit)

    @jax.jit
    def precompute(raw, scl, offs, cmask, freqs):
        x = _raw_decode(raw, scl, offs, nbin, ft)
        noise, snr, nu_fit = _raw_stats(x, cmask, freqs, ft, tiny)
        return x, noise, nu_fit

    x, noise, nu_fit = jax.block_until_ready(
        precompute(raw, scl, offs, cmask, freqs))
    nb = x.shape[0]
    theta0 = jnp.zeros((nb, 5), ft).at[:, 1].set(DMg.astype(ft))
    nu_out = jnp.full((nb,), -1.0, ft)
    fit = _fast_batch_fn(FitFlags(*flags), max_iter, None, None, 0, 0,
                         seed_derotate=bool(np.any(np.asarray(DMg))),
                         x_bf16=True, nharm_eff=hwin,
                         dft_fold=use_dft_fold())
    Ps_b = jnp.broadcast_to(jnp.asarray(Ps, ft), (nb,))

    stages = [
        Stage("decode", lambda: decode_prefix(raw, scl, offs),
              "prefix"),
        Stage("stats", lambda: stats_prefix(raw, scl, offs, cmask,
                                            freqs), "prefix"),
        Stage("fit", lambda: fit(x, model, noise, cmask, freqs, Ps_b,
                                 nu_fit, nu_out, theta0), "piece",
              lambda r: r.phi),
    ]
    return profile_stages(full_fn, stages, pick=lambda r: r, K=K,
                          nrun=nrun)


def align_stage_profile(cube, noise, masks, freqs, P_s, acc_dt,
                        fit_fn, full_fn, K=4, nrun=3):
    """Attribution of the device-resident align iteration
    (pipeline/align.align_archives device lane; parallel/batch.py):

      fit        (prefix)  the batched (phi, DM) fast fit
      rotate     (prefix)  + delays/weights + split-real phasor
                           rotation of the chunked harmonic stacks
                           (_align_rotate_real — the production math)
      accumulate (prefix)  + the donated weighted on-chip accumulate
                           (align_accumulate_archive itself)
      irfft      (prefix)  + the iteration's ONE irfft + normalization
                           (align_finalize)
      host_sync  (piece)   the per-iteration device->host pull of the
                           finalized (npol, nchan, nbin) portrait

    cube: (nb, npol, nchan, nbin); fit_fn() runs the batched fit the
    production lane runs; full_fn() is the end-to-end iteration the
    bench times (fit -> accumulate -> finalize -> host pull), so the
    attribution denominator is exactly the benched program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pulseportraiture_tpu.parallel.batch import (
        ALIGN_DEVICE_CHUNK, _align_chunk, _align_precision,
        _align_rotate_real, _align_weights_fn, align_accumulate_archive,
        align_accumulator_init, align_finalize)
    from pulseportraiture_tpu.ops.fourier import rfft_sr
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    npol, nchan = cube.shape[1], cube.shape[2]
    nbin = cube.shape[-1]
    dt_str = str(jnp.dtype(acc_dt))
    prec = _align_precision()
    # keep the cube in its PRODUCTION dtype (f32 from the loader/synth)
    # and convert inside the measured prefixes, exactly where
    # align_accumulate_archive converts — a precomputed acc_dt cube
    # would leave the (possibly ~100s of MB) widening pass
    # unattributed on CPU, where acc_dt is f64
    cube_j = jnp.asarray(cube)
    chunk = _align_chunk(cube.shape[0], ALIGN_DEVICE_CHUNK)

    def weights(r):
        return _align_weights_fn(dt_str)(
            jnp.asarray(r.phi, acc_dt), jnp.asarray(r.DM, acc_dt),
            jnp.asarray(r.nu_DM, acc_dt), jnp.asarray(P_s, acc_dt),
            jnp.asarray(freqs, acc_dt), jnp.asarray(noise, acc_dt),
            jnp.asarray(masks, acc_dt), jnp.asarray(r.scales, acc_dt))

    # arrays ship as ARGUMENTS, never jit-closed-over constants (XLA
    # would constant-fold the stage at compile time — the exp_breakdown
    # lesson, see scatter_stage_profile)
    @jax.jit
    def rot_chunk(cc, dd):
        cr, ci = rfft_sr(cc, precision=prec)
        rr, ri = _align_rotate_real(cr, ci, dd)
        return jnp.sum(rr) + jnp.sum(ri)

    def pad(a, m):
        return jnp.pad(a, ((0, chunk - m),) + ((0, 0),) * (a.ndim - 1))

    def rotate_prefix():
        r = fit_fn()
        delays, _ = weights(r)
        cd = jnp.asarray(cube_j, acc_dt)  # production widening pass
        tot = jnp.zeros((), acc_dt)
        for lo in range(0, cd.shape[0], chunk):
            cc, dd = cd[lo:lo + chunk], delays[lo:lo + chunk]
            m = cc.shape[0]
            if m != chunk:
                cc, dd = pad(cc, m), pad(dd, m)
            tot = tot + rot_chunk(cc, dd)
        return tot

    def accum_prefix():
        r = fit_fn()
        acc = align_accumulator_init(npol, nchan, nbin, acc_dt)
        return align_accumulate_archive(acc, cube_j, r.phi, r.DM,
                                        r.nu_DM, P_s, freqs, noise,
                                        masks, r.scales)

    def irfft_prefix():
        acc = accum_prefix()
        return align_finalize(acc, nbin)

    # host_sync piece: the d2h pull of a PRECOMPUTED finalized portrait
    # (everything before it is the irfft prefix)
    final_dev = jax.block_until_ready(irfft_prefix())

    stages = [
        Stage("fit", fit_fn, "prefix", lambda r: r.phi),
        Stage("rotate", rotate_prefix, "prefix"),
        Stage("accumulate", accum_prefix, "prefix", lambda a: a[0]),
        Stage("irfft", irfft_prefix, "prefix"),
        Stage("host_sync", lambda: np.asarray(final_dev), "piece"),
    ]
    return profile_stages(full_fn, stages, K=K, nrun=nrun)


def gauss_stage_profile(resid_fn, aux, x0, lo, hi, kind, vary,
                        K=3, nrun=2, jac_fn=None):
    """Attribution of the batched template-LM bucket dispatch
    (fit/lm.levenberg_marquardt_batched, the template factory's
    portrait stage — ISSUE 9): one vmapped LM iteration decomposed as

      resid    (prefix)  batched residual evaluation at the current
                         internal parameters (model gen + weighting)
      jacobian (prefix)  + the Jacobian source under profile: the
                         vmapped jacfwd (nparam forward passes through
                         the model — the AD lane's dominant per-step
                         cost), or, with ``jac_fn`` (ISSUE 14), the
                         ANALYTIC residual-Jacobian companion chained
                         through the bound transform — the same
                         evaluator fit/lm._make_jac builds, so the
                         profile times exactly what the engine runs
      solve    (prefix)  + normal equations (g, JTJ, damped A) and the
                         batched linear solve for the step
      select   (piece)   the accept/convergence bookkeeping (f_new,
                         relative-improvement and gradient tests,
                         state selection) on precomputed pieces

    The full program is exactly the iteration the vmapped while_loop
    body runs (under vmap the lax.cond Jacobian skip becomes a select,
    so jac IS evaluated every iteration — the decomposition matches
    the real batched program, not the single-problem one).  Run it
    once per lane (jac_fn None / provided) for the analytic-vs-AD
    stage A/B bench_gauss reports.  Arrays ship as ARGUMENTS, never
    jit-closed-over constants (XLA would constant-fold the stage at
    compile time — the exp_breakdown lesson)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.lm import (_to_external,
                                             _to_external_grad,
                                             _to_internal)
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    x0 = jnp.asarray(x0)
    dt = x0.dtype
    vary_f = jnp.asarray(vary).astype(dt)
    u0 = _to_internal(x0, lo, hi, kind)  # elementwise: batched as-is
    lam = jnp.full(x0.shape[0], 1e-3, dt)

    def rfun_one(u, lo1, hi1, k1, aux1):
        return resid_fn(_to_external(u, lo1, hi1, k1), *aux1)

    if jac_fn is None:
        def jac_one(u, lo1, hi1, k1, v1, aux1):
            return (jax.jacfwd(rfun_one)(u, lo1, hi1, k1, aux1)
                    * v1[None, :])
    else:
        def jac_one(u, lo1, hi1, k1, v1, aux1):
            Jx = jac_fn(_to_external(u, lo1, hi1, k1), *aux1)
            D = _to_external_grad(u, lo1, hi1, k1)
            return Jx * (D * v1)[None, :]

    @jax.jit
    def resid_prefix(u, lo, hi, kind, aux):
        r = jax.vmap(rfun_one)(u, lo, hi, kind, aux)
        return jnp.sum(r * r, axis=-1)

    def _solve_parts(r, J, u, vary_f, lam):
        g = jnp.einsum("bri,br->bi", J, r)
        JTJ = jnp.einsum("bri,brj->bij", J, J)
        dJ = jnp.diagonal(JTJ, axis1=-2, axis2=-1)
        dJ = jnp.maximum(dJ, 1e-14 * jnp.max(dJ, axis=-1,
                                             keepdims=True))
        A = (JTJ + lam[:, None, None] * jax.vmap(jnp.diag)(dJ)
             + jax.vmap(jnp.diag)(1.0 - vary_f))
        step = -jnp.linalg.solve(A, g[..., None])[..., 0] * vary_f
        smax = 100.0 * (1.0 + jnp.abs(u))
        return g, jnp.clip(step, -smax, smax)

    @jax.jit
    def jac_prefix(u, lo, hi, kind, vary_f, aux):
        r = jax.vmap(rfun_one)(u, lo, hi, kind, aux)
        J = jax.vmap(jac_one)(u, lo, hi, kind, vary_f, aux)
        return jnp.sum(r * r, axis=-1) + jnp.sum(J, axis=(1, 2))

    @jax.jit
    def solve_prefix(u, lo, hi, kind, vary_f, lam, aux):
        r = jax.vmap(rfun_one)(u, lo, hi, kind, aux)
        J = jax.vmap(jac_one)(u, lo, hi, kind, vary_f, aux)
        g, step = _solve_parts(r, J, u, vary_f, lam)
        return jnp.sum(step, axis=-1)

    @jax.jit
    def select_piece(u, f, r_try, g, step, lam, vary_f):
        u_try = u + step
        f_new = jnp.sum(r_try * r_try, axis=-1)
        accept = f_new < f
        rel = (f - f_new) / (jnp.abs(f) + 1e-300)
        done = jnp.logical_and(jnp.logical_and(accept, rel < 1e-10),
                               lam <= 1e-3)
        gnorm = jnp.max(jnp.abs(g * vary_f), axis=-1)
        done = jnp.logical_or(done, gnorm < 1e-14 * (f + 1.0))
        u_new = jnp.where(accept[:, None], u_try, u)
        lam_new = jnp.where(accept, lam * 0.3, lam * 5.0).clip(1e-12,
                                                               1e12)
        return (jnp.sum(u_new) + jnp.sum(lam_new)
                + jnp.sum(done) + jnp.sum(f_new))

    @jax.jit
    def full_iter(u, lo, hi, kind, vary_f, lam, aux):
        r = jax.vmap(rfun_one)(u, lo, hi, kind, aux)
        f = jnp.sum(r * r, axis=-1)
        J = jax.vmap(jac_one)(u, lo, hi, kind, vary_f, aux)
        g, step = _solve_parts(r, J, u, vary_f, lam)
        return select_piece.__wrapped__(u, f, r, g, step, lam, vary_f)

    # precompute the select piece's inputs once (everything before it
    # is the solve prefix)
    @jax.jit
    def precompute(u, lo, hi, kind, vary_f, lam, aux):
        r = jax.vmap(rfun_one)(u, lo, hi, kind, aux)
        f = jnp.sum(r * r, axis=-1)
        J = jax.vmap(jac_one)(u, lo, hi, kind, vary_f, aux)
        g, step = _solve_parts(r, J, u, vary_f, lam)
        return f, r, g, step

    f0, r0, g0, step0 = jax.block_until_ready(
        precompute(u0, lo, hi, kind, vary_f, lam, aux))

    stages = [
        Stage("resid", lambda: resid_prefix(u0, lo, hi, kind, aux),
              "prefix"),
        Stage("jacobian", lambda: jac_prefix(u0, lo, hi, kind, vary_f,
                                             aux), "prefix"),
        Stage("solve", lambda: solve_prefix(u0, lo, hi, kind, vary_f,
                                            lam, aux), "prefix"),
        Stage("select", lambda: select_piece(u0, f0, r0, g0, step0,
                                             lam, vary_f), "piece"),
    ]
    return profile_stages(
        lambda: full_iter(u0, lo, hi, kind, vary_f, lam, aux), stages,
        K=K, nrun=nrun)


def stream_stage_profile(files, modelfile, nsub_batch, end_to_end_s,
                         max_iter=25):
    """Attribution of the streaming campaign lane (pipeline/stream,
    BASELINE config 5), the ISSUE 4 discipline for the multi-device
    executor.  Unlike the device-program lanes, a campaign is a HOST
    pipeline wrapped around one fused device program, so the stages
    are wall-clock costs of the REAL helpers (the same single-source-
    of-truth functions the driver runs) measured over the same archive
    set, and the denominator ``end_to_end_s`` must come from a
    SERIALIZED campaign run (prefetch off, max_inflight 1, one
    device): overlap is a scheduling win the bench_stream scaling
    table reports separately; attribution explains where the
    serialized second goes.

      load     — archive ingest: raw int16 load (_load_raw) + the
                 per-archive template portrait build
      stack    — bucket payload stacking (_stack_raw + masks/Ps)
      h2d      — committed device_put of every stacked dispatch
      fit      — the fused raw-bucket program (_raw_fit_fn), each
                 dispatch group executed and timed ONCE on its own
                 arrays (slope timing on one cached group is the
                 device-lane tool; a campaign touches fresh bucket
                 bytes per dispatch, so repeated-input timing
                 under-reports the memory-bound part)
      scatter  — d2h pull + per-owner unpack of the packed results
      assemble — per-archive TOA assembly (_assemble_archive)

    The corpus must be raw-lane wideband (int16 DATA, npol 1), the
    no-scattering campaign configuration — what bench_stream
    generates."""
    import time

    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.portrait import (
        use_bf16_cross_spectrum, use_fast_fit_default,
        use_scatter_compensated)
    from pulseportraiture_tpu.pipeline.models import TemplateModel
    from pulseportraiture_tpu.pipeline.stream import (
        _assemble_archive, _Bucket, _load_raw, _raw_fit_fn,
        _result_keys, _stack_raw)
    from pulseportraiture_tpu.utils.bunch import DataBunch

    model = TemplateModel(modelfile, quiet=True)
    device = jax.local_devices()[0]
    use_fast = use_fast_fit_default()
    ftname = "float32" if use_fast else "float64"
    ft = jnp.float32 if use_fast else jnp.float64

    # ---- load: archive ingest (the driver's loader + portrait) ------
    t0 = time.perf_counter()
    ds, modelxs = [], []
    for f in files:
        d = _load_raw(f)
        ds.append(d)
        freqs0 = np.asarray(d.freqs[0], float)
        P_mean = float(np.mean(d.Ps[np.asarray(d.ok_isubs, int)]))
        modelxs.append(model.portrait(freqs0, d.nbin, P=P_mean))
    t_load = time.perf_counter() - t0

    # one shape bucket (the bench corpus is homogeneous); flags are
    # the wideband default (phi, DM)
    d0 = ds[0]
    nchan, nbin = d0.nchan, d0.nbin
    freqs0 = np.asarray(d0.freqs[0], float)
    flags = (True, True, False, False, False)
    bucket = _Bucket(freqs0, nbin, modelxs[0], flags, kind="raw")
    metas = []
    for iarch, d in enumerate(ds):
        ok = np.asarray(d.ok_isubs, int)
        masks = np.asarray(d.weights[ok] > 0.0, float)
        DM_stored = float(d.DM)
        # the driver's DM0 fallback collapses to the stored DM here
        # (DM0 is None in the bench campaign)
        DM_guess = DM_stored
        metas.append(DataBunch(
            datafile=files[iarch], iarch=iarch, ok=ok,
            DM0_arch=DM_stored, nbin=nbin, nchan=nchan,
            epochs=[d.epochs[i] for i in ok],
            Ps=[float(d.Ps[i]) for i in ok],
            dfs=[float(d.doppler_factors[i]) for i in ok],
            subtimes=[float(d.subtimes[i]) for i in ok],
            backend_delay=d.backend_delay, backend=d.backend,
            frontend=d.frontend, telescope=d.telescope,
            telescope_code=d.telescope_code))
        for j, isub in enumerate(ok):
            bucket.raw.append(d.raw[isub])
            bucket.scl.append(d.scl[isub])
            bucket.offs.append(d.offs[isub])
            bucket.DM_guess.append(DM_guess)
            bucket.dedisp.append(
                (float(d.DM) if d.get("dmc") else 0.0,
                 float(d.get("dedisp_nu") or d.get("nu0", 0.0) or 0.0)))
            bucket.masks.append(masks[j])
            bucket.Ps.append(float(d.Ps[isub]))
            bucket.owners.append((iarch, int(isub)))

    n_total = len(bucket)
    groups = []
    for lo in range(0, n_total, nsub_batch):
        idx = list(range(lo, min(lo + nsub_batch, n_total)))
        pad = (-len(idx)) % nsub_batch
        groups.append(idx + [idx[0]] * pad)

    # ---- stack: the host-side payload stacking per dispatch ---------
    t0 = time.perf_counter()
    stacked = []
    for idx0 in groups:
        masks_g = np.stack([bucket.masks[i] for i in idx0])
        Ps_g = np.asarray([bucket.Ps[i] for i in idx0])
        raw, scl, offs, redisp, turns = _stack_raw(bucket, idx0, Ps_g)
        DMg = np.asarray([bucket.DM_guess[i] for i in idx0])
        stacked.append((raw, scl, offs, masks_g, Ps_g, redisp, turns,
                        DMg))
    t_stack = time.perf_counter() - t0

    # ---- h2d: committed placement of every dispatch's arrays --------
    hwin = bucket.harmonic_window() if use_fast else None
    t0 = time.perf_counter()
    dev_groups = []
    for raw, scl, offs, masks_g, Ps_g, redisp, turns, DMg in stacked:
        put = [jax.device_put(np.asarray(a, dt) if dt else a, device)
               for a, dt in ((raw, None), (scl, ftname), (offs, ftname),
                             (masks_g, ftname),
                             (np.asarray(bucket.modelx), ftname),
                             (freqs0, ftname), (Ps_g, ftname),
                             (DMg, ftname), (turns, ftname))]
        jax.block_until_ready(put)
        dev_groups.append((put, redisp))
    t_h2d = time.perf_counter() - t0

    # ---- fit: the fused device program, slope-timed -----------------
    redisp0 = dev_groups[0][1]
    fn = _raw_fit_fn(nchan, nbin, flags, int(max_iter), False, "none",
                     use_fast, ftname, use_bf16_cross_spectrum(),
                     redisp=redisp0, want_flux=False, use_ir=False,
                     compensated=use_scatter_compensated(),
                     nharm_eff=hwin,
                     seed_derotate=bool(np.any(
                         np.asarray(bucket.DM_guess) != 0.0)))
    keys = _result_keys(flags)

    def run_group(g):
        (raw_d, scl_d, offs_d, masks_d, modelx_d, freqs_d, Ps_d, DMg_d,
         turns_d), _ = g
        return fn(raw_d, scl_d, offs_d, masks_d, modelx_d, freqs_d,
                  Ps_d, DMg_d, ft(-1.0), ft(0.0), ft(1.0), ft(0.0),
                  ft(0.0), turns_d, None, None)

    jax.block_until_ready(run_group(dev_groups[0]))  # compile
    t_fit, outs = 0.0, []
    for g in dev_groups:
        t0 = time.perf_counter()
        outs.append(jax.block_until_ready(run_group(g)))
        t_fit += time.perf_counter() - t0

    # ---- scatter: d2h pull + per-owner unpack -----------------------
    results = {}
    t0 = time.perf_counter()
    for gi, out in enumerate(outs):
        packed = np.asarray(out)
        owners = [bucket.owners[i] for i in groups[gi]]
        for i, owner in enumerate(owners):
            results[owner] = {k: packed[j, i]
                              for j, k in enumerate(keys)}
    t_scatter = time.perf_counter() - t0

    # ---- assemble: per-archive TOA construction ---------------------
    t0 = time.perf_counter()
    ntoa = 0
    for m in metas:
        toas, _, _ = _assemble_archive(m, results, modelfile, True,
                                       True, {}, quiet=True)
        ntoa += len(toas)
    t_assemble = time.perf_counter() - t0

    stages = {"load": t_load, "stack": t_stack, "h2d": t_h2d,
              "fit": t_fit, "scatter": t_scatter,
              "assemble": t_assemble}
    out = {f"stage_{k}_ms": round(v * 1e3, 3)
           for k, v in stages.items()}
    total = sum(stages.values())
    out["attributed_frac"] = round(total / max(end_to_end_s, 1e-12), 3)
    out["serialized_wall_s"] = round(end_to_end_s, 3)
    out["dominant_stage"] = max(stages, key=stages.get)
    out["ndispatch"] = len(groups)
    out["attrib_ntoa"] = ntoa
    return out


def main():
    lane = sys.argv[1] if len(sys.argv) > 1 else "scatter"
    if lane == "scatter":
        from benchmarks import bench_scatter

        out = bench_scatter.run_bench(attrib_only=True)
    elif lane == "campaign":
        from benchmarks import bench_device_campaign

        out = bench_device_campaign.run_bench(attrib_only=True)
    elif lane == "align":
        from benchmarks import bench_align

        out = bench_align.run_bench(attrib_only=True)
    elif lane == "stream":
        from benchmarks import bench_stream

        out = bench_stream.run_bench(attrib_only=True)
    elif lane == "gauss":
        from benchmarks import bench_gauss

        out = bench_gauss.run_bench(attrib_only=True)
    else:
        raise SystemExit(f"unknown lane {lane!r} "
                         "(scatter|campaign|align|stream|gauss)")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
