"""Serving-loop benchmark (ISSUE 8 acceptance gate): warm-server
steady-state throughput vs the one-shot streaming driver at the same
shape, plus request-latency percentiles under an offered-load sweep.

Arms:
  oneshot   — stream_wideband_TOAs over the whole campaign (the
              bench_campaign measurement, re-run here so the ratio is
              apples-to-apples in one process);
  serve@R   — a warm ToaServer fed R concurrent client threads, each
              submitting an equal slice of the same archives against
              the same template (requests coalesce into shared fused
              buckets).  Measured from first submit to last result;
              per-request latencies give p50/p99.

The gate: serve@R throughput within 1.1x of oneshot (the serving loop
must not tax steady state) — reported as ``serve_vs_oneshot`` (>= 1/1.1
passes).  PPT_TUNNEL_EMU="<mbps>[:<dispatch_ms>]" applies the same
tunneled-transport emulation bench_campaign documents (throttled
device_put + synchronous dispatch floor), so the serve loop is also
measurable under the transport it exists for.

Knobs via env: PPT_NARCH (default 32), PPT_NSUB (16), PPT_NCHAN (64),
PPT_NBIN (256), PPT_NREQ (4 — the offered-load sweep runs 1 and NREQ),
PPT_SERVE_MAX_WAIT_MS (bucket deadline).  The synthetic campaign is
cached under PPT_CAMPAIGN_CACHE (default /tmp/ppt_campaign, shared
with bench_campaign).  When PPT_TELEMETRY is set the serve arm traces
to <path>.serve and the trace is schema-validated (request_done +
batch_coalesce events) — the serve-section drift guard CI runs at tiny
shapes (tests/test_bench_smoke.py).  Prints ONE JSON line.
"""

import io
import json
import os
import sys
import threading
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()

    import jax

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.serve import ToaServer
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = int(os.environ.get("PPT_NARCH", 32))
    NSUB = int(os.environ.get("PPT_NSUB", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 64))
    NBIN = int(os.environ.get("PPT_NBIN", 256))
    NREQ = max(1, int(os.environ.get("PPT_NREQ", 4)))
    TUNNEL = os.environ.get("PPT_TUNNEL_EMU", "")
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * (i % 50), dDM=1e-4 * (i % 40),
                             noise_stds=0.05, quiet=True, rng=i)
        files.append(path)

    # ---- optional tunneled-transport emulation (bench_campaign's) ---
    from pulseportraiture_tpu.pipeline import stream as S
    unpatch = []
    if TUNNEL:
        parts = TUNNEL.split(":")
        mbps = float(parts[0])
        disp_ms = float(parts[1]) if len(parts) > 1 else 100.0
        real_put = jax.device_put

        def throttled_put(x, device=None, **kw):
            out = real_put(x, device, **kw)
            time.sleep(getattr(x, "nbytes", 0) / (mbps * 1e6))
            return out

        real_fit_fn = S._raw_fit_fn

        def sync_fit_fn(*a, **kw):
            fn = real_fit_fn(*a, **kw)

            def run(*args):
                out = jax.block_until_ready(fn(*args))
                time.sleep(disp_ms / 1e3)  # tunnel round-trip floor
                return out

            return run

        jax.device_put = throttled_put
        S._raw_fit_fn = sync_fit_fn
        unpatch = [(jax, "device_put", real_put),
                   (S, "_raw_fit_fn", real_fit_fn)]

    try:
        # warm the jit caches once so BOTH arms measure steady state
        stream_wideband_TOAs(files[:1], mpath, nsub_batch=64, quiet=True)

        # ---- one-shot arm ------------------------------------------
        t0 = time.perf_counter()
        res = stream_wideband_TOAs(files, mpath, nsub_batch=64,
                                   quiet=True)
        oneshot_wall = time.perf_counter() - t0
        ntoa = len(res.TOA_list)
        oneshot_tps = ntoa / oneshot_wall

        # ---- serve arms: offered-load sweep ------------------------
        sweep = []
        for conc in sorted({1, NREQ}):
            trace = (f"{trace_base}.serve{conc}" if trace_base
                     else None)
            srv = ToaServer(nsub_batch=64, telemetry=trace,
                            quiet=True).start()
            slices = [files[i::conc] for i in range(conc)]
            lat = [None] * conc
            errs = []

            def client(i):
                t = time.perf_counter()
                try:
                    srv.submit(slices[i], mpath,
                               name=f"load{i}").result(3600)
                except Exception as e:  # surfaced after join
                    errs.append(e)
                    return
                lat[i] = time.perf_counter() - t

            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(conc)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            srv.stop()
            if errs:
                raise errs[0]
            lat_sorted = sorted(lat)
            arm = {
                "concurrency": conc,
                "toas_per_sec": round(ntoa / wall, 2),
                "wall_s": round(wall, 3),
                "p50_s": round(lat_sorted[len(lat_sorted) // 2], 4),
                "p99_s": round(lat_sorted[-1], 4),
            }
            if trace:
                summary = telemetry.report(trace, file=io.StringIO())
                assert summary["n_requests"] == conc, (
                    f"{summary['n_requests']} request_done events for "
                    f"{conc} clients")
                assert summary["n_coalesce"] > 0, \
                    "serve arm emitted no batch_coalesce events"
                arm["batch_occupancy"] = (
                    round(summary["batch_occupancy"], 3)
                    if summary["batch_occupancy"] is not None else None)
            sweep.append(arm)
    finally:
        for obj, name, val in unpatch:
            setattr(obj, name, val)

    top = sweep[-1]
    print(json.dumps({
        "metric": f"served campaign TOAs incl. PSRFITS IO, {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin, "
                  f"{top['concurrency']} concurrent client(s) vs "
                  "one-shot",
        "value": top["toas_per_sec"],
        "unit": "TOAs/sec",
        "toas": ntoa,
        "oneshot_toas_per_sec": round(oneshot_tps, 2),
        "serve_vs_oneshot": round(top["toas_per_sec"]
                                  / max(oneshot_tps, 1e-9), 3),
        "serve_within_1p1x": bool(top["toas_per_sec"] * 1.1
                                  >= oneshot_tps),
        "p50_s": top["p50_s"],
        "p99_s": top["p99_s"],
        "sweep": sweep,
        "tunnel_emu": TUNNEL or None,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
