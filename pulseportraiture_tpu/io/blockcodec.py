"""Lossless block codec + transport cost model (ISSUE 15, the last
leg of the link war).

The measured campaign bottleneck is the host->device link (BENCHMARKS
5b/5d: 90-95%% of wall under the real tunnel), and every win so far
has been bytes-on-the-wire.  This module goes one step beyond the raw
wire format itself: an optional, LOSSLESS width-reduction codec for
integer raw payloads, in the bitshuffle tradition (Masui et al. 2015
— transposed bit planes are the codec CHIME ships pulsar data with)
but restricted to the fixed-size transform an accelerator can invert
inside a jitted program:

- **Encode (host, here):** a dispatch's stacked integer payload is
  scanned per subint row for its dynamic range; when every row's span
  fits a bit width narrower than the wire dtype (1/2/4/8 of 8- or
  16-bit samples), the payload ships as MSB-first packed ``w``-bit
  residuals plus one per-row minimum — e.g. an int16 payload whose
  rows span < 16 levels ships 4x fewer bytes.
- **Decode (device, ops/decode.unpack_bitplanes):** the same bit-plane
  unpack op the sub-byte NBIT lane uses, plus one add of the per-row
  minimum — integer shifts/masks inside the fused bucket program, so
  the decode is exact (every integer below 2**24 is exact in f32) and
  ``.tim`` output is digit-identical compressed or not.

Variable-length entropy stages (the LZ side of bitshuffle) are
deliberately out of scope for the h2d lane — a device cannot address
a variable-rate stream inside one fused program; the SOCKET transport
(serve/transport.py) uses zlib for its frames instead, where the
decoder is host-side.

The **cost model** decides per dispatch whether compressing pays: the
transfer pipeline feeds it the live link rate from its own
``h2d_start``/``h2d_done`` measurements, the codec rate from its own
past encodes, and ``predict`` compares the predicted codec wall
against the predicted link savings.  On a bare-CPU "link"
(device_put is a memcpy at GB/s) the model never engages; under a
tunneled transport (or PPT_TUNNEL_EMU) it engages as soon as one
copy has been measured.  ``config.transport_compress`` picks the
policy: False = never, 'auto' = the cost model, True = always when
the payload is compressible (the deterministic A/B arm).
"""

import numpy as np

__all__ = ["probe_width", "encode_rows", "decode_rows", "CostModel",
           "resolve_transport_compress"]

# widths the device unpack supports (8 ships plain u8 residuals)
_WIDTHS = (1, 2, 4, 8)


def resolve_transport_compress(value=None):
    """Resolve a transport_compress knob value (None reads config) to
    False / 'auto' / True, loud on anything else."""
    from .. import config

    if value is None:
        value = getattr(config, "transport_compress", False)
    if value in (False, True, "auto"):
        return value
    raise ValueError(
        "transport_compress must be False, 'auto' or True; got "
        f"{value!r}")


def probe_width(arr):
    """Scan an integer payload's dynamic range: (nb, ...) ->
    (vmin (nb,) float32, width or None).

    width is the narrowest supported bit width holding every row's
    (value - row min) residual, or None when no width below the wire
    dtype's helps (the common full-range-quantized archive) or the
    per-row sample count does not pack to whole bytes."""
    if arr.dtype.kind not in "iu":
        return None, None
    flat = arr.reshape(arr.shape[0], -1)
    nsamp = flat.shape[1]
    vmin = flat.min(axis=1)
    # widen BEFORE subtracting: a full-range int16 span (~60000)
    # overflows int16 arithmetic and would falsely read as tiny
    span = int((flat.max(axis=1).astype(np.int64)
                - vmin.astype(np.int64)).max(initial=0))
    native = arr.dtype.itemsize * 8
    for w in _WIDTHS:
        if w >= native:
            return None, None
        if span < (1 << w) and nsamp % (8 // w) == 0:
            return vmin.astype(np.float32), w
    return None, None


def encode_rows(arr, vmin, width):
    """Pack integer payload residuals at ``width`` bits, MSB-first:
    (nb, ...) + per-row vmin -> (nb, nbytes) uint8.  The exact inverse
    is ops/decode.unpack_bitplanes + vmin (device) or
    :func:`decode_rows` (host oracle)."""
    flat = arr.reshape(arr.shape[0], -1)
    # residuals fit a byte by the probe_width contract (w <= 8), but
    # the SUBTRACTION must run widened — int16 - int16 can overflow
    v = (flat.astype(np.int32)
         - np.asarray(vmin, np.int32)[:, None]).astype(np.uint8)
    if width == 8:
        return v
    per = 8 // width
    grp = v.reshape(v.shape[0], v.shape[1] // per, per)
    out = np.zeros(grp.shape[:2], np.uint8)
    for j in range(per):
        out |= (grp[:, :, j] & ((1 << width) - 1)) \
            << np.uint8((per - 1 - j) * width)
    return out


def decode_rows(packed, vmin, width, shape, dtype):
    """Host-side inverse of :func:`encode_rows` (the codec round-trip
    oracle the property tests pin the device decode against)."""
    if width == 8:
        v = packed.astype(np.int64)
    else:
        per = 8 // width
        shifts = (np.arange(per - 1, -1, -1) * width).astype(np.uint8)
        v = ((packed[:, :, None] >> shifts) & ((1 << width) - 1))
        v = v.reshape(packed.shape[0], -1).astype(np.int64)
    nsamp = int(np.prod(shape[1:], dtype=int))
    v = v[:, :nsamp] + np.asarray(vmin, np.int64)[:, None]
    return v.reshape(shape).astype(dtype)


class CostModel:
    """Per-pipeline (per-device) transport cost model.

    ``observe_link`` feeds it each copy's shipped bytes/seconds (the
    same numbers the ``h2d_done`` event records); ``observe_codec``
    each encode's logical bytes/seconds.  ``predict`` answers "would
    compressing this payload have saved wall?": predicted codec wall
    (logical_bytes / codec rate) vs predicted link savings
    (bytes saved / link rate).  Until a link copy has been measured it
    always answers False — 'auto' must never speculate on an unknown
    link (the never-engages-at-a-loss gate)."""

    #: seed codec rate [bytes/s]: numpy bit-packing is memory-bound;
    #: a deliberately conservative figure so the first engagement
    #: decision under-promises (it re-learns from real encodes).
    CODEC_BPS_SEED = 300e6
    _ALPHA = 0.5  # EWMA weight of the newest observation

    def __init__(self):
        self.link_bps = None
        self.codec_bps = self.CODEC_BPS_SEED

    def _ewma(self, old, new):
        return new if old is None else \
            (1.0 - self._ALPHA) * old + self._ALPHA * new

    def observe_link(self, nbytes, seconds):
        if nbytes > 0 and seconds > 0:
            self.link_bps = self._ewma(self.link_bps, nbytes / seconds)

    def observe_codec(self, nbytes, seconds):
        if nbytes > 0 and seconds > 0:
            self.codec_bps = self._ewma(self.codec_bps,
                                        nbytes / seconds)

    def predict(self, logical_bytes, shipped_bytes):
        """True when compressing logical->shipped bytes is predicted
        to win wall time on this link."""
        if self.link_bps is None or shipped_bytes >= logical_bytes:
            return False
        saving_s = (logical_bytes - shipped_bytes) / self.link_bps
        codec_s = logical_bytes / self.codec_bps
        return saving_s > codec_s
