"""Diagnostic plots.

Parity targets: reference pplib.py:3652-4207 (show_portrait,
show_stacked_profiles, show_profiles, show_residual_plot,
show_spline_curve_projections, show_eigenprofiles) and the flux-profile
plot of fit_flux_profile (pplib.py:448-506).  All host-side matplotlib;
headless-safe (Agg) unless a display is configured.
"""

import os

import matplotlib

if not os.environ.get("DISPLAY"):
    matplotlib.use("Agg", force=False)

import matplotlib.pyplot as plt
import numpy as np


def set_colormap(name="viridis"):
    """Set the default image colormap (reference pplib.py:677)."""
    matplotlib.rcParams["image.cmap"] = name


def _finish(fig, show, savefig):
    if savefig:
        fig.savefig(savefig, bbox_inches="tight", dpi=120)
        plt.close(fig)
    elif show:
        plt.show()
    return fig


def show_portrait(port, phases=None, freqs=None, title=None, prof=True,
                  fluxprof=True, rvrsd=False, colorbar=True, show=True,
                  savefig=None, aspect="auto", interpolation="none",
                  origin="lower", extent=None, **kwargs):
    """Portrait image with average-profile (top) and phase-averaged-
    spectrum (left) side panels (reference pplib.py:3652-3757: same
    panel geometry, zero-weight channels compressed out of both side
    panels, rvrsd frequency flip, colorbar, extent override, and
    imshow passthrough kwargs e.g. vmin/vmax)."""
    port = np.asarray(port)
    nchan, nbin = port.shape
    if phases is None:
        phases = np.arange(nbin)
        xlabel = "Bin Number"
    else:
        phases = np.asarray(phases)
        xlabel = "Phase [rot]"
    if freqs is None:
        freqs = np.arange(nchan)
        ylabel = "Channel Number"
    else:
        freqs = np.asarray(freqs)
        ylabel = "Frequency [MHz]"
    if rvrsd:
        freqs = freqs[::-1]
        port = port[::-1]
    if extent is None:
        extent = (phases[0], phases[-1], freqs[0], freqs[-1])
    # zero-weight (zapped) channels carry no flux: compress them out
    # of the side panels exactly like the reference (weights = channel
    # means; np.compress)
    weights = port.mean(axis=1)
    portx = np.compress(weights, port, axis=0)
    fluxx = np.compress(weights, weights)
    freqsx = np.compress(weights, freqs)
    if portx.size == 0:  # fully zapped: fall back to raw panels
        portx, fluxx, freqsx = port, weights, freqs

    fig = plt.figure(figsize=(7.5, 6))
    gs = fig.add_gridspec(2 if prof else 1, 2 if fluxprof else 1,
                          width_ratios=([1, 3] if fluxprof else [1]),
                          height_ratios=([1, 3] if prof else [1]),
                          hspace=0.05, wspace=0.05)
    ax_im = fig.add_subplot(gs[-1, -1])
    im = ax_im.imshow(port, aspect=aspect, origin=origin, extent=extent,
                      interpolation=interpolation, **kwargs)
    if colorbar:
        fig.colorbar(im, ax=ax_im, pad=0.01)
    ax_im.set_xlabel(xlabel)
    if fluxprof:
        ax_im.tick_params(labelleft=False)
    else:
        ax_im.set_ylabel(ylabel)
    if prof:
        ax_p = fig.add_subplot(gs[0, -1], sharex=ax_im)
        avg = portx.mean(axis=0)
        ax_p.plot(phases, avg, "k-", lw=1)
        ax_p.tick_params(labelbottom=False)
        rng = avg.max() - avg.min()
        if rng > 0:  # a flat (fully-zapped) profile keeps auto limits
            ax_p.set_ylim(avg.min() - 0.03 * rng,
                          avg.max() + 0.05 * rng)
        ax_p.set_ylabel("Flux Units")
        if title:
            ax_p.set_title(title)
    elif title:
        ax_im.set_title(title)
    if fluxprof:
        ax_f = fig.add_subplot(gs[-1, 0], sharey=ax_im)
        # phase-averaged spectrum as markers, flux increasing LEFTWARD
        # (the reference's inverted x-axis, pplib.py:3741-3746)
        ax_f.plot(fluxx, freqsx, "kx", ms=4)
        rng = fluxx.max() - fluxx.min()
        if rng > 0:
            ax_f.set_xlim(fluxx.max() + 0.03 * rng,
                          min(fluxx.min(), 0.0) - 0.01 * rng)
        else:
            ax_f.invert_xaxis()
        ax_f.set_xlabel("Flux Units")
        ax_f.set_ylabel(ylabel)
    return _finish(fig, show, savefig)


def show_stacked_profiles(port, freqs=None, *, model_profiles=None,
                          phases=None, rvrsd=False, fit=False,
                          spacing=None, fact=0.25, show=True,
                          savefig=None, title=None):
    """Vertically offset per-channel profiles with optional overlaid
    model profiles (reference pplib.py:3760-3824: dashed model under
    solid data in matching colors; fit=True aligns/scales each model
    to its data profile via fit_phase_shift first; frequency tick
    labels every 10 channels; rvrsd flips the stack)."""
    port = np.asarray(port)
    nchan, nbin = port.shape
    models = None if model_profiles is None else \
        np.asarray(model_profiles)
    if phases is None:
        phases = np.arange(nbin)
        xlabel = "Bin Number"
    else:
        phases = np.asarray(phases)
        xlabel = "Phase [rot]"
    if freqs is None:
        freqs = np.arange(nchan)
        ylabel = "Approx. Channel Number"
    else:
        freqs = np.asarray(freqs)
        ylabel = "Approx. Frequency [MHz]"
    if rvrsd:
        freqs = freqs[::-1]
        port = port[::-1]
        if models is not None:
            models = models[::-1]
    if spacing is None:
        spacing = (port.max() - port.min()) * fact
    fig, ax = plt.subplots(figsize=(5, 8))
    for i in range(nchan):
        base = i * spacing
        if models is not None:
            mprof = models[i]
            if fit and np.any(port[i] - mprof):
                from ..fit import fit_phase_shift
                from ..ops import rotate_profile

                r = fit_phase_shift(port[i], mprof)
                mprof = float(r.scale) * np.asarray(
                    rotate_profile(mprof, -float(r.phase)))
            m, = ax.plot(phases, mprof + base, lw=1.2, ls="dashed")
            ax.plot(phases, port[i] + base, lw=0.8, ls="solid",
                    color=m.get_color())
        else:
            ax.plot(phases, port[i] + base, "k-", lw=0.6)
    ax.set_xlabel(xlabel)
    step = max(1, nchan // 10)
    ax.set_yticks(np.arange(nchan)[::step] * spacing)
    ax.set_yticklabels([str(int(round(f))) for f in freqs[::step]])
    ax.set_ylabel(ylabel)
    if title:
        ax.set_title(title)
    return _finish(fig, show, savefig)


def show_profiles(profiles, labels=None, show=True, savefig=None,
                  title=None):
    """Overlayed profiles (reference pplib.py:3827-3850)."""
    profiles = np.atleast_2d(np.asarray(profiles))
    nbin = profiles.shape[-1]
    phases = (np.arange(nbin) + 0.5) / nbin
    fig, ax = plt.subplots(figsize=(6, 4))
    for i, prof in enumerate(profiles):
        label = labels[i] if labels else None
        ax.plot(phases, prof, lw=1, label=label)
    ax.set_xlabel("Phase [rot]")
    ax.set_ylabel("Flux")
    if labels:
        ax.legend()
    if title:
        ax.set_title(title)
    return _finish(fig, show, savefig)


def show_residual_plot(port, model, phases=None, freqs=None,
                       noise_stds=None, weights=None, titles=None,
                       show=True, savefig=None):
    """Data / model / residual triptych with a per-channel reduced-chi2
    histogram (reference pplib.py:3853-3974)."""
    port = np.asarray(port)
    model = np.asarray(model)
    resid = port - model
    nchan, nbin = port.shape
    phases = np.asarray(phases) if phases is not None else \
        (np.arange(nbin) + 0.5) / nbin
    freqs = np.asarray(freqs) if freqs is not None else np.arange(nchan)
    extent = [phases[0], phases[-1], freqs[0], freqs[-1]]
    fig, axes = plt.subplots(2, 2, figsize=(9, 7))
    panels = [(port, "Data"), (model, "Model"), (resid, "Residuals")]
    for i, (ax, (img, name)) in enumerate(zip(axes.flat, panels)):
        ax.imshow(img, aspect="auto", origin="lower", extent=extent)
        ax.set_title(titles[i] if titles else name)
        ax.set_xlabel("Phase [rot]")
        ax.set_ylabel("Frequency [MHz]")
    ax = axes.flat[3]
    if noise_stds is not None:
        sig = np.where(np.asarray(noise_stds) > 0, noise_stds, np.inf)
        rchi2 = (resid ** 2).sum(axis=1) / sig ** 2 / max(nbin - 1, 1)
        if weights is not None:
            rchi2 = rchi2[np.asarray(weights) > 0]
        ax.hist(rchi2[np.isfinite(rchi2)], bins=min(30, max(5, nchan // 4)),
                color="0.3")
        ax.set_xlabel(r"Channel red-$\chi^2$")
        ax.set_ylabel("Count")
    else:
        ax.axis("off")
    fig.tight_layout()
    return _finish(fig, show, savefig)


def plot_flux_profile(freqs, fluxes, flux_errs, fit_result, nu_ref,
                      show=True, savefig=None):
    """Flux vs frequency with the fitted power law (reference
    fit_flux_profile plot, pplib.py:448-506)."""
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.errorbar(freqs, fluxes, yerr=flux_errs, fmt="k.", ms=4, lw=0.8)
    grid = np.linspace(min(freqs), max(freqs), 200)
    A = float(fit_result.amp)
    alpha = float(fit_result.alpha)
    ax.plot(grid, A * (grid / nu_ref) ** alpha, "r-", lw=1,
            label=rf"$\alpha$ = {alpha:.2f}")
    ax.set_xlabel("Frequency [MHz]")
    ax.set_ylabel("Flux")
    ax.legend()
    return _finish(fig, show, savefig)


def show_eigenprofiles(eigvec, smooth_eigvec=None, mean_prof=None,
                       smooth_mean_prof=None, show=True, savefig=None,
                       title=None):
    """Mean profile + significant eigenprofiles, raw and smoothed
    (reference pplib.py:4126-4207)."""
    eigvec = np.asarray(eigvec)
    ncomp = eigvec.shape[1] if eigvec.ndim == 2 else 0
    nrows = ncomp + (1 if mean_prof is not None else 0)
    fig, axes = plt.subplots(max(nrows, 1), 1,
                             figsize=(6, 2 * max(nrows, 1)),
                             sharex=True, squeeze=False)
    irow = 0
    if mean_prof is not None:
        ax = axes[irow, 0]
        ax.plot(mean_prof, "k-", lw=0.8, label="mean")
        if smooth_mean_prof is not None:
            ax.plot(smooth_mean_prof, "r-", lw=1, label="smoothed")
        ax.legend(loc="upper right", fontsize=7)
        irow += 1
    for icomp in range(ncomp):
        ax = axes[irow, 0]
        ax.plot(eigvec[:, icomp], "k-", lw=0.8,
                label=f"eigvec {icomp}")
        if smooth_eigvec is not None:
            ax.plot(np.asarray(smooth_eigvec)[:, icomp], "r-", lw=1)
        ax.legend(loc="upper right", fontsize=7)
        irow += 1
    axes[-1, 0].set_xlabel("Phase bin")
    if title:
        axes[0, 0].set_title(title)
    fig.tight_layout()
    return _finish(fig, show, savefig)


def show_spline_curve_projections(proj, freqs, tck=None, ncoord=None,
                                  show=True, savefig=None, title=None):
    """Pairwise projected-coordinate plots + coordinate-vs-frequency
    with spline curves and knots (reference pplib.py:3977-4123)."""
    from ..models.spline import bspline_eval

    proj = np.asarray(proj)
    freqs = np.asarray(freqs)
    ncomp = proj.shape[1] if ncoord is None else ncoord
    if tck is not None:
        grid = np.linspace(freqs.min(), freqs.max(), 256)
        curve = np.asarray(bspline_eval(grid, tck))
        knots = np.asarray(tck[0])
        kin = knots[(knots >= freqs.min()) & (knots <= freqs.max())]
        knot_vals = np.asarray(bspline_eval(kin, tck)) if len(kin) else None
    npair = max(ncomp - 1, 0)
    fig, axes = plt.subplots(1, npair + ncomp,
                             figsize=(3 * (npair + ncomp), 3),
                             squeeze=False)
    icol = 0
    for i in range(npair):
        ax = axes[0, icol]
        ax.plot(proj[:, i], proj[:, i + 1], "k.", ms=3)
        if tck is not None:
            ax.plot(curve[:, i], curve[:, i + 1], "r-", lw=1)
        ax.set_xlabel(f"coord {i}")
        ax.set_ylabel(f"coord {i + 1}")
        icol += 1
    for i in range(ncomp):
        ax = axes[0, icol]
        ax.plot(freqs, proj[:, i], "k.", ms=3)
        if tck is not None:
            ax.plot(grid, curve[:, i], "r-", lw=1)
            if knot_vals is not None:
                ax.plot(kin, knot_vals[:, i], "b|", ms=10)
        ax.set_xlabel("Frequency [MHz]")
        ax.set_ylabel(f"coord {i}")
        icol += 1
    if title:
        fig.suptitle(title)
    fig.tight_layout()
    return _finish(fig, show, savefig)
