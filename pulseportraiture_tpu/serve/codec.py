"""Result-over-the-wire codec + router-side ``.tim`` demux (ISSUE 13).

The remote transport (serve/transport.py) has always round-tripped the
FULL per-request TOA payload for its ``result`` op — this module is
that codec factored into its own seam, plus the two pieces that turn
it into a no-shared-filesystem serving story and an exactly-once
failover primitive:

- :func:`encode_result` / :func:`decode_result` — the JSON-safe
  per-request DataBunch codec (MJD ships as exact (int day, f64 frac);
  json round-trips f64 by shortest repr, inf frequency survives via
  the field being a plain float, and flag values keep the
  bool/int/float/str trichotomy ``.tim`` formatting branches on, with
  numpy scalars narrowed to builtins).
- :func:`write_tim_result` — the ROUTER-side demux writer: given a
  decoded result it writes the request's ``.tim`` byte-identical to
  the serving host's own demux (truncate, then per-archive TOA lines +
  completion sentinel).  This is the codec lane: a fleet WITHOUT a
  shared filesystem returns full TOA payloads over the wire and the
  router writes the ``.tim`` wherever the CLIENT lives
  (``ToaRouter(write_tim='router')`` / ``pproute --no-shared-fs``).
- :func:`tim_complete` / :func:`read_tim_result` — the durable-
  ``.tim`` failover primitives: the serving host writes a request's
  ``.tim`` atomically-at-completion (truncate + lines + one sentinel
  per archive), so a completion sentinel for EVERY request datafile
  proves the fit work is durable.  When a host dies with such a
  request uncollected, the router recovers the result from the file
  instead of re-fitting (serve/fleet.py's exactly-once story).

Recovery honesty: a recovered TOA re-serializes BYTE-IDENTICALLY
(``.tim`` numbers round-trip: <= 15 significant decimal digits map
through float64 and back to the same digits, and string flags pass
verbatim), but the in-memory DeltaDM summary statistics are NOT in the
file — a recovered DataBunch carries ``DM0s=[None...]``, NaN
DeltaDM_means/errs, and ``recovered_from_tim=True`` so a campaign
roll-up can tell (and re-derive from the ``-pp_dm`` flags if it must).
"""

import json
import math
import numbers
import os

import numpy as np

from ..utils.bunch import DataBunch

__all__ = ["encode_result", "decode_result", "iter_archive_toas",
           "write_tim_result", "copy_tim_atomic", "tim_complete",
           "read_tim_result"]


def _flag_value(v):
    """Narrow a flag value to what JSON round-trips: the
    bool/int/float/str distinction matters downstream (.tim
    formatting branches on it), and numpy scalars (incl. np.bool_,
    which json.dumps rejects outright) must narrow to the builtin."""
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, numbers.Integral):
        return int(v)
    if isinstance(v, numbers.Real):
        return float(v)
    return v


def _encode_toa(t):
    # MJD ships as (int day, float64 frac) — json round-trips float64
    # by shortest-repr exactly, so epoch precision survives the wire
    return {"archive": t.archive, "frequency": float(t.frequency),
            "mjd": [int(t.MJD.day), float(t.MJD.frac)],
            "toa_error": float(t.TOA_error), "telescope": t.telescope,
            "telescope_code": t.telescope_code,
            "dm": None if t.DM is None else float(t.DM),
            "dm_error": (None if t.DM_error is None
                         else float(t.DM_error)),
            "flags": {k: _flag_value(v) for k, v in t.flags.items()}}


def _decode_toa(d):
    from ..io.tim import TOA
    from ..utils.mjd import MJD

    day, frac = d["mjd"]
    return TOA(d["archive"], d["frequency"], MJD(int(day), float(frac)),
               d["toa_error"], d["telescope"], d["telescope_code"],
               DM=d["dm"], DM_error=d["dm_error"], flags=d["flags"])


def encode_result(res):
    """Per-request DataBunch (serve/server._maybe_complete's shape) ->
    a JSON-safe dict."""
    return {"toas": [_encode_toa(t) for t in res.TOA_list],
            "order": list(res.order),
            "DM0s": [None if v is None else float(v)
                     for v in res.DM0s],
            "DeltaDM_means": [float(v) for v in res.DeltaDM_means],
            "DeltaDM_errs": [float(v) for v in res.DeltaDM_errs],
            "tim_out": res.tim_out, "n_skipped": int(res.n_skipped)}


def decode_result(d):
    return DataBunch(TOA_list=[_decode_toa(t) for t in d["toas"]],
                     order=list(d["order"]), DM0s=list(d["DM0s"]),
                     DeltaDM_means=list(d["DeltaDM_means"]),
                     DeltaDM_errs=list(d["DeltaDM_errs"]),
                     tim_out=d["tim_out"],
                     n_skipped=int(d["n_skipped"]))


def roundtrip_result(res):
    """Encode -> JSON bytes -> decode, exactly what the socket lane
    does (InProcTransport rides this so both transports return
    identical result shapes and the codec is exercised wherever the
    router is)."""
    return decode_result(json.loads(
        json.dumps(encode_result(res), separators=(",", ":"))))


# ---------------------------------------------------------------------------
# router-side .tim demux (the codec / no-shared-fs lane)
# ---------------------------------------------------------------------------

def iter_archive_toas(result):
    """Split ``result.TOA_list`` into per-archive runs following
    ``result.order`` — the inverse of ``_collect_wideband``'s
    concatenation.  Relies on the demux invariant that TOA.archive is
    the submitted datafile path and each archive's TOAs are contiguous
    in request-archive order; refuses adjacent duplicate order entries
    (the grouping would be ambiguous)."""
    toas = list(result.TOA_list)
    i = 0
    prev = object()
    for datafile in result.order:
        if datafile == prev:
            raise ValueError(
                f"result order lists {datafile!r} twice in a row — "
                "per-archive TOA grouping is ambiguous")
        prev = datafile
        j = i
        while j < len(toas) and toas[j].archive == datafile:
            j += 1
        yield datafile, toas[i:j]
        i = j
    if i != len(toas):
        raise ValueError(
            f"{len(toas) - i} TOA(s) name archives missing from the "
            "result order — the payload does not demux")


def write_tim_result(result, tim_out):
    """Write a request's ``.tim`` from its decoded result — byte-
    identical to the SERVING host's demux (per-archive TOA lines +
    completion sentinel, via the same write_TOAs path) — so fleets
    without a shared filesystem produce the same bytes the shared-fs
    lane does.  The write is ATOMIC (temp file + os.replace): a
    reader, a crash, or a concurrent writer on the same path never
    sees a torn file from THIS writer.  Gated by tests and
    bench_router's ``codec_tim_identical``."""
    from ..io.tim import write_TOAs
    from ..pipeline.stream import _DONE_PREFIX

    tmp = tim_out + ".tmp~"
    open(tmp, "w").close()
    for datafile, toas in iter_archive_toas(result):
        write_TOAs(toas, outfile=tmp, append=True)
        with open(tmp, "a") as fh:
            fh.write(_DONE_PREFIX + os.path.abspath(datafile) + "\n")
    os.replace(tmp, tim_out)
    return tim_out


def copy_tim_atomic(src, dst):
    """Byte-copy a durable ``.tim`` (or any completed payload file) to
    ``dst`` with the same temp-then-``os.replace`` discipline as
    :func:`write_tim_result`.  The result-cache hit path serves stored
    entries through this — a hit's output is the stored bytes EXACTLY,
    never a re-serialization, so hit == fresh fit at the byte level
    holds by construction rather than by round-trip proof."""
    tmp = dst + ".tmp~"
    with open(src, "rb") as fin, open(tmp, "wb") as fout:
        while True:
            chunk = fin.read(1 << 20)
            if not chunk:
                break
            fout.write(chunk)
    os.replace(tmp, dst)
    return dst


# ---------------------------------------------------------------------------
# durable-.tim failover primitives
# ---------------------------------------------------------------------------

def tim_complete(tim_out, datafiles):
    """True when the ``.tim`` at ``tim_out`` carries a completion
    sentinel for EVERY request datafile — the request's fit work is
    durable and must not be re-dispatched.  Sentinel parsing (incl.
    the torn-tail rule) is the stream checkpointer's
    ``checkpoint_completed``, so the two consumers of the format
    cannot drift.  A request that skipped archives writes fewer
    sentinels and reads as incomplete here; failover then
    re-dispatches it, which is safe just not free."""
    from ..pipeline.stream import checkpoint_completed

    done = checkpoint_completed(tim_out)
    return bool(done) and all(os.path.abspath(str(f)) in done
                              for f in datafiles)


def read_tim_result(tim_out):
    """Recover a per-request result from its durable ``.tim`` (the
    exactly-once failover collect path: the serving host died AFTER
    the request's sentinels landed but before the client pulled the
    payload).

    The recovered TOAs re-serialize byte-identically (every numeric
    field round-trips through its .tim formatting; flags come back as
    the verbatim strings toa_string writes verbatim), so the ``.tim``
    product is exact.  The DeltaDM summary is NOT in the file: DM0s
    are None, DeltaDM_means/errs NaN, and ``recovered_from_tim=True``
    marks the bunch."""
    from ..io.tim import TOA
    from ..pipeline.stream import _DONE_PREFIX
    from ..timing.tim import read_tim
    from ..utils.mjd import MJD

    TOA_list, order = [], []
    run_lines = []
    with open(tim_out) as fh:
        for line in fh:
            if line.startswith(_DONE_PREFIX):
                datafile = line[len(_DONE_PREFIX):].strip()
                run = read_tim(run_lines)
                if run:
                    # order entries must match TOA.archive (the
                    # SUBMITTED path — iter_archive_toas groups on it);
                    # the sentinel's abspath only covers 0-TOA archives
                    datafile = run[0].archive
                for tt in run:
                    flags = dict(tt.flags)
                    flags.pop("pp_dm", None)
                    flags.pop("pp_dme", None)
                    TOA_list.append(TOA(
                        tt.archive, tt.frequency,
                        MJD(tt.mjd_int, tt.mjd_frac), tt.error_us,
                        tt.site, tt.site, DM=tt.dm,
                        DM_error=tt.dm_err, flags=flags))
                order.append(datafile)
                run_lines = []
            else:
                run_lines.append(line)
    if run_lines and any(ln.strip() for ln in run_lines):
        raise ValueError(
            f"{tim_out}: trailing TOA lines with no completion "
            "sentinel — the file is not a completed request "
            "checkpoint")
    n = len(order)
    return DataBunch(TOA_list=TOA_list, order=order, DM0s=[None] * n,
                     DeltaDM_means=[math.nan] * n,
                     DeltaDM_errs=[math.nan] * n, tim_out=tim_out,
                     n_skipped=0, recovered_from_tim=True)
