"""make_fake_pulsar end-to-end: generated archives load back with the
injected (phase, dDM) recoverable by the portrait fit — the reference's
own verification pattern (examples/example.py:149-158; SURVEY §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import fit_portrait
from pulseportraiture_tpu.io import load_data
from pulseportraiture_tpu.io.gmodel import gen_gmodel_portrait
from pulseportraiture_tpu.ops.phasor import phase_transform
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J0000+0000", "RAJ": "00:00:00.0", "DECJ": "+00:00:00.0",
       "P0": 0.005, "PEPOCH": 55000.0, "DM": 30.0}


@pytest.fixture(scope="module")
def fake_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fake") / "fake.fits")
    model = default_test_model(1500.0)
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, npol=1, nchan=32,
                     nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                     phase=0.02, dDM=5e-3, start_MJD=MJD(55100, 0.25),
                     noise_stds=0.05, dedispersed=False, quiet=True,
                     rng=42)
    return path, model


def test_archive_loads_dispersed(fake_file):
    path, model = fake_file
    d = load_data(path, dedisperse=False, quiet=True)
    assert d.nsub == 2 and d.nchan == 32 and d.nbin == 256
    assert not d.dmc  # written dispersed
    assert d.DM == 30.0  # header DM is the ephemeris DM (dDM hidden)
    assert d.Ps[0] == pytest.approx(0.005)
    assert abs(d.epochs[0] - MJD(55100, 0.25)) * 86400.0 == \
        pytest.approx(30.0, abs=1e-3)  # mid-subint of tsub=60
    assert d.source == "J0000+0000"


def test_injection_recovery(fake_file):
    """Fit the dedispersed fake data against the clean model: recover
    phase and DM+dDM."""
    path, model = fake_file
    d = load_data(path, dedisperse=False, quiet=True)
    P = float(d.Ps[0])
    freqs = jnp.asarray(d.freqs[0])
    mport = jnp.asarray(gen_gmodel_portrait(
        model, d.phases, np.asarray(d.freqs[0]), P=P))
    res = fit_portrait(jnp.asarray(d.subints[0, 0]), mport,
                       jnp.asarray(d.noise_stds[0, 0]), freqs, P,
                       DM0=float(d.DM))
    DM_inj = 30.0 + 5e-3
    assert float(res.DM) == pytest.approx(DM_inj, abs=5 * float(res.DM_err))
    assert abs(float(res.DM) - DM_inj) < 2e-3
    phi_ref = phase_transform(float(res.phi), float(res.DM),
                              float(res.nu_DM), 1500.0, P)
    # injected achromatic phase referenced to infinite frequency; the
    # dispersive part of the recovered phase at 1500 comes from DM_inj
    # measured against the header dedispersion at DM=30: residual
    # phase at 1500 = phase + Dconst*dDM/P/1500^2
    from pulseportraiture_tpu.config import Dconst

    expect = 0.02 + Dconst * 5e-3 / P / 1500.0 ** 2
    expect = ((expect + 0.5) % 1.0) - 0.5
    assert phi_ref == pytest.approx(expect, abs=2e-3)


def test_scintillation_and_weights(tmp_path):
    model = default_test_model(1500.0)
    w = np.ones((1, 16))
    w[:, :3] = 0.0
    path = str(tmp_path / "scint.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=1, nchan=16, nbin=128,
                     tsub=30.0, noise_stds=0.02, weights=w, scint=True,
                     dedispersed=True, quiet=True, rng=7)
    d = load_data(path, quiet=True)
    assert list(d.ok_ichans[0]) == list(range(3, 16))
    # scintillation: channel flux varies more than noise alone
    flux = d.subints[0, 0].mean(axis=-1)
    assert flux[3:].std() > 0.0


def test_scattering_injection(tmp_path):
    model = default_test_model(1500.0)
    path = str(tmp_path / "scat.fits")
    t_scat = 2e-4
    make_fake_pulsar(model, PAR, outfile=path, nsub=1, nchan=8, nbin=256,
                     tsub=30.0, noise_stds=0.0, t_scat=t_scat, alpha=-4.0,
                     dedispersed=True, quiet=True, rng=1)
    d = load_data(path, quiet=True, rm_baseline=False)
    # scattered profiles have positive skew along phase vs the clean model
    clean = np.asarray(gen_gmodel_portrait(model, d.phases,
                                           np.asarray(d.freqs[0]),
                                           P=0.005))
    # lowest channel scatters most (alpha<0): broader profile -> lower peak
    peak_ratio_low = d.subints[0, 0, 0].max() / clean[0].max()
    peak_ratio_high = d.subints[0, 0, -1].max() / clean[-1].max()
    assert peak_ratio_low < peak_ratio_high < 1.01


def test_dm_nu_injection(tmp_path):
    """xs/Cs power-law DM(nu) terms move channels by the expected
    delays."""
    from pulseportraiture_tpu.synth.archive import _dm_nu_delays

    freqs = np.array([1200.0, 1500.0, 1800.0])
    d1 = _dm_nu_delays(0.0, 1e-3, 0.005, freqs, None, None, np.inf)
    from pulseportraiture_tpu.config import Dconst

    np.testing.assert_allclose(d1, Dconst * 1e-3 * freqs ** -2.0 / 0.005)
    d2 = _dm_nu_delays(0.01, 0.0, 0.005, freqs, [-4.0], [2.0], 1500.0)
    np.testing.assert_allclose(
        d2, 0.01 + 2.0 * (freqs ** -4.0 - 1500.0 ** -4.0) / 0.005)
