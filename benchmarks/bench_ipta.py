"""BASELINE.md config 5 (multi-pulsar form): a scaled-down IPTA
campaign — 5 pulsars x 40 archives, each with its own template/period/
DM, streamed through pipeline/ipta.stream_ipta_campaign (per-pulsar
buckets, per-pulsar .tim outputs).

The full config is 45 pulsars x ~1000 archives over a pod; this bench
measures the single-process/one-chip slice end-to-end (file IO, raw
int16 decode on device, fused dispatches, .tim assembly) — multi-host
scaling is archive-parallel with no cross-host communication, so the
pod number is this value x hosts (validated with real processes by
tests/test_multihost_spawn.py).

Prints ONE JSON line like bench.py.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    import jax

    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline import IPTAJob, stream_ipta_campaign
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NPSR = int(os.environ.get("PPT_NPSR", 5))
    NARCH = int(os.environ.get("PPT_NARCH", 40))
    NSUB = int(os.environ.get("PPT_NSUB", 4))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))

    with tempfile.TemporaryDirectory() as td:
        jobs = []
        for k in range(NPSR):
            psr = f"J{k:02d}00+{k:02d}"
            nu_ref = 1400.0 + 50.0 * k
            mpath = os.path.join(td, f"{psr}.gmodel")
            write_gmodel(default_test_model(nu_ref), mpath, quiet=True)
            par = {"PSR": psr, "P0": 0.002 + 5e-4 * k,
                   "DM": 10.0 + 15.0 * k, "PEPOCH": 56000.0}
            files = []
            for i in range(NARCH):
                path = os.path.join(td, f"{psr}_a{i:03d}.fits")
                make_fake_pulsar(mpath, par, outfile=path, nsub=NSUB,
                                 nchan=NCHAN, nbin=NBIN, nu0=nu_ref,
                                 bw=600.0, phase=0.01 * i, dDM=1e-4 * i,
                                 noise_stds=0.05, quiet=True,
                                 rng=100 * k + i)
                files.append(path)
            jobs.append(IPTAJob(psr, files, mpath))

        outdir = os.path.join(td, "tims")
        # warm (compile) on a 1-archive slice of each layout, then the
        # full campaign
        stream_ipta_campaign(
            [IPTAJob(j.pulsar, j.datafiles[:1], j.modelfile)
             for j in jobs], nsub_batch=64, quiet=True)
        t0 = time.perf_counter()
        res = stream_ipta_campaign(jobs, outdir=outdir, nsub_batch=64,
                                   quiet=True)
        wall = time.perf_counter() - t0
        ntim = len(os.listdir(outdir))

    ntoa = len(res.TOA_list)
    print(json.dumps({
        "metric": f"IPTA campaign: {NPSR} pulsars x {NARCH} archives x "
                  f"{NSUB}sub x {NCHAN}ch x {NBIN}bin, per-pulsar "
                  "models + .tim outputs",
        "value": round(ntoa / wall, 2),
        "unit": "TOAs/sec",
        "wall_s": round(wall, 2),
        "toas": ntoa,
        "pulsars": NPSR,
        "tim_files": ntim,
        "fit_fraction": round(float(res.fit_duration) / max(wall, 1e-9),
                              3),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
