"""IPTA-scale multi-pulsar campaign driver (BASELINE.md config 5).

The reference measures one pulsar per invocation with a strictly
sequential archive loop (pptoas.py:258); config 5 is "45 pulsars x
~1000 archives, spline model + TOAs, streamed over pod".  This module
is the orchestration layer above pipeline/stream.py:

- a **job registry**: each pulsar brings its own archive list, template
  model, and optional per-pulsar fit options;
- **multi-host sharding across the (pulsar, archive) grid**: the
  flattened grid is dealt round-robin over processes
  (parallel.shard_files), so every host carries a balanced slice of
  every pulsar and no cross-host coordination is needed until the
  final summary gather;
- **per-pulsar buckets and outputs**: each pulsar's shard streams
  through stream_wideband_TOAs with its own model — bucket keys are
  per-pulsar by construction (different template portraits must never
  share a fused dispatch), and TOAs append incrementally to
  ``outdir/<pulsar>[.p<process>].tim`` so an interrupted campaign
  keeps every completed archive on disk;
- **cross-host summaries**: per-pulsar DeltaDM means/errors are
  allgathered (parallel.process_allgather) so every process returns
  the full campaign picture.

Why per-pulsar passes instead of one pooled cross-pulsar pass: subints
of different pulsars can never share a fused dispatch (each needs its
own template portrait), so pooling across pulsars buys nothing once a
pulsar's shard holds >= nsub_batch subints — at IPTA scale (~1000
archives x subints per pulsar) every bucket fills many times over
within one pulsar.  Cross-pulsar pooling would only reduce padding for
tiny per-pulsar shards, at the cost of per-element template DFTs in
every dispatch.
"""

import os
import time

import numpy as np

from ..utils.bunch import DataBunch
from .stream import stream_wideband_TOAs
from .toas import _is_metafile, _read_metafile

__all__ = ["IPTAJob", "stream_ipta_campaign"]


class IPTAJob:
    """One pulsar's campaign slice: archives + template + options.

    datafiles: list of paths or a metafile path; modelfile: .gmodel /
    spline / PSRFITS template; kwargs: per-pulsar overrides forwarded
    to stream_wideband_TOAs (e.g. fit_scat=True for the scattered
    pulsars only, DM0=...).
    """

    def __init__(self, pulsar, datafiles, modelfile, **kwargs):
        self.pulsar = str(pulsar)
        if isinstance(datafiles, str):
            self.datafiles = (_read_metafile(datafiles)
                              if _is_metafile(datafiles) else [datafiles])
        else:
            self.datafiles = list(datafiles)
        self.modelfile = str(modelfile)
        self.kwargs = dict(kwargs)


def stream_ipta_campaign(jobs, outdir=None, shard=True, nsub_batch=256,
                         quiet=False, **stream_kwargs):
    """Measure wideband TOAs for a multi-pulsar campaign.

    jobs: sequence of IPTAJob (or (pulsar, datafiles, modelfile)
    tuples).  outdir: directory for per-pulsar .tim outputs (created;
    None = no .tim files).  shard=True splits the flattened
    (pulsar, archive) grid round-robin across jax processes when the
    distributed runtime is initialized (parallel/multihost.py) — on a
    single process it is a no-op.  stream_kwargs: campaign-wide
    defaults forwarded to every stream_wideband_TOAs call (per-job
    kwargs override them).

    Returns a DataBunch with:
      pulsars     — job order (all jobs, even if this host's shard of
                    one is empty)
      per_pulsar  — {pulsar: stream result DataBunch} for THIS host's
                    shard
      TOA_list    — this host's TOAs across all pulsars
      DeltaDM_summary — {pulsar: (means, errs)} with per-archive
                    offset-DM statistics ALLGATHERED across hosts
                    (every process sees the whole campaign's values)
      nfit, fit_duration, wall_s — aggregate accounting
    """
    from .. import parallel

    jobs = [j if isinstance(j, IPTAJob) else IPTAJob(*j) for j in jobs]
    names = [j.pulsar for j in jobs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate pulsar names in jobs: {names}")
    if outdir:
        os.makedirs(outdir, exist_ok=True)

    # ---- shard the flattened (pulsar, archive) grid ------------------
    grid = [(j.pulsar, f) for j in jobs for f in j.datafiles]
    pid, nproc = parallel.process_index(), parallel.process_count()
    mine = parallel.shard_files(grid) if shard else grid
    by_psr = {}
    for psr, f in mine:
        by_psr.setdefault(psr, []).append(f)

    t0 = time.time()
    per_pulsar = {}
    TOA_list = []
    nfit = 0
    fit_duration = 0.0
    for job in jobs:
        files = by_psr.get(job.pulsar, [])
        if not files:
            continue
        tim_out = None
        if outdir:
            suffix = f".p{pid}" if (shard and nproc > 1) else ""
            tim_out = os.path.join(outdir, f"{job.pulsar}{suffix}.tim")
        kw = {**stream_kwargs, **job.kwargs}
        res = stream_wideband_TOAs(
            files, job.modelfile, nsub_batch=nsub_batch,
            tim_out=tim_out, quiet=True, **kw)
        per_pulsar[job.pulsar] = res
        TOA_list.extend(res.TOA_list)
        nfit += res.nfit
        fit_duration += res.fit_duration

    # ---- allgather per-pulsar DeltaDM summaries across hosts ---------
    summary = {}
    for job in jobs:
        res = per_pulsar.get(job.pulsar)
        means = np.asarray(res.DeltaDM_means if res else [], float)
        errs = np.asarray(res.DeltaDM_errs if res else [], float)
        gm = parallel.process_allgather(means)
        ge = parallel.process_allgather(errs)
        summary[job.pulsar] = (np.concatenate([np.atleast_1d(g)
                                               for g in gm]),
                               np.concatenate([np.atleast_1d(g)
                                               for g in ge]))

    wall = time.time() - t0
    if not quiet:
        n = len(TOA_list)
        print(f"IPTA campaign: {n} TOAs across {len(per_pulsar)}/"
              f"{len(jobs)} pulsars on process {pid}/{nproc} in "
              f"{wall:.2f} s ({nfit} fused dispatches, "
              f"{n / max(wall, 1e-9):.1f} TOAs/s end-to-end)")
    return DataBunch(pulsars=names, per_pulsar=per_pulsar,
                     TOA_list=TOA_list, DeltaDM_summary=summary,
                     nfit=nfit, fit_duration=fit_duration, wall_s=wall)
