"""Template-builder pipelines: ppgauss/ppspline equivalents.

Oracles: models built from noisy synthetic data reproduce the clean
generating portrait (residuals at the noise level); built templates
feed back into GetTOAs and recover injections (the full reference
workflow example.py: align -> model -> TOAs)."""

import numpy as np
import pytest

from pulseportraiture_tpu.io.gmodel import gen_gmodel_portrait, read_gmodel
from pulseportraiture_tpu.io.splmodel import read_spline_model
from pulseportraiture_tpu.pipeline.gauss import (
    GaussPortrait,
    profile_to_portrait_params,
)
from pulseportraiture_tpu.pipeline.spline import SplinePortrait
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1909-3744", "RAJ": "19:09:47.4", "DECJ": "-37:44:14.5",
       "P0": 0.002947, "PEPOCH": 55000.0, "DM": 10.391}


@pytest.fixture(scope="module")
def avg_file(tmp_path_factory):
    """A high-S/N 'average' archive (the template-building input)."""
    root = tmp_path_factory.mktemp("models")
    model = default_test_model(1500.0)
    path = str(root / "avg.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=1, nchan=48, nbin=256,
                     nu0=1500.0, bw=800.0, tsub=1800.0, noise_stds=0.01,
                     dedispersed=True, start_MJD=MJD(55200, 0.3),
                     quiet=True, rng=21)
    return path, model


def test_gauss_model_recovery(avg_file, tmp_path):
    path, truth = avg_file
    dp = GaussPortrait(path, quiet=True)
    gm = dp.make_gaussian_model(ref_prof=(1500.0, 200.0), niter=3,
                                auto_gauss=0.02, quiet=True)
    # fitted model portrait ~ clean generating portrait
    clean = np.asarray(gen_gmodel_portrait(truth, dp.phases, dp.freqs[0],
                                           P=float(dp.Ps[0])))
    resid = dp.model - clean
    assert np.sqrt((resid ** 2).mean()) < 0.05  # ~5x noise, multi-comp
    assert dp.portrait_red_chi2 < 2.0
    # round-trip to disk and back into a portrait generator
    out = str(tmp_path / "fit.gmodel")
    dp.model_name = "TEST_FIT"
    dp.write_model(out, quiet=True)
    back = read_gmodel(out, quiet=True)
    assert back.ngauss == dp.ngauss
    port = gen_gmodel_portrait(back, dp.phases, dp.freqs[0],
                               P=float(dp.Ps[0]))
    np.testing.assert_allclose(port, dp.model, atol=2e-4)
    err_out = dp.write_errfile(str(tmp_path / "fit.gmodel_errs"),
                               quiet=True)
    errs = read_gmodel(err_out, quiet=True)
    assert errs.ngauss == dp.ngauss


def test_gauss_resume_from_modelfile(avg_file, tmp_path):
    path, truth = avg_file
    from pulseportraiture_tpu.io.gmodel import write_gmodel

    seed = str(tmp_path / "seed.gmodel")
    write_gmodel(truth, seed, quiet=True)
    dp = GaussPortrait(path, quiet=True)
    dp.make_gaussian_model(modelfile=seed, niter=1, quiet=True)
    assert dp.ngauss == truth.ngauss
    assert dp.nu_ref == truth.nu_ref
    clean = np.asarray(gen_gmodel_portrait(truth, dp.phases, dp.freqs[0],
                                           P=float(dp.Ps[0])))
    assert np.sqrt(((dp.model - clean) ** 2).mean()) < 0.03


def test_profile_to_portrait_params():
    out = profile_to_portrait_params([0.1, 2.0, 0.5, 0.05, 3.0,
                                      0.7, 0.02, 1.5])
    np.testing.assert_allclose(
        out, [0.1, 2.0, 0.5, 0.0, 0.05, 0.0, 3.0, 0.0,
              0.7, 0.0, 0.02, 0.0, 1.5, 0.0])


@pytest.mark.slow  # ~23 s full spline build+recovery (tier-1 budget,
# r19): the spline math keeps tier-1 units in test_spline.py and the
# gauss recovery path below covers the model-build pipeline
def test_spline_model_recovery(avg_file, tmp_path):
    path, truth = avg_file
    dp = SplinePortrait(path, quiet=True)
    dp.normalize_portrait("prof")
    spl = dp.make_spline_model(max_ncomp=4, smooth=True, snr_cutoff=50.0,
                               quiet=True)
    assert dp.ncomp >= 1  # evolving profile shape -> >=1 component
    # model matches the (normalized) data at the noise level
    resid = dp.portx - dp.modelx
    assert np.abs(resid).std() < 3.0 * np.median(dp.noise_stdsxs[0])
    # persistence round-trip, both formats
    for name in ("m.spl", "m.npz"):
        out = str(tmp_path / name)
        dp.write_model(out, quiet=True)
        back = read_spline_model(out, quiet=True)
        got = back.portrait(dp.freqsxs[0])
        np.testing.assert_allclose(got, dp.modelx, atol=1e-8)


@pytest.mark.slow  # ~15 s spline build; the spline pipeline stays
# tier-1 via test_built_templates_feed_pptoas
def test_spline_model_zero_components(avg_file, tmp_path):
    """With an impossible S/N cutoff the model degrades to the mean
    profile (reference ncomp == 0 branch)."""
    path, truth = avg_file
    dp = SplinePortrait(path, quiet=True)
    dp.make_spline_model(snr_cutoff=np.inf, smooth=False, quiet=True)
    assert dp.ncomp == 0
    assert np.allclose(dp.model, dp.model[0])


def test_selector_programmatic():
    """GaussianSelector's non-GUI action API drives the same fit."""
    import matplotlib

    matplotlib.use("Agg", force=True)
    from pulseportraiture_tpu.viz.selector import GaussianSelector

    from pulseportraiture_tpu.fit.gauss import gen_gaussian_profile_flat

    prof = np.asarray(gen_gaussian_profile_flat(
        np.array([0.0, 0.0, 0.42, 0.04, 6.0]), 256))
    rng = np.random.default_rng(0)
    noisy = prof + 0.02 * rng.standard_normal(256)
    sel = GaussianSelector(noisy, show=False)
    sel.add_component(0.45, 0.06, noisy.max())
    sel.do_fit()
    assert sel.chi2 / sel.dof < 1.5
    fitted = sel.fitted_params
    assert fitted[2] == pytest.approx(0.42, abs=1e-3)  # loc
    assert fitted[3] == pytest.approx(0.04, abs=2e-3)  # wid
    sel.add_component(0.3, 0.05, 0.5)
    sel.remove_last()
    assert sel.ngauss == 1


def test_built_templates_feed_pptoas(avg_file, tmp_path):
    """The reference workflow: build both template kinds from the
    average portrait, then measure TOAs on fresh epochs with each."""
    path, truth = avg_file
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline import GetTOAs

    # template files
    dpg = GaussPortrait(path, quiet=True)
    dpg.make_gaussian_model(ref_prof=(1500.0, 200.0), niter=2,
                            auto_gauss=0.02, quiet=True)
    gfile = str(tmp_path / "tmpl.gmodel")
    dpg.model_name = "TMPL"
    dpg.write_model(gfile, quiet=True)
    dps = SplinePortrait(path, quiet=True)
    dps.make_spline_model(max_ncomp=4, snr_cutoff=50.0, quiet=True)
    sfile = str(tmp_path / "tmpl.spl")
    dps.write_model(sfile, quiet=True)
    # fresh epoch with a known dDM
    epoch = str(tmp_path / "epoch.fits")
    make_fake_pulsar(truth, PAR, outfile=epoch, nsub=2, nchan=48,
                     nbin=256, tsub=120.0, noise_stds=0.05, dDM=3e-4,
                     dedispersed=False, start_MJD=MJD(55300, 0.2),
                     quiet=True, rng=33)
    for tmpl in (gfile, sfile):
        gt = GetTOAs(epoch, tmpl, quiet=True)
        gt.get_TOAs(quiet=True)
        assert len(gt.TOA_list) == 2
        assert gt.DeltaDM_means[0] == pytest.approx(
            3e-4, abs=max(5 * gt.DeltaDM_errs[0], 2e-4)), tmpl
