"""On-device PSRFITS sample decode (the raw streaming lane's stage 1).

The streaming campaign drivers ship the UNDECODED DATA column payload
to the accelerator — 2-32x fewer bytes than decoded float64 on a link
that bottlenecks the whole campaign — and decode there, inside the
fused bucket program.  These kernels are the single source of truth
for that decode: the bit-plane unpack for sub-byte packed samples, the
affine sample reconstruction per TFORM sample type (including general
FITS column TSCAL/TZERO scaling), and the polarization reduction to
Stokes I for multi-pol archives.  The host-side oracle is
``io/psrfits.read_archive`` / ``io/native.decode_fused`` (the FITS
fuzz corpus pins its semantics); tests assert the two lanes produce
digit-identical TOAs.

Sample-type codes (``RAW_CODES``) name the wire format the host
shipped, after any endian normalization (``io/psrfits`` byteswaps
int16/float32 to native order — a memcpy pass, no float decode):

  'i16'  int16 samples        (TFORM 'I', the classic PSRFITS layout)
  'u8'   unsigned byte        (TFORM 'B')
  'i8'   signed byte          (TFORM 'B' with the FITS TZERO=-128
         convention: stored unsigned, physical = stored - 128 — the
         subtraction happens HERE, exactly, before DAT_SCL/DAT_OFFS,
         matching the host decode order bit-for-bit)
  'f32'  float32 samples      (TFORM 'E'; DAT_SCL/DAT_OFFS usually
         identity but applied uniformly anyway)
  'p1'/'p2'/'p4'  sub-byte packed unsigned samples (NBIT=1/2/4, the
         search/fold-era backends): the wire payload is the PACKED
         bytes, MSB-first per the PSRFITS convention, row byte-pad
         already trimmed on host; :func:`unpack_bitplanes` restores
         the unsigned sample values with integer shifts/masks HERE —
         a 2-bit archive ships 32x fewer bytes than decoded f64.

General FITS column scaling (TSCAL/TZERO beyond the signed-byte
convention) ships as two extra per-subint scalars and folds into
:func:`affine_decode` as one more fused multiply-add, in the exact
host order: physical = (stored*TSCAL + TZERO)*DAT_SCL + DAT_OFFS.
"""

import jax.numpy as jnp

from .noise import min_window_baseline

RAW_CODES = ("i16", "u8", "i8", "f32", "p1", "p2", "p4")

# packed sub-byte codes -> bits per sample
PACKED_BITS = {"p1": 1, "p2": 2, "p4": 4}


def unpack_bitplanes(packed, nbit, nsamp):
    """Unpack MSB-first ``nbit``-wide samples from a packed byte
    payload: (..., nbytes) uint8 -> (..., nsamp) uint8 sample values.

    The PSRFITS packing order (io/psrfits.py host unpack, forge
    corpus): within each byte the FIRST sample occupies the most
    significant bits.  ``nsamp`` trims any trailing byte padding
    (static, so the program shape is fixed).  Integer shifts and masks
    only — this is the jittable mirror of the host unpack, bit-exact
    by construction."""
    if nbit not in (1, 2, 4):
        raise ValueError(f"unpack_bitplanes: nbit must be 1, 2 or 4, "
                         f"got {nbit}")
    per = 8 // nbit
    mask = (1 << nbit) - 1
    # Python-int shifts (weak-typed scalars) rather than an arange
    # vector: scalar constants are legal inside Pallas kernel bodies
    # (ops.fused.fused_decode_cross_spectrum_pallas calls this per
    # channel tile), captured array constants are not.  Identical
    # integer ops either way — bit-exact.
    parts = [(packed[..., :, None] >> ((per - 1 - k) * nbit)) & mask
             for k in range(per)]
    samples = jnp.concatenate(parts, axis=-1)
    samples = samples.reshape(packed.shape[:-1]
                              + (packed.shape[-1] * per,))
    return samples[..., :nsamp]


def _bcast_row(v, x):
    """Broadcast a per-subint (nb,) scalar vector against the payload
    x of shape (nb, [npol,] nchan, nbin)."""
    return jnp.reshape(v, v.shape + (1,) * (x.ndim - v.ndim))


def affine_decode(raw, scl, offs, ft, code="i16", tscal=None, tzero=None):
    """Decode raw samples to physical amplitudes: ``x * scl + offs``
    per channel, in dtype ``ft``, with the signed-byte bias removed
    first for code 'i8' and any general FITS column scaling
    (``tscal``/``tzero``, per-subint scalars) applied first for the
    other integer codes.

    raw: (..., nchan, nbin) integer or float SAMPLE VALUES (packed
    codes must be unpacked with :func:`unpack_bitplanes` first);
    scl/offs: (..., nchan) per-channel DAT_SCL/DAT_OFFS.  The
    operation order (cast, column scaling, scale, offset) mirrors the
    host decode exactly so the two lanes agree to the bit in matching
    precision."""
    if code not in RAW_CODES:
        raise ValueError(f"unknown raw sample code {code!r}; "
                         f"known: {RAW_CODES}")
    x = raw.astype(ft)
    if code == "i8":
        # stored unsigned, TZERO = -128: exact for all 0..255 values
        x = x - jnp.asarray(128.0, ft)
    if tscal is not None:
        # general column scaling, the host's apply_column_scaling
        # order: stored*TSCAL + TZERO happens BEFORE DAT_SCL/DAT_OFFS
        x = x * _bcast_row(tscal.astype(ft), x) \
            + _bcast_row(tzero.astype(ft), x)
    return x * scl[..., None] + offs[..., None]


def decode_stokes_I(raw, scl, offs, ft, code="i16", pol_sum=False,
                    nbin=None, tscal=None, tzero=None):
    """Full decode stage of the fused bucket program: sub-byte
    bit-plane unpack (packed codes), affine sample decode, min-window
    baseline subtraction, and the polarization reduction to Stokes I.

    pol_sum=False: raw is (nb, nchan, nbin) — a single-pol payload
    (Intensity data, or the host-sliced Stokes I plane of an IQUV
    archive, which ships no extra bytes) — or, for packed codes,
    (nb, plane_bytes) packed bytes.  pol_sum=True: raw is
    (nb, 2, nchan, nbin) — the two summand pols of an AA+BB/Coherence
    archive ((nb, 2, plane_bytes) packed), decoded and baselined PER
    POL then summed, matching the host lane's
    remove_baseline-then-pscrunch order bit-for-bit.  ``nbin`` is
    required for packed codes (the unpack target geometry; nchan
    comes from scl)."""
    nbit = PACKED_BITS.get(code)
    if nbit is not None:
        if nbin is None:
            raise ValueError(
                f"decode_stokes_I: packed code {code!r} needs nbin "
                "for the unpack geometry")
        nchan = scl.shape[-1]
        raw = unpack_bitplanes(raw, nbit, nchan * nbin)
        raw = raw.reshape(raw.shape[:-1] + (nchan, nbin))
    x = affine_decode(raw, scl, offs, ft, code=code, tscal=tscal,
                      tzero=tzero)
    x = x - min_window_baseline(x)[..., None]
    if pol_sum:
        if x.ndim < 4:
            raise ValueError(
                f"pol_sum needs a (nb, 2, nchan, nbin) payload; got "
                f"shape {x.shape}")
        x = x[..., 0, :, :] + x[..., 1, :, :]
    return x
