"""Batched RFI excision: the iterative median + nstd noise cut as ONE
device program (ISSUE 12 tentpole, layer 1).

The reference's median algorithm (ppzap.py:24-54) loops on the host:
every iteration pulls (median, std) of the surviving channels, flags
outliers, and repeats — per subint.  Round 14 moved the median onto the
device but kept the loop on host, so the device lane still paid one
host round-trip PER ITERATION per subint.  This module batches the
WHOLE cut — every subint of an archive (or every row of a fused
bucket) iterating together inside one ``lax.while_loop`` — so the
device lane costs one dispatch total, and the same traceable core
(:func:`zap_keep_mask`) fuses directly into the streaming raw-bucket
program, where the noise levels are computed on device and never visit
the host at all.

Exactness contract (what "digit oracle" means here):

- the masked MEDIAN — the sort-shaped statistic that centers the cut —
  is bit-identical to ``np.median`` of the compressed survivor set
  (:func:`masked_median_lastaxis`: an order-statistic bisection on the
  order-preserving u32/u64 integer image of the floats, the
  mask-and-count generalization of ``ops/noise.exact_median_lastaxis``);
- the masked STD is the two-pass formula in the input dtype.  Its sums
  reduce in XLA order, not NumPy's pairwise order, so it can differ
  from ``np.std`` of the survivor set by ~1 ulp of accumulation
  (~1e-16 relative in f64).  A flagged-channel list can therefore only
  diverge from the host oracle (:func:`zap_keep_np`) if a channel sits
  within that margin of ``median + nstd*std`` — a measure-zero
  borderline that the tests and ``benchmarks/bench_zap.py`` gate on
  LIST EQUALITY every run, so a divergence fails loudly instead of
  drifting silently.
"""

import numpy as np

__all__ = ["masked_median_lastaxis", "zap_keep_mask", "zap_keep_device",
           "zap_keep_np", "zap_lists_from_masks", "zap_bunch"]


def _order_bits(x):
    """Order-preserving float -> unsigned-int map (radix-sort trick):
    negatives complement, positives set the top bit; total order as
    unsigned ints matches the float order.  f32 -> u32, f64 -> u64."""
    import jax.numpy as jnp
    from jax import lax

    if x.dtype == jnp.float32:
        utype, top = jnp.uint32, jnp.uint32(0x80000000)
    elif x.dtype == jnp.float64:
        utype, top = jnp.uint64, jnp.uint64(0x8000000000000000)
    else:
        raise ValueError(f"masked median supports f32/f64, got {x.dtype}")
    u = lax.bitcast_convert_type(x, utype)
    return jnp.where(u & top != 0, ~u, u | top), utype, top


def _unorder_bits(m, dtype, top):
    import jax.numpy as jnp
    from jax import lax

    bits = jnp.where(m & top != 0, m ^ top, ~m)
    ftype = jnp.float32 if bits.dtype == jnp.uint32 else jnp.float64
    out = lax.bitcast_convert_type(bits, ftype)
    return out.astype(dtype)


def masked_median_lastaxis(x, keep):
    """Median over the kept entries of the last axis, bit-identical to
    ``np.median(x[row][keep[row]])`` per row (same order statistics,
    same (lo+hi)/2 mean) — traceable, sort-free.

    ``keep``: boolean mask, same shape as ``x``.  Rows with zero kept
    entries return an arbitrary finite value (callers mask those rows
    out).  Finite inputs assumed, like every consumer on the streaming
    path."""
    import jax.numpy as jnp
    from jax import lax

    m, utype, top = _order_bits(x)
    nbits = 32 if utype == jnp.uint32 else 64
    full = ~utype(0)
    m = jnp.where(keep, m, full)  # invalid entries sort last
    n = jnp.sum(keep, axis=-1)
    k_lo = jnp.maximum(n - 1, 0) // 2
    k_hi = n // 2

    def kth(k):
        """Smallest kept value v with count(kept <= v) >= k+1, by
        bisection on the integer key space — one compare+count pass
        per bit, no data-dependent gathers."""
        lo = jnp.zeros(x.shape[:-1], utype)
        hi = jnp.full(x.shape[:-1], full, utype)

        def body(_, st):
            lo, hi = st
            mid = lo + ((hi - lo) >> 1)
            cnt = jnp.sum((m <= mid[..., None]) & keep, axis=-1)
            go_hi = cnt <= k
            return (jnp.where(go_hi, mid + 1, lo),
                    jnp.where(go_hi, hi, mid))

        lo, hi = lax.fori_loop(0, nbits, body, (lo, hi))
        return lo

    v_lo = _unorder_bits(kth(k_lo), x.dtype, top)
    v_hi = _unorder_bits(kth(k_hi), x.dtype, top)
    return (v_lo + v_hi) / 2


def zap_keep_mask(noise, keep, nstd):
    """The iterative median + ``nstd``*std cut, batched and traceable
    (the core the fused raw-bucket program inlines): every row iterates
    inside ONE ``lax.while_loop`` until no row flags a new channel.

    noise: (..., nchan) per-channel noise levels; keep: same-shape
    boolean (or 0/1) survivor mask — channels already zero-weight
    enter False and are never counted.  Returns ``(keep_out, n_iter)``:
    the surviving mask (bool) and, per row, how many passes flagged at
    least one channel (0 = the row was clean).  Semantics match the
    host oracle :func:`zap_keep_np` row for row (see the module
    docstring for the exactness contract)."""
    import jax.numpy as jnp
    from jax import lax

    noise = jnp.asarray(noise)
    kb = jnp.asarray(keep) > 0
    nstd = noise.dtype.type(nstd)
    it0 = jnp.zeros(noise.shape[:-1], jnp.int32)

    def cond(st):
        return st[1]

    def body(st):
        kb, _, it = st
        n = jnp.sum(kb, axis=-1)
        nf = jnp.maximum(n, 1).astype(noise.dtype)
        med = masked_median_lastaxis(noise, kb)
        m1 = jnp.sum(jnp.where(kb, noise, 0), axis=-1) / nf
        var = jnp.sum(jnp.where(kb, (noise - m1[..., None]) ** 2, 0),
                      axis=-1) / nf
        std = jnp.sqrt(var)
        bad = kb & (noise > (med + nstd * std)[..., None])
        row_bad = jnp.any(bad, axis=-1)
        return (kb & ~bad, jnp.any(row_bad),
                it + row_bad.astype(jnp.int32))

    kb, _, it = lax.while_loop(cond, body, (kb, jnp.bool_(True), it0))
    return kb, it


def zap_keep_device(noise, keep, nstd):
    """One jitted dispatch of :func:`zap_keep_mask`; returns host
    ``(keep, n_iter)`` numpy arrays.  This is the device lane of
    ``pipeline/zap.get_zap_channels``: the whole iterative cut for
    every subint of an archive costs ONE dispatch — zero per-iteration
    host round-trips (the iterating happens inside the compiled
    while_loop)."""
    import jax

    fn = _zap_jit_cache.get(None)
    if fn is None:
        fn = _zap_jit_cache[None] = jax.jit(
            zap_keep_mask, static_argnames=("nstd",))
    kb, it = fn(noise, np.asarray(keep) > 0, float(nstd))
    return np.asarray(kb), np.asarray(it)


_zap_jit_cache = {}


def zap_keep_np(noise, keep, nstd):
    """Host oracle: the reference median algorithm (ppzap.py:24-54)
    vectorized over rows, exactly — per row: np.median / np.std of the
    survivor set, flag strictly-greater outliers, repeat until clean.
    Returns ``(keep, n_iter)`` like the device twin."""
    noise = np.asarray(noise)
    keep = np.array(np.asarray(keep) > 0)
    flat = keep.reshape(-1, keep.shape[-1])
    nflat = noise.reshape(-1, noise.shape[-1])
    n_iter = np.zeros(flat.shape[0], int)
    for i in range(flat.shape[0]):
        while True:
            idx = np.flatnonzero(flat[i])
            if idx.size == 0:
                break
            vals = nflat[i, idx]
            med, std = np.median(vals), np.std(vals)
            bad = idx[vals > med + nstd * std]
            if bad.size == 0:
                break
            flat[i, bad] = False
            n_iter[i] += 1
    return (flat.reshape(keep.shape),
            n_iter.reshape(keep.shape[:-1]))


def zap_lists_from_masks(keep0, keep):
    """Per-row sorted flagged-channel lists from before/after survivor
    masks — the ppzap list format ([row][channel indices])."""
    keep0 = np.asarray(keep0) > 0
    keep = np.asarray(keep) > 0
    return [sorted(int(c) for c in np.flatnonzero(k0 & ~k))
            for k0, k in zip(keep0, keep)]


def zap_bunch(d, zap_channels):
    """Apply a zap list to a LOADED archive bunch in memory — weight
    zeroing plus the derived ok-index recomputation — so downstream
    fits see exactly what loading a weight-zapped archive yields.

    This, not ``pipeline/zap.apply_zaps``, is the lossless offline-zap
    arm: the PSRFITS writer re-quantizes DATA from the decoded floats
    (write_archive_file recomputes scl/offs), so a physical
    zap-rewrite-reload round trip perturbs the data in its low bits,
    while load_data/_load_raw never fold weights into the data — they
    only derive masks and ok indices from them.  Zeroing the weights
    here and recomputing those deriveds is therefore bit-identical to
    having loaded an archive whose DAT_WTS were zeroed, which is what
    the inline lane's digit gates (and the serve refit loop) compare
    against.

    ``d``: a ``load_data`` bunch or a raw-mode ``_load_raw`` bunch;
    ``zap_channels``: [subint][channel indices], indexed by TRUE subint
    number (rows beyond ``d.nsub`` ignored).  Returns ``d`` (mutated).
    """
    w = np.asarray(d.weights)
    for isub, chans in enumerate(zap_channels):
        if isub >= w.shape[0] or not len(chans):
            continue
        w[isub, np.asarray(chans, int)] = 0.0
    d.weights = w
    weights_norm = np.where(w == 0.0, 0.0, 1.0)
    nsub, nchan = w.shape
    d.ok_isubs = np.compress(weights_norm.mean(axis=1),
                             np.arange(nsub)).astype(int)
    if "ok_ichans" in d:
        d.ok_ichans = [np.compress(weights_norm[isub],
                                   np.arange(nchan)).astype(int)
                       for isub in range(nsub)]
    if "masks" in d and not d.get("raw_mode", False):
        npol = int(d.get("npol", 1))
        nbin = int(d.nbin)
        d.masks = np.broadcast_to(weights_norm[:, None, :, None],
                                  (nsub, npol, nchan, nbin))
    return d
