"""Reusable per-backend autotune harness (ISSUE 19 tentpole, layer 2).

The round-12 ``PPT_RETUNE`` sweep and the round-9 pipeline-depth A/B
were one-off hand-run scripts: time the default, time each candidate,
eyeball the table, hard-code the winner.  This module generalizes
them into a harness any campaign (or bench, or CLI) can call:

- **Knob tiers.**  :data:`IDENTITY_TIER` holds ONLY knobs whose every
  value is documented output-identity-preserving (fused block size,
  bucket pad, pipeline depth, LM ``compact_every``, harmonic-window
  K) — and the harness does not trust the documentation: every
  candidate's artifact (.tim bytes / digest — whatever ``run_fn``
  returns) is gated byte-identical against the default before its
  timing is even considered.  :data:`NUMERICS_TIER` (dtype choices)
  is swept ONLY behind the explicit ``numerics=True`` opt-in and is
  exempt from the byte gate — changing digits is its point, and it
  must never happen silently.
- **Min-of-N timing** in the spirit of profiling.devtime: each
  candidate is timed ``nrun`` times and the minimum wall is compared;
  ``time_fn`` is injectable (the test stub pattern profiling's
  ``devtime_fn`` established) so tests sweep without a clock.
- **Per-knob independent sweep + combined no-regression gate**: each
  knob is swept against the default config alone; the combined
  winner set is then re-validated (bytes + wall) against the default
  and FALLS BACK to defaults if it regresses — ``tuned_s <=
  default_s`` holds by construction in every result this harness
  returns.
- **Persistence**: winners land in the JSON tuning DB
  (tune/store.TuningStore) keyed (backend fingerprint, shape class);
  :func:`ensure_tuned` on a warm DB applies the stored knobs and
  pays ZERO re-sweeps — the trace witnesses it as a ``tune_apply``
  event with ``db_hit=true`` and no ``tune_sweep`` events.
"""

import contextlib
import time
from typing import NamedTuple

from ..telemetry import NULL_TRACER
from .capability import capability_record
from .store import TuningStore

__all__ = ["Knob", "IDENTITY_TIER", "NUMERICS_TIER", "SweepResult",
           "tuned_config", "shape_class_for", "sweep", "ensure_tuned",
           "apply_knobs", "apply_from_db"]


class Knob(NamedTuple):
    """One sweepable knob: ``name`` is both the config.py attribute
    and the tuning-DB key; ``candidates`` are the values to try
    beyond whatever the current config default is (the default is
    always in the comparison set — that is what makes the
    no-regression gate deterministic)."""

    name: str
    candidates: tuple


# Output-identity-preserving tier: every candidate value of every knob
# here is documented byte-identical (and the sweep enforces it anyway).
IDENTITY_TIER = (
    Knob("fused_block", (None, 8, 16, 32)),
    Knob("bucket_pad", (False, True)),
    Knob("stream_pipeline_depth", (1, 2, 4)),
    Knob("lm_compact_every", (None, 8, 16, 32)),
    Knob("fit_harmonic_window", ("auto", None)),
)

# Numerics tier: value choices that CHANGE DIGITS.  Only swept behind
# the explicit numerics=True / config.tune_numerics opt-in; winners
# are recorded with identity_preserving=False in the DB meta.
NUMERICS_TIER = (
    Knob("cross_spectrum_dtype", ("bfloat16", None)),
    Knob("dft_precision", ("highest", "default")),
)


class SweepResult(NamedTuple):
    knobs: dict        # accepted winners (attr -> value); {} = defaults
    default_s: float   # min-of-N wall of the default config
    tuned_s: float     # min-of-N wall of the accepted set (<= default_s)
    n_swept: int       # candidates actually timed
    n_rejected: int    # candidates refused by the identity gate


@contextlib.contextmanager
def tuned_config(overrides):
    """Apply ``overrides`` (config attr -> value) for the duration of
    the block and restore the previous values after — the sweep's
    candidate-isolation primitive (also what tests use to fake a
    tuned process)."""
    from .. import config

    saved = {k: getattr(config, k) for k in overrides}
    try:
        for k, v in overrides.items():
            setattr(config, k, v)
        yield
    finally:
        for k, v in saved.items():
            setattr(config, k, v)


def apply_knobs(knobs):
    """Set accepted winners on config (persistently for this process
    — the campaign-startup path, unlike the scoped tuned_config)."""
    from .. import config

    for k, v in knobs.items():
        setattr(config, k, v)


def shape_class_for(nchan, nbin):
    """Canonical tuning-DB shape-class key for a bucket layout."""
    return f"{int(nchan)}x{int(nbin)}"


def _default_time_fn(run_fn, nrun):
    def time_fn(overrides):
        best = None
        for _ in range(max(1, int(nrun))):
            t0 = time.perf_counter()
            run_fn(overrides)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best
    return time_fn


def sweep(run_fn, knobs=None, time_fn=None, nrun=3, numerics=False,
          tracer=NULL_TRACER, shape_class="default"):
    """One full sweep against the CURRENT config defaults.

    ``run_fn(overrides)`` executes the representative workload under
    the candidate overrides and returns its identity artifact (.tim
    bytes or any stable digest).  ``time_fn(overrides)`` returns the
    candidate's wall seconds (default: min-of-``nrun`` walls of
    ``run_fn`` itself).  Each knob sweeps independently; identity-tier
    candidates whose artifact differs from the default's are REJECTED
    before timing; the combined winner set is re-validated and falls
    back to defaults on any regression.  Emits one ``tune_sweep``
    event per knob."""
    from .. import config

    if knobs is None:
        knobs = IDENTITY_TIER + (NUMERICS_TIER if numerics else ())
    if time_fn is None:
        time_fn = _default_time_fn(run_fn, nrun)
    identity_names = {k.name for k in IDENTITY_TIER}
    baseline = run_fn({})
    default_s = float(time_fn({}))
    winners = {}
    n_swept = n_rejected = 0
    for knob in knobs:
        default_val = getattr(config, knob.name)
        best_val, best_s = default_val, default_s
        rejected = []
        for cand in knob.candidates:
            if cand == default_val:
                continue
            ov = {knob.name: cand}
            gate = knob.name in identity_names or not numerics
            if gate and run_fn(ov) != baseline:
                # identity gate: a knob value that changes bytes is
                # out of the running no matter how fast it measures
                rejected.append(cand)
                n_rejected += 1
                continue
            t = float(time_fn(ov))
            n_swept += 1
            if t < best_s:
                best_val, best_s = cand, t
        if tracer.enabled:
            tracer.emit(
                "tune_sweep", shape_class=str(shape_class),
                knob=knob.name, default=repr(default_val),
                winner=repr(best_val),
                n_candidates=len(knob.candidates),
                n_rejected=len(rejected),
                default_s=round(default_s, 6), best_s=round(best_s, 6))
        if best_val != default_val:
            winners[knob.name] = best_val
    tuned_s = default_s
    if winners:
        with tuned_config(winners):
            combined_ok = run_fn({}) == baseline
            t_comb = float(time_fn({})) if combined_ok else None
        if not combined_ok or t_comb > default_s:
            # no-regression gate: the combination must beat what it
            # replaced, byte-for-byte and on the clock, or we ship
            # the defaults — a tuned campaign is never slower
            winners = {}
        else:
            tuned_s = t_comb
    return SweepResult(knobs=winners, default_s=default_s,
                       tuned_s=tuned_s, n_swept=n_swept,
                       n_rejected=n_rejected)


def ensure_tuned(run_fn, shape_class, db_path=None, knobs=None,
                 time_fn=None, nrun=3, numerics=None,
                 tracer=NULL_TRACER, apply=True):
    """The campaign entry point: return (and by default apply) the
    winning knobs for this backend + shape class, sweeping ONLY when
    the tuning DB has no entry.

    ``db_path`` None falls back to ``config.tune_db``; with no DB path
    at all the sweep runs unpersisted.  ``numerics`` None follows
    ``config.tune_numerics``.  Emits ``tune_probe`` (the capability
    record) and ``tune_apply`` (with the DB-hit witness) either way."""
    from .. import config

    if db_path is None:
        db_path = getattr(config, "tune_db", None)
    if numerics is None:
        numerics = bool(getattr(config, "tune_numerics", False))
    if tracer.enabled:
        rec = capability_record()
        tracer.emit("tune_probe", backend=rec.platform,
                    device_kind=rec.device_kind,
                    fingerprint=rec.fingerprint,
                    dispatch_floor_s=rec.dispatch_floor_s,
                    matmul_gflops=rec.matmul_gflops,
                    dft_gflops=rec.dft_gflops)
    store = TuningStore(db_path) if db_path else None
    ent = store.get(shape_class) if store else None
    if ent is not None:
        winners = dict(ent["knobs"])
        if tracer.enabled:
            tracer.emit("tune_apply", shape_class=str(shape_class),
                        db_hit=True, db_path=str(db_path),
                        knobs={k: repr(v) for k, v in winners.items()},
                        default_s=ent.get("default_s"),
                        tuned_s=ent.get("tuned_s"))
        if apply:
            apply_knobs(winners)
        return winners
    res = sweep(run_fn, knobs=knobs, time_fn=time_fn, nrun=nrun,
                numerics=numerics, tracer=tracer,
                shape_class=shape_class)
    if store is not None:
        store.put(shape_class, res.knobs,
                  default_s=res.default_s, tuned_s=res.tuned_s,
                  n_swept=res.n_swept,
                  identity_preserving=not numerics)
    if tracer.enabled:
        tracer.emit("tune_apply", shape_class=str(shape_class),
                    db_hit=False,
                    db_path=str(db_path) if db_path else None,
                    knobs={k: repr(v) for k, v in res.knobs.items()},
                    default_s=round(res.default_s, 6),
                    tuned_s=round(res.tuned_s, 6))
    if apply:
        apply_knobs(res.knobs)
    return res.knobs


def apply_from_db(shape_class=None, db_path=None, tracer=NULL_TRACER):
    """Apply persisted winners WITHOUT the ability to sweep (the CLI
    cold path, e.g. ``ppserve --tune-db``): load the DB, pick
    ``shape_class`` (or the sole stored class when None), apply, and
    witness the hit.  Returns the applied knobs ({} when the DB has
    nothing for this backend — loudly warned by the store)."""
    from .. import config

    if db_path is None:
        db_path = getattr(config, "tune_db", None)
    if not db_path:
        return {}
    store = TuningStore(db_path)
    classes = store.shape_classes()
    if shape_class is None:
        if len(classes) != 1:
            return {}
        shape_class = classes[0]
    ent = store.get(shape_class)
    if ent is None:
        return {}
    winners = dict(ent["knobs"])
    if tracer.enabled:
        tracer.emit("tune_apply", shape_class=str(shape_class),
                    db_hit=True, db_path=str(db_path),
                    knobs={k: repr(v) for k, v in winners.items()},
                    default_s=ent.get("default_s"),
                    tuned_s=ent.get("tuned_s"))
    apply_knobs(winners)
    return winners
