from .mjd import MJD
from .bunch import DataBunch

__all__ = ["MJD", "DataBunch"]
