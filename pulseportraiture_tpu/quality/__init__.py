"""Closed-loop data quality (ISSUE 12): inline on-device RFI excision
for the streaming lanes, the model-based post-fit channel cut, and the
helpers behind the serving loop's quality-gated zap-and-refit.

- :mod:`.excision` — the iterative median + nstd noise cut, batched
  into one device program (fused into the raw streaming bucket; one
  dispatch per archive offline), with the host NumPy oracle and the
  in-memory weight-zap (:func:`zap_bunch`) the refit loop and the
  offline ``zap_channels=`` lane apply.
- :mod:`.postfit` — the reference red-chi^2 / S-N channel cut as a
  batched device pass over an archive's quality arrays (bit-exact
  host/device), behind ``GetTOAs.get_channels_to_zap``.
"""

from .excision import (masked_median_lastaxis, zap_bunch,  # noqa: F401
                       zap_keep_device, zap_keep_mask, zap_keep_np,
                       zap_lists_from_masks)
from .postfit import (postfit_cut_device, postfit_cut_mask,  # noqa: F401
                      postfit_cut_np)

__all__ = ["masked_median_lastaxis", "zap_bunch", "zap_keep_device",
           "zap_keep_mask", "zap_keep_np", "zap_lists_from_masks",
           "postfit_cut_device", "postfit_cut_mask", "postfit_cut_np"]
