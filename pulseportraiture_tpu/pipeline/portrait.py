"""DataPortrait: one (t-scrunched) data portrait + metadata, the base
object the template builders (gauss/spline) and interactive use share.

Parity target: reference pplib.DataPortrait (pplib.py:155-670),
including the metafile JOIN path that concatenates archives from
different receivers into one frequency-sorted portrait with per-join
(phase, dDM) alignment parameters (pplib.py:163-349).

TPU-first notes: the portrait is small host state (model building is
offline); heavy math (phase fits, rotations, wavelet smoothing) calls
into the jitted ops/fit kernels.
"""

import numpy as np

from ..fit.phase_shift import fit_phase_shift
from ..fit.powlaw import fit_powlaw
from ..io.psrfits import load_data, noise_std_ps, unload_new_archive
from ..ops.rotation import rotate_portrait
from ..utils.device import on_host
from .toas import _is_metafile, _read_metafile


@on_host
def normalize_portrait(port, method="rms", weights=None,
                       return_norms=False):
    """Normalize each channel profile (reference pplib.py:2553-2598):
    'mean' | 'max' | 'prof' (scale vs the weighted mean profile via a
    phase-shift fit) | 'rms' (unit noise) | 'abs' (unit L2 norm)."""
    port = np.asarray(port, float)
    if method not in ("mean", "max", "prof", "rms", "abs"):
        raise ValueError(f"unknown normalization method {method!r}")
    norm_port = np.zeros_like(port)
    norm_vals = np.ones(len(port))
    if method == "prof":
        good = np.where(port.sum(axis=1) != 0.0)[0]
        w = np.ones(len(good)) if weights is None \
            else np.asarray(weights)[good]
        mean_prof = np.average(port[good], axis=0, weights=w)
    for ichan in range(len(port)):
        if not port[ichan].any():
            continue
        if method == "mean":
            norm = port[ichan].mean()
        elif method == "max":
            norm = port[ichan].max()
        elif method == "prof":
            norm = float(fit_phase_shift(port[ichan], mean_prof).scale)
        elif method == "rms":
            norm = float(noise_std_ps(port[ichan]))
        else:
            norm = float(np.sqrt((port[ichan] ** 2).sum()))
        if norm != 0.0:
            norm_port[ichan] = port[ichan] / norm
            norm_vals[ichan] = norm
    return (norm_port, norm_vals) if return_norms else norm_port


class DataPortrait:
    """Load one archive — or a metafile of archives from different
    receivers (JOIN path) — into a t/p-scrunched portrait ready for
    template building."""

    @on_host
    def __init__(self, datafile=None, joinfile=None, quiet=False,
                 **load_data_kwargs):
        self.datafile = datafile
        self.joinfile = joinfile
        self.norm_values = None
        self.joins = []
        load_data_kwargs.setdefault("tscrunch", True)
        load_data_kwargs.setdefault("pscrunch", True)
        load_data_kwargs.setdefault("dedisperse", True)
        if isinstance(datafile, str) and _is_metafile(datafile):
            self._init_join(datafile, quiet, load_data_kwargs)
        else:
            self._init_single(datafile, quiet, load_data_kwargs)
        if joinfile:
            self.apply_joinfile(joinfile, quiet=quiet)

    # -- construction ------------------------------------------------------
    def _unpack(self, d):
        self.data = d
        self.source = d.source
        self.nbin = d.nbin
        self.phases = d.phases
        self.nu0 = d.nu0
        self.bw = d.bw
        self.Ps = np.atleast_1d(np.asarray(d.Ps))
        self.freqs = np.atleast_2d(np.asarray(d.freqs))
        self.port = np.asarray(d.subints[0, 0], float)
        self.weights = np.asarray(d.weights[0], float)
        self.noise_stds = np.asarray(d.noise_stds[0, 0], float)
        self.SNRs = np.asarray(d.SNRs[0, 0], float)
        self.ok_ichans = np.asarray(d.ok_ichans[0], int)
        self._condense()

    def _condense(self):
        """x-suffixed views keep only unzapped channels (reference
        convention); masks keep the full arrays static elsewhere."""
        okc = self.ok_ichans
        self.portx = self.port[okc]
        self.freqsxs = [self.freqs[0][okc]]
        self.noise_stdsxs = [self.noise_stds[okc]]
        self.SNRsxs = [self.SNRs[okc]]

    def _init_single(self, datafile, quiet, kwargs):
        d = load_data(datafile, quiet=quiet, **kwargs)
        self._unpack(d)

    def _init_join(self, metafile, quiet, kwargs):
        """Concatenate archives across receivers, sorted by frequency;
        per-archive (phase, dDM) JOIN parameters seeded by mean-profile
        phase fits against the first archive (pplib.py:163-315)."""
        paths = _read_metafile(metafile)
        datas = [load_data(p, quiet=quiet, **kwargs) for p in paths]
        nbin = datas[0].nbin
        for d in datas[1:]:
            if d.nbin != nbin:
                raise ValueError("JOIN archives must share nbin")
        ports = [np.asarray(d.subints[0, 0], float) for d in datas]
        freqs = np.concatenate([np.asarray(d.freqs[0]) for d in datas])
        order = np.argsort(freqs)
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        # bookkeeping: channel indices of each archive in sorted port
        self.join_params = []
        self.join_fit_flags = []
        self.join_ichans = []
        start = 0
        ref_prof = ports[0].mean(axis=0)
        for iarch, d in enumerate(datas):
            n = ports[iarch].shape[0]
            self.join_ichans.append(np.sort(inv[start:start + n]))
            start += n
            if iarch == 0:
                phase_guess = 0.0
            else:
                r = fit_phase_shift(ports[iarch].mean(axis=0), ref_prof)
                phase_guess = float(r.phase)
            # (phase, dDM) per join; first archive is the fixed anchor
            self.join_params.extend([phase_guess, 0.0])
            self.join_fit_flags.extend(
                [0, 0] if iarch == 0 else [1, 1])
            self.joins.append(paths[iarch])
        d0 = datas[0]
        port = np.concatenate(ports, axis=0)[order]
        self.data = d0
        self.source = d0.source
        self.nbin = nbin
        self.phases = d0.phases
        self.Ps = np.atleast_1d(np.asarray(d0.Ps))
        all_freqs = freqs[order]
        self.freqs = all_freqs[None, :]
        self.nu0 = float(all_freqs.mean())
        self.bw = float(all_freqs.max() - all_freqs.min())
        self.port = port
        self.weights = np.concatenate(
            [np.asarray(d.weights[0]) for d in datas])[order]
        self.noise_stds = np.concatenate(
            [np.asarray(d.noise_stds[0, 0]) for d in datas])[order]
        self.SNRs = np.concatenate(
            [np.asarray(d.SNRs[0, 0]) for d in datas])[order]
        self.ok_ichans = np.where(self.weights > 0)[0]
        self._condense()

    # -- transforms --------------------------------------------------------
    def normalize_portrait(self, method="rms"):
        """In-place channel normalization; remembers the values so
        unnormalize_portrait can restore (pplib.py:379-420)."""
        self.port, norms = normalize_portrait(
            self.port, method, weights=self.weights, return_norms=True)
        self.norm_values = norms
        self.norm_method = method
        self.noise_stds = np.where(norms != 0.0,
                                   self.noise_stds / norms,
                                   self.noise_stds)
        self._condense()
        return norms

    def unnormalize_portrait(self):
        if self.norm_values is None:
            raise RuntimeError("portrait was not normalized")
        self.port = self.port * self.norm_values[:, None]
        self.noise_stds = self.noise_stds * self.norm_values
        self.norm_values = None
        self._condense()

    @on_host
    def smooth_portrait(self, **kwargs):
        """Wavelet-denoise every channel profile (pplib.py:422-446)."""
        from ..models.wavelet import wavelet_smooth

        self.port = np.asarray(wavelet_smooth(self.port, **kwargs))
        self._condense()

    @on_host
    def fit_flux_profile(self, guessA=1.0, guessalpha=0.0, plot=False,
                         savefig=None, quiet=True):
        """Power-law fit to the phase-averaged flux vs frequency
        (pplib.py:448-506)."""
        okc = self.ok_ichans
        fluxes = self.port[okc].mean(axis=1)
        flux_errs = self.noise_stds[okc] / np.sqrt(self.nbin)
        flux_errs = np.where(flux_errs > 0, flux_errs, 1.0)
        freqs = self.freqs[0][okc]
        res = fit_powlaw(fluxes, init_params=[guessA, guessalpha],
                         errs=flux_errs, nu_ref=self.nu0, freqs=freqs)
        self.flux_fit = res
        if plot:
            from ..viz.plots import plot_flux_profile

            plot_flux_profile(freqs, fluxes, flux_errs, res, self.nu0,
                              savefig=savefig)
        if not quiet:
            print(f"flux spectral index alpha = {float(res.alpha):.3f} "
                  f"+/- {float(res.alpha_err):.3f}")
        return res

    @on_host
    def rotate_stuff(self, phase=0.0, DM=0.0, ichans=None, nu_ref=None,
                     model=False):
        """Coherently rotate the data (or model) portrait and any
        derived products (pplib.py:545-592)."""
        P = float(self.Ps[0])
        if nu_ref is None:
            nu_ref = self.nu0
        if ichans is None:
            ichans = np.arange(self.port.shape[0])
        ichans = np.asarray(ichans, int)
        freqs = self.freqs[0][ichans]
        if not model:
            self.port[ichans] = np.asarray(rotate_portrait(
                self.port[ichans], phase, DM, P, freqs, nu_ref))
            for attr in ("prof", "mean_prof"):
                if hasattr(self, attr):
                    setattr(self, attr, np.asarray(rotate_portrait(
                        getattr(self, attr)[None], phase))[0])
            if hasattr(self, "eigvec"):
                self.eigvec = np.asarray(rotate_portrait(
                    self.eigvec.T, phase)).T
            self._condense()
        elif hasattr(self, "model"):
            self.model[ichans] = np.asarray(rotate_portrait(
                self.model[ichans], phase, DM, P, freqs, nu_ref))
            if hasattr(self, "modelx"):
                self.modelx = self.model[self.ok_ichans]
            if hasattr(self, "smooth_mean_prof"):
                self.smooth_mean_prof = np.asarray(rotate_portrait(
                    self.smooth_mean_prof[None], phase))[0]
            if hasattr(self, "smooth_eigvec"):
                self.smooth_eigvec = np.asarray(rotate_portrait(
                    self.smooth_eigvec.T, phase)).T

    # -- JOIN persistence --------------------------------------------------
    def write_join_parameters(self, outfile, quiet=False):
        """Persist JOIN (phase, dDM) pairs (pplib.py:508-543)."""
        with open(outfile, "w") as f:
            for iarch, path in enumerate(self.joins):
                phi, dDM = self.join_params[2 * iarch: 2 * iarch + 2]
                f.write(f"{path} {phi:+.8f} {dDM:+.8f}\n")
        if not quiet:
            print(f"{outfile} written.")

    def apply_joinfile(self, joinfile, quiet=False):
        """Rotate each join's channels by persisted (phase, dDM)
        (pplib.py:351-377)."""
        with open(joinfile) as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                path, phi, dDM = parts[0], float(parts[1]), float(parts[2])
                if path in self.joins:
                    iarch = self.joins.index(path)
                    self.rotate_stuff(phase=phi, DM=dDM,
                                      ichans=self.join_ichans[iarch])
                    self.join_params[2 * iarch] = phi
                    self.join_params[2 * iarch + 1] = dDM
        if not quiet:
            print(f"Applied {joinfile}.")

    # -- output ------------------------------------------------------------
    def unload_archive(self, outfile, quiet=False):
        """Write the (possibly transformed) portrait back to a PSRFITS
        file via the archive cloning path (pplib.py:594-616)."""
        arch = self.data.arch
        if arch is None:
            from ..io.psrfits import read_archive

            arch = read_archive(self.datafile)
        unload_new_archive(self.port[None, None], arch, outfile,
                           DM=self.data.DM, dmc=1,
                           weights=self.weights[None], quiet=quiet)

    def write_model_archive(self, outfile, quiet=False):
        """Write the model portrait as an archive (pplib.py:618-636)."""
        if not hasattr(self, "model"):
            raise RuntimeError("no model built yet")
        arch = self.data.arch
        if arch is None:
            from ..io.psrfits import read_archive

            arch = read_archive(self.datafile)
        unload_new_archive(np.asarray(self.model)[None, None], arch,
                           outfile, DM=0.0, dmc=1,
                           weights=np.ones_like(self.weights)[None],
                           quiet=quiet)

    # -- plotting ----------------------------------------------------------
    def show_data_portrait(self, **kwargs):
        from ..viz.plots import show_portrait

        show_portrait(self.port * (self.weights > 0)[:, None],
                      self.phases, self.freqs[0], **kwargs)

    def show_model_portrait(self, **kwargs):
        from ..viz.plots import show_portrait

        show_portrait(np.asarray(self.model), self.phases, self.freqs[0],
                      **kwargs)

    def show_model_fit(self, **kwargs):
        from ..viz.plots import show_residual_plot

        show_residual_plot(self.port, np.asarray(self.model),
                           self.phases, self.freqs[0],
                           noise_stds=self.noise_stds,
                           weights=self.weights, **kwargs)
