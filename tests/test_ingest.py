"""Online observatory pipeline (ISSUE 18): watch-folder admission,
truncation-safe ingest, streamed-vs-offline byte identity, anomaly
ground truth, and the new env knobs."""

import io as _io
import os
import shutil
import time

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.cli import ppwatch
from pulseportraiture_tpu.ingest import (AlertMonitor, CusumDetector,
                                         IngestDriver, SocketSource,
                                         WatchFolderSource, announce)
from pulseportraiture_tpu.io import TruncatedFits, scan_fits, write_gmodel
from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.synth.fake import fake_timing_campaign
from pulseportraiture_tpu.timing import IncrementalGLS
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55150.0, "DM": 3.139}
FPAR = {"PSR": "FAKE", "F0": "218.8", "PEPOCH": "55500", "DM": "15.9"}


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Watch-folder corpus: 3 spin-coherent archives (a common
    achromatic offset, NOT per-archive phase jumps — the clean corpus
    must not look like a glitching pulsar), a template, and a parfile
    for the incremental lane."""
    root = tmp_path_factory.mktemp("ingest")
    folder = root / "in"
    folder.mkdir()
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(3):
        path = str(folder / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=16,
                         nbin=128, nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.017, dDM=2e-4 * (i - 1),
                         start_MJD=MJD(55100 + 30 * i, 0.2),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=100 + i, spin_coherent=True)
        files.append(path)
    parfile = str(root / "pulsar.par")
    with open(parfile, "w") as fh:
        for k, v in PAR.items():
            fh.write(f"{k} {v}\n")
    return str(folder), files, gmodel, parfile


# -- satellite: the new env knobs ---------------------------------------


def test_ingest_env_hooks(monkeypatch):
    """The five ISSUE-18 knobs: registered, strict parses, loud
    refusals."""
    names = ("PPT_INGEST_POLL_MS", "PPT_INGEST_STABLE_MS",
             "PPT_ALERT_CUSUM_K", "PPT_ALERT_CUSUM_H",
             "PPT_GLS_RESOLVE_EVERY")
    for name in names:
        assert name in config.KNOWN_PPT_ENV
    old = (config.ingest_poll_ms, config.ingest_stable_ms,
           config.alert_cusum_k, config.alert_cusum_h,
           config.gls_resolve_every)
    try:
        monkeypatch.setenv("PPT_INGEST_POLL_MS", "75.5")
        monkeypatch.setenv("PPT_INGEST_STABLE_MS", "0")
        monkeypatch.setenv("PPT_ALERT_CUSUM_K", "0.75")
        monkeypatch.setenv("PPT_ALERT_CUSUM_H", "6.5")
        monkeypatch.setenv("PPT_GLS_RESOLVE_EVERY", "17")
        changed = config.env_overrides()
        for attr in ("ingest_poll_ms", "ingest_stable_ms",
                     "alert_cusum_k", "alert_cusum_h",
                     "gls_resolve_every"):
            assert attr in changed
        assert config.ingest_poll_ms == 75.5
        assert config.ingest_stable_ms == 0.0
        assert config.alert_cusum_k == 0.75
        assert config.alert_cusum_h == 6.5
        assert config.gls_resolve_every == 17
        for name, bad in (("PPT_INGEST_POLL_MS", "0"),
                          ("PPT_INGEST_STABLE_MS", "-1"),
                          ("PPT_ALERT_CUSUM_K", "-0.1"),
                          ("PPT_ALERT_CUSUM_H", "0"),
                          ("PPT_GLS_RESOLVE_EVERY", "1.5")):
            monkeypatch.setenv(name, bad)
            with pytest.raises(ValueError, match=name):
                config.env_overrides()
            monkeypatch.delenv(name)
    finally:
        (config.ingest_poll_ms, config.ingest_stable_ms,
         config.alert_cusum_k, config.alert_cusum_h,
         config.gls_resolve_every) = old


# -- watch-folder admission ---------------------------------------------


def test_watch_folder_stability_and_sentinel(tmp_path):
    """A still-warm file is NOT admitted until its (size, mtime) holds
    for stable_ms; the .done sentinel bypasses the wait; defer()
    restarts the clock but keeps the discovery time."""
    f = tmp_path / "a.fits"
    f.write_bytes(b"x" * 100)
    src = WatchFolderSource(str(tmp_path), stable_ms=10_000)
    assert src.poll() == []           # discovery pass: not stable yet
    assert src.pending() == [str(f)]
    # a growing file restarts the stability clock
    f.write_bytes(b"x" * 200)
    assert src.poll() == []
    # the explicit sentinel bypasses the wait entirely
    (tmp_path / "a.fits.done").touch()
    out = src.poll()
    assert [p for p, _ in out] == [str(f)]
    assert out[0][1] >= 0.0           # wait_s: discovery -> admission
    assert src.poll() == []           # admitted once
    # defer: back on the watch list, sentinel re-admits immediately
    src.defer(str(f))
    assert src.pending() == [str(f)]
    assert [p for p, _ in src.poll()] == [str(f)]
    # sentinels themselves are never candidates
    src2 = WatchFolderSource(str(tmp_path), stable_ms=0)
    time.sleep(0.01)
    assert [p for p, _ in src2.poll()] == [str(f)]


def test_socket_source_announce_roundtrip(tmp_path):
    """Push-style ingest: announce() delivers paths over the serve
    framing; defer re-queues; unknown ops refuse loudly."""
    with SocketSource() as src:
        ep = f"{src.endpoint[0]}:{src.endpoint[1]}"
        assert announce(ep, ["/data/a.fits", "/data/b.fits"]) == 2
        got = src.poll()
        assert [p for p, _ in got] == ["/data/a.fits", "/data/b.fits"]
        assert all(w >= 0 for _, w in got)
        src.defer("/data/a.fits")
        assert src.pending() == ["/data/a.fits"]
        assert [p for p, _ in src.poll()] == ["/data/a.fits"]
        import socket as _socket

        from pulseportraiture_tpu.serve.transport import (
            _recv_frame, _send_frame)
        with _socket.create_connection(src.endpoint) as s:
            _send_frame(s, {"op": "nope"})
            reply = _recv_frame(s)
        assert not reply["ok"] and "unknown op" in reply["error"]


# -- truncation safety --------------------------------------------------


def test_scan_fits_truncated_two_chunks(corpus, tmp_path):
    """The regression the typed error exists for: a PSRFITS written in
    two chunks is TruncatedFits (retryable) after the first chunk and
    clean after the second."""
    _folder, files, _gmodel, _par = corpus
    whole = open(files[0], "rb").read()
    part = tmp_path / "partial.fits"
    part.write_bytes(whole[:len(whole) // 2])
    with pytest.raises(TruncatedFits) as ei:
        scan_fits(str(part))
    assert ei.value.retryable
    assert isinstance(ei.value, ValueError)  # still a loud bad-input
    # the loaders hit the same typed error, not a cryptic shape crash
    from pulseportraiture_tpu.io import read_archive

    with pytest.raises(TruncatedFits):
        read_archive(str(part))
    with open(part, "ab") as fh:
        fh.write(whole[len(whole) // 2:])
    assert scan_fits(str(part)) >= 2  # header + subint HDUs


def test_driver_defers_truncated_then_admits(corpus, tmp_path):
    """End-to-end retry-on-stable: the driver defers a half-written
    archive (ingest_skip reason='truncated'), then admits and times it
    once the second chunk lands."""

    class FakeRequest:
        def __init__(self):
            class R:
                TOA_list = []
            self._r = R()

        def wait(self, timeout=None):
            return True

        def result(self, timeout=None):
            return self._r

    class FakeServer:
        def __init__(self):
            self.submitted = []

        def submit(self, datafiles, modelfile, **kw):
            self.submitted.extend(datafiles)
            return FakeRequest()

    _folder, files, gmodel, _par = corpus
    whole = open(files[0], "rb").read()
    part = tmp_path / "in"
    part.mkdir()
    dest = part / "x.fits"
    dest.write_bytes(whole[:len(whole) // 2])
    trace = str(tmp_path / "trace.jsonl")
    tracer = telemetry.Tracer(trace, run="ingest-retry")
    src = WatchFolderSource(str(part), stable_ms=0)
    server = FakeServer()
    drv = IngestDriver(server, gmodel, [src],
                       tim_out=str(tmp_path / "out.tim"),
                       tracer=tracer, quiet=True)
    drv.run_once()            # discovery pass registers the file
    time.sleep(0.01)
    assert drv.run_once() == 0  # stable but HALF-WRITTEN: deferred
    assert drv.stats()["deferred"] == 1 and not server.submitted
    with open(dest, "ab") as fh:
        fh.write(whole[len(whole) // 2:])
    drv.run_once()            # growth re-registers (stability clock)
    time.sleep(0.01)
    assert drv.run_once() == 1
    assert drv.drain(10)
    assert server.submitted == [str(dest)]
    tracer.close()
    _m, events = telemetry.validate_trace(trace)
    skips = [e for e in events if e["type"] == "ingest_skip"]
    admits = [e for e in events if e["type"] == "ingest_admit"]
    assert len(skips) == 1 and skips[0]["reason"] == "truncated"
    assert len(admits) == 1 and admits[0]["wait_s"] >= 0
    # the sentinel landed even for an empty fake result
    tim = open(tmp_path / "out.tim").read()
    assert f"C ppt-done {dest}" in tim


# -- the end-to-end acceptance corpus -----------------------------------


def test_ppwatch_drain_byte_identical_to_offline(corpus, tmp_path):
    """The tentpole's e2e gate: ppwatch --drain over a finished
    watch-folder corpus produces a streaming .tim BYTE-IDENTICAL to
    the offline one-shot over the same archives, zero alerts on the
    clean corpus, and a trace whose summary carries the new keys."""
    folder, files, gmodel, parfile = corpus
    for f in files:
        sentinel = f + ".done"
        if not os.path.exists(sentinel):
            open(sentinel, "w").close()
    tim = str(tmp_path / "streamed.tim")
    trace = str(tmp_path / "watch.jsonl")
    rc = ppwatch.main(["-w", folder, "-m", gmodel, "-t", tim,
                       "-p", parfile, "--drain", "--stable-ms", "0",
                       "--resolve-every", "2",
                       "--telemetry", trace, "--quiet"])
    assert rc == 0
    offline = str(tmp_path / "offline.tim")
    stream_wideband_TOAs(sorted(files), gmodel, nsub_batch=8,
                         tim_out=offline, quiet=True)
    assert open(tim, "rb").read() == open(offline, "rb").read()
    summary = telemetry.report(trace, file=_io.StringIO())
    assert summary["n_ingest_admit"] == 3
    assert summary["n_alert"] == 0
    assert summary["ingest_p99_s"] is not None
    assert summary["incremental_resolves"] >= 1


# -- anomaly ground truth (synthetic TOA-level corpora) -----------------


def _run_monitor(glitch=None, dm_step=None, rng=0, tracer=None):
    toas, truth = fake_timing_campaign(
        FPAR, n_epochs=12, toas_per_epoch=2, span_days=120.0,
        dmx=2e-4, rng=rng, glitch=glitch, dm_step=dm_step)
    known = []
    if glitch:
        known.append({"kind": "glitch", "mjd": truth.glitch["mjd"]})
    if dm_step:
        known.append({"kind": "dm_step", "mjd": truth.dm_step["mjd"]})
    inc = IncrementalGLS(FPAR, fit_binary=False, resolve_every=0)
    mon = AlertMonitor("FAKE", tracer=tracer,
                       known_events=known or None)
    result = None
    for toa in toas:
        result = inc.update(toa)
        mon.observe(result, toa)
    mon.finish()
    return mon.alerts, truth, result


def test_alert_clean_control_zero_false_alarms():
    alerts, _, _ = _run_monitor(rng=3)
    assert alerts == []


def test_alert_glitch_recovered_within_one_epoch(tmp_path):
    """A glitch (achromatic phase step) fires exactly one alert whose
    MJD matches the injected epoch to within one epoch spacing — and
    the alert telemetry event validates."""
    trace = str(tmp_path / "alerts.jsonl")
    tracer = telemetry.Tracer(trace, run="glitch")
    alerts, truth, _ = _run_monitor(
        glitch={"epoch": 9, "dphi": 218.8 * 50e-6}, rng=5,
        tracer=tracer)
    tracer.close()
    assert [a["kind"] for a in alerts] == ["glitch"]
    assert not alerts[0]["fp"]
    spacing = 120.0 / 11
    assert abs(alerts[0]["mjd"] - truth.glitch["mjd"]) <= spacing
    _m, events = telemetry.validate_trace(trace)
    evs = [e for e in events if e["type"] == "alert"]
    assert len(evs) == 1 and evs[0]["kind"] == "glitch"
    assert evs[0]["threshold"] == config.alert_cusum_h


def test_alert_dm_step_amplitude_within_3_sigma():
    """A DM step fires exactly one dm_step alert localized at the
    injected epoch whose amplitude recovers the injected ddm within 3
    sigma of the fitted epoch error."""
    ddm = 4e-3
    alerts, truth, result = _run_monitor(
        dm_step={"epoch": 6, "ddm": ddm}, rng=7)
    assert [a["kind"] for a in alerts] == ["dm_step"]
    a = alerts[0]
    assert not a["fp"]
    assert a["epoch"] == 6
    assert abs(a["mjd"] - truth.dm_step["mjd"]) <= 1e-6
    sig = float(result.dmx_errs[6])
    assert abs(a["amp"] - ddm) <= 3 * sig


def test_alert_combined_corpus_both_events_no_fp():
    """One glitch + one DM step in the same stream: both alerted at
    their true epochs, neither tagged fp, nothing else fires."""
    alerts, truth, _ = _run_monitor(
        glitch={"epoch": 9, "dphi": 218.8 * 50e-6},
        dm_step={"epoch": 4, "ddm": 4e-3}, rng=8)
    assert sorted(a["kind"] for a in alerts) == ["dm_step", "glitch"]
    assert all(not a["fp"] for a in alerts)


def test_alert_profile_change_and_refractory():
    """The gof arm: persistent reduced-chi^2 excess fires ONE
    profile_change alert (the refractory window collapses the
    re-crossings of a persistent condition)."""
    mon = AlertMonitor("X", warmup=2, max_gof=1.5)

    class T:
        flags = {}

        def __init__(self, mjd):
            self.mjd_int, self.mjd_frac = int(mjd), mjd - int(mjd)
            self.dm = self.dm_err = None

    for i in range(30):
        mon.observe(None, T(55000 + i), gof=1.1 if i < 10 else 9.0)
    kinds = [a["kind"] for a in mon.alerts]
    assert kinds == ["profile_change"]
    assert mon.alerts[0]["mjd"] >= 55009


def test_cusum_detector_units():
    """CUSUM mechanics: quiet stream never alarms; a step alarms with
    the onset localized at the step, not the crossing."""
    det = CusumDetector(k=0.5, h=5.0)
    for _ in range(100):
        assert det.update(0.0) is None
    rng = np.random.default_rng(0)
    det2 = CusumDetector(k=0.5, h=5.0)
    fired = None
    for i in range(50):
        z = float(rng.normal()) + (4.0 if i >= 30 else 0.0)
        s = det2.update(z)
        if s is not None:
            fired = (i, s, det2.last_lag)
            break
    assert fired is not None
    i, s, lag = fired
    assert s > 5.0
    assert i - (lag - 1) in (30, 31)  # onset at the step
    with pytest.raises(ValueError, match="h must be"):
        CusumDetector(h=0.0)
