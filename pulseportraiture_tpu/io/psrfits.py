"""PSRFITS fold-mode archives without PSRCHIVE.

The reference delegates all archive access to the PSRCHIVE C++ library
(reference pplib.py:51; load_data pplib.py:2749-2915).  Here the same
capabilities are implemented natively on top of the in-repo FITS codec
(`fitsio.py`): an `Archive` class with the PSRCHIVE-verb API the
reference leans on (dedisperse, remove_baseline, scrunches, state
conversion), `load_data` returning the identical 36-key DataBunch, and
writers for creating/cloning archives (reference pplib.py:3146-3299).

All transforms here are host-side float64 numpy — archive I/O is a
streaming/setup stage, not the TPU hot path.  The hot path receives
plain arrays from the DataBunch.
"""

from collections import OrderedDict

import numpy as np

from ..config import Dconst
from ..utils.bunch import DataBunch
from ..utils.mjd import MJD
from . import fitsio, native
from .telescopes import telescope_code

SECPERDAY = 86400.0


# --------------------------------------------------------------------------
# numpy kernels used at load time (device-free mirrors of ops/)
# --------------------------------------------------------------------------

def _as_float(x):
    """float64 view/cast, except float32 input stays float32 (the
    streaming loader's reduced-precision mode)."""
    x = np.asarray(x)
    return x if x.dtype == np.float32 else np.asarray(x, np.float64)


def noise_std_ps(data, frac=0.25):
    """Off-pulse noise std from the top-``frac`` power spectrum (numpy
    mirror of ops.noise.get_noise_PS; reference pplib.py:2312-2338)."""
    data = _as_float(data)
    nbin = data.shape[-1]
    X = np.fft.rfft(data, axis=-1)
    kc = int((1.0 - frac) * X.shape[-1])
    power = np.abs(X[..., kc:]) ** 2.0
    return np.sqrt(power.mean(axis=-1) / nbin)


def profile_snr(profile, noise=None, fudge=3.25):
    """Equivalent-width S/N (numpy mirror of ops.noise.get_SNR;
    reference pplib.py:2376-2395)."""
    p = _as_float(profile)
    p = p - np.median(p, axis=-1, keepdims=True)
    if noise is None:
        noise = noise_std_ps(p)
    noise = np.maximum(np.asarray(noise, np.float64), 1e-30)
    # f64-accumulated sums: the equivalent-width ratio is a difference
    # of large sums even in the f32 loader mode
    psum = np.abs(p.sum(axis=-1, dtype=np.float64))
    peak = np.maximum(np.abs(p).max(axis=-1), 1e-30)
    weq = np.maximum(psum / peak, 1.0)
    return psum / (noise * np.sqrt(weq)) / fudge


def rotate_phase(data, turns):
    """Rotate (..., nbin) profiles **backward** by ``turns`` rotations
    via the rFFT phasor — positive turns moves features to earlier
    phase, matching the reference's rotate convention
    (pplib.py:2427-2515)."""
    data = np.asarray(data, np.float64)
    nbin = data.shape[-1]
    k = np.arange(nbin // 2 + 1)
    turns = np.asarray(turns, np.float64)[..., None]
    phasor = np.exp(2.0j * np.pi * k * turns)
    return np.fft.irfft(np.fft.rfft(data, axis=-1) * phasor, n=nbin, axis=-1)


def dm_delays(DM, P, freqs, nu_ref):
    """Dispersion delay in rotations of each channel relative to
    nu_ref: Dconst * DM * (nu^-2 - nu_ref^-2) / P."""
    freqs = np.asarray(freqs, np.float64)
    return Dconst * DM * (freqs ** -2.0 - float(nu_ref) ** -2.0) / P


def baseline_window_stats(profiles, frac=0.15, need_var=True):
    """(mean, var) of the quietest duty-cycle window of each profile —
    the PSRCHIVE 'minimum window' baseline estimator used by
    remove_baseline / baseline_stats.

    Circular rolling windows via f64-accumulated cumulative sums (one
    O(nbin) pass for means, one more for squares when need_var) —
    equivalent to an FFT-correlation formulation but much cheaper on
    host, which matters because this runs per archive load in
    streaming campaigns.  need_var=False (remove_baseline) skips the
    squares pass and returns var=None."""
    p = _as_float(profiles)
    nbin = p.shape[-1]
    w = max(1, int(round(frac * nbin)))

    def _windowed_means(x):
        # circular window sums from one cumsum: the first nbin-w+1
        # windows are direct differences; wrapped windows add the total
        cs = np.cumsum(x, axis=-1, dtype=np.float64)
        total = cs[..., -1:]
        out = np.empty_like(cs)
        out[..., 0] = cs[..., w - 1]
        out[..., 1:nbin - w + 1] = (cs[..., w:] - cs[..., :nbin - w])
        i = np.arange(nbin - w + 1, nbin)
        out[..., nbin - w + 1:] = (total - cs[..., i - 1]
                                   + cs[..., i + w - 1 - nbin])
        return out / w  # mean of window starting at bin i (circular)

    means = _windowed_means(p)
    imin = means.argmin(axis=-1)
    mean = np.take_along_axis(means, imin[..., None], axis=-1)[..., 0]
    if not need_var:
        return mean, None
    sq_means = _windowed_means(p * p)
    var = np.take_along_axis(sq_means, imin[..., None], axis=-1)[..., 0] \
        - mean ** 2
    return mean, np.maximum(var, 0.0)


# --------------------------------------------------------------------------
# Polyco evaluation
# --------------------------------------------------------------------------

def polyco_phase_freq(polyco_rows, epoch_mjd):
    """Evaluate (phase, spin frequency [Hz]) at epoch_mjd from the
    nearest tempo polyco block.  Standard tempo convention:
    PHASE = REF_PHS + DT*60*F0 + C1 + C2*DT + C3*DT^2 + ... (DT in
    minutes from REF_MJD)."""
    ref_mjds = np.asarray(polyco_rows["REF_MJD"], np.float64).ravel()
    i = int(np.abs(ref_mjds - epoch_mjd).argmin())
    dt_min = (epoch_mjd - ref_mjds[i]) * 1440.0
    f0 = float(np.asarray(polyco_rows["REF_F0"]).ravel()[i])
    ref_phs = float(np.asarray(polyco_rows["REF_PHS"]).ravel()[i])
    coeff = np.asarray(polyco_rows["COEFF"], np.float64)
    coeff = coeff[i].ravel() if coeff.ndim > 1 else coeff
    powers = dt_min ** np.arange(len(coeff))
    phase = ref_phs + dt_min * 60.0 * f0 + float(np.dot(coeff, powers))
    dcoef = coeff[1:] * np.arange(1, len(coeff))
    freq = f0 + float(np.dot(dcoef, dt_min ** np.arange(len(dcoef)))) / 60.0
    return phase, freq


# --------------------------------------------------------------------------
# Archive
# --------------------------------------------------------------------------

class Archive:
    """A PSRFITS fold-mode archive held in memory.

    Mirrors the slice of the PSRCHIVE Archive API the reference uses
    (SURVEY §2.2 L1): metadata getters, state conversion, de/dedisperse,
    baseline removal, t/p/f-scrunch, data access, weights, clone/unload.
    Data layout: amps[nsub, npol, nchan, nbin] float64 (scales/offsets
    already applied), weights[nsub, nchan] float64.
    """

    def __init__(self, primary, subint_header, amps, weights, freqs,
                 tsubints, offs_subs, periods, psrparam=None, polyco=None,
                 par_angs=None, filename=""):
        self.primary = primary
        self.subint_header = subint_header
        # float64 canonical; float32 preserved (streaming loader mode)
        self.amps = _as_float(amps)
        self.weights = np.asarray(weights, np.float64)
        self.freqs_table = np.asarray(freqs, np.float64)  # (nsub, nchan)
        self.tsubints = np.asarray(tsubints, np.float64)
        self.offs_subs = np.asarray(offs_subs, np.float64)
        self.periods = np.asarray(periods, np.float64)
        self.psrparam = list(psrparam) if psrparam else []
        self.polyco = polyco
        self._par_angs_from_file = par_angs is not None
        self.par_angs = (np.asarray(par_angs, np.float64)
                         if par_angs is not None
                         else np.zeros(len(self.amps)))
        self.filename = filename

    # -- metadata ----------------------------------------------------------
    @property
    def nsub(self):
        return self.amps.shape[0]

    @property
    def npol(self):
        return self.amps.shape[1]

    @property
    def nchan(self):
        return self.amps.shape[2]

    @property
    def nbin(self):
        return self.amps.shape[3]

    def get_source(self):
        return str(self.primary.get("SRC_NAME", "")).strip()

    def get_telescope(self):
        return str(self.primary.get("TELESCOP", "")).strip()

    def get_receiver_name(self):
        return str(self.primary.get("FRONTEND", "")).strip()

    def get_backend_name(self):
        return str(self.primary.get("BACKEND", "")).strip()

    def get_backend_delay(self):
        return float(self.primary.get("BE_DELAY", 0.0) or 0.0)

    def get_centre_frequency(self):
        return float(self.primary.get("OBSFREQ", self.freqs_table.mean()))

    def get_bandwidth(self):
        return float(self.primary.get("OBSBW",
                                      self.subint_header.get("CHAN_BW", 0.0)
                                      * self.nchan))

    def get_dispersion_measure(self):
        """Pulsar DM [pc cm^-3]: the SUBINT 'DM' card, falling back to
        the PSRPARAM ephemeris DM and last to 'CHAN_DM' (a file from a
        coherent-dedispersion backend may carry only that; note the
        standard SUBINT template writes CHAN_DM=0.0 unconditionally,
        so a zero CHAN_DM must never shadow the ephemeris)."""
        dm = getattr(self, "_dm_override", None)
        if dm is not None:
            return dm
        dm = self.subint_header.get("DM")
        # a 0.0 DM card is AUTHORITATIVE on a dedispersed file with no
        # coherent-dedispersion record (e.g. an averaged template
        # archive: "fully dedispersed, zero residual DM") but means
        # unset-as-zero on raw data (the standard SUBINT template
        # writes DM unconditionally) and on coherent-backend files
        # (nonzero CHAN_DM: the applied DM is recorded there) — those
        # fall through to the ephemeris/CHAN_DM chain
        if dm in (0.0, 0) and self.get_dedispersed() \
                and self.get_chan_dm() == 0.0:
            return 0.0
        if dm in (None, 0.0, 0, "*"):
            dm = _param_value(self.psrparam, "DM")
        if dm in (None, 0.0, 0, "*"):
            dm = self.subint_header.get("CHAN_DM")
        try:
            return float(dm or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def get_chan_dm(self):
        """The 'CHAN_DM' SUBINT card: the DM of the backend's
        within-channel (coherent) dedispersion — NOT the inter-channel
        subint rotation that DEDISP records (0 when absent)."""
        try:
            return float(self.subint_header.get("CHAN_DM", 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def dedispersion_ref_freq(self):
        """Reference frequency of the on-disk inter-channel
        dedispersion delays: the SUBINT 'REF_FREQ' card when present,
        else the centre frequency."""
        try:
            rf = float(self.subint_header.get("REF_FREQ", 0.0) or 0.0)
        except (TypeError, ValueError):
            rf = 0.0
        return rf if rf > 0.0 else self.get_centre_frequency()

    def set_dispersion_measure(self, DM):
        # the in-memory override makes set(0.0)/get round-trip exactly
        # (a 0.0 DM *card* alone is ambiguous on real files — the
        # standard template writes it unset-as-zero — so the card
        # fallback chain above treats it as missing)
        self._dm_override = float(DM)
        self.subint_header["DM"] = float(DM)

    def get_dedispersed(self):
        return bool(self.subint_header.get("DEDISP", 0))

    def get_state(self):
        pol = str(self.subint_header.get("POL_TYPE", "AA+BB")).strip()
        return {"IQUV": "Stokes", "AA+BB": "PPQQ",
                "AABBCRCI": "Coherence",
                "INTEN": "Intensity"}.get(pol, pol)

    def start_time(self):
        return MJD(int(self.primary.get("STT_IMJD", 50000)),
                   (float(self.primary.get("STT_SMJD", 0))
                    + float(self.primary.get("STT_OFFS", 0.0))) / SECPERDAY)

    def epochs(self):
        """Mid-subint epochs as MJD objects: STT_* + OFFS_SUB.

        The SUBINT 'EPOCHS' convention card is honored: for every
        convention PSRCHIVE writes ('MIDTIME', 'VALID', 'STT_MJD')
        OFFS_SUB is the offset of the subint centre from the file
        start, so the arithmetic is shared — the card records the
        *phase-alignment* guarantee (whether the polyco was evaluated
        at these epochs), not a different time base.  An unrecognized
        convention raises rather than silently misdating TOAs."""
        conv = str(self.subint_header.get("EPOCHS",
                                          "MIDTIME")).strip().upper()
        if conv not in ("", "MIDTIME", "VALID", "STT_MJD"):
            raise ValueError(
                f"{self.filename}: unrecognized SUBINT EPOCHS "
                f"convention {conv!r} (known: MIDTIME, VALID, STT_MJD)")
        t0 = self.start_time()
        return [t0.add_seconds(float(s)) for s in self.offs_subs]

    def folding_periods(self):
        """Per-subint folding period [s]: polyco if present, else the
        stored PERIOD column values."""
        if self.polyco is not None:
            eps = [e.to_float() for e in self.epochs()]
            return np.array(
                [1.0 / polyco_phase_freq(self.polyco, e)[1] for e in eps])
        return self.periods.copy()

    def _source_coords(self):
        """(RA, DEC) [deg] from primary cards or PSRPARAM, else None."""
        from ..utils import ephem

        ra = self.primary.get("RA")
        dec = self.primary.get("DEC")
        if not ra or not dec:
            ra = _coord_param(self.psrparam, "RAJ")
            dec = _coord_param(self.psrparam, "DECJ")
        if not ra or not dec:
            return None
        try:
            return ephem.parse_ra(ra), ephem.parse_dec(dec)
        except ValueError:
            return None

    def _site_itrf(self):
        """Observatory ITRF (x, y, z) [m]: ANT_X/Y/Z primary cards when
        present, else the telescope-name lookup table; None if unknown
        or the 'telescope' is the barycentre."""
        from ..utils import ephem

        try:
            xyz = [float(self.primary[k]) for k in
                   ("ANT_X", "ANT_Y", "ANT_Z")]
            if any(v != 0.0 for v in xyz):
                return np.asarray(xyz, np.float64)
        except (KeyError, TypeError, ValueError):
            pass
        return ephem.telescope_itrf(self.get_telescope())

    def doppler_factors(self):
        """nu_source/nu_observed per subint (reference pplib.py:2795-
        2805, PSRCHIVE ephemeris convention: > 1 for increasing
        distance).  Computed from the analytic barycentric Earth-
        velocity model in utils/ephem.py when source coordinates are
        known; 1.0 for explicitly barycentred archives (PPTBARY card,
        written by the synthetic-archive generator), barycentre 'site'
        codes, or archives with no coordinates."""
        from ..utils import ephem

        if self.primary.get("PPTBARY"):
            return np.ones(self.nsub)
        # any barycentre alias (SSB, BAT, BARYCENTER, '@', ...) — the
        # site-code table canonicalizes them all to tempo code '@'
        tel = str(self.get_telescope())
        if tel.upper() in ("@", "BAT") or telescope_code(tel) == "@":
            return np.ones(self.nsub)
        coords = self._source_coords()
        if coords is None:
            return np.ones(self.nsub)
        mjds = np.array([e.to_float() for e in self.epochs()])
        return ephem.doppler_factors(mjds, coords[0], coords[1],
                                     self._site_itrf())

    def parallactic_angles(self):
        """Per-subint parallactic angle [deg]: the PAR_ANG SUBINT
        column when the file carries one, else computed from the site
        geometry (reference pplib.py:2806-2808 via PSRCHIVE 'fix
        pointing'), else zeros."""
        from ..utils import ephem

        if self._par_angs_from_file:
            return self.par_angs.copy()
        coords = self._source_coords()
        site = self._site_itrf()
        if coords is None or site is None:
            return np.zeros(self.nsub)
        mjds = np.array([e.to_float() for e in self.epochs()])
        return ephem.parallactic_angles(mjds, coords[0], coords[1], site)

    def get_weights(self):
        return self.weights.copy()

    def integration_length(self):
        return float(self.tsubints.sum())

    # -- state transforms (in-place, PSRCHIVE verbs) -----------------------
    def convert_state(self, state):
        """In-place polarization state conversion (reference load_data's
        convert_state option, pplib.py:2782-2814, where PSRCHIVE does
        the work).  Supported: anything -> Intensity (pscrunch), and
        Coherence (AABBCRCI) -> Stokes via the van Straten (2004)
        relations in the feed basis named by the FD_POLN primary card:

          linear  (X, Y): I = AA+BB, Q = AA-BB, U = 2 CR, V = 2 CI
          circular(L, R): I = AA+BB, V = AA-BB, Q = 2 CR, U = 2 CI

        PPQQ (AA+BB with no cross terms) cannot reach full Stokes —
        the cross-hand information does not exist in the file."""
        if state == self.get_state():
            return
        if state == "Intensity":
            self.pscrunch()
            return
        if state == "Stokes" and self.get_state() == "Coherence":
            if self.npol != 4:
                raise ValueError(
                    f"Coherence state with npol={self.npol}; need 4 "
                    "(AA, BB, CR, CI)")
            aa, bb, cr, ci = (self.amps[:, i] for i in range(4))
            basis = str(self.primary.get("FD_POLN", "LIN")).strip().upper()
            I = aa + bb
            if basis.startswith("CIRC"):
                V = aa - bb
                Q = 2.0 * cr
                U = 2.0 * ci
            else:  # LIN (default, like PSRCHIVE for missing FD_POLN)
                Q = aa - bb
                U = 2.0 * cr
                V = 2.0 * ci
            self.amps = np.stack([I, Q, U, V], axis=1)
            self.subint_header["POL_TYPE"] = "IQUV"
            return
        raise ValueError(
            f"unsupported state conversion {self.get_state()!r} -> "
            f"{state!r}")

    def pscrunch(self):
        if self.npol == 1:
            self.subint_header["POL_TYPE"] = "INTEN"
            return
        pol = str(self.subint_header.get("POL_TYPE", "AA+BB")).strip()
        if pol == "IQUV":
            self.amps = self.amps[:, :1]
        else:  # AA+BB (or anything summable in the first two pols)
            self.amps = self.amps[:, :2].sum(axis=1, keepdims=True)
        self.subint_header["POL_TYPE"] = "INTEN"
        self.subint_header["NPOL"] = 1

    def dedisperse(self):
        if not self.get_dedispersed():
            self._rotate_dm(-1.0)
            self.subint_header["DEDISP"] = True
            # record the reference so dededisperse undoes exactly this
            # rotation (CHAN_DM is NOT touched — it records the
            # backend's coherent dedispersion, a different operation)
            self.subint_header["REF_FREQ"] = self.get_centre_frequency()

    def dededisperse(self):
        if self.get_dedispersed():
            self._rotate_dm(+1.0)
            self.subint_header["DEDISP"] = False

    def _rotate_dm(self, sign):
        """sign=-1 removes dispersion delays (dedisperse), +1 restores
        them; reference semantics: rotate_portrait is 'virtually
        identical to arch.dedisperse()' (reference pplib.py:2526).

        Undoing an on-disk dedispersion (sign=+1) honors the REF_FREQ
        card (the reference the delays were computed against); the DM
        is the archive DM in both directions — CHAN_DM records the
        backend's within-channel coherent dedispersion, a different
        operation that subint rotation must not conflate."""
        DM = self.get_dispersion_measure()
        nu0 = (self.dedispersion_ref_freq() if sign > 0
               else self.get_centre_frequency())
        if DM == 0.0:
            return
        Ps = self.folding_periods()
        for isub in range(self.nsub):
            delays = dm_delays(DM, Ps[isub], self.freqs_table[isub], nu0)
            # rotate_phase rotates backward by +turns; removing a delay
            # of d rotations means rotating backward by d.
            self.amps[isub] = rotate_phase(self.amps[isub], sign * -delays)

    def remove_baseline(self):
        mean, _ = baseline_window_stats(self.amps, need_var=False)
        self.amps -= mean.astype(self.amps.dtype)[..., None]

    def baseline_stats(self):
        return baseline_window_stats(self.amps)

    def tscrunch(self):
        if self.nsub == 1:
            return
        w = self.weights  # (nsub, nchan)
        wsum = np.maximum(w.sum(axis=0), 1e-30)  # (nchan,)
        amps = np.einsum("spcb,sc->pcb", self.amps, w) / wsum[:, None]
        self.amps = amps[None]
        total = self.tsubints.sum()
        # duration-weighted central epoch offset
        mid = float((self.offs_subs * self.tsubints).sum()
                    / max(self.tsubints.sum(), 1e-30))
        self.freqs_table = self.freqs_table.mean(axis=0, keepdims=True)
        self.weights = w.sum(axis=0, keepdims=True)
        self.tsubints = np.array([total])
        self.offs_subs = np.array([mid])
        self.periods = np.array([self.folding_periods().mean()])
        self.par_angs = self.par_angs.mean(keepdims=True)

    def fscrunch(self):
        if self.nchan == 1:
            return
        w = self.weights  # (nsub, nchan)
        wsum = np.maximum(w.sum(axis=1), 1e-30)  # (nsub,)
        amps = np.einsum("spcb,sc->spb", self.amps, w) / wsum[:, None, None]
        fmean = (self.freqs_table * w).sum(axis=1) / wsum
        self.amps = amps[:, :, None, :]
        self.freqs_table = fmean[:, None]
        self.weights = wsum[:, None]
        self.subint_header["NCHAN"] = 1

    # -- data --------------------------------------------------------------
    def get_data(self):
        return self.amps.copy()

    def set_data(self, amps):
        amps = _as_float(amps)
        if amps.ndim != 4:
            raise ValueError("amps must be [nsub, npol, nchan, nbin]")
        self.amps = amps.copy()

    def set_weights(self, weights):
        self.weights = np.broadcast_to(
            np.asarray(weights, np.float64),
            (self.nsub, self.nchan)).copy()

    def clone(self):
        import copy
        arch = Archive(
            primary=fitsio.Header(list(self.primary.cards)),
            subint_header=fitsio.Header(list(self.subint_header.cards)),
            amps=self.amps.copy(), weights=self.weights.copy(),
            freqs=self.freqs_table.copy(), tsubints=self.tsubints.copy(),
            offs_subs=self.offs_subs.copy(), periods=self.periods.copy(),
            psrparam=list(self.psrparam),
            polyco=copy.deepcopy(self.polyco),
            par_angs=self.par_angs.copy(), filename=self.filename)
        arch._par_angs_from_file = self._par_angs_from_file
        return arch

    def unload(self, path, nbit=16, levels=None):
        write_archive_file(path, self, nbit=nbit, levels=levels)

    def refresh(self):
        """Reload from disk if this archive came from a file."""
        if self.filename:
            fresh = read_archive(self.filename)
            self.__dict__.update(fresh.__dict__)


# --------------------------------------------------------------------------
# Reading
# --------------------------------------------------------------------------

def read_archive(path, dtype=np.float64, decode=True):
    """Parse a PSRFITS fold-mode file into an Archive (scales, offsets
    applied; weights kept separate).

    dtype: float64 (canonical) or float32 — the streaming campaign
    loader decodes straight to f32, halving host memory traffic for
    data that feeds the f32 fast fit anyway.

    decode=False (raw streaming mode): requires a DATA column in one
    of the raw-transportable sample types — int16 (TFORM 'I'),
    unsigned byte ('B'), signed byte ('B' + the FITS TZERO=-128
    convention), float32 ('E'), or sub-byte packed samples ('B' with
    an NBIT=1/2/4 card, MSB-first per the PSRFITS convention).  The
    Archive's ``amps`` becomes a read-only zero placeholder and the
    undecoded samples are attached as ``raw_data`` — (nsub, npol,
    nchan, nbin) in the native-endian wire dtype, or (nsub, npol,
    plane_bytes) PACKED bytes for sub-byte NBIT (row byte-pad already
    trimmed; each pol plane must byte-align, i.e. nchan*nbin*NBIT
    divisible by 8) — with ``raw_scl``/``raw_offs`` (nsub, npol,
    nchan) float32 and ``raw_code`` naming the sample type for the
    device decode (ops/decode.RAW_CODES; packed codes 'p1'/'p2'/'p4').
    General FITS column scaling (TSCAL/TZERO beyond the signed-byte
    convention) attaches as ``raw_tscal``/``raw_tzero`` scalars the
    device decode applies before DAT_SCL/DAT_OFFS, in the exact host
    order.  The streaming driver ships all of this to the accelerator
    and decodes there, cutting host->device bytes 2x (int16) to 32x
    (2-bit packed) vs decoded float64.

    Coverage matrix (raw mode ships -> device decodes):

      =========================  ==========  =======================
      DATA layout                raw_code    bytes vs decoded f64
      =========================  ==========  =======================
      TFORM 'I' int16            'i16'       4x fewer
      TFORM 'B' unsigned byte    'u8'        8x fewer
      TFORM 'B' + TZERO=-128     'i8'        8x fewer
      TFORM 'E' float32          'f32'       2x fewer
      NBIT=4 packed              'p4'        16x fewer
      NBIT=2 packed              'p2'        32x fewer
      NBIT=1 packed              'p1'        64x fewer
      any above + TSCAL/TZERO    (same)      (same; 2 extra scalars)
      =========================  ==========  =======================

    Raises ValueError for the remaining unrepresentable layouts
    (sub-byte planes that do not byte-align, packed + FITS-scaled
    columns, or config.raw_subbyte / PPT_RAW_SUBBYTE=off — the escape
    hatch forcing the decoded lane); the caller falls back to
    decoding.

    When the native decoder (io/native.py) is available, the DATA
    column is decoded straight from the wire bytes with DAT_SCL /
    DAT_OFFS fused in (one threaded pass, no float64 intermediates);
    otherwise the pure-numpy path below is the reference behavior."""
    dtype = np.dtype(dtype).type
    use_native = native.available() and decode
    defer = ("DATA",) if (use_native or not decode) else ()
    hdus = fitsio.read_fits(path, defer=defer)
    primary = hdus[0].header
    obs_mode = str(primary.get("OBS_MODE", "PSR")).strip().upper()
    if obs_mode in ("SEARCH", "SRCH"):
        # a SEARCH-mode SUBINT table holds unfolded filterbank samples
        # (NSBLK time samples per row, no PERIOD) — silently misparsing
        # it as folded profiles would produce garbage TOAs
        raise ValueError(
            f"{path}: OBS_MODE={obs_mode} is a search-mode PSRFITS "
            "file (unfolded time samples); fold it first (e.g. with "
            "dspsr) — only fold-mode archives carry profiles to time")
    try:
        subint = fitsio.get_hdu(hdus, "SUBINT")
    except KeyError:
        raise ValueError(f"{path}: no SUBINT HDU (not a fold-mode archive)")
    cols = subint.data
    hdr = subint.header
    nchan = int(hdr.get("NCHAN", 0)) or cols["DAT_FREQ"].shape[-1]
    npol = int(hdr.get("NPOL", 1))
    nsub = int(hdr.get("NAXIS2", 0)) or len(cols["DAT_FREQ"])
    scl = np.asarray(cols.get("DAT_SCL",
                              np.ones((nsub, npol * nchan))),
                     np.float64).reshape(nsub, npol, nchan)
    offs = np.asarray(cols.get("DAT_OFFS",
                               np.zeros((nsub, npol * nchan))),
                      np.float64).reshape(nsub, npol, nchan)
    _SAMP_BYTES = {"I": 2, "B": 1, "E": 4}
    # a FITS-scaled DATA column (TSCAL/TZERO — e.g. the signed-byte
    # convention) must go through the scaling-aware numpy path: the
    # raw int16 transport and the native kernel read stored values
    data_scaling = subint.col_scaling.get("DATA")
    raw_data = None
    raw_code = None
    raw_tscal = raw_tzero = None
    if not decode:
        col_off, code, repeat = subint.layout["DATA"]
        nbin = int(hdr.get("NBIN", 0)) or repeat // (npol * nchan)
        nbit = int(hdr.get("NBIT", 8) or 8)
        # wire dtype + device sample code per TFORM (ops/decode).  'B'
        # with the FITS signed-byte convention (TSCAL 1, TZERO -128)
        # ships as-is and the device decode removes the bias exactly;
        # any OTHER TSCAL/TZERO scaling ships its two scalars and the
        # device decode applies them before DAT_SCL/DAT_OFFS, in the
        # exact host order.
        wire = {"I": (">i2", np.int16, "i16"),
                "B": ("u1", np.uint8, "u8"),
                "E": (">f4", np.float32, "f32")}.get(code)
        if code == "B" and data_scaling is not None \
                and float(data_scaling[0]) == 1.0 \
                and float(data_scaling[1]) == -128.0:
            wire = ("u1", np.uint8, "i8")
            data_scaling = None
        if code == "B" and nbit in (1, 2, 4):
            # sub-byte packed samples ship PACKED (raw codes
            # 'p1'/'p2'/'p4'); the device unpacks the bit planes
            # inside the fused program (ops/decode.unpack_bitplanes).
            # Per-pol slicing on host is a byte index, so each pol
            # plane must byte-align; the row byte-pad is trimmed here.
            from .. import config as _cfg

            if not getattr(_cfg, "raw_subbyte", True):
                raise ValueError(
                    f"{path}: sub-byte raw transport disabled "
                    "(config.raw_subbyte / PPT_RAW_SUBBYTE=off); "
                    "decode on host instead")
            per = 8 // nbit
            plane = nchan * nbin
            row_bytes = (npol * plane + per - 1) // per
            if (data_scaling is not None or plane % per != 0
                    or repeat != row_bytes
                    or not int(hdr.get("NBIN", 0))
                    or col_off + row_bytes > subint.row_stride
                    or len(subint.raw) < nsub * subint.row_stride):
                raise ValueError(
                    f"{path}: NBIT={nbit} DATA column is FITS-scaled, "
                    "inconsistent, or its pol planes do not "
                    "byte-align; raw streaming mode cannot ship it "
                    "packed")
            rows = np.frombuffer(subint.raw, np.uint8)[
                : nsub * subint.row_stride].reshape(nsub,
                                                    subint.row_stride)
            plane_bytes = plane // per
            col = np.ascontiguousarray(
                rows[:, col_off:col_off + npol * plane_bytes])
            raw_data = col.reshape(nsub, npol, plane_bytes)
            raw_code = f"p{nbit}"
            amps = np.broadcast_to(np.float32(0.0),
                                   (nsub, npol, nchan, nbin))
        else:
            if wire is not None and data_scaling is not None:
                # general TSCAL/TZERO: stored values ship as-is plus
                # the two column-scaling scalars
                raw_tscal = float(data_scaling[0])
                raw_tzero = float(data_scaling[1])
                data_scaling = None
            samp = np.dtype(wire[0]).itemsize if wire else 0
            if (wire is None or npol * nchan * nbin != repeat
                    or data_scaling is not None
                    or col_off + repeat * samp > subint.row_stride
                    or len(subint.raw) < nsub * subint.row_stride):
                raise ValueError(
                    f"{path}: raw streaming mode needs a consistent "
                    "int16/byte/float32 (or packed NBIT) DATA column")
            rows = np.frombuffer(subint.raw, np.uint8)[
                : nsub * subint.row_stride].reshape(nsub,
                                                    subint.row_stride)
            col = np.ascontiguousarray(
                rows[:, col_off:col_off + repeat * samp])
            # one byteswap/memcpy pass; no float decode anywhere on
            # host
            raw_data = col.view(wire[0]).astype(wire[1]).reshape(
                nsub, npol, nchan, nbin)
            raw_code = wire[2]
            amps = np.broadcast_to(np.float32(0.0), raw_data.shape)
    elif use_native:
        col_off, code, repeat = subint.layout["DATA"]
        nbin = int(hdr.get("NBIN", 0)) or repeat // (npol * nchan)
        samp = _SAMP_BYTES.get(code)
        # the C kernel has no bounds checks: validate the header-derived
        # geometry against the actual column layout before handing it
        # raw bytes (an inconsistent NBIN card must error like the numpy
        # reshape does, not read past the column)
        consistent = (
            samp is not None
            and data_scaling is None
            and npol * nchan * nbin == repeat
            and col_off + repeat * samp <= subint.row_stride
            and len(subint.raw) >= nsub * subint.row_stride
        )
        amps = native.decode_fused(
            subint.raw, nsub, subint.row_stride, col_off, code,
            npol, nchan, nbin, scl=scl, offs=offs,
            dtype=dtype) if consistent else None
    else:
        amps = None
    if amps is None:  # pure-numpy reference path
        if cols["DATA"] is None:
            # deferred but native decode declined: decode the DATA
            # column from the already-read table bytes
            col_off, code, repeat = subint.layout["DATA"]
            samp_dt = {"I": ">i2", "B": "u1", "E": ">f4",
                       "D": ">f8", "J": ">i4"}[code]
            width = repeat * np.dtype(samp_dt).itemsize
            rows = np.frombuffer(subint.raw, np.uint8)[
                : nsub * subint.row_stride].reshape(nsub, subint.row_stride)
            col = np.ascontiguousarray(
                rows[:, col_off:col_off + width]).view(samp_dt)
            if data_scaling is not None:
                col = fitsio.apply_column_scaling(col, *data_scaling)
            cols["DATA"] = col.astype(dtype)
        nbin = int(hdr.get("NBIN", 0)) or cols["DATA"].shape[-1]
        data_col = np.asarray(cols["DATA"])
        nbit = int(hdr.get("NBIT", 8) or 8)
        if nbit in (1, 2, 4):
            # sub-byte packed samples (search-era backends; PSRFITS
            # packs MSB-first within each byte, each ROW padded to
            # whole bytes) — unpack to unsigned sample values and trim
            # the row pad; DAT_SCL/DAT_OFFS restore the physics
            row_samp = npol * nchan * nbin
            per = 8 // nbit
            row_bytes = (row_samp + per - 1) // per
            if data_col.size != nsub * row_bytes:
                raise ValueError(
                    f"NBIT={nbit} DATA column holds {data_col.size} "
                    f"bytes; expected {nsub} rows x {row_bytes}")
            b = data_col.reshape(nsub, row_bytes).astype(np.uint8)
            mask = (1 << nbit) - 1
            shifts = np.arange(per - 1, -1, -1, dtype=np.uint8) * nbit
            samples = (b[:, :, None] >> shifts[None, None, :]) & mask
            data_col = samples.reshape(nsub, row_bytes * per)[:, :row_samp]
        raw = np.asarray(data_col, dtype).reshape(
            nsub, npol, nchan, nbin)
        amps = raw * scl[..., None].astype(dtype) \
            + offs[..., None].astype(dtype)
    weights = np.asarray(cols.get("DAT_WTS", np.ones((nsub, nchan))),
                         np.float64).reshape(nsub, nchan)
    freqs = np.asarray(cols["DAT_FREQ"], np.float64).reshape(nsub, nchan)
    tsub = np.asarray(cols.get("TSUBINT", np.ones(nsub)),
                      np.float64).ravel()
    offs_sub = np.asarray(cols.get("OFFS_SUB", np.zeros(nsub)),
                          np.float64).ravel()
    par_ang = (np.asarray(cols["PAR_ANG"], np.float64).ravel()
               if "PAR_ANG" in cols else None)

    psrparam = []
    try:
        pp = fitsio.get_hdu(hdus, "PSRPARAM")
        col = next(iter(pp.data.values()))
        psrparam = [
            (r.decode("ascii", "replace") if isinstance(r, bytes) else str(r))
            .strip() for r in np.asarray(col).ravel()]
    except (KeyError, StopIteration):
        pass

    polyco = None
    try:
        polyco = fitsio.get_hdu(hdus, "POLYCO").data
    except KeyError:
        pass

    if "PERIOD" in cols:
        periods = np.asarray(cols["PERIOD"], np.float64).ravel()
    elif polyco is not None:
        periods = np.zeros(nsub)  # computed from polyco on demand
    else:
        f0 = _param_value(psrparam, "F0")
        periods = np.full(nsub, 1.0 / f0 if f0 else 1.0)

    arch = Archive(primary, hdr, amps, weights, freqs, tsub, offs_sub,
                   periods, psrparam=psrparam, polyco=polyco,
                   par_angs=par_ang, filename=str(path))
    if raw_data is not None:
        arch.raw_data = raw_data
        arch.raw_code = raw_code
        arch.raw_scl = scl.astype(np.float32)
        arch.raw_offs = offs.astype(np.float32)
        arch.raw_tscal = raw_tscal
        arch.raw_tzero = raw_tzero
    if polyco is not None and "PERIOD" not in cols:
        arch.periods = arch.folding_periods()
    return arch


def _param_value(lines, key):
    for line in lines:
        parts = line.split()
        if parts and parts[0] == key:
            try:
                return float(parts[1].replace("D", "E"))
            except (IndexError, ValueError):
                return None
    return None


def _coord_param(lines, key):
    """RAJ/DECJ string from PSRPARAM lines: a single 'hh:mm:ss.s' (or
    decimal) token, or space-separated sexagesimal 'hh mm ss.s' (three
    tokens, distinguished from a trailing fit-flag/error by the first
    two being integers)."""
    for line in lines:
        parts = line.split()
        if parts and parts[0] == key and len(parts) > 1:
            if (len(parts) >= 4 and ":" not in parts[1]
                    and parts[1].lstrip("+-").isdigit()
                    and parts[2].isdigit()):
                try:
                    float(parts[3])
                    return " ".join(parts[1:4])
                except ValueError:
                    pass
            return parts[1]
    return None


def parse_parfile(path_or_lines):
    """Parse a tempo-style parfile into {PARAM: string value}."""
    if isinstance(path_or_lines, (list, tuple)):
        lines = path_or_lines
    else:
        with open(path_or_lines) as f:
            lines = f.readlines()
    out = OrderedDict()
    for line in lines:
        parts = line.split()
        if len(parts) >= 2 and not line.strip().startswith("#"):
            out[parts[0]] = parts[1]
    return out


# --------------------------------------------------------------------------
# Writing
# --------------------------------------------------------------------------

def write_archive_file(path, arch, nbit=16, levels=None):
    """Serialize an Archive to a PSRFITS fold-mode file (16-bit scaled
    DATA by default; PSRPARAM/POLYCO HDUs preserved).

    nbit: DATA sample width.  16 (default, byte-stable): scaled int16.
    8: scaled unsigned bytes.  1/2/4: sub-byte packed samples,
    MSB-first with the PSRFITS row byte-pad and an NBIT card — the
    search/fold-era layout the raw streaming lane ships packed.
    levels: quantize to this many amplitude levels instead of the full
    2**nbit range (must fit the width) — what a coarsely-quantizing
    backend stores, and the corpus knob the transport-compression
    bench uses (a 4-level byte column packs 4x)."""
    nsub, npol, nchan, nbin = arch.amps.shape
    if nbit not in (1, 2, 4, 8, 16):
        raise ValueError(f"write_archive_file: nbit must be one of "
                         f"1, 2, 4, 8, 16; got {nbit}")
    # the signed int16 container holds q in [0, 32767]; past that the
    # unsigned quantized values would wrap negative silently
    max_levels = 2 ** 15 if nbit == 16 else 2 ** nbit
    if levels is not None and not 2 <= int(levels) <= max_levels:
        raise ValueError(
            f"write_archive_file: levels={levels} does not fit "
            f"nbit={nbit} (need 2 <= levels <= {max_levels})")
    lo = arch.amps.min(axis=-1)
    hi = arch.amps.max(axis=-1)
    nbit_card = None
    if nbit == 16 and levels is None:
        # the historical exact path — golden archives stay
        # byte-identical: per-(sub, pol, chan) scaling to int16
        offs = 0.5 * (hi + lo)
        scl = np.maximum((hi - lo) / 65530.0, 1e-30)
        data = np.round((arch.amps - offs[..., None]) / scl[..., None])
        data = np.clip(data, -32768, 32767).astype(">i2")
    else:
        # unsigned quantization to `span` levels: q in [0, span],
        # DAT_SCL/DAT_OFFS restore the physics exactly like any
        # integer-quantized archive
        span = float((levels or 2 ** nbit) - 1)
        offs = lo
        scl = np.maximum((hi - lo) / span, 1e-30)
        q = np.clip(np.round((arch.amps - offs[..., None])
                             / scl[..., None]), 0, span)
        if nbit == 16:
            data = q.astype(">i2")
        elif nbit == 8:
            data = q.astype("u1")
        else:
            # MSB-first packing, each ROW padded to whole bytes (the
            # PSRFITS convention readers trim)
            per = 8 // nbit
            row_samp = npol * nchan * nbin
            row_bytes = (row_samp + per - 1) // per
            flat = q.astype(np.uint8).reshape(nsub, row_samp)
            padded = np.zeros((nsub, row_bytes * per), np.uint8)
            padded[:, :row_samp] = flat
            grp = padded.reshape(nsub, row_bytes, per)
            data = np.zeros((nsub, row_bytes), np.uint8)
            for j in range(per):
                data |= (grp[:, :, j] & ((1 << nbit) - 1)) \
                    << np.uint8((per - 1 - j) * nbit)
            nbit_card = nbit

    cols = OrderedDict()
    cols["TSUBINT"] = arch.tsubints.astype(">f8")
    cols["OFFS_SUB"] = arch.offs_subs.astype(">f8")
    cols["PERIOD"] = arch.periods.astype(">f8")
    if arch.par_angs.any() or arch._par_angs_from_file:
        # an all-zero placeholder column would shadow the geometric
        # computation in Archive.parallactic_angles() on re-read
        cols["PAR_ANG"] = arch.par_angs.astype(">f8")
    cols["DAT_FREQ"] = arch.freqs_table.astype(">f8")
    cols["DAT_WTS"] = arch.weights.astype(">f4")
    cols["DAT_OFFS"] = offs.reshape(nsub, npol * nchan).astype(">f4")
    cols["DAT_SCL"] = scl.reshape(nsub, npol * nchan).astype(">f4")
    cols["DATA"] = data

    hdr_cards = [(k, v, c) for (k, v, c) in arch.subint_header.cards
                 if not k.startswith(("TTYPE", "TFORM", "TDIM", "TUNIT"))
                 and k not in ("XTENSION", "BITPIX", "NAXIS", "NAXIS1",
                               "NAXIS2", "PCOUNT", "GCOUNT", "TFIELDS",
                               "EXTNAME")]
    hdr = fitsio.Header(hdr_cards)
    hdr["NBIN"] = nbin
    hdr["NCHAN"] = nchan
    hdr["NPOL"] = npol
    hdr["NSBLK"] = 1
    hdr["INT_TYPE"] = "TIME"
    hdr["DEDISP"] = bool(arch.get_dedispersed())
    if nbit_card is not None:
        hdr["NBIT"] = nbit_card

    prim_cards = [(k, v, c) for (k, v, c) in arch.primary.cards
                  if k not in ("SIMPLE", "BITPIX", "NAXIS", "EXTEND")]

    with open(path, "wb") as f:
        fitsio.write_primary(f, prim_cards)
        if arch.psrparam:
            width = max(max(len(s) for s in arch.psrparam), 8)
            par = np.array([s.ljust(width).encode("ascii")
                            for s in arch.psrparam], dtype=f"S{width}")
            fitsio.write_bintable(f, "PSRPARAM",
                                  OrderedDict(PARAM=par))
        if arch.polyco is not None:
            pcols = OrderedDict()
            for k, v in arch.polyco.items():
                v = np.asarray(v)
                if v.dtype.kind in "iufc":
                    v = v.astype(">" + v.dtype.newbyteorder("=").str[1:])
                pcols[k] = v
            fitsio.write_bintable(f, "POLYCO", pcols)
        fitsio.write_bintable(
            f, "SUBINT", cols,
            header_cards=[(k, v, c) for (k, v, c) in hdr.cards],
            # a packed DATA column is a flat byte run per row — its
            # sample geometry lives in the NBIT/NBIN/NCHAN/NPOL cards,
            # not a TDIM (which would misdescribe the byte count)
            tdims=({} if nbit_card is not None
                   else {"DATA": (nbin, nchan, npol)}))


def new_archive(amps, freqs, Ps, epochs_mjd, tsubints, weights=None,
                DM=0.0, dedispersed=True, source="FAKE", telescope="GBT",
                frontend="LBAND", backend="SYNTH", nu0=None, bw=None,
                state="Intensity", psrparam=None, be_delay=0.0):
    """Create an Archive from arrays (reference write_archive,
    pplib.py:3189-3299, without the PSRCHIVE 'ASP' cloning hack).

    amps: [nsub, npol, nchan, nbin]; freqs: (nchan,) or (nsub, nchan);
    epochs_mjd: list of MJD (mid-subint); tsubints: (nsub,) seconds.
    """
    amps = np.asarray(amps, np.float64)
    if amps.ndim == 3:
        amps = amps[:, None]
    nsub, npol, nchan, nbin = amps.shape
    freqs = np.asarray(freqs, np.float64)
    if freqs.ndim == 1:
        freqs = np.broadcast_to(freqs, (nsub, nchan)).copy()
    Ps = np.broadcast_to(np.asarray(Ps, np.float64), (nsub,)).copy()
    tsubints = np.broadcast_to(np.asarray(tsubints, np.float64),
                               (nsub,)).copy()
    if weights is None:
        weights = np.ones((nsub, nchan))
    weights = np.broadcast_to(np.asarray(weights, np.float64),
                              (nsub, nchan)).copy()
    if nu0 is None:
        nu0 = float(freqs.mean())
    if bw is None:
        df = np.diff(np.sort(freqs[0]))
        bw = float((df.mean() if len(df) else 1.0) * nchan)

    t0 = epochs_mjd[0].add_seconds(-0.5 * float(tsubints[0]))
    stt_smjd = int(t0.frac * SECPERDAY)
    stt_offs = t0.frac * SECPERDAY - stt_smjd
    offs_subs = np.array([e - t0 for e in epochs_mjd]) * SECPERDAY

    primary = fitsio.Header([
        ("FITSTYPE", "PSRFITS", "FITS definition for pulsar data"),
        ("OBS_MODE", "PSR", "fold mode"),
        ("SRC_NAME", source, ""),
        ("TELESCOP", telescope, ""),
        ("FRONTEND", frontend, ""),
        ("BACKEND", backend, ""),
        ("BE_DELAY", float(be_delay), "backend delay [s]"),
        ("OBSFREQ", float(nu0), "center frequency [MHz]"),
        ("OBSBW", float(bw), "bandwidth [MHz]"),
        ("OBSNCHAN", nchan, ""),
        ("STT_IMJD", t0.day, "start MJD (int)"),
        ("STT_SMJD", stt_smjd, "start second"),
        ("STT_OFFS", stt_offs, "start fractional second"),
    ])
    subint_header = fitsio.Header([
        ("POL_TYPE", {"Intensity": "INTEN", "Stokes": "IQUV",
                      "PPQQ": "AA+BB"}.get(state, state), ""),
        ("NBIN", nbin, ""), ("NCHAN", nchan, ""), ("NPOL", npol, ""),
        ("CHAN_BW", bw / nchan, "channel bandwidth [MHz]"),
        ("DM", float(DM), "dispersion measure [pc cm^-3]"),
        ("DEDISP", bool(dedispersed), "data dedispersed?"),
    ])
    return Archive(primary, subint_header, amps, weights, freqs,
                   tsubints, offs_subs, Ps, psrparam=psrparam)


def unload_new_archive(amps, arch, path, DM=None, dmc=0, weights=None,
                       quiet=False):
    """Clone ``arch``, overwrite amplitudes/weights/DM, write to
    ``path`` (reference unload_new_archive, pplib.py:3146-3186)."""
    new = arch.clone() if isinstance(arch, Archive) else read_archive(arch)
    amps = np.asarray(amps, np.float64)
    if amps.ndim == 2:
        amps = amps[None, None]
    elif amps.ndim == 3:
        amps = amps[:, None]
    new.set_data(amps)
    if DM is not None:
        new.set_dispersion_measure(DM)
    new.subint_header["DEDISP"] = bool(dmc)
    if weights is not None:
        new.set_weights(weights)
    new.unload(path)
    if not quiet:
        print(f"Unloaded {path}.")


# --------------------------------------------------------------------------
# load_data — the reference's universal ingest (pplib.py:2749-2915)
# --------------------------------------------------------------------------

def load_data(filename, state=None, dedisperse=False, dededisperse=False,
              tscrunch=False, pscrunch=False, fscrunch=False,
              rm_baseline=True, flux_prof=False, refresh_arch=False,
              return_arch=True, quiet=False, dtype=np.float64):
    """Load a PSRFITS archive into the 36-key DataBunch the whole
    framework consumes.  Same signature, keys, and semantics as the
    reference's load_data (pplib.py:2749-2915), implemented without
    PSRCHIVE.  dtype float32 decodes/processes the data cube in single
    precision (streaming campaign mode)."""
    arch = read_archive(filename, dtype=dtype)
    source = arch.get_source()
    if not quiet:
        print(f"\nReading data from {filename} on source {source}...")
    telescope = arch.get_telescope()
    tcode = telescope_code(telescope)
    frontend = arch.get_receiver_name()
    backend = arch.get_backend_name()
    backend_delay = arch.get_backend_delay()
    if state is not None:
        arch.convert_state(state)
    if dedisperse:
        arch.dedisperse()
    if dededisperse:
        arch.dededisperse()
    DM = arch.get_dispersion_measure()
    dmc = arch.get_dedispersed()
    if rm_baseline:
        arch.remove_baseline()
    if tscrunch:
        arch.tscrunch()
    nsub = arch.nsub
    integration_length = arch.integration_length()
    doppler_factors = arch.doppler_factors()
    parallactic_angles = arch.parallactic_angles()
    if pscrunch:
        arch.pscrunch()
    state = arch.get_state()
    npol = arch.npol
    if fscrunch:
        arch.fscrunch()
    nu0 = arch.get_centre_frequency()
    bw = arch.get_bandwidth()
    nchan = arch.nchan
    freqs = arch.freqs_table.copy()
    nbin = arch.nbin
    phases = (np.arange(nbin) + 0.5) / nbin
    subints = arch.get_data()
    Ps = arch.folding_periods()
    epochs = arch.epochs()
    subtimes = list(arch.tsubints)
    weights = arch.get_weights()
    weights_norm = np.where(weights == 0.0, 0.0, 1.0)
    noise_stds = noise_std_ps(subints)  # (nsub, npol, nchan)
    ok_isubs = np.compress(weights_norm.mean(axis=1),
                           np.arange(nsub)).astype(int)
    ok_ichans = [np.compress(weights_norm[isub],
                             np.arange(nchan)).astype(int)
                 for isub in range(nsub)]
    # read-only broadcast view — materializing this (nsub, npol, nchan,
    # nbin) cube would copy ~100 MB per campaign archive for a 0/1 mask
    masks = np.broadcast_to(weights_norm[:, None, :, None],
                            (nsub, npol, nchan, nbin))
    SNRs = profile_snr(subints, noise_stds)
    # the rest ignores npol (reference behavior: pscrunch for summaries)
    summary = arch.clone()
    summary.pscrunch()
    if flux_prof:
        fp = summary.clone()
        fp.dedisperse()
        fp.tscrunch()
        flux_prof = fp.get_data().mean(axis=3)[0][0]
    else:
        flux_prof = np.array([])
    summary.tscrunch()
    summary.fscrunch()
    prof = summary.get_data()[0, 0, 0]
    _, base_var = summary.baseline_stats()
    prof_noise = float(np.sqrt(base_var[0, 0, 0]))
    prof_SNR = float(profile_snr(prof))
    nchanx = np.array([len(x) for x in ok_ichans]).mean() if nsub else 0
    nsubx = len(ok_isubs)
    if not quiet:
        P = Ps[0] * 1000.0 if len(Ps) else 0.0
        print(f"\tP [ms]             = {P:.3f}\n"
              f"\tDM [cm**-3 pc]     = {DM:.6f}\n"
              f"\tcenter freq. [MHz] = {nu0:.4f}\n"
              f"\tbandwidth [MHz]    = {bw:.1f}\n"
              f"\t# bins in prof     = {nbin}\n"
              f"\t# channels         = {nchan}\n"
              f"\t# chan (mean)      = {int(nchanx)}\n"
              f"\t# subints          = {nsub}\n"
              f"\t# unzapped subint  = {nsubx}\n"
              f"\tpol'n state        = {state}\n")
    if refresh_arch:
        arch.refresh()
    if not return_arch:
        arch = None
    return DataBunch(
        arch=arch, backend=backend, backend_delay=backend_delay, bw=bw,
        doppler_factors=doppler_factors, DM=DM, dmc=dmc, epochs=epochs,
        filename=str(filename), flux_prof=flux_prof, freqs=freqs,
        frontend=frontend, integration_length=integration_length,
        masks=masks, nbin=nbin, nchan=nchan, noise_stds=noise_stds,
        npol=npol, nsub=nsub, nu0=nu0, ok_ichans=ok_ichans,
        ok_isubs=ok_isubs, parallactic_angles=parallactic_angles,
        phases=phases, prof=prof, prof_noise=prof_noise,
        prof_SNR=prof_SNR, Ps=Ps, SNRs=SNRs, source=source, state=state,
        subints=subints, subtimes=subtimes, telescope=telescope,
        telescope_code=tcode, weights=weights)
