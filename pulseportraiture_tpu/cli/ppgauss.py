"""ppgauss — fit an evolving Gaussian-component model.

Flag parity: reference ppgauss.py:666-812.
"""

import argparse
import sys


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppgauss", description=__doc__.splitlines()[0])
    p.add_argument("-d", "--datafile", default=None,
                   help="PSRFITS archive to fit.")
    p.add_argument("-M", "--metafile", default=None,
                   help="Metafile of archives (JOIN fit across receivers).")
    p.add_argument("-I", "--improve", dest="modelfile", default=None,
                   help="Start from an existing .gmodel and improve it.")
    p.add_argument("-o", "--outfile", default=None,
                   help="Output model file name.")
    p.add_argument("-e", "--errfile", default=None,
                   help="Output parameter-error file name.")
    p.add_argument("-j", "--joinfile", default=None,
                   help="Joinfile with previously fitted JOIN parameters.")
    p.add_argument("-m", "--model_name", default=None)
    p.add_argument("--nu_ref", type=float, default=None,
                   help="Reference frequency [MHz] of the model.")
    p.add_argument("--bw", dest="bw_ref", type=float, default=None,
                   help="Bandwidth [MHz] of the reference profile slice.")
    p.add_argument("--tau", type=float, default=0.0,
                   help="Scattering timescale [bin].")
    p.add_argument("--fitloc", dest="fixloc", action="store_false",
                   default=True, help="Let component positions evolve.")
    p.add_argument("--fixwid", action="store_true", default=False,
                   help="Do not let widths evolve.")
    p.add_argument("--fixamp", action="store_true", default=False,
                   help="Do not let amplitudes evolve.")
    p.add_argument("--fitscat", dest="fixscat", action="store_false",
                   default=True, help="Fit a scattering timescale.")
    p.add_argument("--fitalpha", dest="fixalpha", action="store_false",
                   default=True, help="Fit the scattering index.")
    p.add_argument("--mcode", dest="model_code", default="000",
                   help="Three-digit evolution-function code.")
    p.add_argument("--niter", type=int, default=0,
                   help="Number of iterations after the initial fit.")
    p.add_argument("--fgauss", action="store_true", default=False,
                   help="Fix the first component as fiducial.")
    p.add_argument("--autogauss", dest="auto_gauss", type=float,
                   default=0.0,
                   help="Initial single-Gaussian width guess [rot] for a "
                        "non-interactive fit.")
    p.add_argument("--norm", dest="normalize", default=None,
                   choices=(None, "mean", "max", "prof", "rms", "abs"))
    p.add_argument("--figure", default=False,
                   help="Save a residual plot to this file name.")
    p.add_argument("--verbose", dest="quiet", action="store_false",
                   default=True)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if not args.datafile and not args.metafile:
        build_parser().error("need -d datafile or -M metafile")
    from ..pipeline.gauss import GaussPortrait

    dp = GaussPortrait(args.metafile or args.datafile,
                       joinfile=args.joinfile, quiet=args.quiet)
    if args.normalize:
        dp.normalize_portrait(args.normalize)
    datafile = args.metafile or args.datafile
    outfile = args.outfile or (datafile + ".gmodel")
    dp.make_gaussian_model(
        modelfile=args.modelfile, ref_prof=(args.nu_ref, args.bw_ref),
        tau=args.tau, fixloc=args.fixloc, fixwid=args.fixwid,
        fixamp=args.fixamp, fixscat=args.fixscat, fixalpha=args.fixalpha,
        model_code=args.model_code, niter=args.niter,
        fiducial_gaussian=args.fgauss, auto_gauss=args.auto_gauss,
        writemodel=True, outfile=outfile, writeerrfile=bool(args.errfile),
        errfile=args.errfile, model_name=args.model_name,
        residplot=args.figure or None, quiet=args.quiet)
    return 0


if __name__ == "__main__":
    sys.exit(main())
