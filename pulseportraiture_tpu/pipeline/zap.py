"""Channel-zap proposals and application (ppzap equivalent).

Parity target: reference ppzap.py:24-104.  Two paths, as in the
reference CLI: the model-less median algorithm on per-channel noise
levels, and the model-based path using GetTOAs red-chi2/S-N cuts
(pipeline/toas.get_channels_to_zap).  Where the reference only emits
`paz` shell commands, this module can also apply the zaps directly
(weight edits through the archive writer) since there is no external
PSRCHIVE to delegate to.
"""

import numpy as np

from ..io.psrfits import read_archive


def resolve_zap_device(device=None):
    """Tri-state resolution of the zap statistics lane: None follows
    config.zap_device; 'auto' = device on TPU backends (where the
    streaming lane's noise_stds already live on chip and a host
    round-trip per iteration is the only cost); True/False force."""
    from .. import config

    if device is None:
        device = getattr(config, "zap_device", "auto")
    if device == "auto":
        import jax

        return jax.default_backend() == "tpu"
    if device in (True, False):
        return bool(device)
    raise ValueError(
        f"zap_device must be True, False or 'auto', got {device!r}")


def _zap_stats_host(noise_stds):
    return float(np.median(noise_stds)), float(np.std(noise_stds))


def _zap_stats_device(noise_stds):
    """(median, std) with the MEDIAN — the expensive, sort-shaped
    statistic — through the device op ops/noise.exact_median_lastaxis
    (ROADMAP item 4 down payment).  Digit parity with the host path is
    a hard guarantee, so the std stays on host: exact_median_lastaxis
    is jnp.median bit-for-bit (f32 by construction, other dtypes fall
    through to jnp.median) and jnp.median/np.median compute identical
    order statistics, but jnp.std's reduction order is NOT np.std's —
    one flipped borderline comparison would cascade through the
    iterative cut and change the whole zap list."""
    import jax.numpy as jnp

    from ..ops.noise import exact_median_lastaxis

    x = jnp.asarray(noise_stds)
    return float(exact_median_lastaxis(x)), float(np.std(noise_stds))


def get_zap_channels(data, nstd=3, device=None):
    """Iterative median + nstd*std cut on per-channel noise levels
    (reference ppzap.py:24-54).  data: a load_data DataBunch.
    Returns [subint][channel indices].

    device: tri-state (resolve_zap_device / config.zap_device /
    PPT_ZAP_DEVICE) — route each iteration's (median, std) through the
    device op instead of host NumPy; the flagged channel lists are
    digit-identical either way (guarded by tests)."""
    stats = (_zap_stats_device if resolve_zap_device(device)
             else _zap_stats_host)
    zap_channels = []
    for isub in data.ok_isubs:
        ichans = list(np.asarray(data.ok_ichans[isub]).copy())
        zap_ichans = []
        while len(ichans):
            noise_stds = data.noise_stds[isub, 0, ichans]
            median, std = stats(noise_stds)
            bad = list(np.where(noise_stds > median + nstd * std)[0])
            if not bad:
                break
            flagged = [ichans[i] for i in bad]
            zap_ichans.extend(flagged)
            for ichan in flagged:
                ichans.remove(ichan)
        zap_channels.append(sorted(zap_ichans))
    return zap_channels


def print_paz_cmds(datafiles, zap_list, all_subs=False, modify=True,
                   outfile=None, quiet=False):
    """Emit PSRCHIVE `paz` commands for a zap list (reference
    ppzap.py:57-104) — for users whose downstream tooling is PSRCHIVE.
    Returns the command lines."""
    lines = []
    for iarch, datafile in enumerate(datafiles):
        count = sum(len(z) for z in zap_list[iarch])
        if not count:
            continue
        if modify:
            paz_outfile = datafile
        else:
            ii = datafile[::-1].find(".")
            paz_outfile = (datafile + ".zap" if ii < 0
                           else datafile[:-ii] + "zap")
            lines.append(f"paz -e zap {datafile}")
        last = ""
        for isub, bad_ichans in enumerate(zap_list[iarch]):
            for bad in bad_ichans:
                if not all_subs:
                    lines.append(
                        f"paz -m -I -z {bad} -w {isub} {paz_outfile}")
                else:
                    line = f"paz -m -z {bad} {paz_outfile}"
                    if line != last:
                        lines.append(line)
                    last = line
    if outfile is not None:
        with open(outfile, "a") as f:
            f.write("".join(line + "\n" for line in lines))
        if not quiet:
            print(f"Wrote {outfile}.")
    elif not quiet:
        for line in lines:
            print(line)
    return lines


def apply_zaps(datafile, zap_channels, all_subs=False, outfile=None,
               quiet=False):
    """Zero the weights of flagged channels directly in the archive —
    the internal replacement for shelling out to `paz`.
    zap_channels: [subint][channel indices]."""
    arch = read_archive(datafile)
    w = arch.get_weights()
    for isub, chans in enumerate(zap_channels):
        if not len(chans):
            continue
        if all_subs:
            w[:, np.asarray(chans, int)] = 0.0
        elif isub < len(w):
            w[isub, np.asarray(chans, int)] = 0.0
    arch.set_weights(w)
    arch.unload(outfile or datafile)
    if not quiet:
        print(f"Zapped {sum(map(len, zap_channels))} channel entries in "
              f"{outfile or datafile}.")
    return w
