"""Doppler factors and parallactic angles from the analytic ephemeris
(utils/ephem.py), plus their plumbing through load_data and GetTOAs.

The reference obtained both from PSRCHIVE (pplib.py:2795-2808) and
applied DM *= df, GM *= df**3 (pptoas.py:583-591); here they come from
the in-repo Earth-velocity model."""

import numpy as np
import pytest

from pulseportraiture_tpu.io import psrfits
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils import ephem
from pulseportraiture_tpu.utils.mjd import MJD

GBT = ephem.telescope_itrf("GBT")


def test_parse_ra_dec():
    assert ephem.parse_ra("12:00:00") == pytest.approx(180.0)
    assert ephem.parse_ra("06:30:00") == pytest.approx(97.5)
    assert ephem.parse_dec("-11:34:54.6") == pytest.approx(
        -(11 + 34 / 60 + 54.6 / 3600))
    assert ephem.parse_dec("45.5") == pytest.approx(45.5)
    assert ephem.parse_ra("180.0") == pytest.approx(180.0)


def test_itrf_to_geodetic_gbt():
    # published GBT site: 38.4331 N, 79.8398 W, ~824 m
    lat, lon, h = ephem.itrf_to_geodetic(GBT)
    assert np.degrees(lat) == pytest.approx(38.4331, abs=1e-3)
    assert np.degrees(lon) == pytest.approx(-79.8398, abs=1e-3)
    assert h == pytest.approx(0.824, abs=0.01)


def test_earth_velocity_magnitude_and_perihelion():
    mjds = np.arange(58849.0, 59215.0)  # calendar year 2020
    v = ephem.earth_ssb_velocity_kms(mjds)
    speed = np.linalg.norm(v, axis=-1)
    # textbook orbital speed range and mean
    assert 29.25 < speed.min() < 29.35
    assert 30.25 < speed.max() < 30.35
    assert speed.mean() == pytest.approx(29.78, abs=0.02)
    # fastest at perihelion, 2020-Jan-05 (MJD 58853)
    assert abs(mjds[np.argmax(speed)] - 58853) <= 2


def test_site_rotation_velocity():
    v = ephem.site_rotation_velocity_kms(np.array([58849.0, 58849.25]), GBT)
    speed = np.linalg.norm(v, axis=-1)
    # omega * R_earth * cos(lat) at 38.4 deg latitude ~ 0.364 km/s
    assert np.allclose(speed, 0.364, atol=0.01)
    # purely equatorial (no z component)
    assert np.all(v[:, 2] == 0.0)


def test_doppler_factor_convention_and_amplitude():
    mjds = np.arange(58849.0, 59215.0)
    # ecliptic-plane source: annual amplitude ~ v_orb/c ~ 1e-4
    df = ephem.doppler_factors(mjds, 180.0, 0.0, GBT)
    assert df.max() - 1.0 == pytest.approx(1e-4, rel=0.2)
    assert 1.0 - df.min() == pytest.approx(1e-4, rel=0.2)
    # ecliptic-pole source (RA 18h, DEC +66.56): orbital term nearly
    # vanishes -> |df-1| < 2e-5 all year
    dfp = ephem.doppler_factors(mjds, 270.0, 66.56, None)
    assert np.abs(dfp - 1.0).max() < 2e-5
    # receding observer => redshift => df > 1: pick the epoch of max
    # recession for the ecliptic source and check sign explicitly
    # (orbital-only on both sides: the site term would shift the argmax)
    df_orb = ephem.doppler_factors(mjds, 180.0, 0.0, None)
    v = ephem.earth_ssb_velocity_kms(mjds)
    n = ephem.radec_unit_vector(180.0, 0.0)
    imax = np.argmax(-(v @ n))  # most strongly receding epoch
    assert df_orb[imax] == df_orb.max() > 1.0


def test_parallactic_angle_transit_and_sign():
    lat, lon, _ = ephem.itrf_to_geodetic(GBT)
    dec = 0.0  # south of GBT zenith
    ra = 180.0
    # find transit: hour angle H = 0 -> LST == RA
    mjd0 = 58849.0
    lst0 = ephem.gmst_rad(mjd0) + lon
    dmjd = ((np.radians(ra) - lst0) % (2 * np.pi)) / (2 * np.pi) / 1.0027379
    t_transit = mjd0 + dmjd
    q = ephem.parallactic_angles(np.array([t_transit]), ra, dec, GBT)[0]
    assert abs(q) < 0.5  # zero at transit for a source south of zenith
    # sign: before transit (east) q < 0, after transit (west) q > 0
    qe = ephem.parallactic_angles(np.array([t_transit - 0.05]), ra, dec, GBT)[0]
    qw = ephem.parallactic_angles(np.array([t_transit + 0.05]), ra, dec, GBT)[0]
    assert qe < -5 and qw > 5
    assert qe == pytest.approx(-qw, abs=0.5)  # symmetric about transit


def test_parallactic_angle_known_value():
    # independent spherical-triangle evaluation at a fixed geometry:
    # sin(q) = sin(H) cos(lat) / cos(alt)
    lat, lon, _ = ephem.itrf_to_geodetic(GBT)
    ra, dec = 150.0, 20.0
    mjd = np.array([59000.123])
    H = ephem.gmst_rad(mjd) + lon - np.radians(ra)
    d = np.radians(dec)
    alt = np.arcsin(np.sin(lat) * np.sin(d)
                    + np.cos(lat) * np.cos(d) * np.cos(H))
    q_ref = np.degrees(np.arcsin(np.sin(H) * np.cos(lat) / np.cos(alt)))
    q = ephem.parallactic_angles(mjd, ra, dec, GBT)
    # arcsin form is degenerate near |q|>90; this geometry is not
    assert q[0] == pytest.approx(q_ref[0], abs=1e-6) or \
        q[0] == pytest.approx(180.0 - q_ref[0], abs=1e-6) or \
        q[0] == pytest.approx(-180.0 - q_ref[0], abs=1e-6)


PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


@pytest.fixture(scope="module")
def topo_archive(tmp_path_factory):
    """A topocentric (non-barycentred) fake archive at GBT."""
    root = tmp_path_factory.mktemp("ephem")
    model = default_test_model(1500.0)
    path = str(root / "topo.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=3, nchan=32, nbin=256,
                     nu0=1500.0, bw=800.0, tsub=60.0, dDM=3e-4,
                     start_MJD=MJD(55100, 0.3), noise_stds=0.08,
                     dedispersed=False, quiet=True, rng=7,
                     barycentred=False)
    from pulseportraiture_tpu.io import write_gmodel

    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    return path, gmodel


def test_load_data_computes_doppler_and_parangle(topo_archive):
    path, _ = topo_archive
    d = psrfits.load_data(path, quiet=True)
    df = np.asarray(d.doppler_factors)
    assert df.shape == (3,)
    assert np.all(df != 1.0)
    assert np.all(np.abs(df - 1.0) < 2e-4)  # orbital+rotation bound
    # three 60 s subints: df drifts smoothly and monotonically
    assert np.all(np.diff(df) != 0.0)
    pa = np.asarray(d.parallactic_angles)
    assert pa.shape == (3,)
    assert np.all(np.abs(pa) <= 180.0) and np.any(pa != 0.0)


def test_synthetic_default_stays_barycentred(tmp_path):
    model = default_test_model(1500.0)
    path = str(tmp_path / "bary.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=16, nbin=128,
                     start_MJD=MJD(55100, 0.3), noise_stds=0.05,
                     dedispersed=False, quiet=True, rng=3)
    arch = psrfits.read_archive(path)
    assert np.all(arch.doppler_factors() == 1.0)


def test_barycentre_site_aliases_get_unit_doppler(topo_archive):
    path, _ = topo_archive
    arch = psrfits.read_archive(path)
    assert np.all(arch.doppler_factors() != 1.0)  # GBT: computed
    for alias in ("BARYCENTER", "SSB", "@", "BAT"):
        arch.primary["TELESCOP"] = alias
        assert np.all(arch.doppler_factors() == 1.0), alias


def test_get_toas_applies_doppler_correction(topo_archive):
    from pulseportraiture_tpu.pipeline import GetTOAs

    path, gmodel = topo_archive
    gt_b = GetTOAs(path, gmodel, quiet=True)
    gt_b.get_TOAs(quiet=True)
    gt_t = GetTOAs(path, gmodel, quiet=True)
    gt_t.get_TOAs(bary=False, quiet=True)
    df = np.asarray(gt_b.doppler_fs[0])
    ok = gt_b.ok_isubs[0]
    # bary DM = topo (fitted) DM * df, per subint (pptoas.py:583-591)
    np.testing.assert_allclose(
        np.asarray(gt_b.DMs[0])[ok],
        (np.asarray(gt_t.DMs[0]) * df)[ok], rtol=1e-12)
    # and the correction actually moved the DM by ~df-1 relative
    rel = np.abs(np.asarray(gt_b.DMs[0])[ok]
                 / np.asarray(gt_t.DMs[0])[ok] - 1.0)
    assert np.all(rel > 1e-6) and np.all(rel < 2e-4)
