"""ISSUE 11: the binary timing subsystem + fleet-batched GLS.

Covers the physics layer (ELL1/BT delays + closed-form partials vs a
host-NumPy oracle, finite differences, and the small-eccentricity
analytic limit), the parfile parsing refusals (incl. the H3/H4/STIG
orthometric-Shapiro regression — those keys used to slip PAST the old
blanket refusal), the end-to-end tier-1 scenario (synthetic ELL1
binary campaign: archives -> TOAs -> .tim -> timing solution, with
injected orbital parameters recovered within errors), the fleet lane
(batched-vs-serial digit identity <= 1e-10, dispatch-count
reduction), the IPTA wiring (one traced pipeline with a pptrace
"timing" section), and the new env knobs/zap device satellite.
"""

import os

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.io.psrfits import parse_parfile
from pulseportraiture_tpu.io.tim import write_TOAs
from pulseportraiture_tpu.pipeline import GetTOAs
from pulseportraiture_tpu.synth import (default_test_model,
                                        fake_timing_campaign,
                                        make_fake_pulsar)
from pulseportraiture_tpu.timing import (TimingJob, fleet_gls_fit,
                                         parse_binary, read_tim,
                                         toas_from_measurements,
                                         wideband_gls_fit)
from pulseportraiture_tpu.timing import binary as B
from pulseportraiture_tpu.utils.mjd import MJD

SECPERDAY = 86400.0

# a mild ELL1 orbit: the synth's epoch-vs-TOA-instant evaluation bound
# (pi * A1 * P / PB ~ 6e-9 s, synth/archive.py docstring) sits far
# below the ~0.06 us TOA errors at noise_stds=0.3
BPAR = {"PSR": "J1012+5307", "P0": 0.004074, "PEPOCH": 55150.0,
        "DM": 3.139, "BINARY": "ELL1", "PB": 1.2, "A1": 0.05,
        "TASC": 55149.3, "EPS1": 2e-6, "EPS2": -1e-6}
DDMS = [3e-4, -2e-4, 5e-4, -4e-4, 1e-4]


# ---------------------------------------------------------------------------
# physics layer: delays + partials
# ---------------------------------------------------------------------------

def test_binary_jnp_matches_numpy_oracle(rng):
    dt = rng.uniform(0.0, 5e5, 128)
    args = (0.3 * SECPERDAY, 0.6, 1e-4, -5e-5, 1e-12, 1e-14,
            1e-18, -1e-18)
    d_j, parts = B.ell1_delay_and_partials(dt, *args)
    np.testing.assert_allclose(np.asarray(d_j),
                               B.ell1_delay_np(dt, *args),
                               rtol=0, atol=1e-13)
    assert np.asarray(parts).shape == (5, 128)
    argsb = (0.9 * SECPERDAY, 0.4, 0.37, 123.0, 1e-12, 1e-14)
    d_j, parts = B.bt_delay_and_partials(dt, *argsb)
    np.testing.assert_allclose(np.asarray(d_j),
                               B.bt_delay_np(dt, *argsb),
                               rtol=0, atol=1e-13)
    # jittable: same digits under jit
    import jax

    f = jax.jit(lambda d: B.ell1_delay_and_partials(d, *args)[0])
    np.testing.assert_allclose(np.asarray(f(dt)),
                               B.ell1_delay_np(dt, *args),
                               rtol=0, atol=1e-12)
    g = jax.jit(lambda d: B.bt_delay_and_partials(d, *argsb)[0])
    np.testing.assert_allclose(np.asarray(g(dt)),
                               B.bt_delay_np(dt, *argsb),
                               rtol=0, atol=1e-12)


def test_ell1_partials_match_finite_differences(rng):
    dt = rng.uniform(0.0, 4e5, 64)
    pb_s, a1, e1, e2 = 0.3 * SECPERDAY, 0.6, 1e-4, -5e-5
    _, P = B.ell1_delay_and_partials(dt, pb_s, a1, e1, e2)
    P = np.asarray(P)

    def fd(i, h):
        args = [pb_s, a1, e1, e2]
        hi, lo = list(args), list(args)
        hi[i] += h
        lo[i] -= h
        return (B.ell1_delay_np(dt, *hi)
                - B.ell1_delay_np(dt, *lo)) / (2 * h)

    np.testing.assert_allclose(P[0], fd(0, 1e-3), atol=2e-10)  # pb_s
    np.testing.assert_allclose(P[1], fd(1, 1e-6), atol=1e-9)   # a1
    np.testing.assert_allclose(P[3], fd(2, 1e-9), atol=1e-6)   # eps1
    np.testing.assert_allclose(P[4], fd(3, 1e-9), atol=1e-6)   # eps2
    # tasc partial == -d/d(dt)
    h = 1e-2
    num = (B.ell1_delay_np(dt - h, pb_s, a1, e1, e2)
           - B.ell1_delay_np(dt + h, pb_s, a1, e1, e2)) / (2 * h)
    np.testing.assert_allclose(P[2], num, atol=1e-10)


def test_bt_partials_match_finite_differences(rng):
    dt = rng.uniform(0.0, 4e5, 64)
    pb_s, a1, ecc, om = 0.3 * SECPERDAY, 0.6, 0.4, 37.0
    _, P = B.bt_delay_and_partials(dt, pb_s, a1, ecc, om)
    P = np.asarray(P)

    def fd(i, h):
        args = [pb_s, a1, ecc, om]
        hi, lo = list(args), list(args)
        hi[i] += h
        lo[i] -= h
        return (B.bt_delay_np(dt, *hi)
                - B.bt_delay_np(dt, *lo)) / (2 * h)

    np.testing.assert_allclose(P[0], fd(0, 1e-3), atol=2e-9)
    np.testing.assert_allclose(P[1], fd(1, 1e-6), atol=1e-8)
    np.testing.assert_allclose(P[3], fd(2, 1e-7), atol=1e-6)
    # om partial is per RADIAN in the raw core
    np.testing.assert_allclose(P[4] * np.pi / 180.0, fd(3, 1e-4),
                               atol=1e-10)
    h = 1e-2
    num = (B.bt_delay_np(dt - h, pb_s, a1, ecc, om)
           - B.bt_delay_np(dt + h, pb_s, a1, ecc, om)) / (2 * h)
    np.testing.assert_allclose(P[2], num, atol=1e-10)


def test_ell1_matches_bt_small_eccentricity_limit(rng):
    """Analytic limit: for e -> 0 the BT delay equals the ELL1 delay
    (eta = e sin(om), kappa = e cos(om), TASC = T0 - om*PB/2pi) up to
    the constant -(3/2)*x*eta the ELL1 convention drops (degenerate
    with the phase OFFSET) and an O(x e^2) remainder."""
    dt = rng.uniform(0.0, 5e5, 256)
    pb_s, a1, om = 0.3 * SECPERDAY, 0.6, 37.0
    om_r = np.deg2rad(om)
    for e in (1e-5, 1e-4, 1e-3):
        eta, kap = e * np.sin(om_r), e * np.cos(om_r)
        tasc_shift = om_r / (2 * np.pi) * pb_s
        d_bt = B.bt_delay_np(dt, pb_s, a1, e, om)
        d_el = (B.ell1_delay_np(dt + tasc_shift, pb_s, a1, eta, kap)
                - 1.5 * a1 * eta)
        assert np.abs(d_bt - d_el).max() < 3.0 * a1 * e * e, e


def test_bt_kepler_solver_converged(rng):
    """The fixed-iteration Newton solve satisfies Kepler's equation to
    f64 round-off across the supported eccentricity range."""
    M = rng.uniform(-20 * np.pi, 20 * np.pi, 512)
    for ecc in (0.01, 0.3, 0.7, 0.9):
        E = B._kepler_E_np(M, ecc)
        np.testing.assert_allclose(E - ecc * np.sin(E), M, rtol=0,
                                   atol=1e-10)


# ---------------------------------------------------------------------------
# parsing + refusals
# ---------------------------------------------------------------------------

def test_parse_binary_semantics():
    assert parse_binary({"F0": 300.0, "PEPOCH": 55000.0}) is None
    bp = parse_binary(parse_parfile([
        "BINARY ELL1", "PB 0.6", "A1 0.58", "TASC 50700.08162891",
        "EPS1 1.2e-7", "EPS2 -7e-8", "PBDOT 1e-13"]))
    assert bp.kind == "ELL1" and bp.param_names[2] == "TASC"
    assert bp.tref_int == 50700 and 0 < bp.tref_frac < 1
    assert bp.pbdot == 1e-13
    # BINARY line optional when the element set disambiguates
    bp = parse_binary({"PB": "67.8", "A1": "32.3", "T0": "55000.5",
                       "ECC": "0.18", "OM": "276.4"})
    assert bp.kind == "BT" and bp.ecc == 0.18
    with pytest.raises(ValueError, match="not implemented"):
        parse_binary({"BINARY": "DD", "PB": 1.0, "A1": 1.0,
                      "T0": 55000.0})
    with pytest.raises(ValueError, match="incomplete"):
        parse_binary({"BINARY": "ELL1", "PB": 1.0, "A1": 1.0})
    with pytest.raises(ValueError, match="underspecified"):
        parse_binary({"PB": 1.0, "A1": 1.0})
    with pytest.raises(ValueError, match="mixes ELL1"):
        parse_binary({"PB": 1.0, "A1": 1.0, "TASC": 55000.0,
                      "T0": 55000.0, "ECC": 0.1})
    with pytest.raises(ValueError, match="eccentricity"):
        parse_binary({"BINARY": "BT", "PB": 1.0, "A1": 1.0,
                      "T0": 55000.0, "ECC": 0.99})
    with pytest.raises(ValueError, match="PB must be positive"):
        parse_binary({"BINARY": "ELL1", "PB": -1.0, "A1": 1.0,
                      "TASC": 55000.0})


def test_gls_refuses_unmodeled_binary_keys():
    """Shapiro/relativistic keys still refuse loudly — INCLUDING the
    orthometric ELL1 parameterization H3/H4/STIG, which slipped PAST
    the old refusal list and would have been silently mistimed."""
    toas, _ = fake_timing_campaign(
        {"PSR": "X", "F0": "300.0", "PEPOCH": "55500", "DM": "10"},
        n_epochs=4, rng=1)
    base = {"PSR": "X", "F0": "300.0", "PEPOCH": "55500", "DM": "10",
            "BINARY": "ELL1", "PB": "0.6", "A1": "0.58",
            "TASC": "55499.1", "EPS1": "1e-6", "EPS2": "-5e-7"}
    for key in ("H3", "H4", "STIG", "SINI", "M2", "GAMMA", "OMDOT",
                "FB0", "SHAPMAX"):
        par = dict(base)
        par[key] = "1e-7"
        with pytest.raises(ValueError, match=key):
            wideband_gls_fit(toas, par)
    # ... and the message points at the modeled alternative
    par = dict(base)
    par["H3"] = "1e-7"
    with pytest.raises(ValueError, match="Shapiro"):
        wideband_gls_fit(toas, par)
    for key in ("H3", "H4", "STIG"):
        from pulseportraiture_tpu.timing.gls import _BINARY_KEYS

        assert key in _BINARY_KEYS


# ---------------------------------------------------------------------------
# archive-free campaigns (the fleet fixture)
# ---------------------------------------------------------------------------

def test_fake_timing_campaign_recovers_injections():
    par = {"PSR": "F", "F0": "245.4261196898081", "PEPOCH": "55500",
           "DM": "10.39", "BINARY": "ELL1", "PB": "0.60467271355",
           "A1": "0.0581817", "TASC": "55499.08162891",
           "EPS1": "1.2e-6", "EPS2": "-7e-7"}
    truth = {"PB": 0.60467271355 + 3e-9, "A1": 0.0581817 + 2e-7,
             "F0": 245.4261196898081 * (1.0 + 2e-13)}
    toas, tb = fake_timing_campaign(par, truth=truth, n_epochs=12,
                                    toas_per_epoch=3, span_days=120.0,
                                    toa_err_us=0.1, dmx=3e-4, rng=7)
    assert len(toas) == 36 and toas[0].frequency == np.inf
    res = wideband_gls_fit(toas, par)
    assert 0.5 < res.red_chi2 < 2.0, res.red_chi2
    for k in ("PB", "A1", "F0"):
        assert res.params[k] == pytest.approx(
            tb.injected[k], abs=4.0 * res.param_errs[k]), k
    # per-epoch DMX recovered
    np.testing.assert_allclose(res.dmx, tb.dmx,
                               atol=4.0 * res.dmx_errs.max())
    # BT campaigns work too
    parb = {"PSR": "G", "F0": "180.0", "PEPOCH": "55500", "DM": "5",
            "BINARY": "BT", "PB": "0.9", "A1": "0.4", "T0": "55499.4",
            "ECC": "0.15", "OM": "100.0"}
    toas, tb = fake_timing_campaign(parb, truth={"PB": 0.9 + 4e-9},
                                    n_epochs=10, toas_per_epoch=2,
                                    rng=9)
    res = wideband_gls_fit(toas, parb)
    assert res.binary.kind == "BT"
    assert res.params["PB"] == pytest.approx(
        4e-9, abs=4.0 * res.param_errs["PB"])
    with pytest.raises(ValueError, match="dmx"):
        fake_timing_campaign(par, dmx=np.zeros(3), n_epochs=4)


# ---------------------------------------------------------------------------
# tier-1 end-to-end: archives -> TOAs -> .tim -> timing solutions
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def binary_campaign(tmp_path_factory):
    """Five spin-coherent ELL1 binary epochs with injected per-epoch
    dDMs — the flagship scenario's binary variant."""
    root = tmp_path_factory.mktemp("binary_timing")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i, dDM in enumerate(DDMS):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, BPAR, outfile=path, nsub=3, nchan=32,
                         nbin=256, nu0=1500.0, bw=800.0, tsub=120.0,
                         phase=0.017, dDM=dDM,
                         start_MJD=MJD(55100 + 23 * i, 0.2 + 0.13 * i),
                         noise_stds=0.3, dedispersed=False, quiet=True,
                         rng=500 + i, spin_coherent=True)
        files.append(path)
    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    out = str(root / "binary.tim")
    write_TOAs(gt.TOA_list, outfile=out)
    return root, files, gmodel, out, gt


def test_binary_campaign_whitens_with_true_par(binary_campaign):
    _, _, _, tim, _ = binary_campaign
    toas = read_tim(tim)
    assert len(toas) == len(DDMS) * 3
    par = parse_parfile([f"{k} {v}" for k, v in BPAR.items()])
    res = wideband_gls_fit(toas, par)
    assert res.binary is not None and res.binary.kind == "ELL1"
    assert set(res.params) == {"OFFSET", "F0", "PB", "A1", "TASC",
                               "EPS1", "EPS2"}
    # white residuals at the TOA errors; the true orbit leaves every
    # fitted correction consistent with zero
    assert 0.3 < res.red_chi2 < 3.0, res.red_chi2
    assert np.all(np.abs(res.time_resids_us) < 5.0 * res.toa_errs_us)
    for k in ("PB", "A1", "TASC", "EPS1", "EPS2"):
        assert abs(res.params[k]) < 5.0 * res.param_errs[k], k
    # per-epoch DMX still recovered alongside the orbit
    for j, dDM in enumerate(DDMS):
        assert res.dmx[j] == pytest.approx(
            dDM, abs=max(4.0 * res.dmx_errs[j], 3e-5)), (j, dDM)


def test_binary_campaign_recovers_injected_orbit(binary_campaign):
    """Fit with a PERTURBED parfile: the injected dPB/dA1 offsets must
    come back as the fitted corrections, within reported errors (the
    ISSUE 11 acceptance criterion)."""
    _, _, _, tim, _ = binary_campaign
    toas = read_tim(tim)
    dPB, dA1 = 3e-6, 2e-4
    par = dict(BPAR)
    par["PB"] = BPAR["PB"] - dPB
    par["A1"] = BPAR["A1"] - dA1
    res = wideband_gls_fit(toas, par)
    assert 0.3 < res.red_chi2 < 3.0
    assert res.params["PB"] == pytest.approx(
        dPB, abs=4.0 * res.param_errs["PB"])
    assert res.params["A1"] == pytest.approx(
        dA1, abs=4.0 * res.param_errs["A1"])
    # the corrections are DETECTED, not just allowed (several sigma)
    assert res.params["PB"] > 3.0 * res.param_errs["PB"]
    assert res.params["A1"] > 3.0 * res.param_errs["A1"]
    # a wildly-wrong orbit loses phase connection LOUDLY
    bad = dict(BPAR)
    bad["A1"] = 5.0
    with pytest.raises(ValueError, match="phase connection"):
        wideband_gls_fit(toas, bad)
    res2 = wideband_gls_fit(toas, bad, allow_wraps=True)
    assert np.isfinite(res2.chi2)


def test_fleet_batched_digit_identity(binary_campaign, tmp_path):
    """The fleet lane: batched device dispatches vs the per-pulsar
    serial solve, digit-identical <= 1e-10 (acceptance criterion),
    with the dispatch-count reduction and the timing trace section."""
    _, _, _, tim, _ = binary_campaign
    jobs = []
    for i in range(5):
        par = {"PSR": f"S{i}", "F0": str(190.0 + 11 * i),
               "PEPOCH": "55500", "DM": str(12 + i)}
        if i % 2 == 0:
            par.update({"BINARY": "ELL1", "PB": str(0.5 + 0.1 * i),
                        "A1": "0.05", "TASC": "55499.2",
                        "EPS1": "1e-6", "EPS2": "-4e-7"})
        toas, _ = fake_timing_campaign(par, n_epochs=6 + (i % 2),
                                       toas_per_epoch=2, rng=50 + i)
        jobs.append(TimingJob(f"S{i}", toas, par))
    # the REAL campaign's .tim rides along as a sixth fleet member
    jobs.append(TimingJob(
        "J1012+5307", tim,
        parse_parfile([f"{k} {v}" for k, v in BPAR.items()])))

    trace = str(tmp_path / "fleet.jsonl")
    batched = fleet_gls_fit(jobs, device=True, batched=True,
                            telemetry=trace)
    serial = fleet_gls_fit(jobs, device=True, batched=False)
    host = fleet_gls_fit(jobs, device=False)
    assert batched.n_dispatches < serial.n_dispatches == len(jobs)

    def max_delta(a, b):
        worst = 0.0
        for name in a.pulsars:
            ra, rc = a.results[name], b.results[name]
            pairs = [(ra.params[k], rc.params[k], ra.param_errs[k])
                     for k in ra.params]
            pairs += list(zip(ra.dmx, rc.dmx, ra.dmx_errs))
            for va, vc, err in pairs:
                worst = max(worst, abs(va - vc)
                            / max(abs(vc), float(err), 1e-300))
        return worst

    assert max_delta(batched, serial) <= 1e-10
    assert max_delta(batched, host) <= 1e-8
    # per-pulsar results equal the single-pulsar entry point
    solo = wideband_gls_fit(read_tim(tim), parse_parfile(
        [f"{k} {v}" for k, v in BPAR.items()]))
    rb = batched.results["J1012+5307"]
    for k in solo.params:
        assert rb.params[k] == pytest.approx(
            solo.params[k], rel=1e-8,
            abs=1e-8 * max(solo.param_errs[k], 1e-300)), k

    manifest, events = telemetry.validate_trace(trace)
    fits = [e for e in events if e["type"] == "timing_fit"]
    assert fits and all(e["batched"] for e in fits)
    assert sum(e["rows"] for e in fits) == len(jobs)
    assert len(fits) == batched.n_dispatches
    ends = [e for e in events if e["type"] == "fleet_end"]
    assert ends[-1]["n_pulsars"] == len(jobs)
    assert manifest["config"]["gls_device"] == config.gls_device
    with open(os.devnull, "w") as sink:
        summary = telemetry.report(trace, file=sink)
    assert summary["n_timing_fit"] == batched.n_dispatches
    assert summary["n_timing_pulsars"] == len(jobs)
    assert summary["timing_dispatches"] == batched.n_dispatches
    assert summary["timing_pad_frac"] is not None


def test_ipta_campaign_runs_timing_stage(binary_campaign, tmp_path):
    """stream_ipta_campaign(timing_pars=): archives -> TOAs ->
    per-pulsar timing solutions in ONE traced pipeline."""
    from pulseportraiture_tpu.pipeline import IPTAJob, stream_ipta_campaign

    root, files, gmodel, tim, _ = binary_campaign
    par = parse_parfile([f"{k} {v}" for k, v in BPAR.items()])
    trace = str(tmp_path / "campaign.jsonl")
    res = stream_ipta_campaign(
        [IPTAJob("J1012+5307", files, gmodel)],
        outdir=str(tmp_path / "tims"), nsub_batch=8, quiet=True,
        telemetry=trace, timing_pars={"J1012+5307": par},
        timing_kwargs={"device": True})
    assert res.timing is not None
    assert res.timing.pulsars == ["J1012+5307"]
    tres = res.timing.results["J1012+5307"]
    assert tres.binary.kind == "ELL1"
    # same TOAs as the offline .tim path -> same solution up to the
    # .tim formatting round-trip (15-decimal MJD, 7-decimal -pp_dm,
    # 3-decimal error), which perturbs parameters at ~1e-3 of their
    # errors — far inside any scientific tolerance
    solo = wideband_gls_fit(read_tim(tim), par)
    for k in solo.params:
        assert tres.params[k] == pytest.approx(
            solo.params[k], abs=1e-2 * max(solo.param_errs[k], 1e-300)
            + 1e-14), k
    # the campaign trace carries BOTH the TOA stage and the timing
    # stage — one pipeline, one trace
    manifest, events = telemetry.validate_trace(trace)
    etypes = {e["type"] for e in events}
    for needed in ("campaign_start", "dispatch", "pulsar_done",
                   "timing_fit", "fleet_end", "campaign_end"):
        assert needed in etypes, needed
    # refusals: unknown pulsar names, and resume=True (a resumed run's
    # TOA_list covers only this run's archives — timing it would
    # silently fit a subsampled campaign)
    with pytest.raises(ValueError, match="not in jobs"):
        stream_ipta_campaign([IPTAJob("J1012+5307", files, gmodel)],
                             timing_pars={"NOPE": par}, quiet=True)
    with pytest.raises(ValueError, match="resume"):
        stream_ipta_campaign([IPTAJob("J1012+5307", files, gmodel)],
                             outdir=str(tmp_path / "tims"), resume=True,
                             timing_pars={"J1012+5307": par},
                             quiet=True)


# ---------------------------------------------------------------------------
# satellites: env knobs, zap device lane
# ---------------------------------------------------------------------------

def test_gls_zap_env_hooks(monkeypatch, capsys):
    """PPT_GLS_DEVICE / PPT_ZAP_DEVICE: registered, strict parses,
    did-you-mean on a typo."""
    old = (config.gls_device, config.zap_device)
    try:
        for name in ("PPT_GLS_DEVICE", "PPT_ZAP_DEVICE"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_GLS_DEVICE", "on")
        monkeypatch.setenv("PPT_ZAP_DEVICE", "off")
        changed = config.env_overrides()
        assert "gls_device" in changed and "zap_device" in changed
        assert config.gls_device is True
        assert config.zap_device is False
        monkeypatch.setenv("PPT_GLS_DEVICE", "auto")
        config.env_overrides()
        assert config.gls_device == "auto"
        monkeypatch.setenv("PPT_GLS_DEVICE", "fast")
        with pytest.raises(ValueError, match="PPT_GLS_DEVICE"):
            config.env_overrides()
        monkeypatch.setenv("PPT_GLS_DEVICE", "on")
        monkeypatch.setenv("PPT_ZAP_DEVICE", "2")
        with pytest.raises(ValueError, match="PPT_ZAP_DEVICE"):
            config.env_overrides()
        monkeypatch.delenv("PPT_GLS_DEVICE")
        monkeypatch.delenv("PPT_ZAP_DEVICE")
        monkeypatch.setattr(config, "_warned_unknown_ppt", set())
        monkeypatch.setenv("PPT_GLS_DEVISE", "on")  # the typo
        config.env_overrides()
        err = capsys.readouterr().err
        assert "PPT_GLS_DEVISE" in err
        assert "PPT_GLS_DEVICE" in err  # did-you-mean hint
        monkeypatch.delenv("PPT_GLS_DEVISE")
    finally:
        config.gls_device, config.zap_device = old


def test_resolve_tristate_refusals():
    from pulseportraiture_tpu.pipeline.zap import resolve_zap_device
    from pulseportraiture_tpu.timing.fleet import resolve_gls_device

    assert resolve_gls_device(True) is True
    assert resolve_gls_device(False) is False
    assert resolve_gls_device("auto") is False  # CPU test backend
    assert resolve_zap_device("auto") is False
    with pytest.raises(ValueError, match="gls_device"):
        resolve_gls_device("fast")
    with pytest.raises(ValueError, match="zap_device"):
        resolve_zap_device("fast")


def test_zap_device_digit_identity(tmp_path):
    """The median-algorithm zap proposals through the device op equal
    the host path exactly (ROADMAP item 4 down payment)."""
    from pulseportraiture_tpu.io.psrfits import load_data
    from pulseportraiture_tpu.pipeline.zap import get_zap_channels

    path = str(tmp_path / "z.fits")
    noise = np.full(64, 0.05)
    noise[[3, 17, 40, 41]] = [0.4, 0.9, 0.3, 0.25]
    make_fake_pulsar(default_test_model(1500.0),
                     {"PSR": "Z", "P0": 0.004, "PEPOCH": 55000.0,
                      "DM": 5.0},
                     outfile=path, nsub=2, nchan=64, nbin=128,
                     nu0=1500.0, bw=800.0, tsub=60.0, noise_stds=noise,
                     dedispersed=True, quiet=True, rng=11)
    d = load_data(path, dedisperse=False, tscrunch=False,
                  pscrunch=True, quiet=True)
    host = get_zap_channels(d, nstd=3, device=False)
    dev = get_zap_channels(d, nstd=3, device=True)
    assert host == dev
    assert host[0], "fixture produced no zap proposals"
    assert 3 in host[0] and 17 in host[0]
    # the f32 streaming dtype rides the bit-exact device op too
    d.noise_stds = d.noise_stds.astype(np.float32)
    assert get_zap_channels(d, device=True) == \
        get_zap_channels(d, device=False)


def test_toas_from_measurements_roundtrip(binary_campaign):
    """The in-memory TOA adapter equals the .tim write/read round-trip
    up to the 15-decimal MJD formatting."""
    _, _, _, tim, gt = binary_campaign
    direct = toas_from_measurements(gt.TOA_list)
    disk = read_tim(tim)
    assert len(direct) == len(disk)
    for a, b in zip(direct, disk):
        assert a.mjd_int == b.mjd_int
        assert a.mjd_frac == pytest.approx(b.mjd_frac, abs=1e-14)
        assert a.dm == pytest.approx(b.dm, abs=1e-6)
        assert a.error_us == pytest.approx(b.error_us, abs=1e-3)
