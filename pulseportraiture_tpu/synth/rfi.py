"""Synthetic RFI injection — the contaminated-data scenario fixture
(ISSUE 12).

Real archives arrive with two broad contamination shapes the quality
subsystem must handle:

- **narrowband tones**: a few channels carry persistent interference.
  The injector models each tone as a WHITE component (raises the
  channel's estimated noise level — what the ppzap median algorithm
  flags) plus an optional STRUCTURED low-harmonic component (corrupts
  the fit's goodness-of-fit WITHOUT inflating the power-spectrum-tail
  noise estimate proportionally — what trips the serving loop's
  quality gate and what channel weighting alone cannot absorb);
- **broadband bursts**: one subint's contiguous channel block takes a
  strong white hit (e.g. lightning, radar sweep).

Everything is seeded and returns the ground-truth channel lists, so
tier-1 can assert recovery: the injected white-component channels are
exactly what the median cut should flag.

Amplitudes are in units of the archive's own median per-channel noise
level (estimated from the decoded data with the same power-spectrum
estimator the pipeline uses), so tests specify strengths as
signal-to-background multiples rather than absolute numbers.
"""

import numpy as np

from ..io.psrfits import noise_std_ps, read_archive
from ..utils.bunch import DataBunch

__all__ = ["inject_rfi"]


def inject_rfi(path, tone_channels=(), tone_white=10.0,
               tone_structured=0.0, bursts=(), rng=None, outfile=None,
               quiet=True):
    """Inject RFI into an existing archive (in place, or to
    ``outfile``) and return the ground truth.

    tone_channels: channel indices contaminated in EVERY subint;
    tone_white / tone_structured: tone amplitudes in units of the
    archive's median per-channel noise (white: Gaussian per bin —
    elevates the noise estimate; structured: a random 2..4-cycle
    sinusoid across pulse phase — corrupts the profile at low
    harmonics, mostly invisible to the PS-tail noise estimator).
    bursts: (isub, channels, white_strength) triples — a one-subint
    broadband hit.

    Returns a DataBunch:
      zap_truth     — [subint][channels] whose NOISE level was raised
                      (what the median algorithm should recover);
      contaminated  — [subint][channels] touched by anything
                      (superset: structured-only tones corrupt fits
                      but are not noise-separable);
      noise_base    — the background noise unit used.
    """
    rng = np.random.default_rng(rng)
    arch = read_archive(path)
    amps = arch.amps  # (nsub, npol, nchan, nbin), decoded float
    nsub, npol, nchan, nbin = amps.shape
    base = float(np.median(noise_std_ps(amps)))
    if not base > 0:
        base = float(np.max(np.abs(amps))) * 1e-3 or 1.0
    phases = (np.arange(nbin) + 0.5) / nbin
    noisy = [set() for _ in range(nsub)]
    touched = [set() for _ in range(nsub)]
    for ch in tone_channels:
        ch = int(ch)
        if not 0 <= ch < nchan:
            raise ValueError(
                f"tone channel {ch} outside 0..{nchan - 1}")
        for isub in range(nsub):
            for ipol in range(npol):
                if tone_white:
                    amps[isub, ipol, ch] += (
                        tone_white * base
                        * rng.standard_normal(nbin))
                if tone_structured:
                    k = int(rng.integers(2, 5))
                    ph = float(rng.uniform())
                    amps[isub, ipol, ch] += (
                        tone_structured * base
                        * np.sin(2.0 * np.pi * (k * phases + ph)))
            if tone_white:
                noisy[isub].add(ch)
            touched[isub].add(ch)
    for isub, chans, strength in bursts:
        isub = int(isub)
        if not 0 <= isub < nsub:
            raise ValueError(f"burst subint {isub} outside 0..{nsub - 1}")
        for ch in chans:
            ch = int(ch)
            if not 0 <= ch < nchan:
                raise ValueError(
                    f"burst channel {ch} outside 0..{nchan - 1}")
            for ipol in range(npol):
                amps[isub, ipol, ch] += (
                    strength * base * rng.standard_normal(nbin))
            noisy[isub].add(ch)
            touched[isub].add(ch)
    arch.unload(outfile or path)
    if not quiet:
        n = sum(len(s) for s in touched)
        print(f"Injected RFI into {n} (subint, channel) cell(s) of "
              f"{outfile or path}.")
    return DataBunch(
        zap_truth=[sorted(s) for s in noisy],
        contaminated=[sorted(s) for s in touched],
        noise_base=base)
