"""ppwatch — the online observatory pipeline: watch a folder (and/or
a socket), time every arriving archive, and alert on anomalies.

The batch tools answer "what were the TOAs?"; ppwatch answers "what is
the pulsar doing RIGHT NOW?".  It keeps one warm ToaServer alive and
pumps three layers around it (ingest/):

  1. INGEST — a watch-folder source admits archives once complete
     (a ``<name>.done`` sentinel, or (size, mtime) unchanged for
     --stable-ms), probes each for truncation (half-written PSRFITS
     defer and retry, they never reach the loaders), and submits
     single-archive requests into the serving loop; results append to
     the streaming ``--tim`` file IN ADMISSION ORDER with durable
     sentinels — byte-identical to the one-shot driver over the
     finished corpus.  ``--listen`` additionally accepts push-style
     path announcements over the serve wire framing
     (``ingest.announce`` is the client helper).
  2. TIMING — with ``--par``, every completed archive's TOAs fold into
     an incremental GLS solution (timing/incremental.py): rank-one
     updates per TOA, with periodic full resolves (--resolve-every /
     PPT_GLS_RESOLVE_EVERY) that cross-check the running solution
     against the batch solver and refuse loudly on drift.
  3. ALERTING — CUSUM detectors on the residual stream
     (ingest/alerts.py) fire ``alert`` telemetry events for glitches
     (achromatic phase/F0 step), DM steps (the chromatic nu^-2
     signature in the wideband DM stream), and profile changes
     (persistent gof excess); ``tools/pptrace.py report`` aggregates
     them in its alerts section.

By default ppwatch runs until SIGINT/SIGTERM, then drains in-flight
work.  ``--drain`` instead exits once the folder has gone idle (every
seen archive timed, nothing in flight) — the batch-corpus mode the
tests and benchmarks drive end-to-end.
"""

import argparse
import os
import sys
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppwatch", description=__doc__.splitlines()[0])
    p.add_argument("-w", "--watch", metavar="DIR", default=None,
                   help="Directory to watch for complete archives. "
                        "At least one of -w / --listen.")
    p.add_argument("--listen", metavar="HOST:PORT", default=None,
                   help="Also accept archive-path announcements over "
                        "the serve wire framing on this endpoint "
                        "(port 0 = ephemeral, printed). [default: off]")
    p.add_argument("-m", "--model", metavar="MODEL", required=True,
                   help="Portrait template every archive fits against "
                        "(.gmodel/.spl).")
    p.add_argument("-t", "--tim", metavar="FILE", default=None,
                   help="Streaming .tim output (append-only, admission "
                        "order, durable sentinels). [default: "
                        "<watch-dir>/ppwatch.tim]")
    p.add_argument("-p", "--par", metavar="PARFILE", default=None,
                   help="Timing model: enables the incremental GLS "
                        "lane + anomaly alerting. Without it ppwatch "
                        "only streams TOAs. [default: off]")
    p.add_argument("--patterns", metavar="GLOB[,GLOB...]",
                   default="*.fits",
                   help="Candidate-file patterns in the watch folder. "
                        "[default: *.fits]")
    p.add_argument("--poll-ms", dest="poll_ms", type=float,
                   default=None, metavar="MS",
                   help="Folder poll cadence. [default: "
                        "config.ingest_poll_ms / PPT_INGEST_POLL_MS]")
    p.add_argument("--stable-ms", dest="stable_ms", type=float,
                   default=None, metavar="MS",
                   help="Size-stability window before an un-senti"
                        "neled file admits. [default: "
                        "config.ingest_stable_ms / "
                        "PPT_INGEST_STABLE_MS]")
    p.add_argument("--drain", action="store_true", default=False,
                   help="Exit once the corpus is idle (batch mode) "
                        "instead of serving until SIGINT.")
    p.add_argument("--idle-polls", dest="idle_polls", type=int,
                   default=5, metavar="N",
                   help="With --drain: consecutive empty polls that "
                        "count as idle. [default: 5]")
    p.add_argument("--resolve-every", dest="resolve_every", type=int,
                   default=None, metavar="N",
                   help="Full batch resolve + drift cross-check every "
                        "N incremental updates (0 = never). [default: "
                        "config.gls_resolve_every / "
                        "PPT_GLS_RESOLVE_EVERY]")
    p.add_argument("--cusum-k", dest="cusum_k", type=float,
                   default=None, metavar="K",
                   help="CUSUM drift allowance per sample (sigmas). "
                        "[default: config.alert_cusum_k / "
                        "PPT_ALERT_CUSUM_K]")
    p.add_argument("--cusum-h", dest="cusum_h", type=float,
                   default=None, metavar="H",
                   help="CUSUM alert threshold (accumulated sigmas). "
                        "[default: config.alert_cusum_h / "
                        "PPT_ALERT_CUSUM_H]")
    p.add_argument("--nsub-batch", dest="nsub_batch", type=int,
                   default=64, metavar="N",
                   help="Fused-bucket row count of the warm serving "
                        "loop. [default: 64]")
    p.add_argument("--max-wait-ms", dest="max_wait_ms", type=float,
                   default=None, metavar="MS",
                   help="Serving-loop deadline for partially-filled "
                        "buckets — the knob that bounds a lone "
                        "arrival's latency. [default: "
                        "config.serve_max_wait_ms]")
    p.add_argument("--telemetry", metavar="trace.jsonl", default=None,
                   help="Write the ingest/alert trace here; analyze "
                        "with tools/pptrace.py. Also via "
                        "PPT_TELEMETRY. [default: off]")
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.watch is None and args.listen is None:
        raise SystemExit("ppwatch: need -w/--watch DIR and/or "
                         "--listen HOST:PORT (an ingest pipeline "
                         "with no source has nothing to do)")
    if args.watch is not None and not os.path.isdir(args.watch):
        raise SystemExit(f"ppwatch: --watch: {args.watch!r} is not a "
                         "directory")
    if not os.path.exists(args.model):
        raise SystemExit(f"ppwatch: --model: {args.model} not found")
    if args.par is not None and not os.path.exists(args.par):
        raise SystemExit(f"ppwatch: --par: {args.par} not found")
    if args.poll_ms is not None and args.poll_ms <= 0:
        raise SystemExit("--poll-ms: must be > 0, got "
                         f"{args.poll_ms}")
    if args.stable_ms is not None and args.stable_ms < 0:
        raise SystemExit("--stable-ms: must be >= 0, got "
                         f"{args.stable_ms}")
    if args.idle_polls < 1:
        raise SystemExit("--idle-polls: must be >= 1, got "
                         f"{args.idle_polls}")
    if args.resolve_every is not None and args.resolve_every < 0:
        raise SystemExit("--resolve-every: must be >= 0, got "
                         f"{args.resolve_every}")
    if args.nsub_batch < 1:
        raise SystemExit("--nsub-batch: must be >= 1, got "
                         f"{args.nsub_batch}")
    if args.listen is not None:
        from .. import config

        try:
            config.parse_hostport(args.listen)
        except ValueError as e:
            raise SystemExit(f"ppwatch: --listen: {e}")
    patterns = tuple(s.strip() for s in args.patterns.split(",")
                     if s.strip())
    if not patterns:
        raise SystemExit("--patterns: no patterns given")
    tim_out = args.tim
    if tim_out is None:
        tim_out = os.path.join(args.watch or ".", "ppwatch.tim")

    import signal
    import threading

    from ..ingest import (AlertMonitor, IngestDriver, SocketSource,
                          WatchFolderSource)
    from ..serve import ToaServer
    from ..timing import IncrementalGLS

    sources = []
    if args.watch is not None:
        sources.append(WatchFolderSource(
            args.watch, patterns=patterns, poll_ms=args.poll_ms,
            stable_ms=args.stable_ms))
    socket_source = None
    if args.listen is not None:
        socket_source = SocketSource(listen=args.listen).start()
        sources.append(socket_source)
        print(f"ppwatch: announcements on "
              f"{socket_source.endpoint[0]}:"
              f"{socket_source.endpoint[1]}", flush=True)

    server = ToaServer(nsub_batch=args.nsub_batch,
                       max_wait_ms=args.max_wait_ms,
                       telemetry=args.telemetry, quiet=args.quiet)
    t0 = time.time()
    inc = monitor = None
    if args.par is not None:
        from ..io import parse_parfile

        par = parse_parfile(args.par)
        inc = IncrementalGLS(par, resolve_every=args.resolve_every,
                             tracer=server.tracer)
        monitor = AlertMonitor(par.get("PSR", "?"),
                               tracer=server.tracer, k=args.cusum_k,
                               h=args.cusum_h)

    def on_toas(datafile, toas):
        if inc is None:
            return
        for toa in toas:
            result = inc.update(toa)
            for alert in monitor.observe(result, toa):
                print(f"ppwatch: ALERT {alert['kind']} "
                      f"{alert['pulsar']} at MJD "
                      f"{alert['mjd']:.4f} (score "
                      f"{alert['score']:.1f})", flush=True)

    stop = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
    except ValueError:
        pass  # not the main thread (tests drive main() directly)

    with server:
        driver = IngestDriver(server, args.model, sources,
                              tim_out=tim_out, tracer=server.tracer,
                              quiet=args.quiet)
        driver.on_toas = on_toas
        if not args.quiet:
            where = " + ".join(s.name for s in sources)
            print(f"ppwatch: watching {where} -> {tim_out}"
                  + ("" if args.drain else "; Ctrl-C to drain and "
                     "exit"), flush=True)
        try:
            driver.run(stop=stop,
                       idle_polls=(args.idle_polls if args.drain
                                   else None),
                       poll_ms=args.poll_ms)
        except KeyboardInterrupt:
            driver.drain()
    if socket_source is not None:
        socket_source.stop()
    if monitor is not None:
        monitor.finish()
    stats = driver.stats()
    if not args.quiet:
        n_alerts = len(monitor.alerts) if monitor is not None else 0
        print(f"ppwatch: {stats['completed']}/{stats['admitted']} "
              f"archives timed, {stats['deferred']} deferred, "
              f"{stats['errors']} errors, {n_alerts} alert(s) in "
              f"{time.time() - t0:.2f} s", flush=True)
    return 1 if stats["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
