"""Device-resident campaign ceiling (link-free config 5).

BENCHMARKS.md round 2 claimed "a real host would stream thousands of
TOAs/s" because the tunneled link eats ~90% of campaign wall — but the
number was extrapolated.  This bench RECORDS it: the streaming driver's
fused raw-bucket program (pipeline/stream._raw_fit_fn — int16 decode,
min-window baseline, power-spectrum noise, S/N, nu_fit seeding, batched
fit, result packing) runs on DEVICE-RESIDENT data, K dispatches
back-to-back with one scalar pull, slope-timed.  That is the per-chip
compute ceiling a locally-attached host sees once IO keeps up
(prefetch threads + the raw int16 lane at ~2x effective link bytes).

The JSON line carries the per-stage breakdown from the stage-attribution
profiler (benchmarks/attrib.py: decode / stats / fit, attributed_frac
>= 0.9 is the full-attribution check), the accuracy-gate boolean, and
the same dtype/window fields bench.py carries.  The program's packed
output on this fixed seed is BIT-STABLE across releases (every
optimization to the decode/stats stages must be an exact rewrite) —
`finite_gate` plus the stored phi checksum guard that.

Knobs via env: PPT_NSUBB (bucket size, default 256), PPT_NCHAN (256),
PPT_NBIN (1024).  Prints ONE JSON line like bench.py.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def run_bench(attrib_only=False, with_attrib=True):
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    # importable API: restore the config this bench overrides (see
    # bench_scatter.run_bench)
    saved_cfg = {k: getattr(config, k) for k in
                 ("dft_precision", "cross_spectrum_dtype")}
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()  # PPT_* A/B switches win over script defaults
    try:
        return _run_bench_inner(attrib_only, with_attrib)
    finally:
        for k, v in saved_cfg.items():
            setattr(config, k, v)


def _run_bench_inner(attrib_only, with_attrib):
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu import config

    from benchmarks.attrib import campaign_stage_profile
    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.pipeline.stream import _raw_fit_fn

    NSUBB = int(os.environ.get("PPT_NSUBB", 256))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))
    P, NU0 = 0.003, 1500.0
    DT = jnp.float32

    model, freqs = bench_model(NCHAN, NBIN)

    # raw int16 bucket, host-built once, device-resident thereafter
    rng = np.random.default_rng(0)
    clean = np.asarray(model, np.float32)
    ports = clean[None] * (1.0 + 0.1 * rng.standard_normal(
        (NSUBB, 1, 1)).astype(np.float32))
    ports = ports + 0.05 * rng.standard_normal(ports.shape).astype(
        np.float32)
    lo, hi = ports.min(axis=-1), ports.max(axis=-1)
    scl = np.maximum((hi - lo) / 65000.0, 1e-12).astype(np.float32)
    offs = ((hi + lo) / 2.0).astype(np.float32)
    raw = np.clip(np.round((ports - offs[..., None]) / scl[..., None]),
                  -32767, 32767).astype(np.int16)

    flags = (True, True, False, False, False)
    from pulseportraiture_tpu.fit.portrait import resolve_harmonic_window

    hwin = resolve_harmonic_window(None, clean, NBIN)
    # seed_derotate=False: every DM guess in this bucket is zero, so
    # the CCF seed's derotation phasor is the identity — skipping it is
    # an exact rewrite (same packed output to the bit)
    fn = _raw_fit_fn(NCHAN, NBIN, flags, 25, False, "none", True,
                     "float32", x_bf16=True, nharm_eff=hwin,
                     seed_derotate=False)
    d = {
        "raw": jnp.asarray(raw), "scl": jnp.asarray(scl, DT),
        "offs": jnp.asarray(offs, DT),
        "cmask": jnp.ones((NSUBB, NCHAN), DT),
        "model": jnp.asarray(clean, DT), "freqs": jnp.asarray(freqs, DT),
        "Ps": jnp.full((NSUBB,), P, DT),
        "DMg": jnp.zeros((NSUBB,), DT),
        "turns": jnp.zeros((NSUBB, 1), DT),
    }
    jax.block_until_ready(d["raw"])

    def run():
        return fn(d["raw"], d["scl"], d["offs"], d["cmask"], d["model"],
                  d["freqs"], d["Ps"], d["DMg"], DT(-1.0), DT(0.0),
                  DT(1.0), DT(0.0), DT(0.0), d["turns"], None, None)

    r = run()
    packed = np.asarray(r)
    phi = packed[0]
    finite_gate = bool(np.all(np.isfinite(phi)))
    assert finite_gate, "non-finite phases"

    att = None
    if with_attrib or attrib_only:
        att = campaign_stage_profile(
            d["raw"], d["scl"], d["offs"], d["cmask"], d["model"],
            d["freqs"], P, np.zeros(NSUBB), hwin, flags, 25, run)
    if attrib_only:
        out = {"metric": "raw-campaign stage attribution",
               "bucket": NSUBB, "device": str(jax.devices()[0])}
        out.update(att.breakdown_ms())
        return out

    slope, single = devtime(run, lambda rr: rr)
    out = {
        "metric": f"device-resident raw campaign buckets, {NSUBB}sub x "
                  f"{NCHAN}ch x {NBIN}bin (decode+stats+fit+pack)",
        "value": round(NSUBB / slope, 1),
        "unit": "TOAs/sec",
        "bucket_latency_ms": round(single * 1e3, 1),
        "device": str(jax.devices()[0]),
        "dtype": "float32",
        "cross_spectrum_dtype": str(config.cross_spectrum_dtype),
        "harmonic_window": hwin,
        "finite_gate": finite_gate,
        # order-independent packed-output checksum on the fixed seed:
        # the raw program promises bit-stable output across releases,
        # and a drifted checksum flags the exact-rewrite contract
        "phi_checksum": float(np.asarray(phi, np.float64).sum()),
    }
    if att is not None:
        out.update(att.breakdown_ms())
        # the full-attribution gate (one-sided >= 0.9; see BENCHMARKS.md)
        out["attrib_ok"] = bool(att.check(0.9))
    return out


def main():
    print(json.dumps(run_bench()))


if __name__ == "__main__":
    main()
