from .gaussian import (
    GaussianModel,
    evolve_parameter,
    power_law_evolution,
    linear_evolution,
    gen_gaussian_profile,
    gen_gaussian_portrait,
)
from .spline import (
    pca,
    reconstruct_portrait,
    find_significant_eigvec,
    bspline_eval,
    gen_spline_portrait,
    fit_spline_curve,
    fft_resample,
)
from .wavelet import wavelet_smooth, smart_smooth, swt, iswt, get_red_chi2

__all__ = [
    "GaussianModel",
    "evolve_parameter",
    "power_law_evolution",
    "linear_evolution",
    "gen_gaussian_profile",
    "gen_gaussian_portrait",
    "pca",
    "reconstruct_portrait",
    "find_significant_eigvec",
    "bspline_eval",
    "gen_spline_portrait",
    "fit_spline_curve",
    "fft_resample",
    "wavelet_smooth",
    "smart_smooth",
    "swt",
    "iswt",
    "get_red_chi2",
]
