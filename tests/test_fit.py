"""Fit-engine validation: parameter recovery from synthetic portraits
with known injections (the reference's own verification pattern,
SURVEY.md §4), error calibration, zero-covariance frequencies, and
|dphi| parity against the independent NumPy implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.config import Dconst
from pulseportraiture_tpu.fit import (
    FitFlags,
    fit_phase_shift,
    fit_portrait,
    fit_portrait_batch,
)
from pulseportraiture_tpu.fit.reference_numpy import fit_portrait_numpy
from pulseportraiture_tpu.ops import gaussian_profile, phase_transform, rotate_profile
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003  # 3 ms pulsar
NCHAN, NBIN = 64, 1024
FREQS = jnp.asarray(np.linspace(1200.0, 1999.0, NCHAN) + 0.5)


def _fake(key, **kw):
    model = default_test_model(nu_ref=1500.0)
    kw.setdefault("noise_std", 0.05)
    return model, fake_portrait(key, model, FREQS, NBIN, P, **kw)


# --- 1-D FFTFIT ---------------------------------------------------------


def test_phase_shift_recovery(rng):
    prof = np.asarray(gaussian_profile(NBIN, 0.5, 0.03, 5.0))
    true_phi = 0.0817
    data = np.asarray(rotate_profile(jnp.asarray(prof), -true_phi))
    data = 3.0 * data + rng.normal(scale=0.02, size=NBIN)
    res = fit_phase_shift(jnp.asarray(data), jnp.asarray(prof), noise_std=0.02)
    assert abs(float(res.phase) - true_phi) < 3.0 * float(res.phase_err)
    assert abs(float(res.phase) - true_phi) < 1e-4
    assert abs(float(res.scale) - 3.0) < 3.0 * float(res.scale_err)
    assert float(res.snr) > 50.0


def test_phase_shift_error_calibration(key):
    """Fitted phase scatter should match the reported uncertainty."""
    prof = gaussian_profile(NBIN, 0.5, 0.03, 5.0)
    ntrial = 64
    keys = jax.random.split(key, ntrial)
    phases, errs = [], []
    for k in keys:
        data = prof + 0.05 * jax.random.normal(k, (NBIN,), jnp.float64)
        res = fit_phase_shift(data, prof, noise_std=0.05)
        phases.append(float(res.phase))
        errs.append(float(res.phase_err))
    z = np.asarray(phases) / np.asarray(errs)
    # z-scores should be ~N(0,1): mean ~ 0, std in [0.6, 1.6]
    assert abs(z.mean()) < 0.5
    assert 0.6 < z.std() < 1.6


# --- 2-param portrait fit ----------------------------------------------


def test_fit_portrait_phi_dm_recovery(key):
    true_phi, true_dm = 0.0513, 0.0037
    model, d = _fake(key, phi=true_phi, DM=true_dm)
    res = fit_portrait(
        d.port, d.model_port, d.noise_stds, d.freqs, P,
        fit_flags=FitFlags(phi=True, DM=True),
    )
    # re-reference the fitted phase to the injection reference
    phi_at_ref = phase_transform(
        float(res.phi), float(res.DM), float(res.nu_DM), d.nu_ref, P
    )
    assert abs(float(phi_at_ref) - true_phi) < 1e-4
    assert abs(float(res.DM) - true_dm) < 5.0 * float(res.DM_err)
    assert int(res.return_code) in (0, 1, 2)
    assert float(res.snr) > 100.0


def test_fit_portrait_zero_covariance(key):
    """At nu_DM the phi-DM covariance must vanish (the defining
    property; replaces the reference's closed-form table
    pptoaslib.py:776-950)."""
    model, d = _fake(key, phi=0.02, DM=0.002)
    res = fit_portrait(d.port, d.model_port, d.noise_stds, d.freqs, P)
    cov = np.asarray(res.covariance)
    # transform covariance to the reported nu_DM:
    # phi_ref = phi_inf + (Dconst/P) nu^-2 DM
    nu_fit = float(
        __import__(
            "pulseportraiture_tpu.ops", fromlist=["guess_fit_freq"]
        ).guess_fit_freq(d.freqs)
    )
    cD_fit = (Dconst / P) * nu_fit**-2.0
    cD_out = (Dconst / P) * float(res.nu_DM) ** -2.0
    # cov is in (phi@nu_fit, DM) coordinates; transform phi to nu_DM:
    # phi@out = phi@fit + (cD_out - cD_fit) * DM
    c2 = cov[:2, :2]
    T = np.array([[1.0, cD_out - cD_fit], [0.0, 1.0]])
    cov_out = T @ c2 @ T.T
    rho = cov_out[0, 1] / np.sqrt(cov_out[0, 0] * cov_out[1, 1])
    assert abs(rho) < 1e-3


def test_fit_portrait_error_calibration(key):
    """phi/DM pulls over noise realizations ~ N(0,1)."""
    ntrial = 32
    keys = jax.random.split(key, ntrial)
    zs_phi, zs_dm = [], []
    model = default_test_model(1500.0)
    for k in keys:
        d = fake_portrait(k, model, FREQS, NBIN, P, phi=0.01, DM=0.001,
                          noise_std=0.05)
        res = fit_portrait(d.port, d.model_port, d.noise_stds, d.freqs, P)
        phi_ref = float(
            phase_transform(float(res.phi), float(res.DM), float(res.nu_DM),
                            d.nu_ref, P)
        )
        # the phase error applies at nu_DM; transforming to nu_ref adds
        # DM-error leverage, so compare at nu_DM instead:
        true_at_nudm = float(
            phase_transform(0.01, 0.001, d.nu_ref, float(res.nu_DM), P)
        )
        zs_phi.append((float(res.phi) - true_at_nudm) / float(res.phi_err))
        zs_dm.append((float(res.DM) - 0.001) / float(res.DM_err))
    zp, zd = np.asarray(zs_phi), np.asarray(zs_dm)
    assert abs(zp.mean()) < 0.6 and 0.5 < zp.std() < 2.0
    assert abs(zd.mean()) < 0.6 and 0.5 < zd.std() < 2.0


def test_fit_portrait_scales(key):
    scales = np.linspace(0.5, 2.0, NCHAN)
    model, d = _fake(key, phi=0.01, DM=0.001, scales=scales, noise_std=0.01)
    res = fit_portrait(d.port, d.model_port, d.noise_stds, d.freqs, P)
    np.testing.assert_allclose(np.asarray(res.scales), scales, rtol=0.2)


def test_fit_portrait_masked_channels(key):
    """Zero-weight channels must not affect the fit."""
    model, d = _fake(key, phi=0.03, DM=0.002)
    mask = np.ones(NCHAN)
    mask[::7] = 0.0
    port = np.array(d.port)
    port[::7] = 1e6  # garbage in masked channels
    res = fit_portrait(
        jnp.asarray(port), d.model_port, d.noise_stds, d.freqs, P,
        chan_mask=jnp.asarray(mask),
    )
    phi_at_ref = phase_transform(
        float(res.phi), float(res.DM), float(res.nu_DM), d.nu_ref, P
    )
    assert abs(float(phi_at_ref) - 0.03) < 1e-4
    assert np.all(np.asarray(res.channel_snrs)[::7] == 0.0)


def test_fit_portrait_batch_matches_single(key):
    keys = jax.random.split(key, 4)
    model = default_test_model(1500.0)
    ds = [
        fake_portrait(k, model, FREQS, NBIN, P, phi=0.01 * (i + 1),
                      DM=0.0005 * (i + 1), noise_std=0.05)
        for i, k in enumerate(keys)
    ]
    ports = jnp.stack([d.port for d in ds])
    models = jnp.stack([d.model_port for d in ds])
    stds = jnp.stack([d.noise_stds for d in ds])
    from pulseportraiture_tpu.ops import guess_fit_freq

    nu_fit = guess_fit_freq(FREQS)
    bres = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    for i, d in enumerate(ds):
        sres = fit_portrait(d.port, d.model_port, d.noise_stds, FREQS, P,
                            nu_fit=nu_fit)
        assert abs(float(bres.phi[i]) - float(sres.phi)) < 1e-9
        assert abs(float(bres.DM[i]) - float(sres.DM)) < 1e-9


# --- parity vs the independent NumPy implementation ---------------------


def test_parity_vs_numpy_reference(key):
    model, d = _fake(key, phi=0.0421, DM=0.0029)
    from pulseportraiture_tpu.ops import guess_fit_freq

    nu_fit = float(guess_fit_freq(d.freqs))
    res_jax = fit_portrait(
        d.port, d.model_port, d.noise_stds, d.freqs, P, nu_fit=nu_fit,
        nu_out=nu_fit,
    )
    res_np = fit_portrait_numpy(
        np.asarray(d.port), np.asarray(d.model_port),
        np.asarray(d.noise_stds), np.asarray(d.freqs), P, nu_fit,
    )
    # BASELINE gate: |dphi| < 1e-4, and DM agreement
    assert abs(float(res_jax.phi) - res_np["phi"]) < 1e-4
    assert abs(float(res_jax.DM) - res_np["DM"]) < 1e-6
    # errors agree to 10%
    assert abs(float(res_jax.phi_err) / res_np["phi_err"] - 1.0) < 0.1
    assert abs(float(res_jax.DM_err) / res_np["DM_err"] - 1.0) < 0.1


@pytest.mark.parametrize("log10_tau", [True, False])
def test_fit_portrait_tau_recovery(key, log10_tau):
    """(phi, DM, tau) fit recovers an injected scattering timescale;
    FitResult.tau is linear rotations for BOTH parameterizations."""
    from pulseportraiture_tpu.fit import fit_portrait_batch

    model, pb = _fake(key, phi=0.02, DM=1e-3, tau=1.5e-4, alpha=-4.0,
                      noise_std=0.02)
    th0 = np.zeros((1, 5))
    # log10 parameterization recovers from the neutral half-bin seed;
    # the linear one needs a scat_guess-quality seed (this is why the
    # reference and the pipeline default to log10, pptoas.py:1497)
    seed = 0.5 / NBIN if log10_tau else 0.01
    th0[0, 3] = np.log10(seed) if log10_tau else seed
    th0[0, 4] = -4.0
    r = fit_portrait_batch(
        pb.port[None], pb.model_port[None], pb.noise_stds[None], FREQS, P,
        1500.0, fit_flags=FitFlags(True, True, False, True, False),
        theta0=jnp.asarray(th0), log10_tau=log10_tau, max_iter=60)
    # injected tau was 1.5e-4 s at nu_ref=1500; result is linear
    # rotations at r.nu_tau with index alpha=-4
    nu_tau = float(r.nu_tau[0])
    expect_rot = (1.5e-4 / P) * (nu_tau / 1500.0) ** -4.0
    got = float(r.tau[0])
    assert abs(got - expect_rot) / expect_rot < 0.1, (got, expect_rot)
    assert abs(float(r.phi[0]) - 0.02) < 1e-3


def test_fit_portrait_gm_recovery(key):
    """(phi, DM, GM) fit recovers an injected nu^-4 'GM' delay.

    Scale: the GM delay is Dconst^2 GM nu^-4 / P rotations, so across
    this band a measurable GM is O(1) (the fit's own GM_err here is
    ~0.01)."""
    true_gm = 2.0
    model, pb = _fake(key, phi=0.01, DM=5e-4, GM=true_gm, noise_std=0.02)
    r = fit_portrait_batch(
        pb.port[None], pb.model_port[None], pb.noise_stds[None], FREQS, P,
        1500.0, fit_flags=FitFlags(True, True, True, False, False),
        max_iter=60)
    # the fitted GM VALUE is reference-frequency independent (only phi
    # absorbs the re-referencing)
    assert float(r.GM[0]) == pytest.approx(true_gm, rel=0.05), \
        (float(r.GM[0]), float(r.GM_err[0]))
    assert abs(float(r.GM[0]) - true_gm) < 4 * float(r.GM_err[0])
    assert abs(float(r.DM[0]) - 5e-4) < 4 * float(r.DM_err[0])


def test_fit_portrait_alpha_recovery(key):
    """Full (phi, DM, tau, alpha) fit recovers the scattering index
    when the injection is strong."""
    model, pb = _fake(key, phi=0.0, DM=0.0, tau=3e-4, alpha=-4.2,
                      noise_std=0.01)
    th0 = np.zeros((1, 5))
    th0[0, 3] = np.log10(0.5 / NBIN)
    th0[0, 4] = -4.0
    r = fit_portrait_batch(
        pb.port[None], pb.model_port[None], pb.noise_stds[None], FREQS, P,
        1500.0, fit_flags=FitFlags(True, True, False, True, True),
        theta0=jnp.asarray(th0), log10_tau=True, max_iter=80)
    assert float(r.alpha[0]) == pytest.approx(-4.2, abs=0.4), \
        (float(r.alpha[0]), float(r.alpha_err[0]))
    # expected tau from the INJECTED index (-4.2), not the fitted one —
    # otherwise a compensated (tau, alpha) drift along the power-law
    # degeneracy would self-confirm
    nu_tau = float(r.nu_tau[0])
    expect_rot = (3e-4 / P) * (nu_tau / 1500.0) ** -4.2
    assert float(r.tau[0]) == pytest.approx(expect_rot, rel=0.15)


def test_fit_portrait_tau_error_calibration(key):
    """Scattering-timescale pulls over noise realizations ~ N(0,1):
    validates the log-tau error propagation through _finalize_fit."""
    ntrial = 16
    keys = jax.random.split(key, ntrial)
    model = default_test_model(1500.0)
    true_tau_s = 2e-4
    zs = []
    for k in keys:
        d = fake_portrait(k, model, FREQS, NBIN, P, tau=true_tau_s,
                          alpha=-4.0, noise_std=0.03)
        th0 = np.zeros((1, 5))
        th0[0, 3] = np.log10(0.5 / NBIN)
        th0[0, 4] = -4.0
        r = fit_portrait_batch(
            d.port[None], d.model_port[None], d.noise_stds[None], FREQS,
            P, 1500.0, fit_flags=FitFlags(True, True, False, True, False),
            theta0=jnp.asarray(th0), log10_tau=True, max_iter=60)
        nu_tau = float(r.nu_tau[0])
        expect_rot = (true_tau_s / P) * (nu_tau / 1500.0) ** -4.0
        zs.append((float(r.tau[0]) - expect_rot) / float(r.tau_err[0]))
    z = np.asarray(zs)
    # mean may carry a small discretization bias; the scatter must
    # match the reported uncertainty
    assert abs(z.mean()) < 1.5, z
    assert 0.4 < z.std() < 2.5, z


def test_fit_portrait_nan_data_poisons_errors(key):
    """Corrupted (NaN) data must yield non-finite phi_err / NaN scales
    and a failure code, not plausible finite values: the Newton loop's
    bootstrap placeholders (H=I, aux=0) are poisoned when no trip ever
    accepts."""
    from pulseportraiture_tpu.fit.portrait import fit_portrait_batch_fast

    model = default_test_model(1500.0)
    d = fake_portrait(key, model, FREQS, NBIN, P, phi=0.01, DM=1e-3,
                      noise_std=0.05)
    port = np.array(d.port)  # writable copy
    port[3, 100] = np.nan
    r = fit_portrait_batch_fast(
        jnp.asarray(port)[None], d.model_port, d.noise_stds[None], FREQS,
        P, 1500.0, max_iter=10)
    assert int(r.return_code[0]) == 3
    assert not np.isfinite(float(r.phi_err[0])) or \
        np.isnan(float(r.phi_err[0]))
    assert not np.all(np.isfinite(np.asarray(r.scales[0])))


def test_fast_path_error_calibration_bf16(key):
    """phi/DM pulls stay ~ N(0,1) through the throughput settings the
    TPU bench enables (single-pass-bf16 DFTs + bf16 cross-spectrum):
    the narrowed arithmetic must not decalibrate reported uncertainties,
    only add (sub-noise) quantization error."""
    from pulseportraiture_tpu import config
    from pulseportraiture_tpu.fit.portrait import fit_portrait_batch_fast

    old_prec, old_x = config.dft_precision, config.cross_spectrum_dtype
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    try:
        ntrial = 24
        keys = jax.random.split(key, ntrial)
        model = default_test_model(1500.0)
        ports, noises = [], []
        for k in keys:
            d = fake_portrait(k, model, FREQS, NBIN, P, phi=0.013,
                              DM=0.0007, noise_std=0.05)
            ports.append(np.asarray(d.port, np.float32))
            noises.append(np.asarray(d.noise_stds, np.float32))
        r = fit_portrait_batch_fast(
            jnp.asarray(np.stack(ports)), d.model_port.astype(jnp.float32),
            jnp.asarray(np.stack(noises)), FREQS.astype(jnp.float32),
            P, 1500.0, max_iter=25)
        zs_phi, zs_dm = [], []
        for i in range(ntrial):
            true_at_nudm = float(phase_transform(
                0.013, 0.0007, d.nu_ref, float(r.nu_DM[i]), P))
            zs_phi.append((float(r.phi[i]) - true_at_nudm)
                          / float(r.phi_err[i]))
            zs_dm.append((float(r.DM[i]) - 0.0007) / float(r.DM_err[i]))
        zp, zd = np.asarray(zs_phi), np.asarray(zs_dm)
        assert abs(zp.mean()) < 0.7 and 0.4 < zp.std() < 2.0, (zp.mean(),
                                                               zp.std())
        assert abs(zd.mean()) < 0.7 and 0.4 < zd.std() < 2.0, (zd.mean(),
                                                               zd.std())
    finally:
        config.dft_precision = old_prec
        config.cross_spectrum_dtype = old_x


@pytest.mark.parametrize("tau_s", [0.0, 5e-5, 5e-4])
def test_estimate_tau_seed_quality(key, tau_s):
    """The data-driven tau seed lands within a factor ~2 of the truth
    across a 10x tau range, returns the neutral half-bin for
    unscattered data, and cuts the scattering fit's Newton evals vs the
    neutral seed."""
    from pulseportraiture_tpu.fit.portrait import estimate_tau

    model = default_test_model(1500.0)
    d = fake_portrait(key, model, FREQS, NBIN, P, tau=tau_s, alpha=-4.0,
                      noise_std=0.03)
    est = float(estimate_tau(d.port, d.model_port, d.noise_stds))
    if tau_s == 0.0:
        assert est == pytest.approx(0.5 / NBIN)
        return
    true_rot = tau_s / P
    assert 0.4 * true_rot < est < 2.5 * true_rot, (est, true_rot)

    th_auto = np.zeros((1, 5)); th_auto[0, 3] = np.log10(est)
    th_neut = np.zeros((1, 5)); th_neut[0, 3] = np.log10(0.5 / NBIN)
    th_auto[0, 4] = th_neut[0, 4] = -4.0
    kw = dict(fit_flags=FitFlags(True, True, False, True, True),
              log10_tau=True, max_iter=60)
    r_a = fit_portrait_batch(d.port[None], d.model_port[None],
                             d.noise_stds[None], FREQS, P, 1500.0,
                             theta0=jnp.asarray(th_auto), **kw)
    r_n = fit_portrait_batch(d.port[None], d.model_port[None],
                             d.noise_stds[None], FREQS, P, 1500.0,
                             theta0=jnp.asarray(th_neut), **kw)
    # both converge to the same tau...
    assert float(r_a.tau[0]) == pytest.approx(float(r_n.tau[0]), rel=0.05)
    # ...but the seeded fit needs fewer evaluations
    assert int(r_a.nfeval[0]) <= int(r_n.nfeval[0])


def test_cgh_scatter_matches_autodiff():
    """The fused analytic (f, grad, hess) of the scattering objective
    (_cgh_scatter, one pass over X) must match autodiff of the plain
    objective — both tau parameterizations, with and without an
    instrumental response folded in."""
    import numpy as np

    from pulseportraiture_tpu.fit.portrait import (_cgh_scatter,
                                                   _chi2_prime_X,
                                                   _t_coeffs)

    rng = np.random.default_rng(7)
    nchan, nharm = 10, 33
    P, nu_fit = 0.003, 1450.0
    freqs = jnp.asarray(np.linspace(1200.0, 1700.0, nchan))
    X = jnp.asarray(rng.standard_normal((nchan, nharm))
                    + 1j * rng.standard_normal((nchan, nharm)))
    M2 = jnp.asarray(np.abs(rng.standard_normal((nchan, nharm))) + 0.1)
    ir = jnp.asarray(rng.standard_normal((nchan, nharm))
                     + 1j * 0.3 * rng.standard_normal((nchan, nharm)))
    cvec, gvec = _t_coeffs(freqs, P, nu_fit)
    cvec = cvec.astype(jnp.float64)
    gvec = gvec.astype(jnp.float64)
    for log10_tau in (False, True):
        for use_ir in (False, True):
            th = jnp.asarray([0.03, 0.002, 1e-7,
                              -2.5 if log10_tau else 0.004, -3.7])
            ir_arg = ir if use_ir else None

            def obj(t):
                return _chi2_prime_X(t, X, M2, freqs, P, nu_fit,
                                     ir_arg, log10_tau)

            f0, g0 = jax.value_and_grad(obj)(th)
            H0 = jax.hessian(obj)(th)
            if use_ir:
                Xs = X * jnp.conj(ir)
                M2s = M2 * (ir.real ** 2.0 + ir.imag ** 2.0)
            else:
                Xs, M2s = X, M2
            for compensated in (False, True):
                f1, g1, H1, (C1, S1) = _cgh_scatter(
                    th, Xs.real, Xs.imag, M2s, freqs, nu_fit,
                    cvec, gvec, log10_tau, compensated)
                assert float(jnp.abs(f1 - f0)) < 1e-9 * abs(float(f0))
                assert float(jnp.abs(g1 - g0).max()) < \
                    1e-10 * float(jnp.abs(g0).max())
                assert float(jnp.abs(H1 - H0).max()) < \
                    1e-9 * float(jnp.abs(H0).max()), (log10_tau, use_ir)
                assert C1.shape == S1.shape == (nchan,)


@pytest.mark.slow  # ~19 s two-engine parity sweep (tier-1 budget,
# r10): fast-vs-complex scattering parity stays tier-1 via
# test_stream_fast_lane_scattering_parity (driver level) and the
# directed option-lattice scatter arm; this direct IR/no-IR sweep
# rides the slow tier with the precision-floor gates below
def test_fast_scatter_lane_matches_complex_engine(key):
    """The complex-free scattering lane (fit_portrait_batch_fast with
    tau/alpha active -> fast_scatter_fit_one) must agree with the
    complex engine (fit_portrait_batch) — same objective, same Newton
    loop, different spectral front end — with and without an
    instrumental response."""
    from pulseportraiture_tpu.fit import fit_portrait_batch
    from pulseportraiture_tpu.fit.portrait import fit_portrait_batch_fast
    from pulseportraiture_tpu.ops.gaussian import (
        instrumental_response_port_FT)

    model = default_test_model(1500.0)
    nb = 3
    keys = jax.random.split(key, nb)
    ds = [fake_portrait(k, model, FREQS, NBIN, P, phi=0.01 * (i + 1),
                        DM=3e-4 * i, tau=1.2e-4, alpha=-4.0,
                        noise_std=0.02)
          for i, k in enumerate(keys)]
    ports = jnp.stack([d.port for d in ds])
    models = jnp.stack([d.model_port for d in ds])
    noise = jnp.stack([d.noise_stds for d in ds])
    th0 = np.zeros((nb, 5))
    th0[:, 3] = np.log10(0.5 / NBIN)
    th0[:, 4] = -4.0
    flags = FitFlags(True, True, False, True, False)
    ir = np.asarray(instrumental_response_port_FT(
        NBIN // 2 + 1, np.asarray(FREQS), widths=[0.25e-3 / P],
        kinds=["rect"]))
    for ir_FT in (None, ir):
        kw = dict(fit_flags=flags, theta0=jnp.asarray(th0),
                  log10_tau=True, max_iter=60)
        r_c = fit_portrait_batch(ports, models, noise, FREQS, P, 1500.0,
                                 ir_FT=None if ir_FT is None
                                 else jnp.asarray(ir_FT), **kw)
        r_f = fit_portrait_batch_fast(ports, models, noise, FREQS, P,
                                      1500.0, ir_FT=ir_FT, **kw)
        for a, b, tol in ((r_c.phi, r_f.phi, 1e-7),
                          (r_c.DM, r_f.DM, 1e-7),
                          (r_c.tau, r_f.tau, None),
                          (r_c.tau_err, r_f.tau_err, None),
                          (r_c.snr, r_f.snr, None),
                          (r_c.chi2, r_f.chi2, None)):
            a, b = np.asarray(a), np.asarray(b)
            if tol is None:
                np.testing.assert_allclose(a, b, rtol=1e-5)
            else:
                np.testing.assert_allclose(a, b, atol=tol)
    # fixed nonzero tau seed (the case the no-scatter lane must refuse)
    th_fix = np.zeros((nb, 5))
    th_fix[:, 3] = 1.2e-4 / P
    th_fix[:, 4] = -4.0
    flags_noscat = FitFlags(True, True, False, False, False)
    r_c = fit_portrait_batch(ports, models, noise, FREQS, P, 1500.0,
                             fit_flags=flags_noscat,
                             theta0=jnp.asarray(th_fix), max_iter=40)
    r_f = fit_portrait_batch_fast(ports, models, noise, FREQS, P, 1500.0,
                                  fit_flags=flags_noscat,
                                  theta0=jnp.asarray(th_fix), max_iter=40)
    np.testing.assert_allclose(np.asarray(r_c.phi), np.asarray(r_f.phi),
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(r_c.DM), np.asarray(r_f.DM),
                               atol=1e-7)


def test_pair_sum_df64_exactness():
    """The df64 pairwise reduction sums adversarially-cancelling f32
    inputs to f64 accuracy (the compensated scattering path's
    foundation)."""
    from pulseportraiture_tpu.fit.portrait import _pair_sum_df64

    rng = np.random.default_rng(11)
    big = rng.standard_normal(1500).astype(np.float32) * 1e4
    x = np.concatenate([big, -big, rng.standard_normal(1025)
                        .astype(np.float32)])
    rng.shuffle(x)
    want = float(np.sum(x.astype(np.float64)))
    got = float(_pair_sum_df64(jnp.asarray(x, jnp.float32)))
    plain = float(jnp.sum(jnp.asarray(x, jnp.float32)))
    assert abs(got - want) < 1e-3 * abs(want - plain) + 1e-4, \
        (got, want, plain)
    # batched axis semantics
    xb = jnp.asarray(np.stack([x[:1024], 2 * x[:1024]]), jnp.float32)
    gb = np.asarray(_pair_sum_df64(xb))
    wb = np.sum(np.asarray(xb, np.float64), axis=-1)
    np.testing.assert_allclose(gb, wb, rtol=1e-6, atol=1e-3)


def test_two_product_and_dot2_exactness():
    """The Dekker/Veltkamp two-product residue is EXACT (p + e equals
    the f64 product of the f32 inputs), and _dot2 beats the plain f32
    dot by orders of magnitude on an ill-conditioned dot product."""
    from pulseportraiture_tpu.fit.portrait import _dot2, _two_product

    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal(4096).astype(np.float32) * 37.5)
    b = jnp.asarray(rng.standard_normal(4096).astype(np.float32))
    p, e = _two_product(a, b)
    exact = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    np.testing.assert_array_equal(np.asarray(p, np.float64)
                                  + np.asarray(e, np.float64), exact)
    assert float(jnp.max(jnp.abs(e))) > 0.0  # residue path is live
    # ill-conditioned dot: huge cancelling pairs + a small signal
    sig = rng.standard_normal(512).astype(np.float32) * 1e-3
    a2 = np.concatenate([a, a, sig]).astype(np.float32)
    b2 = np.concatenate([b, -b, np.ones(512, np.float32)])
    want = float(np.dot(a2.astype(np.float64), b2.astype(np.float64)))
    got = float(_dot2(jnp.asarray(a2), jnp.asarray(b2)))
    plain = float(jnp.sum(jnp.asarray(a2) * jnp.asarray(b2)))
    assert abs(got - want) < 1e-3 * abs(plain - want) + 1e-6, \
        (got, want, plain)


@pytest.mark.slow  # ~28 s precision-floor gate (tier-1 budget, r10):
# the scatter lane's FUNCTIONAL coverage stays tier-1 via the tau
# recovery tests, the stream scattering-parity test, and the directed
# option-lattice subset; this sweep guards the extreme-S/N floor only
def test_f32_scatter_tau_resolution_high_snr(key):
    """The f32 scattering lane resolves tau far below the old ~0.3%
    convergence floor at extreme S/N (VERDICT round 2, weak #3): the
    tightened scatter ftol holds the systematic bias under 2.5e-4 and
    the compensated Dot2 mode reaches its ~1e-4 elementwise floor.
    (sigma_tau-limited for any realistic per-epoch tau S/N; the
    remaining floor is product/trig rounding, not accumulation.)"""
    from pulseportraiture_tpu.fit.portrait import fit_portrait_batch_fast

    model = default_test_model(1500.0)
    true_tau = 2e-4
    for comp, gate in ((False, 2.5e-4), (True, 1.6e-4)):
        rels = []
        for k in jax.random.split(key, 6):
            d = fake_portrait(k, model, FREQS, NBIN, P, tau=true_tau,
                              alpha=-4.0, noise_std=1e-4,
                              dtype=jnp.float32)
            th0 = np.zeros((1, 5), np.float32)
            th0[0, 3] = np.log10(0.5 / NBIN)
            th0[0, 4] = -4.0
            r = fit_portrait_batch_fast(
                d.port[None], d.model_port[None], d.noise_stds[None],
                FREQS.astype(jnp.float32), P, 1500.0,
                fit_flags=FitFlags(True, True, False, True, False),
                theta0=jnp.asarray(th0), log10_tau=True, max_iter=80,
                compensated=comp)
            nu_tau = float(r.nu_tau[0])
            expect = (true_tau / P) * (nu_tau / 1500.0) ** -4.0
            rels.append((float(r.tau[0]) - expect) / expect)
        rels = np.asarray(rels)
        assert np.abs(rels).max() < gate, (comp, rels)


@pytest.mark.slow  # ~24 s compensated-mode guard (tier-1 budget,
# r10): compensated mode is off by default and this guards its
# extreme-S/N bit-identity only, so it rides the slow tier with the
# other Dot2 floor gates
def test_compensated_forces_f32_cross_spectrum(key):
    """scatter_compensated=True must not be silently degraded by the
    bf16 cross-spectrum default: the fast lane forces full-precision X
    storage whenever the Dot2 reductions are on, so the result is
    bit-identical whether or not the bf16 knob is set (ADVICE r3)."""
    from pulseportraiture_tpu.fit.portrait import fast_scatter_fit_one

    model = default_test_model(1500.0)
    d = fake_portrait(key, model, FREQS, NBIN, P, tau=2e-4, alpha=-4.0,
                      noise_std=1e-4, dtype=jnp.float32)
    th0 = np.zeros(5, np.float32)
    th0[3] = np.log10(0.5 / NBIN)
    th0[4] = -4.0
    flags = FitFlags(True, True, False, True, False)
    mask = jnp.ones(NCHAN, bool)
    kw = dict(fit_flags=flags, log10_tau=True, max_iter=40,
              compensated=True)
    args = (d.port, d.model_port, d.noise_stds, mask,
            FREQS.astype(jnp.float32), P, 1500.0,
            jnp.asarray(-1.0, jnp.float32), jnp.asarray(th0))
    r_bf16 = jax.jit(
        lambda *a: fast_scatter_fit_one(*a, x_bf16=True, **kw))(*args)
    r_f32 = jax.jit(
        lambda *a: fast_scatter_fit_one(*a, x_bf16=False, **kw))(*args)
    assert float(r_bf16.tau) == float(r_f32.tau)
    assert float(r_bf16.phi) == float(r_f32.phi)


@pytest.mark.slow  # ~15 s precision-floor gate (tier-1 budget, r10);
# rides the slow tier with its real-lane twin above
def test_complex_engine_compensated_ftol(key):
    """The complex engine forwards `compensated` into the scatter ftol
    (ADVICE r3: it used to stop at the plain 1e-8 threshold, leaving a
    ~1e-4 bias the Dot2 mode exists to remove): a compensated
    high-S/N complex-engine fit must reach the same ~1.6e-4 tau floor
    as the real lane."""
    model = default_test_model(1500.0)
    true_tau = 2e-4
    rels = []
    for k in jax.random.split(key, 4):
        d = fake_portrait(k, model, FREQS, NBIN, P, tau=true_tau,
                          alpha=-4.0, noise_std=1e-4, dtype=jnp.float32)
        th0 = np.zeros((1, 5), np.float32)
        th0[0, 3] = np.log10(0.5 / NBIN)
        th0[0, 4] = -4.0
        r = fit_portrait_batch(
            d.port[None], d.model_port[None], d.noise_stds[None],
            FREQS.astype(jnp.float32), P, 1500.0,
            fit_flags=FitFlags(True, True, False, True, False),
            theta0=jnp.asarray(th0), log10_tau=True, max_iter=80,
            compensated=True)
        nu_tau = float(r.nu_tau[0])
        expect = (true_tau / P) * (nu_tau / 1500.0) ** -4.0
        rels.append((float(r.tau[0]) - expect) / expect)
    assert np.abs(np.asarray(rels)).max() < 1.6e-4, rels


def test_bf16_snr_guard_rail(capsys):
    """The bf16 cross-spectrum default warns (once) when a fit's
    channel S/N leaves the calibrated regime, and stays silent inside
    it or when bf16 storage is off (VERDICT r3 weak #5)."""
    from pulseportraiture_tpu import config
    from pulseportraiture_tpu.fit.portrait import (
        BF16_CALIBRATED_CHANNEL_SNR, _bf16_snr_warned,
        warn_bf16_high_snr)

    old = config.cross_spectrum_dtype
    try:
        config.cross_spectrum_dtype = "bfloat16"
        _bf16_snr_warned[0] = False
        # inside the calibrated regime: silent
        assert not warn_bf16_high_snr(0.5 * BF16_CALIBRATED_CHANNEL_SNR)
        # outside: fires once, prints the knob to flip
        assert warn_bf16_high_snr(10 * BF16_CALIBRATED_CHANNEL_SNR)
        assert "cross_spectrum_dtype" in capsys.readouterr().out
        # latched: no repeat spam
        assert not warn_bf16_high_snr(10 * BF16_CALIBRATED_CHANNEL_SNR)
        # quiet mode fires (returns True) without printing
        _bf16_snr_warned[0] = False
        assert warn_bf16_high_snr(10 * BF16_CALIBRATED_CHANNEL_SNR,
                                  quiet=True)
        assert capsys.readouterr().out == ""
        # bf16 off: never fires
        _bf16_snr_warned[0] = False
        config.cross_spectrum_dtype = None
        assert not warn_bf16_high_snr(10 * BF16_CALIBRATED_CHANNEL_SNR)
    finally:
        config.cross_spectrum_dtype = old
        _bf16_snr_warned[0] = False
