"""SLO engine: per-tenant latency objectives with multi-window
burn-rate tracking.

An objective is "fraction ``objective`` of a tenant's requests finish
within ``target_s`` seconds".  The error budget is ``1 - objective``;
the burn rate over a window is the window's bad-request fraction
divided by the budget, so burn 1.0 means "consuming budget exactly at
the sustainable rate" and burn 10 means "the whole budget gone in a
tenth of the period".  Following the standard multi-window alerting
pattern, a breach fires only when BOTH the short and the long window
burn above threshold — the short window gives fast detection, the long
window keeps a transient blip from paging — and it is edge-triggered:
one ``slo_breach`` event per excursion, re-armed when the short window
recovers.

Window accounting is time-bucketed ring counters (no sample
retention): constant memory per (tenant, window), O(1) per observe.
"""

import threading
import time

DEFAULT_WINDOWS = (300.0, 3600.0)  # 5 min fast-burn, 1 h slow-burn
_NBUCKETS = 30


class _WindowCounts:
    """Ring of (total, bad) counts over a sliding window."""

    def __init__(self, window_s):
        self.window_s = float(window_s)
        self.bucket_s = self.window_s / _NBUCKETS
        self._ring = [[0, 0] for _ in range(_NBUCKETS)]
        self._epoch = None  # absolute bucket index of _ring[0]'s slot

    def _advance(self, now):
        idx = int(now / self.bucket_s)
        if self._epoch is None:
            self._epoch = idx - _NBUCKETS + 1
        shift = idx - (self._epoch + _NBUCKETS - 1)
        if shift >= _NBUCKETS:
            for slot in self._ring:
                slot[0] = slot[1] = 0
            self._epoch = idx - _NBUCKETS + 1
        elif shift > 0:
            for i in range(shift):
                self._ring[(self._epoch + i) % _NBUCKETS] = [0, 0]
            self._epoch += shift
        return idx

    def add(self, now, bad):
        idx = self._advance(now)
        slot = self._ring[idx % _NBUCKETS]
        slot[0] += 1
        if bad:
            slot[1] += 1

    def rates(self, now):
        self._advance(now)
        total = sum(s[0] for s in self._ring)
        bad = sum(s[1] for s in self._ring)
        return total, bad


class SloTracker:
    """Per-tenant latency-objective tracker.

    ``targets`` maps tenant -> latency threshold in seconds; the ``*``
    key is the default applied to tenants without their own entry (the
    ``parse_tenant_spec`` convention).  Tenants with no applicable
    target are observed for attainment bookkeeping but never burn or
    breach.  ``clock`` is injectable for deterministic tests."""

    def __init__(self, targets, objective=0.99, windows=DEFAULT_WINDOWS,
                 burn_threshold=10.0, clock=time.monotonic):
        self._targets = dict(targets or {})
        self.objective = float(objective)
        self.budget = max(1.0 - self.objective, 1e-9)
        self.windows = tuple(float(w) for w in windows)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        self._good = {}      # tenant -> lifetime within-target count
        self._total = {}     # tenant -> lifetime observed count
        self._wins = {}      # tenant -> {window_s: _WindowCounts}
        self._alerting = set()

    def target_for(self, tenant):
        t = self._targets.get(tenant, self._targets.get("*"))
        return float(t) if t is not None else None

    def observe(self, tenant, latency_s, now=None):
        """Account one finished request.  Returns a breach record dict
        the first time a tenant crosses into fast-burn (both windows
        above threshold), else None."""
        target = self.target_for(tenant)
        if now is None:
            now = self._clock()
        with self._lock:
            self._total[tenant] = self._total.get(tenant, 0) + 1
            if target is None:
                return None
            bad = latency_s > target
            if not bad:
                self._good[tenant] = self._good.get(tenant, 0) + 1
            wins = self._wins.get(tenant)
            if wins is None:
                wins = self._wins[tenant] = {
                    w: _WindowCounts(w) for w in self.windows}
            for wc in wins.values():
                wc.add(now, bad)
            burns = {}
            for w, wc in wins.items():
                total, nbad = wc.rates(now)
                burns[w] = (nbad / total / self.budget) if total else 0.0
            hot = all(b >= self.burn_threshold for b in burns.values())
            if hot and tenant not in self._alerting:
                self._alerting.add(tenant)
                return {"tenant": tenant, "target_s": target,
                        "burn_short": round(burns[self.windows[0]], 3),
                        "burn_long": round(burns[self.windows[-1]], 3),
                        "window_s": self.windows[0]}
            if not hot and burns[self.windows[0]] < self.burn_threshold:
                self._alerting.discard(tenant)
        return None

    def burn_rate(self, tenant, window_s, now=None):
        if now is None:
            now = self._clock()
        with self._lock:
            wins = self._wins.get(tenant)
            if not wins or window_s not in wins:
                return 0.0
            total, nbad = wins[window_s].rates(now)
        return (nbad / total / self.budget) if total else 0.0

    def snapshot(self, now=None):
        """Per-tenant attainment + burn rates, for the metrics export."""
        if now is None:
            now = self._clock()
        out = {}
        with self._lock:
            for tenant, total in self._total.items():
                target = self.target_for(tenant)
                good = self._good.get(tenant, 0)
                ent = {"target_s": target, "total": total,
                       "good": good,
                       "attainment": round(good / total, 4) if total
                       and target is not None else None,
                       "alerting": tenant in self._alerting}
                wins = self._wins.get(tenant) or {}
                burns = {}
                for w, wc in wins.items():
                    wt, wb = wc.rates(now)
                    burns[str(int(w))] = round(
                        wb / wt / self.budget, 3) if wt else 0.0
                ent["burn"] = burns
                out[tenant] = ent
        return out
