"""On-device PSRFITS sample decode (the raw streaming lane's stage 1).

The streaming campaign drivers ship the UNDECODED DATA column payload
to the accelerator — 2-4x fewer bytes than decoded float32 on a link
that bottlenecks the whole campaign — and decode there, inside the
fused bucket program.  These kernels are the single source of truth
for that decode: the affine sample reconstruction per TFORM sample
type, and the polarization reduction to Stokes I for multi-pol
archives.  The host-side oracle is ``io/psrfits.read_archive`` /
``io/native.decode_fused`` (the FITS fuzz corpus pins its semantics);
tests assert the two lanes produce digit-identical TOAs.

Sample-type codes (``RAW_CODES``) name the wire format the host
shipped, after any endian normalization (``io/psrfits`` byteswaps
int16/float32 to native order — a memcpy pass, no float decode):

  'i16'  int16 samples        (TFORM 'I', the classic PSRFITS layout)
  'u8'   unsigned byte        (TFORM 'B')
  'i8'   signed byte          (TFORM 'B' with the FITS TZERO=-128
         convention: stored unsigned, physical = stored - 128 — the
         subtraction happens HERE, exactly, before DAT_SCL/DAT_OFFS,
         matching the host decode order bit-for-bit)
  'f32'  float32 samples      (TFORM 'E'; DAT_SCL/DAT_OFFS usually
         identity but applied uniformly anyway)
"""

import jax.numpy as jnp

from .noise import min_window_baseline

RAW_CODES = ("i16", "u8", "i8", "f32")


def affine_decode(raw, scl, offs, ft, code="i16"):
    """Decode raw samples to physical amplitudes: ``x * scl + offs``
    per channel, in dtype ``ft``, with the signed-byte bias removed
    first for code 'i8'.

    raw: (..., nchan, nbin) integer or float samples; scl/offs:
    (..., nchan) per-channel DAT_SCL/DAT_OFFS.  The operation order
    (cast, bias, scale, offset) mirrors the host decode exactly so the
    two lanes agree to the bit in matching precision."""
    if code not in RAW_CODES:
        raise ValueError(f"unknown raw sample code {code!r}; "
                         f"known: {RAW_CODES}")
    x = raw.astype(ft)
    if code == "i8":
        # stored unsigned, TZERO = -128: exact for all 0..255 values
        x = x - jnp.asarray(128.0, ft)
    return x * scl[..., None] + offs[..., None]


def decode_stokes_I(raw, scl, offs, ft, code="i16", pol_sum=False):
    """Full decode stage of the fused bucket program: affine sample
    decode, min-window baseline subtraction, and the polarization
    reduction to Stokes I.

    pol_sum=False: raw is (nb, nchan, nbin) — a single-pol payload
    (Intensity data, or the host-sliced Stokes I plane of an IQUV
    archive, which ships no extra bytes).  pol_sum=True: raw is
    (nb, 2, nchan, nbin) — the two summand pols of an AA+BB/Coherence
    archive, decoded and baselined PER POL then summed, matching the
    host lane's remove_baseline-then-pscrunch order bit-for-bit."""
    x = affine_decode(raw, scl, offs, ft, code=code)
    x = x - min_window_baseline(x)[..., None]
    if pol_sum:
        if x.ndim < 4:
            raise ValueError(
                f"pol_sum needs a (nb, 2, nchan, nbin) payload; got "
                f"shape {x.shape}")
        x = x[..., 0, :, :] + x[..., 1, :, :]
    return x
