"""Device-placement helpers."""

import contextlib
import functools

import jax


def host_compute():
    """Context manager pinning jnp ops to the host CPU backend when the
    session's default backend is an accelerator.

    Used for small offline computations that need complex arithmetic
    (rotation phasors, 1-D FFTFIT guesses, template generation): some
    TPU runtimes cannot compile complex FFTs at all, and a host round
    trip is cheaper than an accelerator dispatch for these sizes
    anyway.
    """
    if jax.default_backend() == "cpu":
        return contextlib.nullcontext()
    return jax.default_device(jax.local_devices(backend="cpu")[0])


def on_host(fn):
    """Decorator: run the whole function under host_compute().

    For offline entry points (template building, normalization, zap
    proposals) whose math uses complex phasors/FFTs — keeps them usable
    in sessions whose default backend cannot compile complex types."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with host_compute():
            return fn(*args, **kwargs)
    return wrapper
