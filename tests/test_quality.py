"""Quality subsystem (ISSUE 12): inline on-device RFI excision, the
model-based post-fit cut, the synth RFI injector's ground truth, and
the serving loop's quality-gated zap-and-refit.

The digit gates here are the subsystem's contract: device and host zap
lanes flag identical channel lists, the inline streaming lanes (raw
fused + decoded prepare-time) produce .tim bytes identical to the
offline zap-then-fit oracle (pre-computed lists through the lossless
``zap_channels=`` weight zap), and the serve loop's refit output equals
the same oracle while clean data rides through byte-identical with the
loop on or off."""

import os

import numpy as np
import pytest

from pulseportraiture_tpu import config
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.io.psrfits import load_data
from pulseportraiture_tpu.pipeline import (get_zap_channels,
                                           print_paz_cmds,
                                           stream_wideband_TOAs)
from pulseportraiture_tpu.quality import (masked_median_lastaxis,
                                          postfit_cut_device,
                                          postfit_cut_np, zap_bunch,
                                          zap_keep_device, zap_keep_np,
                                          zap_lists_from_masks)
from pulseportraiture_tpu.synth import (default_test_model, inject_rfi,
                                        make_fake_pulsar)
from pulseportraiture_tpu.telemetry import report, validate_trace

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


def _full_lists(d, lists):
    """get_zap_channels rows are indexed by TRUE subint number — the
    zap_channels= / zap_bunch format directly (this shim documents the
    invariant and pins the row count)."""
    assert len(lists) == int(d.nsub)
    return lists


@pytest.fixture(scope="module")
def rfi_corpus(tmp_path_factory):
    """3 archives: two contaminated (strong narrowband tones + one
    broadband burst), one clean — with the injector's ground truth."""
    root = tmp_path_factory.mktemp("quality")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files, truths = [], []
    # contaminated fractions stay <= ~2/32 per cut round: the 3-sigma
    # iterative cut peels the strongest interferers first (the burst's
    # 20x channels in round 1, the 8x tones in round 2) — a larger
    # fraction at one strength would inflate the std past its own
    # outliers (the classic masking breakdown, faithfully reproduced
    # by the reference algorithm)
    specs = [dict(tone_channels=[3, 11], tone_white=8.0,
                  tone_structured=60.0,
                  bursts=[(1, [20, 21], 20.0)]),
             dict(tone_channels=[7, 19], tone_white=8.0,
                  tone_structured=60.0),
             None]
    for i, spec in enumerate(specs):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                         nbin=128, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4 * (i - 1),
                         noise_stds=0.05, dedispersed=False, quiet=True,
                         rng=300 + i)
        truths.append(inject_rfi(path, rng=40 + i, **spec)
                      if spec else None)
        files.append(path)
    return files, gmodel, truths


# ---------------------------------------------------------------------------
# excision core: masked median exactness, host/device list identity
# ---------------------------------------------------------------------------

def test_masked_median_bit_exact():
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    for dtype in (np.float64, np.float32):
        x = rng.normal(1.0, 0.3, (9, 31)).astype(dtype)
        keep = rng.random((9, 31)) > 0.3
        keep[3] = False
        keep[4, :5] = True
        keep[4, 5:] = False
        mm = np.asarray(masked_median_lastaxis(jnp.asarray(x),
                                               jnp.asarray(keep)))
        for i in range(9):
            v = x[i, keep[i]]
            if v.size:
                assert mm[i] == np.median(v), (dtype, i)


def test_zap_host_matches_reference_loop():
    """The batched host oracle IS the reference per-subint loop
    (ppzap.py:24-54) vectorized — verified against a literal
    transcription of the original algorithm."""

    def reference(noise_row, ichans, nstd):
        ichans = list(ichans)
        zap = []
        while len(ichans):
            ns = noise_row[ichans]
            med, std = np.median(ns), np.std(ns)
            bad = list(np.where(ns > med + nstd * std)[0])
            if not bad:
                break
            flagged = [ichans[i] for i in bad]
            zap.extend(flagged)
            for c in flagged:
                ichans.remove(c)
        return sorted(zap)

    rng = np.random.default_rng(6)
    noise = rng.normal(1.0, 0.05, (6, 40))
    noise[0, [2, 30]] = [5.0, 3.0]
    noise[2, 11] = 9.0
    noise[4] = 1.0  # constant row: std 0, everything equal -> no flags
    keep = rng.random((6, 40)) > 0.1
    kh, _ = zap_keep_np(noise, keep, 3.0)
    lists = zap_lists_from_masks(keep, kh)
    for i in range(6):
        assert lists[i] == reference(noise[i],
                                     list(np.flatnonzero(keep[i])), 3.0)


@pytest.mark.parametrize("dtype", [np.float64, np.float32])
def test_zap_device_matches_host(dtype):
    """One batched device dispatch == the host loop, f64 and f32 —
    masks AND per-row iteration counts."""
    rng = np.random.default_rng(7)
    noise = rng.normal(1.0, 0.04, (8, 33)).astype(dtype)
    noise[0, [3, 17]] = [6.0, 3.5]
    noise[2, 5] = 9.0
    noise[5, [1, 2, 3]] = [2.0, 4.0, 8.0]  # multi-iteration cascade
    keep = rng.random((8, 33)) > 0.15
    kh, ih = zap_keep_np(noise, keep, 3.0)
    kd, idv = zap_keep_device(noise, keep, 3.0)
    assert np.array_equal(kh, kd)
    assert np.array_equal(ih, idv)
    assert ih.max() >= 2  # the cascade actually iterated


def test_zap_device_iterates_in_one_dispatch(rfi_corpus):
    """The device lane's whole iterative cut is ONE dispatch: the
    zap_propose event records n_iter >= 1 iterations that ran inside
    the compiled while_loop — no per-iteration host round-trips to
    trace (the acceptance criterion's witness)."""
    files, _, truths = rfi_corpus
    from pulseportraiture_tpu.telemetry import Tracer

    d = load_data(files[0], dedisperse=False, dededisperse=True,
                  pscrunch=True, quiet=True)
    trace = str(os.path.dirname(files[0]) + "/zap_dev.jsonl")
    with Tracer(trace, run="zap-device") as tr:
        dev = get_zap_channels(d, device=True, tracer=tr)
    host = get_zap_channels(d, device=False)
    assert dev == host
    _, evs = validate_trace(trace)
    props = [e for e in evs if e["type"] == "zap_propose"]
    assert len(props) == 1 and props[0]["device"] is True
    assert props[0]["n_iter"] >= 1


# ---------------------------------------------------------------------------
# injector ground truth
# ---------------------------------------------------------------------------

def test_injector_ground_truth_recovered(rfi_corpus):
    files, _, truths = rfi_corpus
    for f, truth in zip(files, truths):
        d = load_data(f, dedisperse=False, dededisperse=True,
                      pscrunch=True, quiet=True)
        flagged = _full_lists(d, get_zap_channels(d, device=False))
        if truth is None:
            assert sum(len(z) for z in flagged) == 0
            continue
        for isub, expect in enumerate(truth.zap_truth):
            assert set(expect) <= set(flagged[isub]), (f, isub)
            # no wild over-zapping: at most one spurious channel
            assert len(flagged[isub]) <= len(expect) + 1, (f, isub)


# ---------------------------------------------------------------------------
# streaming inline zap: digit identity vs the offline oracle
# ---------------------------------------------------------------------------

def test_stream_inline_zap_matches_offline_oracle(rfi_corpus, tmp_path):
    """Raw-lane fused inline zap == offline proposal + lossless weight
    zap + fit, byte-for-byte on .tim — and the zap actually changed
    the output vs no excision."""
    files, gmodel, _ = rfi_corpus
    zap_map = {}
    for f in files:
        d = load_data(f, dedisperse=False, dededisperse=True,
                      pscrunch=True, quiet=True)
        zap_map[f] = _full_lists(d, get_zap_channels(d, device=False))
    a = str(tmp_path / "offline.tim")
    b = str(tmp_path / "inline.tim")
    c = str(tmp_path / "none.tim")
    trace = str(tmp_path / "inline.jsonl")
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tim_out=a, zap_channels=zap_map)
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tim_out=b, zap_inline=True, telemetry=trace)
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tim_out=c)
    assert open(a, "rb").read() == open(b, "rb").read()
    assert open(a, "rb").read() != open(c, "rb").read()
    # the fused lane's zap_apply events carry the per-archive cut
    # (no event for the clean archive — zero-cut applies are not
    # emitted)
    _, evs = validate_trace(trace)
    apps = {e["datafile"]: e["n_channels"] for e in evs
            if e["type"] == "zap_apply"}
    for f in files:
        n = sum(len(z) for z in zap_map[f])
        assert apps.get(f, 0) == n
    assert files[2] not in apps
    # every raw archive's fused proposal is traced: device=True,
    # wall_s 0 by design (the cut rides the fit dispatch), n_iter from
    # the packed in-program loop counter — the no-host-round-trips
    # witness for the fused lane
    props = {e["datafile"]: e for e in evs
             if e["type"] == "zap_propose"}
    assert set(props) == set(files)
    for f in files:
        assert props[f]["device"] is True
        assert props[f]["wall_s"] == 0.0
    assert max(e["n_iter"] for e in props.values()) >= 1
    assert props[files[2]]["n_channels"] == 0


def test_stream_inline_zap_dec_lane(rfi_corpus, tmp_path):
    """tscrunch routes the decoded lane: the prepare-time cut matches
    the offline oracle too (masks zeroed before nu_fit/flag
    derivation)."""
    files, gmodel, _ = rfi_corpus
    zap_map = {}
    for f in files:
        d = load_data(f, dedisperse=False, dededisperse=True,
                      tscrunch=True, pscrunch=True, quiet=True)
        zap_map[f] = _full_lists(d, get_zap_channels(d, device=False))
    a = str(tmp_path / "offline.tim")
    b = str(tmp_path / "inline.tim")
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tscrunch=True, tim_out=a, zap_channels=zap_map)
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tscrunch=True, tim_out=b, zap_inline=True)
    assert open(a, "rb").read() == open(b, "rb").read()


def test_zap_bunch_matches_zapped_load(rfi_corpus):
    """zap_bunch's derived ok-index recomputation equals load_data's
    own derivation from zeroed weights."""
    files, _, _ = rfi_corpus
    d = load_data(files[0], dedisperse=False, dededisperse=True,
                  pscrunch=True, quiet=True)
    zap_bunch(d, [[3, 11], []])
    assert 3 not in d.ok_ichans[0] and 11 not in d.ok_ichans[0]
    assert 3 in d.ok_ichans[1]
    assert list(d.ok_isubs) == [0, 1]
    # empty a whole subint -> it drops from ok_isubs
    zap_bunch(d, [list(range(32)), []])
    assert list(d.ok_isubs) == [1]


# ---------------------------------------------------------------------------
# model-based post-fit cut
# ---------------------------------------------------------------------------

def test_zap_rows_are_true_subint_indexed(tmp_path):
    """An archive whose FIRST subint is fully weight-zapped: the
    flagged rows must still land on the true subint numbers, so
    print_paz_cmds' -w flags and apply_zaps hit the right subint
    (per-OK-subint rows — the reference's format — would shift every
    row down and zap the wrong subint)."""
    from pulseportraiture_tpu.pipeline import apply_zaps

    path = str(tmp_path / "deadsub.fits")
    noise = np.where(np.arange(32) == 6, 1.2, 0.06)
    make_fake_pulsar(default_test_model(1500.0), PAR, outfile=path,
                     nsub=2, nchan=32, nbin=128, tsub=60.0,
                     noise_stds=noise,
                     weights=np.stack([np.zeros(32), np.ones(32)]),
                     dedispersed=False, quiet=True, rng=91)
    d = load_data(path, dedisperse=False, dededisperse=True,
                  pscrunch=True, quiet=True)
    assert list(d.ok_isubs) == [1]
    zaps = get_zap_channels(d, device=False)
    assert zaps == [[], [6]]
    cmds = print_paz_cmds([path], [zaps], quiet=True)
    assert any("-z 6 -w 1" in c for c in cmds)
    assert not any("-w 0" in c for c in cmds)
    apply_zaps(path, zaps, quiet=True)
    d2 = load_data(path, dedisperse=False, dededisperse=True,
                   pscrunch=True, quiet=True)
    assert 6 not in d2.ok_ichans[1]


def test_postfit_cut_device_bit_identical():
    rng = np.random.default_rng(8)
    rchi2 = rng.uniform(0.6, 1.25, (6, 24))
    rchi2[1, [2, 3]] = [40.0, 6.0]
    rchi2[3, 9] = 2.0
    snr = rng.uniform(5.0, 60.0, (6, 24))
    snr[4, 7] = 0.05
    snr_tot = np.array([50.0, 45.0, np.nan, 55.0, 30.0, 20.0])
    okc = rng.random((6, 24)) > 0.15
    okc[5] = False
    for iterate in (True, False):
        bh = postfit_cut_np(rchi2, snr, snr_tot, okc, iterate=iterate)
        bd = postfit_cut_device(rchi2, snr, snr_tot, okc,
                                iterate=iterate)
        assert np.array_equal(bh, bd)
    assert postfit_cut_np(rchi2, snr, snr_tot, okc).any()


def test_get_channels_to_zap_device_routing(rfi_corpus):
    """GetTOAs.get_channels_to_zap routes through the shared core:
    host and device lanes agree, and the structured tone channels are
    flagged by the model-based cut."""
    from pulseportraiture_tpu.pipeline import GetTOAs

    files, gmodel, truths = rfi_corpus
    gt = GetTOAs(files[:1], gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    host = gt.get_channels_to_zap(device=False)
    dev = gt.get_channels_to_zap(device=True)
    assert host == dev
    for ch in truths[0].contaminated[0]:
        assert ch in host[0][0]


# ---------------------------------------------------------------------------
# serve: the quality-gated zap-and-refit loop
# ---------------------------------------------------------------------------

def test_serve_quality_refit_matches_oracle(rfi_corpus, tmp_path):
    """The closed loop end-to-end: contaminated archives trip the
    gate, refit once through the warm lanes, post-refit red-chi^2
    strictly improves, and the served .tim equals the offline
    zap-then-fit oracle byte-for-byte."""
    from pulseportraiture_tpu.serve import ToaServer

    files, gmodel, _ = rfi_corpus
    trace = str(tmp_path / "serve.jsonl")
    tim = str(tmp_path / "served.tim")
    srv = ToaServer(nsub_batch=8, telemetry=trace,
                    quality_refit=True).start()
    try:
        res = srv.submit(files, gmodel, tim_out=tim).result(timeout=600)
    finally:
        srv.stop()
    assert len(res.TOA_list) == 6
    _, evs = validate_trace(trace)
    refits = [e for e in evs if e["type"] == "refit"]
    refit_files = {e["datafile"] for e in refits}
    assert refit_files == set(files[:2])  # both contaminated archives
    for e in refits:
        assert e["n_channels"] > 0
        assert e["gof_after"] < e["gof_before"]  # strictly improves
        assert e["improved"] is True
    # oracle: offline host proposals through the lossless weight zap
    zap_map = {}
    for f in files[:2]:
        d = load_data(f, dedisperse=False, dededisperse=True,
                      pscrunch=True, quiet=True)
        zap_map[f] = _full_lists(d, get_zap_channels(d, device=False))
    oracle = str(tmp_path / "oracle.tim")
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tim_out=oracle, zap_channels=zap_map)
    assert open(tim, "rb").read() == open(oracle, "rb").read()
    # pptrace quality section summary keys
    summary = report(trace, file=open(os.devnull, "w"))
    assert summary["n_refit"] == 2
    assert summary["n_refit_improved"] == 2
    assert summary["refit_rate"] == 2.0  # 2 refits / 1 request
    assert summary["zap_channels_cut"] > 0
    assert summary["n_zap_propose"] == 2


def test_serve_clean_corpus_identical_loop_on_off(rfi_corpus, tmp_path):
    """Clean data never trips a gate: .tim bytes identical with the
    quality loop on vs off, zero refits."""
    from pulseportraiture_tpu.serve import ToaServer

    files, gmodel, _ = rfi_corpus
    clean = [files[2]]
    tims = []
    for qr, name in ((True, "on.tim"), (False, "off.tim")):
        tim = str(tmp_path / name)
        trace = str(tmp_path / f"{name}.jsonl")
        srv = ToaServer(nsub_batch=8, telemetry=trace,
                        quality_refit=qr).start()
        try:
            srv.submit(clean, gmodel, tim_out=tim).result(timeout=600)
        finally:
            srv.stop()
        tims.append(open(tim, "rb").read())
        _, evs = validate_trace(trace)
        assert not [e for e in evs if e["type"] == "refit"]
    assert tims[0] == tims[1]


def test_serve_refit_exactly_once_and_loud_fallback(rfi_corpus,
                                                    tmp_path, capsys):
    """A doctored gate every archive trips (max_gof ~ 0) with nothing
    to zap: every archive refits AT MOST once, falls back to the
    original fit loudly, and the request still completes with the same
    bytes as the loop-off run."""
    from pulseportraiture_tpu.serve import ToaServer

    files, gmodel, _ = rfi_corpus
    clean = [files[2]]
    tim = str(tmp_path / "forced.tim")
    ref = str(tmp_path / "ref.tim")
    trace = str(tmp_path / "forced.jsonl")
    srv = ToaServer(nsub_batch=8, telemetry=trace, quality_refit=True,
                    quality_max_gof=1e-6).start()
    try:
        srv.submit(clean, gmodel, tim_out=tim).result(timeout=600)
    finally:
        srv.stop()
    err = capsys.readouterr().err
    assert "not possible" in err  # the loud fallback
    stream_wideband_TOAs(clean, gmodel, nsub_batch=8, quiet=True,
                         tim_out=ref)
    assert open(tim, "rb").read() == open(ref, "rb").read()
    _, evs = validate_trace(trace)
    refits = [e for e in evs if e["type"] == "refit"]
    assert len(refits) == 1  # one archive, exactly one bounded pass
    assert refits[0]["n_channels"] == 0
    assert refits[0]["improved"] is False


# ---------------------------------------------------------------------------
# satellites: paz-file write mode, env hooks
# ---------------------------------------------------------------------------

def test_print_paz_cmds_write_not_append(tmp_path):
    """Reruns must not silently duplicate the command file (the old
    unconditional append mode); append stays available explicitly."""
    out = tmp_path / "paz.sh"
    zaps = [[[2, 5], []]]
    print_paz_cmds(["a.fits"], zaps, outfile=str(out), quiet=True)
    once = out.read_text()
    print_paz_cmds(["a.fits"], zaps, outfile=str(out), quiet=True)
    assert out.read_text() == once  # rerun overwrites, not duplicates
    print_paz_cmds(["a.fits"], zaps, outfile=str(out), quiet=True,
                   append=True)
    assert out.read_text() == once * 2


def test_quality_env_hooks(monkeypatch, capsys):
    """PPT_ZAP_NSTD / PPT_QUALITY_*: registered, strict parses,
    did-you-mean on a typo."""
    old = (config.zap_nstd, config.quality_refit, config.quality_max_gof,
           config.quality_min_snr)
    try:
        for name in ("PPT_ZAP_NSTD", "PPT_QUALITY_REFIT",
                     "PPT_QUALITY_MAX_GOF", "PPT_QUALITY_MIN_SNR"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_ZAP_NSTD", "4.5")
        monkeypatch.setenv("PPT_QUALITY_REFIT", "on")
        monkeypatch.setenv("PPT_QUALITY_MAX_GOF", "2.0")
        monkeypatch.setenv("PPT_QUALITY_MIN_SNR", "3.0")
        changed = config.env_overrides()
        for key in ("zap_nstd", "quality_refit", "quality_max_gof",
                    "quality_min_snr"):
            assert key in changed
        assert config.zap_nstd == 4.5
        assert config.quality_refit is True
        assert config.quality_max_gof == 2.0
        assert config.quality_min_snr == 3.0
        monkeypatch.setenv("PPT_ZAP_NSTD", "-1")
        with pytest.raises(ValueError, match="PPT_ZAP_NSTD"):
            config.env_overrides()
        monkeypatch.setenv("PPT_ZAP_NSTD", "3")
        monkeypatch.setenv("PPT_QUALITY_REFIT", "maybe")
        with pytest.raises(ValueError, match="PPT_QUALITY_REFIT"):
            config.env_overrides()
        monkeypatch.setenv("PPT_QUALITY_REFIT", "off")
        monkeypatch.setenv("PPT_QUALITY_MAX_GOF", "zero")
        with pytest.raises(ValueError, match="PPT_QUALITY_MAX_GOF"):
            config.env_overrides()
        monkeypatch.setenv("PPT_QUALITY_MAX_GOF", "1.3")
        monkeypatch.setenv("PPT_QUALITY_MIN_SNR", "-2")
        with pytest.raises(ValueError, match="PPT_QUALITY_MIN_SNR"):
            config.env_overrides()
        monkeypatch.delenv("PPT_QUALITY_MIN_SNR")
        monkeypatch.setattr(config, "_warned_unknown_ppt", set())
        monkeypatch.setenv("PPT_ZAP_NSTDS", "3")  # the typo
        config.env_overrides()
        err = capsys.readouterr().err
        assert "PPT_ZAP_NSTDS" in err and "PPT_ZAP_NSTD" in err
        monkeypatch.delenv("PPT_ZAP_NSTDS")
    finally:
        (config.zap_nstd, config.quality_refit, config.quality_max_gof,
         config.quality_min_snr) = old

# ---------------------------------------------------------------------------
# narrowband streaming inline zap (ISSUE 16 satellite)
# ---------------------------------------------------------------------------

def _nb_tim_lines(path):
    """Parse a narrowband .tim into (key, line) with key =
    (archive, subint, chan) for TOA lines and key = None for
    headers/sentinels."""
    import re

    out = []
    for line in open(path).read().splitlines(keepends=True):
        m = re.search(r"-subint (\d+)\b.*-chan (\d+)\b", line)
        if m:
            arch = line.split()[0]
            out.append(((arch, int(m.group(1)), int(m.group(2))), line))
        else:
            out.append((None, line))
    return out


def test_stream_nb_inline_zap_drops_flagged_lines(rfi_corpus, tmp_path):
    """Raw-lane narrowband inline zap: because every narrowband fit is
    per-channel independent, the zapped run's .tim must equal the
    unzapped run's MINUS exactly the offline-proposed channels' lines —
    surviving lines bit-identical, nothing else touched."""
    from pulseportraiture_tpu.pipeline.stream import (
        stream_narrowband_TOAs)

    files, gmodel, truths = rfi_corpus
    zap_map = {}
    for f in files:
        d = load_data(f, dedisperse=False, dededisperse=True,
                      pscrunch=True, quiet=True)
        zap_map[f] = _full_lists(d, get_zap_channels(d, device=False))
    a = str(tmp_path / "none.tim")
    b = str(tmp_path / "inline.tim")
    trace = str(tmp_path / "nb_inline.jsonl")
    stream_narrowband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                           tim_out=a)
    stream_narrowband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                           tim_out=b, zap_inline=True, telemetry=trace)

    def flagged(key):
        if key is None:
            return False
        arch, isub, chan = key
        for f in files:
            if arch in (f, os.path.basename(f)):
                return chan in zap_map[f][isub]
        raise AssertionError(f"unmatched tim archive {arch!r}")

    expect = [ln for key, ln in _nb_tim_lines(a) if not flagged(key)]
    got = [ln for _, ln in _nb_tim_lines(b)]
    assert got == expect
    n_zap = sum(len(z) for zs in zap_map.values() for z in zs)
    assert n_zap > 0  # the cut did something
    assert len(_nb_tim_lines(a)) - len(got) == n_zap
    # traced like the wideband lane: device proposal rides the fit
    # dispatch (wall_s 0), applies only for archives that lost lines
    _, evs = validate_trace(trace)
    props = {e["datafile"]: e for e in evs if e["type"] == "zap_propose"}
    assert set(props) == set(files)
    for e in props.values():
        assert e["device"] is True and e["wall_s"] == 0.0
    apps = {e["datafile"]: e["n_channels"] for e in evs
            if e["type"] == "zap_apply"}
    for f in files:
        n = sum(len(z) for z in zap_map[f])
        assert apps.get(f, 0) == n
    assert files[2] not in apps


def test_stream_nb_inline_zap_dec_lane(rfi_corpus, tmp_path):
    """tscrunch routes the decoded narrowband lane: the prepare-time
    cut drops the same offline-proposed channels' lines, survivors
    bit-identical."""
    from pulseportraiture_tpu.pipeline.stream import (
        stream_narrowband_TOAs)

    files, gmodel, _ = rfi_corpus
    zap_map = {}
    for f in files:
        d = load_data(f, dedisperse=False, dededisperse=True,
                      tscrunch=True, pscrunch=True, quiet=True)
        zap_map[f] = _full_lists(d, get_zap_channels(d, device=False))
    a = str(tmp_path / "none.tim")
    b = str(tmp_path / "inline.tim")
    stream_narrowband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                           tscrunch=True, tim_out=a)
    stream_narrowband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                           tscrunch=True, tim_out=b, zap_inline=True)

    def flagged(key):
        if key is None:
            return False
        arch, isub, chan = key
        for f in files:
            if arch in (f, os.path.basename(f)):
                return chan in zap_map[f][isub]
        raise AssertionError(f"unmatched tim archive {arch!r}")

    expect = [ln for key, ln in _nb_tim_lines(a) if not flagged(key)]
    got = [ln for _, ln in _nb_tim_lines(b)]
    assert got == expect
    assert len(got) < len(expect) + sum(
        len(z) for zs in zap_map.values() for z in zs)


# ---------------------------------------------------------------------------
# wideband streaming post-fit cut (ISSUE 16 satellite)
# ---------------------------------------------------------------------------

def test_stream_postfit_cut_matches_offline(rfi_corpus, tmp_path):
    """stream_wideband_TOAs(postfit_cut=True) reports the SAME
    per-archive channel lists as the offline
    GetTOAs.get_TOAs + get_channels_to_zap recipe, and the cut is
    report-only: .tim bytes identical with the knob on or off."""
    from pulseportraiture_tpu.pipeline import GetTOAs

    files, gmodel, truths = rfi_corpus
    a = str(tmp_path / "off.tim")
    b = str(tmp_path / "on.tim")
    trace = str(tmp_path / "postfit.jsonl")
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tim_out=a)
    res = stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                               tim_out=b, postfit_cut=True,
                               telemetry=trace)
    assert open(a, "rb").read() == open(b, "rb").read()

    gt = GetTOAs(files, gmodel, quiet=True)
    gt.get_TOAs(quiet=True)
    offline = gt.get_channels_to_zap(device=False)
    assert set(res.postfit_zaps) == set(files)
    for f, rows in zip(files, offline):
        zaps = res.postfit_zaps[f]
        for isub, expect in enumerate(rows):
            assert zaps.get(isub, []) == sorted(expect), (f, isub)
    # the structured tones are model-detected on the contaminated
    # archives; the clean archive reports nothing
    n0 = sum(len(z) for z in res.postfit_zaps[files[0]].values())
    assert n0 > 0
    assert sum(len(z) for z in res.postfit_zaps[files[2]].values()) == 0
    # proposal events ride the fit dispatch, one per archive
    _, evs = validate_trace(trace)
    props = {e["datafile"]: e for e in evs if e["type"] == "zap_propose"}
    assert set(props) == set(files)
    for e in props.values():
        assert e["device"] is True and e["wall_s"] == 0.0
