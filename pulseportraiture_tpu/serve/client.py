"""Thin in-process client of a :class:`~.server.ToaServer`.

The server's ``submit`` is already thread-safe; this wrapper is the
blocking convenience most callers want — submit-and-wait with the
one-shot driver's return shape — plus a fan-out helper for scripted
multi-request clients (benchmarks, the ppserve CLI).  A remote
transport would implement this same two-call surface over a socket;
everything below it (queueing, coalescing, demux) is transport-
agnostic.
"""

__all__ = ["ToaClient", "collect_results"]


def collect_results(handles, timeout=None, return_errors=False):
    """Collect every handle's result, in order, waiting on ALL of
    them before anything raises — one failed request must never
    strand its siblings mid-flight.  With ``return_errors=True`` a
    failed slot holds its exception object; otherwise the first
    failure re-raises after the full collection pass.  Shared by
    ToaClient.map and ToaRouter.map (both hand out result(timeout)
    handles), so the two fan-out surfaces cannot drift."""
    out = []
    for h in handles:
        try:
            out.append(h.result(timeout))
        except Exception as e:
            out.append(e)
    if not return_errors:
        for r in out:
            if isinstance(r, Exception):
                raise r
    return out


class ToaClient:
    """Blocking client: each call is one request against the shared
    warm server; concurrent callers coalesce into shared fused
    dispatches whenever they use the same template and options."""

    def __init__(self, server):
        self.server = server

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               **options):
        """Non-blocking: returns the ServeRequest handle (may raise
        ServeRejected — the backpressure signal)."""
        return self.server.submit(datafiles, modelfile,
                                  tim_out=tim_out, name=name,
                                  **options)

    def get_TOAs(self, datafiles, modelfile, timeout=None,
                 tim_out=None, name=None, **options):
        """Submit and wait: returns the per-request DataBunch
        (TOA_list, order, DM0s, DeltaDM_means/errs, tim_out), the same
        result shape as stream_wideband_TOAs."""
        return self.submit(datafiles, modelfile, tim_out=tim_out,
                           name=name, **options).result(timeout)

    def map(self, specs, timeout=None, return_errors=False):
        """Submit many requests, then wait for all: ``specs`` is a
        sequence of (datafiles, modelfile[, kwargs-dict]) tuples;
        returns the results in spec order.  Submission errors
        (ServeRejected) raise immediately — before any wait — so a
        load-shedding server is visible at the call site.

        A request that fails MID-BATCH (a bad option set, a broken
        archive) is isolated: every sibling handle is still collected
        before anything raises, so one failure never strands the rest
        of the batch mid-flight.  With ``return_errors=True`` the
        failed slot holds its exception object instead of raising —
        the fan-out caller decides per request."""
        handles = []
        for spec in specs:
            datafiles, modelfile = spec[0], spec[1]
            kwargs = dict(spec[2]) if len(spec) > 2 else {}
            handles.append(self.submit(datafiles, modelfile, **kwargs))
        return collect_results(handles, timeout, return_errors)
