"""The ingest driver: sources -> warm ToaServer -> ordered streaming
``.tim`` (ISSUE 18 tentpole, layer 1).

No new executor: every admitted archive becomes ONE single-archive
request into the existing serving loop, whose continuous-batching
deadline (config.serve_max_wait_ms / flush_stale) already solves the
latency-vs-occupancy problem a trickle of archives poses — single
arrivals launch partial buckets within the deadline, bursts coalesce.
The driver's own jobs are the observatory-specific edges:

* ADMISSION SAFETY — every candidate passes io.scan_fits before it
  touches the loaders.  A truncated file raises the typed
  ``TruncatedFits`` (retryable) and the driver DEFERS it back to its
  source (retry once stable again) instead of poisoning the source or
  the request stream.
* BACKPRESSURE — a full admission queue raises
  ``ServeRejected(retryable=True)``; the driver defers the archive
  and re-admits on a later poll, so a slow fit lane throttles the
  folder scan instead of growing an unbounded queue.
* ORDERED DURABLE OUTPUT — results append to the streaming per-pulsar
  ``.tim`` strictly IN ADMISSION ORDER, each archive's TOA lines
  followed by the same durable completion sentinel the one-shot
  driver writes: the streamed file is byte-identical to running the
  finished corpus through ``stream_wideband_TOAs`` offline, and a
  restart can resume from the sentinels.

Telemetry: ``ingest_admit`` per admission (wait_s = discovery ->
admission, the latency bench_ingest gates), ``ingest_skip`` per
deferral with the reason ('truncated' | 'backpressure' | 'error').
"""

import os
import time

from .. import config
from ..io.fitsio import TruncatedFits, scan_fits
from ..io.tim import write_TOAs
from ..pipeline.stream import _DONE_PREFIX
from ..serve.queue import ServeRejected
from ..telemetry import NULL_TRACER, finite, log

__all__ = ["IngestDriver"]


class IngestDriver:
    """Pump archives from ingest sources through a warm ToaServer.

    server:    a STARTED serve.ToaServer (the driver never owns it).
    modelfile: the template every admitted archive fits against.
    sources:   iterable of WatchFolderSource / SocketSource.
    tim_out:   streaming .tim path (append-only, admission order,
               durable sentinels).  None = keep results in memory only.
    on_toas:   optional callback(datafile, tim_toas) fired per
               completed archive IN ADMISSION ORDER with the archive's
               timing.tim.TimTOA list (parsed from the exact lines
               appended to tim_out) — the hook ppwatch chains the
               incremental GLS + alert monitor onto.
    options:   make_wideband_lane fit options, passed to every submit
               (requests sharing (modelfile, options) share a lane and
               coalesce).
    """

    def __init__(self, server, modelfile, sources, tim_out=None,
                 tracer=None, quiet=False, **options):
        self.server = server
        self.modelfile = str(modelfile)
        self.sources = list(sources)
        if not self.sources:
            raise ValueError("IngestDriver: no sources")
        self.tim_out = tim_out
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.quiet = quiet
        self.options = dict(options)
        self.on_toas = None
        # admission-ordered FIFO of dicts:
        #   {'datafile', 'request', 'source'}
        self._inflight = []
        self._seq = 0
        self.n_admitted = 0
        self.n_completed = 0
        self.n_deferred = 0
        self.n_errors = 0
        if tim_out and not os.path.exists(tim_out):
            open(tim_out, "a").close()

    # -- admission ------------------------------------------------------

    def _skip(self, source, path, reason):
        if self.tracer.enabled:
            self.tracer.emit("ingest_skip", datafile=path,
                             source=source.name, reason=reason)

    def _admit_one(self, source, path, wait_s):
        """Probe + submit one candidate.  Returns True when admitted,
        False when deferred back to the source."""
        try:
            scan_fits(path)
        except TruncatedFits as e:
            # half-written (or torn) file: retry once stable again —
            # the typed error is the signal this is a WAIT, not a
            # failure; a file torn forever just keeps deferring and
            # never reaches the loaders
            self.n_deferred += 1
            source.defer(path)
            self._skip(source, path, "truncated")
            log(f"ingest: deferred truncated {path}: {e}",
                quiet=self.quiet, tracer=None)
            return False
        except (OSError, ValueError) as e:
            # unreadable / structurally-bad candidate: poisoning one
            # file must not poison the source, so skip it for good
            self.n_errors += 1
            self._skip(source, path, "error")
            log(f"ingest: skipped unreadable {path}: {e}",
                level="warn", quiet=self.quiet, tracer=None)
            return True  # consumed (never retried)
        try:
            req = self.server.submit(
                [path], self.modelfile,
                name=f"ingest{self._seq}", **self.options)
        except ServeRejected as e:
            if not e.retryable:
                raise
            # backpressure: the serve queue is full — throttle the
            # source instead of queueing unboundedly here
            self.n_deferred += 1
            source.defer(path)
            self._skip(source, path, "backpressure")
            return False
        self._seq += 1
        self.n_admitted += 1
        self._inflight.append({"datafile": path, "request": req,
                               "source": source})
        if self.tracer.enabled:
            self.tracer.emit("ingest_admit", datafile=path,
                             source=source.name,
                             wait_s=finite(wait_s, 6))
        return True

    # -- ordered collection --------------------------------------------

    def _append_result(self, datafile, result):
        """Append one archive's TOA lines + sentinel to the streaming
        .tim (the server's own demux idiom — byte-identical lines) and
        fire on_toas with the parsed TimTOAs."""
        toas = list(result.TOA_list)
        if self.tim_out:
            write_TOAs(toas, outfile=self.tim_out, append=True)
            with open(self.tim_out, "a") as fh:
                fh.write(_DONE_PREFIX + os.path.abspath(datafile)
                         + "\n")
        if self.on_toas is not None:
            from ..io.tim import toa_string
            from ..timing.tim import read_tim

            lines = [toa_string(t) for t in toas]
            self.on_toas(datafile, read_tim(lines))

    def _collect_ready(self, block_s=0.0):
        """Drain completed HEAD-of-queue requests (admission order; a
        later-finished earlier archive blocks later ones — ordering is
        the contract).  Returns the number collected."""
        n = 0
        deadline = time.monotonic() + block_s
        while self._inflight:
            head = self._inflight[0]
            timeout = max(0.0, deadline - time.monotonic())
            if not head["request"].wait(timeout):
                break
            self._inflight.pop(0)
            try:
                result = head["request"].result(timeout=0.0)
            except Exception as e:
                # the fit failed server-side; the archive is consumed
                # (a deterministic failure would defer forever)
                self.n_errors += 1
                self._skip(head["source"], head["datafile"], "error")
                log(f"ingest: request for {head['datafile']} failed: "
                    f"{e}", level="warn", quiet=self.quiet, tracer=None)
                continue
            self._append_result(head["datafile"], result)
            self.n_completed += 1
            n += 1
        return n

    # -- the loop -------------------------------------------------------

    def run_once(self):
        """One poll cycle over every source + one collection pass.
        Returns the number of archives admitted this cycle."""
        admitted = 0
        for source in self.sources:
            for path, wait_s in source.poll():
                if self._admit_one(source, path, wait_s):
                    admitted += 1
        self._collect_ready()
        return admitted

    def drain(self, timeout=None):
        """Block until every in-flight request has been collected into
        the ordered .tim (up to ``timeout`` seconds).  Returns True
        when fully drained."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while self._inflight:
            block = (1.0 if deadline is None
                     else min(1.0, deadline - time.monotonic()))
            if block <= 0:
                return False
            self._collect_ready(block_s=block)
        return True

    def run(self, stop=None, idle_polls=None, poll_ms=None):
        """Poll until ``stop`` (a threading.Event) is set — or, with
        ``idle_polls``, until that many consecutive polls admitted
        nothing, completed nothing, and left nothing in flight (the
        batch-corpus mode ppwatch --drain uses).  Drains in-flight
        work before returning."""
        poll_s = (config.ingest_poll_ms if poll_ms is None
                  else float(poll_ms)) * 1e-3
        idle = 0
        while True:
            if stop is not None and stop.is_set():
                break
            before = self.n_completed
            admitted = self.run_once()
            active = (admitted or self.n_completed != before
                      or self._inflight
                      or any(s.pending() for s in self.sources))
            idle = 0 if active else idle + 1
            if idle_polls is not None and idle >= idle_polls:
                break
            time.sleep(poll_s)
        self.drain()

    def stats(self):
        return {"admitted": self.n_admitted,
                "completed": self.n_completed,
                "deferred": self.n_deferred,
                "errors": self.n_errors,
                "inflight": len(self._inflight)}
