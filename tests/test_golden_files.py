"""Golden-file loader robustness: archives HAND-FORGED by an
independent FITS writer (tests/fits_forge.py — shares no code with
io/fitsio or io/psrfits) in layouts the repo's own writer never emits.
This breaks the round-2 closed loop where every IO test round-tripped
through the repo's writer (VERDICT round 2, missing #1)."""

import numpy as np
import pytest

from fits_forge import forge_archive, gaussian_portrait
from pulseportraiture_tpu.io.psrfits import load_data, read_archive


def _check_amps(arch, stored, rtol=1e-5, atol=1e-3):
    """Loaded amps equal the forge's independently-computed stored
    values (quantization applied) for every (sub, pol, chan)."""
    np.testing.assert_allclose(np.asarray(arch.amps), stored,
                               rtol=rtol, atol=atol)


def test_plain_i2_archive_loads(tmp_path):
    """Baseline forge sanity: scaled int16, all standard columns."""
    p = str(tmp_path / "plain.fits")
    stored, freqs = forge_archive(p)
    arch = read_archive(p)
    assert (arch.nsub, arch.npol, arch.nchan, arch.nbin) == (2, 1, 8, 64)
    _check_amps(arch, stored)
    np.testing.assert_allclose(arch.freqs_table[0], freqs)
    # forge zaps channel 0 via DAT_WTS
    assert np.all(arch.get_weights()[:, 0] == 0.0)
    assert arch.get_dispersion_measure() == pytest.approx(12.5)
    # the full pipeline-facing loader runs too
    d = load_data(p, quiet=True)
    assert d.nchan == 8 and d.nbin == 64
    assert np.all(np.asarray(d.ok_ichans[0]) != 0)  # chan 0 zapped


def test_missing_wts_scl_offs_columns(tmp_path):
    """No DAT_WTS / DAT_SCL / DAT_OFFS at all (float DATA): PSRFITS
    semantics are weight 1, scale 1, offset 0."""
    p = str(tmp_path / "nowts.fits")
    stored, _ = forge_archive(p, data_dtype=">f4", with_wts=False,
                              with_scl_offs=False)
    arch = read_archive(p)
    _check_amps(arch, stored, atol=1e-4)
    assert np.all(arch.get_weights() == 1.0)
    d = load_data(p, quiet=True)
    assert len(d.ok_ichans[0]) == 8  # nothing zapped


def test_unsigned_byte_data(tmp_path):
    """DATA as unsigned bytes (TFORM 'B', offset-binary scaling) —
    search-era archives and some backends store u1."""
    p = str(tmp_path / "u1.fits")
    stored, _ = forge_archive(p, data_dtype="u1")
    arch = read_archive(p)
    _check_amps(arch, stored, atol=0.05)  # 8-bit quantization
    # and the data is still physically meaningful: profile recovered
    prof = np.asarray(arch.amps)[0, 0, 4]
    want = stored[0, 0, 4]
    assert np.corrcoef(prof, want)[0, 1] > 0.999


def test_alien_tdim_spellings(tmp_path):
    """TDIM with spaces inside the parentheses, and no TDIM at all
    (header-geometry fallback), both decode to the same cube."""
    cubes = []
    for style in ("spaced", "plain", None):
        p = str(tmp_path / f"tdim_{style}.fits")
        stored, _ = forge_archive(p, tdim_style=style)
        arch = read_archive(p)
        _check_amps(arch, stored)
        cubes.append(np.asarray(arch.amps))
    np.testing.assert_array_equal(cubes[0], cubes[1])
    np.testing.assert_array_equal(cubes[0], cubes[2])


def test_ragged_per_subint_freqs(tmp_path):
    """DAT_FREQ differing per subint row (Doppler-tracking backends)
    must survive into freqs_table, not be collapsed to row 0."""
    p = str(tmp_path / "ragged.fits")
    stored, freqs0 = forge_archive(p, nsub=3, ragged_freqs=True)
    arch = read_archive(p)
    _check_amps(arch, stored)
    for s in range(3):
        np.testing.assert_allclose(arch.freqs_table[s],
                                   freqs0 + 0.25 * 25.0 * s)


def test_multirow_polyco_periods(tmp_path):
    """A 3-row POLYCO table (and no PERIOD column would be the harder
    case; here both exist — folding_periods must pick the nearest
    block per epoch and produce the forged spin period)."""
    p = str(tmp_path / "polyco.fits")
    stored, _ = forge_archive(p, polyco_rows=3, period=0.007)
    arch = read_archive(p)
    per = arch.folding_periods()
    np.testing.assert_allclose(per, 0.007, rtol=1e-9)


def test_coherence_to_stokes_conversion(tmp_path):
    """4-pol AABBCRCI data converts to full Stokes (linear feed basis):
    the round-2 gap (io/psrfits.py previously raised on anything but
    ->Intensity).  Reference parity: pplib.py:2782-2814."""
    nchan, nbin = 8, 64
    base = gaussian_portrait(nchan, nbin)
    # construct coherence products from known Stokes: I = base,
    # Q = 0.3 I, U = 0.2 I, V = -0.1 I
    I, Q, U, V = base, 0.3 * base, 0.2 * base, -0.1 * base
    AA, BB, CR, CI = 0.5 * (I + Q), 0.5 * (I - Q), 0.5 * U, 0.5 * V
    coher = [AA, BB, CR, CI]

    p = str(tmp_path / "coher.fits")
    stored, _ = forge_archive(
        p, npol=4, pol_type="AABBCRCI", fd_poln="LIN",
        data_maker=lambda s, ipol: coher[ipol])
    arch = read_archive(p)
    assert arch.get_state() == "Coherence"
    arch.convert_state("Stokes")
    assert arch.get_state() == "Stokes"
    got = np.asarray(arch.amps)
    for k, want in enumerate((I, Q, U, V)):
        np.testing.assert_allclose(got[0, k], want, rtol=1e-3,
                                   atol=2e-3), k

    # circular basis swaps the roles: Q<->V per van Straten (2004)
    p2 = str(tmp_path / "coher_circ.fits")
    forge_archive(p2, npol=4, pol_type="AABBCRCI", fd_poln="CIRC",
                  data_maker=lambda s, ipol: coher[ipol])
    arch2 = read_archive(p2)
    arch2.convert_state("Stokes")
    got2 = np.asarray(arch2.amps)
    np.testing.assert_allclose(got2[0, 1], U, rtol=1e-3, atol=2e-3)  # Q=2CR
    np.testing.assert_allclose(got2[0, 3], Q, rtol=1e-3, atol=2e-3)  # V=AA-BB

    # load_data(state="Stokes") plumbs it end to end; pscrunch gives I
    d = load_data(p, state="Stokes", rm_baseline=False, quiet=True)
    assert d.subints.shape[1] == 4
    dI = load_data(p, pscrunch=True, rm_baseline=False, quiet=True)
    np.testing.assert_allclose(dI.subints[0, 0], I, rtol=1e-3, atol=2e-3)
    # PPQQ -> Stokes is impossible and must say so
    p3 = str(tmp_path / "ppqq.fits")
    forge_archive(p3, npol=2, pol_type="AA+BB",
                  data_maker=lambda s, ipol: base)
    with pytest.raises(ValueError, match="unsupported"):
        read_archive(p3).convert_state("Stokes")


def test_forged_archive_through_the_fit(tmp_path):
    """End to end on a forged file: TOAs measure the forged portrait
    against itself (phase ~ 0) — the loader feeds the real pipeline,
    not just the accessors."""
    from pulseportraiture_tpu.fit import fit_phase_shift

    p = str(tmp_path / "fit.fits")
    stored, freqs = forge_archive(p, nchan=16, nbin=128)
    d = load_data(p, quiet=True)
    prof = np.asarray(d.subints[0, 0]).mean(axis=0)
    tmpl = np.asarray(stored[0, 0]).mean(axis=0)
    tmpl = tmpl - np.median(tmpl)
    r = fit_phase_shift(prof, tmpl, noise_std=max(float(
        np.median(np.asarray(d.noise_stds[0, 0]))), 1e-6))
    assert abs(float(r.phase)) < 2e-3


def test_streaming_raw_lane_on_forged_archives(tmp_path):
    """The campaign driver's raw int16 lane ingests hand-forged
    archives (alien writer, no TDIM card) end to end: bucketed fused
    dispatches, .tim output, phases ~ 0 against the forged portrait as
    template."""
    from pulseportraiture_tpu.pipeline.stream import (_load_raw,
                                                      stream_wideband_TOAs)

    files = []
    for i in range(2):
        p = str(tmp_path / f"raw{i}.fits")
        # the forge writes ALIGNED profiles, so declare the truth
        # (DEDISP=1): the raw lane then re-disperses on device by the
        # stored DM and the fit measures it back out
        forge_archive(p, nsub=2, nchan=16, nbin=128, dedisp=1)
        files.append(p)
    # the forge's i2 DATA + scl/offs is raw-lane compatible
    d = _load_raw(files[0])
    assert d.raw.dtype == np.int16 and d.raw.shape == (2, 16, 128)

    # template: the forged portrait itself, written as a PSRFITS
    # template through the normal writer (the template path is not
    # under test here)
    from pulseportraiture_tpu.io.psrfits import (read_archive,
                                                 unload_new_archive)

    arch = read_archive(files[0])
    arch.tscrunch()
    tmpl = str(tmp_path / "tmpl.fits")
    unload_new_archive(np.asarray(arch.amps), arch, tmpl, DM=0.0,
                       dmc=1, quiet=True)
    out = str(tmp_path / "forged.tim")
    res = stream_wideband_TOAs(files, tmpl, nsub_batch=4, tim_out=out,
                               quiet=True)
    assert len(res.TOA_list) == 4
    epochs = {i: e for i, e in enumerate(read_archive(files[0]).epochs())}
    for t in res.TOA_list:
        # same data as template: the arrival time IS the subint epoch
        # (fitted phase ~ 0; under 1% of a turn = 50 us at P = 5 ms),
        # DM pinned at the stored 12.5
        dt_s = (t.MJD - epochs[t.flags["subint"]]) * 86400.0
        assert abs(dt_s) < 0.01 * 0.005, dt_s
        assert t.TOA_error < 50.0
        assert t.DM == pytest.approx(12.5, abs=0.05)
    assert len(open(out).read().splitlines()) >= 4


@pytest.mark.parametrize("nbit", [1, 2, 4])
def test_sub_byte_packed_data(tmp_path, nbit):
    """1/2/4-bit MSB-first packed DATA (search-era backends; PSRCHIVE
    handles these in C++) unpacks through the numpy loader path with
    DAT_SCL/DAT_OFFS restoring the physics."""
    p = str(tmp_path / f"nbit{nbit}.fits")
    stored, _ = forge_archive(p, data_dtype=f"nbit{nbit}", nchan=8,
                              nbin=64)
    arch = read_archive(p)
    assert (arch.nsub, arch.npol, arch.nchan, arch.nbin) == (2, 1, 8, 64)
    got = np.asarray(arch.amps)
    np.testing.assert_allclose(got, stored, rtol=1e-5, atol=1e-4)
    # heavy quantization, but the pulse is still there
    cc = np.corrcoef(got[0, 0, 4], gaussian_portrait(8, 64)[4])[0, 1]
    assert cc > (0.7 if nbit == 1 else 0.97), cc
    # non-byte-aligned rows: each row pads to whole bytes and the
    # reader trims the pad (npol*nchan*nbin not divisible by 8//nbit)
    p2 = str(tmp_path / f"nbit{nbit}_odd.fits")
    stored2, _ = forge_archive(p2, data_dtype=f"nbit{nbit}", nchan=3,
                               nbin=33)
    arch2 = read_archive(p2)
    assert (arch2.nchan, arch2.nbin) == (3, 33)
    np.testing.assert_allclose(np.asarray(arch2.amps), stored2,
                               rtol=1e-5, atol=1e-4)


# --- round-4 real-world conventions (VERDICT r3 missing #1) --------------


def test_signed_byte_data_tzero(tmp_path):
    """Signed-byte DATA via the FITS convention TFORM='B' +
    TZERO=-128 (stored unsigned, physical = stored - 128): the loader
    must apply the column scaling before DAT_SCL/DAT_OFFS."""
    p = str(tmp_path / "i1.fits")
    stored, _ = forge_archive(p, data_dtype="i1")
    arch = read_archive(p)
    _check_amps(arch, stored, atol=0.05)
    prof = np.asarray(arch.amps)[0, 0, 4]
    assert np.corrcoef(prof, stored[0, 0, 4])[0, 1] > 0.999
    # raw streaming mode carries the signed-byte convention since r10:
    # the payload ships as stored unsigned bytes and the DEVICE decode
    # removes the TZERO=-128 bias exactly (ops/decode code 'i8')
    raw = read_archive(p, decode=False)
    assert raw.raw_code == "i8"
    assert raw.raw_data.dtype == np.uint8
    dec = (raw.raw_data.astype(np.float64) - 128.0) \
        * np.asarray(raw.raw_scl, np.float64)[..., None] \
        + np.asarray(raw.raw_offs, np.float64)[..., None]
    np.testing.assert_allclose(dec, stored, rtol=0, atol=1e-6)
    # sub-byte layouts ship PACKED since r18 (raw code 'p4'); the
    # PPT_RAW_SUBBYTE escape hatch restores the decoded fallback
    forge_archive(str(tmp_path / "nbit.fits"), data_dtype="nbit4")
    assert read_archive(str(tmp_path / "nbit.fits"),
                        decode=False).raw_code == "p4"
    from pulseportraiture_tpu import config
    try:
        config.raw_subbyte = False
        with pytest.raises(ValueError, match="sub-byte"):
            read_archive(str(tmp_path / "nbit.fits"), decode=False)
    finally:
        config.raw_subbyte = True


def test_chan_dm_fallback_and_dedispersion(tmp_path):
    """CHAN_DM / REF_FREQ cards: a file with no SUBINT DM card falls
    back to CHAN_DM for the pulsar DM, and a dedispersed-on-disk file
    is re-dispersed at the DM/reference the cards say were APPLIED."""
    # 1) DM card absent -> CHAN_DM supplies the DM
    p1 = str(tmp_path / "chandm.fits")
    forge_archive(p1, omit_dm_card=True,
                  extra_subint_cards=(("CHAN_DM", 12.5),))
    arch = read_archive(p1)
    assert arch.get_dispersion_measure() == pytest.approx(12.5)
    assert arch.get_chan_dm() == pytest.approx(12.5)

    # a present-but-ZERO CHAN_DM (the standard SUBINT template writes
    # it unconditionally) must not shadow the fallback chain
    p1b = str(tmp_path / "chandm0.fits")
    forge_archive(p1b, dm=7.25,
                  extra_subint_cards=(("CHAN_DM", 0.0),))
    assert read_archive(p1b).get_dispersion_measure() \
        == pytest.approx(7.25)

    # 2) dedispersed-on-disk: dededisperse restores the archive DM's
    # delays at the REF_FREQ card's reference (CHAN_DM records the
    # backend's coherent within-channel dedispersion — a different
    # operation — and must be left alone)
    from pulseportraiture_tpu.io.psrfits import dm_delays, rotate_phase

    base = gaussian_portrait(8, 64)
    p2 = str(tmp_path / "dedisp.fits")
    stored2, freqs = forge_archive(
        p2, nsub=1, data_maker=lambda s, p: base, dedisp=1, dm=12.5,
        extra_subint_cards=(("CHAN_DM", 9.0), ("REF_FREQ", 1500.0)))
    arch2 = read_archive(p2)
    assert arch2.get_chan_dm() == pytest.approx(9.0)
    assert arch2.dedispersion_ref_freq() == pytest.approx(1500.0)
    before = np.asarray(arch2.amps[0, 0]).copy()
    arch2.dededisperse()
    after = np.asarray(arch2.amps[0, 0])
    delays = np.asarray(dm_delays(12.5, 0.005, freqs, 1500.0))
    want = np.asarray(rotate_phase(before, -delays))
    np.testing.assert_allclose(after, want, rtol=1e-4, atol=1e-3)
    # CHAN_DM untouched by the round trip
    arch2.dedisperse()
    assert arch2.get_chan_dm() == pytest.approx(9.0)


def test_epochs_convention_card(tmp_path):
    """The SUBINT EPOCHS card: every PSRCHIVE-written convention keeps
    the STT + OFFS_SUB arithmetic; an unknown convention is refused
    (silently misdating TOAs is worse than failing)."""
    eps = []
    for conv in ("MIDTIME", "VALID", "STT_MJD", None):
        p = str(tmp_path / f"ep_{conv}.fits")
        cards = (("EPOCHS", conv),) if conv else ()
        forge_archive(p, extra_subint_cards=cards)
        eps.append([e.to_float() for e in read_archive(p).epochs()])
    for e in eps[1:]:
        np.testing.assert_array_equal(eps[0], e)
    p = str(tmp_path / "ep_bad.fits")
    forge_archive(p, extra_subint_cards=(("EPOCHS", "FUTURE_CONV"),))
    with pytest.raises(ValueError, match="EPOCHS"):
        read_archive(p).epochs()


def test_descending_frequency_band(tmp_path):
    """Descending DAT_FREQ / negative OBSBW (upper-sideband backends):
    the loader keeps the stored order and the fit still recovers an
    injected dispersion offset."""
    from pulseportraiture_tpu.io.psrfits import dm_delays, rotate_phase

    nchan, nbin, P = 8, 64, 0.005
    base = gaussian_portrait(nchan, nbin)
    freqs_desc = 1575.0 - 25.0 * np.arange(nchan)
    dDM = 0.02

    def maker(isub, ipol):
        delays = np.asarray(dm_delays(dDM, P, freqs_desc, np.inf))
        return np.asarray(rotate_phase(base, -delays))

    p = str(tmp_path / "desc.fits")
    stored, freqs = forge_archive(p, nsub=2, nchan=nchan, nbin=nbin,
                                  freq0=1575.0, chan_bw=-25.0, dm=0.0,
                                  data_maker=maker)
    np.testing.assert_allclose(freqs, freqs_desc)
    arch = read_archive(p)
    assert arch.get_bandwidth() == pytest.approx(-200.0)
    np.testing.assert_allclose(arch.freqs_table[0], freqs_desc)
    d = load_data(p, quiet=True)
    np.testing.assert_allclose(np.asarray(d.freqs[0]), freqs_desc)

    import jax.numpy as jnp

    from pulseportraiture_tpu.fit import FitFlags, fit_portrait

    res = fit_portrait(
        jnp.asarray(d.subints[0, 0]), jnp.asarray(base),
        jnp.asarray(d.noise_stds[0, 0]), jnp.asarray(freqs_desc), P,
        nu_fit=1500.0, fit_flags=FitFlags(phi=True, DM=True))
    assert abs(float(res.DM) - dDM) < 1e-3, float(res.DM)


def test_search_mode_rejected(tmp_path):
    """SEARCH-mode PSRFITS (unfolded filterbank samples) must be
    refused with an actionable error, not misparsed as profiles."""
    from fits_forge import forge_search_mode

    p = str(tmp_path / "search.fits")
    forge_search_mode(p)
    with pytest.raises(ValueError, match="[Ss]earch"):
        read_archive(p)
    with pytest.raises(ValueError, match="fold"):
        load_data(p, quiet=True)


def test_set_dispersion_measure_zero_round_trips(tmp_path):
    """set_dispersion_measure(0.0) must stick on the live object even
    when a PSRPARAM/CHAN_DM fallback exists — dedisperse() after
    zeroing stays a no-op."""
    p = str(tmp_path / "dm0.fits")
    forge_archive(p, dm=12.5)
    arch = read_archive(p)
    assert arch.get_dispersion_measure() == pytest.approx(12.5)
    before = np.asarray(arch.amps).copy()
    arch.set_dispersion_measure(0.0)
    assert arch.get_dispersion_measure() == 0.0
    arch.dedisperse()
    np.testing.assert_array_equal(np.asarray(arch.amps), before)
