"""Streaming metrics: thread-safe counters/gauges and fixed log-bucket
latency histograms.

The histogram keeps NO samples — just one count per bucket — so the
cost of observing a latency is a bisect plus an increment, and the
memory is constant no matter how long the server runs.  Bucket bounds
are a module-level constant shared by every host in a fleet, which is
what makes fleet-wide aggregation a bucket-wise sum: the router merges
per-host exports without ever seeing a sample.

Quantiles come from the cumulative bucket counts; with 8 buckets per
decade the worst-case relative error of a reported quantile is
10**(1/8) - 1 ~= 33%, which is plenty for p50/p90/p99 dashboards and
burn-rate alerting (the exact latencies still land in the JSONL trace
for post-hoc analysis).
"""

import bisect
import threading

# Fixed log-spaced bucket upper bounds, 8 per decade from 100 us to
# 1e4 s (65 finite bounds + one overflow bucket).  Shared fleet-wide:
# changing these invalidates cross-host merging, so treat them as a
# wire-format constant.
_BUCKETS_PER_DECADE = 8
_DECADES = 8
HIST_BOUNDS = tuple(
    1e-4 * 10.0 ** (i / _BUCKETS_PER_DECADE)
    for i in range(_BUCKETS_PER_DECADE * _DECADES + 1))


class LatencyHistogram:
    """Fixed-bucket latency histogram; thread-safe, no sample
    retention."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(HIST_BOUNDS) + 1)
        self._count = 0
        self._sum = 0.0

    def observe(self, value):
        i = bisect.bisect_left(HIST_BOUNDS, value)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += value

    def export(self):
        with self._lock:
            return {"count": self._count, "sum": round(self._sum, 6),
                    "counts": list(self._counts)}


def quantile_from_export(hist, q):
    """Estimate the q-quantile (0 < q <= 1) from an exported histogram
    dict; returns None on an empty histogram.  The estimate is the
    geometric midpoint of the bucket holding the q-th sample."""
    total = hist.get("count", 0)
    if not total:
        return None
    counts = hist["counts"]
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            if i == 0:
                return HIST_BOUNDS[0]
            if i >= len(HIST_BOUNDS):
                return HIST_BOUNDS[-1]
            return (HIST_BOUNDS[i - 1] * HIST_BOUNDS[i]) ** 0.5
    return HIST_BOUNDS[-1]


def merge_exports(exports):
    """Merge a list of MetricsRegistry exports (bucket-wise histogram
    sum, counter sum; gauges are dropped — they are per-host facts)."""
    counters = {}
    hists = {}
    for ex in exports:
        if not ex:
            continue
        for k, v in (ex.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for name, h in (ex.get("histograms") or {}).items():
            m = hists.get(name)
            if m is None:
                hists[name] = {"count": h["count"], "sum": h["sum"],
                               "counts": list(h["counts"])}
            else:
                m["count"] += h["count"]
                m["sum"] = round(m["sum"] + h["sum"], 6)
                # zip stops at the shorter list, so a peer running a
                # different bound table can under-merge: refuse loudly
                if len(m["counts"]) != len(h["counts"]):
                    raise ValueError(
                        f"histogram '{name}' bucket-count mismatch "
                        f"({len(m['counts'])} vs {len(h['counts'])}): "
                        "fleet hosts disagree on HIST_BOUNDS")
                m["counts"] = [a + b
                               for a, b in zip(m["counts"], h["counts"])]
    return {"counters": counters, "gauges": {}, "histograms": hists}


class MetricsRegistry:
    """Thread-safe named counters, gauges, and latency histograms.

    One lock covers the name tables; each histogram carries its own
    lock so concurrent observes on different names never serialize on
    the registry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._hists = {}

    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name, value):
        with self._lock:
            self._gauges[name] = value

    def observe(self, name, value):
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = LatencyHistogram()
        h.observe(value)

    def counter(self, name):
        with self._lock:
            return self._counters.get(name, 0)

    def quantile(self, name, q):
        with self._lock:
            h = self._hists.get(name)
        return quantile_from_export(h.export(), q) if h else None

    def export(self):
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
        return {"counters": counters, "gauges": gauges,
                "histograms": {k: h.export() for k, h in hists.items()}}


# Process-global registry for hot paths that have no handle on a
# server (the h2d copy workers live in the transfer pipeline, which
# predates serving).  ToaServer.metrics() folds these in so the link
# numbers ride the same export.
_GLOBAL = MetricsRegistry()


def global_registry():
    return _GLOBAL


def record_h2d(nbytes, h2d_s, overlap):
    """Account one host->device copy: total copy seconds vs copy
    seconds NOT hidden behind an in-flight fit (the live link-stall
    signal; the post-hoc equivalent is pptrace's h2d section)."""
    _GLOBAL.inc("h2d_copies")
    _GLOBAL.inc("h2d_bytes", int(nbytes))
    _GLOBAL.inc("h2d_us", int(h2d_s * 1e6))
    if not overlap:
        _GLOBAL.inc("h2d_stall_us", int(h2d_s * 1e6))


def link_stall_frac(export):
    """Fraction of copy seconds not hidden behind compute, from an
    export's counters; None before any copy has been accounted."""
    c = export.get("counters") or {}
    total = c.get("h2d_us", 0)
    if not total:
        return None
    return round(c.get("h2d_stall_us", 0) / total, 4)
