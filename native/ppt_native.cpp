// Native decode kernels for the PSRFITS SUBINT hot path.
//
// The reference reaches folded-archive data through the PSRCHIVE C++
// library (reference pplib.py:51, load_data pplib.py:2749); this
// framework carries its own FITS engine (io/fitsio.py) and uses this
// module to fuse the expensive part of ingestion: decoding the
// big-endian DATA column and applying DAT_SCL / DAT_OFFS in one pass,
// threaded over subints, with no float64 intermediates.  The Python
// fallback (io/psrfits.py read_archive) is the reference
// implementation; tests assert bit-equality between the two.
//
// Build: g++ -O3 -shared -fPIC -fopenmp -o libppt_native.so ppt_native.cpp
// (io/native.py builds lazily at import when the .so is absent).

#include <cstdint>
#include <cstring>

static inline int16_t load_i16be(const uint8_t* p) {
    return (int16_t)((uint16_t)(p[0] << 8) | p[1]);
}

static inline float load_f32be(const uint8_t* p) {
    uint32_t v = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
                 ((uint32_t)p[2] << 8) | (uint32_t)p[3];
    float f;
    std::memcpy(&f, &v, 4);
    return f;
}

// Sample-type codes for the DATA column (matches io/native.py).
enum { PPT_I16BE = 0, PPT_U8 = 1, PPT_F32BE = 2, PPT_I8 = 3 };

template <typename OutT>
static void decode_rows(const uint8_t* raw, int64_t nrows, int64_t row_stride,
                        int64_t col_off, int64_t ngrp, int64_t nbin,
                        const double* scl, const double* offs, int code,
                        OutT* out) {
    const int64_t samp = (code == PPT_I16BE) ? 2 : (code == PPT_F32BE ? 4 : 1);
#pragma omp parallel for schedule(static)
    for (int64_t r = 0; r < nrows; ++r) {
        const uint8_t* row = raw + r * row_stride + col_off;
        OutT* orow = out + r * ngrp * nbin;
        for (int64_t g = 0; g < ngrp; ++g) {
            const double s = scl ? scl[r * ngrp + g] : 1.0;
            const double o = offs ? offs[r * ngrp + g] : 0.0;
            const uint8_t* p = row + g * nbin * samp;
            OutT* q = orow + g * nbin;
            switch (code) {
                case PPT_I16BE:
                    for (int64_t k = 0; k < nbin; ++k)
                        q[k] = (OutT)(load_i16be(p + 2 * k) * s + o);
                    break;
                case PPT_U8:
                    for (int64_t k = 0; k < nbin; ++k)
                        q[k] = (OutT)(p[k] * s + o);
                    break;
                case PPT_I8:
                    for (int64_t k = 0; k < nbin; ++k)
                        q[k] = (OutT)((int8_t)p[k] * s + o);
                    break;
                case PPT_F32BE:
                    for (int64_t k = 0; k < nbin; ++k)
                        q[k] = (OutT)(load_f32be(p + 4 * k) * s + o);
                    break;
            }
        }
    }
}

extern "C" {

// Decode a strided big-endian DATA column with fused scale/offset.
//   raw        table payload (nrows rows of row_stride bytes)
//   col_off    byte offset of the DATA column within a row
//   ngrp       npol * nchan groups per row
//   nbin       samples per group
//   scl, offs  (nrows * ngrp) each, or NULL
//   code       sample type (PPT_* above)
//   out_f64    1 -> out is double*, 0 -> out is float*
// Returns 0 on success, nonzero on bad arguments.
int ppt_decode_fused(const uint8_t* raw, int64_t nrows, int64_t row_stride,
                     int64_t col_off, int64_t ngrp, int64_t nbin,
                     const double* scl, const double* offs, int code,
                     int out_f64, void* out) {
    if (!raw || !out || nrows < 0 || ngrp <= 0 || nbin <= 0) return 1;
    if (code < PPT_I16BE || code > PPT_I8) return 2;
    if (out_f64)
        decode_rows(raw, nrows, row_stride, col_off, ngrp, nbin, scl, offs,
                    code, (double*)out);
    else
        decode_rows(raw, nrows, row_stride, col_off, ngrp, nbin, scl, offs,
                    code, (float*)out);
    return 0;
}

// Gather a big-endian float32/float64 column (e.g. DAT_SCL, DAT_FREQ)
// from strided rows into a contiguous float64 array.
int ppt_gather_f(const uint8_t* raw, int64_t nrows, int64_t row_stride,
                 int64_t col_off, int64_t nelem, int is_f64, double* out) {
    if (!raw || !out || nrows < 0 || nelem <= 0) return 1;
#pragma omp parallel for schedule(static)
    for (int64_t r = 0; r < nrows; ++r) {
        const uint8_t* p = raw + r * row_stride + col_off;
        double* q = out + r * nelem;
        if (is_f64) {
            for (int64_t k = 0; k < nelem; ++k) {
                uint64_t v = 0;
                for (int b = 0; b < 8; ++b) v = (v << 8) | p[8 * k + b];
                double d;
                std::memcpy(&d, &v, 8);
                q[k] = d;
            }
        } else {
            for (int64_t k = 0; k < nelem; ++k) q[k] = load_f32be(p + 4 * k);
        }
    }
    return 0;
}

}  // extern "C"
