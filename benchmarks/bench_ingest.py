"""Online-ingest benchmark (ISSUE 18 acceptance gate): the watch-folder
observatory pipeline end-to-end — ppwatch over a finished corpus with
one injected glitch and one injected DM step — plus the detection /
false-alarm sweep over synthetic TOA campaigns.

Arms:
  oneshot   — stream_wideband_TOAs over the event corpus (the offline
              reference the streamed .tim must match byte-for-byte);
  ppwatch   — the full pipeline in --drain mode: watch-folder
              admission -> warm ToaServer -> ordered streaming .tim,
              with the incremental GLS lane (periodic full resolves
              cross-check the running solution against the batch
              solver at <= 1e-10: GLSDriftError on violation) and the
              CUSUM alert monitor riding the residual stream;
  clean     — the same pipeline over an event-free control corpus;
  replay    — the streamed TOAs re-fed through IncrementalGLS with a
              from-scratch batch fit at EVERY update (the explicit
              parity measurement the resolve gate enforces online);
  sweep     — PPT_NSEEDS clean + PPT_NSEEDS event-injected synthetic
              campaigns (synth.fake_timing_campaign ground truth)
              through the incremental + alert chain.

Gates, ENFORCED at every shape including CI smoke:
  * streamed .tim byte-identical to the offline one-shot;
  * exactly one glitch + one dm_step alert, each localized within one
    day of its injected epoch, nothing else on the event corpus;
  * ZERO alerts on the clean control corpus;
  * replay parity: max relative delta vs batch <= 1e-10 at every
    update; the online run completed >= 1 full resolve (so the same
    gate ran inside ppwatch);
  * sweep: detection rate 1.0 (both events, every seed), false-alarm
    rate 0.0 (no alert on any clean seed).
PPT_INGEST_P99_GATE=<seconds> additionally gates the admit->TOA p99
latency (real bench runs; tiny CPU shapes pay the whole bucket
deadline + compile per dispatch, so the default is off).

Knobs via env: PPT_NARCH (default 10, min 6), PPT_NSUB (2), PPT_NCHAN
(32), PPT_NBIN (256), PPT_NSEEDS (8).  Archives cache under
PPT_CAMPAIGN_CACHE (default /tmp/ppt_campaign).  When PPT_TELEMETRY is
set the pipeline traces to <path>.ingest / <path>.clean and both are
schema-validated.  Prints ONE JSON line.
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.env_overrides()

    import jax
    import numpy as np

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.cli import ppwatch
    from pulseportraiture_tpu.ingest import AlertMonitor
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar
    from pulseportraiture_tpu.synth.fake import fake_timing_campaign
    from pulseportraiture_tpu.timing import (IncrementalGLS,
                                             wideband_gls_fit)
    from pulseportraiture_tpu.timing.tim import read_tim
    from pulseportraiture_tpu.utils.mjd import MJD

    NARCH = max(6, int(os.environ.get("PPT_NARCH", 10)))
    NSUB = int(os.environ.get("PPT_NSUB", 2))
    NCHAN = int(os.environ.get("PPT_NCHAN", 32))
    NBIN = int(os.environ.get("PPT_NBIN", 256))
    NSEEDS = max(1, int(os.environ.get("PPT_NSEEDS", 8)))
    P99_GATE = float(os.environ.get("PPT_INGEST_P99_GATE", 0) or 0)
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    P0 = 0.004074
    SPACING = 30.0  # days between archives (one timing epoch each)
    PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4",
           "DECJ": "-11:34:54.6", "P0": P0,
           "PEPOCH": 55100.0 + 15.0 * (NARCH - 1), "DM": 3.139}
    # injected ground truth: achromatic 100-us phase step (glitch)
    # mid-corpus, 4e-3 pc/cc DM step late enough for the detector's
    # epoch warmup
    GLITCH_I = NARCH // 2
    DM_I = max(4, (2 * NARCH) // 3)
    DPHI = 100e-6 / P0  # turns
    DDM = 4e-3

    tag = f"ingest{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    parfile = os.path.join(root, "pulsar.par")
    with open(parfile, "w") as fh:
        for k, v in PAR.items():
            fh.write(f"{k} {v}\n")

    def build_corpus(sub, events):
        folder = os.path.join(root, sub)
        os.makedirs(folder, exist_ok=True)
        files = []
        for i in range(NARCH):
            path = os.path.join(folder, f"ep{i:03d}.fits")
            if not os.path.exists(path):
                phase = 0.017 + (DPHI if events and i >= GLITCH_I
                                 else 0.0)
                dDM = (2e-4 * ((i % 3) - 1)
                       + (DDM if events and i >= DM_I else 0.0))
                make_fake_pulsar(
                    mpath, PAR, outfile=path, nsub=NSUB, nchan=NCHAN,
                    nbin=NBIN, nu0=1500.0, bw=400.0, tsub=60.0,
                    phase=phase, dDM=dDM,
                    start_MJD=MJD(int(55100 + SPACING * i), 0.2),
                    noise_stds=0.05, dedispersed=False, quiet=True,
                    rng=100 + i, spin_coherent=True)
            files.append(path)
            sentinel = path + ".done"
            if not os.path.exists(sentinel):
                open(sentinel, "w").close()
        return folder, files

    event_dir, event_files = build_corpus("event", events=True)
    clean_dir, clean_files = build_corpus("clean", events=False)
    out = os.path.join(root, "out")
    os.makedirs(out, exist_ok=True)

    # ---- oneshot arm: the offline byte-identity reference ----------
    ref_tim = os.path.join(out, "offline.tim")
    t0 = time.perf_counter()
    res = stream_wideband_TOAs(sorted(event_files), mpath,
                               nsub_batch=8, tim_out=ref_tim,
                               quiet=True)
    oneshot_wall = time.perf_counter() - t0
    ntoa = len(res.TOA_list)

    # ---- ppwatch arms: event corpus, then clean control ------------
    def watch(folder, suffix):
        tim = os.path.join(out, f"{suffix}.tim")
        for stale in (tim,):
            if os.path.exists(stale):
                os.remove(stale)
        trace = (f"{trace_base}.{suffix}" if trace_base
                 else os.path.join(out, f"{suffix}.jsonl"))
        if os.path.exists(trace):
            os.remove(trace)
        t0 = time.perf_counter()
        rc = ppwatch.main(["-w", folder, "-m", mpath, "-t", tim,
                           "-p", parfile, "--drain",
                           "--poll-ms", "20", "--stable-ms", "0",
                           "--resolve-every", "3",
                           "--telemetry", trace, "--quiet"])
        wall = time.perf_counter() - t0
        if rc != 0:
            raise SystemExit(f"bench_ingest: ppwatch over {folder} "
                             f"exited {rc}")
        _, events = telemetry.validate_trace(trace)
        summary = telemetry.report(trace, file=io.StringIO())
        return tim, trace, events, summary, wall

    tim, trace, events, summary, online_wall = watch(event_dir,
                                                     "ingest")
    streamed = open(tim, "rb").read()
    tim_identical = streamed == open(ref_tim, "rb").read()
    if not tim_identical:
        raise SystemExit("bench_ingest: streamed .tim differs from "
                         "the offline one-shot")
    if summary["n_ingest_admit"] != NARCH:
        raise SystemExit(f"bench_ingest: {summary['n_ingest_admit']} "
                         f"admissions for {NARCH} archives")
    if not summary["incremental_resolves"]:
        raise SystemExit("bench_ingest: the online run never cross-"
                         "checked against the batch oracle")

    # admit -> TOA latency: ingest_admit (admission order) paired with
    # its request's request_done on the events' monotonic clock
    admits = [e for e in events if e["type"] == "ingest_admit"]
    done = {e["req"]: e["t"] for e in events
            if e["type"] == "request_done"}
    lats = sorted(done[f"ingest{i}"] - ev["t"]
                  for i, ev in enumerate(admits))
    admit_p50 = lats[len(lats) // 2]
    admit_p99 = lats[max(0, int(np.ceil(0.99 * len(lats))) - 1)]
    p99_ok = None if not P99_GATE else bool(admit_p99 <= P99_GATE)
    if p99_ok is False:
        raise SystemExit(f"bench_ingest: admit->TOA p99 "
                         f"{admit_p99:.3f} s over the "
                         f"{P99_GATE:.3f} s gate")

    # both injected events alerted at their true epochs, nothing else
    alerts = [e for e in events if e["type"] == "alert"]
    truth_mjd = {"glitch": 55100 + SPACING * GLITCH_I + 0.2,
                 "dm_step": 55100 + SPACING * DM_I + 0.2}
    mjd_err = {}
    for kind, tmjd in truth_mjd.items():
        hits = [e for e in alerts if e["kind"] == kind]
        if len(hits) != 1:
            raise SystemExit(f"bench_ingest: {len(hits)} {kind} "
                             f"alert(s) on the event corpus, want 1")
        mjd_err[kind] = abs(hits[0]["mjd"] - tmjd)
        if mjd_err[kind] > 1.0:
            raise SystemExit(f"bench_ingest: {kind} localized "
                             f"{mjd_err[kind]:.2f} d from the "
                             f"injected epoch")
    if len(alerts) != 2:
        raise SystemExit(f"bench_ingest: {len(alerts)} alerts on the "
                         "event corpus, want exactly the 2 injected")

    _, _, _, clean_summary, _ = watch(clean_dir, "clean")
    if clean_summary["n_alert"] != 0:
        raise SystemExit(f"bench_ingest: {clean_summary['n_alert']} "
                         "false alarm(s) on the clean control")

    # ---- replay arm: explicit <= 1e-10 parity at every update ------
    toas = read_tim(tim)
    inc = IncrementalGLS(PAR, fit_binary=False, resolve_every=0)
    inc_max = 0.0
    for i, toa in enumerate(toas):
        r = inc.update(toa)
        # the 2-TOA prefix is conditioning-limited (phase + F0 + DMX
        # against two same-epoch TOAs: both solvers' pseudo-inverses
        # wobble there — the same caveat tests/test_incremental.py
        # documents); strict parity starts once overdetermined
        if r is None or i < 2:
            continue
        batch = wideband_gls_fit(toas[:i + 1], PAR, fit_binary=False)
        for name, val in batch.params.items():
            inc_max = max(inc_max, abs(r.params[name] - val)
                          / max(1.0, abs(val)))
        inc_max = max(inc_max, float(np.max(
            np.abs(np.asarray(r.dmx) - np.asarray(batch.dmx))
            / np.maximum(1.0, np.abs(batch.dmx)))))
    parity_ok = inc_max <= 1e-10
    if not parity_ok:
        raise SystemExit(f"bench_ingest: incremental-vs-batch parity "
                         f"{inc_max:.2e} over the 1e-10 gate")

    # ---- sweep arm: detection / false-alarm rates ------------------
    FPAR = {"PSR": "FAKE", "F0": "218.8", "PEPOCH": "55500",
            "DM": "15.9"}

    def monitor(rng, glitch=None, dm_step=None):
        toas, truth = fake_timing_campaign(
            FPAR, n_epochs=12, toas_per_epoch=2, span_days=120.0,
            dmx=2e-4, rng=rng, glitch=glitch, dm_step=dm_step)
        known = [{"kind": k, "mjd": getattr(truth, k)["mjd"]}
                 for k, spec in (("glitch", glitch),
                                 ("dm_step", dm_step)) if spec]
        gls = IncrementalGLS(FPAR, fit_binary=False, resolve_every=0)
        mon = AlertMonitor("FAKE", known_events=known or None)
        for toa in toas:
            mon.observe(gls.update(toa), toa)
        mon.finish()
        return mon.alerts

    clean_alerts = sum(len(monitor(rng=s)) for s in range(NSEEDS))
    detected = n_fp = 0
    for s in range(NSEEDS):
        alerts_s = monitor(rng=100 + s,
                           glitch={"epoch": 9, "dphi": 218.8 * 50e-6},
                           dm_step={"epoch": 4, "ddm": 4e-3})
        true_kinds = {a["kind"] for a in alerts_s if not a["fp"]}
        detected += true_kinds == {"glitch", "dm_step"}
        n_fp += sum(1 for a in alerts_s if a["fp"])
    detection_rate = detected / NSEEDS
    fp_rate = n_fp / max(1, n_fp + 2 * NSEEDS)
    if clean_alerts or fp_rate or detection_rate != 1.0:
        raise SystemExit(
            f"bench_ingest: sweep gates failed — {clean_alerts} "
            f"clean-corpus alert(s), detection {detection_rate:.2f}, "
            f"fp rate {fp_rate:.2f} over {NSEEDS} seed(s)")

    print(json.dumps({
        "metric": f"online observatory ingest e2e (watch-folder -> "
                  f"warm serve -> incremental GLS + alerts), {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin",
        "value": round(ntoa / online_wall, 2),
        "unit": "TOAs/sec",
        "toas": ntoa,
        "oneshot_toas_per_sec": round(ntoa / oneshot_wall, 2),
        "ingest_vs_oneshot": round(oneshot_wall / online_wall, 3),
        "tim_identical": tim_identical,
        "admit_to_toa_p50_s": round(admit_p50, 4),
        "admit_to_toa_p99_s": round(admit_p99, 4),
        "p99_gate_s": P99_GATE or None,
        "p99_ok": p99_ok,
        "discovery_wait_p99_s": summary["ingest_p99_s"],
        "incremental_resolves": summary["incremental_resolves"],
        "incremental_max_rel": float(inc_max),
        "incremental_parity_ok": parity_ok,
        "n_alerts": len(alerts),
        "glitch_mjd_err_d": round(mjd_err["glitch"], 4),
        "dm_step_mjd_err_d": round(mjd_err["dm_step"], 4),
        "clean_alerts": clean_alerts,
        "seeds": NSEEDS,
        "detection_rate": detection_rate,
        "fp_rate": fp_rate,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
