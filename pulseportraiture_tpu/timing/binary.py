"""Binary-pulsar orbital delay models (ELL1 and BT) with closed-form
partials — the timing subsystem's physics layer (ISSUE 11 tentpole).

Most IPTA millisecond pulsars are binaries, so the wideband GLS fit
(timing/gls.py) needs the orbital Roemer delay and its parameter
derivatives for the design matrix.  Two parameterizations cover the
MSP population the paper's flagship scenario targets (SURVEY §2/§7,
PAPER.md §timing):

* **ELL1** (Lange et al. 2001, eq. A6): small-eccentricity orbits
  parameterized by (PB, A1, TASC, EPS1=e·sinω, EPS2=e·cosω) —
  numerically stable where ω is undefined (e → 0), which is almost
  every recycled pulsar.  First-order-in-e Roemer delay:

      Δ_R = x·[ sinΦ + (κ/2)·sin2Φ − (η/2)·cos2Φ ],
      Φ = 2π·[ (t−TASC)/PB − (PBDOT/2)·((t−TASC)/PB)² ],
      x = A1 + XDOT·(t−TASC),  η = EPS1 + EPS1DOT·(t−TASC),
      κ = EPS2 + EPS2DOT·(t−TASC).

* **BT** (Blandford & Teukolsky 1976): full Keplerian orbits
  (PB, A1, T0, ECC, OM).  Mean anomaly M → eccentric anomaly E by a
  fixed-iteration Newton solve of Kepler's equation (jittable: the
  iteration count is static; 12 Newton steps converge to f64
  round-off for e ≤ 0.95), then

      Δ_R = x·sinω·(cosE − e) + x·cosω·√(1−e²)·sinE.

Every delay function exists twice, deliberately:

* a **jittable jax.numpy f64 op** (``ell1_delay_and_partials`` /
  ``bt_delay_and_partials``) — pure fixed-shape array math, safe
  under ``jax.jit``/``vmap`` (the fleet lane and the GLS design-matrix
  builder use these);
* a **host NumPy oracle** (``ell1_delay_np`` / ``bt_delay_np``) — the
  digit-parity reference the tests gate against, and what the synth
  injection uses (synth/archive.py stays host-pure NumPy).

Partials are CLOSED FORM (no autodiff): tempo's classic derivative
set, in per-second units internally — callers converting to parfile
units (PB/TASC/T0 in days) multiply the corresponding partials by
SECPERDAY.  Shapiro/relativistic terms (SINI, M2, H3/H4/STIG, GAMMA,
OMDOT, ...) are NOT modeled here; timing/gls.py refuses parfiles that
carry them.
"""

from dataclasses import dataclass

import numpy as np

__all__ = ["BinaryParams", "parse_binary", "binary_delay_np",
           "binary_delay_and_partials",
           "ell1_delay_np", "ell1_delay_and_partials",
           "bt_delay_np", "bt_delay_and_partials",
           "SUPPORTED_BINARY_MODELS", "KEPLER_NEWTON_ITERS"]

SECPERDAY = 86400.0
SUPPORTED_BINARY_MODELS = ("ELL1", "BT")

# Newton iterations for Kepler's equation in the BT model.  Static so
# the op stays jittable (lax.fori_loop over a fixed count); 12
# quadratically-converging steps from E0 = M reach f64 round-off for
# any e <= 0.95 (tested against scipy-free bisection in the oracle
# suite).
KEPLER_NEWTON_ITERS = 12


# ---------------------------------------------------------------------------
# Parfile parsing
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class BinaryParams:
    """Parsed orbital elements in parfile units.

    kind: 'ELL1' | 'BT'.  tref_int/tref_frac: the epoch the orbit is
    referenced to (TASC for ELL1, T0 for BT) split digit-exactly into
    (int MJD, fractional day) — one f64 MJD would cost ~µs of orbital
    phase over a long campaign.  pb [days], a1 [lt-s], eps1/eps2
    dimensionless, om [deg], pbdot dimensionless (s/s), xdot [lt-s/s],
    eps1dot/eps2dot [1/s].
    """

    kind: str
    pb: float
    a1: float
    tref_int: int
    tref_frac: float
    eps1: float = 0.0
    eps2: float = 0.0
    ecc: float = 0.0
    om: float = 0.0
    pbdot: float = 0.0
    xdot: float = 0.0
    eps1dot: float = 0.0
    eps2dot: float = 0.0

    @property
    def param_names(self):
        """Fit-parameter names, in design-column order."""
        if self.kind == "ELL1":
            return ("PB", "A1", "TASC", "EPS1", "EPS2")
        return ("PB", "A1", "T0", "ECC", "OM")

    def dt_seconds(self, mjd_int, mjd_frac):
        """Seconds since the orbital reference epoch, precision-split:
        the integer-day difference and the fractional-day difference
        are reduced separately so a 50 000-day MJD never rounds the
        sub-second part."""
        mjd_int = np.asarray(mjd_int, np.int64)
        mjd_frac = np.asarray(mjd_frac, np.float64)
        return ((mjd_int - self.tref_int) * SECPERDAY
                + (mjd_frac - self.tref_frac) * SECPERDAY)


def _fget(par, key, default=None):
    v = par.get(key, default)
    if v is None:
        return None
    return float(str(v).replace("D", "E").replace("d", "e"))


def parse_binary(par):
    """Parse the binary model out of a parfile mapping.

    Returns None when the parfile carries no binary keys at all, a
    BinaryParams when it carries a complete supported (ELL1 or BT)
    element set, and raises a loud ValueError on anything in between:
    an unsupported BINARY model name, a partial element set (the
    likeliest hand-edit failure mode), or mixed ELL1/BT keys without a
    BINARY line to disambiguate.  Keys this model family does NOT
    implement (Shapiro, relativistic terms) are the caller's to refuse
    — see timing/gls.py _UNMODELED_BINARY_KEYS.
    """
    if not hasattr(par, "get"):
        return None
    kind = par.get("BINARY")
    ell1_keys = [k for k in ("TASC", "EPS1", "EPS2") if par.get(k) is not None]
    bt_keys = [k for k in ("T0", "ECC", "E", "OM") if par.get(k) is not None]
    have_any = (kind is not None or ell1_keys or bt_keys
                or par.get("PB") is not None or par.get("A1") is not None)
    if not have_any:
        return None
    if kind is not None:
        kind = str(kind).strip().upper()
        if kind not in SUPPORTED_BINARY_MODELS:
            raise ValueError(
                f"timing/binary: BINARY model {kind!r} is not "
                f"implemented — supported models are "
                f"{'/'.join(SUPPORTED_BINARY_MODELS)} (DD/T2/DDK-class "
                "orbits need tempo2/PINT)")
    else:
        # infer from the element set; refuse ambiguity loudly
        if ell1_keys and bt_keys:
            raise ValueError(
                "timing/binary: parfile mixes ELL1 keys "
                f"({', '.join(ell1_keys)}) and BT keys "
                f"({', '.join(bt_keys)}) without a BINARY line — add "
                "'BINARY ELL1' or 'BINARY BT'")
        if ell1_keys:
            kind = "ELL1"
        elif bt_keys:
            kind = "BT"
        else:
            raise ValueError(
                "timing/binary: parfile carries PB/A1 but neither an "
                "ELL1 (TASC/EPS1/EPS2) nor a BT (T0/ECC/OM) element "
                "set — the orbit is underspecified")

    pb = _fget(par, "PB")
    a1 = _fget(par, "A1")
    missing = [k for k, v in (("PB", pb), ("A1", a1)) if v is None]
    if kind == "ELL1":
        tref = par.get("TASC")
        if tref is None:
            missing.append("TASC")
    else:
        tref = par.get("T0")
        if tref is None:
            missing.append("T0")
    if missing:
        raise ValueError(
            f"timing/binary: incomplete {kind} binary parfile — "
            f"missing {', '.join(sorted(missing))} (a partial orbit "
            "would be silently mistimed; complete it or remove every "
            "binary key)")
    if pb <= 0:
        raise ValueError(f"timing/binary: PB must be positive, got {pb}")

    # digit-exact reference-epoch split (same stance as tim.read_tim)
    tref_s = str(tref)
    if "." in tref_s and "E" not in tref_s.upper():
        day_s, frac_s = tref_s.split(".", 1)
        tref_int, tref_frac = int(day_s), float("0." + frac_s)
    else:
        tref_f = float(tref_s.replace("D", "E").replace("d", "e"))
        tref_int = int(tref_f // 1.0)
        tref_frac = tref_f - tref_int

    kw = dict(kind=kind, pb=pb, a1=a1, tref_int=tref_int,
              tref_frac=tref_frac,
              pbdot=_fget(par, "PBDOT", 0.0) or 0.0,
              xdot=(_fget(par, "XDOT", None)
                    if par.get("XDOT") is not None
                    else _fget(par, "A1DOT", 0.0)) or 0.0)
    if kind == "ELL1":
        kw.update(eps1=_fget(par, "EPS1", 0.0) or 0.0,
                  eps2=_fget(par, "EPS2", 0.0) or 0.0,
                  eps1dot=_fget(par, "EPS1DOT", 0.0) or 0.0,
                  eps2dot=_fget(par, "EPS2DOT", 0.0) or 0.0)
    else:
        ecc = _fget(par, "ECC")
        if ecc is None:
            ecc = _fget(par, "E", 0.0) or 0.0
        if not 0.0 <= ecc < 0.95:
            raise ValueError(
                "timing/binary: BT eccentricity must sit in [0, 0.95) "
                f"for the fixed-iteration Kepler solve, got {ecc}")
        kw.update(ecc=ecc, om=_fget(par, "OM", 0.0) or 0.0)
    return BinaryParams(**kw)


# ---------------------------------------------------------------------------
# ELL1 (Lange et al. 2001)
# ---------------------------------------------------------------------------

def _ell1_core(xp, dt, pb_s, a1, eps1, eps2, pbdot, xdot,
               eps1dot, eps2dot):
    """Shared ELL1 math over an array module xp (numpy or jax.numpy):
    returns (delay, partials wrt (pb_s, a1, tasc_s, eps1, eps2)), all
    in seconds (per second / per lt-s / per unit-eps)."""
    u = dt / pb_s  # orbits since TASC
    phi = 2.0 * np.pi * (u - 0.5 * pbdot * u * u)
    x = a1 + xdot * dt
    eta = eps1 + eps1dot * dt
    kap = eps2 + eps2dot * dt
    s1, c1 = xp.sin(phi), xp.cos(phi)
    s2, c2 = 2.0 * s1 * c1, 1.0 - 2.0 * s1 * s1  # sin2Φ, cos2Φ exactly
    shape = s1 + 0.5 * kap * s2 - 0.5 * eta * c2
    delay = x * shape
    # dΔ/dΦ, then the chain through Φ's PB and TASC dependence
    ddelay_dphi = x * (c1 + kap * c2 + eta * s2)
    dphi_dpb = -2.0 * np.pi * (u / pb_s) * (1.0 - pbdot * u)
    dphi_dtasc = -2.0 * np.pi * (1.0 / pb_s) * (1.0 - pbdot * u)
    d_pb = ddelay_dphi * dphi_dpb
    d_a1 = shape
    # TASC also enters through dt in x(t), η(t), κ(t); those secular
    # terms are second-order tiny but free to carry exactly
    d_tasc = (ddelay_dphi * dphi_dtasc
              - xdot * shape
              - x * (0.5 * eps2dot * s2 - 0.5 * eps1dot * c2))
    d_eps1 = -0.5 * x * c2
    d_eps2 = 0.5 * x * s2
    return delay, (d_pb, d_a1, d_tasc, d_eps1, d_eps2)


def ell1_delay_np(dt, pb_s, a1, eps1, eps2, pbdot=0.0, xdot=0.0,
                  eps1dot=0.0, eps2dot=0.0):
    """Host-NumPy oracle: ELL1 Roemer delay [s] at dt seconds past
    TASC.  pb_s in SECONDS (callers convert from parfile days)."""
    dt = np.asarray(dt, np.float64)
    return _ell1_core(np, dt, pb_s, a1, eps1, eps2, pbdot, xdot,
                      eps1dot, eps2dot)[0]


def ell1_delay_and_partials(dt, pb_s, a1, eps1, eps2, pbdot=0.0,
                            xdot=0.0, eps1dot=0.0, eps2dot=0.0):
    """Jittable f64 op: (delay [s], partials (5, n) wrt
    (pb_s, a1, tasc_s, eps1, eps2)).  Pure jax.numpy — safe under
    jit/vmap; f64 end-to-end (jax_enable_x64 is package policy)."""
    import jax.numpy as jnp

    dt = jnp.asarray(dt, jnp.float64)
    delay, parts = _ell1_core(jnp, dt, pb_s, a1, eps1, eps2, pbdot,
                              xdot, eps1dot, eps2dot)
    return delay, jnp.stack([jnp.broadcast_to(p, dt.shape)
                             for p in parts])


# ---------------------------------------------------------------------------
# BT (Blandford & Teukolsky 1976)
# ---------------------------------------------------------------------------

def _kepler_E_np(M, ecc):
    """Newton-solve E − e·sinE = M with the same fixed iteration count
    as the jittable op, so oracle and device agree to round-off."""
    E = np.array(M, np.float64, copy=True)
    for _ in range(KEPLER_NEWTON_ITERS):
        E = E - (E - ecc * np.sin(E) - M) / (1.0 - ecc * np.cos(E))
    return E


def _bt_core(xp, E, dt, pb_s, a1, ecc, om_rad, pbdot, xdot):
    """Shared BT math given the solved eccentric anomaly E: returns
    (delay, partials wrt (pb_s, a1, t0_s, ecc, om_rad))."""
    sE, cE = xp.sin(E), xp.cos(E)
    so, co = np.sin(om_rad), np.cos(om_rad)
    rt = np.sqrt(1.0 - ecc * ecc)  # ecc is a host scalar < 0.95
    x = a1 + xdot * dt
    delay = x * so * (cE - ecc) + x * co * rt * sE
    # dΔ/dE, then E's dependence on (M, e): dE/dM = 1/(1−e·cosE),
    # dE/de|_M = sinE/(1−e·cosE)
    ddelay_dE = -x * so * sE + x * co * rt * cE
    dE_dM = 1.0 / (1.0 - ecc * cE)
    u = dt / pb_s
    dM_dpb = -2.0 * np.pi * (u / pb_s) * (1.0 - pbdot * u)
    dM_dt0 = -2.0 * np.pi * (1.0 / pb_s) * (1.0 - pbdot * u)
    d_pb = ddelay_dE * dE_dM * dM_dpb
    d_a1 = so * (cE - ecc) + co * rt * sE
    d_t0 = ddelay_dE * dE_dM * dM_dt0 - xdot * d_a1
    d_ecc = (ddelay_dE * dE_dM * sE          # through E at fixed M
             - x * so                         # explicit −e term
             - x * co * sE * (ecc / rt))      # through √(1−e²)
    d_om = x * co * (cE - ecc) - x * so * rt * sE
    return delay, (d_pb, d_a1, d_t0, d_ecc, d_om)


def bt_delay_np(dt, pb_s, a1, ecc, om_deg, pbdot=0.0, xdot=0.0):
    """Host-NumPy oracle: BT Roemer delay [s] at dt seconds past T0."""
    dt = np.asarray(dt, np.float64)
    u = dt / pb_s
    M = 2.0 * np.pi * (u - 0.5 * pbdot * u * u)
    E = _kepler_E_np(M, ecc)
    om_rad = np.deg2rad(om_deg)
    return _bt_core(np, E, dt, pb_s, a1, ecc, om_rad, pbdot, xdot)[0]


def bt_delay_and_partials(dt, pb_s, a1, ecc, om_deg, pbdot=0.0,
                          xdot=0.0):
    """Jittable f64 op: (delay [s], partials (5, n) wrt
    (pb_s, a1, t0_s, ecc, om_rad)).  Kepler's equation is solved by a
    fixed-count Newton loop (lax.fori_loop — static trip count, so the
    program shape never depends on the data)."""
    import jax.numpy as jnp
    from jax import lax

    dt = jnp.asarray(dt, jnp.float64)
    u = dt / pb_s
    M = 2.0 * jnp.pi * (u - 0.5 * pbdot * u * u)

    def newton(_, E):
        return E - (E - ecc * jnp.sin(E) - M) / (1.0 - ecc * jnp.cos(E))

    E = lax.fori_loop(0, KEPLER_NEWTON_ITERS, newton, M)
    om_rad = np.deg2rad(om_deg)
    delay, parts = _bt_core(jnp, E, dt, pb_s, a1, ecc, om_rad, pbdot,
                            xdot)
    return delay, jnp.stack([jnp.broadcast_to(p, dt.shape)
                             for p in parts])


# ---------------------------------------------------------------------------
# Dispatch by BinaryParams
# ---------------------------------------------------------------------------

def binary_delay_np(bp, mjd_int, mjd_frac):
    """Delay [s] at the given epochs for a parsed BinaryParams — the
    host-NumPy lane (synth injection, oracles)."""
    dt = bp.dt_seconds(mjd_int, mjd_frac)
    if bp.kind == "ELL1":
        return ell1_delay_np(dt, bp.pb * SECPERDAY, bp.a1, bp.eps1,
                             bp.eps2, bp.pbdot, bp.xdot, bp.eps1dot,
                             bp.eps2dot)
    return bt_delay_np(dt, bp.pb * SECPERDAY, bp.a1, bp.ecc, bp.om,
                       bp.pbdot, bp.xdot)


def binary_delay_and_partials(bp, mjd_int, mjd_frac):
    """(delay [s], partials (5, n)) via the jittable ops, with the
    PB and TASC/T0 partials converted to PARFILE units (per day), and
    the BT ω partial converted to per degree — ready to drop into the
    GLS design matrix as d(delay)/d(param) columns.

    Column order matches ``bp.param_names``.
    """
    import jax.numpy as jnp

    dt = bp.dt_seconds(mjd_int, mjd_frac)
    if bp.kind == "ELL1":
        delay, parts = ell1_delay_and_partials(
            dt, bp.pb * SECPERDAY, bp.a1, bp.eps1, bp.eps2, bp.pbdot,
            bp.xdot, bp.eps1dot, bp.eps2dot)
        scale = jnp.array([SECPERDAY, 1.0, SECPERDAY, 1.0, 1.0])
    else:
        delay, parts = bt_delay_and_partials(
            dt, bp.pb * SECPERDAY, bp.a1, bp.ecc, bp.om, bp.pbdot,
            bp.xdot)
        scale = jnp.array([SECPERDAY, 1.0, SECPERDAY, 1.0,
                           np.pi / 180.0])
    return delay, parts * scale[:, None]
