"""FFT-based rotation (phase shifting / dedispersion) of profiles and
portraits.

The reference implements four separate rotate functions with per-channel
Python loops (reference pplib.py:2427-2669); here a single set of
broadcasting kernels covers profiles (nbin,), portraits (nchan, nbin)
and full cubes (nsub, npol, nchan, nbin), all jit/vmap-friendly.

Sign convention: positive (phi, DM) rotate to *earlier* phase, i.e.
rotating data by a fit's result aligns it with the template, and
rotating with the data's own DM dedisperses it.
"""

import jax.numpy as jnp

from .phasor import cexp, phase_shifts, phasor
from .fourier import irfft_c, rfft_c


def fft_shift_bins(profile, shift_bins):
    """Shift a profile to earlier phase by ``shift_bins`` bins
    (non-integer allowed) via the FFT shift theorem."""
    nbin = profile.shape[-1]
    pFT = rfft_c(profile)
    k = jnp.arange(pFT.shape[-1], dtype=profile.dtype)
    pFT = pFT * cexp(2.0 * jnp.pi * k * (shift_bins / nbin))
    return irfft_c(pFT, n=nbin)


def rotate_profile(profile, phi):
    """Rotate a 1-D profile to earlier phase by phi [rot].

    Parity: reference pplib.py:2641-2652.
    """
    nbin = profile.shape[-1]
    return fft_shift_bins(profile, phi * nbin)


def rotate_portrait(port, phi, DM=0.0, P=None, freqs=None, nu_ref=jnp.inf):
    """Rotate a (…, nchan, nbin) portrait by phi [rot] and DM [pc cm^-3].

    With the data's own (DM, nu_ref=inf) this is dedispersion —
    behaviorally equivalent to PSRCHIVE's arch.dedisperse() per the
    reference's own oracle (reference pplib.py:2518-2550, 2526-2527).
    """
    port = jnp.asarray(port)
    nbin = port.shape[-1]
    pFT = rfft_c(port)
    if freqs is None:
        delays = jnp.asarray(phi)[..., None] * jnp.ones(port.shape[-2], pFT.real.dtype)
    else:
        delays = phase_shifts(phi, DM, 0.0, freqs, P, nu_ref, 1.0)
    ph = phasor(delays, pFT.shape[-1])
    return irfft_c(pFT * ph, n=nbin)


def rotate_full(cube, phi, DM, Ps, freqs, nu_ref=jnp.inf):
    """Rotate a (nsub, npol, nchan, nbin) cube with per-subint periods
    ``Ps`` (nsub,) and per-subint frequencies ``freqs`` (nsub, nchan).

    Parity: reference pplib.py:2427-2515 (4-D path).
    """
    cube = jnp.asarray(cube)
    nbin = cube.shape[-1]
    cFT = rfft_c(cube)
    # delays: (nsub, nchan) -> broadcast over npol
    delays = phase_shifts(phi, DM, 0.0, freqs, Ps[:, None], nu_ref, 1.0)
    ph = phasor(delays, cFT.shape[-1])  # (nsub, nchan, nharm)
    return irfft_c(cFT * ph[:, None, :, :], n=nbin)


def add_DM_nu(port, phi, DM_coeffs, powers, P, freqs, nu_ref):
    """Rotate a portrait by an arbitrary sum of power-law dispersion
    terms: t_n = phi + (Dconst/P) * sum_j C_j (nu**x_j - nu_ref**x_j).

    Used by the synthetic-data generator to inject non-nu^-2 DM(nu)
    structure.  Parity: reference pplib.py:2601-2638.
    """
    from ..config import Dconst

    port = jnp.asarray(port)
    nbin = port.shape[-1]
    freqs = jnp.asarray(freqs)
    DM_coeffs = jnp.asarray(DM_coeffs, dtype=port.dtype)
    powers = jnp.asarray(powers, dtype=port.dtype)
    terms = DM_coeffs[:, None] * (
        freqs[None, :] ** powers[:, None] - nu_ref ** powers[:, None]
    )
    delays = phi + (Dconst / P) * jnp.sum(terms, axis=0)
    pFT = rfft_c(port)
    ph = phasor(delays, pFT.shape[-1])
    return irfft_c(pFT * ph, n=nbin)


def fft_rotate(arr, bins):
    """Rotate a 1-D series LEFT by ``bins`` places (can be fractional):
    y(n) = x(n + bins), i.e. np.roll(x, -bins) for integers — the
    reference's PRESTO-style testing helper (pplib.py:2655-2669).

    Implemented as its own phase ramp (not via rotate_profile), so it
    serves as an independent oracle for the main rotation kernels:
    fft_rotate(x, b) == rotate_profile(x, b/nbin).
    """
    arr = jnp.asarray(arr)
    nbin = arr.shape[-1]
    dt = jnp.result_type(arr.dtype, jnp.float32)
    b = jnp.asarray(bins, dt)
    k = jnp.arange(nbin // 2 + 1, dtype=dt)
    ramp = jnp.exp(2.0j * jnp.pi * k * b / nbin)
    return irfft_c(rfft_c(arr.astype(dt)) * ramp, n=nbin)
