"""Sharded batched fits: the scale-out execution path.

`fit_portrait_sharded` runs `fit_portrait_batch`'s core under jit with
input shardings on a ('data', 'chan') mesh: the batch axis is split
across 'data' (pure data parallelism over archives/subints), and the
channel axis of each portrait across 'chan' (XLA inserts psum
collectives over ICI for the chi^2 channel reductions).  Replaces the
reference's sequential per-archive Python loop (pptoas.py:258-384).
"""

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..fit.portrait import (FitFlags, _fit_portrait_core, derive_use_scatter,
                            fast_fit_one, make_weights)
from .mesh import batch_sharding
from ..ops.fourier import rfft_c


def shard_batch(mesh, arrays, chan_axis=None):
    """Device-put a pytree of batched arrays with leading-axis 'data'
    sharding (and optional channel-axis sharding)."""
    return jax.tree.map(
        lambda a: jax.device_put(
            a, batch_sharding(mesh, jnp.ndim(a), chan_axis)
        ),
        arrays,
    )


def fit_portrait_sharded(
    mesh,
    ports,
    models,
    noise_stds,
    freqs,
    P_s,
    nu_fit,
    theta0=None,
    nu_out=None,
    fit_flags=FitFlags(),
    log10_tau=False,
    max_iter=40,
    shard_channels=False,
    use_scatter=None,
):
    """Batched (nb, nchan, nbin) portrait fit sharded over the mesh.

    freqs may be (nchan,) shared or (nb, nchan); P_s/nu_fit scalar or
    (nb,).  Returns a FitResult with batched leaves (still sharded;
    use jax.device_get to fetch).  use_scatter: None -> derived from
    fit_flags/log10_tau/theta0 so a fixed nonzero tau is not ignored.
    """
    if use_scatter is None:
        use_scatter = derive_use_scatter(fit_flags, log10_tau, theta0)
    ports = jnp.asarray(ports)
    nb, nchan, nbin = ports.shape
    w = make_weights(noise_stds, nbin, dtype=ports.dtype)
    dFT = rfft_c(ports)
    mFT = rfft_c(jnp.asarray(models).astype(ports.dtype))
    dt = w.dtype
    freqs = jnp.asarray(freqs, dt)
    P_s = jnp.broadcast_to(jnp.asarray(P_s, dt), (nb,))
    nu_fit = jnp.broadcast_to(jnp.asarray(nu_fit, dt), (nb,))
    nu_out_val = jnp.full((nb,), -1.0 if nu_out is None else nu_out, dt)
    if theta0 is None:
        theta0 = jnp.zeros((nb, 5), dt)

    f_ax = 0 if freqs.ndim == 2 else None
    core = jax.vmap(
        partial(
            _fit_portrait_core,
            fit_flags=FitFlags(*[bool(f) for f in fit_flags]),
            log10_tau=log10_tau,
            max_iter=max_iter,
            use_ir=False,
            use_scatter=use_scatter,
        ),
        in_axes=(0, 0, 0, f_ax, 0, 0, 0, 0),
    )

    chan_axis = 1 if shard_channels else None
    sh3 = batch_sharding(mesh, 3, chan_axis)  # (nb, nchan, nharm)
    sh_theta = batch_sharding(mesh, 2)  # (nb, 5): batch only
    sh1 = batch_sharding(mesh, 1)
    shf = (
        batch_sharding(mesh, 2, chan_axis)
        if freqs.ndim == 2
        else NamedSharding(mesh, P("chan") if shard_channels else P())
    )

    jitted = jax.jit(
        core,
        in_shardings=(sh3, sh3, sh3, shf, sh1, sh1, sh1, sh_theta),
    )
    dFT = jax.device_put(dFT, sh3)
    mFT = jax.device_put(mFT, sh3)
    w = jax.device_put(w, sh3)
    return jitted(dFT, mFT, w, freqs, P_s, nu_fit, nu_out_val, theta0)


def fit_portrait_sharded_fast(
    mesh,
    ports,
    models,
    noise_stds,
    freqs,
    P_s,
    nu_fit,
    theta0=None,
    nu_out=None,
    fit_flags=FitFlags(),
    chan_masks=None,
    max_iter=40,
    shard_channels=False,
    log10_tau=False,
    compensated=None,
    harmonic_window=None,
):
    """fit_portrait_sharded through the complex-free real-arithmetic
    cores: matmul DFTs, CCF seed, and the Newton loop in one sharded
    program — the scale-out path for TPU runtimes that cannot compile
    complex FFTs.  No-scattering fits run _fit_portrait_core_real's
    3-moment pass; scattering fits (tau/alpha flags, log10_tau, or a
    fixed nonzero tau seed) the fused analytic _cgh_scatter lane
    (fast_scatter_fit_one) — both complex-free end to end.

    models may be (nb, nchan, nbin) or a shared (nchan, nbin) template.
    The moment passes run the fused XLA reductions, which shard cleanly
    (psum over 'chan' for the channel reductions).
    harmonic_window: as fit_portrait_batch_fast — band-limits both fast
    lanes to the template's spectral support ('auto' needs a host
    numpy model).
    """
    from ..fit.portrait import (derive_use_scatter,
                                reject_fixed_tau_seed,
                                resolve_harmonic_window,
                                use_scatter_compensated)

    use_scatter = derive_use_scatter(fit_flags, log10_tau, theta0)
    if not use_scatter:
        reject_fixed_tau_seed(theta0, "fit_portrait_sharded_fast")
    if compensated is None:
        compensated = use_scatter_compensated()
    ports = jnp.asarray(ports)
    nb, nchan, nbin = ports.shape
    dt = ports.dtype
    nharm_eff = resolve_harmonic_window(harmonic_window, models, nbin)
    models = jnp.asarray(models, dt)
    m_ax = 0 if models.ndim == 3 else None
    freqs = jnp.asarray(freqs, dt)
    f_ax = 0 if freqs.ndim == 2 else None
    P_s = jnp.broadcast_to(jnp.asarray(P_s, dt), (nb,))
    nu_fit = jnp.broadcast_to(jnp.asarray(nu_fit, dt), (nb,))
    nu_out_val = jnp.full((nb,), -1.0 if nu_out is None else nu_out, dt)
    if theta0 is None:
        theta0 = jnp.zeros((nb, 5), dt)
    theta0 = jnp.asarray(theta0, dt)
    if chan_masks is None:
        chan_masks = jnp.ones((nb, nchan), dt)
    chan_masks = jnp.asarray(chan_masks, dt)
    noise_stds = jnp.asarray(noise_stds, dt)
    flags = FitFlags(*[bool(f) for f in fit_flags])

    jitted, shardings = _sharded_fast_fn(
        mesh, flags, int(max_iter), m_ax, f_ax,
        bool(shard_channels), use_scatter=bool(use_scatter),
        log10_tau=bool(log10_tau), compensated=bool(compensated),
        nharm_eff=nharm_eff)
    sh3, shm, sh2c, _, _, _ = shardings
    ports = jax.device_put(ports, sh3)
    models = jax.device_put(models, shm)
    noise_stds = jax.device_put(noise_stds, sh2c)
    chan_masks = jax.device_put(chan_masks, sh2c)
    return jitted(ports, models, noise_stds, chan_masks, freqs, P_s,
                  nu_fit, nu_out_val, theta0)


def align_iteration_sharded(mesh, ports, model, noise_stds, chan_masks,
                            freqs, P_s, fit_dm=True, max_iter=20,
                            shard_channels=False):
    """ONE ppalign iteration on the device mesh: the batched
    (phi[, DM]) fit of every (archive, subint) against the shared
    template AND the template update — back-rotation plus
    scales/sigma^2-weighted accumulation (reference ppalign.py:220-248)
    — in a single sharded program.  The batch-axis reduction of the
    accumulate lowers to a psum over 'data' (the cross-chip collective
    of the align workload); everything stays complex-free (matmul DFT
    rotation), so the same program shape runs on TPU runtimes.

    ports: (nb, nchan, nbin); model: shared (nchan, nbin) template;
    noise_stds/chan_masks: (nb, nchan); freqs: (nchan,); P_s: (nb,).
    Returns (new_template (nchan, nbin) replicated jax.Array,
    FitResult) — the template is fully reduced (replicated
    out-sharding); np.asarray it for host use or feed it to the next
    iteration as-is.
    """
    ports = jnp.asarray(ports)
    nb, nchan, nbin = ports.shape
    dt = ports.dtype
    model = jnp.asarray(model, dt)
    freqs = jnp.asarray(freqs, dt)
    P_s = jnp.broadcast_to(jnp.asarray(P_s, dt), (nb,))
    noise_stds = jnp.asarray(noise_stds, dt)
    chan_masks = jnp.asarray(chan_masks, dt)
    flags = FitFlags(True, bool(fit_dm), False, False, False)

    jitted = _sharded_align_fn(mesh, flags, int(max_iter),
                               bool(shard_channels))
    sh3 = batch_sharding(mesh, 3, 1 if shard_channels else None)
    sh2c = batch_sharding(mesh, 2, 1 if shard_channels else None)
    ports = jax.device_put(ports, sh3)
    noise_stds = jax.device_put(noise_stds, sh2c)
    chan_masks = jax.device_put(chan_masks, sh2c)
    new_template, res = jitted(ports, model, noise_stds, chan_masks,
                               freqs, P_s)
    return new_template, res


@lru_cache(maxsize=None)
def _sharded_align_fn(mesh, flags, max_iter, shard_channels):
    """Cached sharded jit of one align iteration (fit + rotate +
    weighted template reduction)."""
    from ..ops.fourier import irfft_mm, rfft_mm
    from ..ops.phasor import phase_shifts

    def rotate_real(port, t_n):
        """Rotate each channel to earlier phase by t_n [rot] via the
        matmul DFT (same convention as ops.rotation.rotate_portrait:
        phasor exp(+2 pi i k t))."""
        nbin = port.shape[-1]
        k = jnp.arange(nbin // 2 + 1, dtype=port.dtype)
        ang = 2.0 * jnp.pi * t_n[:, None] * k
        c, s = jnp.cos(ang), jnp.sin(ang)
        Xr, Xi = rfft_mm(port)
        return irfft_mm(Xr * c - Xi * s, Xr * s + Xi * c, nbin)

    def run(ports, model, noise_stds, chan_masks, freqs, P_s):
        dt = ports.dtype
        nu0 = jnp.mean(freqs)
        nb = ports.shape[0]
        one = partial(fast_fit_one, fit_flags=flags, max_iter=max_iter)
        res = jax.vmap(one, in_axes=(0, None, 0, 0, None, 0, None, None,
                                     0))(
            ports, model, noise_stds, chan_masks, freqs, P_s, nu0,
            nu0, jnp.zeros((nb, 5), dt))
        t_n = jax.vmap(
            lambda ph, dm, p: phase_shifts(ph, dm, 0.0, freqs, p, nu0,
                                           nu0)
        )(res.phi, res.DM, P_s)
        rot = jax.vmap(rotate_real)(ports, t_n)
        good = noise_stds > 0.0
        inv = jnp.where(good, 1.0 / jnp.where(good, noise_stds, 1.0) ** 2,
                        0.0)
        w = chan_masks * jnp.maximum(res.scales, 0.0) * inv  # (nb, nchan)
        # the cross-device collective: reductions over the sharded
        # batch axis (psum over 'data')
        aligned = jnp.sum(rot * w[:, :, None], axis=0)
        wsum = jnp.sum(w, axis=0)
        new_template = aligned / jnp.maximum(wsum, _ALIGN_TINY)[:, None]
        return new_template, res

    sh3 = batch_sharding(mesh, 3, 1 if shard_channels else None)
    sh2c = batch_sharding(mesh, 2, 1 if shard_channels else None)
    sh1 = batch_sharding(mesh, 1)
    rep = NamedSharding(mesh, P())
    shm = NamedSharding(mesh, P("chan", None) if shard_channels else P())
    shf = NamedSharding(mesh, P("chan") if shard_channels else P())
    return jax.jit(run, in_shardings=(sh3, shm, sh2c, sh2c, shf, sh1),
                   out_shardings=(rep, None))


_ALIGN_TINY = 1e-30

# ---------------------------------------------------------------------------
# Single-device align iteration: the split-real rotate-and-accumulate
# equivalent of align_iteration_sharded for the single-process
# CLI/pipeline path (pipeline/align.align_archives, config.align_device).
# The template update accumulates in the HARMONIC domain on the default
# device — phasor rotation is a split-real (cos, sin) multiply on the
# spectra, the weighted sum over subints stays on-chip with the
# accumulator buffers DONATED across calls, and ONE irfft per iteration
# recovers the average.  The DFTs dispatch through ops.fourier.rfft_sr:
# matmul weights on TPU (no complex dtypes anywhere in the program, so
# it compiles on runtimes that reject c64/c128), jnp.fft on backends
# with a working FFT (CPU f64 matmul DFTs would cost ~n/log n times the
# FLOPs).
# ---------------------------------------------------------------------------

# Subints per accumulate dispatch: bounds the transient (Cr, Ci, phasor)
# HBM footprint to ~4 * chunk * npol * nchan * nharm floats while keeping
# ONE compiled program per (chunk, npol, nchan, nbin, dtype) shape —
# callers zero-pad the batch axis (w = 0 rows contribute exactly nothing).
ALIGN_DEVICE_CHUNK = 64


def use_align_device(setting=None):
    """Whether align_archives should run its rotate-and-accumulate on
    the default device: config.align_device (True/False force; 'auto' =
    TPU backends, where the chunked c128 host accumulate idles the
    chip).  Read per call, so in-process A/B flips take effect.
    setting: an explicit per-call override (align_archives'
    align_device= argument / ppalign --align-device); None -> config."""
    if setting is None:
        from .. import config

        setting = getattr(config, "align_device", "auto")
    from ..tune.capability import resolve_auto

    # strict like config's other tri-state knobs — a typo must not
    # silently mean 'auto'; resolve_auto enforces it
    return resolve_auto("align_device", setting)


def _align_rotate_real(cube_r, cube_i, delays):
    """Split-real phasor rotation of per-subint harmonic stacks:
    (Cr + i Ci) * exp(+2 pi i k t) expanded into real parts.  Rotating
    by positive delays moves features to earlier phase — the same
    convention as ops.phasor.phasor / ops.rotation.rotate_portrait.

    cube_r/cube_i: (nb, npol, nchan, nharm); delays: (nb, nchan) [rot].
    Shared by the accumulate program and the bench attribution's
    'rotate' prefix stage (benchmarks/attrib.py), so the profiled stage
    is the production math, not a re-creation."""
    k = jnp.arange(cube_r.shape[-1], dtype=cube_r.dtype)
    ang = 2.0 * jnp.pi * delays[..., None] * k          # (nb, nchan, K)
    c, s = jnp.cos(ang)[:, None], jnp.sin(ang)[:, None]
    return cube_r * c - cube_i * s, cube_r * s + cube_i * c


@lru_cache(maxsize=None)
def _align_weights_fn(dt_str):
    """Cached jit of the per-archive (delays, weights) computation on
    device — the fit results never round-trip to the host (on a
    tunneled TPU a (nb, nchan) f64 pull costs more than the whole
    dispatch).  Same formulas as the host path (pipeline/align.py):
    delays = phase_shifts(phi, DM, GM=0) and w = mask * max(scales, 0)
    / noise**2 with non-positive noise zero-weighted."""
    from ..ops.phasor import phase_shifts

    def run(phi, DM, nu_ref, P_s, freqs, noise, masks, scales):
        delays = phase_shifts(phi[:, None], DM[:, None], 0.0,
                              freqs[None, :], P_s[:, None],
                              nu_ref[:, None], 1.0)
        # wrap to [-0.5, 0.5): integer-harmonic phasors are 1-periodic,
        # and small arguments keep the f32 trig on TPU accurate
        delays = delays - jnp.round(delays)
        good = noise > 0.0
        inv = jnp.where(good, 1.0 / jnp.where(good, noise, 1.0) ** 2, 0.0)
        # masked channels must weight EXACTLY zero even when the fit
        # left NaN scales there (0 * NaN = NaN would poison the stack)
        w = jnp.where(masks > 0.0,
                      masks * jnp.maximum(scales, 0.0) * inv, 0.0)
        return delays, w

    return jax.jit(run)


@lru_cache(maxsize=None)
def _align_accum_fn(dt_str, prec, mm):
    """Cached donated jit of ONE accumulate chunk.  The lru key carries
    the resolved DFT precision AND the DFT-dispatch arm (matmul vs
    jnp.fft, ops.fourier.rfft_sr) so config flips retrace instead of
    silently reusing the other arm's program; shapes key the underlying
    jit cache as usual."""
    from ..ops.fourier import rfft_sr

    def run(acc_r, acc_i, wacc, cube, delays, w):
        # cube: (C, npol, nchan, nbin); delays/w: (C, nchan)
        cr, ci = rfft_sr(cube, precision=prec)
        rr, ri = _align_rotate_real(cr, ci, delays)
        wb = w[:, None, :, None]
        acc_r = acc_r + jnp.sum(rr * wb, axis=0)
        acc_i = acc_i + jnp.sum(ri * wb, axis=0)
        wacc = wacc + jnp.sum(w, axis=0)
        return acc_r, acc_i, wacc

    return jax.jit(run, donate_argnums=(0, 1, 2))


@lru_cache(maxsize=None)
def _align_finalize_fn(dt_str, nbin, prec, mm):
    """Cached jit of the iteration's ONE irfft + weight normalization."""
    from ..ops.fourier import irfft_sr

    def run(acc_r, acc_i, wacc):
        aligned = irfft_sr(acc_r, acc_i, n=nbin, precision=prec)
        return aligned / jnp.maximum(wacc, _ALIGN_TINY)[:, None]

    return jax.jit(run)


def _align_precision():
    """Alignment math follows the complex-interface precision policy:
    config.dft_precision with 'default' clamped up to 'high'
    (ops.fourier._gated_precision) — the single-pass-bf16 setting is
    validated only for the portrait fit's gates."""
    from ..ops.fourier import _gated_precision

    return _gated_precision(None)


def _align_chunk(nb, chunk):
    """Bucketed chunk size: the configured chunk when the batch fills
    it, else the next power of two >= nb — padding waste stays <= 2x
    for small archives while the compiled-program count stays
    O(log chunk) across archive sizes (a per-size program would
    recompile for every distinct nsub in a campaign)."""
    if nb >= chunk:
        return chunk
    c = 1
    while c < nb:
        c <<= 1
    return c


def align_accumulator_init(npol, nchan, nbin, dtype):
    """Fresh zeroed device accumulators (acc_r, acc_i, wacc) for one
    align iteration; feed to align_accumulate_archive and finish with
    align_finalize.  The buffers are donated by every accumulate call,
    so hold no other references to them."""
    k = nbin // 2 + 1
    return (jnp.zeros((npol, nchan, k), dtype),
            jnp.zeros((npol, nchan, k), dtype),
            jnp.zeros((nchan,), dtype))


def align_accumulate_archive(acc, cube, phi, DM, nu_ref, P_s, freqs,
                             noise, masks, scales,
                             chunk=ALIGN_DEVICE_CHUNK):
    """Accumulate one archive's weighted, back-rotated subints into the
    donated harmonic accumulators (the device-resident core of one
    align_archives iteration; reference ppalign.py:236-242).

    acc: (acc_r, acc_i, wacc) from align_accumulator_init (donated and
    replaced).  cube: (nb, npol, nchan, nbin) device or host array;
    phi/DM/nu_ref/scales may be device arrays straight from the batched
    fit — nothing here forces a host sync.  Returns the new acc tuple.
    """
    from ..ops.fourier import use_matmul_dft

    acc_r, acc_i, wacc = acc
    dt = acc_r.dtype
    cube = jnp.asarray(cube, dt)
    nb = cube.shape[0]
    chunk = _align_chunk(nb, chunk)
    dt_str = str(dt)
    prec = _align_precision()
    delays, w = _align_weights_fn(dt_str)(
        jnp.asarray(phi, dt), jnp.asarray(DM, dt),
        jnp.asarray(nu_ref, dt), jnp.asarray(P_s, dt),
        jnp.asarray(freqs, dt), jnp.asarray(noise, dt),
        jnp.asarray(masks, dt), jnp.asarray(scales, dt))
    step = _align_accum_fn(dt_str, prec, use_matmul_dft())
    for lo in range(0, nb, chunk):
        cc = cube[lo:lo + chunk]
        dd = delays[lo:lo + chunk]
        ww = w[lo:lo + chunk]
        m = cc.shape[0]
        if m != chunk:
            # zero-weight padding rows contribute exactly nothing;
            # padding the tail keeps ONE compiled accumulate program
            # across archive sizes
            cc = jnp.pad(cc, ((0, chunk - m),) + ((0, 0),) * (cc.ndim - 1))
            dd = jnp.pad(dd, ((0, chunk - m), (0, 0)))
            ww = jnp.pad(ww, ((0, chunk - m), (0, 0)))
        acc_r, acc_i, wacc = step(acc_r, acc_i, wacc, cc, dd, ww)
    return acc_r, acc_i, wacc


def align_finalize(acc, nbin):
    """The iteration's single irfft + weight normalization: harmonic
    accumulators -> (npol, nchan, nbin) average portrait (device)."""
    from ..ops.fourier import use_matmul_dft

    acc_r, acc_i, wacc = acc
    return _align_finalize_fn(str(acc_r.dtype), int(nbin),
                              _align_precision(),
                              use_matmul_dft())(acc_r, acc_i, wacc)


@lru_cache(maxsize=None)
def _sharded_fast_fn(mesh, flags, max_iter, m_ax, f_ax,
                     shard_channels, use_scatter=False, log10_tau=False,
                     compensated=False, nharm_eff=None):
    """Cached sharded jit of the shared per-element fast fit
    (fit.portrait.fast_fit_one, or fast_scatter_fit_one when the
    scattering kernel is active) — a fresh jit per call would recompile
    the full sharded program every invocation.  Mesh is hashable, so it
    keys the cache."""
    if use_scatter:
        from ..fit.portrait import fast_scatter_fit_one

        one = partial(fast_scatter_fit_one, fit_flags=flags,
                      log10_tau=log10_tau, max_iter=max_iter,
                      compensated=compensated, nharm_eff=nharm_eff)
    else:
        one = partial(fast_fit_one, fit_flags=flags, max_iter=max_iter,
                      nharm_eff=nharm_eff)
    core = jax.vmap(one, in_axes=(0, m_ax, 0, 0, f_ax, 0, 0, 0, 0))

    chan_axis = 1 if shard_channels else None
    sh3 = batch_sharding(mesh, 3, chan_axis)   # (nb, nchan, nbin)
    sh2c = batch_sharding(mesh, 2, chan_axis)  # (nb, nchan)
    sh_theta = batch_sharding(mesh, 2)
    sh1 = batch_sharding(mesh, 1)
    shm = (
        sh3 if m_ax == 0
        else NamedSharding(mesh, P("chan", None) if shard_channels else P())
    )
    shf = (
        sh2c if f_ax == 0
        else NamedSharding(mesh, P("chan") if shard_channels else P())
    )
    jitted = jax.jit(
        core,
        in_shardings=(sh3, shm, sh2c, sh2c, shf, sh1, sh1, sh1, sh_theta),
    )
    return jitted, (sh3, shm, sh2c, shf, sh_theta, sh1)
