"""BASELINE.md config 5 (multi-device): streamed wideband TOAs for a
batch of PSRFITS archives through the full pipeline — file IO, native
SUBINT decode, shape-bucketed fused fit dispatches dealt ROUND-ROBIN
across 1..N local devices (ISSUE 4), .tim assembly.

Archives are generated on the fly into a temp dir (16 archives x 16
subints x 256 chan x 1024 bin by default — sized so generation stays a
small fraction of the benchmark); the measured figure is end-to-end
wall time of stream_wideband_TOAs including IO, which is the number an
IPTA-scale campaign sees per HOST.  The sweep reports a 1 -> N device
scaling table (powers of two up to every local device) plus the
round-6-style per-stage attribution of the SERIALIZED lane
(load / stack / h2d / fit / scatter / assemble, attributed_frac >= 0.9
gate) so a scaling shortfall names its stage.

PPT_DEVICES caps the sweep; on a CPU backend it also requests that
many VIRTUAL devices (set before jax initializes), so
``PPT_DEVICES=8 python benchmarks/bench_stream.py`` reproduces the
8-virtual-device table on any host.  Output digit-identity across
device counts is asserted every run on the first archive's TOAs.

Prints ONE JSON line like bench.py.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _ensure_devices():
    """PPT_DEVICES=N requests an N-device sweep.  On a host where jax
    is not yet initialized, also force N virtual CPU devices (the
    XLA flag must be set pre-init; harmless under a TPU plugin, whose
    chips are real).  Returns the requested count or None."""
    n = os.environ.get("PPT_DEVICES", "")
    if not n:
        return None
    n = int(n)
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    return n


def run_bench(attrib_only=False):
    requested = _ensure_devices()
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()  # PPT_* A/B switches win over script defaults

    import jax

    from benchmarks.attrib import stream_stage_profile
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = int(os.environ.get("PPT_NARCH", 16))
    NSUB = int(os.environ.get("PPT_NSUB", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))
    NSUB_BATCH = int(os.environ.get("PPT_NSUBB", 64))
    # the >=8-device campaign-throughput gate (ISSUE 4 acceptance);
    # overridable for constrained hosts
    GATE = float(os.environ.get("PPT_STREAM_SPEEDUP_GATE", 1.5))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}

    ndev = len(jax.local_devices())
    maxdev = min(requested, ndev) if requested else ndev
    counts = sorted({1, maxdev} | {k for k in (2, 4, 8, 16, 32)
                                   if k < maxdev})

    with tempfile.TemporaryDirectory() as td:
        mpath = os.path.join(td, "model.gmodel")
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
        files = []
        for i in range(NARCH):
            path = os.path.join(td, f"a{i:03d}.fits")
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * i, dDM=1e-4 * i, noise_stds=0.05,
                             quiet=True, rng=i)
            files.append(path)

        # ---- serialized lane + stage attribution --------------------
        # prefetch off, one pending dispatch, one device: no overlap,
        # so the independently measured stages must SUM to this wall
        stream_wideband_TOAs(files[:1], mpath, nsub_batch=NSUB_BATCH,
                             stream_devices=1, quiet=True)  # warm
        t0 = time.perf_counter()
        stream_wideband_TOAs(files, mpath, nsub_batch=NSUB_BATCH,
                             stream_devices=1, max_inflight=1,
                             prefetch=False, quiet=True)
        serial_wall = time.perf_counter() - t0
        attrib = stream_stage_profile(files, mpath, NSUB_BATCH,
                                      serial_wall)
        if attrib_only:
            return attrib

        # ---- 1 -> N device scaling sweep ----------------------------
        # nsub_batch 64: buckets fill (and their h2d copies start, on
        # the per-device dispatch threads) while later archives load.
        # Each count runs warm-then-measure: a device's first dispatch
        # compiles its executable, and compile time is not campaign
        # throughput.  Digit-identity across counts is asserted on the
        # first archive's TOA fields.
        rows, ref_fields = [], None
        for k in counts:
            stream_wideband_TOAs(files, mpath, nsub_batch=NSUB_BATCH,
                                 stream_devices=k, quiet=True)  # warm
            t0 = time.perf_counter()
            res = stream_wideband_TOAs(files, mpath,
                                       nsub_batch=NSUB_BATCH,
                                       stream_devices=k, quiet=True)
            wall = time.perf_counter() - t0
            ntoa = len(res.TOA_list)
            fields = [(t.MJD.day, t.MJD.frac, t.DM, t.TOA_error)
                      for t in res.TOA_list if t.archive == files[0]]
            if ref_fields is None:
                ref_fields = fields
            elif fields != ref_fields:
                raise AssertionError(
                    f"{k}-device TOAs differ from the 1-device lane")
            rows.append({
                "devices": k, "toas_per_sec": round(ntoa / wall, 2),
                "wall_s": round(wall, 2),
                "devices_used": int(res.devices_used),
                "nfit": int(res.nfit),
                "fit_fraction": round(float(res.fit_duration) / wall,
                                      3),
            })

    r1 = rows[0]["toas_per_sec"]
    for row in rows:
        row["speedup"] = round(row["toas_per_sec"] / r1, 3)
        row["efficiency"] = round(row["speedup"] / row["devices"], 3)
    speedup_max = rows[-1]["speedup"]
    ntoa = NARCH * NSUB

    out = {
        "metric": f"streamed TOAs incl. PSRFITS IO, {NARCH} archives x "
                  f"{NSUB}sub x {NCHAN}ch x {NBIN}bin, "
                  f"1->{maxdev} devices",
        "value": rows[-1]["toas_per_sec"],
        "unit": "TOAs/sec",
        "wall_s": rows[-1]["wall_s"],
        "toas": ntoa,
        "single_device_toas_per_sec": r1,
        "speedup_max": speedup_max,
        # the gate only binds at >= 8 devices (the acceptance config);
        # smaller hosts report it as informational null
        "scaling_ok": (bool(speedup_max >= GATE) if maxdev >= 8
                       else None),
        "speedup_gate": GATE,
        "scaling": rows,
        "attrib_ok": bool(attrib["attributed_frac"] >= 0.9),
        "device": str(jax.devices()[0]),
        "ndev_local": ndev,
    }
    out.update(attrib)
    return out


def main():
    print(json.dumps(run_bench()))


if __name__ == "__main__":
    main()
