"""Telescope name -> TOA site code mapping.

Mirrors the reference's telescope_codes.py: prefer the observatory
tables of a TEMPO2 runtime ($TEMPO2/observatory/observatories.dat and
aliases, telescope_codes.py:7-32), falling back to a built-in table of
standard tempo/tempo2 observatory codes.
"""

import os

# name (upper) -> (one-character tempo code or itoa code, canonical name)
_BUILTIN = {
    "GBT": ("1", "gbt"),
    "GREEN BANK": ("1", "gbt"),
    "QUABBIN": ("2", "quabbin"),
    "ARECIBO": ("3", "arecibo"),
    "AO": ("3", "arecibo"),
    "HOBART": ("4", "hobart"),
    "PRINCETON": ("5", "princeton"),
    "VLA": ("6", "vla"),
    "PARKES": ("7", "pks"),
    "PKS": ("7", "pks"),
    "JODRELL": ("8", "jb"),
    "JODRELL BANK": ("8", "jb"),
    "JB": ("8", "jb"),
    "JBODFB": ("8", "jb"),
    "JBROACH": ("8", "jb"),
    "JBDFB": ("8", "jb"),
    "GB300": ("a", "gb300"),
    "GB140": ("b", "gb140"),
    "GB853": ("c", "gb853"),
    "LA PALMA": ("d", "lap"),
    "HARTEBEESTHOEK": ("e", "hart"),
    "HARTRAO": ("e", "hart"),
    "NANCAY": ("f", "ncy"),
    "NCY": ("f", "ncy"),
    "NUPPI": ("f", "ncy"),
    "EFFELSBERG": ("g", "eff"),
    "EFF": ("g", "eff"),
    "JBMK2": ("h", "jbmk2"),
    "WSRT": ("i", "wsrt"),
    "WESTERBORK": ("i", "wsrt"),
    "FAST": ("k", "fast"),
    "GMRT": ("r", "gmrt"),
    "CHIME": ("y", "chime"),
    "PRINCETON-OBS": ("5", "princeton"),
    "SRT": ("z", "srt"),
    "SARDINIA": ("z", "srt"),
    "LOFAR": ("t", "lofar"),
    "DE601": ("EF", "eflfrlba"),
    "DE602": ("UW", "uwlfrlba"),
    "DE603": ("TB", "tblfrlba"),
    "DE604": ("PO", "polfrlba"),
    "DE605": ("JU", "julfrlba"),
    "FR606": ("NC", "nclfrlba"),
    "SE607": ("ON", "onlfrlba"),
    "UK608": ("CH", "chlfrlba"),
    "MEERKAT": ("m", "meerkat"),
    "KAT-7": ("k7", "kat7"),
    "MOST": ("u", "most"),
    "MWA": ("x", "mwa"),
    "LWA": ("x", "lwa1"),
    "LWA1": ("x", "lwa1"),
    "NANSHAN": ("n", "nanshan"),
    "UAO": ("n", "nanshan"),
    "DSS_43": ("tid43", "tid43"),
    "TIDBINBILLA": ("tid43", "tid43"),
    "BARYCENTER": ("@", "bat"),
    "@": ("@", "bat"),
    "COE": ("coe", "coe"),
    "SSB": ("@", "bat"),
    "GEOCENTER": ("0", "geo"),
    "STL": ("stl", "stl"),
    "ATA": ("j", "ata"),
}


def _from_tempo2():
    """Parse $TEMPO2/observatory/{observatories.dat,aliases} into
    {ALIAS_UPPER: (code, canonical)}; returns {} when unavailable."""
    t2 = os.environ.get("TEMPO2")
    if not t2:
        return {}
    obs_path = os.path.join(t2, "observatory", "observatories.dat")
    alias_path = os.path.join(t2, "observatory", "aliases")
    if not os.path.isfile(obs_path):
        return {}
    table = {}
    canonical = {}
    try:
        with open(obs_path) as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 5 and not line.strip().startswith("#"):
                    name, code = parts[3], parts[4]
                    canonical[name.upper()] = (code, name.lower())
                    table[name.upper()] = (code, name.lower())
                    try:
                        _TEMPO2_ITRF[name.lower()] = (
                            float(parts[0]), float(parts[1]),
                            float(parts[2]))
                    except ValueError:
                        pass
        if os.path.isfile(alias_path):
            with open(alias_path) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and not line.strip().startswith("#"):
                        canon = parts[0].upper()
                        if canon in canonical:
                            for alias in parts[1:]:
                                table[alias.upper()] = canonical[canon]
    except OSError:
        return {}
    return table


# canonical name (lower) -> ITRF (x, y, z) [m], filled from a TEMPO2
# runtime's observatories.dat columns 1-3 when $TEMPO2 is set
_TEMPO2_ITRF = {}

telescope_code_dict = {**_BUILTIN, **_from_tempo2()}


def telescope_code(name):
    """TOA site code for a telescope name; unknown names pass through
    unchanged (reference pplib.py:2773-2777)."""
    try:
        return telescope_code_dict[str(name).upper()][0]
    except KeyError:
        return str(name)


def canonical_name(name):
    """Canonical tempo2 site name for a telescope name/alias, or None."""
    try:
        return telescope_code_dict[str(name).upper()][1]
    except KeyError:
        return None


def tempo2_itrf(name):
    """ITRF (x, y, z) [m] from a TEMPO2 runtime's observatory table,
    or None when $TEMPO2 is unset or the site is unknown."""
    canon = canonical_name(name)
    return _TEMPO2_ITRF.get(canon or str(name).lower())
