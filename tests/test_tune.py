"""Per-backend autotune subsystem (ISSUE 19): the capability table's
fingerprint and tri-state resolver, the persisted tuning store's
stale/corrupt refusals, the sweep harness's identity gate and
no-regression fallback, the warm-DB zero-resweep witness, and the
source-scan lock that keeps every 'auto' spelling on the ONE
resolver."""

import glob
import json
import os
import re
import warnings

import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.tune import (IDENTITY_TIER, Knob, TuningStore,
                                       apply_from_db, ensure_tuned,
                                       shape_class_for, sweep,
                                       tuned_config)
from pulseportraiture_tpu.tune import capability as cap


# ---------------------------------------------------------------------------
# capability table


def test_backend_fingerprint_stable():
    """Same process, same backend -> same fingerprint; the string
    carries the platform, device kind, and jax version the tuning DB
    keys on."""
    import jax

    fp = cap.backend_fingerprint()
    assert fp == cap.backend_fingerprint()
    platform, kind, jaxver = fp.split(":")
    assert platform == jax.default_backend()
    assert kind == jax.devices()[0].device_kind
    assert jaxver == f"jax-{jax.__version__}"


def test_capability_record_cached_and_upgraded():
    """probe=False serves the static table without timing probes; a
    later probe=True upgrades the cached record in place; the wire
    summary is JSON-safe."""
    rec0 = cap.capability_record(probe=False)
    assert rec0.fingerprint == cap.backend_fingerprint()
    assert isinstance(rec0.pallas_available, bool)
    rec1 = cap.capability_record(probe=True)
    assert rec1.fingerprint == rec0.fingerprint
    assert rec1.dispatch_floor_s is not None
    assert rec1.dispatch_floor_s >= 0
    assert rec1.matmul_gflops > 0 and rec1.dft_gflops > 0
    assert cap.capability_record() is rec1  # cached
    json.dumps(cap.capability_summary())


def test_resolve_auto_tristate_lattice(monkeypatch):
    """The full lattice for BOTH polarities: booleans pass through,
    'auto' (any case/whitespace) resolves by KNOB_POLARITY against the
    LIVE backend, anything else is the knob's strict ValueError."""
    assert cap.resolve_auto("fit_fused", True) is True
    assert cap.resolve_auto("fit_fused", False) is False
    on_cpu = cap.resolve_auto("fit_fused", "auto")
    assert on_cpu is False        # tpu-polarity knob off-TPU
    assert cap.resolve_auto("dft_fold", "auto") is True   # inverted
    assert cap.resolve_auto("fit_fused", " AUTO ") is on_cpu
    monkeypatch.setattr(cap.jax, "default_backend", lambda: "tpu")
    assert cap.resolve_auto("fit_fused", "auto") is True
    assert cap.resolve_auto("dft_fold", "auto") is False
    monkeypatch.undo()
    with pytest.raises(ValueError, match="fit_fused"):
        cap.resolve_auto("fit_fused", "ture")
    with pytest.raises(ValueError, match="config.dft_fold"):
        cap.resolve_auto("dft_fold", 1, label="config.dft_fold")
    with pytest.raises(KeyError):
        cap.resolve_auto("no_such_knob", "auto")  # no polarity row


def test_no_adhoc_tpu_spellings_outside_tune():
    """The collapse is locked: no module outside tune/ may spell the
    backend test privately — every 'auto' resolution goes through
    resolve_auto, one rule, one test, no drift."""
    pkg = os.path.join(os.path.dirname(__file__), "..",
                       "pulseportraiture_tpu")
    pat = re.compile(r"default_backend\(\)\s*[!=]=\s*[\"']tpu[\"']")
    offenders = []
    for path in glob.glob(os.path.join(pkg, "**", "*.py"),
                          recursive=True):
        if os.sep + "tune" + os.sep in path:
            continue
        if pat.search(open(path).read()):
            offenders.append(os.path.relpath(path, pkg))
    assert not offenders, (
        f"ad-hoc 'tpu' backend tests outside tune/: {offenders} — "
        "route them through tune.capability.resolve_auto")


# ---------------------------------------------------------------------------
# tuning store


def test_store_roundtrip(tmp_path):
    db = str(tmp_path / "db.json")
    store = TuningStore(db)
    store.put("16x128", {"fused_block": 16}, default_s=1.0,
              tuned_s=0.8, n_swept=7, identity_preserving=True)
    fresh = TuningStore(db)
    ent = fresh.get("16x128")
    assert ent["knobs"] == {"fused_block": 16}
    assert ent["tuned_s"] == 0.8 and ent["identity_preserving"] is True
    assert fresh.shape_classes() == ["16x128"]
    assert fresh.get("999x999") is None
    raw = json.load(open(db))
    assert raw["fingerprint"] == cap.backend_fingerprint()


def test_store_corrupt_refused_loudly(tmp_path):
    """Garbage bytes never crash a campaign: the store WARNS and
    behaves empty (defaults), and the next put overwrites cleanly."""
    db = str(tmp_path / "db.json")
    open(db, "w").write("{not json!!")
    with pytest.warns(UserWarning, match="corrupt"):
        assert TuningStore(db).get("16x128") is None
    with pytest.warns(UserWarning, match="corrupt"):
        TuningStore(db).put("16x128", {"fused_block": 16})
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the rewritten DB is clean
        ent = TuningStore(db).get("16x128")
    assert ent["knobs"] == {"fused_block": 16}


def test_store_stale_fingerprint_refused(tmp_path):
    """A DB measured on a DIFFERENT backend fingerprint is refused
    with a warning — winners never cross backends — and the next put
    re-keys the file to the live fingerprint."""
    db = str(tmp_path / "db.json")
    json.dump({"version": 1, "fingerprint": "tpu:TPU v4:jax-9.9",
               "entries": {"16x128": {"knobs": {"fused_block": 8}}}},
              open(db, "w"))
    with pytest.warns(UserWarning, match="fingerprint"):
        assert TuningStore(db).get("16x128") is None
    with pytest.warns(UserWarning, match="fingerprint"):
        TuningStore(db).put("16x128", {"fused_block": 16})
    raw = json.load(open(db))
    assert raw["fingerprint"] == cap.backend_fingerprint()
    assert TuningStore(db).get("16x128")["knobs"] == {"fused_block": 16}


def test_store_wrong_schema_version_refused(tmp_path):
    db = str(tmp_path / "db.json")
    json.dump({"version": 99,
               "fingerprint": cap.backend_fingerprint(),
               "entries": {}}, open(db, "w"))
    with pytest.warns(UserWarning, match="version"):
        assert TuningStore(db).shape_classes() == []


# ---------------------------------------------------------------------------
# sweep harness (stubbed workload — no jax in the timed path)


def _stub_workload(byte_changers=(), times=None):
    """run_fn returns bytes that differ when any knob in
    ``byte_changers`` deviates from config; time_fn reads the LIVE
    config (so the combined validation pass sees applied winners)."""
    times = times or {}

    def run_fn(overrides):
        with tuned_config(overrides):
            bad = tuple(getattr(config, k) for k in byte_changers)
        return b"tim" + repr(bad).encode()

    def time_fn(overrides):
        with tuned_config(overrides):
            for (k, v), t in times.items():
                if getattr(config, k) == v:
                    return t
        return 1.0

    return run_fn, time_fn


def test_sweep_picks_winner_and_never_regresses():
    """A knob value that measures faster (and keeps bytes) wins;
    tuned_s <= default_s holds by the combined no-regression gate."""
    run_fn, time_fn = _stub_workload(
        times={("stream_pipeline_depth", 1): 0.5})
    knobs = (Knob("stream_pipeline_depth", (1, 4)),)
    res = sweep(run_fn, knobs=knobs, time_fn=time_fn)
    assert res.knobs == {"stream_pipeline_depth": 1}
    assert res.tuned_s == 0.5 and res.default_s == 1.0
    assert res.n_rejected == 0
    # the winner was never APPLIED by sweep itself
    assert config.stream_pipeline_depth == 2


def test_sweep_identity_gate_rejects_byte_changer():
    """A candidate that changes the artifact bytes is out of the
    running no matter how fast it measures — the byte gate runs
    BEFORE the clock."""
    run_fn, time_fn = _stub_workload(
        byte_changers=("fused_block",),
        times={("fused_block", 16): 0.01})  # fastest, but byte-dirty
    res = sweep(run_fn, knobs=(Knob("fused_block", (16,)),),
                time_fn=time_fn)
    assert res.knobs == {} and res.n_rejected == 1 and res.n_swept == 0
    assert res.tuned_s == res.default_s


def test_sweep_combined_regression_falls_back_to_defaults():
    """Two knobs that each measure faster alone but regress combined:
    the combined validation ships the DEFAULTS (a tuned campaign is
    never slower)."""
    def run_fn(overrides):
        return b"tim"

    def time_fn(overrides):
        with tuned_config(overrides):
            d = config.stream_pipeline_depth
            c = config.lm_compact_every
        if d == 1 and c == 8:
            return 2.0       # the combination regresses
        if d == 1 or c == 8:
            return 0.5       # each wins alone
        return 1.0

    res = sweep(run_fn, time_fn=time_fn,
                knobs=(Knob("stream_pipeline_depth", (1,)),
                       Knob("lm_compact_every", (8,))))
    assert res.knobs == {} and res.tuned_s == res.default_s == 1.0


def test_ensure_tuned_db_hit_pays_zero_resweeps(tmp_path):
    """First call sweeps and persists; second call loads the DB and
    NEVER calls the workload — witnessed by the call counter and by
    the trace (tune_apply db_hit=true, zero tune_sweep events)."""
    db = str(tmp_path / "db.json")
    calls = [0]
    base_run, time_fn = _stub_workload(
        times={("stream_pipeline_depth", 1): 0.5})

    def run_fn(overrides):
        calls[0] += 1
        return base_run(overrides)

    knobs = (Knob("stream_pipeline_depth", (1,)),)
    trace1 = str(tmp_path / "t1.jsonl")
    with telemetry.Tracer(trace1, run="tune") as tr:
        w1 = ensure_tuned(run_fn, "16x128", db_path=db, knobs=knobs,
                          time_fn=time_fn, tracer=tr, apply=False)
    assert w1 == {"stream_pipeline_depth": 1} and calls[0] > 0
    _, evs = telemetry.validate_trace(trace1)
    assert [e["db_hit"] for e in evs if e["type"] == "tune_apply"] \
        == [False]
    assert any(e["type"] == "tune_sweep" for e in evs)
    assert any(e["type"] == "tune_probe" for e in evs)

    calls[0] = 0
    trace2 = str(tmp_path / "t2.jsonl")
    with telemetry.Tracer(trace2, run="tune") as tr:
        w2 = ensure_tuned(run_fn, "16x128", db_path=db, knobs=knobs,
                          time_fn=time_fn, tracer=tr, apply=False)
    assert w2 == w1 and calls[0] == 0
    _, evs = telemetry.validate_trace(trace2)
    assert [e["db_hit"] for e in evs if e["type"] == "tune_apply"] \
        == [True]
    assert not any(e["type"] == "tune_sweep" for e in evs)
    summary = telemetry.report(trace2, file=__import__("io").StringIO())
    assert summary["tune_db_hits"] == 1 and summary["n_tune_sweep"] == 0


def test_ensure_tuned_applies_winners_scoped(tmp_path):
    """apply=True sets the winners on config (the campaign-startup
    path); apply_from_db replays them in a fresh 'process'."""
    db = str(tmp_path / "db.json")
    run_fn, time_fn = _stub_workload(
        times={("stream_pipeline_depth", 1): 0.5})
    knobs = (Knob("stream_pipeline_depth", (1,)),)
    old = config.stream_pipeline_depth
    try:
        ensure_tuned(run_fn, "16x128", db_path=db, knobs=knobs,
                     time_fn=time_fn)
        assert config.stream_pipeline_depth == 1
        config.stream_pipeline_depth = old
        # the CLI cold path: sole stored class is picked when None
        assert apply_from_db(db_path=db) \
            == {"stream_pipeline_depth": 1}
        assert config.stream_pipeline_depth == 1
    finally:
        config.stream_pipeline_depth = old


def test_numerics_tier_never_swept_silently(tmp_path):
    """Without the explicit numerics opt-in, dtype knobs are not in
    the default sweep set — byte-identity is the default contract."""
    names = {k.name for k in IDENTITY_TIER}
    assert "cross_spectrum_dtype" not in names
    assert "dft_precision" not in names
    seen = []

    def run_fn(overrides):
        seen.append(dict(overrides))
        return b"tim"

    sweep(run_fn, time_fn=lambda ov: 1.0)
    swept_names = {k for ov in seen for k in ov}
    assert "cross_spectrum_dtype" not in swept_names
    assert "dft_precision" not in swept_names


def test_shape_class_key():
    assert shape_class_for(16, 128) == "16x128"
    assert shape_class_for(16.0, 128.0) == "16x128"


# ---------------------------------------------------------------------------
# env hooks (satellite b)


def test_tune_env_hooks(monkeypatch):
    old = (config.tune_db, config.autotune, config.tune_numerics)
    try:
        for name in ("PPT_TUNE_DB", "PPT_AUTOTUNE",
                     "PPT_TUNE_NUMERICS"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_TUNE_DB", "/tmp/db.json")
        monkeypatch.setenv("PPT_AUTOTUNE", "on")
        monkeypatch.setenv("PPT_TUNE_NUMERICS", "off")
        changed = config.env_overrides()
        assert {"tune_db", "autotune", "tune_numerics"} <= set(changed)
        assert config.tune_db == "/tmp/db.json"
        assert config.autotune is True
        assert config.tune_numerics is False
        monkeypatch.setenv("PPT_TUNE_DB", "off")
        config.env_overrides()
        assert config.tune_db is None
        monkeypatch.setenv("PPT_AUTOTUNE", "maybe")
        with pytest.raises(ValueError, match="PPT_AUTOTUNE"):
            config.env_overrides()
        monkeypatch.setenv("PPT_AUTOTUNE", "off")
        monkeypatch.setenv("PPT_TUNE_NUMERICS", "2")
        with pytest.raises(ValueError, match="PPT_TUNE_NUMERICS"):
            config.env_overrides()
    finally:
        (config.tune_db, config.autotune, config.tune_numerics) = old
        for name in ("PPT_TUNE_DB", "PPT_AUTOTUNE",
                     "PPT_TUNE_NUMERICS"):
            monkeypatch.delenv(name, raising=False)
        config.env_overrides()


def test_tune_keys_in_telemetry_snapshot():
    for key in ("tune_db", "autotune", "tune_numerics",
                "lm_compact_every"):
        assert key in telemetry.CONFIG_SNAPSHOT_KEYS
    for ev in ("tune_probe", "tune_sweep", "tune_apply"):
        assert ev in telemetry.EVENT_FIELDS
