"""Adversarial fuzz of the dependency-free FITS codec (VERDICT r4 #4).

The golden-file corpus is builder-authored on both sides (forge writes,
codec reads), so a shared misconception passes silently.  This sweep is
the independent pressure available without PSRCHIVE/astropy: every case
is forged byte-by-byte by tests/fits_forge.py (which shares NO code
with pulseportraiture_tpu.io) under a seeded RNG — randomized column
types/orders/repeats, TDIM spellings, TSCAL/TZERO conventions, header
value spellings — and the decode is compared field-by-field against
the arrays the forge wrote.  Deliberately malformed files must refuse
with a clear error (ValueError/KeyError), NEVER silently misparse.

Reference envelope: /root/reference/pplib.py:2749-2915 (the reference
inherits these conventions from PSRCHIVE; this codec must earn them).
"""

import numpy as np
import pytest

from pulseportraiture_tpu.io import native
from pulseportraiture_tpu.io.fitsio import (_parse_card, parse_tform,
                                            read_fits)

from fits_forge import BLOCK, bintable_hdu, primary_hdu

# column dtype pool: (numpy big-endian dtype, TFORM letter)
_DTYPES = [("u1", "B"), (">i2", "I"), (">i4", "J"),
           (">f4", "E"), (">f8", "D")]


def _random_table(rng, ncols=None, nrows=None):
    """Forge-side random table: returns (columns, col_cards,
    tdim_overrides, expected) where expected maps name -> the
    physical-value array the codec must produce."""
    nrows = nrows or int(rng.integers(1, 6))
    ncols = ncols or int(rng.integers(1, 6))
    columns, col_cards, tdims, expected = [], {}, {}, {}
    for c in range(ncols):
        name = f"COL{c}"
        if rng.random() < 0.15:
            width = int(rng.integers(1, 12))
            vals = np.array(
                ["".join(chr(rng.integers(65, 90)) for _ in range(width))
                 .encode() for _ in range(nrows)], dtype=f"S{width}")
            columns.append((name, vals))
            expected[name] = vals
            continue
        dts, code = _DTYPES[int(rng.integers(len(_DTYPES)))]
        dt = np.dtype(dts)
        repeat = int(rng.integers(1, 9))
        shape = (nrows, repeat) if repeat > 1 else (nrows,)
        if dt.kind == "f":
            arr = rng.standard_normal(shape).astype(dt)
        else:
            info = np.iinfo(dt)
            arr = rng.integers(info.min, info.max + 1, shape).astype(dt)
        columns.append((name, arr))
        exp = arr.astype(arr.dtype.newbyteorder("="))
        # FITS scaling conventions, chosen per column
        r = rng.random()
        if code == "B" and r < 0.3:
            col_cards[name] = {"TZERO": -128.0}
            exp = exp.astype(np.int64) - 128
        elif code == "I" and r < 0.3:
            col_cards[name] = {"TZERO": 32768.0}
            exp = exp.astype(np.int64) + 32768
        elif r < 0.45:
            tscal, tzero = 0.5, 3.0  # exactly representable
            col_cards[name] = {"TSCAL": tscal, "TZERO": tzero}
            exp = exp.astype(np.float64) * tscal + tzero
        elif r < 0.55:
            # trivial scaling cards present: must be a no-op
            col_cards[name] = {"TSCAL": 1.0, "TZERO": 0.0}
        # TDIM on multi-element columns, sometimes with alien spacing
        if repeat > 1 and rng.random() < 0.4:
            a = int(rng.integers(1, repeat + 1))
            while repeat % a:
                a -= 1
            b = repeat // a
            sp = " " if rng.random() < 0.5 else ""
            tdims[name] = f"({sp}{a},{sp}{b}{sp})"
            exp = exp.reshape((nrows, b, a))
        expected[name] = exp
    return columns, col_cards, tdims, expected


@pytest.mark.parametrize("seed", range(64))
def test_fuzz_bintable_roundtrip(seed, tmp_path):
    """Randomized table layouts decode EXACTLY (values, shapes, dtypes
    of the physical data) through the codec."""
    rng = np.random.default_rng(1000 + seed)
    columns, col_cards, tdims, expected = _random_table(rng)
    # random junk header cards that must not disturb decoding
    extra = []
    if rng.random() < 0.5:
        extra.append(("OBSERVER", "o'brien"))
    if rng.random() < 0.5:
        extra.append(("JUNKF", float(rng.standard_normal())))
    blob = primary_hdu() + bintable_hdu(
        "FUZZ", columns, extra_cards=extra, tdim_overrides=tdims,
        col_cards=col_cards)
    path = tmp_path / "fuzz.fits"
    path.write_bytes(blob)

    hdus = read_fits(str(path))
    assert len(hdus) == 2
    tbl = hdus[1]
    assert tbl.name == "FUZZ"
    assert list(tbl.data.keys()) == [n for n, _ in columns]
    for name, _ in columns:
        got, want = tbl.data[name], expected[name]
        assert got.shape == want.shape, name
        if want.dtype.kind == "S":
            assert list(got) == list(want), name
        else:
            # exact: integer conventions stay integral, scalings are
            # exactly-representable factors
            assert got.dtype.kind == want.dtype.kind, name
            np.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize("seed", range(24))
def test_fuzz_multi_hdu_and_row_padding(seed, tmp_path):
    """Two tables back-to-back (block padding between) decode
    independently; trailing block padding never leaks into data."""
    rng = np.random.default_rng(5000 + seed)
    cols1, cc1, td1, exp1 = _random_table(rng)
    cols2, cc2, td2, exp2 = _random_table(rng)
    blob = (primary_hdu()
            + bintable_hdu("T1", cols1, tdim_overrides=td1, col_cards=cc1)
            + bintable_hdu("T2", cols2, tdim_overrides=td2, col_cards=cc2))
    path = tmp_path / "two.fits"
    path.write_bytes(blob)
    hdus = read_fits(str(path))
    assert [h.name for h in hdus[1:]] == ["T1", "T2"]
    for hdu, exp, cols in ((hdus[1], exp1, cols1), (hdus[2], exp2, cols2)):
        for name, _ in cols:
            want = exp[name]
            got = hdu.data[name]
            if want.dtype.kind == "S":
                assert list(got) == list(want)
            else:
                np.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize("seed", range(40))
def test_fuzz_header_card_spellings(seed):
    """Randomized legal header card spellings parse to the right
    value: quote escaping, '/' inside strings vs comment delimiters,
    FORTRAN D exponents, spaced integers/floats, booleans."""
    rng = np.random.default_rng(9000 + seed)
    kind = int(rng.integers(5))
    key = "FUZZKEY"
    if kind == 0:  # string with escaped quotes and a slash
        s = "it''s a/test"
        card = f"{key:8s}= '{s}'            / comment /x"
        want = "it's a/test"
    elif kind == 1:  # integer, random width
        v = int(rng.integers(-10**9, 10**9))
        card = f"{key:8s}= {str(v).rjust(int(rng.integers(1, 21)))} / c"
        want = v
    elif kind == 2:  # float with D exponent (FORTRAN spelling)
        mant = round(float(rng.uniform(-9, 9)), 6)
        exp = int(rng.integers(-10, 11))
        card = f"{key:8s}= {mant}D{exp:+03d}"
        want = float(f"{mant}E{exp:+03d}")
    elif kind == 3:  # boolean
        want = bool(rng.integers(2))
        card = f"{key:8s}= {'T' if want else 'F':>20s} / bool"
    else:  # float plain
        want = round(float(rng.uniform(-1e6, 1e6)), 6)
        card = f"{key:8s}= {want:>20} / f"
    k, v, _ = _parse_card(card.ljust(80))
    assert k == key
    if isinstance(want, float):
        assert isinstance(v, float) and v == pytest.approx(want, rel=0,
                                                           abs=0)
    else:
        assert v == want and type(v) is type(want)


def _forge_valid(rng, tmp_path):
    cols, cc, td, _ = _random_table(rng, ncols=3, nrows=3)
    blob = primary_hdu() + bintable_hdu("T", cols, tdim_overrides=td,
                                        col_cards=cc)
    path = tmp_path / "m.fits"
    return blob, path


def _patch_card(blob, key, newcard):
    """Replace the 80-char header card starting with `key` in raw HDU
    bytes (byte-level, no codec involvement)."""
    pat = key.ljust(8).encode()
    i = blob.find(pat)
    assert i >= 0 and i % 80 == 0
    return blob[:i] + newcard.ljust(80).encode("ascii") + blob[i + 80:]


MALFORMED_KINDS = [
    "truncated_header", "truncated_data", "bad_tform", "tdim_mismatch",
    "naxis1_mismatch", "missing_end", "missing_ttype"]


def _forge_malformed(kind, rng, tmp_path):
    """Build one deliberately-broken file of the given class; returns
    its path.  Shared by the Python-codec and native-lane refusal
    tests so both lanes face the identical corpus."""
    blob, path = _forge_valid(rng, tmp_path)
    if kind == "truncated_header":
        cut = int(rng.integers(1, BLOCK))
        blob = blob[:cut]
    elif kind == "truncated_data":
        # find the table HDU's data start (second END card) and cut
        # inside the data
        first_end = blob.find(b"END" + b" " * 77)
        second_end = blob.find(b"END" + b" " * 77, first_end + 80)
        data_start = ((second_end + 80 + BLOCK - 1) // BLOCK) * BLOCK
        assert len(blob) > data_start + 1
        blob = blob[:data_start + 1]
    elif kind == "bad_tform":
        blob = _patch_card(blob, "TFORM2", "TFORM2  = 'Z       '")
    elif kind == "tdim_mismatch":
        # a well-formed table whose only defect is a TDIM that does not
        # factor its column's repeat count: must refuse at the reshape,
        # not return a silently mis-shaped array
        cols = [("A", np.arange(3, dtype=">i2")),
                ("B", rng.standard_normal((3, 8)).astype(">f4"))]
        blob = primary_hdu() + bintable_hdu(
            "T", cols, tdim_overrides={"B": "(3,5)"})
    elif kind == "naxis1_mismatch":
        hdr_off = blob.find(b"XTENSION")
        i = blob.find(b"NAXIS1", hdr_off)
        width = int(blob[i + 10:i + 30].decode())
        blob = _patch_card(blob, "NAXIS1",
                           f"NAXIS1  = {width + 7:>20d}")
    elif kind == "missing_end":
        blob = blob.replace(b"END" + b" " * 77, b"        " + b" " * 72)
    elif kind == "missing_ttype":
        blob = _patch_card(blob, "TTYPE2", "TXXXX2  = 'GONE    '")
    path.write_bytes(blob)
    return path


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("kind", MALFORMED_KINDS)
def test_fuzz_malformed_refuses_cleanly(kind, seed, tmp_path):
    """Deliberately broken files raise ValueError/KeyError — the codec
    must never return silently-misparsed arrays."""
    rng = np.random.default_rng(seed)
    path = _forge_malformed(kind, rng, tmp_path)
    with pytest.raises((ValueError, KeyError)):
        read_fits(str(path))


def test_fuzz_random_bytes_refuse(tmp_path):
    """Pure garbage never decodes."""
    rng = np.random.default_rng(0)
    for n in (10, 2879, 2880, 5000):
        p = tmp_path / f"junk{n}.fits"
        p.write_bytes(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
        with pytest.raises((ValueError, KeyError)):
            read_fits(str(p))


def test_parse_tform_variants():
    assert parse_tform("2048E") == (2048, "E", "")
    assert parse_tform(" 1J ") == (1, "J", "")
    assert parse_tform("D") == (1, "D", "")
    assert parse_tform("16X") == (16, "X", "")


# --------------------------------------------------------------------------
# Native C++ lane (VERDICT r5 #4): the SAME forged corpus through
# ppt_native's fused decode kernel.  The kernel normally sees only
# SUBINT DATA columns; here every supported column of every fuzz table
# goes through it (npol=1, nchan=1, nbin=repeat) and must match the
# Python codec bit-for-bit, and every malformed class must refuse in
# this lane too — the C path reads raw bytes with no bounds checks of
# its own, so the refusal discipline lives in the geometry validation
# that fronts it (mirrored from psrfits.read_archive).
# --------------------------------------------------------------------------

_NATIVE_CODES = ("B", "I", "E")  # sample types the C kernel implements
_NATIVE_SAMP = {"B": 1, "I": 2, "E": 4}


class _DeferAll:
    """Membership-always container: defers every bintable column, so
    read_fits parses headers and validates row geometry but decodes NO
    samples — the values under test come only from the C kernel."""

    def __contains__(self, name):
        return True


def _native_decode_tables(path):
    """Native-lane decoder for the fuzz corpus: header parse through
    the Python codec with EVERY column deferred (no numpy sample
    decode anywhere), samples of each supported column through
    native.decode_fused straight from the wire bytes, TSCAL/TZERO
    fused in as the kernel's scale/offset plane.  Mirrors
    psrfits.read_archive's discipline: the C kernel has no bounds
    checks, so column extents and TDIM factorizations are validated
    here and inconsistent files refuse with ValueError instead of
    reading past the column.  Returns [(extname, {col: f64 array})]
    for the bintable HDUs."""
    out = []
    for hdu in read_fits(path, defer=_DeferAll()):
        if not hdu.layout:
            continue
        nrows = int(hdu.header["NAXIS2"])
        if len(hdu.raw) < nrows * hdu.row_stride:
            raise ValueError("bintable payload shorter than NAXIS1*NAXIS2")
        cols = {}
        for i, (name, (col_off, code, repeat)) in enumerate(
                hdu.layout.items()):
            tdim = hdu.header.get(f"TDIM{i + 1}")
            shape = (repeat,) if repeat > 1 else ()
            if tdim:
                shape = tuple(int(x) for x in
                              str(tdim).strip("() ").split(","))[::-1]
                if int(np.prod(shape)) != repeat:
                    raise ValueError(
                        f"TDIM{i + 1} {tdim!r} does not factor "
                        f"repeat={repeat}")
            if code not in _NATIVE_CODES:
                continue
            if col_off + repeat * _NATIVE_SAMP[code] > hdu.row_stride:
                raise ValueError(f"column {name} exceeds its row extent")
            tscal, tzero = hdu.col_scaling.get(name, (1.0, 0.0))
            arr = native.decode_fused(
                hdu.raw, nrows, hdu.row_stride, col_off, code,
                1, 1, repeat,
                scl=np.full((nrows, 1), tscal),
                offs=np.full((nrows, 1), tzero))
            cols[name] = arr.reshape((nrows,) + shape)
        out.append((hdu.name, cols))
    return out


def _assert_bit_equal(native_arr, py_arr, msg):
    """The kernel's f64 output must carry the Python codec's value
    EXACTLY — compared as raw bytes after the lossless widening to
    f64 (u8/i16/f32/int conventions are all exactly representable),
    so even a sign-of-zero or ULP discrepancy fails."""
    py_arr = np.asarray(py_arr)
    assert native_arr.shape == py_arr.shape, msg
    as64 = np.ascontiguousarray(py_arr, np.float64)
    assert native_arr.tobytes() == as64.tobytes(), msg


@pytest.mark.parametrize("seed", range(32))
def test_fuzz_native_lane_bit_equal(seed, tmp_path):
    """The randomized corpus (same seeds as the Python roundtrip
    sweep) decodes identically through both lanes: every
    kernel-supported column, bit-for-bit."""
    if not native.available():
        pytest.skip("native build unavailable (no g++ / no .so)")
    rng = np.random.default_rng(1000 + seed)
    columns, col_cards, tdims, expected = _random_table(rng)
    blob = primary_hdu() + bintable_hdu(
        "FUZZ", columns, tdim_overrides=tdims, col_cards=col_cards)
    path = tmp_path / "fuzz.fits"
    path.write_bytes(blob)

    py = read_fits(str(path))[1]
    (extname, ncols), = _native_decode_tables(str(path))
    assert extname == "FUZZ"
    for name, arr in ncols.items():
        _assert_bit_equal(arr, py.data[name], name)


def test_native_lane_conventions_bit_equal(tmp_path):
    """Deterministic coverage of every kernel sample type crossed with
    every scaling convention the codec implements (the random sweep
    cannot guarantee each cell is hit): unscaled, signed-byte
    TZERO=-128, unsigned-16 TZERO=32768, float TSCAL/TZERO, trivial
    scaling cards, and a TDIM reshape."""
    if not native.available():
        pytest.skip("native build unavailable (no g++ / no .so)")
    rng = np.random.default_rng(7)
    nrows = 5
    columns = [
        ("BRAW", rng.integers(0, 256, (nrows, 3)).astype("u1")),
        ("BSGN", rng.integers(0, 256, (nrows,)).astype("u1")),
        ("IRAW", rng.integers(-2**15, 2**15, (nrows, 4)).astype(">i2")),
        ("IUNS", rng.integers(-2**15, 2**15, (nrows,)).astype(">i2")),
        ("ISCL", rng.integers(-2**15, 2**15, (nrows, 6)).astype(">i2")),
        ("ERAW", rng.standard_normal((nrows, 8)).astype(">f4")),
        ("ESCL", rng.standard_normal((nrows, 2)).astype(">f4")),
        ("ETRV", rng.standard_normal((nrows,)).astype(">f4")),
    ]
    col_cards = {"BSGN": {"TZERO": -128.0},
                 "IUNS": {"TZERO": 32768.0},
                 "ISCL": {"TSCAL": 0.5, "TZERO": 3.0},
                 "ESCL": {"TSCAL": 0.25, "TZERO": -1.0},
                 "ETRV": {"TSCAL": 1.0, "TZERO": 0.0}}
    blob = primary_hdu() + bintable_hdu(
        "CONV", columns, tdim_overrides={"ERAW": "(4,2)"},
        col_cards=col_cards)
    path = tmp_path / "conv.fits"
    path.write_bytes(blob)

    py = read_fits(str(path))[1]
    (_, ncols), = _native_decode_tables(str(path))
    assert set(ncols) == {n for n, _ in columns}
    for name, arr in ncols.items():
        _assert_bit_equal(arr, py.data[name], name)
    assert ncols["ERAW"].shape == (nrows, 2, 4)  # TDIM honored


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("kind", MALFORMED_KINDS)
def test_fuzz_malformed_refuses_in_native_lane(kind, seed, tmp_path):
    """Every malformed class refuses in the native lane too — the
    identical corpus (shared _forge_malformed) must never reach the
    bounds-check-free C kernel with inconsistent geometry."""
    if not native.available():
        pytest.skip("native build unavailable (no g++ / no .so)")
    rng = np.random.default_rng(seed)
    path = _forge_malformed(kind, rng, tmp_path)
    with pytest.raises((ValueError, KeyError)):
        _native_decode_tables(str(path))


# ---------------------------------------------------------------------------
# ISSUE 15: sub-byte NBIT packed layouts + general TSCAL/TZERO DATA,
# fuzzed through the full archive loader AND the raw transport lane
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", range(12))
def test_fuzz_packed_nbit_layouts(seed, tmp_path):
    """Randomized sub-byte NBIT archives (width, geometry, subint
    count) decode EXACTLY through the archive loader, and the packed
    raw lane's payload unpacks bit-identically to the host unpack
    wherever its byte-alignment contract holds."""
    from fits_forge import forge_archive

    from pulseportraiture_tpu.io.psrfits import read_archive

    rng = np.random.default_rng(3000 + seed)
    nbit = int(rng.choice([1, 2, 4]))
    nsub = int(rng.integers(1, 4))
    nchan = int(rng.integers(2, 10))
    # nbin a multiple of 8: every real fold-mode archive is, and it
    # keeps the plane byte-aligned for the raw-lane half below
    nbin = 8 * int(rng.integers(2, 9))
    path = str(tmp_path / "packed.fits")
    stored, freqs = forge_archive(path, nsub=nsub, nchan=nchan,
                                  nbin=nbin, data_dtype=f"nbit{nbit}")
    arch = read_archive(path)
    np.testing.assert_allclose(arch.amps, stored, rtol=1e-6, atol=1e-7)

    raw = read_archive(path, decode=False)
    assert raw.raw_code == f"p{nbit}"
    per = 8 // nbit
    assert raw.raw_data.shape == (nsub, 1, nchan * nbin // per)
    # bit identity: host-side unpack of the shipped payload must
    # reproduce the loader's decode exactly through DAT_SCL/DAT_OFFS
    shifts = (np.arange(per - 1, -1, -1) * nbit).astype(np.uint8)
    v = (raw.raw_data[..., :, None] >> shifts) & ((1 << nbit) - 1)
    v = v.reshape(nsub, 1, nchan, nbin).astype(np.float64)
    dec = v * raw.raw_scl[..., None] + raw.raw_offs[..., None]
    np.testing.assert_allclose(dec, arch.amps, rtol=0, atol=1e-5)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_general_tscal_tzero_data(seed, tmp_path):
    """Randomized general TSCAL/TZERO DATA columns (beyond the
    signed-byte convention) decode exactly on the host loader and
    attach their scalars in raw mode — the host order
    (stored*TSCAL + TZERO)*DAT_SCL + DAT_OFFS is the contract the
    device decode mirrors."""
    from fits_forge import forge_archive

    from pulseportraiture_tpu.io.psrfits import read_archive

    rng = np.random.default_rng(4000 + seed)
    dt = str(rng.choice([">i2", "u1"]))
    # exactly-representable scalings so the truth comparison is exact
    tscal = float(rng.choice([0.5, 0.25, 2.0]))
    tzero = float(rng.choice([-3.0, 0.0, 7.5]))
    nsub = int(rng.integers(1, 4))
    nchan = int(rng.integers(2, 8))
    nbin = 8 * int(rng.integers(2, 6))
    path = str(tmp_path / "tscal.fits")
    stored, freqs = forge_archive(path, nsub=nsub, nchan=nchan,
                                  nbin=nbin, data_dtype=dt,
                                  data_tscal=tscal, data_tzero=tzero)
    arch = read_archive(path)
    np.testing.assert_allclose(arch.amps, stored, rtol=0, atol=1e-9)

    raw = read_archive(path, decode=False)
    assert raw.raw_tscal == tscal
    assert raw.raw_tzero == tzero
    # host-order reconstruction from the shipped pieces is exact
    dec = (raw.raw_data.astype(np.float64) * tscal + tzero) \
        * raw.raw_scl.astype(np.float64)[..., None] \
        + raw.raw_offs.astype(np.float64)[..., None]
    np.testing.assert_allclose(dec, arch.amps, rtol=0, atol=1e-9)
