"""BASELINE config 6: the template factory (ISSUE 9) — batched vs
serial Gaussian model building at a synthetic N-pulsar fleet.

Two A/Bs, both printed in ONE JSON line:

1. **The production A/B** (the headline; >= 3x CPU gate at N >= 16):
   serial arm = the pre-factory workflow, one ``ppgauss`` process per
   pulsar (the reference CLI takes ONE datafile — a PTA template
   campaign is N cold processes, each re-paying interpreter + jax
   import + every per-shape LM trace/compile + one serial LM dispatch
   per fit); batched arm = ONE ``ppfactory`` process building the
   whole fleet through the batched engine's power-of-two buckets.
   Both arms run cold in subprocesses, so the measured ratio is the
   end-to-end cost an operator actually pays.  On CPU the win is
   process/compile amortization (this box has ONE core, so lock-step
   SIMD cannot beat a warm serial loop on raw FLOPs); on TPU the
   per-fit dispatch amortization dominates — pre-scoped in
   BENCHMARKS.md.

2. **The oracle A/B + digit gate** (in-process, warm):
   build_templates with gauss_device=False (host-serial oracle — the
   SAME padded problems through the single-problem engine one at a
   time) vs gauss_device=True; the batched lane's .gmodel output must
   be digit-identical (<= 1e-10) to the oracle's on the full fleet,
   and the warm speedup is reported honestly (vs_oracle_warm — on a
   single-core host the lock-step engine pays the Jacobian on rejected
   steps too, so expect < 1 here; compaction keeps it bounded).

Plus the gauss stage profile (benchmarks/attrib.py: resid / jacobian /
solve / select of one batched LM iteration) with attributed_frac
>= 0.9.

Each pulsar is a distinct evolving-Gaussian source (varied component
locations/widths/amplitudes), written once to a PSRFITS cache
(PPT_GAUSS_CACHE).  Shapes via PPT_NPSR / PPT_NCHAN / PPT_NBIN /
PPT_NGAUSS / PPT_NITER.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

DIGIT_GATE = 1e-10
SPEEDUP_GATE = 3.0


def _fleet_model(rng, i, nu_ref=1500.0):
    """A per-pulsar evolving-Gaussian truth model: NGAUSS components
    with jittered locations/widths/amplitudes so the fleet's fits are
    genuinely heterogeneous problems (different selected ngauss,
    different iteration counts — the straggler regime the shared
    while_loop must absorb)."""
    from pulseportraiture_tpu.models.gaussian import GaussianModel

    ng = int(os.environ.get("PPT_NGAUSS", 3))
    locs = np.sort(0.35 + 0.3 * rng.random(ng))
    return GaussianModel(
        name=f"FLEET_{i:04d}", code="000", nu_ref=nu_ref, dc=0.0,
        tau=0.0, alpha=-4.0,
        locs=locs,
        wids=0.01 + 0.03 * rng.random(ng),
        amps=1.0 + 6.0 * rng.random(ng),
        mlocs=0.004 * rng.standard_normal(ng),
        mwids=0.2 * rng.standard_normal(ng),
        mamps=-1.0 + 0.5 * rng.standard_normal(ng),
    )


def _make_fleet(root, npsr, nchan, nbin):
    from pulseportraiture_tpu.synth import make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    os.makedirs(root, exist_ok=True)
    files = []
    for i in range(npsr):
        p = os.path.join(root, f"psr{i:03d}.fits")
        if not os.path.exists(p):
            rng = np.random.default_rng(1000 + i)
            par = {"PSR": f"FLEET_{i:04d}", "P0": 0.003 + 0.002 * i,
                   "DM": 20.0 + 2.0 * i, "PEPOCH": 56000.0}
            make_fake_pulsar(_fleet_model(rng, i), par, outfile=p,
                             nsub=2, nchan=nchan, nbin=nbin,
                             nu0=1500.0, bw=600.0, tsub=60.0,
                             start_MJD=MJD(55100 + i, 0.3),
                             noise_stds=0.05, dedispersed=False,
                             quiet=True, rng=2000 + i)
        files.append(p)
    return files


def _gmodel_params(path):
    from pulseportraiture_tpu.io.gmodel import model_to_flat, read_gmodel

    m = read_gmodel(path, quiet=True)
    params, _ = model_to_flat(m)
    return params, float(m.alpha)


def _attrib_problem(files, max_ngauss):
    """Build the dominant batched dispatch's problem arrays: ONE
    portrait bucket built exactly the way the factory builds it
    (padded channels/components/rows) from the fleet's own
    profile-stage selections.  Returns (resid, resid_jac, aux, x0s,
    lo, hi, kind, varys, label) — shared by the per-lane stage
    profiles and the analytic-vs-AD Jacobian digit gate."""
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.gauss import (
        _portrait_fns, pad_portrait_params, portrait_bounds,
        portrait_vary)
    from pulseportraiture_tpu.fit.lm import _bounds_spec
    from pulseportraiture_tpu.pipeline.factory import _pow2ceil
    from pulseportraiture_tpu.pipeline.gauss import (
        GaussPortrait, portrait_fit_flags, profile_to_portrait_params)

    rows = []
    for f in files:
        dp = GaussPortrait(f, quiet=True)
        profile, nu_ref = dp.select_ref_profile()
        dp.nu_ref = nu_ref
        dp.auto_fit_profile(profile, max_ngauss=max_ngauss,
                            gauss_device=True, quiet=True)
        rows.append((dp, profile_to_portrait_params(dp.init_params)))
    gclass = _pow2ceil(max(((len(x0) - 2) // 6) for _, x0 in rows))
    cclass = _pow2ceil(max(len(dp.ok_ichans) for dp, _ in rows))
    nbin = rows[0][0].nbin
    B = _pow2ceil(len(rows))
    nmain = 2 + 6 * gclass
    data = np.zeros((B, cclass, nbin))
    errs = np.full((B, cclass), np.inf)
    freqs = np.zeros((B, cclass))
    x0s = np.zeros((B, nmain + 1))
    varys = np.zeros((B, nmain + 1), bool)
    nu_refs = np.zeros(B)
    Ps = np.full(B, 0.003)
    for b, (dp, x0) in enumerate(rows):
        okc = dp.ok_ichans
        n_ok = len(okc)
        data[b, :n_ok] = dp.port[okc]
        errs[b, :n_ok] = np.where(
            dp.noise_stds[okc] > 0, dp.noise_stds[okc],
            np.median(dp.noise_stds[okc]))
        freqs[b] = dp.freqsxs[0][-1]
        freqs[b, :n_ok] = dp.freqsxs[0]
        xp, ng = pad_portrait_params(x0, gclass)
        x0s[b] = np.concatenate([xp, [-4.0]])
        flags = portrait_fit_flags(ng)
        varys[b] = portrait_vary(flags, gclass)
        nu_refs[b] = dp.nu_ref
        Ps[b] = float(dp.Ps[0])
    for b in range(len(rows), B):  # frozen pad rows, as in the factory
        data[b], errs[b], freqs[b] = data[0], errs[0], freqs[0]
        x0s[b], nu_refs[b], Ps[b] = x0s[0], nu_refs[0], Ps[0]
    lower, upper = portrait_bounds(gclass, nbin)
    lo, hi, kind = _bounds_spec(np.broadcast_to(lower, x0s.shape),
                                np.broadcast_to(upper, x0s.shape),
                                x0s.shape, jnp.asarray(x0s).dtype)
    resid, resid_jac = _portrait_fns("000", nbin, 0, nmain)
    aux = (jnp.asarray(data), jnp.asarray(errs), jnp.asarray(freqs),
           jnp.asarray(nu_refs), jnp.asarray(Ps),
           jnp.zeros((B, 0, cclass), bool))
    return (resid, resid_jac, aux, x0s, lo, hi, kind, varys,
            {"attrib_batch": B,
             "attrib_bucket": f"port:{cclass}c:{nbin}b:{gclass}g"})


def _jac_digit_gate(resid, resid_jac, aux, x0s, lo, hi, kind, varys):
    """The ISSUE 14 Jacobian digit gate on the real bucket problem:
    evaluate the batched internal-space Jacobian through BOTH sources
    (fit/lm._make_jac — exactly the evaluator the engine runs) at the
    bucket's starting point and gate the RELATIVE max deviation at
    1e-10 (the absolute scale is set by the archives' S/N; relative is
    the digit claim)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.lm import _make_jac, _to_internal

    def one(jac_src):
        def row(x0, lo1, hi1, k1, v1, aux1):
            u0 = _to_internal(x0, lo1, hi1, k1)
            return _make_jac(resid, jac_src, aux1, lo1, hi1, k1,
                             v1.astype(x0.dtype))(u0)
        return jax.vmap(row)(jnp.asarray(x0s), lo, hi, kind,
                             jnp.asarray(varys), aux)

    J_ad = np.asarray(one(None))
    J_an = np.asarray(one(resid_jac))
    scale = max(float(np.max(np.abs(J_ad))), 1.0)
    return float(np.max(np.abs(J_ad - J_an)) / scale)


def run_bench(attrib_only=False, with_attrib=True):
    import jax

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    config.env_overrides()  # PPT_* A/B switches win over defaults

    from pulseportraiture_tpu.pipeline.factory import build_templates
    from pulseportraiture_tpu.pipeline.gauss import GaussPortrait

    NPSR = int(os.environ.get("PPT_NPSR", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 32))
    NBIN = int(os.environ.get("PPT_NBIN", 512))
    NITER = int(os.environ.get("PPT_NITER", 1))
    MAX_NG = int(os.environ.get("PPT_NGAUSS", 3)) + 1
    cache = os.environ.get("PPT_GAUSS_CACHE", "/tmp/ppt_gauss_fleet")
    root = os.path.join(cache, f"{NPSR}x{NCHAN}x{NBIN}")
    files = _make_fleet(root, NPSR, NCHAN, NBIN)

    if attrib_only:
        from benchmarks.attrib import gauss_stage_profile

        (resid, resid_jac, aux, x0s, lo, hi, kind, varys,
         extra) = _attrib_problem(files, MAX_NG)
        out = {"metric": "template-factory batched-LM stage "
                         "attribution (ad vs analytic jacobian)",
               "device": str(jax.devices()[0])}
        out.update(extra)
        att_ad = gauss_stage_profile(resid, aux, x0s, lo, hi, kind,
                                     varys)
        att_an = gauss_stage_profile(resid, aux, x0s, lo, hi, kind,
                                     varys, jac_fn=resid_jac)
        out.update({f"ad_{k}": v for k, v in
                    att_ad.breakdown_ms().items()})
        out.update({f"analytic_{k}": v for k, v in
                    att_an.breakdown_ms().items()})
        out["iter_speedup_analytic_vs_ad"] = round(
            att_ad.total_s / att_an.total_s, 2)
        return out

    # ---- production A/B: N ppgauss processes vs one ppfactory -------
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    meta = os.path.join(root, "fleet.txt")
    with open(meta, "w") as fh:
        fh.write("\n".join(files) + "\n")
    out_p = os.path.join(root, "out_production")
    out_f = os.path.join(root, "out_factory")
    os.makedirs(out_p, exist_ok=True)

    def sub(args):
        t0 = time.perf_counter()
        subprocess.run([sys.executable, "-m"] + args, check=True,
                       env=env, cwd=repo,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)
        return time.perf_counter() - t0

    t_production = 0.0
    for f in files:
        t_production += sub(
            ["pulseportraiture_tpu.cli.ppgauss", "-d", f,
             "--niter", str(NITER), "--max-ngauss", str(MAX_NG),
             "-o", os.path.join(out_p,
                                os.path.basename(f) + ".gmodel")])
    t_batched = sub(
        ["pulseportraiture_tpu.cli.ppfactory", "-M", meta,
         "-O", out_f, "--niter", str(NITER),
         "--max-ngauss", str(MAX_NG), "--gauss-device", "on"])

    # ---- oracle A/B + digit gate (in-process, warm) -----------------
    def fresh_jobs():
        # reload per run: build_templates rotates the portraits
        # in place, so each timed run must start from disk state
        return [(GaussPortrait(f, quiet=True), f) for f in files]

    def run(lane, outdir):
        jobs = fresh_jobs()
        t0 = time.perf_counter()
        # fixloc=True: the CLI arms above run the reference ppgauss
        # flag defaults; the in-process arms must fit the same flags
        res = build_templates(jobs, outdir=outdir, max_ngauss=MAX_NG,
                              niter=NITER, fixloc=True,
                              gauss_device=lane, quiet=True)
        return time.perf_counter() - t0, res

    out_s = os.path.join(root, "out_serial")
    out_b = os.path.join(root, "out_batched")
    # two reps per arm: first pays trace+compile, min is the warm cost
    runs_s = [run(False, out_s) for _ in range(2)]
    runs_b = [run(True, out_b) for _ in range(2)]
    t_oracle_w = min(t for t, _ in runs_s)
    t_batched_w = min(t for t, _ in runs_b)
    res_s, res_b = runs_s[-1][1], runs_b[-1][1]

    # ---- analytic-vs-AD Jacobian A/B (ISSUE 14): the same warm
    # batched arm with lm_jacobian forced to the autodiff oracle ----
    out_ad = os.path.join(root, "out_batched_ad")
    jac_prev = config.lm_jacobian
    config.lm_jacobian = "ad"
    try:
        runs_ad = [run(True, out_ad) for _ in range(2)]
    finally:
        config.lm_jacobian = jac_prev
    t_batched_ad_w = min(t for t, _ in runs_ad)
    res_ad = runs_ad[-1][1]

    # digit gate on the IN-MEMORY parameters (the .gmodel text grammar
    # rounds to 8 decimals, which would hide 1e-10-scale drift); the
    # production (unpadded, per-pulsar CLI) outputs are compared from
    # their files as a loose cross-check of the whole refactor
    from pulseportraiture_tpu.io.gmodel import model_to_flat

    max_delta = 0.0
    max_delta_prod = 0.0
    n_select_mismatch = 0
    for f, rs, rb in zip(files, res_s, res_b):
        ps = model_to_flat(rs.model)[0]
        pb = model_to_flat(rb.model)[0]
        if len(ps) != len(pb):
            # a lane-dependent component-count selection is a digit
            # failure outright (only possible when no trial converged
            # — see fit/gauss.select_best_trial)
            max_delta = max(max_delta, np.inf)
            continue
        max_delta = max(max_delta, float(np.max(np.abs(ps - pb))),
                        abs(rs.model.alpha - rb.model.alpha))
        base = os.path.basename(f)
        pf, al_f = _gmodel_params(os.path.join(out_f, base + ".gmodel"))
        pp, al_p = _gmodel_params(os.path.join(out_p, base + ".gmodel"))
        if len(pp) != len(pf):
            n_select_mismatch += 1
            continue
        max_delta_prod = max(max_delta_prod,
                             float(np.max(np.abs(pp - pf))),
                             abs(al_p - al_f))

    # analytic-vs-AD: ZERO component-count selection flips on the full
    # fleet (the reproducibility claim — a Jacobian-source ulp wobble
    # must never change the selected model), parameter drift reported
    # honestly (trajectory-level, NOT the 1e-10 Jacobian gate: an
    # ill-conditioned valley amplifies last-ulp J differences over
    # ~100 iterations)
    n_jac_flips = 0
    max_delta_jac_lane = 0.0
    for rb, ra in zip(res_b, res_ad):
        pb = model_to_flat(rb.model)[0]
        pa = model_to_flat(ra.model)[0]
        if len(pb) != len(pa):
            n_jac_flips += 1
            continue
        max_delta_jac_lane = max(max_delta_jac_lane,
                                 float(np.max(np.abs(pb - pa))),
                                 abs(rb.model.alpha - ra.model.alpha))

    speedup = t_production / t_batched
    out = {
        "metric": f"template factory (one ppfactory process) vs "
                  f"production serial (one ppgauss process per "
                  f"pulsar), {NPSR} pulsars x {NCHAN}ch x {NBIN}bin "
                  f"(trials 1..{MAX_NG}, niter {NITER}, cold)",
        "value": round(NPSR / t_batched, 3),
        "unit": "templates/sec",
        "production_templates_per_sec": round(NPSR / t_production, 3),
        "batched_wall_s": round(t_batched, 3),
        "production_wall_s": round(t_production, 3),
        "ab_speedup_vs_serial": round(speedup, 2),
        "speedup_gate_3x": bool(speedup >= SPEEDUP_GATE),
        "oracle_warm_wall_s": round(t_oracle_w, 3),
        "batched_warm_wall_s": round(t_batched_w, 3),
        "ab_speedup_vs_oracle_warm": round(t_oracle_w / t_batched_w, 2),
        "batched_ad_warm_wall_s": round(t_batched_ad_w, 3),
        "ab_speedup_analytic_vs_ad": round(
            t_batched_ad_w / t_batched_w, 2),
        "n_jac_selection_flips": n_jac_flips,
        "jac_selection_flips_ok": bool(n_jac_flips == 0),
        "gmodel_max_delta_analytic_vs_ad": float(
            f"{max_delta_jac_lane:.3g}"),
        "gmodel_max_delta": float(f"{max_delta:.3g}"),
        "digit_gate": DIGIT_GATE,
        "digit_ok": bool(max_delta <= DIGIT_GATE),
        "gmodel_max_delta_vs_production": float(
            f"{max_delta_prod:.3g}"),
        "n_production_select_mismatch": n_select_mismatch,
        "npsr": NPSR,
        "single_core_host": os.cpu_count() == 1,
        "device": str(jax.devices()[0]),
    }
    if with_attrib:
        from benchmarks.attrib import gauss_stage_profile

        (resid, resid_jac, aux, x0s, lo, hi, kind, varys,
         extra) = _attrib_problem(files, MAX_NG)
        out.update(extra)
        att_ad = gauss_stage_profile(resid, aux, x0s, lo, hi, kind,
                                     varys)
        att_an = gauss_stage_profile(resid, aux, x0s, lo, hi, kind,
                                     varys, jac_fn=resid_jac)
        out.update({f"ad_{k}": v for k, v in
                    att_ad.breakdown_ms().items()})
        out.update({f"analytic_{k}": v for k, v in
                    att_an.breakdown_ms().items()})
        out["attrib_ok"] = bool(att_ad.check(0.9)
                                and att_an.check(0.9))
        out["dominant_stage_ad"] = max(att_ad.stages,
                                       key=lambda s: s.cost_s).name
        out["dominant_stage_analytic"] = max(
            att_an.stages, key=lambda s: s.cost_s).name
        # warm batched-LM ITERATION A/B — the ISSUE 14 CPU acceptance
        # (>= 1.5x; the jac stage shrinks by the AD overhead factor)
        out["iter_speedup_analytic_vs_ad"] = round(
            att_ad.total_s / att_an.total_s, 2)
        out["iter_speedup_gate_1p5x"] = bool(
            att_ad.total_s / att_an.total_s >= 1.5)
        # the Jacobian DIGIT gate (<= 1e-10 relative) on the real
        # bucket problem — enforced every run
        jdelta = _jac_digit_gate(resid, resid_jac, aux, x0s, lo, hi,
                                 kind, varys)
        out["jac_rel_delta"] = float(f"{jdelta:.3g}")
        out["jac_digit_ok"] = bool(jdelta <= DIGIT_GATE)
        if not out["jac_digit_ok"] or not out["jac_selection_flips_ok"]:
            raise SystemExit(
                f"bench_gauss: analytic-vs-AD gate FAILED "
                f"(jac_rel_delta={jdelta:.3g}, "
                f"n_jac_selection_flips={n_jac_flips})")
    return out


def main():
    print(json.dumps(run_bench()))


if __name__ == "__main__":
    main()
