"""Continuous-batching TOA service (ISSUE 8; ROADMAP item 2).

One warm stream executor per host, fed by a shape-bucketed admission
queue: concurrent clients submit archives, compatible subints coalesce
into shared fused dispatches across requests (a bucket launches when
full or past ``config.serve_max_wait_ms``), and completed TOAs
demultiplex back to per-request ``.tim`` results byte-identical to the
one-shot drivers.  See serve/server.py for the architecture and
docs/GUIDE.md "Serving TOAs" for usage; the CLI is ``ppserve``.

Cross-host scale-out (ISSUE 10): ``transport.py`` wraps the client
surface in a length-prefixed JSON protocol (``ppserve --listen`` /
``SocketTransport``; ``InProcTransport`` for tests and emulated
fleets), and ``router.ToaRouter`` + the ``pproute`` CLI shard a
campaign's requests across N such hosts — least-loaded placement,
sticky per-template affinity, backpressure retries — with the demux
still byte-identical to one-shot no matter which host served; see
docs/GUIDE.md "Routing a campaign across hosts".

Elastic fleet (ISSUE 13): ``fleet.py`` gives the router dynamic
membership with a per-host health state machine (JOINING -> HEALTHY
-> SUSPECT -> DEAD -> REJOINED off bounded probes), ``codec.py``
factors the result wire codec into the no-shared-fs ``.tim`` demux
and the durable-``.tim`` failover primitives, and the router layers
exactly-once mid-fit failover, hedged requests, routed quality
refits, and per-tenant QoS lanes (``queue.AdmissionQueue``) on top;
see docs/GUIDE.md "Operating an elastic fleet".
"""

from .client import ToaClient  # noqa: F401
from .codec import (decode_result, encode_result,  # noqa: F401
                    read_tim_result, tim_complete, write_tim_result)
from .fleet import (DEAD, HEALTHY, JOINING, REJOINED,  # noqa: F401
                    SUSPECT, Fleet, FleetFileWatcher, FleetMember)
from .queue import AdmissionQueue, ServeRejected, ServeRequest  # noqa: F401
from .router import RouteHandle, ToaRouter  # noqa: F401
from .server import ToaServer  # noqa: F401
from .transport import (InProcTransport, RemoteRequestError,  # noqa: F401
                        SocketTransport, TransportError,
                        TransportServer)
