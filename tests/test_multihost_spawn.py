"""REAL multi-process distributed execution (no monkeypatching).

Spawns N actual OS processes, each bootstrapping jax's distributed
runtime through parallel.init_multihost against a local coordinator,
then runs the documented multi-host campaign recipe
(parallel/multihost.py module docstring): shard_files -> per-process
stream_wideband_TOAs -> process_allgather of the per-archive summaries
— plus a global-mesh collective that actually crosses the process
boundary (the DCN psum).  This is the coverage VERDICT round 2 called
out as missing: until round 3 no code path had ever executed with more
than one real process.

CPU multi-process jax needs the gloo collectives backend, which
init_multihost now configures (parallel/multihost.py
_enable_cpu_collectives).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import json, sys
import numpy as np
port, pid, n, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]

import jax
# mirror tests/conftest.py: the site customization may register a TPU
# backend at interpreter start; this test must run CPU-only
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from pulseportraiture_tpu import parallel

assert parallel.init_multihost(
    coordinator_address=f"localhost:{port}", num_processes=n,
    process_id=pid) is True
assert jax.process_count() == n, jax.process_count()
assert jax.process_index() == pid

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

# --- a collective that really crosses the process boundary ----------
mesh = parallel.global_mesh()
assert mesh.devices.size == n
local = np.asarray([float(pid + 1)])
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(("data",))), local)
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
psum = float(np.asarray(jax.device_get(
    total.addressable_data(0))))

# --- the documented campaign recipe ---------------------------------
files = json.load(open(f"{outdir}/files.json"))
mine = parallel.shard_files(files)
from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs

res = stream_wideband_TOAs(mine, f"{outdir}/m.gmodel", nsub_batch=4,
                           tim_out=f"{outdir}/part{pid}.tim", quiet=True)
gathered = parallel.process_allgather(res.DeltaDM_means)


# --- the multi-pulsar IPTA campaign across REAL processes -----------
from pulseportraiture_tpu.pipeline import IPTAJob, stream_ipta_campaign

jobs = [IPTAJob("PSRA", files[:2], f"{outdir}/m.gmodel"),
        IPTAJob("PSRB", files[2:], f"{outdir}/m.gmodel")]
ires = stream_ipta_campaign(jobs, outdir=f"{outdir}/ipta",
                            nsub_batch=4, quiet=True)

out = {
    "pid": pid,
    "process_count": jax.process_count(),
    "psum": psum,
    "my_files": mine,
    "gathered": [np.asarray(g).tolist() for g in gathered],
    "toas": {f"{t.archive}|{t.flags['subint']}":
             [t.MJD.tim_string(), t.TOA_error] for t in res.TOA_list},
    "ipta_ntoa": len(ires.TOA_list),
    "ipta_pulsars": sorted(ires.per_pulsar),
    "ipta_summary": {k: sorted(np.round(v[0], 12).tolist())
                     for k, v in ires.DeltaDM_summary.items()},
}
with open(f"{outdir}/out{pid}.json", "w") as fh:
    json.dump(out, fh)
"""


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_real_processes_run_a_sharded_campaign(tmp_path):
    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.synth import (default_test_model,
                                            make_fake_pulsar)
    from pulseportraiture_tpu.utils.mjd import MJD

    n = 2
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(4):
        p = str(tmp_path / f"mh{i}.fits")
        make_fake_pulsar(model, {"PSR": "MH", "P0": 0.003, "DM": 10.0,
                                 "PEPOCH": 55000.0},
                         outfile=p, nsub=2, nchan=16, nbin=128,
                         dDM=2e-4 * i, start_MJD=MJD(55100 + i, 0.1),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=i)
        files.append(p)
    json.dump(files, open(tmp_path / "files.json", "w"))
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)

    env = dict(os.environ)
    # per-process 1-device CPU clients (the parent suite's 8-virtual-
    # device XLA_FLAGS would give 8 local x 2 processes)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the worker script lives in tmp_path, so the repo must be on the
    # import path explicitly (python puts the script dir there, not cwd)
    import pulseportraiture_tpu

    repo = os.path.dirname(os.path.dirname(pulseportraiture_tpu.__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # Bounded retry on the SPAWN phase only (the r10
    # test_worker_death_and_resume pattern): under 2-core CPU
    # contention the jax distributed runtime occasionally SIGABRTs a
    # worker during coordinator barrier setup (rc -6, "Socket
    # closed") before any campaign work starts — a runtime flake, not
    # the sharded-campaign behavior under test.  Each attempt gets a
    # fresh port and clean worker outputs; a genuine failure still
    # fails on the last try (its rc/output are asserted below).
    for attempt in range(3):
        for i in range(n):
            for leftover in (tmp_path / f"out{i}.json",
                             tmp_path / f"part{i}.tim"):
                if leftover.exists():
                    leftover.unlink()
        import shutil as _shutil

        if (tmp_path / "ipta").exists():
            _shutil.rmtree(tmp_path / "ipta")
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker_py), str(port), str(i),
                 str(n), str(tmp_path)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=repo)
            for i in range(n)
        ]
        outs = [p.communicate(timeout=600) for p in procs]
        if all(p.returncode == 0 for p in procs):
            break
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{so}\n{se}"

    results = [json.load(open(tmp_path / f"out{i}.json"))
               for i in range(n)]
    for r in results:
        assert r["process_count"] == n
        # the cross-process psum: 1 + 2 = 3 — this number cannot be
        # produced without bytes moving between the two processes
        assert r["psum"] == pytest.approx(3.0)
    # disjoint round-robin shards covering the campaign
    assert sorted(results[0]["my_files"] + results[1]["my_files"]) == \
        sorted(files)
    assert not set(results[0]["my_files"]) & set(results[1]["my_files"])
    # allgather: both processes see BOTH shards' per-archive DM stats
    for r in results:
        assert len(r["gathered"]) == n
        assert [len(g) for g in r["gathered"]] == [2, 2]
    assert np.allclose(results[0]["gathered"], results[1]["gathered"])

    # the union of the per-process TOAs equals a single-process run
    whole = stream_wideband_TOAs(files, gmodel, nsub_batch=4, quiet=True)
    want = {f"{t.archive}|{t.flags['subint']}":
            [t.MJD.tim_string(), t.TOA_error] for t in whole.TOA_list}
    got = {}
    for r in results:
        got.update(r["toas"])
    assert got.keys() == want.keys()
    for k in want:
        assert got[k][0] == want[k][0]  # digit-exact MJD strings
        assert got[k][1] == pytest.approx(want[k][1], rel=1e-9)
    # and the per-process incremental .tim checkpoints exist on disk
    for i in range(n):
        assert (tmp_path / f"part{i}.tim").read_text().count("\n") >= 4

    # --- the IPTA campaign really ran across the two processes -------
    for r in results:
        # round-robin grid sharding: every host works on BOTH pulsars,
        # 2 archives each -> 4 TOAs per host
        assert r["ipta_pulsars"] == ["PSRA", "PSRB"]
        assert r["ipta_ntoa"] == 4
    # the ALLGATHERED per-pulsar summaries are identical on both hosts
    # and cover every archive of each pulsar (2 each)
    assert results[0]["ipta_summary"] == results[1]["ipta_summary"]
    for psr in ("PSRA", "PSRB"):
        assert len(results[0]["ipta_summary"][psr]) == 2
    # per-pulsar per-process .tim shards on disk
    names = sorted(p.name for p in (tmp_path / "ipta").iterdir())
    assert names == ["PSRA.p0.tim", "PSRA.p1.tim",
                     "PSRB.p0.tim", "PSRB.p1.tim"]


SLIM_WORKER = r"""
import json, sys
import numpy as np
port, pid, n, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from pulseportraiture_tpu import parallel
assert parallel.init_multihost(
    coordinator_address=f"localhost:{port}", num_processes=n,
    process_id=pid) is True
assert jax.process_count() == n
files = json.load(open(f"{outdir}/files.json"))
mine = parallel.shard_files(files)
from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
res = stream_wideband_TOAs(mine, f"{outdir}/m.gmodel", nsub_batch=4,
                           tim_out=f"{outdir}/part{pid}.tim", quiet=True)
gathered = parallel.process_allgather(res.DeltaDM_means)
out = {"pid": pid, "my_files": mine,
       "gathered": [np.asarray(g).tolist() for g in gathered],
       "toas": {f"{t.archive}|{t.flags['subint']}":
                [t.MJD.tim_string(), t.TOA_error] for t in res.TOA_list}}
with open(f"{outdir}/out{pid}.json", "w") as fh:
    json.dump(out, fh)
"""


def _spawn_env(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    import pulseportraiture_tpu

    repo = os.path.dirname(os.path.dirname(pulseportraiture_tpu.__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env, repo


def _forge_campaign(tmp_path, nfiles, nsub=1):
    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.synth import (default_test_model,
                                            make_fake_pulsar)
    from pulseportraiture_tpu.utils.mjd import MJD

    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(nfiles):
        p = str(tmp_path / f"mh{i}.fits")
        make_fake_pulsar(model, {"PSR": "MH", "P0": 0.003, "DM": 10.0,
                                 "PEPOCH": 55000.0},
                         outfile=p, nsub=nsub, nchan=16, nbin=128,
                         dDM=2e-4 * i, start_MJD=MJD(55100 + i, 0.1),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=i)
        files.append(p)
    json.dump(files, open(tmp_path / "files.json", "w"))
    return gmodel, files


@pytest.mark.slow  # ~20 s, 4 real processes on a 2-core host (tier-1
# budget + contention flake surface, r10): the uneven round-robin
# arithmetic is unit-tested in test_parallel.py::test_shard_files_*,
# and real-process spawn + allgather stay tier-1 via the 2-process
# campaign test above
def test_four_processes_uneven_shards(tmp_path):
    """4 real processes over 6 archives: the round-robin shard
    arithmetic under uneven counts (2,2,1,1) — the >2-way coverage
    VERDICT r3 missing #4 asked for — plus cross-process allgather and
    digit-exact union vs a single-process run."""
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs

    n = 4
    gmodel, files = _forge_campaign(tmp_path, 6)
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(SLIM_WORKER)
    env, repo = _spawn_env(tmp_path)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_py), str(port), str(i), str(n),
             str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=repo)
        for i in range(n)
    ]
    outs = [p.communicate(timeout=900) for p in procs]
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{so}\n{se}"
    results = [json.load(open(tmp_path / f"out{i}.json"))
               for i in range(n)]
    shards = [r["my_files"] for r in results]
    # uneven round-robin: 6 files over 4 procs -> 2,2,1,1; disjoint;
    # complete
    assert [len(s) for s in shards] == [2, 2, 1, 1]
    flat = [f for s in shards for f in s]
    assert sorted(flat) == sorted(files) and len(set(flat)) == 6
    # every process gathers every shard's stats, same values everywhere
    for r in results:
        assert [len(g) for g in r["gathered"]] == [2, 2, 1, 1]
        for g0, g in zip(results[0]["gathered"], r["gathered"]):
            assert np.allclose(g0, g)
    # digit-exact union vs one process doing the whole campaign
    whole = stream_wideband_TOAs(files, gmodel, nsub_batch=4, quiet=True)
    want = {f"{t.archive}|{t.flags['subint']}":
            [t.MJD.tim_string(), t.TOA_error] for t in whole.TOA_list}
    got = {}
    for r in results:
        got.update(r["toas"])
    assert got.keys() == want.keys()
    for k in want:
        assert got[k][0] == want[k][0]
        assert got[k][1] == pytest.approx(want[k][1], rel=1e-9)


DYING_WORKER = r"""
import json, os, sys, threading, time
port, pid, n, outdir = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from pulseportraiture_tpu import parallel
assert parallel.init_multihost(
    coordinator_address=f"localhost:{port}", num_processes=n,
    process_id=pid) is True
files = json.load(open(f"{outdir}/files.json"))

# hard-kill this worker once its PSRA shard has >= 1 complete archive,
# leaving a torn partial line after the last sentinel (what a real
# mid-append death leaves on disk)
mytim = f"{outdir}/ipta/PSRA.p{pid}.tim"


def killer():
    while True:
        time.sleep(0.1)
        try:
            done = sum(1 for l in open(mytim)
                       if l.startswith("C ppt-done"))
        except FileNotFoundError:
            continue
        if done >= 1:
            with open(mytim, "a") as fh:
                fh.write("torn_archive 1400.0 55100.12")  # torn line
            os._exit(9)


threading.Thread(target=killer, daemon=True).start()
from pulseportraiture_tpu.pipeline import IPTAJob, stream_ipta_campaign

jobs = [IPTAJob("PSRA", files[:4], f"{outdir}/m.gmodel"),
        IPTAJob("PSRB", files[4:], f"{outdir}/m.gmodel")]
stream_ipta_campaign(jobs, outdir=f"{outdir}/ipta", nsub_batch=2,
                     quiet=True)
os._exit(7)  # campaign outlived the killer: test setup failed
"""


@pytest.mark.slow
def test_worker_death_and_resume(tmp_path):
    """SURVEY S5 elastic recovery at campaign scale: two workers die
    mid-IPTA-campaign (each leaving a torn checkpoint tail after its
    last completion sentinel); the campaign is re-entered with a
    DIFFERENT process layout (one process, resume=True) and finishes
    only the missing archives — the union of all .tim shards is
    digit-exact against an uninterrupted run."""
    from pulseportraiture_tpu.pipeline import (IPTAJob,
                                               stream_ipta_campaign)

    import shutil

    n = 2
    gmodel, files = _forge_campaign(tmp_path, 8, nsub=2)
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(DYING_WORKER)
    env, repo = _spawn_env(tmp_path)

    # Bounded retry on the SPAWN phase only: under 2-core CPU
    # contention the jax distributed runtime occasionally SIGABRTs a
    # worker during coordinator barrier setup (rc -6, "Socket
    # closed") before the campaign even starts — a runtime flake, not
    # the death-and-resume behavior under test (the test passes
    # standalone every time).  Each attempt gets a fresh port and a
    # clean ipta dir; genuine assertion failures (rc 7/0: killer
    # never fired or campaign survived) still fail on the last try.
    last = None
    for attempt in range(3):
        if (tmp_path / "ipta").exists():
            shutil.rmtree(tmp_path / "ipta")
        (tmp_path / "ipta").mkdir()
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker_py), str(port), str(i),
                 str(n), str(tmp_path)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=repo)
            for i in range(n)
        ]
        outs = [p.communicate(timeout=900) for p in procs]
        rcs = [p.returncode for p in procs]
        # 9 = self-killed mid-campaign; 1 = taken down by the jax
        # distributed runtime when its peer (the coordinator)
        # vanished — both are genuine worker deaths.  7 would mean
        # the killer never fired; 0 would mean the campaign survived.
        torn = 0
        for i in range(n):
            f = tmp_path / "ipta" / f"PSRA.p{i}.tim"
            if f.exists():
                torn += f.read_text().rstrip("\n").endswith("55100.12")
        last = (rcs, outs, torn)
        if all(rc in (9, 1) for rc in rcs) and 9 in rcs and torn >= 1:
            break
    rcs, outs, torn = last
    assert all(rc in (9, 1) for rc in rcs), (rcs, outs)
    assert 9 in rcs, (rcs, outs)
    # at least one torn checkpoint tail is really on disk
    assert torn >= 1

    # ---- re-enter with ONE process, resume=True ---------------------
    jobs = [IPTAJob("PSRA", files[:4], gmodel),
            IPTAJob("PSRB", files[4:], gmodel)]
    stream_ipta_campaign(jobs, outdir=str(tmp_path / "ipta"),
                         nsub_batch=2, quiet=True, resume=True)

    # ---- union of shards == uninterrupted run, digit-exact ----------
    from pulseportraiture_tpu.timing import read_tim

    fresh = tmp_path / "fresh"
    fresh.mkdir()
    stream_ipta_campaign(jobs, outdir=str(fresh), nsub_batch=2,
                         quiet=True)
    import glob as _glob

    def lineset(paths):
        out = {}
        for f in paths:
            for t in read_tim(f):
                out[f"{t.archive}|{t.flags.get('subint')}"] = (
                    t.mjd_int, t.mjd_frac, t.error_us)
        return out

    got = lineset(_glob.glob(str(tmp_path / "ipta" / "*.tim")))
    want = lineset(_glob.glob(str(fresh / "*.tim")))
    assert got.keys() == want.keys()
    for k in want:
        assert got[k][0] == want[k][0]
        assert got[k][1] == pytest.approx(want[k][1], abs=0.0)
        assert got[k][2] == pytest.approx(want[k][2], rel=1e-12)
    # no torn/duplicate lines survived anywhere
    for f in _glob.glob(str(tmp_path / "ipta" / "*.tim")):
        text = open(f).read()
        assert "torn_archive" not in text
    all_keys = []
    for f in _glob.glob(str(tmp_path / "ipta" / "*.tim")):
        for t in read_tim(f):
            all_keys.append(f"{t.archive}|{t.flags.get('subint')}")
    assert len(all_keys) == len(set(all_keys))
