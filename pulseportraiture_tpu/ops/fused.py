"""Fused (hand-blocked) DFT -> cross-spectrum hot path (ISSUE 14).

The wideband fit's prepare stage historically ran as separate XLA ops
with full-size intermediates between them: two (nchan, nharm) DFT
pairs for data and model (dr/di/mr/mi), then the elementwise
cross-spectrum, then the per-channel power reductions — six
(nchan, nharm) HBM-resident arrays to produce the two the Newton loop
actually reads (Xr, Xi).  On an MXU that is the difference between a
roofline matmul and a pipeline of HBM round-trips (BENCH_r04/r05: the
fit lane flat at 22.1-22.4k TOAs/s, mfu 0.121, since round 4).

`fused_cross_spectrum` blocks the channel axis through ONE lax.scan:
each step DFTs a channel block (reusing ops.fourier.rfft_mm — the
matmul-DFT single source of truth, so precision/fold semantics are
shared), forms the block's weighted cross-spectrum and model power in
registers/VMEM-sized tiles, and emits only the persistent outputs.
Per-row matmul results and per-row reductions are BITWISE identical to
the unblocked program (blocking never re-associates a row's
contraction; guarded by tests/test_fastpath.py and the .tim byte gates
in tests/test_stream.py), which is what lets config.fit_fused flip
with zero behavior drift.

Scope: the fused program is the WINDOWED hot path — the caller's
full-spectrum data power must come from the exact time-domain Parseval
form (fit/portrait._parseval_Sd), which the harmonic-window lane
already uses; fit/portrait only activates fusion when nharm_eff is
set.  The Pallas kernel variant (fusing the per-Newton-pass moment
reductions into the same VMEM-resident tiles) is stubbed below for the
chip session; on TPU today config.fit_fused='auto' takes this same
hand-blocked XLA program.
"""

import jax
import jax.numpy as jnp

__all__ = ["fused_cross_spectrum", "fused_cross_spectrum_pallas",
           "HAVE_PALLAS_FUSED"]

# The chip-session Pallas kernel is not implemented yet; when it lands
# this flips and fused_cross_spectrum dispatches to it on TPU backends.
HAVE_PALLAS_FUSED = False

# Channel-block target: big enough that the block DFT matmul amortizes
# loop overhead, small enough that a block's (cb, nbin) input tile and
# (cb, nharm) output tiles stay cache/VMEM-resident at production
# shapes (512ch x 2048bin f32: 32 x 2048 x 4B = 256 KB in, 4 x 32 x
# nharm out).
_BLOCK_TARGET = 32


def _block_size(nchan, target=_BLOCK_TARGET):
    """Block size for the channel tiling: the target, clamped to
    nchan.  A ragged channel count is ZERO-PADDED up to a block
    multiple rather than degrading the block (a degenerate 1-row
    block would lower the DFT matmul to a gemv, whose contraction
    order differs from the gemm rows the unfused program computes —
    measured non-bitwise on CPU; zero pad rows cost their flops but
    keep every real row's kernel identical)."""
    return min(int(target), int(nchan))


def fused_cross_spectrum(port, model, w, nharm, precision=None,
                         fold=None, want_m2=False, block=None):
    """One blocked pass: windowed split-real DFT of data + model ->
    weighted cross-spectrum (+ model power), never materializing the
    full (nchan, nharm) DFT intermediates.

    port/model: (nchan, nbin) time-domain portraits (model may be the
    shared template — under vmap with in_axes=None its per-block DFT
    stays unbatched and hoists).  w: (nchan, nharm) weights already
    sliced to the harmonic window.  nharm: the window (static).
    want_m2=False returns (Xr, Xi, S0) with S0 the per-channel model
    power (the no-scattering lane); want_m2=True returns (Xr, Xi, M2w)
    with the full weighted model power spectrum (the scattering lane,
    which needs it per harmonic).

    Every output row is bitwise identical to the unfused program's —
    the per-row DFT contraction and the per-row harmonic reduction are
    untouched by channel blocking."""
    if HAVE_PALLAS_FUSED and jax.default_backend() == "tpu":
        return fused_cross_spectrum_pallas(port, model, w, nharm,
                                           precision=precision,
                                           fold=fold, want_m2=want_m2)
    from .fourier import rfft_mm

    nchan, nbin = port.shape[-2], port.shape[-1]
    cb = _block_size(nchan, _BLOCK_TARGET if block is None else block)
    nblk = -(-nchan // cb)
    pad = nblk * cb - nchan

    def tile(x, width):
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad, width), x.dtype)], axis=0)
        return x.reshape(nblk, cb, width)

    pb = tile(port, nbin)
    mb = tile(model, nbin)
    wb = tile(w, nharm)

    def step(carry, xs):
        p, m, wk = xs
        drb, dib = rfft_mm(p, precision=precision, nharm=nharm,
                           fold=fold)
        mrb, mib = rfft_mm(m, precision=precision, nharm=nharm,
                           fold=fold)
        Xrb = (drb * mrb + dib * mib) * wk
        Xib = (dib * mrb - drb * mib) * wk
        m2b = (mrb**2 + mib**2) * wk
        out2 = m2b if want_m2 else jnp.sum(m2b, axis=-1)
        return carry, (Xrb, Xib, out2)

    _, (Xr, Xi, o2) = jax.lax.scan(step, 0, (pb, mb, wb))
    Xr = Xr.reshape(nblk * cb, nharm)[:nchan]
    Xi = Xi.reshape(nblk * cb, nharm)[:nchan]
    o2 = (o2.reshape(nblk * cb, nharm)[:nchan] if want_m2
          else o2.reshape(nblk * cb)[:nchan])
    return Xr, Xi, o2


def fused_cross_spectrum_pallas(port, model, w, nharm, precision=None,
                                fold=None, want_m2=False):
    """Pallas kernel variant — STUB, pre-scoped for the next chip
    session (BENCHMARKS.md config 6/2): one VMEM-resident kernel per
    channel tile computing DFT matmul + cross-spectrum + the first
    moment pass without touching HBM between stages, the step the
    hand-blocked XLA program cannot express (XLA will not fuse a dot
    into its consumers).  Guarded by HAVE_PALLAS_FUSED so nothing
    dispatches here until the kernel exists."""
    raise NotImplementedError(
        "the Pallas fused cross-spectrum kernel is pre-scoped for the "
        "next chip session (HAVE_PALLAS_FUSED is False); "
        "fused_cross_spectrum runs the hand-blocked XLA program on "
        "every backend today")
