"""Data access & formats (SURVEY §2.2 L1): PSRFITS archives without
PSRCHIVE, model-file formats, TOA/tim writers, telescope codes."""

from .fitsio import TruncatedFits, scan_fits  # noqa: F401
from .psrfits import (  # noqa: F401
    Archive,
    load_data,
    new_archive,
    parse_parfile,
    read_archive,
    unload_new_archive,
    write_archive_file,
)
from .gmodel import (  # noqa: F401
    gen_gmodel_portrait,
    model_from_flat,
    model_to_flat,
    read_gmodel,
    write_gmodel,
)
from .splmodel import (  # noqa: F401
    SplineModel,
    read_spline_model,
    spline_model_coords,
    write_spline_model,
)
from .telescopes import telescope_code, telescope_code_dict  # noqa: F401
from .tim import (  # noqa: F401
    TOA,
    filter_TOAs,
    toa_string,
    write_princeton_TOAs,
    write_TOAs,
)
