"""Power-law and linear frequency fits.

Replaces the reference's lmfit-based fit_powlaw (pplib.py:1841-1880)
with a jittable Gauss-Newton, and fit_DM_to_freq_resids
(pplib.py:1883-1919) with a closed-form weighted linear solve.
"""


import jax.numpy as jnp

from ..config import Dconst
from ..utils.bunch import DataBunch

__all__ = ["powlaw", "powlaw_integral", "powlaw_freqs", "fit_powlaw",
           "fit_powlaw_function", "fit_DM_to_freq_resids"]


def powlaw(nu, nu_ref, A, alpha):
    """A * (nu/nu_ref)**alpha (reference pplib.py:1087-1099)."""
    return A * (nu / nu_ref) ** alpha


def powlaw_integral(nu2, nu1, nu_ref, A, alpha):
    """Integral of powlaw from nu1 to nu2 (reference pplib.py:1102-1114)."""
    alpha = jnp.asarray(alpha, float)
    A = jnp.asarray(A, float)
    C = A * (nu_ref ** -alpha)
    return jnp.where(
        alpha == -1.0,
        A * nu_ref * jnp.log(nu2 / nu1),
        (C / (1.0 + alpha)) * (nu2 ** (1.0 + alpha) - nu1 ** (1.0 + alpha)),
    )


def powlaw_freqs(lo, hi, N, alpha):
    """N+1 channel edges between lo and hi such that each channel has
    equal flux for a spectral index alpha (reference pplib.py:1117-1137)."""
    import numpy as np

    alpha = float(alpha)
    if alpha == -1.0:
        return np.exp(np.linspace(np.log(lo), np.log(hi), N + 1))
    a1 = 1.0 + alpha
    return (np.linspace(lo**a1, hi**a1, N + 1)) ** (1.0 / a1)


def _powlaw_resid(theta, ys, sqrtw, x):
    return (ys - theta[0] * jnp.exp(theta[1] * x)) * sqrtw


def _fit_powlaw_core(ys, errs, nu_ref, freqs):
    """Weighted log-space init + damped LM (fit/lm.py).  The LM engine
    already scales the covariance by red-chi2, matching lmfit's default
    scale_covar=True that the reference relies on (pplib.py:1841-1880)."""
    from .lm import levenberg_marquardt

    dt = ys.dtype
    w = jnp.where(errs > 0, errs**-2.0, 0.0)
    x = jnp.log(freqs / nu_ref)

    # init: weighted log-space linear fit on positive ys
    pos = ys > 0
    ly = jnp.log(jnp.where(pos, ys, 1.0))
    wp = jnp.where(pos, w, 0.0)
    Sw = wp.sum()
    Sx = (wp * x).sum()
    Sy = (wp * ly).sum()
    Sxx = (wp * x * x).sum()
    Sxy = (wp * x * ly).sum()
    det = Sw * Sxx - Sx**2.0
    det = jnp.where(jnp.abs(det) > 0, det, 1.0)
    alpha0 = (Sw * Sxy - Sx * Sy) / det
    lnA0 = jnp.clip((Sxx * Sy - Sx * Sxy) / det, -300.0, 300.0)
    theta0 = jnp.array([jnp.exp(lnA0), alpha0], dt)

    res = levenberg_marquardt(_powlaw_resid, theta0,
                              aux=(ys, jnp.sqrt(w), x), max_iter=100)
    return res.x, res.cov, res.chi2


def fit_powlaw(data, init_params=None, errs=None, nu_ref=None, freqs=None):
    """Fit A*(nu/nu_ref)**alpha to data(freqs) with uncertainties.

    Returns a DataBunch(amp, amp_err, alpha, alpha_err, chi2, dof,
    red_chi2, residuals, nu_ref, freqs) mirroring reference
    pplib.py:1841-1880 (lmfit leastsq -> Gauss-Newton here).
    init_params is accepted for API compatibility; the initial guess is
    derived from a weighted log-space fit.
    """
    ys = jnp.asarray(data, float)
    freqs = jnp.asarray(freqs, float)
    if errs is None:
        errs = jnp.ones_like(ys)
    errs = jnp.asarray(errs, float)
    if nu_ref is None:
        nu_ref = float(freqs.mean())
    theta, cov, chi2 = _fit_powlaw_core(ys, errs, nu_ref, freqs)
    dof = ys.shape[0] - 2
    resids = ys - theta[0] * (freqs / nu_ref) ** theta[1]
    return DataBunch(
        amp=float(theta[0]),
        amp_err=float(jnp.sqrt(jnp.maximum(cov[0, 0], 0.0))),
        alpha=float(theta[1]),
        alpha_err=float(jnp.sqrt(jnp.maximum(cov[1, 1], 0.0))),
        chi2=float(chi2),
        dof=int(dof),
        red_chi2=float(chi2 / max(dof, 1)),
        residuals=resids,
        nu_ref=nu_ref,
        freqs=freqs,
    )


def fit_DM_to_freq_resids(freqs, frequency_residuals, errs):
    """Weighted linear fit of residuals [s] vs nu^-2 -> (DM, offset,
    nu_ref) and uncertainties (reference pplib.py:1883-1919).

    res = Dconst*DM*nu^-2 + offset = Dconst*DM*(nu^-2 - nu_ref^-2).

    Deliberate deviation from the reference: np.polyfit applies `w`
    multiplicatively to residuals, so the reference's w=errs**-2
    effectively minimizes sum(errs^-4 * resid^2) — an inverse-variance
    weighting in errs^2, not errs.  Here the standard chi^2
    sum((resid/errs)^2) is minimized; with non-uniform errs the point
    estimates differ from PulsePortraiture's (ours are the maximum-
    likelihood ones).  Covariance is scaled by red-chi2 as
    polyfit(cov=True) does.
    """
    x = jnp.asarray(freqs, float) ** -2.0
    y = jnp.asarray(frequency_residuals, float)
    w = jnp.asarray(errs, float) ** -2.0
    Sw, Sx, Sy = w.sum(), (w * x).sum(), (w * y).sum()
    Sxx, Sxy = (w * x * x).sum(), (w * x * y).sum()
    det = Sw * Sxx - Sx**2.0
    a = (Sw * Sxy - Sx * Sy) / det
    b = (Sxx * Sy - Sx * Sxy) / det
    resids = y - (a * x + b)
    chi2 = float(jnp.sum(w * resids**2.0))
    dof = int(y.shape[0] - 2)
    red = chi2 / max(dof, 1)
    # cov of (a, b), scaled by red-chi2 as polyfit(cov=True) does
    va = Sw / det * red
    vb = Sxx / det * red
    vab = -Sx / det * red
    DM = float(a / Dconst)
    DM_err = float(jnp.sqrt(jnp.maximum(va, 0.0)) / Dconst)
    offset = float(b)
    offset_err = float(jnp.sqrt(jnp.maximum(vb, 0.0)))
    nu_ref = float((-b / a) ** -0.5) if (b / a) < 0 else float("nan")
    if nu_ref == nu_ref:  # not NaN
        nu_ref_err = float(
            jnp.sqrt(
                jnp.maximum(
                    (nu_ref**2.0 / 4.0)
                    * ((va / a**2.0) + (vb / b**2.0) - (2.0 * vab / (a * b))),
                    0.0,
                )
            )
        )
    else:
        nu_ref_err = float("nan")
    return DataBunch(
        DM=DM, DM_err=DM_err, offset=offset, offset_err=offset_err,
        nu_ref=nu_ref, nu_ref_err=nu_ref_err, ab_cov=float(vab),
        residuals=resids, chi2=chi2, dof=dof, red_chi2=red,
    )


def fit_powlaw_function(params, freqs, nu_ref, data, errs=None):
    """Weighted residuals of a power-law model — the reference's
    objective callable (fit_powlaw_function, pplib.py:1251-1264), kept
    for API parity and as a finite-difference oracle for the
    Gauss-Newton fit; params = (A, alpha)."""
    A, alpha = params[0], params[1]
    resid = data - powlaw(jnp.asarray(freqs), nu_ref, A, alpha)
    if errs is not None:
        resid = resid / jnp.asarray(errs)
    return resid
