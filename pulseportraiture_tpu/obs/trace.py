"""Trace-context minting for distributed request tracing.

A ``trace_id`` is minted once — at ``ToaRouter.submit`` (or at
``ToaServer.submit`` for direct clients) — and then propagated
unchanged through the wire submit op, ``ServeRequest``, hedge and
failover re-dispatches, and every telemetry event the request touches
on any host.  The id is an opaque 16-hex-char token; nothing parses
it, everything joins on it.
"""

import uuid


def new_trace_id():
    """Mint a fresh opaque trace id (16 hex chars, collision-safe for
    any realistic campaign size)."""
    return uuid.uuid4().hex[:16]
