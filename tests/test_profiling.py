"""Unit tests for the stage-attribution profiler
(pulseportraiture_tpu.profiling) — the reusable promotion of
exp_breakdown.py's methodology (ISSUE 1 tentpole)."""

import jax.numpy as jnp
import pytest

from pulseportraiture_tpu.profiling import (Attribution, Stage, devtime,
                                            profile_stages)


def _fake_devtime(table):
    """Stub timer: each stage fn returns its key into `table`."""

    def dt(fn, pick=None, K=4, warm=1, nrun=3):
        s = table[fn()]
        return s, s

    return dt


def test_prefix_differencing_and_attribution_math():
    table = {"full": 10.0, "a": 2.0, "b": 5.0, "p": 4.0}
    stages = [
        Stage("a", lambda: "a"),
        Stage("b", lambda: "b"),
        Stage("p", lambda: "p", "piece"),
    ]
    att = profile_stages(lambda: "full", stages,
                         devtime_fn=_fake_devtime(table))
    assert att.total_s == 10.0
    # prefix costs are differenced; the piece adds directly
    assert att.cost("a") == 2.0
    assert att.cost("b") == 3.0
    assert att.cost("p") == 4.0
    # attributed = last prefix slope + pieces, NEVER built from total
    assert att.attributed_s == 9.0
    assert att.attributed_frac == pytest.approx(0.9)
    assert att.check(0.9)
    assert not att.check(0.95)


def test_breakdown_ms_fields():
    table = {"full": 0.010, "a": 0.004, "p": 0.005}
    att = profile_stages(
        lambda: "full",
        [Stage("a", lambda: "a"), Stage("p", lambda: "p", "piece")],
        devtime_fn=_fake_devtime(table))
    d = att.breakdown_ms()
    assert d["stage_a_ms"] == 4.0
    assert d["stage_p_ms"] == 5.0
    assert d["full_ms"] == 10.0
    assert d["attributed_frac"] == 0.9


def test_negative_prefix_difference_clamps_to_zero():
    # load noise can make a later prefix measure FASTER; the stage cost
    # clamps at 0 instead of going negative
    table = {"full": 10.0, "a": 5.0, "b": 4.0}
    att = profile_stages(
        lambda: "full",
        [Stage("a", lambda: "a"), Stage("b", lambda: "b")],
        devtime_fn=_fake_devtime(table))
    assert att.cost("b") == 0.0
    # attribution still uses the last prefix's own slope
    assert att.attributed_s == 4.0


def test_prefix_after_piece_raises():
    table = {"full": 1.0, "a": 0.5, "p": 0.2}
    with pytest.raises(ValueError, match="prefix.*piece"):
        profile_stages(
            lambda: "full",
            [Stage("p", lambda: "p", "piece"),
             Stage("a", lambda: "a")],
            devtime_fn=_fake_devtime(table))


def test_unknown_stage_kind_raises():
    with pytest.raises(ValueError, match="unknown stage kind"):
        profile_stages(
            lambda: "full", [Stage("x", lambda: "full", "weird")],
            devtime_fn=_fake_devtime({"full": 1.0}))


def test_unknown_stage_name_raises():
    att = Attribution(1.0, 1.0, (), 1.0, 1.0)
    with pytest.raises(KeyError):
        att.cost("nope")


def test_devtime_real_dispatch_smoke():
    x = jnp.arange(64.0)
    slope, single = devtime(lambda: x * 2.0, K=2, warm=1, nrun=1)
    assert slope > 0.0
    assert single > 0.0
