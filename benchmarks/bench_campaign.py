"""Campaign-scale streaming benchmark (BASELINE.md config 5 shape):
NARCH archives x NSUB subints of NCHAN x NBIN through
stream_wideband_TOAs, end-to-end (PSRFITS IO -> raw int16 h2d ->
on-device decode/stats/fit -> .tim assembly).

The synthetic dataset is generated once into a cache directory (env
PPT_CAMPAIGN_CACHE, default /tmp/ppt_campaign) and reused across runs —
generation is host-bound and would otherwise dominate.

Knobs via env: PPT_NARCH (default 200), PPT_NSUB (64), PPT_NCHAN (256),
PPT_NBIN (1024).  Prints ONE JSON line like bench.py.
"""

import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    import jax

    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = int(os.environ.get("PPT_NARCH", 200))
    NSUB = int(os.environ.get("PPT_NSUB", 64))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    t_gen = time.perf_counter()
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * (i % 50), dDM=1e-4 * (i % 40),
                             noise_stds=0.05, quiet=True, rng=i)
        files.append(path)
    t_gen = time.perf_counter() - t_gen

    # warm (compile) on one archive, then measure the full campaign
    stream_wideband_TOAs(files[:1], mpath, nsub_batch=64, quiet=True)
    t0 = time.perf_counter()
    res = stream_wideband_TOAs(files, mpath, nsub_batch=64, quiet=True)
    wall = time.perf_counter() - t0

    ntoa = len(res.TOA_list)
    print(json.dumps({
        "metric": f"streamed campaign TOAs incl. PSRFITS IO, {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin",
        "value": round(ntoa / wall, 2),
        "unit": "TOAs/sec",
        "wall_s": round(wall, 2),
        "gen_s": round(t_gen, 2),
        "toas": ntoa,
        "dispatches": int(res.nfit),
        "blocked_on_device_fraction": round(float(res.fit_duration) / wall,
                                            3),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
