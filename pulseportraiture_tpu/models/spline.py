"""PCA + B-spline profile-evolution models.

TPU-native counterpart of the reference's spline modeling stack
(reference pplib.py:1564-1689 pca/reconstruct_portrait/
find_significant_eigvec; pplib.py:966-990 gen_spline_portrait;
ppspline.py:39-217 make_spline_model).  The PCA and all model
*evaluation* run on device in JAX (eigh, de Boor B-spline basis);
the one-off knot selection (scipy.interpolate.splprep) stays on host —
model building is offline, model evaluation is the hot path.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .wavelet import smart_smooth
from ..ops.noise import get_noise_PS

__all__ = [
    "pca",
    "reconstruct_portrait",
    "count_crossings",
    "find_significant_eigvec",
    "bspline_eval",
    "gen_spline_portrait",
    "fit_spline_curve",
    "fft_resample",
]


@jax.jit
def pca(port, mean_prof=None, weights=None):
    """Weighted principal component analysis of an (nchan, nbin) portrait.

    Returns (eigval, eigvec) sorted by descending eigenvalue; eigvec are
    column vectors (nbin, nbin).  Matches reference pplib.py:1564-1602:
    weighted mean-profile subtraction, np.cov(..., aweights=w, ddof=1)
    normalization, eigh.
    """
    port = jnp.asarray(port)
    nchan = port.shape[0]
    if weights is None:
        weights = jnp.ones((nchan,), port.dtype)
    weights = jnp.asarray(weights, port.dtype)
    if mean_prof is None:
        mean_prof = (port * weights[:, None]).sum(0) / weights.sum()
    delta = port - mean_prof
    # np.cov(delta.T, aweights=w, ddof=1) normalization:
    # denom = sum(w) - sum(w^2)/sum(w)
    wsum = weights.sum()
    denom = wsum - (weights**2.0).sum() / wsum
    cov = (delta.T * weights) @ delta / denom
    eigval, eigvec = jnp.linalg.eigh(cov)
    return eigval[::-1], eigvec[:, ::-1]


@jax.jit
def reconstruct_portrait(port, mean_prof, eigvec):
    """Project (port - mean) onto the eigvec subspace and rebuild
    (reference pplib.py:1605-1622)."""
    delta = jnp.asarray(port) - mean_prof
    return (delta @ eigvec) @ eigvec.T + mean_prof


def count_crossings(x, threshold):
    """Number of sign changes of (x - threshold), i.e. crossings in
    either direction (reference pplib.py:710-718)."""
    x = np.asarray(x)
    return int(np.sum(np.diff(np.sign(x - threshold)) != 0))


def find_significant_eigvec(eigvec, check_max=10, return_max=10,
                            snr_cutoff=150.0, check_crossings=True,
                            check_acorr=False, return_smooth=True, **kwargs):
    """Select "significant" eigenvectors by smoothed Fourier S/N with a
    crossing-count veto (reference pplib.py:1625-1689).

    check_acorr adds an autocorrelation-FWHM veto for borderline
    eigenvectors.  It defaults to False because the corresponding
    branch in the reference is unreachable (the `elif ... and
    add_eigvec` at pplib.py:1671 can never be True), so the reference's
    effective behavior never applies it; enable it here to get the
    documented-but-dead stricter check.

    eigvec: (nbin, ncomp) column eigenvectors.  Returns (ieig, smooth_eigvec)
    when return_smooth else ieig.
    """
    eigvec = np.asarray(eigvec)
    nbin = eigvec.shape[0]
    # the loop below never examines candidates past check_max, so only
    # smooth that many (smoothing is the expensive step)
    ncheck = min(check_max, eigvec.shape[1])
    # smooth all candidates at once on device
    cands = eigvec.T[:ncheck]
    smoothed = np.asarray(smart_smooth(cands, **kwargs))
    smooth_eigvec = np.zeros_like(eigvec)
    ieig = []
    for ivec in range(ncheck):
        ev = smoothed[ivec]
        ev_noise = float(get_noise_PS(jnp.asarray(cands[ivec]))) * \
            np.sqrt(nbin / 2.0)
        if ev_noise <= 0.0:
            continue
        ev_snr = float(np.sum(np.abs(np.fft.rfft(ev)[1:]) ** 2.0)) / ev_noise
        add = False
        if ev_snr >= snr_cutoff:
            add = True
            if check_crossings and ev_snr < 3.0 * snr_cutoff:
                ncross = count_crossings(np.abs(ev), 0.1 * np.abs(ev).max())
                add = ncross < int(0.02 * nbin)
            if add and check_acorr and ev_snr < 3.0 * snr_cutoff:
                acorr = np.correlate(ev, ev, "same")
                half = np.where(acorr > acorr.max() / 2.0)[0]
                fwhm = acorr.argmax() - half.min() if len(half) else 0
                add = fwhm > 5
        if add:
            ieig.append(ivec)
            smooth_eigvec[:, ivec] = ev
        if ivec + 1 == check_max or len(ieig) == return_max:
            break
    ieig = np.array(ieig, dtype=int)
    return (ieig, smooth_eigvec) if return_smooth else ieig


# --------------------------------------------------------------------------
# B-spline evaluation in JAX (de Boor / Cox recursion, fixed knots)
# --------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k",))
def _bspline_basis(x, t, k):
    """All B-spline basis functions B_{i,k}(x) on knot vector t.

    x: (nx,), t: (nknot,), degree k.  Returns (nx, nknot-k-1).
    Cox-de Boor bottom-up recursion with 0/0 := 0 — static shapes,
    fully vectorized (no data-dependent control flow).
    """
    x = jnp.asarray(x)
    t = jnp.asarray(t, x.dtype)
    nknot = t.shape[0]
    # clamp x into the valid interval so ext=0 (splev default:
    # extrapolate) becomes clamp-to-edge; scipy ext=0 extrapolates the
    # polynomial, but clamped evaluation is the numerically sane choice
    # for frequencies outside the fitted band and is what the pipeline
    # wants.  (reference gen_spline_portrait passes ext=0.)
    lo = t[k]
    hi = t[nknot - k - 1]
    eps = jnp.finfo(x.dtype).eps
    xc = jnp.clip(x, lo, hi * (1.0 - eps) + lo * eps)
    # degree-0: indicator of [t_i, t_{i+1})
    ti = t[None, :-1]
    tip1 = t[None, 1:]
    B = ((xc[:, None] >= ti) & (xc[:, None] < tip1)).astype(x.dtype)
    # make the last nonempty interval right-closed
    last = jnp.argmax(jnp.where(t[1:] > t[:-1], jnp.arange(nknot - 1), -1))
    B = B.at[:, last].set(
        jnp.where(xc >= t[last], ((xc >= t[last]) & (xc <= t[last + 1])),
                  B[:, last] > 0).astype(x.dtype))
    for d in range(1, k + 1):
        tid = t[d:-1] if d < nknot - 1 else t[d:]
        left_den = t[d:nknot - 1] - t[0:nknot - 1 - d]
        right_den = t[d + 1:nknot] - t[1:nknot - d]
        left_den_safe = jnp.where(left_den > 0, left_den, 1.0)
        right_den_safe = jnp.where(right_den > 0, right_den, 1.0)
        wl = (xc[:, None] - t[None, 0:nknot - 1 - d]) / left_den_safe
        wl = jnp.where(left_den > 0, wl, 0.0)
        wr = (t[None, d + 1:nknot] - xc[:, None]) / right_den_safe
        wr = jnp.where(right_den > 0, wr, 0.0)
        B = wl * B[:, :nknot - 1 - d] + wr * B[:, 1:nknot - d]
    return B


def bspline_eval(x, tck):
    """Evaluate a (possibly vector-valued) B-spline at x.

    tck = (t, c, k) as from scipy.interpolate.splprep: t (nknot,),
    c a list/array of coefficient vectors (ncomp, ncoef), degree k.
    Returns (nx, ncomp).  JAX equivalent of si.splev(x, tck).T.
    """
    t, c, k = tck
    c = jnp.atleast_2d(jnp.asarray(c))
    B = _bspline_basis(jnp.atleast_1d(jnp.asarray(x)), jnp.asarray(t), int(k))
    return B @ c.T[: B.shape[1]]


@partial(jax.jit, static_argnames=("nbin",))
def fft_resample(port, nbin):
    """Fourier resampling along the last axis (scipy.signal.resample
    equivalent), used when evaluating a model at a different nbin."""
    port = jnp.asarray(port)
    n_in = port.shape[-1]
    F = jnp.fft.rfft(port, axis=-1)
    nh_out = nbin // 2 + 1
    nh_in = F.shape[-1]
    if nh_out > nh_in:
        pad = [(0, 0)] * (F.ndim - 1) + [(0, nh_out - nh_in)]
        F = jnp.pad(F, pad)
    else:
        F = F[..., :nh_out]
    return jnp.fft.irfft(F, n=nbin, axis=-1) * (nbin / n_in)


def gen_spline_portrait(mean_prof, freqs, eigvec, tck, nbin=None):
    """Model portrait = mean_prof + B-spline(freqs) . eigvec^T
    (reference pplib.py:966-990).

    mean_prof: (nbin_model,); freqs: (nchan,); eigvec: (nbin_model, ncomp);
    tck from fit_spline_curve/splprep.  Optional resampling to a
    different nbin with the half-bin rotation fix.
    """
    mean_prof = jnp.asarray(mean_prof)
    freqs = jnp.atleast_1d(jnp.asarray(freqs))
    eigvec = jnp.asarray(eigvec)
    if eigvec.shape[1] == 0:
        port = jnp.tile(mean_prof, (freqs.shape[0], 1))
    else:
        proj = bspline_eval(freqs, tck)  # (nchan, ncomp)
        port = proj @ eigvec.T + mean_prof
    if nbin is not None and nbin != mean_prof.shape[-1]:
        from ..ops.rotation import rotate_portrait

        shift = 0.5 * (nbin**-1.0 - mean_prof.shape[-1] ** -1.0)
        port = fft_resample(port, nbin)
        port = rotate_portrait(port, shift)
    return port


def fit_spline_curve(proj, freqs, flux_errs=None, snrs=None, sfac=1.0,
                     max_nbreak=None, k=3):
    """Fit a parametric B-spline curve to projected PCA coordinates vs
    frequency (reference ppspline.py:141-162).

    proj: (nchan, ncomp) projections of delta-profiles onto eigvec;
    freqs: (nchan,) strictly increasing; snrs/flux_errs set the
    smoothing condition s = sfac * nchan * sum((snr*err)^2)/sum(snr^2).
    Host-side (scipy.interpolate.splprep); returns tck = (t, c, k) with
    c shaped (ncomp, ncoef).
    """
    import scipy.interpolate as si

    proj = np.asarray(proj)
    freqs = np.asarray(freqs)
    nchan, ncomp = proj.shape
    if ncomp == 0:
        return (np.array([freqs[0], freqs[-1]]), np.zeros((0, 2)), 1)
    if snrs is None:
        snrs = np.ones(nchan)
    if flux_errs is None:
        flux_errs = np.ones(nchan)
    # normalized weights w_i = snr_i / sum(snr) with the matching
    # smoothing condition s = sfac*nchan*sum((snr*err)^2)/(sum(snr))^2,
    # so that E[sum((w_i * resid_i)^2)] ~ s for a good fit
    # (reference ppspline.py:141-152)
    snrs = np.asarray(snrs, float)
    flux_errs = np.asarray(flux_errs, float)
    w = snrs / snrs.sum()
    s = sfac * nchan * np.sum((snrs * flux_errs) ** 2.0) / (snrs.sum() ** 2.0)
    kwargs = {}
    if max_nbreak is not None:
        kwargs["nest"] = max_nbreak + 2 * k
    (t, c, kk), _ = si.splprep(proj.T, w=w, u=freqs, s=s, k=k, **kwargs)
    return (np.asarray(t), np.asarray(c), int(kk))
