"""Online observatory pipeline (ISSUE 18): continuous ingest into the
warm serving loop, incremental GLS timing, and anomaly alerting.

Everything upstream of this package is request/response over finished
archives; an observatory wants wideband TOAs AS DATA ARRIVES.  This
package adds the always-on lane without adding a new executor
(ROADMAP: "add an ingest driver, not a new executor"):

* ``source.py`` — where archives come from: a watch-folder source
  with size-stability + completion-sentinel admission (half-written
  PSRFITS never reach the loaders) and a socket source reusing the
  serve/transport.py framing for push-style announcement.
* ``driver.py`` — the ingest driver: probes each candidate for
  truncation (io.scan_fits -> typed retry-on-stable), submits
  single-archive requests into the warm ToaServer (backpressure rides
  ServeRejected(retryable)), and appends each result to the streaming
  per-pulsar ``.tim`` IN ADMISSION ORDER with the same durable
  completion sentinels the one-shot driver writes — the streamed file
  is byte-identical to running the whole corpus offline.
* ``alerts.py`` — CUSUM change detection on the timing-residual
  stream: glitches (achromatic phase/F0 step), DM steps (the nu^-2
  chromatic signature riding the wideband DM stream), and profile
  changes (persistent red-chi^2 excess over the quality gate), each
  emitting the ``alert`` telemetry event pptrace's alerts section
  reports.

The ``ppwatch`` CLI (cli/ppwatch.py) wires folder -> TOAs ->
timing.IncrementalGLS -> alerts end-to-end.
"""

from .alerts import AlertMonitor, CusumDetector  # noqa: F401
from .driver import IngestDriver  # noqa: F401
from .source import SocketSource, WatchFolderSource, announce  # noqa: F401

__all__ = ["WatchFolderSource", "SocketSource", "announce",
           "IngestDriver", "AlertMonitor", "CusumDetector"]
