"""Fourier-domain numerical kernels (JAX, batched, jittable).

These are the hot primitives of the framework — everything the fit
engines and pipelines evaluate per optimizer step.  All kernels are
shape-polymorphic over leading batch dimensions, free of Python-level
control flow on traced values, and dtype-polymorphic (f32 on TPU,
f64 in CPU tests).
"""

from .phasor import (
    cexp,
    DM_delay,
    dispersion_phases,
    phase_transform,
    phase_shifts,
    phasor,
    guess_fit_freq,
    doppler_correct_freqs,
)
from .rotation import (
    rotate_profile,
    rotate_portrait,
    rotate_full,
    add_DM_nu,
    fft_shift_bins,
)
from .scattering import (
    scattering_times,
    scattering_profile_FT,
    scattering_portrait_FT,
    scattering_kernel_time,
    add_scattering,
)
from .gaussian import (
    gaussian_profile,
    gaussian_profile_FT,
    instrumental_response_FT,
    instrumental_response_port_FT,
    dm_smearing_width,
)
from .noise import (
    get_noise,
    get_noise_PS,
    channel_SNRs_FT,
    get_SNR,
    get_scales,
)
from .filters import (
    wiener_filter,
    brickwall_filter,
    fit_brickwall,
    half_triangle_function,
    find_kc,
    get_noise_fit,
)
from .ism import (
    mean_C2N,
    dDM,
    GM_from_DMc,
    DMc_from_GM,
)
from .decode import (
    PACKED_BITS,
    RAW_CODES,
    affine_decode,
    decode_stokes_I,
    unpack_bitplanes,
)

__all__ = [
    "cexp",
    "DM_delay",
    "dispersion_phases",
    "phase_transform",
    "phase_shifts",
    "phasor",
    "guess_fit_freq",
    "doppler_correct_freqs",
    "rotate_profile",
    "rotate_portrait",
    "rotate_full",
    "add_DM_nu",
    "fft_shift_bins",
    "scattering_times",
    "scattering_profile_FT",
    "scattering_portrait_FT",
    "scattering_kernel_time",
    "add_scattering",
    "gaussian_profile",
    "gaussian_profile_FT",
    "instrumental_response_FT",
    "instrumental_response_port_FT",
    "dm_smearing_width",
    "get_noise",
    "get_noise_PS",
    "channel_SNRs_FT",
    "get_SNR",
    "get_scales",
    "wiener_filter",
    "brickwall_filter",
    "fit_brickwall",
    "half_triangle_function",
    "find_kc",
    "get_noise_fit",
    "mean_C2N",
    "dDM",
    "GM_from_DMc",
    "DMc_from_GM",
    "PACKED_BITS",
    "RAW_CODES",
    "affine_decode",
    "decode_stokes_I",
    "unpack_bitplanes",
]
