"""ppmon — live fleet dashboard over the ``metrics`` transport op
(ISSUE 20).

Polls one endpoint — a ``pproute --monitor`` port (fleet-wide view:
per-host health/queue/p99/throughput plus the router's own latency and
SLO burn) or a single ``ppserve --listen`` host (that host's registry
alone) — and renders a terminal dashboard every ``--interval`` ms.
``--once`` polls a single time; with ``--json`` the raw reply is
dumped as one JSON object for scripting (``ppmon --once --json host |
jq .fleet.p99_s``).

The endpoint never blocks the serving/routing hot path: the metrics
reply is a lock-held snapshot of counters and fixed log-bucket
histograms (quantiles are derived from bucket counts — no samples are
retained server-side and no device sync is ever taken).
"""

import argparse
import json
import sys
import time


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppmon", description=__doc__.splitlines()[0])
    p.add_argument("endpoint", metavar="HOST:PORT",
                   help="A 'pproute --monitor' port (fleet view) or a "
                        "'ppserve --listen' host (single-host view).")
    p.add_argument("--interval", type=float, default=None,
                   metavar="MS",
                   help="Poll interval in milliseconds. [default: "
                        "config.mon_interval_ms / PPT_MON_INTERVAL_MS "
                        "— 1000]")
    p.add_argument("--once", action="store_true", default=False,
                   help="Poll once, render, exit 0 (exit 1 if the "
                        "endpoint is unreachable).")
    p.add_argument("--json", dest="as_json", action="store_true",
                   default=False,
                   help="Emit the raw metrics reply as one JSON "
                        "object per poll instead of the dashboard.")
    p.add_argument("--timeout", type=float, default=5.0, metavar="S",
                   help="Socket timeout per poll. [default: 5]")
    return p


def _fmt_s(v):
    """Latency cell: seconds -> human unit, '-' for absent."""
    if v is None:
        return "-"
    if v < 1e-3:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.1f}ms"
    return f"{v:.2f}s"


def _fmt(v, spec="{:.1f}", none="-"):
    return none if v is None else spec.format(v)


def _render_slo(slo, p):
    if not slo:
        return
    p("  tenant            target   attain%   burn5m   burn1h  state")
    for tenant in sorted(slo):
        s = slo[tenant]
        att = (f"{100 * s['attainment']:.2f}"
               if s.get("attainment") is not None else "-")
        burn = s.get("burn", {})
        state = "ALERT" if s.get("alerting") else "ok"
        p(f"  {tenant:<16} {_fmt_s(s.get('target_s')):>7} {att:>9} "
          f"{_fmt(burn.get('300'), '{:.1f}x'):>8} "
          f"{_fmt(burn.get('3600'), '{:.1f}x'):>8}  {state}")


def render(reply, file=None):
    """Render one metrics reply (fleet-shaped or host-shaped) as the
    text dashboard."""
    out = file or sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    if "hosts" in reply and "fleet" in reply:
        f = reply["fleet"]
        r = reply["router"]
        p(f"== ppmon: fleet ({f['n_hosts']} host(s)) ==")
        p(f"  routed latency: p50 {_fmt_s(r['p50_s'])}  "
          f"p90 {_fmt_s(r['p90_s'])}  p99 {_fmt_s(r['p99_s'])}   "
          f"cache hit rate "
          f"{_fmt(r['cache_hit_rate'], '{:.1%}', 'n/a')}")
        p(f"  fleet serve latency: p50 {_fmt_s(f['p50_s'])}  "
          f"p99 {_fmt_s(f['p99_s'])}   queue depth "
          f"{_fmt(f['queue_depth'], '{:d}', '?')}  in-flight "
          f"{f['in_flight']}  TOAs/s "
          f"{_fmt(f['toas_per_s'], '{:.1f}')}  link stall "
          f"{_fmt(f['link_stall_frac'], '{:.1%}', 'n/a')}")
        p("  host                      state    queue  inflt  "
          "p50      p99      TOA/s")
        hosts = reply["hosts"]
        for label in sorted(hosts):
            h = hosts[label]
            row = (f"  {label:<25} {h['state']:<8} "
                   f"{_fmt(h['queue_len'], '{:d}', '?'):>6} "
                   f"{h['outstanding']:>6} "
                   f"{_fmt_s(h['p50_s']):>8} {_fmt_s(h['p99_s']):>8} "
                   f"{_fmt(h['toas_per_s'], '{:.1f}'):>8}")
            if h.get("error"):
                row += f"  [{h['error']}]"
            p(row)
        slo = r.get("slo") or {}
        # host-level SLO snapshots fold under the same table, keyed by
        # the tenant the host reported them for
        for label in sorted(hosts):
            for tenant, s in (hosts[label].get("slo") or {}).items():
                slo.setdefault(tenant, s)
        if slo:
            p("  -- slo --")
            _render_slo(slo, p)
        return
    # single-host (ToaServer.metrics) shape
    p("== ppmon: host ==")
    p(f"  queue {reply.get('queue_len')}  pending archives "
      f"{reply.get('pending_archives')}  live requests "
      f"{reply.get('n_live')}  TOAs/s "
      f"{_fmt(reply.get('toas_per_s'), '{:.1f}')}  link stall "
      f"{_fmt(reply.get('link_stall_frac'), '{:.1%}', 'n/a')}")
    m = reply.get("metrics")
    if m:
        from ..obs.metrics import quantile_from_export

        h = m.get("histograms", {}).get("request_latency_s")
        if h:
            p(f"  request latency: p50 "
              f"{_fmt_s(quantile_from_export(h, 0.50))}  p90 "
              f"{_fmt_s(quantile_from_export(h, 0.90))}  p99 "
              f"{_fmt_s(quantile_from_export(h, 0.99))}  "
              f"(n={h['count']})")
        c = m.get("counters", {})
        p(f"  requests {c.get('requests_total', 0)} "
          f"({c.get('requests_failed', 0)} failed)  TOAs "
          f"{c.get('toas_total', 0)}  cache hits "
          f"{reply.get('cache_hits', 0)}")
    elif not reply.get("metrics_enabled", True):
        p("  (metrics registry disabled on this host — start it with "
          "--metrics on / PPT_METRICS=on)")
    if reply.get("slo"):
        p("  -- slo --")
        _render_slo(reply["slo"], p)


def main(argv=None):
    args = build_parser().parse_args(argv)
    from .. import config

    interval_ms = args.interval
    if interval_ms is None:
        interval_ms = config.mon_interval_ms
    if not interval_ms > 0:
        raise SystemExit(f"ppmon: --interval: must be > 0, got "
                         f"{interval_ms}")
    try:
        config.parse_hostport(args.endpoint)
    except ValueError as e:
        raise SystemExit(f"ppmon: endpoint: {e}")

    from ..serve.transport import SocketTransport, TransportError

    try:
        transport = SocketTransport(args.endpoint,
                                    timeout=args.timeout)
    except TransportError as e:
        raise SystemExit(f"ppmon: {e}")
    try:
        while True:
            try:
                reply = transport.metrics()
            except TransportError as e:
                if args.once:
                    print(f"ppmon: {e}", file=sys.stderr)
                    return 1
                print(f"ppmon: poll failed: {e} (retrying)",
                      file=sys.stderr)
                time.sleep(interval_ms / 1000.0)
                continue
            if args.as_json:
                print(json.dumps(reply, sort_keys=True), flush=True)
            else:
                if not args.once and sys.stdout.isatty():
                    # home + clear-to-end keeps a live terminal stable
                    # without erasing scrollback
                    print("\x1b[H\x1b[J", end="")
                render(reply)
                print(f"-- {time.strftime('%H:%M:%S')}  "
                      f"poll every {interval_ms:.0f} ms  "
                      "(Ctrl-C to exit) --" if not args.once else "",
                      flush=True)
            if args.once:
                return 0
            time.sleep(interval_ms / 1000.0)
    except KeyboardInterrupt:
        return 0
    finally:
        transport.close()


if __name__ == "__main__":
    sys.exit(main())
