"""Tests for ops.filters (noise-floor cutoff, Wiener/brickwall) and
ops.ism (scattering-screen helpers, GM<->DMc conversions)."""

import numpy as np
import pytest

from pulseportraiture_tpu.ops.filters import (
    brickwall_filter,
    find_kc,
    fit_brickwall,
    get_noise_fit,
    half_triangle_function,
    wiener_filter,
)
from pulseportraiture_tpu.ops.ism import (
    DMc_from_GM,
    GM_from_DMc,
    dDM,
    mean_C2N,
)


def _noisy_gaussian_profile(nbin=512, width=0.02, amp=50.0, noise=1.0, seed=0):
    rng = np.random.default_rng(seed)
    x = np.arange(nbin) / nbin
    prof = amp * np.exp(-0.5 * ((x - 0.5) / width) ** 2)
    return prof + noise * rng.standard_normal(nbin), noise


class TestWienerBrickwall:
    def test_wiener_range_and_shape(self):
        prof, noise = _noisy_gaussian_profile()
        wf = wiener_filter(prof, noise)
        assert wf.shape == (len(prof) // 2 + 1,)
        assert np.all(wf >= 0.0) and np.all(wf <= 1.0)

    def test_wiener_passes_signal_kills_noise(self):
        prof, noise = _noisy_gaussian_profile()
        wf = wiener_filter(prof, noise)
        # strong low harmonics pass, noise-floor harmonics are crushed
        assert wf[1:5].min() > 0.85
        assert wf[-50:].mean() < 0.3

    def test_wiener_noise_floor_units(self):
        # a harmonic with power ~100x the noise floor must pass nearly
        # unattenuated (guards the nbin/2 floor-units bug)
        rng = np.random.default_rng(7)
        nbin = 512
        x = np.arange(nbin) / nbin
        prof = 2.0 * np.cos(2 * np.pi * 3 * x) + rng.standard_normal(nbin)
        # harmonic 3 power: (nbin*amp/2)^2*... in pows units = nbin*amp^2/4
        wf = wiener_filter(prof, 1.0)
        assert wf[3] > 0.95

    def test_brickwall(self):
        fk = brickwall_filter(10, 4)
        assert np.array_equal(fk, [1, 1, 1, 1, 0, 0, 0, 0, 0, 0])

    def test_fit_brickwall_matches_signal_extent(self):
        prof, noise = _noisy_gaussian_profile(width=0.05)
        kc = fit_brickwall(prof, noise)
        # Gaussian of width w has harmonics out to ~ 1/(2 pi w) ~ 3;
        # allow a generous band but require the cutoff to be small
        assert 1 <= kc < 40

    def test_fit_brickwall_is_argmin_of_explicit_cost(self):
        prof, noise = _noisy_gaussian_profile(seed=3)
        wf = wiener_filter(prof, noise)
        N = len(wf)
        explicit = np.array(
            [np.sum((wf - brickwall_filter(N, ii)) ** 2) for ii in range(N)]
        )
        assert fit_brickwall(prof, noise) == int(np.argmin(explicit))


class TestFindKc:
    def test_half_triangle_function(self):
        fn = half_triangle_function(4, 8.0, 1.0, 8)
        assert fn[0] == pytest.approx(9.0)
        assert np.allclose(fn[4:], 1.0)

    def test_find_kc_locates_noise_floor(self):
        # power spectrum: exponential decay to a flat floor at k=30
        rng = np.random.default_rng(1)
        N = 200
        k = np.arange(N)
        pows = 1e4 * np.exp(-k / 6.0) + 1.0 * (1 + 0.1 * rng.standard_normal(N))
        kc = find_kc(pows)
        # signal crosses the floor at k ~= 55; the 0.5%-decay criterion
        # lands above that (conservative = safe for noise estimation)
        assert 30 <= kc <= 150

    def test_find_kc_half_tri(self):
        rng = np.random.default_rng(2)
        N = 150
        pows = 10 ** half_triangle_function(25, 4.0, 0.0, N)
        pows *= 1 + 0.05 * rng.standard_normal(N)
        kc = find_kc(pows, fn="half_tri")
        assert 10 <= kc <= 60

    def test_find_kc_zero_power_is_finite(self):
        # exact-zero DC power (baseline-removed profile) must not NaN
        # the grid and degenerate to kc = N-1
        rng = np.random.default_rng(11)
        N = 513
        pows = np.abs(rng.standard_normal(N)) + 0.5
        pows[:10] = 1e4 * np.exp(-np.arange(10) / 1.5)
        pows[0] = 0.0
        kc = find_kc(pows)
        assert kc < N - 1

    def test_get_noise_fit_zero_dc(self):
        rng = np.random.default_rng(12)
        prof = 2.0 * rng.standard_normal(1024)
        prof -= prof.mean()  # exact-zero DC
        est = get_noise_fit(prof)
        assert est == pytest.approx(2.0, rel=0.35)

    def test_get_noise_fit_recovers_sigma(self):
        prof, noise = _noisy_gaussian_profile(nbin=1024, noise=2.0, seed=5)
        est = get_noise_fit(prof)
        assert est == pytest.approx(2.0, rel=0.35)

    def test_get_noise_fit_chans(self):
        profs = np.stack([_noisy_gaussian_profile(seed=s)[0] for s in range(3)])
        est = get_noise_fit(profs, chans=True)
        assert est.shape == (3,)
        assert np.all(est > 0)

    def test_get_noise_dispatch_fit_is_per_channel_for_2d(self):
        from pulseportraiture_tpu.ops import get_noise

        profs = np.stack([_noisy_gaussian_profile(seed=s)[0] for s in range(3)])
        est = np.asarray(get_noise(profs, method="fit"))
        assert est.shape == (3,)

    def test_find_kc_all_zero_channel(self):
        # fully zapped channel: no NaN grid, no warnings, returns 0
        with np.errstate(divide="raise", invalid="raise"):
            assert find_kc(np.zeros(128)) == 0
        assert get_noise_fit(np.zeros(256)) == 0.0


class TestISM:
    def test_mean_c2n_scalings(self):
        # positive, and decreasing with scintillation bandwidth
        a = mean_C2N(1400.0, 1.0, 1.0)
        b = mean_C2N(1400.0, 1.0, 10.0)
        assert a > b > 0

    def test_ddm_positive_and_screen_scaling(self):
        d1 = dDM(1.0, 0.5, 1400.0, 1.0)
        d2 = dDM(1.0, 0.25, 1400.0, 1.0)
        assert d1 > d2 > 0

    def test_gm_dmc_roundtrip(self):
        # DMc_from_GM is the exact inverse of GM_from_DMc (the
        # reference's version is not; defect documented in ops/ism.py)
        DMc = 1e-3
        GM = GM_from_DMc(DMc, 1.0, 10.0)
        assert GM > 0
        assert DMc_from_GM(GM, 1.0, 10.0) == pytest.approx(DMc, rel=1e-12)
