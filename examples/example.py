"""End-to-end synthetic walkthrough (the reference's examples/example.py
flow, reference example.py:22-158): generate fake epochs with known
injected dispersion-measure offsets, align and average them, build both
template-model types, measure wideband TOAs + DMs, and verify the
injected values are recovered.

Run from the repo root:  python examples/example.py
Everything happens in a temp directory; no files are left behind unless
--keep is given.  Runs on CPU in a couple of minutes.
"""

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402


def main(keep=False, nepoch=5):
    from pulseportraiture_tpu.io.tim import write_TOAs
    from pulseportraiture_tpu.pipeline import GetTOAs, align_archives
    from pulseportraiture_tpu.pipeline.gauss import GaussPortrait
    from pulseportraiture_tpu.pipeline.spline import SplinePortrait
    from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    root = tempfile.mkdtemp(prefix="ppt_example_")
    print(f"working in {root}")
    par = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
           "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}

    # --- 1. generate fake epochs with known injected dDMs ---------------
    truth = default_test_model(1500.0)
    rng = np.random.default_rng(42)
    injected_dDMs = rng.normal(0.0, 3e-4, nepoch)
    files = []
    for i, dDM in enumerate(injected_dDMs):
        path = os.path.join(root, f"epoch-{i}.fits")
        # spin_coherent ties each epoch's absolute pulse phase to the
        # ephemeris (polyco-folding behavior), so step 6's timing fit
        # can phase-connect the campaign; the achromatic offset is
        # common (it becomes the fitted OFFSET)
        make_fake_pulsar(truth, par, outfile=path, nsub=4, nchan=64,
                         nbin=512, nu0=1500.0, bw=800.0, tsub=120.0,
                         phase=0.1, dDM=float(dDM),
                         start_MJD=MJD(55100 + 20 * i, 0.13),
                         noise_stds=0.06, dedispersed=False, quiet=True,
                         rng=1000 + i, spin_coherent=True)
        files.append(path)
    meta = os.path.join(root, "epochs.meta")
    with open(meta, "w") as f:
        f.write("\n".join(files) + "\n")
    print(f"generated {nepoch} epochs, injected dDMs:", injected_dDMs)

    # --- 2. align and average into a high-S/N portrait ------------------
    avg = os.path.join(root, "average.fits")
    align_archives(meta, files[0], outfile=avg, niter=2, quiet=True)
    print("aligned average written:", avg)

    # --- 3a. evolving-Gaussian model ------------------------------------
    dpg = GaussPortrait(avg, quiet=True)
    dpg.make_gaussian_model(auto_gauss=0.05, niter=3, quiet=True)
    gmodel = os.path.join(root, "example.gmodel")
    dpg.write_model(gmodel, quiet=True)
    print("gaussian model written:", gmodel)

    # --- 3b. PCA + B-spline model ---------------------------------------
    dps = SplinePortrait(avg, quiet=True)
    dps.make_spline_model(max_ncomp=4, snr_cutoff=50.0, quiet=True)
    spl = os.path.join(root, "example.spl")
    dps.write_model(spl, quiet=True)
    print("spline model written:", spl)

    # --- 4. measure wideband TOAs + DMs against the spline model --------
    gt = GetTOAs(meta, spl, quiet=True)
    gt.get_TOAs(quiet=True)
    tim = os.path.join(root, "example.tim")
    write_TOAs(gt.TOA_list, outfile=tim)
    print(f"wrote {len(gt.TOA_list)} TOAs to {tim}")

    # --- 5. verify: fitted DeltaDM means vs injections ------------------
    # (reference example.py:149-158)
    print("\nepoch   injected dDM   fitted dDM      err        pull")
    ok = True
    fitted = np.asarray(gt.DeltaDM_means) - np.mean(gt.DeltaDM_means)
    inj = injected_dDMs - np.mean(injected_dDMs)
    for i in range(nepoch):
        err = gt.DeltaDM_errs[i]
        pull = (fitted[i] - inj[i]) / err
        # 4-sigma pull with a 2e-5 absolute floor: the data-derived
        # spline template induces small correlated biases the formal
        # per-epoch error does not cover
        good = abs(pull) < 4 or abs(fitted[i] - inj[i]) < 2e-5
        flag = "" if good else "  <-- BAD"
        ok &= good
        print(f"{i:3d}   {inj[i]:+12.3e} {fitted[i]:+12.3e} "
              f"{err:10.2e} {pull:+8.2f}{flag}")
    print("\nRECOVERY", "OK" if ok else "FAILED",
          "(relative dDMs within 4 sigma)")

    # --- 6. close the timing loop: wideband GLS on the .tim -------------
    # (the reference notebook's tempo GLS with DMDATA 1, cells 43-56,
    # without the tempo binary: arrival times + DM measurements fit
    # jointly for offset, dF0, and per-epoch DMX)
    from pulseportraiture_tpu.timing import read_tim, wideband_gls_fit

    toas = read_tim(tim)
    res = wideband_gls_fit(toas, par, fit_f0=True)
    print(f"\nwideband GLS: {len(toas)} TOAs, {len(res.dmx)} epochs, "
          f"red chi2 = {res.red_chi2:.2f}, "
          f"post-fit wrms = {res.wrms_us * 1e3:.1f} ns "
          f"(median TOA err {np.median(res.toa_errs_us) * 1e3:.1f} ns)")
    white = 0.3 < res.red_chi2 < 3.0
    # mean-removed like step 5: the template carries a common DM offset
    dmx_ok = np.all(np.abs((res.dmx - res.dmx.mean())
                           - (injected_dDMs - injected_dDMs.mean()))
                    < np.maximum(4.0 * res.dmx_errs, 3e-5))
    print("TIMING", "OK" if (white and dmx_ok) else "FAILED",
          "(white residuals; DMX matches injections)")
    ok &= white and dmx_ok

    if keep:
        print(f"\nkept outputs in {root}")
    else:
        shutil.rmtree(root)
    return 0 if ok else 1


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the temp directory")
    ap.add_argument("--nepoch", type=int, default=5)
    args = ap.parse_args()
    sys.exit(main(keep=args.keep, nepoch=args.nepoch))
