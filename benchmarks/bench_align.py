"""BASELINE.md config 4: one ppalign-style iteration over 256 epochs at
512 chan x 2048 bin — batched (phi, DM) fits of every epoch against the
current template, then the weighted rotate-and-stack template update.

ISSUE 2: the template update now has a DEVICE-RESIDENT lane (jitted
split-real harmonic accumulate with donated on-chip buffers,
parallel/batch.py, selected by config.align_device) next to the chunked
c128 host lane that used to idle the chip.  This bench measures BOTH
lanes of the production iteration (same fit engine, same inputs),
checks they are digit-exact on the fixed seed, and prints the
stage-attribution breakdown of the device lane (benchmarks/attrib.py:
fit / rotate / accumulate / irfft / host_sync, gated >= 0.9) so the
dominant stage is named — the TPU re-measure next chip session is
pre-scoped by the breakdown, the CPU A/B gates the routing today.

This is the in-memory math of pipeline/align.align_archives's inner
loop (the file-level driver adds PSRFITS IO around exactly this — run
with --cli for that path); the multi-chip form shards the epoch axis
(parallel/batch.py).

Prints ONE JSON line like bench.py.  Shapes via PPT_NE / PPT_NCHAN /
PPT_NBIN; --cli shapes via PPT_NARCH / PPT_NSUB / PPT_NCHAN / PPT_NBIN.
"""

import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# digit-exactness gates, device vs host accumulate on the same fixed
# seed: f64 round-off discipline (round 5's align test) when the device
# accumulate runs f64 (CPU A/B), f32-grade when it runs f32 (TPU)
EXACT_GATE_F64 = 1e-10
EXACT_GATE_F32 = 2e-5


def main_cli():
    """--cli: the file-level align_archives path (PSRFITS IO + batched
    phase-guess + harmonic-domain accumulate; round 5 batched its two
    per-subint host loops — A/B numbers in BENCHMARKS.md).  The
    accumulate lane follows config.align_device (PPT_ALIGN_DEVICE
    flips it); archives cached like bench_campaign."""
    import jax

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu.parallel.batch import use_align_device
    from pulseportraiture_tpu.pipeline import align_archives
    from pulseportraiture_tpu.synth import default_test_model, \
        make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    NARCH = int(os.environ.get("PPT_NARCH", 4))
    NSUB = int(os.environ.get("PPT_NSUB", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 64))
    NBIN = int(os.environ.get("PPT_NBIN", 512))
    NITER = int(os.environ.get("PPT_NITER", 2))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_ALIGN_CACHE", "/tmp/ppt_align_cli")
    root = os.path.join(cache, f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}")
    os.makedirs(root, exist_ok=True)
    model = default_test_model(1500.0)
    files = []
    for i in range(NARCH):
        p = os.path.join(root, f"ep{i}.fits")
        if not os.path.exists(p):
            make_fake_pulsar(model, PAR, outfile=p, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0,
                             bw=600.0, tsub=60.0, phase=0.03 * i,
                             dDM=1e-4 * i, start_MJD=MJD(55100 + i, 0.2),
                             noise_stds=0.06, dedispersed=False,
                             quiet=True, rng=i)
        files.append(p)
    out = os.path.join(root, "out.fits")
    times = []
    for _ in range(3):  # first rep pays compile; report min (warm)
        t0 = time.perf_counter()
        align_archives(files, files[0], niter=NITER, quiet=True,
                       outfile=out)
        times.append(time.perf_counter() - t0)
    print(json.dumps({
        "metric": f"align_archives CLI path (IO + {NITER} iterations), "
                  f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}",
        "value": round(NARCH * NSUB * NITER / min(times), 2),
        "unit": "subint-iterations/sec",
        "warm_s": round(min(times), 2),
        "cold_s": round(times[0], 2),
        "align_device": bool(use_align_device()),
        "device": str(jax.devices()[0]),
    }))


def run_bench(attrib_only=False, with_attrib=True):
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    # importable by attrib.py / tests: restore the process-global
    # config this bench overrides
    saved = {k: getattr(config, k) for k in
             ("dft_precision", "cross_spectrum_dtype")}
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()  # PPT_* A/B switches win over script defaults
    try:
        return _run_bench_inner(attrib_only, with_attrib)
    finally:
        for k, v in saved.items():
            setattr(config, k, v)


def _run_bench_inner(attrib_only, with_attrib):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.attrib import align_stage_profile
    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.fit import fit_portrait_batch_fast
    from pulseportraiture_tpu.fit.portrait import resolve_harmonic_window
    from pulseportraiture_tpu.ops.fourier import irfft_c
    from pulseportraiture_tpu.parallel.batch import (
        align_accumulate_archive, align_accumulator_init, align_finalize)
    from pulseportraiture_tpu.pipeline.align import \
        _host_accumulate_archive
    from pulseportraiture_tpu.utils.device import host_compute

    NE = int(os.environ.get("PPT_NE", 256))
    NCHAN = int(os.environ.get("PPT_NCHAN", 512))
    NBIN = int(os.environ.get("PPT_NBIN", 2048))
    DT = jnp.float32
    P, NU_FIT = 0.003, 1500.0
    # the device accumulate dtype mirrors align_archives' rule: f32 on
    # TPU (no f64 there), f64 elsewhere (the host lane's digit peer)
    on_tpu = jax.default_backend() == "tpu"
    ACC_DT = jnp.float32 if on_tpu else jnp.float64
    model, freqs = bench_model(NCHAN, NBIN)

    @jax.jit
    def synth(key):
        k1, k2 = jax.random.split(key)
        scales = 0.5 + jax.random.uniform(k1, (NE, 1, 1), DT)
        return model[None] * scales + 0.05 * jax.random.normal(
            k2, (NE, NCHAN, NBIN), DT)

    ports = synth(jax.random.PRNGKey(0))
    noise = jnp.full((NE, NCHAN), 0.05, DT)
    masks = jnp.ones((NE, NCHAN), DT)
    P_s = jnp.full((NE,), P, DT)
    cube = ports[:, None]  # (NE, npol=1, NCHAN, NBIN)

    # the production align_archives derives the harmonic window from
    # its host template each iteration; mirror that here
    hwin = resolve_harmonic_window(None, np.asarray(model), NBIN)

    def run_fit():
        return fit_portrait_batch_fast(
            ports, model, noise, freqs, P, NU_FIT, max_iter=25,
            harmonic_window=hwin if hwin is not None else False)

    def device_iteration():
        """The production device lane: batched fit -> on-chip
        split-real rotate-accumulate (donated buffers) -> ONE irfft ->
        the per-iteration host pull."""
        r = run_fit()
        acc = align_accumulator_init(1, NCHAN, NBIN, ACC_DT)
        acc = align_accumulate_archive(acc, cube, r.phi, r.DM, r.nu_DM,
                                       P_s, freqs, noise, masks,
                                       r.scales)
        return np.asarray(align_finalize(acc, NBIN))

    # host-lane numpy views (the host accumulate is eager)
    cube_np = np.asarray(cube, float)
    freqs_np = np.asarray(freqs, float)
    noise_np = np.asarray(noise, float)
    masks_np = np.asarray(masks, float)
    Ps_np = np.asarray(P_s, float)

    def host_iteration():
        """The pre-ISSUE-2 host lane: same fit, then the chunked c128
        harmonic accumulate under host_compute() (the production
        oracle, pipeline/align._host_accumulate_archive)."""
        r = run_fit()
        aligned_FT = np.zeros((1, NCHAN, NBIN // 2 + 1), complex)
        total_weights = np.zeros((NCHAN, NBIN))
        aligned_FT, total_weights = _host_accumulate_archive(
            aligned_FT, total_weights, cube_np, np.asarray(r.phi),
            np.asarray(r.DM), np.asarray(r.nu_DM), Ps_np, freqs_np,
            noise_np, masks_np, np.asarray(r.scales) * masks_np)
        with host_compute():
            aligned = np.array(irfft_c(jnp.asarray(aligned_FT),
                                       n=NBIN))
        return aligned / np.maximum(total_weights, 1e-30)[None]

    # digit-exactness on the fixed seed BEFORE timing (also the warmup)
    dev_out = device_iteration()
    host_out = host_iteration()
    scale = float(np.abs(host_out).max())
    exact_rel = float(np.abs(dev_out - host_out).max() / scale)
    exact_gate = (EXACT_GATE_F32 if ACC_DT == jnp.float32
                  else EXACT_GATE_F64)

    att = None
    if with_attrib or attrib_only:
        att = align_stage_profile(cube, noise, masks, freqs, P_s,
                                  ACC_DT, run_fit, device_iteration)
    if attrib_only:
        out = {"metric": "align-lane stage attribution",
               "batch": NE, "device": str(jax.devices()[0])}
        out.update(att.breakdown_ms())
        return out

    dev_slope, dev_single = devtime(device_iteration)
    host_slope, host_single = devtime(host_iteration)

    out = {
        "metric": f"align iteration (fit + rotate-and-stack), "
                  f"{NE} epochs x {NCHAN}ch x {NBIN}bin",
        "value": round(NE / dev_slope, 2),
        "unit": "epochs/sec",
        "iteration_latency_ms": round(dev_single * 1e3, 1),
        "batch": NE,
        "device": str(jax.devices()[0]),
        "align_device_dtype": str(jnp.dtype(ACC_DT)),
        "harmonic_window": hwin,
        # the measured A/B: same fit engine both lanes, the accumulate
        # lane is the variable (acceptance: device no slower on CPU)
        "host_epochs_per_sec": round(NE / host_slope, 2),
        "host_iteration_latency_ms": round(host_single * 1e3, 1),
        "ab_speedup_vs_host": round(host_slope / dev_slope, 2),
        "ab_device_not_slower": bool(dev_slope <= host_slope),
        "digit_exact_rel": float(f"{exact_rel:.3g}"),
        "digit_exact_gate": exact_gate,
        "digit_exact_ok": bool(exact_rel < exact_gate),
    }
    if att is not None:
        out.update(att.breakdown_ms())
        # >= 90% of the device lane's slope must be explained by
        # independently measured stages (one-sided; see BENCHMARKS.md)
        out["attrib_ok"] = bool(att.check(0.9))
        out["dominant_stage"] = max(att.stages,
                                    key=lambda s: s.cost_s).name
    return out


def main():
    if "--cli" in sys.argv:
        main_cli()
    else:
        print(json.dumps(run_bench()))


if __name__ == "__main__":
    main()
