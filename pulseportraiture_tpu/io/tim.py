"""TOA records and .tim output.

Parity targets: reference pptoas.py:42-84 (TOA class),
pplib.py:3502-3649 (filter_TOAs / write_princeton_TOA / write_TOAs).
The reference's filter_TOAs defects (`criterio` typo, `.appens`,
returning the flag instead of the culled list; SURVEY §2.8) are fixed
here, not replicated.
"""

import operator

import numpy as np

_OPS = {">": operator.gt, ">=": operator.ge, "<": operator.lt,
        "<=": operator.le, "==": operator.eq, "!=": operator.ne}


class TOA:
    """One wideband TOA: epoch + reference frequency + error + DM and
    arbitrary flags (reference pptoas.py:42-84)."""

    def __init__(self, archive, frequency, MJD, TOA_error, telescope,
                 telescope_code, DM=None, DM_error=None, flags=None):
        self.archive = archive
        self.frequency = frequency
        self.MJD = MJD  # utils.mjd.MJD
        self.TOA_error = TOA_error  # [us]
        self.telescope = telescope
        self.telescope_code = telescope_code
        self.DM = DM
        self.DM_error = DM_error
        self.flags = dict(flags) if flags else {}

    def write_TOA(self, inf_is_zero=True, outfile=None):
        write_TOAs(self, inf_is_zero=inf_is_zero, outfile=outfile,
                   append=True)

    def __repr__(self):
        return (f"TOA({self.archive}, {self.frequency} MHz, "
                f"{self.MJD}, +/-{self.TOA_error:.3f} us)")


def filter_TOAs(TOAs, flag, cutoff, criterion=">=", pass_unflagged=False,
                return_culled=False):
    """Filter a TOA list on a flag value (reference pplib.py:3502-3548
    with its three defects fixed)."""
    op = _OPS.get(criterion)
    if op is None:
        print(f"Undefined criterion {criterion}; defaulting to '=='")
        op = operator.eq
    kept, culled = [], []
    for toa in TOAs:
        if flag in toa.flags:
            (kept if op(toa.flags[flag], cutoff) else culled).append(toa)
        else:
            (kept if pass_unflagged else culled).append(toa)
    return (kept, culled) if return_culled else kept


def _mjd_fields(day, frac, ndecimals):
    """(day, '.ffff...') with rounding carry handled — delegates to
    MJD.tim_string so 0.99999..9 rounds to the next day, not to a
    silent 1-day error."""
    from ..utils.mjd import MJD

    s = MJD(int(day), float(frac)).tim_string(ndecimals)
    whole, _, fracpart = s.partition(".")
    return int(whole), "." + fracpart


def princeton_TOA_string(TOA_MJDi, TOA_MJDf, TOA_err, nu_ref, dDM,
                         obs="@", name=" " * 13):
    """Princeton-format TOA line (reference pplib.py:3551-3585)."""
    if nu_ref == np.inf:
        nu_ref = 0.0
    day, frac = _mjd_fields(TOA_MJDi, TOA_MJDf, 13)
    toa = f"{day:5d}" + frac
    return (f"{obs} {name:>13s} {nu_ref:8.3f} {toa} {TOA_err:8.3f}"
            f"              {dDM:9.5f}")


def write_princeton_TOAs(TOAs, outfile=None, dDMs=None):
    """Write Princeton-style TOAs for a list of TOA objects — the
    reference CLI advertises this but the method was never written
    (pptoas.py:1658 latent AttributeError; SURVEY §2.8)."""
    lines = []
    for i, toa in enumerate(TOAs):
        dDM = dDMs[i] if dDMs is not None else (toa.flags.get("pp_ddm", 0.0))
        lines.append(princeton_TOA_string(
            toa.MJD.day, toa.MJD.frac, toa.TOA_error, toa.frequency, dDM,
            obs=toa.telescope_code))
    _emit(lines, outfile, append=False)


def toa_string(toa, inf_is_zero=True):
    """One loosely-IPTA .tim line (reference pplib.py:3588-3649):
    `archive freq MJD err code [-pp_dm ...] [-pp_dme ...] [-flag val]...`
    with the TEMPO2 convention that 0.0 MHz means infinite frequency
    and per-flag-type value formatting."""
    freq = toa.frequency
    if freq == np.inf and inf_is_zero:
        freq = 0.0
    mjd = toa.MJD.tim_string(15)
    s = f"{toa.archive} {freq:.8f} {mjd}   {toa.TOA_error:.3f}  " \
        f"{toa.telescope_code}"
    if toa.DM is not None:
        s += f" -pp_dm {toa.DM:.7f}"
    if toa.DM_error is not None:
        s += f" -pp_dme {toa.DM_error:.7f}"
    for flag, value in toa.flags.items():
        if value is None:
            continue
        if hasattr(value, "lower"):
            s += f" -{flag} {value}"
        elif "int" in str(type(value)):
            s += f" -{flag} {value:d}"
        elif "_cov" in flag:
            s += f" -{flag} {value:.1e}"
        elif "phs" in flag:
            s += f" -{flag} {value:.8f}"
        elif "flux" in flag:
            s += f" -{flag} {value:.5f}"
        else:
            s += f" -{flag} {value:.3f}"
    return s


def write_TOAs(TOAs, inf_is_zero=True, SNR_cutoff=0.0, outfile=None,
               append=True):
    """Write .tim lines to a file or stdout (reference
    pplib.py:3588-3649; appends by default, the reference's de-facto
    checkpointing behavior, SURVEY §5)."""
    toas = TOAs if hasattr(TOAs, "__len__") else [TOAs]
    # only apply the S/N filter when a cutoff is actually requested —
    # with the reference's unconditional pass_unflagged=False, a TOA
    # list without 'snr' flags would be silently dropped
    if SNR_cutoff > 0.0:
        toas = filter_TOAs(toas, "snr", SNR_cutoff, ">=",
                           pass_unflagged=False)
    _emit([toa_string(t, inf_is_zero) for t in toas], outfile, append)


def _emit(lines, outfile, append):
    if outfile is None:
        for line in lines:
            print(line)
    else:
        with open(outfile, "a" if append else "w") as f:
            f.write("".join(line + "\n" for line in lines))
