from .mjd import MJD
from .bunch import DataBunch
from .device import host_compute

__all__ = ["MJD", "DataBunch", "host_compute"]
