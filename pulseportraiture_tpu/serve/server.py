"""Continuous-batching TOA service: a long-lived serving loop over the
stream executor (ISSUE 8 tentpole; ROADMAP item 2).

Every driver before this PR was one-shot: ``stream_ipta_campaign``
sharded a fixed job list and exited, re-paying executor spin-up, jit
traces, and cold h2d warmup per invocation.  The wideband-TOA pipeline
is embarrassingly batchable across pulsars AND requests, so this
module applies the LLM-serving shape (continuous batching a la
Orca/vLLM) to pulsar timing:

- ONE warm :class:`~..pipeline.stream._StreamExecutor` per host lives
  for the server's lifetime (``service=True``): jit caches, device
  transfer pipelines, the persistent compile cache, and the AOT warmup
  all survive across requests, so steady-state requests never pay a
  cold start;
- concurrent clients :meth:`~ToaServer.submit` archives through a
  bounded :class:`~.queue.AdmissionQueue` (backpressure is LOUD —
  ``ServeRejected`` — never an unbounded host-memory queue);
- the serving loop builds ONE lane per (template, options) pair
  (``make_wideband_lane``; the TemplateModel load amortizes across
  requests) and admits every request's subints into SHARED shape
  buckets: compatible subints from different requests coalesce into
  the same fused dispatch (``batch_coalesce`` telemetry proves it);
- a bucket launches when FULL or when its oldest subint exceeds the
  ``serve_max_wait_ms`` deadline (partial buckets pad to the compiled
  shape class) — heavy traffic fills buckets, light traffic still
  meets latency targets;
- completed TOAs demultiplex back per request, in the request's
  archive order, with the one-shot driver's checkpoint format
  (completion sentinels) as the durability story — per-request
  ``.tim`` output is byte-identical to ``stream_wideband_TOAs``;
- :meth:`~ToaServer.stop` drains gracefully: the queue closes (new
  submissions reject), pending buckets flush, in-flight dispatches
  drain, every outstanding request resolves.

Scope: the wideband campaign configuration (the same option set
``stream_wideband_TOAs`` streams).  Multi-host serving stacks this
per-host loop under a router, exactly as the campaign drivers stack
under ``parallel/multihost.py``.
"""

import os
import threading
import time

import numpy as np

from ..io.tim import write_TOAs
from ..pipeline.stream import (_DONE_PREFIX, _StreamExecutor,
                               _collect_wideband, make_wideband_lane)
from ..telemetry import log, resolve_tracer
from ..utils.bunch import DataBunch
from .queue import AdmissionQueue, ServeRejected, ServeRequest

__all__ = ["ToaServer"]

# Most-recently-used (template, options) lanes a long-lived server
# keeps cached.  Each entry pins a loaded TemplateModel plus its
# instrumental-response cache, so an unbounded cache would grow host
# memory for every distinct template ever served; eviction is safe —
# buckets and in-flight records hold their own lane references, and a
# re-request simply rebuilds the lane (whose key_prefix, and therefore
# bucket keys, are unchanged).
LANE_CACHE_MAX = 32


def _freeze(v):
    """Hashable canonical form of an option value (lists/dicts arrive
    from JSON request specs) for the lane-cache key."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, np.ndarray):
        return (v.shape, v.tobytes())
    return v


class ToaServer:
    """A long-lived wideband-TOA serving loop over one warm executor.

    Thread model: ``submit`` is safe from any thread (it only touches
    the admission queue and the tracer); everything executor-facing —
    archive loads, bucket fills, dispatch launches, drains, request
    completion — happens on the single server thread, so the executor
    needs no locking.  Client threads block in
    ``ServeRequest.result()``.

    nsub_batch: the fused-bucket row count (every dispatch pads to a
    multiple of it, so it is also the compiled batch shape class).
    max_wait_ms / queue_depth default to ``config.serve_max_wait_ms`` /
    ``config.serve_queue_depth``.  stream_devices / max_inflight /
    pipeline_depth / telemetry follow the streaming drivers.
    warmup_manifest: a prior run's telemetry trace — every dispatch
    shape it records is AOT-compiled at :meth:`start`
    (``utils/device.warmup_from_manifest``) and marked warm, so the
    serve trace shows zero cold dispatches for manifest shapes;
    warmup_model: template whose portrait shapes the warmup programs
    (defaults to a synthetic smooth profile); warmup_options:
    fit-option overrides forwarded to the warmup pass.
    """

    def __init__(self, nsub_batch=64, max_wait_ms=None, queue_depth=None,
                 stream_devices=None, max_inflight=None,
                 pipeline_depth=None, telemetry=None,
                 warmup_manifest=None, warmup_model=None,
                 warmup_options=None, quiet=True):
        from .. import config

        if max_wait_ms is None:
            max_wait_ms = config.serve_max_wait_ms
        if queue_depth is None:
            queue_depth = config.serve_queue_depth
        self.nsub_batch = int(nsub_batch)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.quiet = quiet
        self.tracer, self._own_tracer = resolve_tracer(telemetry,
                                                       run="ppserve")
        self.queue = AdmissionQueue(queue_depth)
        self._ex = _StreamExecutor(
            None, [], None, self.nsub_batch, max_inflight=max_inflight,
            prefetch=False, tim_out=None, quiet=quiet,
            stream_devices=stream_devices, tracer=self.tracer,
            pipeline_depth=pipeline_depth, service=True)
        self._ex.on_archive_done = self._archive_done
        self._ex.on_launch = self._launched
        self._lanes = {}      # (modelfile, frozen options) -> lane pair
        self._by_iarch = {}   # executor iarch -> (request, position)
        self._iarch = 0
        # id(request) -> request (admitted, unresolved).  Keyed by
        # OBJECT identity, not name: names are client-chosen labels
        # and two in-flight requests may collide on one — an abort
        # must still fail BOTH loudly, never strand a blocked client
        self._live = {}
        self._thread = None
        self._started = False
        self._stopping = threading.Event()
        self._drain = True
        self._fatal = None
        self._warmup = (warmup_manifest, warmup_model,
                        dict(warmup_options or {}))

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               **options):
        """Enqueue one request (thread-safe).  Raises
        :class:`ServeRejected` when the admission queue is full
        (backpressure) or the server is stopping; returns a
        :class:`ServeRequest` whose ``result()`` blocks for the
        per-request DataBunch."""
        req = ServeRequest(datafiles, modelfile, options=options,
                           tim_out=tim_out, name=name)
        if self._stopping.is_set():
            raise ServeRejected(
                f"server is stopping; request {req.name!r} rejected")
        if self._fatal is not None:
            raise ServeRejected(
                f"server died: {self._fatal!r}; request {req.name!r} "
                "rejected")
        self.queue.submit(req)
        if self.tracer.enabled:
            self.tracer.emit("request_submit", req=req.name,
                             n_archives=len(req.datafiles))
        return req

    def stats(self):
        """Load snapshot (thread-safe): pending_archives is the
        admission queue's in-ARCHIVES depth (submitted, not yet
        prepared — the backpressure bound), queue_len the queued
        request count, n_live the admitted-but-unresolved requests.
        This is the signal the cross-host router's least-loaded
        placement and the transport ``stat`` op read."""
        return {"pending_archives": self.queue.pending_archives,
                "queue_len": len(self.queue),
                "n_live": len(self._live)}

    def start(self):
        """Run the optional AOT warmup, then start the serving thread.
        Returns self (usable as ``with ToaServer(...).start() as s:``
        via the context manager)."""
        if self._started:
            raise RuntimeError("ToaServer.start() called twice")
        self._started = True
        manifest, wmodel, wopts = self._warmup
        if manifest:
            from ..utils.device import warmup_from_manifest

            warmed = warmup_from_manifest(
                manifest, modelfile=wmodel, devices=self._ex.devices,
                nsub_batch=self.nsub_batch, tracer=self.tracer,
                quiet=self.quiet, **wopts)
            for shape, idev in warmed:
                # pre-seed the executor's warm set: the first REAL
                # dispatch of a warmed shape is not a cold start, and
                # the trace must say so (ROADMAP item 5's gate).
                # TRUSTED, not verified: warmup_options/warmup_model
                # must match the serving workload (they ride the
                # program cache keys) — a mismatched warmup still
                # marks the shape warm while the first real dispatch
                # pays its own compile.  Cross-check with pptrace's
                # dispatch->dispatched worker gaps if in doubt.
                self._ex._warm.add((shape, idev))
        if self.tracer.enabled:
            self.tracer.emit(
                "serve_start", n_devices=len(self._ex.devices),
                nsub_batch=self.nsub_batch,
                max_wait_ms=round(self.max_wait_s * 1e3, 3),
                queue_depth=self.queue.max_pending)
        log(f"ppserve: serving on {len(self._ex.devices)} device(s), "
            f"bucket {self.nsub_batch} subints / "
            f"{self.max_wait_s * 1e3:.0f} ms deadline, queue depth "
            f"{self.queue.max_pending} archive(s)", quiet=self.quiet,
            tracer=None)
        self._thread = threading.Thread(target=self._loop,
                                        name="ppt-serve", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Stop serving.  drain=True (graceful): close the queue (new
        submissions reject), serve everything already accepted —
        pending buckets flush, in-flight dispatches drain, every
        outstanding request resolves — then shut the executor down.
        drain=False: abort; outstanding requests fail loudly.  Raises
        the serving loop's error if it died."""
        self._drain = bool(drain)
        self._stopping.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout)
        else:
            # never started: nothing admitted; fail anything queued
            self._fail_requests(self.queue.drain(),
                                ServeRejected("server never started"))
        if self.tracer.enabled:
            self.tracer.emit("serve_stop", drained=bool(drain))
        if self._own_tracer:
            self.tracer.close()
        if self._fatal is not None:
            raise self._fatal

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        # on an exception path, don't block on a graceful drain
        self.stop(drain=exc_type is None)
        return False

    # ------------------------------------------------------------------
    # serving loop (single thread owns the executor)
    # ------------------------------------------------------------------

    def _loop(self):
        ex = self._ex
        try:
            while True:
                req = self.queue.get(self._tick())
                if req is not None:
                    self._admit_request(req)
                ex.flush_stale(self.max_wait_s)
                ex._drain_ready()
                if self._stopping.is_set() and (
                        not self._drain or len(self.queue) == 0):
                    break
            if self._drain:
                ex.flush_all()
                ex.drain_all()
                # archives that never completed through the drain
                # (lanes admitting fewer entries than ok subints)
                for ia in sorted(self._by_iarch):
                    ex.assemble_leftover(ia)
                ex._shutdown(wait=True)
            else:
                ex._shutdown(wait=False)
                self._fail_requests(
                    list(self._live.values()) + self.queue.drain(),
                    ServeRejected("server stopped without drain"))
        except BaseException as e:  # the loop must never die silently
            self._fatal = e
            ex._shutdown(wait=False)
            self._fail_requests(
                list(self._live.values()) + self.queue.drain(), e)

    def _tick(self):
        """How long the queue wait may block before the loop must tick
        again: the oldest bucket's remaining deadline, a short poll
        while dispatches are in flight, a longer idle poll otherwise."""
        if self._stopping.is_set():
            return 0.0
        age = self._ex.oldest_bucket_age()
        if age is not None:
            return max(0.0, min(self.max_wait_s - age, 0.05))
        if any(self._ex.in_flight):
            return 0.002
        return 0.05

    def _lane_for(self, req):
        key = (os.path.abspath(req.modelfile),
               tuple(sorted((k, _freeze(v))
                            for k, v in req.options.items())))
        ent = self._lanes.pop(key, None)
        if ent is None:
            # one lane per (template, options): the model load
            # amortizes across every request that reuses it, and the
            # key_prefix namespaces bucket keys so same-layout buckets
            # of DIFFERENT templates can never share a dispatch while
            # same-(template, options) requests always can
            lane, loader = make_wideband_lane(
                req.modelfile, nsub_batch=self.nsub_batch,
                quiet=self.quiet, tracer=self.tracer,
                key_prefix=(key,), **req.options)
            ent = (lane, loader)
        # re-insert = move to most-recent; evict the oldest beyond the
        # cache bound (dicts iterate in insertion order)
        self._lanes[key] = ent
        while len(self._lanes) > LANE_CACHE_MAX:
            self._lanes.pop(next(iter(self._lanes)))
        return ent

    def _admit_request(self, req):
        req.t_admit = time.monotonic()
        try:
            lane, loader = self._lane_for(req)
        except Exception as e:
            # a bad modelfile/option set fails ITS request, not the
            # server
            self.queue.release(len(req.datafiles))
            self._complete(req, error=e)
            return
        self._live[id(req)] = req
        ex = self._ex
        from ..pipeline.toas import _iter_archives

        # archive IO runs ahead of admission on prefetch threads (the
        # same overlap discipline as the one-shot driver) — the
        # serving thread buckets archive N while N+1..N+4 load
        for pos, (f, d) in enumerate(
                _iter_archives(req.datafiles, loader, prefetch=True)):
            skip = None
            if isinstance(d, Exception):
                skip = str(d)
            if skip is None:
                ok = np.asarray(d.ok_isubs, int)
                if d.nsub == 0 or len(ok) == 0:
                    skip = "no subints to fit"
            if skip is not None:
                self.tracer.emit("archive_skip", datafile=f,
                                 reason=skip)
                self.tracer.counter("archives_skipped")
                log(f"Skipping {f}: {skip}", level="warn", tracer=None)
                req.n_skipped += 1
                self.queue.release(1)
                continue
            ia = self._iarch
            self._iarch += 1
            self._by_iarch[ia] = (req, pos)
            # admit may block on a full device queue; the drains it
            # runs fire _archive_done callbacks on this same thread
            if ex.admit(ia, f, d, ok, lane=lane) is None:
                del self._by_iarch[ia]
                req.n_skipped += 1
            self.queue.release(1)
            # keep latency honest while a long request streams in
            ex.flush_stale(self.max_wait_s)
            ex._drain_ready()
        req.all_admitted = True
        self._maybe_complete(req)

    # -- executor hooks (server thread) --------------------------------

    def _launched(self, seq, owners, pad):
        if not self.tracer.enabled:
            return
        names = {self._by_iarch[ia][0].name for ia, _ in owners
                 if ia in self._by_iarch}
        self.tracer.emit("batch_coalesce", seq=seq,
                         n_requests=len(names),
                         requests=sorted(names), rows=len(owners),
                         pad=int(pad))

    def _archive_done(self, iarch, m, out):
        ent = self._by_iarch.pop(iarch, None)
        if ent is None:
            return
        req, pos = ent
        req.meta[pos] = m
        req.assembled[pos] = out
        self._ex.forget(iarch)  # keep the warm executor O(live work)
        self._maybe_complete(req)

    # -- request completion --------------------------------------------

    def _maybe_complete(self, req):
        if not req.all_admitted:
            return
        if len(req.assembled) + req.n_skipped < len(req.datafiles):
            return
        try:
            positions = sorted(req.assembled)
            meta = [req.meta[p] for p in positions]
            assembled = {m.iarch: req.assembled[p]
                         for p, m in zip(positions, meta)}
            (TOA_list, order, DM0s, means,
             errs) = _collect_wideband(meta, assembled)
            if req.tim_out:
                # the one-shot checkpoint format, in the REQUEST's
                # archive order: truncate, then per-archive TOA lines +
                # completion sentinel — byte-identical to
                # stream_wideband_TOAs(tim_out=...)
                open(req.tim_out, "w").close()
                for m in meta:
                    write_TOAs(assembled[m.iarch][0],
                               outfile=req.tim_out, append=True)
                    with open(req.tim_out, "a") as fh:
                        fh.write(_DONE_PREFIX
                                 + os.path.abspath(m.datafile) + "\n")
            result = DataBunch(
                TOA_list=TOA_list, order=order, DM0s=DM0s,
                DeltaDM_means=means, DeltaDM_errs=errs,
                tim_out=req.tim_out, n_skipped=req.n_skipped)
            self._complete(req, result=result)
        except Exception as e:
            self._complete(req, error=e)

    def _complete(self, req, result=None, error=None):
        req._result = result
        req._error = error
        req.t_done = time.monotonic()
        self._live.pop(id(req), None)
        if self.tracer.enabled:
            t_sub = req.t_submit if req.t_submit is not None \
                else req.t_done
            t_adm = req.t_admit if req.t_admit is not None \
                else req.t_done
            self.tracer.emit(
                "request_done", req=req.name,
                n_toas=len(result.TOA_list) if result else 0,
                n_archives=len(result.order) if result else 0,
                wall_s=round(req.t_done - t_sub, 6),
                queue_s=round(t_adm - t_sub, 6),
                error=str(error) if error else None)
        req._event.set()

    def _fail_requests(self, requests, error):
        for req in requests:
            if not req.done():
                self._complete(req, error=error)
