"""Test configuration: run everything on CPU with 8 virtual XLA devices
so sharding/mesh tests exercise the multi-chip code paths without TPU
hardware (the driver separately dry-runs the real multi-chip path via
__graft_entry__.dryrun_multichip)."""

import os

# 8 virtual CPU devices; must be set before the backend initializes
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Force CPU even when the launch environment routes to a TPU plugin
# (bench.py uses the real chip; tests must not).  The env var alone is
# not enough here because the site customization registers the TPU
# backend at interpreter start.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running sweeps (full option lattice) excluded from "
        "tier-1's -m 'not slow' run")
    # tier-1 runs under a wall-clock cap on single-core runners, so the
    # suite always reports its heaviest tests — the data the slow-mark
    # budget is maintained from.  An explicit --durations wins.
    if config.option.durations is None:
        config.option.durations = 20


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def key():
    return jax.random.PRNGKey(42)
