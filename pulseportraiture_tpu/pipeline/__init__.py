"""Pipeline / orchestration layer (SURVEY §2.2 L4): TOA measurement,
align-and-average, template building, channel zapping."""

from .align import (  # noqa: F401
    align_archives,
    gaussian_seed_portrait,
    make_constant_portrait,
    psradd_archives,
    psrsmooth_archive,
)
from .factory import TemplateJob, build_templates  # noqa: F401
from .ipta import IPTAJob, stream_ipta_campaign  # noqa: F401
from .models import TemplateModel, sniff_model_type  # noqa: F401
from .portrait import DataPortrait, normalize_portrait  # noqa: F401
from .stream import (stream_narrowband_TOAs,  # noqa: F401
                     stream_wideband_TOAs)
from .toas import GetTOAs  # noqa: F401
from .zap import (apply_zaps, get_zap_channels,  # noqa: F401
                  print_paz_cmds, resolve_zap_device)
