"""Interactive Gaussian-component hand-fitting GUI.

Parity target: the reference's GaussianSelector (ppgauss.py:382-663):
a matplotlib event-driven tool where a left-click drag sketches a new
Gaussian (position+width from the span, height from the drag), middle
click runs the profile fit, right click removes the last component,
and 'q' finishes.  The fit engine is the JAX LM profile fitter.

Requires an interactive matplotlib backend; headless pipelines should
use GaussPortrait.fit_profile(auto_gauss=...) instead.
"""

import numpy as np

from ..fit.gauss import fit_gaussian_profile, gen_gaussian_profile_flat
from ..io.psrfits import noise_std_ps


class GaussianSelector:
    def __init__(self, profile, errs=None, tau=0.0, fixscat=True,
                 profile_fit_flags=None, show=True, ax=None):
        import matplotlib.pyplot as plt

        self.profile = np.asarray(profile, float)
        self.nbin = len(self.profile)
        self.phases = (np.arange(self.nbin) + 0.5) / self.nbin
        self.errs = float(errs) if errs is not None else \
            float(noise_std_ps(self.profile))
        self.tau = float(tau)
        self.fixscat = fixscat
        self.profile_fit_flags = profile_fit_flags
        self.init_params = [0.0, self.tau]  # [dc, tau] + (loc, wid, amp)*
        self.ngauss = 0
        self.fitted_params = np.asarray(self.init_params)
        self.fit_errs = np.zeros(2)
        self.chi2 = np.inf
        self.dof = self.nbin - 2

        if ax is None:
            self.fig, (self.ax, self.ax_resid) = plt.subplots(
                2, 1, sharex=True, figsize=(7, 6))
        else:
            self.fig = ax.figure
            self.ax = ax
            self.ax_resid = None
        self._press = None
        self._draw()
        self.cids = [
            self.fig.canvas.mpl_connect("button_press_event",
                                        self._on_press),
            self.fig.canvas.mpl_connect("button_release_event",
                                        self._on_release),
            self.fig.canvas.mpl_connect("key_press_event", self._on_key),
        ]
        if show:
            plt.show()

    # -- drawing -----------------------------------------------------------
    def _draw(self):
        self.ax.cla()
        self.ax.plot(self.phases, self.profile, "k-", lw=0.8)
        if self.ngauss:
            model = np.asarray(gen_gaussian_profile_flat(
                np.asarray(self.fitted_params), self.nbin))
            self.ax.plot(self.phases, model, "r-", lw=1.2)
            if self.ax_resid is not None:
                self.ax_resid.cla()
                self.ax_resid.plot(self.phases, self.profile - model, "k-",
                                   lw=0.6)
                self.ax_resid.set_xlabel("Pulse Phase")
                self.ax_resid.set_ylabel("Data-Fit Residuals")
        self.ax.set_ylabel("Flux")
        self.ax.set_title(
            f"{self.ngauss} component(s) — left-drag: add, middle: fit, "
            f"right: remove last, 'q': done")
        self.fig.canvas.draw_idle()

    # -- events ------------------------------------------------------------
    def _on_press(self, event):
        if event.inaxes != self.ax:
            return
        if event.button == 1:
            self._press = (event.xdata, event.ydata)
        elif event.button == 2:
            self.do_fit()
        elif event.button == 3:
            self.remove_last()

    def _on_release(self, event):
        if self._press is None or event.inaxes != self.ax or \
                event.button != 1:
            return
        x0, y0 = self._press
        self._press = None
        self.add_component(loc=0.5 * (x0 + event.xdata),
                           wid=max(abs(event.xdata - x0), 1.0 / self.nbin),
                           amp=max(abs(y0), abs(event.ydata or y0)))

    def _on_key(self, event):
        if event.key == "q":
            import matplotlib.pyplot as plt

            for cid in self.cids:
                self.fig.canvas.mpl_disconnect(cid)
            plt.close(self.fig)

    # -- actions (also usable programmatically/for tests) ------------------
    def add_component(self, loc, wid, amp):
        self.init_params = list(self.init_params) + \
            [float(loc) % 1.0, float(wid), float(amp)]
        self.ngauss += 1
        self.fitted_params = np.asarray(self.init_params)
        self._draw()

    def remove_last(self):
        if self.ngauss:
            self.init_params = list(self.init_params)[:-3]
            self.ngauss -= 1
            self.fitted_params = np.asarray(self.init_params)
            self._draw()

    def do_fit(self):
        if not self.ngauss:
            return
        fgp = fit_gaussian_profile(
            self.profile, np.asarray(self.init_params), self.errs,
            fit_flags=self.profile_fit_flags,
            fit_scattering=not self.fixscat, quiet=True)
        self.fitted_params = np.asarray(fgp.fitted_params)
        self.fit_errs = np.asarray(fgp.fit_errs)
        self.chi2 = float(fgp.chi2)
        self.dof = int(fgp.dof)
        self.init_params = list(self.fitted_params)
        self._draw()
