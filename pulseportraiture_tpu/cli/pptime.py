"""pptime — fleet-batched wideband GLS timing from .tim + parfiles.

The timing tail of the flagship pipeline (pptoas -> .tim -> timing
solution), fleet-shaped: every pulsar's linearized system is bucketed
by power-of-two (rows, params) class and solved in one padded device
dispatch per bucket (timing/fleet.py), instead of one solve per
pulsar.  Handles isolated and ELL1/BT binary parfiles (Keplerian
elements fitted; Shapiro/relativistic keys refused loudly).

Single pulsar:    pptime psr.tim psr.par
Fleet:            pptime -j jobs.txt        # lines: <pulsar> <tim> <par>
"""

import argparse
import sys


def build_parser():
    p = argparse.ArgumentParser(
        prog="pptime", description=__doc__.splitlines()[0])
    p.add_argument("timfile", nargs="?", default=None,
                   help="Wideband .tim file (single-pulsar mode).")
    p.add_argument("parfile", nargs="?", default=None,
                   help="Parfile (single-pulsar mode).")
    p.add_argument("-j", "--jobs", default=None,
                   help="Fleet jobs file: one '<pulsar> <timfile> "
                        "<parfile>' line per pulsar (# comments ok).")
    p.add_argument("--fit-f1", action="store_true", default=False,
                   help="Also fit the spin-down term dF1.")
    p.add_argument("--no-fit-binary", dest="fit_binary",
                   action="store_false", default=True,
                   help="Model the parfile's binary orbit but hold "
                        "its elements fixed.")
    p.add_argument("--allow-wraps", action="store_true", default=False,
                   help="Accept per-TOA nearest-turn wrapping even "
                        "when phase connection looks lost.")
    p.add_argument("--epoch-gap", type=float, default=0.5,
                   help="DMX epoch grouping gap [days] (default 0.5).")
    p.add_argument("--gls-device", default=None,
                   choices=("off", "auto", "on"),
                   help="Route the fleet solve through the batched "
                        "device lane (default: config.gls_device / "
                        "PPT_GLS_DEVICE).")
    p.add_argument("--serial", action="store_true", default=False,
                   help="One solve dispatch per pulsar instead of one "
                        "per bucket (the bench A/B arm).")
    p.add_argument("--telemetry", default=None,
                   help="Append timing_fit/fleet_end events to this "
                        "JSONL trace.")
    p.add_argument("--json", action="store_true", default=False,
                   help="Print one JSON line per pulsar instead of "
                        "the table.")
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def _load_jobs(args, parser):
    """Resolve the fleet spec; anything malformed dies loudly BEFORE
    any file IO (SystemExit carries the message so tests can match)."""
    if args.jobs is not None:
        if args.timfile is not None or args.parfile is not None:
            raise SystemExit("pptime: pass -j/--jobs OR a single "
                             "timfile+parfile pair, not both")
        import os

        if not os.path.exists(args.jobs):
            raise SystemExit(f"pptime: jobs file not found: "
                             f"{args.jobs}")
        jobs = []
        with open(args.jobs) as fh:
            for lineno, line in enumerate(fh, 1):
                s = line.strip()
                if not s or s.startswith("#"):
                    continue
                parts = s.split()
                if len(parts) != 3:
                    raise SystemExit(
                        f"pptime: {args.jobs}:{lineno}: expected "
                        f"'<pulsar> <timfile> <parfile>', got {s!r}")
                jobs.append(tuple(parts))
        if not jobs:
            raise SystemExit(f"pptime: {args.jobs}: no jobs")
        return jobs
    if args.timfile is None or args.parfile is None:
        raise SystemExit("pptime: need a timfile and a parfile (or "
                         "-j jobs.txt)")
    import os

    name = os.path.basename(args.timfile)
    name = name[:-4] if name.endswith(".tim") else name
    return [(name, args.timfile, args.parfile)]


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    specs = _load_jobs(args, parser)

    from ..timing.fleet import TimingJob, fleet_gls_fit

    device = {None: None, "off": False, "auto": "auto",
              "on": True}[args.gls_device]
    jobs = [TimingJob(*spec) for spec in specs]
    fleet = fleet_gls_fit(
        jobs, fit_f1=args.fit_f1, fit_binary=args.fit_binary,
        epoch_gap_days=args.epoch_gap, allow_wraps=args.allow_wraps,
        device=device, batched=not args.serial,
        telemetry=args.telemetry, quiet=args.quiet)

    if args.json:
        import json

        for name in fleet.pulsars:
            r = fleet.results[name]
            print(json.dumps({
                "pulsar": name, "n_toas": int(len(r.time_resids_us)),
                "chi2": float(r.chi2), "dof": int(r.dof),
                "red_chi2": float(r.red_chi2),
                "wrms_us": float(r.wrms_us),
                "params": {k: float(v) for k, v in r.params.items()},
                "param_errs": {k: float(v)
                               for k, v in r.param_errs.items()},
                "dmx": [float(v) for v in r.dmx],
                "binary": (r.binary.kind if r.binary is not None
                           else None)}))
    else:
        for name in fleet.pulsars:
            r = fleet.results[name]
            orbit = f"  binary={r.binary.kind}" if r.binary else ""
            print(f"{name}: {len(r.time_resids_us)} TOAs, "
                  f"red-chi2 {r.red_chi2:.3f}, wrms "
                  f"{r.wrms_us:.4f} us, {len(r.dmx)} DMX "
                  f"epoch(s){orbit}")
            for k, v in r.params.items():
                print(f"    {k:>7s} {v:+.6e} +/- {r.param_errs[k]:.1e}")
    if not args.quiet and not args.json:
        lane = "device" if fleet.device else "host"
        print(f"{len(fleet.pulsars)} pulsar(s) in "
              f"{fleet.n_dispatches} solve dispatch(es) [{lane}"
              f"{', batched' if fleet.device and fleet.batched else ''}]"
              f" in {fleet.wall_s:.3f} s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
