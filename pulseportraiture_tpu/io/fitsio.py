"""Minimal FITS codec (read + write), dependency-free.

The reference reaches PSRFITS through the PSRCHIVE C++ bindings
(reference pplib.py:51, load_data pplib.py:2749).  This framework has
no PSRCHIVE and no astropy, so it carries its own small FITS engine:
2880-byte blocks, 80-char header cards, primary HDUs and BINTABLE
extensions — everything PSRFITS fold-mode archives need, nothing more.

Reading returns numpy arrays (big-endian decoded to native); writing
produces standard-conforming files that astropy/PSRCHIVE can open.
A faster C++ decoder for the hot SUBINT path lives in `native/`; this
module is the reference implementation and the writer.
"""

import math
from collections import OrderedDict

import numpy as np

BLOCK = 2880
CARDLEN = 80


class TruncatedFits(ValueError):
    """A FITS file ended mid-header or mid-data: the bytes on disk are
    shorter than the structure the headers promise.  The classic cause
    is reading a file that is STILL BEING WRITTEN (an observatory
    watch-folder racing the telescope backend), so this error is typed
    and marked retryable — the ingest driver catches it and re-admits
    the file once its size stabilizes instead of poisoning the source.
    A torn file that never completes keeps raising; it is still a
    loud ValueError for every non-ingest caller."""

    retryable = True

# TFORM letter -> (numpy big-endian dtype, bytes per element)
_TFORM2DTYPE = {
    "L": ("u1", 1),  # logical, stored as 'T'/'F' bytes
    "B": ("u1", 1),
    "I": (">i2", 2),
    "J": (">i4", 4),
    "K": (">i8", 8),
    "E": (">f4", 4),
    "D": (">f8", 8),
    "C": (">c8", 8),
    "M": (">c16", 16),
}


class Header:
    """Ordered FITS header: keeps card order, dict-style access by key."""

    def __init__(self, cards=None):
        # list of (key, value, comment); COMMENT/HISTORY may repeat
        self.cards = list(cards) if cards else []

    def __contains__(self, key):
        return any(k == key for k, _, _ in self.cards)

    def __getitem__(self, key):
        for k, v, _ in self.cards:
            if k == key:
                return v
        raise KeyError(key)

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __setitem__(self, key, value):
        comment = ""
        if isinstance(value, tuple):
            value, comment = value
        for i, (k, _, c) in enumerate(self.cards):
            if k == key:
                self.cards[i] = (key, value, comment or c)
                return
        self.cards.append((key, value, comment))

    def append(self, key, value, comment=""):
        self.cards.append((key, value, comment))

    def keys(self):
        return [k for k, _, _ in self.cards]


class HDU:
    """One header-data unit.  `data` is None, an ndarray (image), or an
    OrderedDict of column name -> ndarray (bintable, rows-first).

    For bintables, `raw` keeps the undecoded table payload and
    `layout` maps column name -> (byte_offset, tform_code, repeat) so
    callers (the native SUBINT fast path) can decode columns straight
    from the wire bytes; columns listed in a reader's `defer` set are
    left as None in `data` and must be fetched through these.
    `col_scaling` maps column name -> (TSCAL, TZERO) for every numeric
    column carrying a nontrivial FITS scaling (e.g. the signed-byte
    convention 'B' + TZERO=-128); decoded columns have it applied
    already, deferred columns must apply it themselves."""

    def __init__(self, header, data=None, name="", raw=None, layout=None,
                 col_scaling=None):
        self.header = header
        self.data = data
        self.name = name or header.get("EXTNAME", "")
        self.raw = raw
        self.layout = layout or {}
        self.col_scaling = col_scaling or {}

    @property
    def row_stride(self):
        return int(self.header.get("NAXIS1", 0))


# --------------------------------------------------------------------------
# Card parsing / formatting
# --------------------------------------------------------------------------

def _parse_value(raw):
    s = raw.strip()
    if not s:
        return None
    if s[0] == "'":  # string: '' escapes a quote
        end = 1
        out = []
        while end < len(s):
            if s[end] == "'":
                if end + 1 < len(s) and s[end + 1] == "'":
                    out.append("'")
                    end += 2
                    continue
                break
            out.append(s[end])
            end += 1
        return "".join(out).rstrip()
    if s == "T":
        return True
    if s == "F":
        return False
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s.replace("D", "E").replace("d", "e"))
    except ValueError:
        return s


def _parse_card(card):
    key = card[:8].strip()
    if key in ("COMMENT", "HISTORY", "") or card[8:10] != "= ":
        return key, None, card[8:].strip()
    rest = card[10:]
    # split value / comment at first '/' outside a quoted string
    in_str = False
    i = 0
    while i < len(rest):
        c = rest[i]
        if c == "'":
            in_str = not in_str
        elif c == "/" and not in_str:
            break
        i += 1
    value = _parse_value(rest[:i])
    comment = rest[i + 1:].strip() if i < len(rest) else ""
    return key, value, comment


def _format_value(value):
    if isinstance(value, bool):
        return "T".rjust(20) if value else "F".rjust(20)
    if isinstance(value, (int, np.integer)):
        return str(int(value)).rjust(20)
    if isinstance(value, (float, np.floating)):
        v = float(value)
        if v != v or math.isinf(v):
            raise ValueError(f"non-finite header value: {v}")
        s = repr(v)
        if len(s) > 20:
            s = f"{v:.13E}"
        return s.rjust(20)
    # string
    s = str(value).replace("'", "''")
    return ("'" + s.ljust(8) + "'").ljust(20)


def _format_card(key, value, comment):
    if key in ("COMMENT", "HISTORY", ""):
        card = key.ljust(8) + str(comment)
    elif value is None:
        card = key.ljust(8) + (" " + comment if comment else "")
    else:
        card = key.ljust(8) + "= " + _format_value(value)
        if comment:
            card += " / " + comment
    return card[:CARDLEN].ljust(CARDLEN)


# --------------------------------------------------------------------------
# Reading
# --------------------------------------------------------------------------

def _read_header(buf, off):
    cards = []
    while True:
        block = buf[off:off + BLOCK]
        if len(block) < BLOCK:
            raise TruncatedFits(
                f"truncated FITS header: block at offset {off} holds "
                f"{len(block)} of {BLOCK} bytes")
        off += BLOCK
        done = False
        for i in range(0, BLOCK, CARDLEN):
            card = block[i:i + CARDLEN].decode("ascii", "replace")
            if card.startswith("END") and card[3:].strip() == "":
                done = True
                break
            if card.strip() == "":
                continue
            cards.append(_parse_card(card))
        if done:
            return Header(cards), off


def parse_tform(tform):
    """'2048E' -> (2048, 'E', extra). Variable-length 'P'/'Q' unsupported."""
    tform = tform.strip()
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    code = tform[i]
    return repeat, code, tform[i + 1:]


def _table_dtype(header):
    tfields = header["TFIELDS"]
    names, fields = [], []
    for n in range(1, tfields + 1):
        name = str(header[f"TTYPE{n}"]).strip()
        repeat, code, _ = parse_tform(str(header[f"TFORM{n}"]))
        if code == "A":
            fields.append((f"f{n}", f"S{repeat}"))
        elif code == "X":
            fields.append((f"f{n}", "u1", ((repeat + 7) // 8,)))
        elif code in _TFORM2DTYPE:
            dt, _ = _TFORM2DTYPE[code]
            fields.append((f"f{n}", dt, (repeat,)) if repeat != 1 else (f"f{n}", dt))
        else:
            raise ValueError(f"unsupported TFORM code {code!r}")
        names.append(name)
    return names, np.dtype(fields)


def apply_column_scaling(col, tscal, tzero):
    """Physical values TZERO + TSCAL*stored.  Integer columns with an
    integral pure offset stay integral (the FITS signed/unsigned
    conventions: 'B'+TZERO=-128 -> signed byte, 'I'+TZERO=32768 ->
    unsigned 16-bit); anything else promotes to float64."""
    if col.dtype.kind in "iu" and tscal == 1.0 \
            and float(tzero).is_integer():
        return col.astype(np.int64) + int(tzero)
    return col.astype(np.float64) * tscal + tzero


def _data_size(header):
    naxis = header.get("NAXIS", 0)
    if naxis == 0:
        return 0
    size = abs(header.get("BITPIX", 8)) // 8
    for i in range(1, naxis + 1):
        size *= header[f"NAXIS{i}"]
    size *= header.get("GCOUNT", 1)
    size += header.get("PCOUNT", 0)
    return size


def _read_hdu(buf, off, defer=()):
    header, off = _read_header(buf, off)
    size = _data_size(header)
    raw = buf[off:off + size]
    if len(raw) < size:
        # a short DATA payload would otherwise surface as an opaque
        # np.frombuffer count mismatch far from the real cause
        raise TruncatedFits(
            f"truncated FITS data: HDU at offset {off} promises "
            f"{size} bytes, file holds {len(raw)}")
    off += ((size + BLOCK - 1) // BLOCK) * BLOCK
    xt = str(header.get("XTENSION", "")).strip()
    data = None
    layout = None
    if xt == "BINTABLE":
        names, dt = _table_dtype(header)
        nrows = header["NAXIS2"]
        # the row stride must equal the summed field widths — decoding
        # a table whose NAXIS1 disagrees would read every row after
        # the first from the wrong offset (silent misparse), so refuse
        if int(header["NAXIS1"]) != dt.itemsize:
            raise ValueError(
                f"BINTABLE NAXIS1={header['NAXIS1']} != "
                f"{dt.itemsize} bytes implied by the TFORM columns")
        rec = np.frombuffer(raw, dtype=dt, count=nrows)
        data = OrderedDict()
        layout = {}
        col_scaling = {}
        for i, name in enumerate(names):
            fname = f"f{i + 1}"
            repeat, code, _ = parse_tform(str(header[f"TFORM{i + 1}"]))
            layout[name] = (int(dt.fields[fname][1]), code, repeat)
            tscal = float(header.get(f"TSCAL{i + 1}", 1.0) or 1.0)
            tzero = float(header.get(f"TZERO{i + 1}", 0.0) or 0.0)
            scaled = (tscal != 1.0 or tzero != 0.0) and code not in "AX"
            if scaled:
                col_scaling[name] = (tscal, tzero)
            if name in defer:
                data[name] = None
                continue
            col = rec[fname]
            tdim = header.get(f"TDIM{i + 1}")
            if tdim:
                shape = tuple(int(x) for x in str(tdim).strip("() ").split(","))
                col = col.reshape((nrows,) + shape[::-1])
            if col.dtype.kind in "iufc":
                col = col.astype(col.dtype.newbyteorder("="))
            if scaled:
                col = apply_column_scaling(col, tscal, tzero)
            data[name] = col
        return HDU(header, data, raw=raw, layout=layout,
                   col_scaling=col_scaling), off
    if size and header.get("NAXIS", 0) > 0:
        bitpix = header["BITPIX"]
        dt = {8: "u1", 16: ">i2", 32: ">i4", 64: ">i8",
              -32: ">f4", -64: ">f8"}[bitpix]
        shape = tuple(header[f"NAXIS{i}"]
                      for i in range(header["NAXIS"], 0, -1))
        data = np.frombuffer(raw, dtype=dt).reshape(shape)
        data = data.astype(np.dtype(dt).newbyteorder("="))
    return HDU(header, data), off


def scan_fits(path):
    """Walk a FITS file's HDU boundaries WITHOUT decoding any data —
    the cheap completeness probe the ingest driver runs before handing
    an archive to the loaders.  Raises :class:`TruncatedFits` when the
    bytes on disk end before the structure the headers promise (the
    half-written-file signature); returns the HDU count otherwise.
    Costs header parsing only, so it is safe to run on every poll."""
    with open(path, "rb") as f:
        buf = f.read()
    n = 0
    off = 0
    while off < len(buf):
        if not buf[off:off + BLOCK].strip():
            break
        header, off = _read_header(buf, off)
        size = _data_size(header)
        if len(buf) < off + size:
            raise TruncatedFits(
                f"truncated FITS data: HDU {n} at offset {off} "
                f"promises {size} bytes, file holds {len(buf) - off}")
        off += ((size + BLOCK - 1) // BLOCK) * BLOCK
        n += 1
    if n == 0:
        raise TruncatedFits(f"{path}: no complete HDU")
    return n


def read_fits(path, defer=()):
    """Read a FITS file -> list of HDU.

    Column names in `defer` are not decoded in bintables (left None in
    `hdu.data`); fetch them from `hdu.raw`/`hdu.layout` — used by the
    native SUBINT fast path to avoid a second pass over the big DATA
    column."""
    with open(path, "rb") as f:
        buf = f.read()
    hdus = []
    off = 0
    while off < len(buf):
        if not buf[off:off + BLOCK].strip():
            break
        hdu, off = _read_hdu(buf, off, defer=defer)
        hdus.append(hdu)
    return hdus


# --------------------------------------------------------------------------
# Writing
# --------------------------------------------------------------------------

def _write_header(f, cards):
    out = bytearray()
    for key, value, comment in cards:
        out += _format_card(key, value, comment).encode("ascii")
    out += b"END".ljust(CARDLEN)
    pad = (-len(out)) % BLOCK
    out += b" " * pad
    f.write(bytes(out))


def _pad_block(f, nbytes):
    pad = (-nbytes) % BLOCK
    if pad:
        f.write(b"\x00" * pad)


def write_primary(f, header_cards):
    cards = [("SIMPLE", True, "file conforms to FITS standard"),
             ("BITPIX", 8, ""), ("NAXIS", 0, ""),
             ("EXTEND", True, "")]
    cards += header_cards
    _write_header(f, cards)


def _column_tform(arr, ncols_shape):
    kind = arr.dtype.kind
    if kind == "S":
        return f"{arr.dtype.itemsize}A", None
    code = {"u1": "B", "i2": "I", "i4": "J", "i8": "K",
            "f4": "E", "f8": "D"}[arr.dtype.newbyteorder("=").str[1:]]
    repeat = int(np.prod(ncols_shape)) if ncols_shape else 1
    return f"{repeat}{code}", code


def write_bintable(f, name, columns, header_cards=(), tdims=None, units=None):
    """columns: OrderedDict name -> ndarray with shape (nrows, ...).
    tdims: optional {colname: shape-tuple (FITS order, fastest first)}."""
    tdims = tdims or {}
    units = units or {}
    names = list(columns)
    nrows = len(next(iter(columns.values()))) if columns else 0
    fields = []
    cards = []
    for i, cname in enumerate(names, 1):
        arr = np.ascontiguousarray(columns[cname])
        if len(arr) != nrows:
            raise ValueError(f"column {cname}: row count mismatch")
        elem_shape = arr.shape[1:]
        tform, code = _column_tform(arr, elem_shape)
        if arr.dtype.kind == "S":
            fields.append((f"f{i}", arr.dtype.str))
        else:
            be = ">" + arr.dtype.newbyteorder("=").str[1:]
            fields.append((f"f{i}", be, elem_shape) if elem_shape
                          else (f"f{i}", be))
        cards.append((f"TTYPE{i}", cname, ""))
        cards.append((f"TFORM{i}", tform, ""))
        if cname in units:
            cards.append((f"TUNIT{i}", units[cname], ""))
        if cname in tdims:
            dim = ",".join(str(d) for d in tdims[cname])
            cards.append((f"TDIM{i}", f"({dim})", ""))
    dt = np.dtype(fields)
    rec = np.zeros(nrows, dtype=dt)
    for i, cname in enumerate(names, 1):
        arr = np.ascontiguousarray(columns[cname])
        if arr.dtype.kind == "S":
            rec[f"f{i}"] = arr
        else:
            rec[f"f{i}"] = arr.reshape(nrows, -1).reshape(
                rec[f"f{i}"].shape)
    head = [("XTENSION", "BINTABLE", "binary table extension"),
            ("BITPIX", 8, ""), ("NAXIS", 2, ""),
            ("NAXIS1", dt.itemsize, "bytes per row"),
            ("NAXIS2", nrows, "number of rows"),
            ("PCOUNT", 0, ""), ("GCOUNT", 1, ""),
            ("TFIELDS", len(names), "")]
    head += cards
    head += [("EXTNAME", name, "")]
    head += list(header_cards)
    _write_header(f, head)
    raw = rec.tobytes()
    f.write(raw)
    _pad_block(f, len(raw))


def get_hdu(hdus, name):
    for h in hdus:
        if str(h.name).strip() == name:
            return h
    raise KeyError(f"no HDU named {name!r}")
