"""DataPortrait, align_archives, zap, and viz smoke tests.

Oracles: alignment of phase/DM-shifted noisy copies recovers the clean
portrait (correlation with truth improves and residual rms decreases
vs the unaligned average); median zap algorithm flags the loud
channel; normalization methods have their defining properties.
"""

import numpy as np
import pytest

from pulseportraiture_tpu.io import load_data
from pulseportraiture_tpu.io.gmodel import gen_gmodel_portrait
from pulseportraiture_tpu.pipeline import (
    DataPortrait,
    align_archives,
    apply_zaps,
    gaussian_seed_portrait,
    get_zap_channels,
    normalize_portrait,
    print_paz_cmds,
    psradd_archives,
)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J0613-0200", "RAJ": "06:13:43.9", "DECJ": "-02:00:47.2",
       "P0": 0.003062, "PEPOCH": 55000.0, "DM": 38.779}


@pytest.fixture(scope="module")
def epochs_files(tmp_path_factory):
    root = tmp_path_factory.mktemp("align")
    model = default_test_model(1500.0)
    files = []
    phases = [0.0, 0.11, -0.07]
    for i in range(3):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=24,
                         nbin=256, nu0=1500.0, bw=600.0, tsub=60.0,
                         phase=phases[i], dDM=1e-4 * i,
                         start_MJD=MJD(55100 + i, 0.2), noise_stds=0.06,
                         dedispersed=False, quiet=True, rng=i)
        files.append(path)
    meta = root / "meta.txt"
    meta.write_text("\n".join(files) + "\n")
    return str(meta), files, model


def test_align_archives_recovers_clean_portrait(epochs_files, tmp_path):
    meta, files, model = epochs_files
    out = str(tmp_path / "avg.fits")
    avg = align_archives(meta, files[0], outfile=out, niter=2, quiet=True)
    assert avg.shape == (1, 24, 256)
    d = load_data(out, quiet=True)
    assert d.DM == 0.0 and d.dmc
    # correlation with the clean (dedispersed, unshifted at file-0
    # phase) template portrait
    clean = np.asarray(gen_gmodel_portrait(
        model, d.phases, np.asarray(d.freqs[0]), P=PAR["P0"]))
    a = avg[0] - avg[0].mean(axis=1, keepdims=True)
    c = clean - clean.mean(axis=1, keepdims=True)
    # per-channel correlation maximized over a common phase lag
    ccf = np.fft.irfft(np.fft.rfft(a, axis=1).conj()
                       * np.fft.rfft(c, axis=1), axis=1).sum(axis=0)
    corr = ccf.max() / np.sqrt((a ** 2).sum() * (c ** 2).sum())
    assert corr > 0.95
    # noise should beat a single file by ~sqrt(nfiles*nsub)
    resid_rms = np.sqrt(((a - np.roll(c, np.argmax(ccf), axis=1)) ** 2
                         ).mean())
    assert resid_rms < 0.06


def test_psradd_and_gaussian_seed(epochs_files, tmp_path):
    meta, files, model = epochs_files
    avg = psradd_archives(files, outfile=str(tmp_path / "sum.fits"),
                          quiet=True)
    assert avg.shape == (24, 256)
    seed = gaussian_seed_portrait(24, 256, fwhm=0.05)
    assert seed.shape == (24, 256)
    # align against the constant-Gaussian seed also works
    out = align_archives(files, seed, outfile=str(tmp_path / "g.fits"),
                         niter=1, quiet=True)
    assert np.isfinite(out).all()


def test_data_portrait_normalize_and_flux(epochs_files):
    meta, files, model = epochs_files
    dp = DataPortrait(files[0], quiet=True)
    assert dp.port.shape == (24, 256)
    assert len(dp.portx) == len(dp.ok_ichans)
    norms = dp.normalize_portrait("rms")
    from pulseportraiture_tpu.io.psrfits import noise_std_ps

    after = noise_std_ps(dp.port[dp.ok_ichans])
    np.testing.assert_allclose(after, 1.0, rtol=0.2)
    dp.unnormalize_portrait()
    res = dp.fit_flux_profile(quiet=True)
    assert np.isfinite(res.alpha)
    # rotate_stuff round-trips
    before = dp.port.copy()
    dp.rotate_stuff(phase=0.3)
    dp.rotate_stuff(phase=-0.3)
    spec = np.abs(np.fft.rfft(before - dp.port, axis=1))[:, :-1]
    assert spec.max() < 1e-8


def test_normalize_methods():
    rng = np.random.default_rng(0)
    port = np.abs(rng.normal(size=(8, 64))) + 1.0
    for method, check in [
        ("mean", lambda p: p.mean(axis=1)),
        ("max", lambda p: p.max(axis=1)),
        ("abs", lambda p: np.sqrt((p ** 2).sum(axis=1))),
    ]:
        out = normalize_portrait(port, method)
        np.testing.assert_allclose(check(out), 1.0, atol=1e-10)
    out, norms = normalize_portrait(port, "prof", return_norms=True)
    assert norms.shape == (8,)


def test_join_metafile_path(epochs_files, tmp_path):
    """Two 'receivers' (disjoint bands) concatenate frequency-sorted
    with join bookkeeping."""
    model = default_test_model(1500.0)
    lo = str(tmp_path / "lo.fits")
    hi = str(tmp_path / "hi.fits")
    make_fake_pulsar(model, PAR, outfile=lo, nsub=1, nchan=16, nbin=256,
                     nu0=1200.0, bw=400.0, tsub=60.0, noise_stds=0.05,
                     dedispersed=True, quiet=True, rng=3)
    make_fake_pulsar(model, PAR, outfile=hi, nsub=1, nchan=16, nbin=256,
                     nu0=1700.0, bw=400.0, tsub=60.0, noise_stds=0.05,
                     dedispersed=True, quiet=True, rng=4)
    meta = tmp_path / "join_meta.txt"
    meta.write_text(f"{lo}\n{hi}\n")
    dp = DataPortrait(str(meta), quiet=True)
    assert dp.port.shape == (32, 256)
    assert np.all(np.diff(dp.freqs[0]) > 0)
    assert len(dp.join_ichans) == 2
    assert dp.join_fit_flags == [0, 0, 1, 1]
    jf = tmp_path / "join.txt"
    dp.write_join_parameters(str(jf), quiet=True)
    assert len(jf.read_text().strip().splitlines()) == 2


def test_zap_median_and_apply(epochs_files, tmp_path):
    meta, files, model = epochs_files
    noisy = str(tmp_path / "noisy.fits")
    make_fake_pulsar(model, PAR, outfile=noisy, nsub=1, nchan=24, nbin=256,
                     tsub=60.0, noise_stds=np.where(
                         np.arange(24) == 7, 1.0, 0.05),
                     dedispersed=True, quiet=True, rng=9)
    d = load_data(noisy, quiet=True)
    zaps = get_zap_channels(d, nstd=3)
    assert 7 in zaps[0]
    cmds = print_paz_cmds([noisy], [zaps], quiet=True)
    assert any("-z 7" in c for c in cmds)
    apply_zaps(noisy, zaps, quiet=True)
    d2 = load_data(noisy, quiet=True)
    assert 7 not in d2.ok_ichans[0]


def test_viz_smoke(epochs_files, tmp_path):
    meta, files, model = epochs_files
    dp = DataPortrait(files[0], quiet=True)
    dp.model = np.asarray(gen_gmodel_portrait(
        model, dp.phases, dp.freqs[0], P=float(dp.Ps[0])))
    dp.show_data_portrait(show=False,
                          savefig=str(tmp_path / "port.png"))
    dp.show_model_fit(show=False, savefig=str(tmp_path / "fit.png"))
    assert (tmp_path / "port.png").stat().st_size > 1000
    assert (tmp_path / "fit.png").stat().st_size > 1000


def test_align_fast_routing_matches(epochs_files, tmp_path):
    """config.use_fast_fit=True (the TPU routing) gives the same
    average portrait to f32 accuracy."""
    from pulseportraiture_tpu import config

    meta, files, model = epochs_files
    out_a = str(tmp_path / "a.fits")
    out_b = str(tmp_path / "b.fits")
    avg_a = align_archives(meta, files[0], outfile=out_a, niter=1,
                           quiet=True)
    old = config.use_fast_fit
    try:
        config.use_fast_fit = True
        avg_b = align_archives(meta, files[0], outfile=out_b, niter=1,
                               quiet=True)
    finally:
        config.use_fast_fit = old
    # f32 phases differ at the 1e-6-rot level, which steep profile
    # gradients amplify into ~1e-3 amplitude differences; demand the
    # two averages be essentially the same portrait, not bitwise equal
    a = avg_a.ravel() - avg_a.mean()
    b = avg_b.ravel() - avg_b.mean()
    corr = float(a @ b / np.sqrt((a @ a) * (b @ b)))
    assert corr > 0.99999, corr
    scale = np.abs(avg_a).max()
    assert np.abs(avg_a - avg_b).max() < 0.02 * scale


def test_align_batched_accumulate_matches_loop_reference(epochs_files,
                                                         tmp_path):
    """Round-5 batched the two per-subint host loops (phase-guess and
    weighted accumulate; reference ppalign.py:214-242).  The batched
    harmonic-domain accumulate (one irfft per iteration) must match a
    straightforward per-subint rotate-and-stack loop at f64 round-off.
    The loop reference here re-implements round 4's exact per-subint
    path over the SAME fit outputs."""
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.phase_shift import fit_phase_shift
    from pulseportraiture_tpu.fit.portrait import (FitFlags,
                                                   fit_portrait_batch)
    from pulseportraiture_tpu.ops.rotation import rotate_portrait

    meta, files, model = epochs_files
    out = str(tmp_path / "avg_b.fits")
    avg = align_archives(meta, files[0], outfile=out, niter=1, quiet=True)

    # loop reference: identical math, per-subint eager ops
    md = load_data(files[0], state="Intensity", dedisperse=True,
                   tscrunch=True, pscrunch=True, quiet=True)
    model_port = np.asarray(md.masks[0, 0] * md.subints[0, 0])
    mean_model = model_port.mean(axis=0)
    aligned = np.zeros((1, 24, 256))
    total_w = np.zeros((24, 256))
    for path in files:
        d = load_data(path, state="Intensity", dedisperse=False,
                      dededisperse=True, pscrunch=True, quiet=True)
        ok = np.asarray(d.ok_isubs, int)
        freqs0 = np.asarray(d.freqs[0], float)
        Ps_ok = np.asarray(d.Ps[ok], float)
        masks = np.asarray(d.weights[ok] > 0.0, float)
        ports = np.asarray(d.subints[ok, 0], float)
        noise = np.asarray(d.noise_stds[ok, 0], float)
        DM_guess = 0.0 if d.dmc else float(d.DM)
        theta0 = np.zeros((len(ok), 5))
        theta0[:, 1] = DM_guess
        for j in range(len(ok)):
            rot = np.asarray(rotate_portrait(
                jnp.asarray(ports[j]), 0.0, DM_guess, float(Ps_ok[j]),
                jnp.asarray(freqs0), np.inf))
            r = fit_phase_shift(rot.mean(axis=0), mean_model,
                                np.median(noise[j]))
            theta0[j, 0] = float(r.phase)
        res = fit_portrait_batch(
            jnp.asarray(ports), jnp.broadcast_to(
                jnp.asarray(model_port), ports.shape),
            jnp.asarray(noise), jnp.asarray(freqs0), jnp.asarray(Ps_ok),
            jnp.asarray(np.full(len(ok), freqs0.mean())),
            nu_out=freqs0.mean(), theta0=jnp.asarray(theta0),
            fit_flags=FitFlags(True, True, False, False, False),
            chan_masks=jnp.asarray(masks))
        phis, DMs = np.asarray(res.phi), np.asarray(res.DM)
        scales = np.asarray(res.scales) * masks
        nu_ref_fit = np.asarray(res.nu_DM)
        sub_cube = np.asarray(d.subints[ok], float)
        for j in range(len(ok)):
            rotated = np.asarray(rotate_portrait(
                jnp.asarray(sub_cube[j]), float(phis[j]), float(DMs[j]),
                float(Ps_ok[j]), jnp.asarray(freqs0),
                float(nu_ref_fit[j])))
            noise_j = np.where(noise[j] > 0, noise[j], np.inf)
            w_j = masks[j] * np.maximum(scales[j], 0.0) / noise_j ** 2
            aligned += rotated * w_j[None, :, None]
            total_w += w_j[:, None]
    aligned /= np.maximum(total_w, 1e-30)[None]

    # f64 round-off agreement (sum order differs: harmonic-domain
    # accumulate + one irfft vs per-subint irfft + sequential adds)
    scale = np.abs(aligned).max()
    assert np.abs(avg - aligned).max() < 1e-10 * scale


def test_align_device_lane_matches_host(epochs_files, tmp_path):
    """ISSUE 2 tentpole: the device-resident split-real accumulate
    (parallel/batch.py, jitted with donated on-chip buffers) is
    digit-exact against the chunked-c128 host oracle over full
    align_archives runs — same tolerance discipline as round 5's
    batched-accumulate test (f64 round-off, <= 1e-10 relative)."""
    meta, files, model = epochs_files
    host = align_archives(meta, files[0], niter=2, quiet=True,
                          outfile=str(tmp_path / "h.fits"),
                          align_device=False)
    dev = align_archives(meta, files[0], niter=2, quiet=True,
                         outfile=str(tmp_path / "d.fits"),
                         align_device=True)
    scale = np.abs(host).max()
    assert np.abs(dev - host).max() < 1e-10 * scale


def test_align_device_config_flip_rides_per_call(epochs_files, tmp_path,
                                                 monkeypatch):
    """config.align_device is read per align_archives call (no cached
    routing decision), so in-process A/B flips actually switch lanes."""
    from pulseportraiture_tpu import config
    from pulseportraiture_tpu.pipeline import align as align_mod

    meta, files, model = epochs_files
    calls = []
    real = align_mod.align_accumulate_archive
    monkeypatch.setattr(align_mod, "align_accumulate_archive",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    monkeypatch.setattr(config, "align_device", True)
    align_archives(files[:1], files[0], niter=1, quiet=True,
                   outfile=str(tmp_path / "on.fits"))
    n_on = len(calls)
    assert n_on > 0, "align_device=True did not route to the device lane"
    monkeypatch.setattr(config, "align_device", False)
    align_archives(files[:1], files[0], niter=1, quiet=True,
                   outfile=str(tmp_path / "off.fits"))
    assert len(calls) == n_on, \
        "align_device=False still hit the device accumulate"


def test_align_device_option_strict_and_program_keys():
    """Tri-state strictness (a typo must not mean 'auto') and the
    cached-program keys: the accumulate/finalize programs are keyed on
    the resolved DFT precision AND dispatch arm, so in-process config
    flips retrace instead of silently reusing the other arm's
    program."""
    import jax

    from pulseportraiture_tpu.parallel.batch import (
        _align_accum_fn, _align_chunk, _align_finalize_fn,
        use_align_device)

    assert use_align_device(True) is True
    assert use_align_device(False) is False
    assert use_align_device("auto") == (jax.default_backend() == "tpu")
    with pytest.raises(ValueError):
        use_align_device("ture")

    hi = jax.lax.Precision.HIGHEST
    lo = jax.lax.Precision.HIGH
    assert _align_accum_fn("float64", hi, True) \
        is not _align_accum_fn("float64", hi, False)
    assert _align_accum_fn("float64", hi, True) \
        is not _align_accum_fn("float64", lo, True)
    assert _align_finalize_fn("float64", 256, hi, True) \
        is not _align_finalize_fn("float64", 256, hi, False)
    # same key -> same cached program (the retrace is keyed, not
    # unconditional)
    assert _align_accum_fn("float64", hi, True) \
        is _align_accum_fn("float64", hi, True)

    # chunk bucketing: full batches keep the configured chunk, small
    # archives round up to the next power of two (bounded padding AND
    # bounded program count)
    assert _align_chunk(256, 64) == 64
    assert _align_chunk(64, 64) == 64
    assert _align_chunk(5, 64) == 8
    assert _align_chunk(1, 64) == 1


def test_align_device_env_hook(monkeypatch):
    """PPT_ALIGN_DEVICE rides config.env_overrides() like the other
    A/B switches, strictly (a typo raises)."""
    from pulseportraiture_tpu import config

    old = config.align_device
    try:
        monkeypatch.setenv("PPT_ALIGN_DEVICE", "on")
        assert "align_device" in config.env_overrides()
        assert config.align_device is True
        monkeypatch.setenv("PPT_ALIGN_DEVICE", "off")
        config.env_overrides()
        assert config.align_device is False
        monkeypatch.setenv("PPT_ALIGN_DEVICE", "auto")
        config.env_overrides()
        assert config.align_device == "auto"
        monkeypatch.setenv("PPT_ALIGN_DEVICE", "bogus")
        with pytest.raises(ValueError):
            config.env_overrides()
    finally:
        config.align_device = old


def test_canonical_real_dtype_keeps_f64_under_host_compute(monkeypatch):
    """On a TPU session, _canonical_real_dtype downcasts f64 (c128
    spectra do not compile there) — but NOT inside host_compute(),
    where ops run on the pinned CPU device: align's batched
    phase-guess relies on keeping f64 on host (review finding r5)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit import portrait as pmod
    from pulseportraiture_tpu.utils.device import host_compute

    monkeypatch.setattr(pmod.jax, "default_backend", lambda: "tpu")
    x = jnp.asarray(np.arange(4.0), jnp.float64)
    assert pmod._canonical_real_dtype(x).dtype == jnp.float32
    with host_compute():
        # CPU session: host_compute is a nullcontext and default_device
        # stays unset -> emulate the TPU session's pinned-CPU state
        with jax.default_device(jax.devices("cpu")[0]):
            assert pmod._canonical_real_dtype(x).dtype == jnp.float64
