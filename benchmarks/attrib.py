"""Stage-attribution driver for the historically-unprofiled lanes:
the 5-parameter scattering fit (BASELINE config 3), the
device-resident raw-campaign bucket program (config 5c), and — ISSUE 2
— the device-resident align iteration (config 4).

Built on pulseportraiture_tpu.profiling (the reusable promotion of
exp_breakdown.py's methodology): each lane is decomposed into named
PREFIX stages — cumulative slices of the real program, so fusion
behavior stays honest — plus a PIECE stage (the Newton loop on
precomputed inputs), and the profiler checks that the independently
measured stages sum to the end-to-end slope (>= 90% gates the
benchmarks).

The stage builders here are imported by bench_scatter.py,
bench_device_campaign.py and bench_align.py so their JSON lines carry
the same per-stage breakdown this script prints; run standalone for
the attribution alone:

    python benchmarks/attrib.py scatter
    python benchmarks/attrib.py campaign
    python benchmarks/attrib.py align

Shapes via PPT_NB / PPT_NCHAN / PPT_NBIN (campaign: PPT_NSUBB; align:
PPT_NE).
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def scatter_stage_profile(ports, model, noise, freqs, P, nu_fit, th0,
                          flags, hwin, max_iter, compensated, full_fn,
                          K=3, nrun=2):
    """Attribution of the complex-free scattering lane
    (fit_portrait_batch_fast -> fast_scatter_fit_one):

      dft    (prefix)  windowed matmul DFTs of data + model
      xasm   (prefix)  + weights, X/M2 assembly, Parseval Sd (no seed)
      seed   (prefix)  + the tau-matched CCF phase seed
      newton (piece)   the _cgh_scatter Newton loop + finalize on a
                       precomputed cross-spectrum

    full_fn: the end-to-end batched fit the bench times (so the
    attribution denominator is exactly the benched program)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.portrait import (
        FitFlags, _fit_portrait_core_real_scatter, effective_x_bf16,
        prepare_scatter_fit_real)
    from pulseportraiture_tpu.ops.fourier import _gated_precision, rfft_mm
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    dt = ports.dtype
    nbin = ports.shape[-1]
    prec = _gated_precision(None)
    x_bf16 = effective_x_bf16(compensated)
    kw = dict(fit_flags=flags, log10_tau=True, compensated=compensated,
              x_bf16=x_bf16, nharm_eff=hwin, seed_derotate=False)

    # every stage program takes its arrays as ARGUMENTS: a jnp array
    # closed over by jit becomes an embedded constant, and XLA
    # constant-folds the whole stage at compile time (minutes of
    # single-threaded folding; the exp_breakdown lesson, round 5)
    @jax.jit
    def dft_prefix(ports, model):
        dr, di = jax.vmap(
            lambda p: rfft_mm(p, precision=prec, nharm=hwin))(ports)
        mr, mi = rfft_mm(model.astype(dt), precision=prec, nharm=hwin)
        return (jnp.sum(dr) + jnp.sum(di) + jnp.sum(mr) + jnp.sum(mi))

    def _prep(seed):
        fl = flags if seed else FitFlags(False, *flags[1:])

        def one(p, m, n, t):
            Xr, Xi, M2w, Sd, th = prepare_scatter_fit_real(
                p, m, n, jnp.ones(p.shape[0], dt), freqs, P,
                nu_fit, t, **{**kw, "fit_flags": fl})
            return (jnp.sum(Xr.astype(jnp.float32)) + jnp.sum(M2w)
                    + Sd + jnp.sum(th))

        return jax.jit(jax.vmap(one, in_axes=(0, None, 0, 0)))

    xasm = _prep(False)
    seed = _prep(True)

    @jax.jit
    def prep_out(ports, model, noise, th0):
        def one(p, m, n, t):
            return prepare_scatter_fit_real(
                p, m, n, jnp.ones(p.shape[0], dt), freqs, P,
                nu_fit, t, **kw)

        return jax.vmap(one, in_axes=(0, None, 0, 0))(
            ports, model, noise, th0)

    Xr, Xi, M2w, Sd, th = jax.block_until_ready(
        prep_out(ports, model, noise, th0))

    # X ships as arguments, not closed-over constants — a closure would
    # embed the spectra into the program (compile-request size limits
    # on tunneled runtimes)
    nu_out = jnp.asarray(-1.0, dt)
    core = jax.jit(jax.vmap(
        lambda xr, xi, m2, sd, t0: (
            _fit_portrait_core_real_scatter.__wrapped__(
                xr, xi, m2, sd, freqs, P, nu_fit, nu_out, t0,
                fit_flags=flags, log10_tau=True, max_iter=max_iter,
                compensated=compensated,
                nharm_total=nbin // 2 + 1 if hwin else None))))

    stages = [
        Stage("dft", lambda: dft_prefix(ports, model), "prefix"),
        Stage("xasm", lambda: xasm(ports, model, noise, th0), "prefix"),
        Stage("seed", lambda: seed(ports, model, noise, th0), "prefix"),
        Stage("newton", lambda: core(Xr, Xi, M2w, Sd, th), "piece",
              lambda r: r.phi),
    ]
    return profile_stages(full_fn, stages, pick=lambda r: r.phi, K=K,
                          nrun=nrun)


def campaign_stage_profile(raw, scl, offs, cmask, model, freqs, Ps,
                           DMg, hwin, flags, max_iter, full_fn,
                           K=3, nrun=2):
    """Attribution of the fused raw-bucket program (pipeline/stream
    _raw_fit_fn):

      decode (prefix)  int16 decode + min-window baseline
      stats  (prefix)  + PS noise, S/N (sort-free median), nu_fit seed
      fit    (piece)   the batched no-scatter fit on the decoded ports

    The prefixes call the SAME _raw_decode/_raw_stats helpers the
    production program runs."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.fit.portrait import FitFlags, _fast_batch_fn
    from pulseportraiture_tpu.ops.fourier import use_dft_fold
    from pulseportraiture_tpu.pipeline.stream import _raw_decode, _raw_stats
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    ft = jnp.float32
    nbin = raw.shape[-1]
    tiny = float(np.finfo("float32").tiny)

    # arrays ship as ARGUMENTS, never jit-closed-over constants (XLA
    # would constant-fold the whole stage at compile time — see
    # scatter_stage_profile)
    @jax.jit
    def decode_prefix(raw, scl, offs):
        return jnp.sum(_raw_decode(raw, scl, offs, nbin, ft))

    @jax.jit
    def stats_prefix(raw, scl, offs, cmask, freqs):
        x = _raw_decode(raw, scl, offs, nbin, ft)
        noise, snr, nu_fit = _raw_stats(x, cmask, freqs, ft, tiny)
        return jnp.sum(x) + jnp.sum(noise) + jnp.sum(nu_fit)

    @jax.jit
    def precompute(raw, scl, offs, cmask, freqs):
        x = _raw_decode(raw, scl, offs, nbin, ft)
        noise, snr, nu_fit = _raw_stats(x, cmask, freqs, ft, tiny)
        return x, noise, nu_fit

    x, noise, nu_fit = jax.block_until_ready(
        precompute(raw, scl, offs, cmask, freqs))
    nb = x.shape[0]
    theta0 = jnp.zeros((nb, 5), ft).at[:, 1].set(DMg.astype(ft))
    nu_out = jnp.full((nb,), -1.0, ft)
    fit = _fast_batch_fn(FitFlags(*flags), max_iter, None, None, 0, 0,
                         seed_derotate=bool(np.any(np.asarray(DMg))),
                         x_bf16=True, nharm_eff=hwin,
                         dft_fold=use_dft_fold())
    Ps_b = jnp.broadcast_to(jnp.asarray(Ps, ft), (nb,))

    stages = [
        Stage("decode", lambda: decode_prefix(raw, scl, offs),
              "prefix"),
        Stage("stats", lambda: stats_prefix(raw, scl, offs, cmask,
                                            freqs), "prefix"),
        Stage("fit", lambda: fit(x, model, noise, cmask, freqs, Ps_b,
                                 nu_fit, nu_out, theta0), "piece",
              lambda r: r.phi),
    ]
    return profile_stages(full_fn, stages, pick=lambda r: r, K=K,
                          nrun=nrun)


def align_stage_profile(cube, noise, masks, freqs, P_s, acc_dt,
                        fit_fn, full_fn, K=4, nrun=3):
    """Attribution of the device-resident align iteration
    (pipeline/align.align_archives device lane; parallel/batch.py):

      fit        (prefix)  the batched (phi, DM) fast fit
      rotate     (prefix)  + delays/weights + split-real phasor
                           rotation of the chunked harmonic stacks
                           (_align_rotate_real — the production math)
      accumulate (prefix)  + the donated weighted on-chip accumulate
                           (align_accumulate_archive itself)
      irfft      (prefix)  + the iteration's ONE irfft + normalization
                           (align_finalize)
      host_sync  (piece)   the per-iteration device->host pull of the
                           finalized (npol, nchan, nbin) portrait

    cube: (nb, npol, nchan, nbin); fit_fn() runs the batched fit the
    production lane runs; full_fn() is the end-to-end iteration the
    bench times (fit -> accumulate -> finalize -> host pull), so the
    attribution denominator is exactly the benched program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pulseportraiture_tpu.parallel.batch import (
        ALIGN_DEVICE_CHUNK, _align_chunk, _align_precision,
        _align_rotate_real, _align_weights_fn, align_accumulate_archive,
        align_accumulator_init, align_finalize)
    from pulseportraiture_tpu.ops.fourier import rfft_sr
    from pulseportraiture_tpu.profiling import Stage, profile_stages

    npol, nchan = cube.shape[1], cube.shape[2]
    nbin = cube.shape[-1]
    dt_str = str(jnp.dtype(acc_dt))
    prec = _align_precision()
    # keep the cube in its PRODUCTION dtype (f32 from the loader/synth)
    # and convert inside the measured prefixes, exactly where
    # align_accumulate_archive converts — a precomputed acc_dt cube
    # would leave the (possibly ~100s of MB) widening pass
    # unattributed on CPU, where acc_dt is f64
    cube_j = jnp.asarray(cube)
    chunk = _align_chunk(cube.shape[0], ALIGN_DEVICE_CHUNK)

    def weights(r):
        return _align_weights_fn(dt_str)(
            jnp.asarray(r.phi, acc_dt), jnp.asarray(r.DM, acc_dt),
            jnp.asarray(r.nu_DM, acc_dt), jnp.asarray(P_s, acc_dt),
            jnp.asarray(freqs, acc_dt), jnp.asarray(noise, acc_dt),
            jnp.asarray(masks, acc_dt), jnp.asarray(r.scales, acc_dt))

    # arrays ship as ARGUMENTS, never jit-closed-over constants (XLA
    # would constant-fold the stage at compile time — the exp_breakdown
    # lesson, see scatter_stage_profile)
    @jax.jit
    def rot_chunk(cc, dd):
        cr, ci = rfft_sr(cc, precision=prec)
        rr, ri = _align_rotate_real(cr, ci, dd)
        return jnp.sum(rr) + jnp.sum(ri)

    def pad(a, m):
        return jnp.pad(a, ((0, chunk - m),) + ((0, 0),) * (a.ndim - 1))

    def rotate_prefix():
        r = fit_fn()
        delays, _ = weights(r)
        cd = jnp.asarray(cube_j, acc_dt)  # production widening pass
        tot = jnp.zeros((), acc_dt)
        for lo in range(0, cd.shape[0], chunk):
            cc, dd = cd[lo:lo + chunk], delays[lo:lo + chunk]
            m = cc.shape[0]
            if m != chunk:
                cc, dd = pad(cc, m), pad(dd, m)
            tot = tot + rot_chunk(cc, dd)
        return tot

    def accum_prefix():
        r = fit_fn()
        acc = align_accumulator_init(npol, nchan, nbin, acc_dt)
        return align_accumulate_archive(acc, cube_j, r.phi, r.DM,
                                        r.nu_DM, P_s, freqs, noise,
                                        masks, r.scales)

    def irfft_prefix():
        acc = accum_prefix()
        return align_finalize(acc, nbin)

    # host_sync piece: the d2h pull of a PRECOMPUTED finalized portrait
    # (everything before it is the irfft prefix)
    final_dev = jax.block_until_ready(irfft_prefix())

    stages = [
        Stage("fit", fit_fn, "prefix", lambda r: r.phi),
        Stage("rotate", rotate_prefix, "prefix"),
        Stage("accumulate", accum_prefix, "prefix", lambda a: a[0]),
        Stage("irfft", irfft_prefix, "prefix"),
        Stage("host_sync", lambda: np.asarray(final_dev), "piece"),
    ]
    return profile_stages(full_fn, stages, K=K, nrun=nrun)


def main():
    lane = sys.argv[1] if len(sys.argv) > 1 else "scatter"
    if lane == "scatter":
        from benchmarks import bench_scatter

        out = bench_scatter.run_bench(attrib_only=True)
    elif lane == "campaign":
        from benchmarks import bench_device_campaign

        out = bench_device_campaign.run_bench(attrib_only=True)
    elif lane == "align":
        from benchmarks import bench_align

        out = bench_align.run_bench(attrib_only=True)
    else:
        raise SystemExit(f"unknown lane {lane!r} "
                         "(scatter|campaign|align)")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
