"""Evolving Gaussian-component template building (ppgauss equivalent).

Parity target: reference ppgauss.DataPortrait (ppgauss.py:27-379):
initial per-profile component fit (auto single-Gaussian or interactive
GaussianSelector), iterative portrait fitting alternating with a
(phi, DM) convergence check that rotates the data between iterations,
JOIN un-rotation, and .gmodel/error-file output.

The template fitter is the JAX LM engine (fit/gauss.py); the
convergence check is the fused-Newton (phi, DM) portrait fit.  The
interactive GUI lives in viz/selector.py (host matplotlib); the
auto_gauss path used by headless pipelines is first-class here.
"""

import numpy as np

import jax.numpy as jnp

from ..utils.device import on_host
from ..config import default_model_code, scattering_alpha
from ..fit.gauss import fit_gaussian_portrait, fit_gaussian_profile
from ..fit.phase_shift import fit_phase_shift
from ..fit.portrait import FitFlags, fit_portrait
from ..io.gmodel import model_from_flat, read_gmodel, write_gmodel
from ..io.psrfits import noise_std_ps
from ..models.gaussian import gen_gaussian_profile
from ..ops.phasor import guess_fit_freq
from ..ops.rotation import rotate_portrait
from .portrait import DataPortrait as _BasePortrait


def portrait_fit_flags(ngauss, fixloc=False, fixwid=False, fixamp=False,
                       fixscat=True, fiducial_gaussian=False):
    """The portrait-layout fit flags (ppgauss.py:147-166): dc and every
    component's (loc, wid, amp) always vary; tau and the evolution
    moduli follow the fix* options; fiducial_gaussian pins the first
    component's loc evolution.  Single source of truth for
    make_gaussian_model AND the template factory (their flag semantics
    must not drift — the factory-vs-single-driver parity test gates
    it)."""
    flags = np.zeros(2 + 6 * ngauss, int)
    flags[0] = 1                       # dc
    flags[1] = int(not fixscat)        # tau
    for ig in range(ngauss):
        flags[2 + 6 * ig + 0] = 1                  # loc
        flags[2 + 6 * ig + 1] = int(not fixloc)    # mloc
        flags[2 + 6 * ig + 2] = 1                  # wid
        flags[2 + 6 * ig + 3] = int(not fixwid)    # mwid
        flags[2 + 6 * ig + 4] = 1                  # amp
        flags[2 + 6 * ig + 5] = int(not fixamp)    # mamp
    if fiducial_gaussian and ngauss:
        flags[2 + 1] = 0  # first component's loc evolution fixed
    return flags


def profile_to_portrait_params(profile_params):
    """[dc, tau, (loc, wid, amp)*g] -> [dc, tau, (loc, mloc, wid, mwid,
    amp, mamp)*g] with zero evolution slopes (ppgauss.py:147-156)."""
    profile_params = np.asarray(profile_params, float)
    ngauss = (len(profile_params) - 2) // 3
    out = np.zeros(2 + 6 * ngauss)
    out[:2] = profile_params[:2]
    for ig in range(ngauss):
        loc, wid, amp = profile_params[2 + 3 * ig: 5 + 3 * ig]
        out[2 + 6 * ig: 8 + 6 * ig] = [loc, 0.0, wid, 0.0, amp, 0.0]
    return out


class GaussPortrait(_BasePortrait):
    """DataPortrait specialized with make_gaussian_model (alias
    `DataPortrait` kept for ppgauss-style scripts)."""

    # -- initial profile fit ----------------------------------------------
    def select_ref_profile(self, nu_ref=None, bw_ref=None):
        """Mean profile of the (nu_ref, bw) band slice, or of the whole
        portrait (ppgauss.py:129-146).  Returns (profile, nu_ref)."""
        freqs = self.freqs[0]
        okc = self.ok_ichans
        if nu_ref is None:
            prof = self.portx.mean(axis=0)
            nu_ref = float(freqs[okc].mean())
        else:
            bw_ref = bw_ref or abs(self.bw) / 4.0
            sel = okc[np.abs(freqs[okc] - nu_ref) <= bw_ref / 2.0]
            if not len(sel):
                raise ValueError("no unzapped channels in the reference "
                                 "band slice")
            prof = self.port[sel].mean(axis=0)
        return np.asarray(prof, float), float(nu_ref)

    @on_host
    def fit_profile(self, profile=None, tau=0.0, fixscat=True,
                    auto_gauss=0.0, profile_fit_flags=None, show=True):
        """Fit Gaussian components to a single profile.  With
        auto_gauss != 0 (initial width guess [rot]) this runs
        non-interactively (the reference's auto_gauss path,
        ppgauss.py:450-487); otherwise it launches the interactive
        GaussianSelector GUI."""
        if profile is None:
            profile, _ = self.select_ref_profile()
        noise = float(noise_std_ps(profile))
        if auto_gauss:
            amp = float(profile.max())
            wid = float(auto_gauss)
            first = amp * np.asarray(gen_gaussian_profile(
                {"dc": 0.0, "locs": np.array([0.5]),
                 "wids": np.array([wid]), "amps": np.array([1.0]),
                 "mlocs": np.zeros(1), "mwids": np.zeros(1),
                 "mamps": np.zeros(1), "tau": 0.0, "alpha": 0.0},
                len(profile), scattered=False))
            loc = 0.5 + float(fit_phase_shift(profile, first, noise).phase)
            loc %= 1.0
            init = [0.0, tau, loc, wid, amp]
            fgp = fit_gaussian_profile(
                profile, init, noise, fit_flags=profile_fit_flags,
                fit_scattering=not fixscat, quiet=True)
            self.init_params = np.asarray(fgp.fitted_params)
            self.init_param_errs = np.asarray(fgp.fit_errs)
        else:
            from ..viz.selector import GaussianSelector

            sel = GaussianSelector(profile, noise, tau=tau,
                                   fixscat=fixscat, show=show)
            self.init_params = np.asarray(sel.fitted_params)
            self.init_param_errs = np.asarray(sel.fit_errs)
        self.ngauss = (len(self.init_params) - 2) // 3
        return self.init_params

    @on_host
    def auto_fit_profile(self, profile=None, max_ngauss=8, wid0=0.02,
                         rchi2_tol=0.1, tau=0.0, fixscat=True,
                         gauss_device=None, quiet=True):
        """Breadth-first multi-component auto fit (ISSUE 9): ALL
        ``ngauss in 1..max_ngauss`` trials — seeded by matching pursuit
        on the raw profile (fit/gauss.profile_trial_seeds) — are fit in
        ONE batched LM dispatch (or, on the host-serial oracle lane,
        one at a time on the same padded problems), and the best
        reduced chi2 is selected on host with the serial add-refit
        loop's acceptance rule.  Lane via gauss_device (None ->
        config.gauss_device tri-state).  This is the headless
        replacement for hand-sketching components in the GUI — the
        reference's only automatic path is single-Gaussian
        (ppgauss.py:450-487)."""
        max_ngauss = int(max_ngauss)
        if max_ngauss < 1:
            raise ValueError(
                f"auto_fit_profile needs max_ngauss >= 1 (got "
                f"{max_ngauss}): no trial component counts to fit")
        from ..fit.gauss import fit_profile_trials, use_gauss_device

        if profile is None:
            profile, _ = self.select_ref_profile()
        profile = np.asarray(profile, float)
        noise = float(noise_std_ps(profile))
        sel = fit_profile_trials(
            profile, max_ngauss, noise, wid0=wid0, tau=tau,
            fit_scattering=not fixscat, rchi2_tol=rchi2_tol,
            serial=not use_gauss_device(gauss_device))
        if sel is None:
            raise ValueError(
                f"auto_fit_profile: every trial fit of "
                f"{self.datafile!r} failed (non-finite chi2 for all "
                f"ngauss in 1..{max_ngauss}) — check the input profile "
                "and noise level")
        self.init_params = sel.params
        self.init_param_errs = sel.param_errs
        self.ngauss = sel.ngauss
        if not quiet:
            print(f"auto_fit_profile: {self.ngauss} components, "
                  f"red chi2 = {sel.red_chi2s[sel.index]:.2f}")
        return self.init_params

    # -- the main loop -----------------------------------------------------
    @on_host
    def make_gaussian_model(self, modelfile=None, ref_prof=(None, None),
                            tau=0.0, fixloc=False, fixwid=False,
                            fixamp=False, fixscat=True, fixalpha=True,
                            scattering_index=scattering_alpha,
                            model_code=default_model_code, niter=0,
                            fiducial_gaussian=False, auto_gauss=0.0,
                            writemodel=False, outfile=None,
                            writeerrfile=False, errfile=None,
                            model_name=None, residplot=None,
                            gauss_device=None, max_ngauss=8,
                            quiet=False):
        """Fit the evolving-Gaussian portrait model (reference
        ppgauss.py:62-245; same options).  Returns the fitted
        GaussianModel."""
        P = float(self.Ps[0])
        nbin = self.nbin
        njoin = len(getattr(self, "join_ichans", []))
        if modelfile:
            start_model = read_gmodel(modelfile, quiet=quiet)
            self.nu_ref = start_model.nu_ref
            model_code = start_model.code
            scattering_index = start_model.alpha
            from ..io.gmodel import model_to_flat

            init_portrait, flat_flags = model_to_flat(start_model)
            init_portrait = init_portrait.copy()
            init_portrait[1] *= nbin / P  # tau seconds -> bins
            self.ngauss = start_model.ngauss
            model_name = model_name or start_model.name
        else:
            profile, nu_ref = self.select_ref_profile(*ref_prof)
            self.nu_ref = nu_ref
            if not len(np.atleast_1d(getattr(self, "init_params", []))):
                self.auto_fit_profile(profile, wid0=auto_gauss or 0.02,
                                      max_ngauss=max_ngauss, tau=tau,
                                      fixscat=fixscat,
                                      gauss_device=gauss_device,
                                      quiet=quiet)
            init_portrait = profile_to_portrait_params(self.init_params)
        model_name = model_name or (str(self.datafile) + ".gmodel")
        self.model_name = model_name
        self.model_code = model_code

        flags = portrait_fit_flags(self.ngauss, fixloc=fixloc,
                                   fixwid=fixwid, fixamp=fixamp,
                                   fixscat=fixscat,
                                   fiducial_gaussian=fiducial_gaussian)
        self._flags_cache = flags

        join_params = None
        if njoin:
            join_params = (self.join_ichans,
                           np.asarray(self.join_params, float),
                           np.asarray(self.join_fit_flags, int))

        self.nu_fit = float(guess_fit_freq(jnp.asarray(self.freqsxs[0]),
                                           jnp.asarray(self.SNRsxs[0])))
        errs = np.where(self.noise_stds > 0, self.noise_stds,
                        np.median(self.noise_stds[self.ok_ichans]))
        x0 = init_portrait
        self.niter = int(niter)
        itern = 0
        converged = False
        while True:
            if not quiet:
                print(f"Fitting Gaussian model portrait... "
                      f"(iteration {itern})")
            fgp = fit_gaussian_portrait(
                self.port[self.ok_ichans], x0, scattering_index,
                errs[self.ok_ichans], flags, int(not fixalpha),
                self.freqsxs[0], self.nu_ref, model_code=model_code,
                join_params=join_params, P=P, quiet=True)
            self.fitted_params = np.asarray(fgp.fitted_params)
            self.fit_errs = np.asarray(fgp.fit_errs)
            self.portrait_red_chi2 = float(fgp.red_chi2)
            scattering_index = float(fgp.scattering_index)
            if njoin:
                self.join_params = list(np.asarray(fgp.join_fit, float))
            x0 = self.fitted_params
            self._rebuild_model(model_code, scattering_index, P)
            converged = self.check_convergence(efac=1.0, quiet=quiet)
            if writemodel:
                self.write_model(outfile=outfile, quiet=True)
            if writeerrfile:
                self.write_errfile(errfile=errfile, quiet=True)
            itern += 1
            if converged or itern > self.niter:
                break
            # rotate the *data* by the fitted residual (phi, DM)
            # (ppgauss.py:198-202)
            if not njoin:
                self.rotate_stuff(phase=self.phi, DM=self.DM,
                                  nu_ref=self.nu_fit)

        # JOIN un-rotation at the end (ppgauss.py:213-231)
        if njoin:
            for ii in range(njoin):
                jic = self.join_ichans[ii]
                phi_j = self.join_params[2 * ii]
                dDM_j = self.join_params[2 * ii + 1]
                self.port[jic] = np.asarray(rotate_portrait(
                    jnp.asarray(self.port[jic]), -phi_j, -dDM_j, P,
                    jnp.asarray(self.freqs[0][jic]), self.nu_ref))
            self._condense()

        self.scattering_index = scattering_index
        self.gaussian_model = self._to_gmodel(model_name, model_code,
                                              scattering_index,
                                              int(not fixalpha), flags, P)
        if residplot:
            from ..viz.plots import show_residual_plot

            show_residual_plot(self.port, np.asarray(self.model),
                               self.phases, self.freqs[0],
                               noise_stds=self.noise_stds,
                               weights=self.weights, show=False,
                               savefig=residplot)
        if not quiet:
            resid = self.portx - self.model[self.ok_ichans]
            print(f"\nResiduals mean: {resid.mean():.2e}")
            print(f"Residuals std:  {resid.std():.2e}")
            print(f"Data std:       "
                  f"{np.median(self.noise_stdsxs[0]):.2e}\n")
        return self.gaussian_model

    def _rebuild_model(self, model_code, alpha, P):
        from ..fit.gauss import gen_gaussian_portrait_flat

        self.model = np.asarray(gen_gaussian_portrait_flat(
            self.fitted_params, jnp.asarray(self.freqs[0]), self.nu_ref,
            self.nbin, alpha, code=model_code, P=P))
        self.modelx = self.model[self.ok_ichans]

    def _to_gmodel(self, name, code, alpha, fit_alpha, flags, P):
        params = self.fitted_params.copy()
        params[1] *= P / self.nbin  # tau bins -> seconds
        return model_from_flat(name, code, self.nu_ref, params, flags,
                               alpha, fit_alpha)

    def check_convergence(self, efac=1.0, quiet=False):
        """Fit (phi, DM) of the data against the current model:
        converged when both are within their errors (ppgauss.py:
        285-341; the reference's None-return defect on the mixed
        branch is fixed — this always returns a bool)."""
        portx = self.portx
        modelx = self.modelx
        njoin = len(getattr(self, "join_ichans", []))
        if njoin:
            portx = portx.copy()
            modelx = modelx.copy()
            P = float(self.Ps[0])
            for ii in range(njoin):
                jic = self.join_ichans[ii]
                okpos = np.searchsorted(self.ok_ichans, jic)
                okpos = okpos[(okpos < len(self.ok_ichans))
                              & (np.isin(jic, self.ok_ichans))]
                if not len(okpos):
                    continue
                phi_j = self.join_params[2 * ii]
                dDM_j = self.join_params[2 * ii + 1]
                fsel = self.freqsxs[0][okpos]
                portx[okpos] = np.asarray(rotate_portrait(
                    jnp.asarray(portx[okpos]), -phi_j, -dDM_j, P,
                    jnp.asarray(fsel), self.nu_ref))
                modelx[okpos] = np.asarray(rotate_portrait(
                    jnp.asarray(modelx[okpos]), -phi_j, -dDM_j, P,
                    jnp.asarray(fsel), self.nu_ref))
        res = fit_portrait(
            jnp.asarray(portx), jnp.asarray(modelx),
            jnp.asarray(self.noise_stdsxs[0]),
            jnp.asarray(self.freqsxs[0]), float(self.Ps[0]),
            nu_fit=self.nu_fit, nu_out=self.nu_fit,
            fit_flags=FitFlags(True, True, False, False, False))
        self.phi = float(res.phi)
        self.phierr = float(res.phi_err)
        self.DM = float(res.DM)
        self.DMerr = float(res.DM_err)
        self.red_chi2 = float(res.red_chi2)
        if not quiet:
            print(f" phase offset of {self.phi:.2e} +/- "
                  f"{self.phierr:.2e} [rot]")
            print(f" DM of {self.DM:.6e} +/- {self.DMerr:.2e} "
                  f"[cm**-3 pc]")
            print(f" red. chi**2 of {self.red_chi2:.2f}.")
        phase_ok = min(abs(self.phi), abs(1 - self.phi)) < \
            abs(self.phierr) * efac
        dm_ok = abs(self.DM) < abs(self.DMerr) * efac
        if phase_ok and dm_ok and not quiet:
            print("\nIteration converged.\n")
        return bool(phase_ok and dm_ok)

    # -- output ------------------------------------------------------------
    def write_model(self, outfile=None, quiet=False):
        """Write the fitted .gmodel (ppgauss.py:343-361; written after
        every iteration 'for safety' by make_gaussian_model)."""
        if not hasattr(self, "fitted_params"):
            raise RuntimeError("no fitted model yet")
        outfile = outfile or (str(self.datafile) + ".gmodel")
        model = self._to_gmodel(
            getattr(self, "model_name", outfile),
            getattr(self, "model_code", default_model_code),
            getattr(self, "scattering_index", scattering_alpha),
            0, self._current_flags(), float(self.Ps[0]))
        write_gmodel(model, outfile, quiet=quiet)
        return outfile

    def write_errfile(self, errfile=None, quiet=False):
        """Write the parameter errors as a .gmodel-grammar file
        (ppgauss.py:363-379)."""
        if not hasattr(self, "fit_errs"):
            raise RuntimeError("no fitted model yet")
        errfile = errfile or (str(self.datafile) + ".gmodel_errs")
        errs = self.fit_errs.copy()
        errs[1] *= float(self.Ps[0]) / self.nbin
        model = model_from_flat(
            getattr(self, "model_name", errfile) + "_errs",
            getattr(self, "model_code", default_model_code),
            self.nu_ref, errs, self._current_flags(),
            getattr(self, "scattering_index", scattering_alpha), 0)
        write_gmodel(model, errfile, quiet=quiet)
        return errfile

    def _current_flags(self):
        n = len(self.fitted_params)
        return getattr(self, "_flags_cache", np.ones(n, int))


# reference ppgauss scripts use the name DataPortrait
DataPortrait = GaussPortrait
