"""Diagnostic-plot smoke + behavior tests (Agg backend): the round-3
verdict noted viz was functional but thin — these lock the reference
behaviors show_portrait/show_stacked_profiles gained in round 4
(pplib.py:3652-3824): zero-weight compression of the side panels,
rvrsd, inverted flux axis, model overlays with per-profile fitting."""

import matplotlib

matplotlib.use("Agg", force=True)

import matplotlib.pyplot as plt
import numpy as np
import pytest

from pulseportraiture_tpu.viz.plots import (
    show_portrait,
    show_profiles,
    show_residual_plot,
    show_stacked_profiles,
)


@pytest.fixture(autouse=True)
def _close_all():
    yield
    plt.close("all")


def _port(nchan=16, nbin=64):
    x = (np.arange(nbin) + 0.5) / nbin
    prof = np.exp(-0.5 * ((x - 0.3) / 0.04) ** 2)
    scales = 1.0 + 0.5 * np.linspace(-1, 1, nchan)
    return scales[:, None] * prof[None, :]


def test_show_portrait_panels_and_zap_compression():
    port = _port()
    port[3] = 0.0  # zapped channel
    freqs = np.linspace(1300.0, 1500.0, len(port))
    phases = (np.arange(port.shape[1]) + 0.5) / port.shape[1]
    fig = show_portrait(port, phases, freqs, title="t", show=False)
    # image + colorbar + profile + flux panels
    assert len(fig.axes) == 4
    ax_f = next(a for a in fig.axes if a.get_xlabel() == "Flux Units"
                and a.get_ylabel())
    xs, ys = ax_f.lines[0].get_data()
    # zapped channel compressed out of the spectrum panel
    assert len(ys) == len(port) - 1
    assert not np.any(np.isclose(ys, freqs[3]))
    # flux axis inverted (reference convention: flux grows leftward)
    lo, hi = ax_f.get_xlim()
    assert lo > hi


def test_show_portrait_rvrsd_and_kwargs():
    port = _port()
    freqs = np.linspace(1300.0, 1500.0, len(port))
    fig = show_portrait(port, None, freqs, rvrsd=True, colorbar=False,
                        prof=False, fluxprof=False, show=False,
                        vmin=0.0, vmax=2.0)
    (ax,) = fig.axes
    im = ax.get_images()[0]
    assert im.get_clim() == (0.0, 2.0)
    # reversed frequency extent
    ext = im.get_extent()
    assert ext[2] > ext[3]


def test_show_stacked_profiles_model_overlay_and_fit():
    port = _port(nchan=12)
    rng = np.random.default_rng(0)
    data = np.roll(port, 3, axis=-1) * 1.7 + \
        0.01 * rng.standard_normal(port.shape)
    fig = show_stacked_profiles(data, model_profiles=port, fit=True,
                                freqs=np.linspace(1300., 1500., 12),
                                show=False)
    (ax,) = fig.axes
    # one dashed model + one solid data line per channel
    assert len(ax.lines) == 2 * 12
    dashed = [l for l in ax.lines if l.get_linestyle() == "--"]
    assert len(dashed) == 12
    # fit=True aligned+scaled the model onto the data: the residual of
    # the first (model, data) pair is noise-level, not the raw offset
    m, d = ax.lines[0].get_ydata(), ax.lines[1].get_ydata()
    assert np.abs(m - d).max() < 0.1 * np.ptp(data[0])
    # frequency tick labels present
    assert ax.get_yticklabels()[0].get_text() == "1300"


def test_show_portrait_fully_zapped_no_degenerate_limits():
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        fig = show_portrait(np.zeros((8, 32)), show=False)
    assert len(fig.axes) == 4


def test_show_profiles_and_residual_smoke():
    port = _port()
    fig = show_profiles([port[0], port[1]], labels=["a", "b"],
                        show=False)
    assert fig.axes[0].get_legend() is not None
    fig2 = show_residual_plot(port, port * 1.01,
                              noise_stds=np.full(len(port), 0.01),
                              show=False)
    assert len(fig2.axes) == 4
