"""Benchmark: batched wideband (phi, DM) portrait fits on one TPU chip
vs the single-core NumPy reference implementation (BASELINE.md config 2:
batch of synthetic archives at 512 chan x 2048 bin).

Measures the full fit from time-domain portraits — matmul real DFTs,
CCF phase seed, damped-Newton loop, covariance/packaging — through
fit_portrait_batch_fast (the complex-free TPU throughput path).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401  (x64 host config)
    from pulseportraiture_tpu.fit import fit_portrait_batch_fast
    from pulseportraiture_tpu.fit.reference_numpy import fit_portrait_numpy

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    NB, NCHAN, NBIN = 128, 512, 2048
    DTYPE = jnp.float32
    P = 0.003
    NU_FIT = 1500.0

    # --- synthesize the batch on device (f32) ---------------------------
    from __graft_entry__ import _synth_batch

    dFT, mFT, w, freqs, Ps, nus, nu_out, theta0 = _synth_batch(
        NB, NCHAN, NBIN, DTYPE
    )
    ports = jnp.fft.irfft(dFT, n=NBIN, axis=-1).astype(DTYPE)
    models = jnp.fft.irfft(mFT, n=NBIN, axis=-1).astype(DTYPE)
    noise = jnp.full((NB, NCHAN), 0.05, DTYPE)
    jax.block_until_ready(ports)

    def run():
        return fit_portrait_batch_fast(
            ports, models, noise, freqs, Ps, nus, max_iter=25
        )

    # warmup/compile; timing forces a host transfer per rep because
    # block_until_ready can return early under the tunneled TPU runtime
    res = run()
    _ = np.asarray(res.phi)

    nrep = 5
    t0 = time.perf_counter()
    for _ in range(nrep):
        res = run()
        _ = np.asarray(res.phi)
    t_tpu = (time.perf_counter() - t0) / nrep
    toas_per_sec = NB / t_tpu

    # --- single-core NumPy baseline on a few portraits ------------------
    ports_np = np.asarray(ports, np.float64)
    models_np = np.asarray(models, np.float64)
    freqs_np = np.asarray(freqs, np.float64)
    noise_np = np.full(NCHAN, 0.05)

    n_base = 3
    t0 = time.perf_counter()
    base_res = [
        fit_portrait_numpy(
            ports_np[i], models_np[i], noise_np, freqs_np, P, NU_FIT
        )
        for i in range(n_base)
    ]
    t_np = (time.perf_counter() - t0) / n_base
    base_toas_per_sec = 1.0 / t_np

    # --- accuracy gate: |dphi| vs NumPy ref on the same portraits -------
    dphi = max(
        abs(float(res.phi[i]) - _ref_phi_at(base_res[i], float(res.nu_DM[i]), P))
        for i in range(n_base)
    )

    out = {
        "metric": "wideband (phi,DM) portrait fits, 512ch x 2048bin",
        "value": round(toas_per_sec, 2),
        "unit": "TOAs/sec",
        "vs_baseline": round(toas_per_sec / base_toas_per_sec, 1),
        "baseline_toas_per_sec": round(base_toas_per_sec, 3),
        "batch": NB,
        "device": str(dev),
        "dtype": "float32" if on_tpu else str(np.dtype("float32")),
        "max_dphi_vs_numpy": float(f"{dphi:.2e}"),
        "accuracy_gate_1e-4": bool(dphi < 1e-4),
    }
    print(json.dumps(out))


def _ref_phi_at(ref, nu, P):
    """Transform the NumPy reference phi (at NU_FIT=1500) to nu."""
    from pulseportraiture_tpu.config import Dconst

    phi = ref["phi"] + (Dconst * ref["DM"] / P) * (nu**-2.0 - 1500.0**-2.0)
    return ((phi + 0.5) % 1.0) - 0.5


if __name__ == "__main__":
    main()
