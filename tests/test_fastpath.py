"""Fast-path validation: matmul real DFT parity with numpy's FFT, the
XLA harmonic-moment forms against each other, and end-to-end
fit_portrait_batch_fast parity with the complex-arithmetic
fit_portrait_batch.  (The Pallas moment kernel this file once covered
was deleted in round 4 — it measured slower than XLA's fused
reductions; see benchmarks/BENCHMARKS.md.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import fit_portrait_batch, fit_portrait_batch_fast
from pulseportraiture_tpu.fit.portrait import _moments_real_xla, _moments_xla
from pulseportraiture_tpu.ops.fourier import irfft_mm, rfft_mm
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003
NCHAN, NBIN = 32, 512
FREQS = jnp.asarray(np.linspace(1200.0, 1999.0, NCHAN) + 0.5)


# --- matmul DFT ----------------------------------------------------------


@pytest.mark.parametrize("n", [64, 255, 1024])
def test_rfft_mm_matches_numpy(rng, n):
    x = jnp.asarray(rng.normal(size=(5, n)))
    Xr, Xi = rfft_mm(x)
    ref = np.fft.rfft(np.asarray(x))
    assert np.allclose(Xr, ref.real, atol=1e-10 * n)
    assert np.allclose(Xi, ref.imag, atol=1e-10 * n)


@pytest.mark.parametrize("n", [64, 255, 1024])
def test_irfft_mm_roundtrip(rng, n):
    x = jnp.asarray(rng.normal(size=(3, n)))
    Xr, Xi = rfft_mm(x)
    back = irfft_mm(Xr, Xi, n)
    assert np.allclose(back, x, atol=1e-11 * n)


# --- XLA moment forms ----------------------------------------------------


def test_moments_real_vs_complex(rng):
    """Split-real XLA moments == complex XLA moments (f64)."""
    nchan, nharm = 16, 129
    X = jnp.asarray(rng.normal(size=(nchan, nharm)) + 1j * rng.normal(size=(nchan, nharm)))
    t = jnp.asarray(rng.uniform(-0.5, 0.5, nchan))
    Cc, C1c, C2c = _moments_xla(t, X)
    Cr, C1r, C2r = _moments_real_xla(t, X.real, X.imag)
    assert np.allclose(Cc, Cr)
    assert np.allclose(C1c, C1r)
    assert np.allclose(C2c, C2r)


# --- end-to-end fast-path parity ----------------------------------------


def _batch(key, nb=4):
    model = default_test_model(nu_ref=1500.0)
    keys = jax.random.split(key, nb)
    phis = np.linspace(-0.2, 0.25, nb)
    dms = np.linspace(-2e-3, 3e-3, nb)
    ports, models, stds = [], [], []
    for k, phi, dm in zip(keys, phis, dms):
        pb = fake_portrait(k, model, FREQS, NBIN, P, phi=phi, DM=dm, noise_std=0.05)
        ports.append(pb.port)
        models.append(pb.model_port)
        stds.append(pb.noise_stds)
    return (jnp.stack(ports), jnp.stack(models), jnp.stack(stds)), phis, dms


def test_fast_batch_matches_reference(key):
    (ports, models, stds), phis, dms = _batch(key)
    a = fit_portrait_batch(ports, models, stds, FREQS, P, 1500.0)
    b = fit_portrait_batch_fast(ports, models, stds, FREQS, P, 1500.0)
    assert np.allclose(a.phi, b.phi, atol=1e-10)
    assert np.allclose(a.DM, b.DM, atol=1e-10)
    assert np.allclose(a.phi_err, b.phi_err, rtol=1e-6)
    assert np.allclose(a.DM_err, b.DM_err, rtol=1e-6)
    assert np.allclose(a.snr, b.snr, rtol=1e-8)
    assert np.allclose(a.chi2, b.chi2, rtol=1e-6)
    assert np.allclose(a.nu_DM, b.nu_DM, rtol=1e-8)
    # the fast path must still recover the injections
    assert np.abs(np.asarray(b.phi) - phis).max() < 1e-3


def test_fast_batch_shared_model(key):
    """A shared 2-D template gives the same answers as per-batch
    copies of it."""
    (ports, models, stds), phis, dms = _batch(key)
    shared = models[0]
    a = fit_portrait_batch_fast(
        ports, jnp.broadcast_to(shared, ports.shape), stds, FREQS, P,
        1500.0)
    b = fit_portrait_batch_fast(ports, shared, stds, FREQS, P, 1500.0)
    assert np.allclose(a.phi, b.phi, atol=1e-12)
    assert np.allclose(a.DM, b.DM, atol=1e-12)
    assert np.allclose(a.snr, b.snr, rtol=1e-10)


def test_fast_batch_masked_channels(key):
    (ports, models, stds), phis, dms = _batch(key)
    mask = jnp.ones(ports.shape[:2])
    mask = mask.at[:, ::5].set(0.0)
    a = fit_portrait_batch(
        ports, models, stds, FREQS, P, 1500.0, chan_masks=mask
    )
    b = fit_portrait_batch_fast(
        ports, models, stds, FREQS, P, 1500.0, chan_masks=mask
    )
    assert np.allclose(a.phi, b.phi, atol=1e-10)
    assert np.allclose(a.DM, b.DM, atol=1e-10)


def test_fast_batch_routes_scattering_to_real_lane():
    """Since round 3 fit_portrait_batch_fast no longer rejects
    scattering work: tau/alpha flags and fixed nonzero tau seeds route
    to the complex-free _cgh_scatter lane (and an IR kernel with
    use_scatter=False explicitly forced off still raises)."""
    from pulseportraiture_tpu.fit import FitFlags

    args = (jnp.zeros((1, 4, 64)), jnp.zeros((1, 4, 64)),
            jnp.ones((1, 4)), jnp.linspace(1000.0, 1100.0, 4), P, 1050.0)
    r = fit_portrait_batch_fast(
        *args, fit_flags=FitFlags(True, True, False, True, False))
    assert r.phi.shape == (1,)
    theta0 = jnp.zeros((1, 5)).at[0, 3].set(1.0e-4)
    r2 = fit_portrait_batch_fast(*args, theta0=theta0)
    assert r2.phi.shape == (1,)
    with pytest.raises(ValueError, match="instrumental response"):
        fit_portrait_batch_fast(
            *args, use_scatter=False,
            ir_FT=np.ones((4, 33), complex))
