"""Device-placement helpers."""

import contextlib
import functools

import jax


def host_compute():
    """Context manager pinning jnp ops to the host CPU backend when the
    session's default backend is an accelerator.

    Used for small offline computations that need complex arithmetic
    (rotation phasors, 1-D FFTFIT guesses, template generation): some
    TPU runtimes cannot compile complex FFTs at all, and a host round
    trip is cheaper than an accelerator dispatch for these sizes
    anyway.
    """
    if jax.default_backend() == "cpu":
        return contextlib.nullcontext()
    return jax.default_device(jax.local_devices(backend="cpu")[0])


_compile_cache_dir = None  # what enable_compile_cache last applied


def enable_compile_cache(path=None):
    """Route jax's persistent compilation cache to ``path`` (None =
    ``config.compile_cache_dir``), so fleet restarts stop re-paying
    the per-(bucket shape x device) trace + XLA compile cold start
    (ROADMAP item 5).  The thresholds are zeroed so even the small
    CPU-test programs cache — campaign bucket programs are far above
    any default cutoff anyway.

    Returns the applied directory, or None when unconfigured.
    Idempotent: re-applying the same path is free; the streaming
    executor calls this on every construction so a config flip (or
    PPT_COMPILE_CACHE) takes effect without restart."""
    global _compile_cache_dir
    from .. import config

    if path is None:
        path = getattr(config, "compile_cache_dir", None)
    if not path:
        # unconfigure: a flip BACK to off (PPT_COMPILE_CACHE=off over a
        # config default) must stop routing compiles to the old dir,
        # not silently keep the previous cache
        if _compile_cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", None)
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:
                pass
            _compile_cache_dir = None
        return None
    path = str(path)
    if path == _compile_cache_dir:
        return path
    import os

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every program: the defaults skip fast-compiling entries,
    # which is exactly the K-small-shapes lattice a campaign compiles
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except AttributeError:
            pass  # older jax: threshold knob absent, cache still works
    try:
        # jax initializes its cache singleton at most once per process;
        # a dir configured AFTER the first compile would be silently
        # ignored without this reset
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass
    _compile_cache_dir = path
    return path


def on_host(fn):
    """Decorator: run the whole function under host_compute().

    For offline entry points (template building, normalization, zap
    proposals) whose math uses complex phasors/FFTs — keeps them usable
    in sessions whose default backend cannot compile complex types."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with host_compute():
            return fn(*args, **kwargs)
    return wrapper
