"""Spline (PCA + B-spline) model files.

Two on-disk forms:
- the reference-compatible pickle `[modelname, source, datafile,
  mean_prof, eigvec, tck]` (reference ppspline.py:219-244, read at
  pplib.py:3060-3096), readable/writable for migration;
- a versioned `.npz` (preferred): same content, no pickle execution
  risk, forward-compatible via a format-version key.

`SplineModel.portrait(freqs, nbin)` evaluates through the jittable
B-spline generator (models/spline.py).
"""

import pickle
from dataclasses import dataclass, field

import numpy as np

from ..models.spline import gen_spline_portrait

NPZ_VERSION = 1


@dataclass
class SplineModel:
    modelname: str
    source: str
    datafile: str
    mean_prof: np.ndarray  # (nbin,)
    eigvec: np.ndarray     # (nbin, ncomp)
    tck: tuple             # (t (nknot,), c (ncomp, ncoef), k)
    extra: dict = field(default_factory=dict)

    @property
    def nbin(self):
        return len(self.mean_prof)

    @property
    def ncomp(self):
        return self.eigvec.shape[1] if self.eigvec.ndim == 2 else 0

    def freq_range(self):
        t = np.asarray(self.tck[0], float)
        return float(t.min()), float(t.max())

    def portrait(self, freqs, nbin=None):
        """Model portrait at the given frequencies (and optionally a
        different nbin, via Fourier resampling + half-bin fix)."""
        return np.asarray(gen_spline_portrait(
            self.mean_prof, np.atleast_1d(np.asarray(freqs, float)),
            self.eigvec, self.tck, nbin=nbin))


def _normalize_tck(tck):
    t, c, k = tck
    t = np.asarray(t, float)
    c = np.asarray([np.asarray(ci, float) for ci in c]) \
        if isinstance(c, (list, tuple)) else np.asarray(c, float)
    if c.ndim == 1:
        c = c[None]
    return (t, c, int(k))


def write_spline_model(model, filename, quiet=False):
    """Write a SplineModel; `.spl` extension -> reference-compatible
    pickle, anything else -> versioned npz."""
    t, c, k = _normalize_tck(model.tck)
    if str(filename).endswith(".spl"):
        payload = [model.modelname, model.source, model.datafile,
                   np.asarray(model.mean_prof), np.asarray(model.eigvec),
                   (t, [ci for ci in c], k)]
        with open(filename, "wb") as f:
            pickle.dump(payload, f, protocol=2)
    else:
        np.savez(
            filename, format_version=NPZ_VERSION,
            modelname=model.modelname, source=model.source,
            datafile=model.datafile,
            mean_prof=np.asarray(model.mean_prof),
            eigvec=np.asarray(model.eigvec),
            tck_t=t, tck_c=c, tck_k=k)
    if not quiet:
        print(f"{filename} written.")


def read_spline_model(modelfile, quiet=False):
    """Read either on-disk form -> SplineModel (reference
    read_spline_model, pplib.py:3060-3096)."""
    if not quiet:
        print(f"Reading model from {modelfile}...")
    name = str(modelfile)
    if name.endswith((".npz", ".ppspl")):
        z = np.load(modelfile, allow_pickle=False)
        return SplineModel(
            modelname=str(z["modelname"]), source=str(z["source"]),
            datafile=str(z["datafile"]), mean_prof=z["mean_prof"],
            eigvec=z["eigvec"],
            tck=(z["tck_t"], z["tck_c"], int(z["tck_k"])))
    with open(modelfile, "rb") as f:
        try:
            payload = pickle.load(f)
        except UnicodeDecodeError:
            f.seek(0)
            payload = pickle.load(f, encoding="latin1")
    modelname, source, datafile, mean_prof, eigvec, tck = payload
    return SplineModel(
        modelname=str(modelname), source=str(source),
        datafile=str(datafile), mean_prof=np.asarray(mean_prof, float),
        eigvec=np.asarray(eigvec, float), tck=_normalize_tck(tck))


def spline_model_coords(model, freqs):
    """Projected curve coordinates at the given frequencies (reference
    get_spline_model_coords, pplib.py:3099-3123)."""
    from ..models.spline import bspline_eval

    return np.asarray(bspline_eval(
        np.atleast_1d(np.asarray(freqs, float)),
        _normalize_tck(model.tck)))
