"""User-facing box bounds on the portrait fit (VERDICT r4 #6).

Reference capability: fit_portrait_full's TNC `bounds`
(pptoaslib.py:1039-1060, plumbed from pptoas.py:503-513).  Here the
box is enforced by projected (clipped) damped-Newton steps in the
shared loop, with TNC's return-code vocabulary in bounds mode: a fit
converging ON an active bound reports 0 (LOCALMINIMUM — the projected
gradient vanishes), interior convergence reports 1 (CONVERGED);
without bounds the historical codes are unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import FitFlags, fit_portrait
from pulseportraiture_tpu.fit.portrait import (fit_portrait_batch,
                                               fit_portrait_batch_fast)
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

NCHAN, NBIN, P = 32, 512, 0.003
FREQS = jnp.asarray(np.linspace(1200.0, 1999.0, NCHAN) + 0.5,
                    jnp.float32)
WIDE = np.array([[-0.5, 0.5], [-1.0, 1.0], [-1.0, 1.0],
                 [-1.0, 1.0], [-10.0, 10.0]])


@pytest.fixture(scope="module")
def data():
    model = default_test_model(1500.0)
    return fake_portrait(jax.random.PRNGKey(7), model, FREQS, NBIN, P,
                         phi=0.04, DM=0.005, noise_std=0.05,
                         dtype=jnp.float32)


def _args(d):
    return (d.port[None], d.model_port[None], d.noise_stds[None],
            FREQS, P, 1500.0)


def test_interior_bounds_do_not_change_fit(data):
    r0 = fit_portrait_batch_fast(*_args(data))
    r1 = fit_portrait_batch_fast(*_args(data), bounds=WIDE)
    assert abs(float(r1.phi[0]) - float(r0.phi[0])) < 1e-7
    assert abs(float(r1.DM[0]) - float(r0.DM[0])) < 1e-9
    # TNC vocabulary in bounds mode: interior convergence -> 1
    assert int(r0.return_code[0]) == 0
    assert int(r1.return_code[0]) == 1


def test_active_bound_clamps_and_reports_rc0(data):
    """A DM box excluding the optimum pins DM exactly at the nearer
    bound and reports 0 (LOCALMINIMUM: |projected g| ~= 0) — the TNC
    bound-hit semantics."""
    r0 = fit_portrait_batch_fast(*_args(data))
    DMfit = float(r0.DM[0])
    tight = WIDE.copy()
    tight[1] = [DMfit - 0.01, DMfit - 0.002]
    r = fit_portrait_batch_fast(*_args(data), bounds=tight)
    assert float(r.DM[0]) == pytest.approx(DMfit - 0.002, abs=1e-9)
    assert int(r.return_code[0]) == 0
    # phi still converges to its (slightly shifted) optimum, errors
    # finite
    assert np.isfinite(float(r.phi_err[0]))
    # the complex engine enforces the same box with the same code
    rc = fit_portrait_batch(*_args(data), bounds=tight)
    assert float(rc.DM[0]) == pytest.approx(DMfit - 0.002, abs=1e-7)
    assert int(rc.return_code[0]) == 0
    # and the single-fit wrapper
    rs = fit_portrait(data.port, data.model_port, data.noise_stds,
                      FREQS, P, nu_fit=1500.0, bounds=tight)
    assert float(rs.DM) == pytest.approx(DMfit - 0.002, abs=1e-7)


def test_per_element_bounds(data):
    r0 = fit_portrait_batch_fast(*_args(data))
    DMfit = float(r0.DM[0])
    tight = WIDE.copy()
    tight[1] = [DMfit - 0.01, DMfit - 0.002]
    ports = jnp.tile(data.port[None], (2, 1, 1))
    noise = jnp.tile(data.noise_stds[None], (2, 1))
    r = fit_portrait_batch_fast(ports, data.model_port, noise, FREQS,
                                P, 1500.0,
                                bounds=np.stack([tight, WIDE]))
    assert float(r.DM[0]) == pytest.approx(DMfit - 0.002, abs=1e-9)
    assert float(r.DM[1]) == pytest.approx(DMfit, abs=1e-7)
    assert int(r.return_code[0]) == 0
    assert int(r.return_code[1]) == 1


def test_infeasible_seed_projected_into_box(data):
    """A theta0 outside the box is projected in (TNC behavior), not
    carried along."""
    tight = WIDE.copy()
    tight[1] = [0.1, 0.2]  # far above any real DM here
    th0 = np.zeros((1, 5), np.float32)
    th0[0, 1] = 5.0  # infeasible seed
    r = fit_portrait_batch_fast(*_args(data), bounds=tight,
                                theta0=jnp.asarray(th0))
    assert 0.1 - 1e-9 <= float(r.DM[0]) <= 0.2 + 1e-9


@pytest.mark.slow
def test_scatter_lane_tau_upper_bound():
    """The scattering lane honors a log10-tau upper bound: tau pins at
    the bound with rc 0."""
    model = default_test_model(1500.0)
    d = fake_portrait(jax.random.PRNGKey(3), model, FREQS, NBIN, P,
                      tau=2e-4, alpha=-4.0, noise_std=0.01,
                      dtype=jnp.float32)
    th0 = np.zeros((1, 5), np.float32)
    th0[0, 3] = np.log10(0.5 / NBIN)
    th0[0, 4] = -4.0
    flags = FitFlags(True, True, False, True, False)
    kw = dict(fit_flags=flags, theta0=jnp.asarray(th0), log10_tau=True,
              max_iter=60)
    args = (d.port[None], d.model_port[None], d.noise_stds[None],
            FREQS, P, 1500.0)
    r0 = fit_portrait_batch_fast(*args, **kw)
    ltau = float(np.log10(float(r0.tau[0])))
    b = np.full((5, 2), (-np.inf, np.inf))
    b[3, 1] = ltau - 0.1
    b[4] = [-10.0, 10.0]
    r1 = fit_portrait_batch_fast(*args, bounds=b, **kw)
    assert float(np.log10(float(r1.tau[0]))) == pytest.approx(
        ltau - 0.1, abs=1e-5)
    assert int(r1.return_code[0]) == 0


@pytest.mark.slow
def test_gettoas_bounds_plumbing(tmp_path):
    """bounds reach the fits through GetTOAs: a DM box excluding the
    injected dDM pins every subint's DM at the bound with rc 0, and
    bad shapes/orderings are rejected."""
    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.pipeline import GetTOAs
    from pulseportraiture_tpu.synth import make_fake_pulsar
    from pulseportraiture_tpu.utils.mjd import MJD

    PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4",
           "DECJ": "-11:34:54.6", "P0": 0.004074, "PEPOCH": 55000.0,
           "DM": 3.139}
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    path = str(tmp_path / "ep.fits")
    make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                     nbin=256, nu0=1500.0, bw=800.0, tsub=60.0,
                     dDM=3e-4, start_MJD=MJD(55100, 0.1),
                     noise_stds=0.08, dedispersed=False, quiet=True,
                     rng=5)
    gt0 = GetTOAs([path], gmodel, quiet=True)
    gt0.get_TOAs(quiet=True, max_iter=25)
    free_DM = float(gt0.DMs[0][0])
    cap = free_DM - 2e-4
    b = np.full((5, 2), (-np.inf, np.inf))
    b[1, 1] = cap
    gt = GetTOAs([path], gmodel, quiet=True)
    gt.get_TOAs(quiet=True, max_iter=25, bounds=b)
    for isub in gt.ok_isubs[0]:
        assert float(gt.DMs[0][isub]) <= cap * (1 + 1e-12)
        assert int(gt.rcs[0][isub]) == 0
    with pytest.raises(ValueError):
        gt.get_TOAs(quiet=True, bounds=np.zeros((4, 2)))
    bad = np.full((5, 2), (-np.inf, np.inf))
    bad[1] = [1.0, 0.0]
    with pytest.raises(ValueError):
        gt.get_TOAs(quiet=True, bounds=bad)


def test_cli_bound_parsing():
    from pulseportraiture_tpu.cli.pptoas import parse_bounds

    assert parse_bounds([]) is None
    b = parse_bounds(["dm:0.1,0.2", "tau:None,-1.3", "alpha:-10,10"])
    assert b[1, 0] == 0.1 and b[1, 1] == 0.2
    assert b[3, 0] == -np.inf and b[3, 1] == -1.3
    assert b[4, 0] == -10 and b[4, 1] == 10
    assert b[0, 0] == -np.inf and b[0, 1] == np.inf
    with pytest.raises(SystemExit):
        parse_bounds(["zeta:0,1"])
    with pytest.raises(SystemExit):
        parse_bounds(["dm:nope"])


def test_bounds_cache_no_collision_with_unbounded(data):
    """Regression (review r5): False == 0 in Python, so a boolean
    no-bounds sentinel collided with per-element bounds (axis 0) in
    the lru_cache key — the cached unbounded program was returned for
    a bounded call (vmap arity crash) and vice versa.  Same axis
    config, all three orders."""
    args1 = (jnp.tile(data.port[None], (2, 1, 1)), data.model_port,
             jnp.tile(data.noise_stds[None], (2, 1)), FREQS, P, 1500.0)
    r_free = fit_portrait_batch_fast(*args1)
    r_pe = fit_portrait_batch_fast(*args1,
                                   bounds=np.stack([WIDE, WIDE]))
    r_free2 = fit_portrait_batch_fast(*args1)
    assert abs(float(r_pe.DM[0]) - float(r_free.DM[0])) < 1e-9
    assert float(r_free2.DM[0]) == float(r_free.DM[0])


def test_bounds_never_clip_fixed_parameters(data):
    """Regression (review r5): a box on a NON-fitted parameter must
    not move its held value (reference TNC only bounds fitted
    parameters) — a gm:0.5,1 bound without fit_GM used to clip the
    fixed GM seed from 0 to 0.5 and silently shift phi/DM."""
    r0 = fit_portrait_batch_fast(*_args(data))
    b = np.full((5, 2), (-np.inf, np.inf))
    b[2] = [0.5, 1.0]  # GM is not fitted (default flags)
    r1 = fit_portrait_batch_fast(*_args(data), bounds=b)
    assert float(r1.GM[0]) == 0.0
    assert abs(float(r1.phi[0]) - float(r0.phi[0])) < 1e-7
    assert abs(float(r1.DM[0]) - float(r0.DM[0])) < 1e-9
