"""Device-placement helpers."""

import contextlib
import functools

import jax


def host_compute():
    """Context manager pinning jnp ops to the host CPU backend when the
    session's default backend is an accelerator.

    Used for small offline computations that need complex arithmetic
    (rotation phasors, 1-D FFTFIT guesses, template generation): some
    TPU runtimes cannot compile complex FFTs at all, and a host round
    trip is cheaper than an accelerator dispatch for these sizes
    anyway.
    """
    if jax.default_backend() == "cpu":
        return contextlib.nullcontext()
    return jax.default_device(jax.local_devices(backend="cpu")[0])


_compile_cache_dir = None  # what enable_compile_cache last applied


def enable_compile_cache(path=None):
    """Route jax's persistent compilation cache to ``path`` (None =
    ``config.compile_cache_dir``), so fleet restarts stop re-paying
    the per-(bucket shape x device) trace + XLA compile cold start
    (ROADMAP item 5).  The thresholds are zeroed so even the small
    CPU-test programs cache — campaign bucket programs are far above
    any default cutoff anyway.

    Returns the applied directory, or None when unconfigured.
    Idempotent: re-applying the same path is free; the streaming
    executor calls this on every construction so a config flip (or
    PPT_COMPILE_CACHE) takes effect without restart."""
    global _compile_cache_dir
    from .. import config

    if path is None:
        path = getattr(config, "compile_cache_dir", None)
    if not path:
        # unconfigure: a flip BACK to off (PPT_COMPILE_CACHE=off over a
        # config default) must stop routing compiles to the old dir,
        # not silently keep the previous cache
        if _compile_cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", None)
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:
                pass
            _compile_cache_dir = None
        return None
    path = str(path)
    if path == _compile_cache_dir:
        return path
    import os

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every program: the defaults skip fast-compiling entries,
    # which is exactly the K-small-shapes lattice a campaign compiles
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except AttributeError:
            pass  # older jax: threshold knob absent, cache still works
    try:
        # jax initializes its cache singleton at most once per process;
        # a dir configured AFTER the first compile would be silently
        # ignored without this reset
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass
    _compile_cache_dir = path
    return path


def warmup_from_manifest(manifest_path, modelfile=None, devices=None,
                         nsub_batch=64, tracer=None, quiet=True,
                         max_iter=25, fit_scat=False, log10_tau=True,
                         scat_guess=None, print_flux=False,
                         nu_ref_DM=None):
    """AOT warmup pass (ROADMAP item 5's tail): lower + compile the
    fused fit programs for every dispatch shape recorded in a PRIOR
    run's telemetry trace, before a server starts taking traffic.

    R9 run manifests already record every shape a campaign dispatched
    (the ``dispatch`` events' ``shape`` strings), so past traces ARE
    the shape manifest: each distinct shape is parsed back to its
    bucket geometry (pipeline/stream.parse_shape_key), a synthetic
    bucket of that geometry is built, and ONE padded dispatch runs
    through the REAL launch path per (shape x device) — jit compiles
    per shape/dtype/placement, not values, so the compiled programs
    are exactly the ones real traffic will hit (and they land in the
    persistent compile cache when ``config.compile_cache_dir`` is
    set).  ``modelfile`` shapes the warmup template (its harmonic
    window feeds the compiled program class on fast-fit backends);
    without one a synthetic smooth profile is used.  The remaining
    fit options must match the serving workload (they ride the program
    cache keys); warmup assumes nonzero DM guesses and
    not-dedispersed-on-disk archives — a dedispersed archive still
    pays its own first compile.

    Narrowband (flagless) shapes are skipped with a warning — their
    launch path is driver-local.  Returns the ``[(shape, device_index)]``
    list actually compiled; a server feeds it into the executor's warm
    set so the serve trace records zero cold dispatches for manifest
    shapes (the before/after gate)."""
    import time

    import numpy as np

    from ..pipeline import stream as S
    from ..telemetry import NULL_TRACER, load_trace, log

    tracer = NULL_TRACER if tracer is None else tracer
    _, events = load_trace(manifest_path)
    shapes, seen = [], set()
    for ev in events:
        if ev.get("type") == "dispatch":
            s = ev.get("shape")
            if s and s not in seen:
                seen.add(s)
                shapes.append(s)
    devices = S.resolve_stream_devices(devices)

    # tau seeding resolution mirroring make_wideband_lane
    if scat_guess is not None and not isinstance(scat_guess, str):
        tau_mode = "explicit"
        tau_args = tuple(float(v) for v in scat_guess)
    elif fit_scat and scat_guess == "auto":
        tau_mode, tau_args = "auto", (0.0, 1.0, 0.0)
    elif fit_scat:
        tau_mode, tau_args = "neutral", (0.0, 1.0, 0.0)
    else:
        tau_mode, tau_args = "none", (0.0, 1.0, 0.0)
    if not fit_scat:
        log10_tau = False
    from ..ops.decode import PACKED_BITS as packed_bits

    wire = {"i16": np.int16, "u8": np.uint8, "i8": np.uint8,
            "f32": np.float32, "p1": np.uint8, "p2": np.uint8,
            "p4": np.uint8}

    rng = np.random.default_rng(0)
    warmed = []
    t_all = time.perf_counter()
    for shape in shapes:
        try:
            spec = S.parse_shape_key(shape)
        except ValueError as e:
            log(f"warmup: skipping {shape!r}: {e}", level="warn")
            continue
        if spec["flags"] is None:
            log(f"warmup: skipping narrowband shape {shape!r} (only "
                "the wideband launch path is warmed)", level="warn")
            continue
        nchan, nbin = spec["nchan"], spec["nbin"]
        freqs = np.linspace(1400.0, 1600.0, nchan) if nchan > 1 \
            else np.array([1500.0])
        modelx = None
        if modelfile:
            try:
                from ..pipeline.models import TemplateModel
                modelx = np.asarray(TemplateModel(
                    modelfile, quiet=True).portrait(freqs, nbin,
                                                    P=0.003))
            except Exception as e:
                log(f"warmup: template portrait failed for {shape!r} "
                    f"({e}); using a synthetic profile", level="warn")
        if modelx is None:
            ph = np.arange(nbin) / nbin
            prof = np.exp(-0.5 * ((ph - 0.3) / 0.02) ** 2)
            modelx = np.broadcast_to(prof, (nchan, nbin)).copy()

        if spec["raw_code"] in packed_bits \
                and (nchan * nbin * packed_bits[spec["raw_code"]]) \
                % 8 != 0:
            log(f"warmup: skipping {shape!r} (sub-byte plane does "
                "not byte-align)", level="warn")
            continue
        for idev, dev in enumerate(devices):
            b = S._Bucket(freqs, nbin, modelx, spec["flags"],
                          kind=spec["kind"],
                          raw_code=spec["raw_code"],
                          pol_sum=spec["pol_sum"],
                          col_scaled=spec.get("col_scaled", False))
            # ONE row; _launch pads to nsub_batch — the real batch
            # shape class.  Values are arbitrary (compiles key on
            # shape/dtype); the DM guess is NONZERO so the general
            # seed-derotation program compiles, matching real archives
            if spec["kind"] == "raw":
                nbit = packed_bits.get(spec["raw_code"])
                if nbit is not None:
                    # packed payload rows: the byte-aligned pol plane
                    plane_bytes = nchan * nbin * nbit // 8
                    rshape = ((2, plane_bytes) if spec["pol_sum"]
                              else (plane_bytes,))
                else:
                    rshape = ((2, nchan, nbin) if spec["pol_sum"]
                              else (nchan, nbin))
                cshape = (2, nchan) if spec["pol_sum"] else (nchan,)
                if spec["raw_code"] == "f32":
                    b.raw.append(rng.standard_normal(rshape)
                                 .astype(np.float32))
                else:
                    b.raw.append(rng.integers(1, 100, size=rshape)
                                 .astype(wire[spec["raw_code"]]))
                b.scl.append(np.ones(cshape, np.float32))
                b.offs.append(np.zeros(cshape, np.float32))
                if spec.get("col_scaled"):
                    b.tscal.append(0.5)
                    b.tzero.append(1.0)
                b.DM_guess.append(1.0)
                b.dedisp.append((0.0, 0.0))
            else:
                b.ports.append(rng.standard_normal((nchan, nbin)))
                b.noise.append(np.ones(nchan))
                b.nu_fits.append(float(freqs.mean()))
                th = np.zeros(5)
                th[1] = 1.0
                b.theta0.append(th)
            b.masks.append(np.ones(nchan))
            b.Ps.append(0.003)
            b.owners.append((0, 0))
            pl = S._DevicePipeline(dev, idev, 1, NULL_TRACER,
                                   lambda seq: False)
            t0 = time.perf_counter()
            try:
                rec = S._launch(b, nu_ref_DM, max_iter, nsub_batch,
                                log10_tau=log10_tau, tau_mode=tau_mode,
                                tau_args=tau_args, alpha0=-4.0,
                                pipeline=pl, want_flux=print_flux,
                                seq=0)
                out = rec[0].result()
                try:
                    jax.block_until_ready(out)
                except TypeError:
                    pass
            finally:
                pl.shutdown(wait=True)
            dt = time.perf_counter() - t0
            if tracer.enabled:
                tracer.emit("warmup_compile", shape=shape, device=idev,
                            compile_s=round(dt, 6))
            warmed.append((shape, idev))
    if warmed:
        log(f"warmup: compiled {len(warmed)} (shape x device) "
            f"program(s) from {manifest_path} in "
            f"{time.perf_counter() - t_all:.2f} s", quiet=quiet,
            tracer=None)
    return warmed


def on_host(fn):
    """Decorator: run the whole function under host_compute().

    For offline entry points (template building, normalization, zap
    proposals) whose math uses complex phasors/FFTs — keeps them usable
    in sessions whose default backend cannot compile complex types."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with host_compute():
            return fn(*args, **kwargs)
    return wrapper
