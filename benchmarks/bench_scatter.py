"""BASELINE.md config 3: full (phi, DM, GM, tau, alpha) scattering fit,
64 subints x 512 chan x 2048 bin, jitted inner optimizer, one TPU chip.

Default engine is the round-3 complex-free fast lane
(fit_portrait_batch_fast -> fast_scatter_fit_one): matmul DFTs + the
fused analytic _cgh_scatter Newton loop in one real-arithmetic program.
`--engine complex` benches the round-2 complex engine for comparison;
`--compensated` turns on the Dot2 reductions.

Prints ONE JSON line like bench.py, including the per-stage breakdown
from the stage-attribution profiler (benchmarks/attrib.py; the
`attributed_frac` field is the >= 0.9 full-attribution check) and the
same accuracy-gate / dtype / window / mfu fields bench.py carries.

Shapes via PPT_NB / PPT_NCHAN / PPT_NBIN (defaults 64 x 512 x 2048);
PPT_XSPEC / PPT_DFT_PRECISION / PPT_DFT_FOLD A/B hooks via config.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# tau-accuracy gates (ISSUE 1: unchanged from the round-4/5 calibration)
TAU_GATE_PLAIN = 1.5e-4
TAU_GATE_COMPENSATED = 7e-5


def run_bench(engine="fast", compensated=False, attrib_only=False,
              with_attrib=True):
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    # run_bench is importable (attrib.py, tests): restore the process-
    # global config it overrides so a caller's later fits don't
    # silently inherit the bench's A/B settings
    saved_cfg = {k: getattr(config, k) for k in
                 ("dft_precision", "dft_fold", "scatter_compensated")}
    config.dft_precision = "default"
    # fold-symmetry DFT: halves the dominant matmul contraction on
    # non-TPU backends ('auto' excludes TPU, where the relayout loses —
    # exp_folddft.py); the tau gates below re-validate accuracy
    config.dft_fold = "auto"
    config.env_overrides()  # PPT_* A/B switches win over script defaults
    if compensated:
        config.scatter_compensated = True
    try:
        return _run_bench_inner(engine, attrib_only, with_attrib)
    finally:
        for k, v in saved_cfg.items():
            setattr(config, k, v)


def _run_bench_inner(engine, attrib_only, with_attrib):
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu import config

    from benchmarks.attrib import scatter_stage_profile
    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.fit import FitFlags, fit_portrait_batch
    from pulseportraiture_tpu.fit.portrait import (
        estimate_tau_batch, fit_portrait_batch_fast,
        model_harmonic_window)
    from pulseportraiture_tpu.ops.fourier import irfft_c, rfft_c, use_dft_fold
    from pulseportraiture_tpu.ops.scattering import (scattering_portrait_FT,
                                                     scattering_times)

    NB = int(os.environ.get("PPT_NB", 64))
    NCHAN = int(os.environ.get("PPT_NCHAN", 512))
    NBIN = int(os.environ.get("PPT_NBIN", 2048))
    DT = jnp.float32
    P, NU_FIT = 0.003, 1500.0
    TAU_S = 2e-4
    MAX_ITER = 40
    model, freqs = bench_model(NCHAN, NBIN)

    @jax.jit
    def synth(key):
        taus = scattering_times(TAU_S / P, -4.0, freqs, NU_FIT).astype(DT)
        B = scattering_portrait_FT(taus, NBIN // 2 + 1)
        sFT = rfft_c(model) * B
        k1, k2 = jax.random.split(key)
        phis = 0.05 * jax.random.uniform(k1, (NB,), DT)
        kk = jnp.arange(sFT.shape[-1], dtype=DT)
        ph = jnp.exp(-2j * jnp.pi * phis[:, None, None] * kk)
        rot = irfft_c(sFT * ph, n=NBIN)
        return rot + 0.03 * jax.random.normal(k2, rot.shape, DT)

    ports = synth(jax.random.PRNGKey(0))
    noise = jnp.full((NB, NCHAN), 0.03, DT)
    models = model  # shared 2-D template: one model DFT for the batch
    # data-driven tau seed (fit.portrait.estimate_tau_batch) — the
    # pipeline's scat_guess="auto"; with the round-6 parabolic grid
    # refinement + tau-matched CCF phase seed the vmapped Newton tail
    # collapses (nfev max 16 -> ~4 at this config)
    tau_seed = np.asarray(estimate_tau_batch(ports, model, noise))
    th0 = np.zeros((NB, 5), np.float32)
    th0[:, 3] = np.log10(np.maximum(tau_seed, 1e-12))
    th0[:, 4] = -4.0
    th0 = jnp.asarray(th0)

    flags = FitFlags(True, True, False, True, True)
    # harmonic window from the UNSCATTERED template's support (the
    # scattering kernel only narrows the spectrum; production templates
    # are host numpy so pipelines derive this automatically)
    hwin = model_harmonic_window(np.asarray(model), NBIN)

    def run():
        if engine == "fast":
            return fit_portrait_batch_fast(
                ports, models, noise, freqs, P, NU_FIT,
                fit_flags=flags, theta0=th0, log10_tau=True,
                max_iter=MAX_ITER,
                harmonic_window=hwin if hwin is not None else False)
        return fit_portrait_batch(
            ports, models, noise, freqs, P, NU_FIT,
            fit_flags=flags, theta0=th0, log10_tau=True,
            max_iter=MAX_ITER)

    r = run()
    exp = (TAU_S / P) * (np.asarray(r.nu_tau) / NU_FIT) ** np.asarray(r.alpha)
    rel = np.abs(np.asarray(r.tau) - exp) / exp
    tau_err = float(np.median(rel))
    tau_gate = (TAU_GATE_COMPENSATED if config.scatter_compensated
                else TAU_GATE_PLAIN)

    att = None
    if attrib_only and engine != "fast":
        raise ValueError(
            "stage attribution decomposes the fast lane only; "
            "run attrib_only with engine='fast'")
    if engine == "fast" and (with_attrib or attrib_only):
        att = scatter_stage_profile(
            ports, model, noise, freqs, jnp.asarray(P, DT),
            jnp.asarray(NU_FIT, DT), th0, flags, hwin, MAX_ITER,
            bool(config.scatter_compensated), run)
    if attrib_only:
        out = {"metric": "scatter-lane stage attribution",
               "batch": NB, "device": str(jax.devices()[0])}
        out.update(att.breakdown_ms())
        return out

    slope, single = devtime(run, lambda rr: rr.phi)

    # analytic-FLOP MFU, honest to the dispatched matmuls: the batched
    # data DFT (fold halves the contraction rows), the shared model
    # DFT, and the per-element CCF inverse DFT at 2x oversampling
    from benchmarks.common import mxu_peak_tflops

    nharm = hwin if hwin is not None else NBIN // 2 + 1
    contract = (NBIN // 2 - 1) if use_dft_fold() else NBIN
    dft_flops = NB * 2 * (2.0 * NCHAN * contract * nharm)
    mdl_flops = 2 * (2.0 * NCHAN * contract * nharm)
    ccf_flops = NB * 2 * (2.0 * nharm * 2 * NBIN)
    tflops = (dft_flops + mdl_flops + ccf_flops) / slope / 1e12
    dev = jax.devices()[0]
    peak = mxu_peak_tflops(dev)

    out = {
        "metric": f"5-param scattering fits, {NB}sub x {NCHAN}ch x "
                  f"{NBIN}bin",
        "value": round(NB / slope, 2),
        "unit": "TOAs/sec",
        "engine": engine,
        "compensated": bool(config.scatter_compensated),
        "batch_latency_ms": round(single * 1e3, 1),
        "batch": NB,
        "device": str(dev),
        "dtype": "float32",
        "cross_spectrum_dtype": str(config.cross_spectrum_dtype),
        "dft_fold": bool(use_dft_fold()),
        "harmonic_window": hwin,
        "tau_rel_err_median": float(f"{tau_err:.3g}"),
        "tau_gate": tau_gate,
        "tau_gate_ok": bool(tau_err < tau_gate),
        "nfev_median": float(np.median(np.asarray(r.nfeval))),
        "nfev_max": int(np.max(np.asarray(r.nfeval))),
        "rc0_frac": float(np.mean(np.asarray(r.return_code) == 0)),
        "dft_tflops": round(tflops, 2),
        "mfu": round(tflops / peak, 3) if peak else None,
    }
    if att is not None:
        out.update(att.breakdown_ms())
        # the full-attribution gate: >= 90% of the measured slope must
        # be explained by independently measured stages (one-sided —
        # isolated pieces can overestimate under load, see
        # BENCHMARKS.md)
        out["attrib_ok"] = bool(att.check(0.9))
    return out


def main():
    engine = "complex" if "--engine=complex" in sys.argv[1:] or \
        ("--engine" in sys.argv[1:] and "complex" in sys.argv[1:]) \
        else "fast"
    compensated = "--compensated" in sys.argv[1:]
    print(json.dumps(run_bench(engine=engine, compensated=compensated)))


if __name__ == "__main__":
    main()
