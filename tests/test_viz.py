"""Diagnostic-plot smoke + behavior tests (Agg backend): the round-3
verdict noted viz was functional but thin — these lock the reference
behaviors show_portrait/show_stacked_profiles gained in round 4
(pplib.py:3652-3824): zero-weight compression of the side panels,
rvrsd, inverted flux axis, model overlays with per-profile fitting."""

import matplotlib

matplotlib.use("Agg", force=True)

import matplotlib.pyplot as plt
import numpy as np
import pytest

from pulseportraiture_tpu.viz.plots import (
    show_portrait,
    show_profiles,
    show_residual_plot,
    show_stacked_profiles,
)


@pytest.fixture(autouse=True)
def _close_all():
    yield
    plt.close("all")


def _port(nchan=16, nbin=64):
    x = (np.arange(nbin) + 0.5) / nbin
    prof = np.exp(-0.5 * ((x - 0.3) / 0.04) ** 2)
    scales = 1.0 + 0.5 * np.linspace(-1, 1, nchan)
    return scales[:, None] * prof[None, :]


def test_show_portrait_panels_and_zap_compression():
    port = _port()
    port[3] = 0.0  # zapped channel
    freqs = np.linspace(1300.0, 1500.0, len(port))
    phases = (np.arange(port.shape[1]) + 0.5) / port.shape[1]
    fig = show_portrait(port, phases, freqs, title="t", show=False)
    # image + colorbar + profile + flux panels
    assert len(fig.axes) == 4
    ax_f = next(a for a in fig.axes if a.get_xlabel() == "Flux Units"
                and a.get_ylabel())
    xs, ys = ax_f.lines[0].get_data()
    # zapped channel compressed out of the spectrum panel
    assert len(ys) == len(port) - 1
    assert not np.any(np.isclose(ys, freqs[3]))
    # flux axis inverted (reference convention: flux grows leftward)
    lo, hi = ax_f.get_xlim()
    assert lo > hi


def test_show_portrait_rvrsd_and_kwargs():
    port = _port()
    freqs = np.linspace(1300.0, 1500.0, len(port))
    fig = show_portrait(port, None, freqs, rvrsd=True, colorbar=False,
                        prof=False, fluxprof=False, show=False,
                        vmin=0.0, vmax=2.0)
    (ax,) = fig.axes
    im = ax.get_images()[0]
    assert im.get_clim() == (0.0, 2.0)
    # reversed frequency extent
    ext = im.get_extent()
    assert ext[2] > ext[3]


def test_show_stacked_profiles_model_overlay_and_fit():
    port = _port(nchan=12)
    rng = np.random.default_rng(0)
    data = np.roll(port, 3, axis=-1) * 1.7 + \
        0.01 * rng.standard_normal(port.shape)
    fig = show_stacked_profiles(data, model_profiles=port, fit=True,
                                freqs=np.linspace(1300., 1500., 12),
                                show=False)
    (ax,) = fig.axes
    # one dashed model + one solid data line per channel
    assert len(ax.lines) == 2 * 12
    dashed = [l for l in ax.lines if l.get_linestyle() == "--"]
    assert len(dashed) == 12
    # fit=True aligned+scaled the model onto the data: the residual of
    # the first (model, data) pair is noise-level, not the raw offset
    m, d = ax.lines[0].get_ydata(), ax.lines[1].get_ydata()
    assert np.abs(m - d).max() < 0.1 * np.ptp(data[0])
    # frequency tick labels present
    assert ax.get_yticklabels()[0].get_text() == "1300"


def test_show_portrait_fully_zapped_no_degenerate_limits():
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        fig = show_portrait(np.zeros((8, 32)), show=False)
    assert len(fig.axes) == 4


def test_show_profiles_and_residual_smoke():
    port = _port()
    fig = show_profiles([port[0], port[1]], labels=["a", "b"],
                        show=False)
    assert fig.axes[0].get_legend() is not None
    fig2 = show_residual_plot(port, port * 1.01,
                              noise_stds=np.full(len(port), 0.01),
                              colorbar=False, show=False)
    assert len(fig2.axes) == 4


def test_show_residual_plot_reference_behaviors():
    """Round-5 parity for show_residual_plot (pplib.py:3853-3974):
    model panel inherits the DATA panel's clim; per-panel colorbars;
    composite step histogram counting only unzapped channels with the
    '# chans. (total = N)' label; default bin/channel-number labels
    when phases/freqs are absent."""
    port = _port()
    model = 0.9 * port
    noise = np.full(len(port), 0.05)
    w = np.ones(len(port))
    w[2] = 0.0
    fig = show_residual_plot(port, model, noise_stds=noise, weights=w,
                             show=False)
    # 4 panels + 3 colorbars
    assert len(fig.axes) == 7
    img_axes = [a for a in fig.axes if a.get_images()]
    assert len(img_axes) == 3
    data_im, model_im, _ = [a.get_images()[0] for a in img_axes]
    assert model_im.get_clim() == data_im.get_clim()
    # default labels are bin/channel numbers (no phases/freqs given)
    assert img_axes[0].get_xlabel() == "Bin Number"
    assert img_axes[0].get_ylabel() == "Channel Number"
    # histogram: zapped channel excluded from the count label
    ax_h = next(a for a in fig.axes if "# chans." in a.get_ylabel())
    assert f"total = {len(port) - 1}" in ax_h.get_ylabel()
    # step outline (Polygon patch), not filled bars only
    assert ax_h.patches


def test_show_residual_plot_rvrsd_and_clim_override():
    port = _port()
    freqs = np.linspace(1300.0, 1500.0, len(port))
    phases = (np.arange(port.shape[1]) + 0.5) / port.shape[1]
    fig = show_residual_plot(port, port * 0.5, phases, freqs,
                             noise_stds=np.full(len(port), 0.05),
                             rvrsd=True, colorbar=False, show=False,
                             vmin=0.0, vmax=3.0)
    img_axes = [a for a in fig.axes if a.get_images()]
    im = img_axes[0].get_images()[0]
    # rvrsd flips the frequency extent
    ext = im.get_extent()
    assert ext[2] > ext[3]
    # explicit vmin/vmax wins everywhere
    assert im.get_clim() == (0.0, 3.0)
    assert img_axes[1].get_images()[0].get_clim() == (0.0, 3.0)
    assert img_axes[0].get_xlabel() == "Phase [rot]"


def test_show_eigenprofiles_reference_behaviors():
    """Round-5 parity (pplib.py:4126-4207): phase-in-rotations x axis,
    1-indexed 'Eigenprofile N' labels, raw-dotted under smoothed-solid,
    S/N annotation, xlim clipping."""
    nbin, ncomp = 128, 2
    rng = np.random.default_rng(0)
    x = (np.arange(nbin) + 0.5) / nbin
    ev = np.stack([np.sin(2 * np.pi * x), np.cos(2 * np.pi * x)], -1)
    ev_noisy = ev + 0.05 * rng.standard_normal(ev.shape)
    mean = np.exp(-0.5 * ((x - 0.5) / 0.05) ** 2)
    from pulseportraiture_tpu.viz.plots import show_eigenprofiles

    fig = show_eigenprofiles(ev_noisy, smooth_eigvec=ev, mean_prof=mean,
                             smooth_mean_prof=mean, show=False,
                             show_snrs=True, xlim=(0.1, 0.9),
                             title="t")
    assert len(fig.axes) == 3
    assert fig.axes[0].get_ylabel() == "Mean profile"
    assert fig.axes[1].get_ylabel() == "Eigenprofile 1"
    assert fig.axes[2].get_ylabel() == "Eigenprofile 2"
    assert fig.axes[2].get_xlabel() == "Phase [rot]"
    assert fig.axes[0].get_title() == "t"
    # phases in rotations, clipped to xlim
    assert fig.axes[1].get_xlim() == (0.1, 0.9)
    xs = fig.axes[1].lines[0].get_xdata()
    assert 0.0 < xs[0] < 0.01 and 0.99 < xs[-1] < 1.0
    # S/N annotations on the smoothed eigen panels
    texts = [t.get_text() for ax in fig.axes[1:] for t in ax.texts]
    assert len(texts) == 2 and all(t.startswith("S/N") for t in texts)


def test_show_spline_curve_projections_reference_behaviors(tmp_path):
    """Round-5 parity (pplib.py:3977-4123): two figures (pair grid +
    frequency column), knot stars, weight-mapped marker sizes,
    descending-frequency flip, icoord single-panel mode, and the
    .proj.png/.freq.png save convention."""
    from scipy.interpolate import splprep

    from pulseportraiture_tpu.viz.plots import (
        show_spline_curve_projections)

    nchan, ncomp = 24, 3
    freqs = np.linspace(1500.0, 1300.0, nchan)  # descending band
    t = np.linspace(0, 1, nchan)
    proj = np.stack([t, t ** 2, np.sin(3 * t)], -1)
    tck, _ = splprep(list(proj.T), u=freqs[::-1], k=3, s=0.0)
    w = np.linspace(1.0, 3.0, nchan)
    figp, figf = show_spline_curve_projections(
        proj, freqs, tck=tck, weights=w, show=False)
    # pair grid: (ncomp-1)^2 layout with the lower triangle blanked
    pair_axes = [a for a in figp.axes if a.axison]
    assert len(pair_axes) == ncomp * (ncomp - 1) // 2
    # frequency column: one panel per coordinate, shared x, knot stars
    assert len(figf.axes) == ncomp
    assert figf.axes[-1].get_xlabel() == "Frequency [MHz]"
    assert figf.axes[0].get_ylabel() == "Coordinate 1"
    # scatter sizes map the weights onto [5,15]pt (s = ms^2)
    sc = figf.axes[0].collections[0]
    sizes = sc.get_sizes()
    assert sizes.min() == pytest.approx(25.0) \
        and sizes.max() == pytest.approx(225.0)
    # icoord mode: single frequency panel, no pair figure
    figp1, figf1 = show_spline_curve_projections(
        proj, freqs, tck=tck, icoord=2, show=False)
    assert figp1 is None and len(figf1.axes) == 1
    assert figf1.axes[0].get_ylabel() == "Coordinate 3"
    # save convention
    base = str(tmp_path / "spl")
    show_spline_curve_projections(proj, freqs, tck=tck, savefig=base)
    import os
    assert os.path.exists(base + ".proj.png")
    assert os.path.exists(base + ".freq.png")
