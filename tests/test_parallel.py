"""Sharded execution: results on a multi-device mesh must match the
single-device batch fit exactly (it is the same program, partitioned)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import fit_portrait_batch
from pulseportraiture_tpu.ops import guess_fit_freq
from pulseportraiture_tpu.parallel import fit_portrait_sharded, make_mesh
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003
NCHAN, NBIN, NB = 32, 512, 8
FREQS = jnp.asarray(np.linspace(1300.0, 1899.0, NCHAN))


@pytest.fixture(scope="module")
def batch():
    model = default_test_model(1500.0)
    keys = jax.random.split(jax.random.PRNGKey(0), NB)
    ds = [
        fake_portrait(k, model, FREQS, NBIN, P, phi=0.005 * i, DM=0.0004 * i,
                      noise_std=0.05)
        for i, k in enumerate(keys)
    ]
    return (
        jnp.stack([d.port for d in ds]),
        jnp.stack([d.model_port for d in ds]),
        jnp.stack([d.noise_stds for d in ds]),
    )


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def _check(res_sharded, res_ref):
    np.testing.assert_allclose(
        np.asarray(res_sharded.phi), np.asarray(res_ref.phi), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(res_sharded.DM), np.asarray(res_ref.DM), atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(res_sharded.snr), np.asarray(res_ref.snr), rtol=1e-9
    )


def test_data_parallel_matches_batch(batch):
    ports, models, stds = batch
    nu_fit = guess_fit_freq(FREQS)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    mesh = make_mesh(n_data=8, n_chan=1)
    res = fit_portrait_sharded(mesh, ports, models, stds, FREQS, P, nu_fit)
    _check(res, ref)


def test_data_x_chan_mesh_matches_batch(batch):
    """2-D mesh: batch over 'data', channels over 'chan' (psum path)."""
    ports, models, stds = batch
    nu_fit = guess_fit_freq(FREQS)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    mesh = make_mesh(n_data=4, n_chan=2)
    res = fit_portrait_sharded(
        mesh, ports, models, stds, FREQS, P, nu_fit, shard_channels=True
    )
    _check(res, ref)


def test_sharded_fast_matches_batch(batch):
    """The complex-free sharded core (the real-TPU-pod path) matches
    the batch reference on both mesh shapes, incl. a shared template."""
    from pulseportraiture_tpu.parallel import fit_portrait_sharded_fast

    ports, models, stds = batch
    nu_fit = guess_fit_freq(FREQS)
    ref = fit_portrait_batch(ports, models, stds, FREQS, P, nu_fit)
    res = fit_portrait_sharded_fast(
        make_mesh(n_data=8, n_chan=1), ports, models, stds, FREQS, P,
        nu_fit)
    _check(res, ref)
    res2 = fit_portrait_sharded_fast(
        make_mesh(n_data=4, n_chan=2), ports, models, stds, FREQS, P,
        nu_fit, shard_channels=True)
    _check(res2, ref)
    # shared 2-D template path (fake_portrait's model_port is the same
    # clean template for every element, so ref is the right oracle)
    res3 = fit_portrait_sharded_fast(
        make_mesh(n_data=8, n_chan=1), ports, models[0], stds, FREQS, P,
        nu_fit)
    _check(res3, ref)
    # the guard shared with fit_portrait_batch_fast
    bad = jnp.zeros((NB, 5)).at[0, 3].set(1e-4)
    with pytest.raises(ValueError):
        fit_portrait_sharded_fast(
            make_mesh(n_data=8, n_chan=1), ports, models, stds, FREQS, P,
            nu_fit, theta0=bad)
