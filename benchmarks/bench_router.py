"""Cross-host router benchmark (ISSUE 10 acceptance gate): aggregate
campaign throughput of a 1 -> H emulated-host fleet vs the single-host
arm, with byte-identical per-request ``.tim`` output.

Arms (all in ONE process — bench_stream's virtual-device discipline
applied to HOSTS):
  oneshot   — stream_wideband_TOAs per request slice (the reference
              .tim bytes, and the single-host throughput baseline);
  router@H  — H warm ToaServers, each pinned to its OWN virtual device
              (its own dispatch + copy worker threads, i.e. its own
              emulated host->device link), reached through
              InProcTransport — the same codepath a SocketTransport
              fleet runs minus the TCP bytes.  A ToaRouter shards the
              campaign's PPT_NREQ requests across them; measured from
              first submit to last collected result.

The scale-out claim is about the LINK (BENCHMARKS 5b/5d: ~90-95% of
campaign wall blocked on host->device transfer; the link multiplies
with hosts while the archive grid is embarrassingly parallel), so the
gate applies under the tunneled-transport emulation:
PPT_TUNNEL_EMU="<mbps>[:<dispatch_ms>]" (bench_campaign's model —
throttled device_put + synchronous dispatch floor, here PER HOST
because each host owns its device's copy worker).  Gate:
``router_speedup`` (router@H vs router@1 aggregate TOAs/s) >= 1.8 at
H=2 (``scaling_ok``); without tunnel emu the ratio is still printed
but the gate is not claimed (a bare-CPU box has no link to multiply —
compute serializes on the shared cores).

Always-on gates, any transport regime: every request's routed .tim is
byte-identical to its one-shot reference (``tim_identical``), zero
lost/duplicated requests (``n_route_done`` == requests, TOA totals
match), and the per-arm telemetry trace schema-validates with the
router section populated (placement imbalance reported).

Elastic-fleet arms (ISSUE 13, H >= 2):
  fleet/kill — the SAME campaign with host0 KILLED mid-sweep (its
              transport raises TransportError, its server aborts):
              gates zero lost requests, zero duplicated .tim lines
              (every routed .tim still byte-identical to one-shot),
              and bounded p99 inflation vs the no-kill router@H arm
              (``p99_inflation`` <= 10x, ``failover_ok``); the .fleet
              trace must carry fleet_transition DEAD + route_failover.
  codec     — the no-shared-fs lane (ToaRouter(write_tim='router')):
              hosts return full TOA payloads, the router writes every
              .tim — gated byte-identical (``codec_tim_identical``).
  hedge     — hedging forced on (hedge_ms=0) over a clean fleet:
              gated byte-identical to hedging-off
              (``hedge_tim_identical``) with ``n_hedge`` > 0.
  kill-during-hit — (ISSUE 17) the request set replayed from the
              router's RESULT CACHE after host0 dies: every request
              must resolve as a settled cache hit (no re-placement,
              no failover, zero lost, byte-identical) — the .chit
              trace must show n_cache_hit == requests, n_failover 0.

Knobs via env: PPT_NARCH (32), PPT_NSUB (16), PPT_NCHAN (64),
PPT_NBIN (256), PPT_NREQ (8 requests), PPT_NHOSTS (2),
PPT_TUNNEL_EMU, PPT_CAMPAIGN_CACHE (shared with bench_campaign),
PPT_TELEMETRY (traces to <path>.h<H>/.fleet/.hedge).  Prints ONE
JSON line.
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _ensure_devices(n):
    """Force >= n virtual CPU devices BEFORE jax initializes (the
    bench_stream discipline): each emulated host needs its own device
    so its copy worker — and therefore its emulated link — runs in its
    own thread."""
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def main():
    NHOSTS = max(1, int(os.environ.get("PPT_NHOSTS", 2)))
    _ensure_devices(NHOSTS)
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()

    import jax

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.serve import (InProcTransport, ToaClient,
                                            ToaRouter, ToaServer)
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = int(os.environ.get("PPT_NARCH", 32))
    NSUB = int(os.environ.get("PPT_NSUB", 16))
    NCHAN = int(os.environ.get("PPT_NCHAN", 64))
    NBIN = int(os.environ.get("PPT_NBIN", 256))
    NREQ = max(1, int(os.environ.get("PPT_NREQ", 8)))
    TUNNEL = os.environ.get("PPT_TUNNEL_EMU", "")
    GATE = 1.8
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    ndev = len(jax.local_devices())
    if ndev < NHOSTS:
        raise SystemExit(
            f"bench_router: {NHOSTS} emulated hosts need {NHOSTS} "
            f"virtual devices, got {ndev} (jax was initialized before "
            "the device-count flag could apply?)")

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * (i % 50), dDM=1e-4 * (i % 40),
                             noise_stds=0.05, quiet=True, rng=i)
        files.append(path)
    slices = [files[i::NREQ] for i in range(NREQ)]

    # ---- optional tunneled-transport emulation (bench_campaign's) ---
    from pulseportraiture_tpu.pipeline import stream as S
    unpatch = []
    if TUNNEL:
        parts = TUNNEL.split(":")
        mbps = float(parts[0])
        disp_ms = float(parts[1]) if len(parts) > 1 else 100.0
        real_put = jax.device_put

        def throttled_put(x, device=None, **kw):
            out = real_put(x, device, **kw)
            time.sleep(getattr(x, "nbytes", 0) / (mbps * 1e6))
            return out

        real_fit_fn = S._raw_fit_fn

        def sync_fit_fn(*a, **kw):
            fn = real_fit_fn(*a, **kw)

            def run(*args):
                out = jax.block_until_ready(fn(*args))
                time.sleep(disp_ms / 1e3)  # tunnel round-trip floor
                return out

            return run

        jax.device_put = throttled_put
        S._raw_fit_fn = sync_fit_fn
        unpatch = [(jax, "device_put", real_put),
                   (S, "_raw_fit_fn", real_fit_fn)]

    out_root = os.path.join(root, "router_out")
    os.makedirs(out_root, exist_ok=True)

    def ref_tim(i):
        return os.path.join(out_root, f"ref{i}.tim")

    try:
        # ---- one-shot reference arm: per-request .tim bytes + the
        # single-process baseline wall ------------------------------
        stream_wideband_TOAs(files[:1], mpath, nsub_batch=64,
                             quiet=True)  # warm the jit caches
        t0 = time.perf_counter()
        ntoa = 0
        for i, sl in enumerate(slices):
            res = stream_wideband_TOAs(sl, mpath, nsub_batch=64,
                                       tim_out=ref_tim(i), quiet=True)
            ntoa += len(res.TOA_list)
        oneshot_wall = time.perf_counter() - t0
        oneshot_tps = ntoa / oneshot_wall

        # ---- router arms: 1 -> H emulated hosts --------------------
        sweep = []
        tim_identical = True
        nokill_walls = None
        for H in sorted({1, NHOSTS}):
            trace = f"{trace_base}.h{H}" if trace_base else None
            servers = [
                ToaServer(nsub_batch=64, quiet=True,
                          stream_devices=[jax.local_devices()[h]])
                .start()
                for h in range(H)]
            # warm EVERY host's jit/device caches out of the timed
            # window (each device pays its own first-dispatch compile)
            for srv in servers:
                ToaClient(srv).get_TOAs(files[:1], mpath, timeout=600)
            router = ToaRouter(
                [InProcTransport(srv, label=f"host{h}")
                 for h, srv in enumerate(servers)],
                telemetry=trace)
            tims = [os.path.join(out_root, f"h{H}_r{i}.tim")
                    for i in range(NREQ)]
            t0 = time.perf_counter()
            handles = [router.submit(sl, mpath, tim_out=tims[i],
                                     name=f"req{i}")
                       for i, sl in enumerate(slices)]
            results, req_walls = [], []
            import time as _t
            for h in handles:
                results.append(h.result(3600))
                req_walls.append(_t.monotonic() - h._t_submit)
            wall = time.perf_counter() - t0
            if H == NHOSTS:
                nokill_walls = req_walls
            placed = router.stats()
            router.close()
            for srv in servers:
                srv.stop()
            arm_ntoa = sum(len(r.TOA_list) for r in results)
            for i in range(NREQ):
                same = (open(ref_tim(i), "rb").read()
                        == open(tims[i], "rb").read())
                tim_identical = tim_identical and same
            arm = {
                "hosts": H,
                "toas_per_sec": round(arm_ntoa / wall, 2),
                "wall_s": round(wall, 3),
                "n_toas": arm_ntoa,
                "placement": {lbl: st["n_archives"]
                              for lbl, st in placed.items()},
            }
            if trace:
                summary = telemetry.report(trace, file=io.StringIO())
                assert summary["n_route_submit"] == NREQ, summary
                assert summary["n_route_done"] == NREQ, (
                    "lost/duplicated requests: "
                    f"{summary['n_route_done']} != {NREQ}")
                arm["router_imbalance"] = (
                    round(summary["router_imbalance"], 3)
                    if summary["router_imbalance"] is not None
                    else None)
                arm["n_route_retry"] = summary["n_route_retry"]
            assert arm_ntoa == ntoa, (
                f"router@{H} produced {arm_ntoa} TOAs, one-shot "
                f"{ntoa} — lost or duplicated work")
            sweep.append(arm)

        # ---- elastic-fleet arms (ISSUE 13) -------------------------
        import numpy as np

        from pulseportraiture_tpu.pipeline.stream import _DONE_PREFIX
        from pulseportraiture_tpu.serve.transport import (
            KillableTransport as _Killable)

        fleet = None
        codec_tim_identical = None
        hedge_tim_identical = None
        n_hedge = None
        kill_during_hit = None
        if NHOSTS >= 2 and NREQ >= 2:
            # --- kill-one-host arm: host0 dies mid-sweep ------------
            trace = f"{trace_base}.fleet" if trace_base else None
            servers = [
                ToaServer(nsub_batch=64, quiet=True,
                          stream_devices=[jax.local_devices()[h]])
                .start()
                for h in range(NHOSTS)]
            for srv in servers:
                ToaClient(srv).get_TOAs(files[:1], mpath, timeout=600)
            transports = [
                _Killable(InProcTransport(srv, label=f"k{h}"))
                for h, srv in enumerate(servers)]
            router = ToaRouter(transports, telemetry=trace)
            tims = [os.path.join(out_root, f"kill_r{i}.tim")
                    for i in range(NREQ)]
            handles = [router.submit(sl, mpath, tim_out=tims[i],
                                     name=f"req{i}")
                       for i, sl in enumerate(slices)]
            killed_reqs = router.stats()["k0"]["n_requests"]
            # the kill: transport first (the router must see a DEAD
            # host, never a server-side error), then abort the server
            # so the dead host stops writing its .tim files
            transports[0].killed = True
            servers[0].stop(drain=False)
            import time as _t
            kill_results, kill_walls = [], []
            for h in handles:
                kill_results.append(h.result(3600))
                kill_walls.append(_t.monotonic() - h._t_submit)
            router.close()
            for srv in servers[1:]:
                srv.stop()
            kill_ntoa = sum(len(r.TOA_list) for r in kill_results)
            lost = NREQ - len(kill_results)
            dup_lines = 0
            kill_tim_ok = True
            for i in range(NREQ):
                got = open(tims[i], "rb").read()
                kill_tim_ok = kill_tim_ok and got == open(
                    ref_tim(i), "rb").read()
                sent = sum(1 for ln in got.decode().splitlines()
                           if ln.startswith(_DONE_PREFIX.rstrip()))
                dup_lines += max(0, sent - len(slices[i]))
            p99_kill = float(np.percentile(kill_walls, 99))
            p99_nokill = float(np.percentile(nokill_walls, 99))
            p99_inflation = p99_kill / max(p99_nokill, 1e-9)
            # bounded-p99 gate with absolute slack for CI noise at
            # tiny shapes: a failover costs one detection poll + one
            # re-fit, never an unbounded stall
            p99_bounded = p99_kill <= max(10.0 * p99_nokill,
                                          p99_nokill + 10.0)
            failover_ok = (lost == 0 and dup_lines == 0
                           and kill_tim_ok and kill_ntoa == ntoa)
            assert failover_ok, (
                f"failover arm lost={lost} dup_lines={dup_lines} "
                f"tim_ok={kill_tim_ok} toas={kill_ntoa}/{ntoa}")
            fleet = {
                "killed_host": "k0",
                "killed_host_requests": killed_reqs,
                "lost_requests": lost,
                "duplicated_tim_lines": dup_lines,
                "tim_identical": bool(kill_tim_ok),
                "p99_nokill_s": round(p99_nokill, 3),
                "p99_kill_s": round(p99_kill, 3),
                "p99_inflation": round(p99_inflation, 3),
                "p99_bounded": bool(p99_bounded),
                "failover_ok": bool(failover_ok),
            }
            if trace:
                summary = telemetry.report(trace, file=io.StringIO())
                assert summary["fleet_states"].get("k0") == "DEAD", \
                    summary["fleet_states"]
                if killed_reqs:
                    assert summary["n_failover"] >= 1, summary
                fleet["n_failover"] = summary["n_failover"]
                fleet["n_failover_collected"] = \
                    summary["n_failover_collected"]

            # --- codec (no-shared-fs) + hedge arms on a clean fleet -
            servers = [
                ToaServer(nsub_batch=64, quiet=True,
                          stream_devices=[jax.local_devices()[h]])
                .start()
                for h in range(NHOSTS)]
            for srv in servers:
                ToaClient(srv).get_TOAs(files[:1], mpath, timeout=600)
            router = ToaRouter(
                [InProcTransport(srv, label=f"c{h}")
                 for h, srv in enumerate(servers)],
                write_tim="router")
            tims = [os.path.join(out_root, f"codec_r{i}.tim")
                    for i in range(NREQ)]
            handles = [router.submit(sl, mpath, tim_out=tims[i],
                                     name=f"req{i}")
                       for i, sl in enumerate(slices)]
            for h in handles:
                h.result(3600)
            router.close()
            codec_tim_identical = all(
                open(tims[i], "rb").read()
                == open(ref_tim(i), "rb").read()
                for i in range(NREQ))
            assert codec_tim_identical, (
                "the router-written (no-shared-fs) .tim diverged "
                "from the shared-fs lane")

            trace = f"{trace_base}.hedge" if trace_base else None
            router = ToaRouter(
                [InProcTransport(srv, label=f"g{h}")
                 for h, srv in enumerate(servers)],
                hedge_ms=0.0, telemetry=trace)
            tims = [os.path.join(out_root, f"hedge_r{i}.tim")
                    for i in range(NREQ)]
            handles = [router.submit(sl, mpath, tim_out=tims[i],
                                     name=f"req{i}")
                       for i, sl in enumerate(slices)]
            for h in handles:
                h.result(3600)
            router.close()
            for srv in servers:
                srv.stop()
            hedge_tim_identical = all(
                open(tims[i], "rb").read()
                == open(ref_tim(i), "rb").read()
                for i in range(NREQ))
            assert hedge_tim_identical, (
                "hedging changed .tim bytes on a clean fleet")
            if trace:
                summary = telemetry.report(trace, file=io.StringIO())
                n_hedge = summary["n_hedge"]
                assert n_hedge >= 1, "hedge_ms=0 never hedged"

            # --- kill-during-hit arm (ISSUE 17): requests served
            # from the router's result cache while a host is DEAD —
            # a hit is settled on arrival, so failover/hedge must
            # never re-place it and nothing may stall on the corpse -
            trace = f"{trace_base}.chit" if trace_base else None
            cache_dir = os.path.join(out_root, "kill_hit_cache")
            servers = [
                ToaServer(nsub_batch=64, quiet=True,
                          stream_devices=[jax.local_devices()[h]])
                .start()
                for h in range(NHOSTS)]
            for srv in servers:
                ToaClient(srv).get_TOAs(files[:1], mpath, timeout=600)
            transports = [
                _Killable(InProcTransport(srv, label=f"ch{h}"))
                for h, srv in enumerate(servers)]
            router = ToaRouter(transports, telemetry=trace,
                               result_cache=True, cache_dir=cache_dir)
            for i, sl in enumerate(slices):  # populate: real fits
                router.submit(
                    sl, mpath,
                    tim_out=os.path.join(out_root, f"chp_r{i}.tim"),
                    name=f"req{i}").result(3600)
            placed0 = {lbl: st["n_requests"]
                       for lbl, st in router.stats().items()}
            transports[0].killed = True
            servers[0].stop(drain=False)
            tims = [os.path.join(out_root, f"chit_r{i}.tim")
                    for i in range(NREQ)]
            t0 = time.perf_counter()
            handles = [router.submit(sl, mpath, tim_out=tims[i],
                                     name=f"req{i}")
                       for i, sl in enumerate(slices)]
            chit_results = [h.result(60) for h in handles]
            chit_wall = time.perf_counter() - t0
            placed1 = {lbl: st["n_requests"]
                       for lbl, st in router.stats().items()}
            router.close()
            for srv in servers[1:]:
                srv.stop()
            chit_ok = (len(chit_results) == NREQ
                       and router.cache_hits == NREQ
                       and placed0 == placed1)
            assert chit_ok, (
                f"kill-during-hit re-placed work: {placed0} -> "
                f"{placed1}, cache_hits={router.cache_hits}")
            chit_tim_ok = all(
                open(tims[i], "rb").read()
                == open(ref_tim(i), "rb").read()
                for i in range(NREQ))
            assert chit_tim_ok, (
                "a cache hit served over a dead host diverged from "
                "its one-shot reference")
            kill_during_hit = {
                "lost_requests": NREQ - len(chit_results),
                "cache_hits": router.cache_hits,
                "replaced_work": placed0 != placed1,
                "tim_identical": bool(chit_tim_ok),
                "replay_wall_s": round(chit_wall, 3),
            }
            if trace:
                summary = telemetry.report(trace, file=io.StringIO())
                assert summary["n_cache_hit"] == NREQ, summary
                assert summary["n_failover"] == 0, (
                    "failover fired for settled cache hits")
    finally:
        for obj, name, val in unpatch:
            setattr(obj, name, val)

    top = sweep[-1]
    speedup = (top["toas_per_sec"]
               / max(sweep[0]["toas_per_sec"], 1e-9))
    print(json.dumps({
        "metric": f"routed campaign TOAs incl. PSRFITS IO, {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin, "
                  f"{NREQ} requests over {top['hosts']} emulated "
                  "host(s)",
        "value": top["toas_per_sec"],
        "unit": "TOAs/sec",
        "toas": ntoa,
        "oneshot_toas_per_sec": round(oneshot_tps, 2),
        "router_speedup": round(speedup, 3),
        # the >= 1.8x @ 2 hosts claim is about multiplying the
        # host->device LINK; it is only claimable when the link is
        # what binds (tunnel emu) — bare-CPU hosts share cores
        "scaling_ok": (bool(speedup >= GATE) if TUNNEL and
                       top["hosts"] >= 2 else None),
        "scaling_gate": GATE,
        "tim_identical": bool(tim_identical),
        "sweep": sweep,
        # elastic-fleet arms (None when NHOSTS < 2): kill-mid-sweep
        # failover gates, the no-shared-fs codec-lane byte gate, and
        # the hedging-on-vs-off byte gate
        "fleet": fleet,
        "codec_tim_identical": codec_tim_identical,
        "hedge_tim_identical": hedge_tim_identical,
        "n_hedge": n_hedge,
        # ISSUE 17: the whole request set served from the router's
        # result cache AFTER host0 died — hits are settled on
        # arrival, so nothing re-places and nothing stalls
        "kill_during_hit": kill_during_hit,
        "tunnel_emu": TUNNEL or None,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
