"""Gaussian profile kernels and instrumental-response FTs.

Phase-domain Gaussians (FWHM parameterization, periodic wrap-around)
and their analytic Fourier transforms, plus channel instrumental
response kernels ('rect' -> sinc, 'gauss' -> Gaussian) and the
DM-smearing width.

Parity targets: reference pplib.py:782-883 (gaussian_profile),
pptoaslib.py:22-58 (gaussian_profile_FT), pptoaslib.py:124-192
(instrumental response).
"""

import math

import jax
import jax.numpy as jnp

from .phasor import cexp

# host math, NOT jnp: a module-level jnp computation would initialize
# the default (TPU) backend at import time, before callers can force a
# CPU platform (e.g. the driver's dryrun_multichip)
FWHM2SIGMA = 1.0 / (8.0 * math.log(2.0)) ** 0.5  # sigma = FWHM * this


def gaussian_profile(nbin, loc, wid, amp=1.0, dtype=jnp.float64):
    """Periodic Gaussian profile: amp * exp(-4 ln2 d^2 / wid^2) with
    d = wrapped phase distance to loc; wid is FWHM [rot].

    Wrap-around is handled exactly (distance through the nearer edge),
    matching the reference's relocation logic (pplib.py:801-856) without
    its |z|<20 cutoff (XLA computes the exp everywhere; underflow to 0
    is the same result).
    """
    phases = jnp.arange(nbin, dtype=dtype) / nbin
    d = phases - loc
    d = jnp.mod(d + 0.5, 1.0) - 0.5
    wid = jnp.maximum(jnp.abs(wid), jnp.finfo(dtype).tiny ** 0.5)
    return amp * jnp.exp(-4.0 * jnp.log(2.0) * (d / wid) ** 2.0)


def gaussian_profile_FT(nharm, loc, wid, amp=1.0):
    """Analytic rFFT coefficients (unnormalized, numpy convention) of
    the periodic Gaussian with unit-peak amplitude ``amp``, sampled on
    nbin = 2*(nharm-1) bins.

    G(k) = amp * nbin * (wid/2) sqrt(pi/ln 2) * exp(-(pi k wid)^2/(4 ln2))
           * exp(-2 pi i k loc)

    Accurate when wid << 1 so periodic images are negligible — the
    regime enforced by wid_max = 0.25.  Parity: reference
    pptoaslib.py:22-58 (whose erf sinc-correction is folded into the
    instrumental response kernels here).
    """
    nbin = 2 * (nharm - 1)
    k = jnp.arange(nharm, dtype=jnp.result_type(loc, jnp.float32))
    # |wid|: a width that evolves through zero must not flip the
    # component's sign (matches gaussian_profile's clamping)
    sigma = jnp.abs(wid) * FWHM2SIGMA
    mag = (
        amp
        * nbin
        * sigma
        * jnp.sqrt(2.0 * jnp.pi)
        * jnp.exp(-2.0 * (jnp.pi * k * sigma) ** 2.0)
    )
    return mag * cexp(-2.0 * jnp.pi * k * loc)


def gaussian_profile_FT_jac(nharm, loc, wid, amp):
    """Analytic (G, dG/dloc, dG/dwid, dG/damp) of gaussian_profile_FT
    — the closed-form Jacobian block the LM template engine uses
    instead of autodiff (ISSUE 14; the reference's analytic-gradient
    heritage, SURVEY §L3).  Broadcasts like gaussian_profile_FT (pass
    loc/wid/amp with a trailing singleton axis for per-component
    stacks).

    With U(k) = nbin sqrt(2 pi) exp(-2 (pi k sigma)^2) e^{-2 pi i k loc}
    (the amp- and sigma-stripped kernel) and sigma = |wid| * FWHM2SIGMA:

        G        = amp * sigma * U
        dG/dloc  = G * (-2 pi i k)
        dG/dwid  = amp * U * (1 - (2 pi k sigma)^2)
                   * FWHM2SIGMA * sign(wid)
        dG/damp  = sigma * U

    The dwid form multiplies through by sigma (never divides), so a
    zero-width (or zero-amplitude padded) component yields exact
    finite zeros instead of inf*0 — the batched engine's frozen pads
    stay poison-free.  sign(wid) follows autodiff's |.|' convention
    (+1 at exactly 0) so the 'ad' digit-oracle lane agrees there too.
    """
    nbin = 2 * (nharm - 1)
    k = jnp.arange(nharm, dtype=jnp.result_type(loc, jnp.float32))
    sigma = jnp.abs(wid) * FWHM2SIGMA
    mag = nbin * jnp.sqrt(2.0 * jnp.pi) * jnp.exp(
        -2.0 * (jnp.pi * k * sigma) ** 2.0)
    U = mag * cexp(-2.0 * jnp.pi * k * loc)
    G = amp * sigma * U
    two_pi_k = 2.0 * jnp.pi * k
    dloc = G * jax.lax.complex(jnp.zeros_like(two_pi_k), -two_pi_k)
    dwid = (amp * U * (1.0 - (two_pi_k * sigma) ** 2.0)
            * FWHM2SIGMA * jnp.where(wid >= 0.0, 1.0, -1.0))
    damp = sigma * U
    return G, dloc, dwid, damp


def instrumental_response_FT(width, nharm, kind="rect"):
    """FT of a channel's instrumental smearing kernel of ``width`` [rot].

    kind='rect': boxcar -> sinc(k*width); kind='gauss': Gaussian FWHM
    ``width``.  width=0 -> identity.  Parity: reference
    pptoaslib.py:124-155.
    """
    k = jnp.arange(nharm, dtype=jnp.result_type(width, jnp.float32))
    if kind == "rect":
        return jnp.sinc(k * width)
    elif kind == "gauss":
        sigma = width * FWHM2SIGMA
        return jnp.exp(-2.0 * (jnp.pi * k * sigma) ** 2.0)
    else:
        raise ValueError(f"unknown instrumental response kind {kind!r}")


def dm_smearing_width(DM, chan_bw, freqs, P):
    """Per-channel DM-smearing width [rot]:
    8.3e-6 s * DM * BW_MHz / nu_GHz^3 / P.

    Parity: reference pptoaslib.py:158-192 (:189).
    """
    return 8.3e-6 * DM * chan_bw / (freqs / 1.0e3) ** 3.0 / P


def instrumental_response_port_FT(
    nharm, freqs, widths=(), kinds=(), DM_smear=None, chan_bw=None, P=None
):
    """Product of instrumental response FTs per channel ->
    (nchan, nharm) real array.

    ``widths``/``kinds`` are parallel sequences of achromatic kernels;
    if ``DM_smear`` (a DM value) is given, a per-channel rect kernel of
    the DM-smearing width is included.  Parity: reference
    pptoaslib.py:158-192.
    """
    freqs = jnp.asarray(freqs)
    nchan = freqs.shape[0]
    out = jnp.ones((nchan, nharm), dtype=freqs.dtype)
    for width, kind in zip(widths, kinds):
        out = out * instrumental_response_FT(
            jnp.asarray(width, freqs.dtype), nharm, kind
        )[None, :]
    if DM_smear is not None:
        w = dm_smearing_width(DM_smear, chan_bw, freqs, P)
        k = jnp.arange(nharm, dtype=freqs.dtype)
        out = out * jnp.sinc(k[None, :] * w[:, None])
    return out


def gaussian_function(xs, loc, wid, norm=False):
    """Plain (non-wrapped) Gaussian with FWHM ``wid`` evaluated at xs
    (reference signature, pplib.py:782-798): peak 1 by default,
    unit-area with norm=True.  The phase-wrapped profile version is
    gaussian_profile."""
    xs = jnp.asarray(xs)
    sigma = wid * FWHM2SIGMA
    z = (xs - loc) / sigma
    y = jnp.exp(-0.5 * z ** 2.0)
    if norm:
        y = y / (sigma * jnp.sqrt(2.0 * jnp.pi))
    return y
