"""Tests for the PCA/B-spline/wavelet modeling stack.

Oracles (SURVEY.md §4): perfect reconstruction of the SWT pair,
B-spline evaluation parity with scipy.interpolate.splev, PCA parity
with np.cov+eigh, denoising actually denoises, spline portrait model
recovers a synthetic frequency-evolving portrait.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.interpolate as si

from pulseportraiture_tpu.fit.powlaw import (fit_DM_to_freq_resids,
                                             fit_powlaw, powlaw,
                                             powlaw_freqs)
from pulseportraiture_tpu.models.spline import (bspline_eval, fft_resample,
                                                fit_spline_curve,
                                                gen_spline_portrait, pca,
                                                reconstruct_portrait)
from pulseportraiture_tpu.models.wavelet import (daubechies, iswt,
                                                 smart_smooth, swt,
                                                 wavelet_smooth)


class TestWavelet:
    def test_daubechies_orthonormal(self):
        for N in (2, 4, 8):
            lo, hi = daubechies(N)
            assert len(lo) == 2 * N
            assert np.isclose(lo.sum(), np.sqrt(2.0))
            assert np.isclose(np.sum(lo**2), 1.0)
            # orthogonality to even shifts
            for s in range(2, 2 * N, 2):
                assert abs(np.sum(lo[s:] * lo[:-s])) < 1e-10

    def test_swt_perfect_reconstruction(self, rng):
        x = jnp.asarray(rng.normal(size=256))
        cA, cD = swt(x, nlevel=4)
        xr = iswt(cA, cD)
        assert np.allclose(np.asarray(xr), np.asarray(x), atol=1e-10)

    def test_swt_batched(self, rng):
        x = jnp.asarray(rng.normal(size=(3, 128)))
        cA, cD = swt(x, nlevel=3)
        assert cA.shape == (3, 3, 128)
        xr = iswt(cA, cD)
        assert np.allclose(np.asarray(xr), np.asarray(x), atol=1e-10)

    def test_denoise_improves_mse(self, rng):
        nbin = 512
        t = np.linspace(0, 1, nbin, endpoint=False)
        clean = np.exp(-0.5 * ((t - 0.5) / 0.02) ** 2)
        noisy = clean + 0.05 * rng.normal(size=nbin)
        sm = np.asarray(wavelet_smooth(noisy, nlevel=5, fact=1.0))
        assert np.mean((sm - clean) ** 2) < 0.5 * np.mean((noisy - clean) ** 2)

    @pytest.mark.slow  # ~12 s; wavelet shrinkage basics stay tier-1 in
    # the surrounding TestWavelet cases
    def test_smart_smooth_zeroes_pure_noise_keeps_signal(self, rng):
        nbin = 256
        t = np.linspace(0, 1, nbin, endpoint=False)
        clean = np.exp(-0.5 * ((t - 0.5) / 0.03) ** 2)
        port = np.stack([clean + 0.05 * rng.normal(size=nbin),
                         np.zeros(nbin)])
        sm = np.asarray(smart_smooth(port))
        assert np.mean((sm[0] - clean) ** 2) < np.mean(
            (port[0] - clean) ** 2)
        assert np.all(sm[1] == 0.0)


class TestPCA:
    def test_pca_matches_numpy(self, rng):
        port = rng.normal(size=(32, 64))
        w = rng.uniform(1.0, 2.0, size=32)
        eigval, eigvec = pca(jnp.asarray(port), weights=jnp.asarray(w))
        mean = (port.T * w).T.sum(0) / w.sum()
        cov = np.cov((port - mean).T, aweights=w, ddof=1)
        ev_np, evec_np = np.linalg.eigh(cov)
        assert np.allclose(np.asarray(eigval), ev_np[::-1], atol=1e-8)
        # leading (non-degenerate) eigvectors match up to sign; the
        # null space of the rank-deficient cov is arbitrary
        lead = np.asarray(eigvec)[:, :20]
        dots = np.abs(np.sum(lead * evec_np[:, ::-1][:, :20], axis=0))
        assert np.allclose(dots, 1.0, atol=1e-6)

    def test_reconstruct_identity_full_basis(self, rng):
        port = rng.normal(size=(16, 32))
        eigval, eigvec = pca(jnp.asarray(port))
        mean = port.mean(0)
        rec = reconstruct_portrait(jnp.asarray(port), jnp.asarray(mean),
                                   eigvec)
        assert np.allclose(np.asarray(rec), port, atol=1e-8)


class TestBSpline:
    def test_matches_scipy_splev(self, rng):
        freqs = np.linspace(1000.0, 2000.0, 64)
        proj = np.stack([np.sin(freqs / 200.0), np.cos(freqs / 300.0)]).T
        proj += 0.01 * rng.normal(size=proj.shape)
        tck = fit_spline_curve(proj, freqs, sfac=1.0)
        x = np.linspace(1000.0, 2000.0, 200)
        ours = np.asarray(bspline_eval(x, tck))
        scipys = np.array(si.splev(x, (tck[0], list(tck[1]), tck[2]))).T
        assert np.allclose(ours, scipys, atol=1e-8)

    def test_gen_spline_portrait_recovers_evolution(self, rng):
        nchan, nbin = 64, 128
        freqs = np.linspace(1200.0, 1800.0, nchan)
        t = np.arange(nbin) / nbin
        mean = np.exp(-0.5 * ((t - 0.5) / 0.05) ** 2)
        ev1 = np.roll(mean, 5) - mean  # a shape-evolution direction
        coef = 0.3 * (freqs - 1500.0) / 300.0
        port = mean + np.outer(coef, ev1)
        eigval, eigvec = pca(jnp.asarray(port))
        k = 1
        vecs = np.asarray(eigvec)[:, :k]
        proj = (port - mean) @ vecs
        tck = fit_spline_curve(proj, freqs, sfac=0.01)
        model = np.asarray(
            gen_spline_portrait(jnp.asarray(mean), jnp.asarray(freqs),
                                jnp.asarray(vecs), tck))
        assert np.allclose(model, port, atol=1e-3)

    def test_fft_resample(self):
        nbin = 64
        t = np.arange(nbin) / nbin
        x = np.sin(2 * np.pi * 3 * t) + 0.5 * np.cos(2 * np.pi * 5 * t)
        up = np.asarray(fft_resample(jnp.asarray(x), 128))
        t2 = np.arange(128) / 128.0
        expect = np.sin(2 * np.pi * 3 * t2) + 0.5 * np.cos(2 * np.pi * 5 * t2)
        assert np.allclose(up, expect, atol=1e-10)


class TestPowlaw:
    def test_fit_powlaw_recovers(self, rng):
        freqs = np.linspace(1000.0, 2000.0, 50)
        truth = powlaw(freqs, 1500.0, 2.5, -1.8)
        noisy = truth * (1.0 + 0.01 * rng.normal(size=50))
        res = fit_powlaw(noisy, errs=0.025 * np.asarray(truth),
                         nu_ref=1500.0, freqs=freqs)
        assert abs(res.amp - 2.5) < 0.1
        assert abs(res.alpha + 1.8) < 0.1
        assert res.alpha_err > 0

    def test_powlaw_freqs_equal_flux(self):
        edges = powlaw_freqs(1000.0, 2000.0, 8, -1.0)
        assert len(edges) == 9
        from pulseportraiture_tpu.fit.powlaw import powlaw_integral

        fluxes = [
            float(powlaw_integral(edges[i + 1], edges[i], 1500.0, 1.0, -1.0))
            for i in range(8)
        ]
        assert np.allclose(fluxes, fluxes[0])

    def test_fit_powlaw_noisy_stays_finite(self, rng):
        # regression: undamped Gauss-Newton diverged to NaN on low-S/N
        # data with negative fluxes
        freqs = np.linspace(1000.0, 2000.0, 16)
        truth = powlaw(freqs, 1500.0, 1.0, -1.5)
        noisy = truth + 1.5 * np.mean(truth) * rng.normal(size=16)
        res = fit_powlaw(noisy, errs=1.5 * np.mean(truth) * np.ones(16),
                         nu_ref=1500.0, freqs=freqs)
        assert np.isfinite(res.amp) and np.isfinite(res.alpha)
        assert np.isfinite(res.amp_err) and np.isfinite(res.alpha_err)

    def test_fit_dm_to_freq_resids(self, rng):
        from pulseportraiture_tpu.config import Dconst

        freqs = np.linspace(1000.0, 2000.0, 64)
        DM_true, off = 3.0e-3, 5.0e-6
        resids = Dconst * DM_true * freqs**-2.0 + off
        errs = np.full(64, 1.0e-7)
        resids = resids + errs * rng.normal(size=64)
        out = fit_DM_to_freq_resids(freqs, resids, errs)
        assert abs(out.DM - DM_true) < 5 * out.DM_err
        assert abs(out.offset - off) < 5 * out.offset_err
        assert out.red_chi2 == pytest.approx(1.0, abs=0.5)
