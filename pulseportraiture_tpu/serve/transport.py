"""Remote transport for the TOA service: the ToaClient surface over a
wire (ISSUE 10 tentpole, first half).

The per-host serving loop (serve/server.ToaServer) is already the
right scale-out unit — pulsar archives are embarrassingly parallel and
a campaign's bottleneck is the per-host host->device link, which
MULTIPLIES when archives shard across hosts.  What was missing is a
way to reach a warm server that lives in another process: this module
wraps the in-process client surface (submit / result / stat / drain)
behind a minimal length-prefixed JSON-over-socket protocol so a router
(serve/router.ToaRouter) can own a fleet of hosts.

Design constraints, in order:

- **No bulk data on the wire.**  Requests name archive paths that are
  host-visible (shared filesystem — the same assumption the multihost
  campaign drivers make), and each request's ``.tim`` is written BY
  THE SERVING HOST through the server's existing demux, so it stays
  byte-identical to the one-shot driver no matter which host served
  it.  Only the request spec and the per-TOA result records cross the
  socket.
- **Backpressure crosses the wire intact.**  A remote
  ``ServeRejected`` arrives with its ``retryable`` flag, so the
  router's retry policy cannot tell (and need not care) whether a
  host is local or remote.
- **One protocol, two transports.**  ``InProcTransport`` wraps a local
  ToaServer through the SAME encode/decode path as the socket lane
  (results round-trip the codec), so tests and the emulated-host
  benchmark exercise exactly what a real fleet runs, minus the TCP
  bytes.

Wire protocol (SocketTransport <-> TransportServer): every frame is a
4-byte big-endian length followed by a UTF-8 JSON object; a set top
bit in the length marks a zlib-compressed body
(config.transport_compress — big result frames shrink severalfold,
and plain frames stay bit-identical to prior releases).  Ops:

  {"op": "submit", "datafiles": [...], "modelfile": m,
   "tim_out": p|null, "name": n|null, "tenant": t|null,
   "trace_id": id|null, "options": {...}}
      (trace_id: distributed-tracing context minted by the router —
       ISSUE 20; absent/null on old peers, the server mints its own)
      -> {"ok": true, "handle": k}
      -> {"ok": false, "error": msg, "rejected": true,
          "retryable": bool}                 (ServeRejected)
      -> {"ok": false, "error": msg}        (anything else)
  {"op": "result", "handle": k, "wait": seconds}
      -> {"ok": true, "done": false}        (poll again)
      -> {"ok": true, "done": true, "result": {...}}
      -> {"ok": false, "error": msg, "etype": "TypeError", ...}
  {"op": "stat"}
      -> {"ok": true, "pending_archives": n, "queue_len": n,
          "n_live": n, "cache_hits": n, "cache_bytes": n}
         (cache_* count result-cache hit traffic served OUTSIDE the
          load signal; absent on pre-cache hosts — readers default 0)
  {"op": "metrics"}
      -> {"ok": true, ...ToaServer.metrics()...}
         (ISSUE 20: the stat-shaped load snapshot plus the streaming
          registry export — counters/gauges/log-bucket latency
          histograms — the link stall fraction, and the per-tenant
          SLO snapshot; a pre-obs host replies unknown-op and the
          caller degrades to ``stat``)
  {"op": "drain"}
      -> {"ok": true, "n_done": n}          (this connection's handles
                                             all resolved)

``result`` is a POLL (the server blocks at most ``wait`` seconds per
frame), so one connection can interleave submits while earlier
requests are still in flight — a blocking result would serialize the
router's whole fleet behind one slow request.
"""

import json
import socket
import struct
import threading
import zlib

from ..telemetry import log
from .queue import ServeRejected

__all__ = ["TransportError", "RemoteRequestError", "InProcTransport",
           "SocketTransport", "TransportServer", "KillableTransport",
           "parse_hostport", "encode_result", "decode_result"]

# A frame above this is a protocol violation, not a big request: the
# largest legitimate payload is a result frame (~200 bytes per TOA).
MAX_FRAME = 256 * 1024 * 1024
# Compressed-frame marker (ISSUE 15): the top bit of the 4-byte length
# prefix is free (MAX_FRAME < 2**31), so a set bit means "the body is
# zlib-compressed JSON" — both peers in this repo understand it; plain
# frames are bit-identical to every prior release.
_FRAME_ZLIB = 0x80000000
# A frame smaller than this never compresses: the zlib call costs more
# than any conceivable link saving (result frames are ~200 bytes/TOA,
# so only multi-hundred-TOA results cross it).
COMPRESS_MIN_FRAME = 64 * 1024
# Static socket cost model for 'auto': engage only when zlib saves at
# least this fraction of the frame — below it the decompress wall on
# the peer rivals the wire saving on any LAN-class link.
COMPRESS_MIN_SAVING = 0.125
# Per-poll server-side block in the result op; the client loops.
RESULT_POLL_S = 0.25
# Per-round-trip server-side block in the drain op — must stay well
# below the client's socket timeout or a long drain would poison the
# connection; the client loops until nothing is pending.
DRAIN_CHUNK_S = 5.0


class TransportError(ConnectionError):
    """The transport itself failed (connection refused/reset, protocol
    violation) — distinct from a request-level failure, which arrives
    as the request's own error.  The router treats a TransportError as
    'this host is unreachable': it places elsewhere."""


class RemoteRequestError(RuntimeError):
    """A request failed ON THE SERVING HOST; ``etype`` names the
    original exception class (the object itself stayed remote)."""

    def __init__(self, msg, etype="Exception"):
        super().__init__(msg)
        self.etype = str(etype)


def parse_hostport(spec):
    """'host:port' -> (host, port); the strict parse lives in config
    (shared with the PPT_ROUTER_HOSTS / PPT_SERVE_LISTEN env hooks)."""
    from ..config import parse_hostport as _parse

    return _parse(spec)


# ---------------------------------------------------------------------------
# result codec: factored into serve/codec.py (ISSUE 13 — the codec is
# also the no-shared-fs lane's .tim demux and the durable-.tim
# failover primitive); re-exported here so R13 call sites keep working
# ---------------------------------------------------------------------------

from .codec import decode_result, encode_result  # noqa: E402,F401
from .codec import roundtrip_result as _roundtrip_result  # noqa: E402


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def _send_frame(sock, obj):
    """Send one length-prefixed JSON frame, zlib-compressing the body
    when ``config.transport_compress`` allows and the frame is big
    enough to pay for it ('auto' = the static size/saving rule above;
    True = whenever smaller; False = never — byte-identical to every
    prior release).  The receiver keys on the length prefix's top bit,
    so mixed traffic on one connection is fine."""
    from ..io.blockcodec import resolve_transport_compress

    body = json.dumps(obj, separators=(",", ":")).encode()
    mode = resolve_transport_compress()
    if mode is not False and len(body) >= COMPRESS_MIN_FRAME:
        comp = zlib.compress(body, 1)
        saving = 1.0 - len(comp) / len(body)
        if (mode is True and len(comp) < len(body)) or \
                (mode == "auto" and saving >= COMPRESS_MIN_SAVING):
            sock.sendall(struct.pack(
                ">I", len(comp) | _FRAME_ZLIB) + comp)
            return
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock):
    head = _recv_exact(sock, 4)
    (n,) = struct.unpack(">I", head)
    compressed = bool(n & _FRAME_ZLIB)
    n &= ~_FRAME_ZLIB
    if n > MAX_FRAME:
        raise TransportError(f"frame of {n} bytes exceeds the "
                             f"{MAX_FRAME}-byte protocol limit")
    body = _recv_exact(sock, n)
    if compressed:
        # bounded inflate: the limit must be enforced DURING
        # decompression (a hostile frame within MAX_FRAME compressed
        # can inflate ~1000x — a plain zlib.decompress would attempt
        # the full allocation before any post-hoc size check runs)
        try:
            d = zlib.decompressobj()
            body = d.decompress(body, MAX_FRAME + 1)
        except zlib.error as e:
            raise TransportError(f"bad compressed frame: {e}")
        if len(body) > MAX_FRAME or d.unconsumed_tail:
            raise TransportError(
                f"compressed frame inflates past the {MAX_FRAME}-byte "
                "protocol limit")
    return json.loads(body.decode())


# ---------------------------------------------------------------------------
# transports (the client side the router holds)
# ---------------------------------------------------------------------------

class InProcTransport:
    """The ToaClient surface against a ToaServer in THIS process,
    through the same result codec as the socket lane — what tests, the
    emulated-host benchmark, and the dryrun witness route over."""

    def __init__(self, server, label=None):
        self.server = server
        self.label = str(label) if label is not None else \
            f"inproc:{id(server):x}"
        self._handles = []
        self._lock = threading.Lock()

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               options=None, tenant=None, trace_id=None):
        req = self.server.submit(datafiles, modelfile, tim_out=tim_out,
                                 name=name, tenant=tenant,
                                 trace_id=trace_id,
                                 **dict(options or {}))
        with self._lock:
            self._handles.append(req)
        return req

    def result(self, handle, timeout=None):
        try:
            res = handle.result(timeout)
        except TimeoutError:
            raise  # still outstanding: keep it in the drain set
        except Exception:
            self._evict(handle)
            raise
        self._evict(handle)
        # round-trip the codec so both transports return IDENTICAL
        # result shapes (and the codec is exercised wherever the
        # router is) — the bytes never leave the process
        return _roundtrip_result(res)

    def _evict(self, handle):
        # collect-once, like the socket lane's per-connection handle
        # table: a collected request must not pin its result
        with self._lock:
            try:
                self._handles.remove(handle)
            except ValueError:
                pass

    def stat(self):
        return self.server.stats()

    def metrics(self):
        return self.server.metrics()

    def drain(self, timeout=None):
        """Wait for the not-yet-collected requests submitted through
        this transport; returns how many of them resolved.
        ``timeout`` is a TOTAL deadline (the socket lane's
        semantics), not a per-handle wait."""
        import time

        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        with self._lock:
            handles = list(self._handles)
        n = 0
        for h in handles:
            left = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            if h.wait(left):
                n += 1
        return n

    def close(self):
        pass


class KillableTransport:
    """Fault-injection wrapper: delegates to ``inner`` until
    :meth:`kill`, after which every transport call raises
    TransportError — the router's host-unreachable signal.  This is
    the dead-host emulation bench_router's kill arm and the fleet
    tests share (a real fleet exercises the same path when a host's
    socket resets)."""

    def __init__(self, inner):
        self.inner = inner
        self.label = inner.label
        self.killed = False

    def kill(self):
        self.killed = True

    def _check(self):
        if self.killed:
            raise TransportError(f"{self.label} killed")

    def submit(self, *a, **kw):
        self._check()
        return self.inner.submit(*a, **kw)

    def result(self, handle, timeout=None):
        self._check()
        return self.inner.result(handle, timeout)

    def stat(self):
        self._check()
        return self.inner.stat()

    def metrics(self):
        self._check()
        return self.inner.metrics()

    def __getattr__(self, name):
        return getattr(self.inner, name)


class SocketTransport:
    """The ToaClient surface against a ``ppserve --listen`` host.

    One TCP connection per transport; a lock serializes frames so the
    router may call it from many threads.  ``result`` polls (bounded
    server-side waits), so a slow request never wedges the connection
    for sibling submits."""

    def __init__(self, address, timeout=30.0):
        self.host, self.port = parse_hostport(address)
        self.label = f"{self.host}:{self.port}"
        self._lock = threading.Lock()
        self._io_timeout = float(timeout)
        try:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self._io_timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        except OSError as e:
            raise TransportError(
                f"cannot reach ppserve at {self.label}: {e}")

    def _call(self, msg):
        with self._lock:
            if self._sock is None:
                raise TransportError(
                    f"transport to {self.label} is closed (a prior "
                    "I/O failure poisoned the connection)")
            try:
                _send_frame(self._sock, msg)
                reply = _recv_frame(self._sock)
            except (TransportError, OSError, ValueError) as e:
                # the request/reply framing is now ambiguous (a late
                # reply to THIS op would be read as the next op's) —
                # close the socket so every subsequent op fails loudly
                # instead of desynchronizing
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
                if isinstance(e, TransportError):
                    raise
                raise TransportError(
                    f"transport to {self.label} failed: {e}")
        if not isinstance(reply, dict):
            raise TransportError(
                f"malformed reply from {self.label}: {reply!r}")
        return reply

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               options=None, tenant=None, trace_id=None):
        reply = self._call({"op": "submit",
                            "datafiles": list(datafiles)
                            if not isinstance(datafiles, str)
                            else datafiles,
                            "modelfile": str(modelfile),
                            "tim_out": tim_out, "name": name,
                            "tenant": tenant,
                            "trace_id": trace_id,
                            "options": dict(options or {})})
        if reply.get("ok"):
            return reply["handle"]
        if reply.get("rejected"):
            # the remote admission queue's backpressure, flag intact
            raise ServeRejected(reply.get("error", "rejected"),
                                retryable=bool(reply.get("retryable")))
        raise RemoteRequestError(reply.get("error", "submit failed"),
                                 etype=reply.get("etype", "Exception"))

    def result(self, handle, timeout=None):
        import time

        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while True:
            wait = RESULT_POLL_S if deadline is None else \
                max(0.0, min(RESULT_POLL_S, deadline - time.monotonic()))
            reply = self._call({"op": "result", "handle": handle,
                                "wait": wait})
            if not reply.get("ok"):
                raise RemoteRequestError(
                    reply.get("error", "request failed"),
                    etype=reply.get("etype", "Exception"))
            if reply.get("done"):
                return decode_result(reply["result"])
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no result from {self.label} within {timeout} s")

    def stat(self):
        reply = self._call({"op": "stat"})
        if not reply.get("ok"):
            raise TransportError(
                f"stat on {self.label} failed: {reply.get('error')}")
        out = {k: reply[k] for k in ("pending_archives", "queue_len",
                                     "n_live")}
        # cache counters (ISSUE 17): .get with a 0 default so a newer
        # router can probe a pre-cache host without tripping
        for k in ("cache_hits", "cache_bytes"):
            out[k] = reply.get(k, 0)
        # backend-aware routing signals (ISSUE 19): None-default so a
        # newer router degrades to least-loaded against an older host
        for k in ("toas_per_s", "capability"):
            out[k] = reply.get(k)
        return out

    def metrics(self):
        """The live-metrics op (ISSUE 20).  A pre-obs host replies
        unknown-op — surfaced as a TransportError naming the mismatch
        so a fleet aggregator can degrade that host to ``stat``."""
        reply = self._call({"op": "metrics"})
        if not reply.get("ok"):
            raise TransportError(
                f"metrics on {self.label} failed (pre-obs host?): "
                f"{reply.get('error')}")
        return {k: v for k, v in reply.items() if k != "ok"}

    def drain(self, timeout=None):
        """Wait for this connection's outstanding requests.  The
        server bounds each reply below the socket timeout and reports
        how many are still pending; the client loops until done or
        ``timeout`` expires (returns the resolved count either
        way)."""
        import time

        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        while True:
            wait = DRAIN_CHUNK_S if deadline is None else \
                max(0.0, min(DRAIN_CHUNK_S,
                             deadline - time.monotonic()))
            reply = self._call({"op": "drain", "timeout": wait})
            if not reply.get("ok"):
                raise TransportError(
                    f"drain on {self.label} failed: "
                    f"{reply.get('error')}")
            n_done = int(reply.get("n_done", 0))
            if not reply.get("pending") or (
                    deadline is not None
                    and time.monotonic() >= deadline):
                return n_done

    def close(self):
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None


# ---------------------------------------------------------------------------
# the listener (``ppserve --listen`` wraps this around its ToaServer)
# ---------------------------------------------------------------------------

class TransportServer:
    """Accept loop exposing one local ToaServer to SocketTransports.

    One daemon thread per connection; per-connection handle tables (a
    dropped client's requests still run to completion server-side —
    their .tim files are the durable artifact, exactly the campaign
    drivers' crash stance).  Request-level failures reply as errors on
    that handle; only protocol violations drop the connection."""

    def __init__(self, server, host="127.0.0.1", port=0, quiet=True):
        self.server = server
        self.quiet = quiet
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self.label = f"{self.host}:{self.port}"
        self._closing = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ppt-listen", daemon=True)

    def start(self):
        self._accept_thread.start()
        log(f"ppserve: listening on {self.label}", quiet=self.quiet,
            tracer=None)
        return self

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # close() shut the listening socket
            threading.Thread(target=self._serve_conn, args=(conn, addr),
                             name="ppt-conn", daemon=True).start()

    def _serve_conn(self, conn, addr):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        handles = {}
        next_id = 0
        try:
            while True:
                try:
                    msg = _recv_frame(conn)
                except TransportError:
                    return  # client went away (normal teardown)
                op = msg.get("op") if isinstance(msg, dict) else None
                if op == "submit":
                    try:
                        req = self.server.submit(
                            msg["datafiles"], msg["modelfile"],
                            tim_out=msg.get("tim_out"),
                            name=msg.get("name"),
                            tenant=msg.get("tenant"),
                            trace_id=msg.get("trace_id"),
                            **dict(msg.get("options") or {}))
                    except ServeRejected as e:
                        _send_frame(conn, {
                            "ok": False, "error": str(e),
                            "rejected": True,
                            "retryable": bool(e.retryable)})
                    except Exception as e:
                        _send_frame(conn, {
                            "ok": False, "error": str(e),
                            "etype": type(e).__name__})
                    else:
                        handles[next_id] = req
                        _send_frame(conn, {"ok": True,
                                           "handle": next_id})
                        next_id += 1
                elif op == "result":
                    req = handles.get(msg.get("handle"))
                    if req is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"unknown handle "
                                     f"{msg.get('handle')!r} on this "
                                     "connection (already collected, "
                                     "or never submitted here)",
                            "etype": "KeyError"})
                        continue
                    wait = min(max(float(msg.get("wait", 0.0)), 0.0),
                               30.0)
                    if not req.wait(wait):
                        _send_frame(conn, {"ok": True, "done": False})
                        continue
                    # collect-once: evict the resolved request so a
                    # long-lived fleet connection stays O(outstanding)
                    # — a retained handle would pin its whole result
                    # DataBunch for the connection's lifetime
                    del handles[msg["handle"]]
                    try:
                        res = req.result(0)
                    except Exception as e:
                        _send_frame(conn, {
                            "ok": False, "error": str(e),
                            "etype": type(e).__name__})
                    else:
                        _send_frame(conn, {"ok": True, "done": True,
                                           "result":
                                               encode_result(res)})
                elif op == "stat":
                    st = self.server.stats()
                    _send_frame(conn, {"ok": True, **st})
                elif op == "metrics":
                    try:
                        m = self.server.metrics()
                    except Exception as e:
                        _send_frame(conn, {
                            "ok": False, "error": str(e),
                            "etype": type(e).__name__})
                    else:
                        _send_frame(conn, {"ok": True, **m})
                elif op == "drain":
                    # bounded: reply well under the client's socket
                    # timeout with the still-pending count; the
                    # client loops (SocketTransport.drain)
                    import time as _time

                    t_req = msg.get("timeout")
                    # an explicit 0.0 is a non-blocking "how many are
                    # done" probe — only None falls back to the chunk
                    budget = DRAIN_CHUNK_S if t_req is None else \
                        min(max(float(t_req), 0.0), DRAIN_CHUNK_S)
                    t_end = _time.monotonic() + budget
                    pending = len(handles)
                    for req in list(handles.values()):
                        if req.wait(max(0.0,
                                        t_end - _time.monotonic())):
                            pending -= 1
                    _send_frame(conn, {
                        "ok": True,
                        "n_done": len(handles) - pending,
                        "pending": pending})
                else:
                    _send_frame(conn, {
                        "ok": False,
                        "error": f"unknown op {op!r} (protocol "
                                 "mismatch? known ops: submit, "
                                 "result, stat, metrics, drain)"})
        except OSError:
            pass  # peer reset mid-reply
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._closing.set()
        # shutdown() wakes the thread blocked in accept() — a bare
        # close() leaves the kernel listener alive behind the blocked
        # syscall, still accepting connections for a dead server
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread.is_alive():
            self._accept_thread.join(1.0)

    def __enter__(self):
        if not self._accept_thread.is_alive():
            self.start()
        return self

    def __exit__(self, *exc):
        self.close()
        return False
