"""Bounded Levenberg-Marquardt least squares in JAX.

Replaces the reference's lmfit/MINPACK dependency (used by
fit_gaussian_profile pplib.py:1922-2002, fit_gaussian_portrait
pplib.py:2005-2133, fit_powlaw pplib.py:1841-1880).  Bounds are handled
with the same MINUIT-style parameter transforms lmfit uses, so bounded
parameters stay strictly inside their intervals and the Jacobian is
taken in the unbounded internal space — by autodiff (jax.jacfwd), or,
when the caller provides an analytic external-space residual-Jacobian
companion (``jacobian=``), by the closed form chained through the
transform's elementwise dx/du (ISSUE 14; config.lm_jacobian selects
'auto'/'analytic'/'ad' — 'ad' is the digit oracle).  The loop is a
fixed-shape `lax.while_loop`; frozen parameters (vary=False) have their
Jacobian columns masked rather than changing the parameter vector's
shape, keeping everything jittable.  The vary mask is applied in ONE
place (_make_jac) for every Jacobian source and both evaluation sites
(init + in-loop) — one masking rule, three consumers.

Error bars follow lmfit's default convention: covariance scaled by
reduced chi^2 (scale_covar=True), reported in external space via the
transform's chain rule.

ISSUE 9: the engine also runs BATCHED (`levenberg_marquardt_batched`):
the same `_lm_core` vmapped over a leading problem axis, per-problem
`done` flags inside one shared `lax.while_loop` — a converged problem
holds its state (vmap's while_loop batching rule selects per-element on
the original cond) while stragglers iterate, so `nfev`/`success` keep
their per-problem semantics.  Heterogeneous problems coexist in one
compiled program by padding parameter vectors to a common width with
`vary=False` masking (a fully-frozen pad row converges on iteration 0)
and by `nres_valid` (per-problem true residual count, so dof/errors
ignore zero-weight padded residual entries).  The single-problem API is
unchanged and is the B=1 digit-exactness oracle.
"""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LMResult", "levenberg_marquardt", "levenberg_marquardt_batched",
           "use_lm_jacobian", "resolve_lm_jacobian"]


def use_lm_jacobian(setting=None):
    """The engine's Jacobian-source knob: config.lm_jacobian
    ('auto' | 'analytic' | 'ad'), strict like the other tri-states (a
    typo must not silently mean 'auto').  Read per call so in-process
    A/B flips take effect.  setting: explicit per-call override
    (the CLIs' --lm-jacobian); None -> config."""
    if setting is None:
        from .. import config

        setting = getattr(config, "lm_jacobian", "auto")
    if setting not in ("auto", "analytic", "ad"):
        raise ValueError(
            f"lm_jacobian must be 'auto', 'analytic' or 'ad'; got "
            f"{setting!r}")
    return setting


def resolve_lm_jacobian(jacobian, setting=None):
    """Resolve the provided analytic companion against the knob:
    returns the jacobian function to use, or None for jacfwd.
    'analytic' with no companion refuses loudly — an A/B run forcing
    the analytic lane must not silently fall back to autodiff."""
    mode = use_lm_jacobian(setting)
    if mode == "ad":
        return None
    if jacobian is None:
        if mode == "analytic":
            raise ValueError(
                "lm_jacobian='analytic' but this fit's residual "
                "function provides no analytic Jacobian companion; "
                "use 'auto' (analytic when available) or 'ad'")
        return None
    return jacobian


# --- bound transforms (lmfit/MINUIT convention) ---------------------------
# free:        x = u
# lower only:  x = lo - 1 + sqrt(u^2 + 1)
# upper only:  x = hi + 1 - sqrt(u^2 + 1)
# two-sided:   x = lo + (hi - lo)/2 * (sin(u) + 1)


def _to_external(u, lo, hi, kind):
    s = jnp.sqrt(u**2.0 + 1.0)
    return jnp.where(
        kind == 0, u,
        jnp.where(
            kind == 1, lo - 1.0 + s,
            jnp.where(kind == 2, hi + 1.0 - s,
                      lo + 0.5 * (hi - lo) * (jnp.sin(u) + 1.0)),
        ),
    )


def _to_internal(x, lo, hi, kind):
    xl = jnp.sqrt(jnp.maximum((x - lo + 1.0) ** 2.0 - 1.0, 0.0))
    xu = jnp.sqrt(jnp.maximum((hi - x + 1.0) ** 2.0 - 1.0, 0.0))
    frac = jnp.clip(2.0 * (x - lo) / jnp.where(hi > lo, hi - lo, 1.0) - 1.0,
                    -1.0, 1.0)
    return jnp.where(
        kind == 0, x,
        jnp.where(kind == 1, xl, jnp.where(kind == 2, -xu, jnp.arcsin(frac))),
    )


def _to_external_grad(u, lo, hi, kind):
    """Elementwise dx/du of _to_external in closed form (the analytic
    Jacobian's chain factor; _lm_finalize's jax.grad-vmap computes the
    same values for the covariance transform)."""
    s = jnp.sqrt(u**2.0 + 1.0)
    return jnp.where(
        kind == 0, jnp.ones_like(u),
        jnp.where(
            kind == 1, u / s,
            jnp.where(kind == 2, -u / s,
                      0.5 * (hi - lo) * jnp.cos(u)),
        ),
    )


def _make_jac(resid_fn, jacobian, aux, lo, hi, kind, vary):
    """THE Jacobian evaluator — and the single place the vary mask is
    applied (both the initial Jacobian in _lm_init and the in-loop one
    in _lm_run call this; historically each site masked on its own).

    jacobian None: forward-mode autodiff through residual-of-transform.
    jacobian given: the analytic external-space residual Jacobian
    J_x(x, *aux) -> (nres, nparam), chained to internal space by the
    transform's elementwise dx/du.  ``vary`` must already be cast to
    the working float dtype."""

    def rfun(u):
        return resid_fn(_to_external(u, lo, hi, kind), *aux)

    if jacobian is None:
        def jac(u):
            J = jax.jacfwd(rfun)(u)  # (nres, nparam)
            return J * vary[None, :]
    else:
        def jac(u):
            Jx = jacobian(_to_external(u, lo, hi, kind), *aux)
            D = _to_external_grad(u, lo, hi, kind)
            return Jx * (D * vary)[None, :]
    return jac


def _bounds_spec(lower, upper, shape, dtype):
    """Resolve (lower, upper) into (lo, hi, kind) arrays of ``shape``
    (an int for the single-problem path, a (B, n) tuple batched —
    per-problem bounds broadcast from (n,) or given per row)."""
    lo = np.full(shape, -np.inf) if lower is None \
        else np.broadcast_to(np.asarray(lower, float), shape).copy()
    hi = np.full(shape, np.inf) if upper is None \
        else np.broadcast_to(np.asarray(upper, float), shape).copy()
    kind = np.zeros(shape, np.int32)
    kind[np.isfinite(lo) & ~np.isfinite(hi)] = 1
    kind[~np.isfinite(lo) & np.isfinite(hi)] = 2
    kind[np.isfinite(lo) & np.isfinite(hi)] = 3
    # replace infs by dummies so the transforms never see inf arithmetic
    lo = np.where(np.isfinite(lo), lo, 0.0)
    hi = np.where(np.isfinite(hi), hi, 0.0)
    return (jnp.asarray(lo, dtype), jnp.asarray(hi, dtype),
            jnp.asarray(kind))


class LMResult(NamedTuple):
    x: jnp.ndarray          # fitted external parameters
    x_err: jnp.ndarray      # 1-sigma errors (scale_covar convention)
    chi2: jnp.ndarray
    dof: jnp.ndarray
    nfev: jnp.ndarray
    cov: jnp.ndarray        # external-space covariance (scaled)
    success: jnp.ndarray
    # the fit stopped on the STALL criterion (two consecutive accepted
    # steps with sub-ftol improvement at high damping — an
    # ill-conditioned valley it would otherwise wander in until
    # max_iter).  Counted as success (MINPACK's ftol-convergence
    # spirit: further iteration polishes noise), but the stop point is
    # not digit-reproducible across program variants the way a clean
    # convergence is, so template-trial selection excludes these.
    stalled: jnp.ndarray


class _LMState(NamedTuple):
    u: jnp.ndarray
    f: jnp.ndarray
    r: jnp.ndarray   # residual at u (kept so rejected steps don't recompute)
    J: jnp.ndarray   # Jacobian at u (ditto — the dominant per-step cost)
    lam: jnp.ndarray
    it: jnp.ndarray
    nfev: jnp.ndarray
    nstall: jnp.ndarray  # consecutive accepted sub-ftol improvements
    done: jnp.ndarray


def _lm_run(resid_fn, aux, s0, lo, hi, kind, vary, it_cap,
            ftol=1e-10, lam0=1e-3, jacobian=None):
    """Advance an _LMState until convergence or ``it == it_cap`` (the
    shared while_loop; ``it_cap`` is a traced operand so chunked
    execution reuses one compiled program).  Splitting the loop at an
    iteration boundary and resuming from the carried state reproduces
    the unsplit trajectory exactly — the property the batched
    front-end's compaction relies on."""
    dt = s0.u.dtype
    vary = vary.astype(dt)

    def rfun(u):
        return resid_fn(_to_external(u, lo, hi, kind), *aux)

    jac = _make_jac(resid_fn, jacobian, aux, lo, hi, kind, vary)

    def cond(s):
        return jnp.logical_and(s.it < it_cap, jnp.logical_not(s.done))

    def body(s):
        g = s.J.T @ s.r
        JTJ = s.J.T @ s.J
        dJ = jnp.diag(JTJ)
        dJ = jnp.maximum(dJ, 1e-14 * jnp.max(dJ))
        A = JTJ + s.lam * jnp.diag(dJ) + jnp.diag(1.0 - vary)
        step = -jnp.linalg.solve(A, g) * vary
        # near-degenerate Jacobian columns (e.g. a parameter just
        # inside a bound) can produce explosive internal steps; clamp
        # each element to a generous multiple of its current scale
        smax = 100.0 * (1.0 + jnp.abs(s.u))
        step = jnp.clip(step, -smax, smax)
        u_try = s.u + step
        r_try = rfun(u_try)
        f_new = jnp.sum(r_try**2.0)
        accept = f_new < s.f
        # converged: accepted near-Newton step (small damping) with
        # negligible relative improvement.  With large lam a small
        # improvement only means the step was short, not convergence.
        rel = (s.f - f_new) / (jnp.abs(s.f) + 1e-300)
        done_clean = jnp.logical_and(
            jnp.logical_and(accept, rel < ftol), s.lam <= lam0)
        # also converged if the gradient is essentially zero
        gnorm = jnp.max(jnp.abs(g * vary))
        done_clean = jnp.logical_or(done_clean,
                                    gnorm < 1e-14 * (s.f + 1.0))
        # STALL: two consecutive accepted steps whose improvement is
        # below ftol but at high damping (so the lam<=lam0 clause never
        # fires) — an ill-conditioned valley the loop would otherwise
        # wander in until max_iter, each wander step paying a Jacobian.
        # Further iteration only polishes noise (MINPACK stops on the
        # same ftol evidence); flagged separately in LMResult.stalled.
        # A clean convergence on this very iteration resets the
        # counter: `stalled` must mean the stall criterion is what
        # stopped the fit, not that the counter happened to reach 2 as
        # the fit converged properly.
        nstall = jnp.where(accept,
                           jnp.where(rel < ftol, s.nstall + 1, 0),
                           s.nstall)
        nstall = jnp.where(done_clean, 0, nstall)
        done = jnp.logical_or(done_clean, nstall >= 2)
        u_new = jnp.where(accept, u_try, s.u)
        # the Jacobian only changes when the step is accepted; a
        # rejected step reuses the stored one (skipping the dominant
        # per-iteration cost during lambda adjustment)
        J_new = jax.lax.cond(accept, jac, lambda _: s.J, u_new)
        return _LMState(
            u=u_new,
            f=jnp.where(accept, f_new, s.f),
            r=jnp.where(accept, r_try, s.r),
            J=J_new,
            lam=jnp.where(accept, s.lam * 0.3, s.lam * 5.0).clip(1e-12, 1e12),
            it=s.it + 1,
            nfev=s.nfev + 1,
            nstall=nstall,
            done=done,
        )

    return jax.lax.while_loop(cond, body, s0)


def _lm_init(resid_fn, aux, x0, lo, hi, kind, vary, lam0=1e-3,
             jacobian=None):
    """Initial _LMState at x0 (one residual + one Jacobian eval; the
    Jacobian — and its vary mask — comes from the same _make_jac the
    loop body uses)."""
    dt = x0.dtype
    u0 = _to_internal(x0, lo, hi, kind)
    vary = vary.astype(dt)

    def rfun(u):
        return resid_fn(_to_external(u, lo, hi, kind), *aux)

    r0 = rfun(u0)
    J0 = _make_jac(resid_fn, jacobian, aux, lo, hi, kind, vary)(u0)
    return _LMState(
        u=u0,
        f=jnp.sum(r0**2.0),
        r=r0,
        J=J0,
        lam=jnp.asarray(lam0, dt),
        it=jnp.asarray(0, jnp.int32),
        nfev=jnp.asarray(1, jnp.int32),
        nstall=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
    )


def _lm_finalize(s, lo, hi, kind, vary, nres_valid, max_iter):
    """Final _LMState -> LMResult (covariance in external space, lmfit
    scale_covar convention)."""
    dt = s.u.dtype
    vary = vary.astype(dt)
    nvary = jnp.sum(vary)
    r, J = s.r, s.J
    JTJ = J.T @ J + jnp.diag(1.0 - vary)
    cov_u = jnp.linalg.inv(JTJ)
    # padded residual entries (batched lane: zero-weight channels) are
    # exactly zero and carry no information; nres_valid restores the
    # true degrees of freedom so red-chi2 scaling matches the unpadded
    # problem digit-for-digit
    nres = r.shape[0] if nres_valid is None else nres_valid
    dof = nres - nvary
    red = s.f / jnp.maximum(dof, 1.0)
    cov_u = cov_u * red
    # the transform is elementwise, so dx/du is diagonal
    D = jax.vmap(jax.grad(_to_external), in_axes=(0, 0, 0, 0))(
        s.u, lo, hi, kind)
    cov_x = cov_u * jnp.outer(D, D) * jnp.outer(vary, vary)
    x = _to_external(s.u, lo, hi, kind)
    x_err = jnp.sqrt(jnp.maximum(jnp.diagonal(cov_x), 0.0))
    return LMResult(
        x=x, x_err=x_err, chi2=s.f, dof=dof, nfev=s.nfev, cov=cov_x,
        success=s.done | (s.it < max_iter),
        stalled=s.nstall >= 2,
    )


def _lm_core_impl(resid_fn, aux, x0, lo, hi, kind, vary, nres_valid=None,
                  max_iter=100, ftol=1e-10, lam0=1e-3, jacobian=None):
    s0 = _lm_init(resid_fn, aux, x0, lo, hi, kind, vary, lam0=lam0,
                  jacobian=jacobian)
    s = _lm_run(resid_fn, aux, s0, lo, hi, kind, vary, max_iter,
                ftol=ftol, lam0=lam0, jacobian=jacobian)
    return _lm_finalize(s, lo, hi, kind, vary, nres_valid, max_iter)


_lm_core = partial(jax.jit,
                   static_argnames=("resid_fn", "max_iter", "jacobian"))(
    _lm_core_impl)


def _nudge_into_bounds(x0, lo, hi, kind, vary):
    """Nudge VARYING parameters strictly inside their bounds: at the
    exact bound every transform has dx/du = 0 (u = 0 for one-sided,
    the arcsin endpoints for two-sided), which zeroes the Jacobian
    column and freezes the parameter forever.  Frozen (vary=False)
    parameters keep their exact value.  The nudge must be large
    enough that dx/du ~ sqrt(2*eps) does not make the column
    numerically singular (which produces explosive internal steps).
    Elementwise, so the single and batched front-ends share it."""
    eps = 1e-4
    inside3 = jnp.clip(x0, lo + eps * (hi - lo), hi - eps * (hi - lo))
    inside1 = jnp.maximum(x0, lo + eps * (1.0 + jnp.abs(lo)))
    inside2 = jnp.minimum(x0, hi - eps * (1.0 + jnp.abs(hi)))
    x0 = jnp.where(vary & (kind == 3), inside3, x0)
    x0 = jnp.where(vary & (kind == 1), inside1, x0)
    x0 = jnp.where(vary & (kind == 2), inside2, x0)
    # frozen params still need finite internal coordinates
    x0 = jnp.where(~vary & (kind == 3),
                   jnp.clip(x0, lo, hi), x0)
    x0 = jnp.where(~vary & (kind == 1), jnp.maximum(x0, lo), x0)
    x0 = jnp.where(~vary & (kind == 2), jnp.minimum(x0, hi), x0)
    return x0


def levenberg_marquardt(resid_fn, x0, aux=(), lower=None, upper=None,
                        vary=None, max_iter=100, ftol=1e-10,
                        nres_valid=None, jacobian=None):
    """Minimize sum(resid_fn(x, *aux)**2) over x with optional bounds.

    resid_fn: callable (x, *aux) -> residual vector; must be
    jax-traceable and HASHABLE (a module-level function).  Pass data
    arrays through `aux` — they are traced operands, so repeated fits
    with different data reuse one compilation.
    x0: (n,) initial external parameters (clipped into bounds).
    lower/upper: (n,) bounds with +-inf for unbounded; vary: (n,) bool.
    nres_valid: true residual count for dof when some residual entries
    are structural zero-weight padding (see levenberg_marquardt_batched).
    jacobian: optional ANALYTIC residual-Jacobian companion
    (x, *aux) -> (nres, nparam) in external space, hashable like
    resid_fn; config.lm_jacobian routes between it and jacfwd
    ('auto' = use it when given, 'ad' = the autodiff digit oracle,
    'analytic' = require it).
    """
    x0 = jnp.asarray(x0, float)
    n = x0.shape[0]
    lo, hi, kind = _bounds_spec(lower, upper, n, x0.dtype)
    if vary is None:
        vary = jnp.ones(n, bool)
    vary = jnp.asarray(vary)
    x0 = _nudge_into_bounds(x0, lo, hi, kind, vary)
    return _lm_core(resid_fn, tuple(aux), x0, lo, hi, kind, vary,
                    nres_valid=(None if nres_valid is None
                                else jnp.asarray(nres_valid)),
                    max_iter=max_iter, ftol=ftol,
                    jacobian=resolve_lm_jacobian(jacobian))


# one compiled batched program per (resid_fn, max_iter, dof source);
# shapes/dtypes key the underlying jit cache as usual
_BATCHED_CORE_CACHE = {}


def _batched_core(resid_fn, max_iter, has_nres, jacobian=None):
    key = (resid_fn, max_iter, has_nres, jacobian)
    if key not in _BATCHED_CORE_CACHE:
        def run(aux, x0, lo, hi, kind, vary, nres_valid, ftol):
            return _lm_core_impl(resid_fn, aux, x0, lo, hi, kind, vary,
                                 nres_valid=nres_valid,
                                 max_iter=max_iter, ftol=ftol,
                                 jacobian=jacobian)

        axes = (0, 0, 0, 0, 0, 0, 0 if has_nres else None, None)
        _BATCHED_CORE_CACHE[key] = jax.jit(jax.vmap(run, in_axes=axes))
    return _BATCHED_CORE_CACHE[key]


_BATCHED_PIECE_CACHE = {}


def _batched_pieces(resid_fn, has_nres, jacobian=None):
    """jitted vmapped (init, run-chunk, finalize) programs for the
    compacting front-end.  The run chunk takes ``it_cap`` as a traced
    operand, so every chunk of every problem subset reuses one
    compiled program per batch-width class."""
    key = (resid_fn, has_nres, jacobian)
    if key not in _BATCHED_PIECE_CACHE:
        def init(aux, x0, lo, hi, kind, vary):
            return _lm_init(resid_fn, aux, x0, lo, hi, kind, vary,
                            jacobian=jacobian)

        def run(aux, s, lo, hi, kind, vary, it_cap, ftol):
            return _lm_run(resid_fn, aux, s, lo, hi, kind, vary,
                           it_cap, ftol=ftol, jacobian=jacobian)

        def fin(s, lo, hi, kind, vary, nres_valid, max_iter):
            return _lm_finalize(s, lo, hi, kind, vary, nres_valid,
                                max_iter)

        _BATCHED_PIECE_CACHE[key] = (
            jax.jit(jax.vmap(init, in_axes=(0, 0, 0, 0, 0, 0))),
            jax.jit(jax.vmap(run, in_axes=(0, 0, 0, 0, 0, 0, None,
                                           None))),
            jax.jit(jax.vmap(fin, in_axes=(0, 0, 0, 0, 0,
                                           0 if has_nres else None,
                                           None))),
        )
    return _BATCHED_PIECE_CACHE[key]


def _pow2ceil(n):
    return 1 << max(int(n) - 1, 0).bit_length()


# Sentinel for "resolve compact_every from config.lm_compact_every":
# callers (fit/gauss.py's batched template fits) must keep None
# meaning "one uninterrupted dispatch", so the config indirection —
# which the autotune sweep retunes per backend — needs its own token.
COMPACT_EVERY_CONFIG = "config"


def resolve_compact_every(setting):
    """Resolve a compact_every argument: the COMPACT_EVERY_CONFIG
    sentinel reads ``config.lm_compact_every`` (PPT-tunable, autotune
    identity tier); None and positive ints pass through; loud on
    anything else."""
    if setting == COMPACT_EVERY_CONFIG:
        from .. import config

        setting = getattr(config, "lm_compact_every", 16)
    if setting is None:
        return None
    k = int(setting)
    if k < 1:
        raise ValueError(
            f"compact_every must be a positive int or None; got "
            f"{setting!r}")
    return k


def levenberg_marquardt_batched(resid_fn, x0, aux=(), lower=None,
                                upper=None, vary=None, max_iter=100,
                                ftol=1e-10, nres_valid=None,
                                compact_every=None, compact_min_rows=4,
                                jacobian=None):
    """Minimize B independent problems in ONE dispatch: `_lm_core`
    vmapped over the leading problem axis, all problems sharing one
    `lax.while_loop` whose per-problem `done` flags let converged
    problems hold their state while stragglers iterate.

    resid_fn: as in levenberg_marquardt — ONE hashable module-level
    function shared by every problem; per-problem data goes through
    ``aux``, a tuple of arrays each stacked with a leading B axis.
    x0: (B, n) initial parameters padded to a common width n —
    heterogeneous problems freeze their pad entries with vary=False
    (a zero-amplitude frozen component contributes exactly nothing, so
    the padded fit is digit-identical to the unpadded one).
    lower/upper: (n,) shared or (B, n) per-problem; vary: (B, n).
    nres_valid: (B,) true residual counts when problems carry
    zero-weight padded residual entries (channel padding); dof and the
    scale_covar error bars then match the unpadded problems.
    Returns an LMResult whose every field has a leading B axis;
    nfev/success keep their per-problem single-fit semantics.
    jacobian: analytic residual-Jacobian companion, as in
    levenberg_marquardt — vmapped alongside resid_fn, so each problem
    row gets its closed-form (nres, nparam) block instead of nparam
    forward-mode passes (under vmap the lax.cond Jacobian-reuse is a
    both-branches select, so this is the dominant per-iteration cost).

    compact_every: with an int K, the shared while_loop runs in chunks
    of K iterations with host-side COMPACTION between chunks: problems
    still iterating are re-batched into the next power-of-two width
    (never below compact_min_rows), so one straggler stops costing a
    full-width lock-step iteration — sum-of-iterations work like the
    serial loop instead of B*max(iterations).  Chunking splits the
    loop at iteration boundaries and carries exact state, so per-
    problem trajectories (and results) are identical to the unchunked
    dispatch.  None (default) = one dispatch, one uninterrupted loop.
    """
    x0 = jnp.asarray(x0, float)
    if x0.ndim != 2:
        raise ValueError(
            f"levenberg_marquardt_batched needs x0 of shape (B, n); "
            f"got {x0.shape}")
    B, n = x0.shape
    lo, hi, kind = _bounds_spec(lower, upper, (B, n), x0.dtype)
    if vary is None:
        vary = jnp.ones((B, n), bool)
    vary = jnp.broadcast_to(jnp.asarray(vary), (B, n))
    x0 = _nudge_into_bounds(x0, lo, hi, kind, vary)
    aux = tuple(jnp.asarray(a) for a in aux)
    if nres_valid is not None:
        nres_valid = jnp.asarray(nres_valid)
    jacobian = resolve_lm_jacobian(jacobian)
    if compact_every is None:
        fn = _batched_core(resid_fn, int(max_iter),
                           nres_valid is not None, jacobian)
        return fn(aux, x0, lo, hi, kind, vary, nres_valid, ftol)

    init_fn, run_fn, fin_fn = _batched_pieces(resid_fn,
                                              nres_valid is not None,
                                              jacobian)
    lo_j, hi_j = jnp.asarray(lo), jnp.asarray(hi)
    kind_j, vary_j = jnp.asarray(kind), jnp.asarray(vary)
    state = init_fn(aux, x0, lo_j, hi_j, kind_j, vary_j)
    K = int(compact_every)
    max_iter = int(max_iter)
    it_cap = 0
    while True:
        done = np.asarray(state.done)
        itv = np.asarray(state.it)
        alive = np.where(~done & (itv < max_iter))[0]
        if alive.size == 0:
            break
        it_cap = min(it_cap + K, max_iter)
        cls = min(max(_pow2ceil(alive.size), int(compact_min_rows)), B)
        if cls == B:
            state = run_fn(aux, state, lo_j, hi_j, kind_j, vary_j,
                           it_cap, ftol)
            continue
        idx = jnp.asarray(np.concatenate(
            [alive, np.full(cls - alive.size, alive[0])]))

        def take(a):
            return jnp.take(a, idx, axis=0)

        sub = jax.tree_util.tree_map(take, state)
        if cls > alive.size:
            # pad rows hold a copy of an alive problem; force them done
            # so the chunk cond skips their updates (results discarded)
            pad_mask = jnp.arange(cls) >= alive.size
            sub = sub._replace(done=sub.done | pad_mask)
        out = run_fn(tuple(take(a) for a in aux), sub, take(lo_j),
                     take(hi_j), take(kind_j), take(vary_j), it_cap,
                     ftol)
        ai = jnp.asarray(alive)
        na = alive.size
        state = jax.tree_util.tree_map(
            lambda fs, cs: fs.at[ai].set(cs[:na]), state, out)
    return fin_fn(state, lo_j, hi_j, kind_j, vary_j, nres_valid,
                  max_iter)
